"""The on-device scoring service: micro-batched, state-cached, rank-fused.

Orchestrates the serve subsystem end-to-end::

    client threads ──submit()──▶ MicroBatcher lanes ──▶ serve worker
                                                        ├─ encode lane: window
                                                        │  batch → CompiledInference
                                                        │  bucket executable
                                                        ├─ hit lane: cached [E]
                                                        │  states → hidden scorer
                                                        └─ retrieval: MIPS top-C
                                                           → re-rank → top-k

Three serving modes, fixed at construction (one compiled program family each):

* **full** (default): responses carry full-catalog scores, an exact host
  top-k cut, or exact gathers for per-request candidate lists.
* **slate** (``candidates=...``): every response scores one fixed candidate
  slate compiled into the executables (the reference's ``candidates_to_score``
  serving shape).
* **retrieval** (``retrieval=CandidatePipeline(...)``): the fused
  candidate→rank path — full-catalog logits never materialize.

Parity contract (tested in ``tests/serve/``): response scores are BITWISE
identical to a direct AOT ``forward_inference`` call on the same right-aligned
window at the routed (length, batch) bucket — and within a bucket program they
are bitwise independent of the fill level, the co-riding requests' content,
and the row order, so micro-batching and caching never change a score. (The
bucket qualifier is XLA reality: the same math compiled at two different batch
shapes may differ in the last float ulp; every response carries its
``batch_bucket`` so the exact program is always reconstructible.)

Resilience (docs/serving.md "Overload and degradation"): admission control
bounds every lane's queue (``max_queue_depth`` — beyond it, futures fail fast
with :class:`RequestShed`); per-request ``deadline_ms`` budgets are enforced
at batch-build time so expired waiters never reach the device; consecutive
engine failures open a :class:`CircuitBreaker` over the encode path; and
under an open breaker or a saturated lane, traffic walks the degradation
ladder — cache-only scoring (the existing hit lane, encode skipped), then the
host-side :class:`FallbackScorer` floor. Every response's ``served_by`` names
its rung; ``served_by == "primary"`` responses keep the full parity contract.

Observability: requests record ``queue_wait`` spans (cross-thread, via
``obs.trace.lifecycle_span``), batches record ``batch_build``/``score`` and
the pipeline's ``retrieve``/``rerank`` spans; ``on_serve_start`` /
``on_serve_batch`` / ``on_serve_end`` events flow through any
:class:`~replay_tpu.obs.RunLogger` — joined by ``on_shed`` / ``on_breaker`` /
``on_degrade`` from the resilience layer — and ``on_serve_end`` carries the
serve goodput breakdown (``SERVE_GOODPUT_SPANS`` fractions, summing to 1.0)
plus the shed / deadline-miss / degradation totals ``obs.report`` renders and
gates on.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from concurrent.futures import Future
from concurrent.futures import TimeoutError as FutureTimeoutError
from typing import Any, Dict, Hashable, List, Optional, Sequence, Tuple

import numpy as np

from replay_tpu.obs import TrainerEvent, Tracer
from replay_tpu.obs.trace import SERVE_GOODPUT_SPANS, goodput_breakdown, lifecycle_span

from .batcher import MicroBatcher
from .breaker import CircuitBreaker
from .cache import UserState, UserStateCache
from .degrade import FallbackScorer
from .engine import ScoringEngine
from .errors import CircuitOpen, DeadlineExceeded, RequestShed
from .futures import mark_running, safe_fail, safe_set_result
from .pipeline import CandidatePipeline
from .promote import ROLES, ParamStore, in_canary_slice
from .request import PendingRequest, ScoreRequest, ScoreResponse, make_window

logger = logging.getLogger("replay_tpu")


class ScoringService:
    """Thread-safe online scoring over a trained sequential model.

    Resilience knobs (see docs/serving.md for tuning guidance):

    :param max_queue_depth: per-lane queued-request bound. ``None`` (default)
        sizes it automatically at ``16 x`` the largest batch bucket; ``0``
        disables the bound (the pre-resilience unbounded behavior).
    :param default_deadline_ms: end-to-end budget applied to requests that
        don't carry their own ``deadline_ms``. ``None`` = no default deadline.
    :param breaker: the engine :class:`CircuitBreaker`; ``None`` builds one
        with defaults. Its ``on_transition`` is wired to ``on_breaker`` events.
    :param fallback: optional :class:`FallbackScorer` — the degradation
        ladder's host-side floor. Without it, requests that can't be absorbed
        by cache-only scoring fail fast (:class:`CircuitOpen` under an open
        breaker, :class:`RequestShed` under overload).
    :param metrics_port: serve a live Prometheus ``/metrics`` endpoint (+
        ``/snapshot`` JSON) for the service's lifetime — qps, batch fill,
        queue-wait histograms, shed/degrade/breaker counters bridged from the
        serve event stream (docs/observability.md). ``0`` binds an ephemeral
        port (read :attr:`metrics_exporter`); a busy port degrades to a
        logged no-op.
    :param slo_rules: :class:`~replay_tpu.obs.SLORule` set evaluated after
        every dispatched batch; breaches emit ``on_slo_violation`` through
        the attached ``logger`` and count in the registry.
    :param cold_miss: what a state-less request (unknown user, no
        ``history``) gets. ``"error"`` (default) keeps the original contract
        — the future fails with ``KeyError`` naming the cold path. With
        ``"fallback"`` (and a ``fallback`` scorer attached) it rides the
        degradation ladder's floor instead: the fleet-failover setting, where
        a rerouted user's cache is cold on the new replica by construction
        and a generic answer beats an error (``served_by == "fallback"``
        keeps the degradation visible). ``new_items`` requests error in BOTH
        modes — an interaction that cannot land must never be masked by a
        success response.
    :param flight_path: record every serve event into a SIGKILL-proof mmap
        flight ring (:mod:`replay_tpu.obs.blackbox`) at this path. Defaults
        to ``$REPLAY_TPU_FLIGHT_PATH`` when set. A SIGKILLed replica's last
        batches, sheds and breaker flips stay readable via ``read_flight``
        — the evidence ``obs.report --postmortem`` reconstructs.
    """

    def __init__(
        self,
        model,
        params,
        length_buckets: Optional[Sequence[int]] = None,
        batch_buckets: Sequence[int] = (1, 8, 64),
        max_wait_ms: float = 2.0,
        cache_capacity: int = 10_000,
        candidates: Optional[Sequence[int]] = None,
        retrieval: Optional[CandidatePipeline] = None,
        feature_name: str = "item_id",
        pad_id: int = 0,
        tracer: Optional[Tracer] = None,
        logger=None,
        trace_path: Optional[str] = None,
        max_queue_depth: Optional[int] = None,
        default_deadline_ms: Optional[float] = None,
        breaker: Optional[CircuitBreaker] = None,
        fallback: Optional[FallbackScorer] = None,
        metrics_port: Optional[int] = None,
        slo_rules: Optional[Sequence[Any]] = None,
        param_store: Optional[ParamStore] = None,
        cold_miss: str = "error",
        flight_path: Optional[str] = None,
        quality: Optional[Any] = None,
    ) -> None:
        if retrieval is not None and candidates is not None:
            msg = "retrieval mode and a fixed candidate slate are mutually exclusive"
            raise ValueError(msg)
        if cold_miss not in ("error", "fallback"):
            msg = f"cold_miss must be 'error' or 'fallback', got {cold_miss!r}"
            raise ValueError(msg)
        self.cold_miss = cold_miss
        self.mode = (
            "retrieval" if retrieval is not None
            else "slate" if candidates is not None
            else "full"
        )
        self.retrieval = retrieval
        self.pad_id = int(pad_id)
        self._model = model
        self._feature_name = feature_name
        self.tracer = tracer if tracer is not None else Tracer(enabled=False)
        self.logger = logger
        self.trace_path = trace_path
        self.default_deadline_ms = default_deadline_ms
        self.engine = ScoringEngine(
            model,
            params,
            length_buckets=length_buckets,
            batch_buckets=batch_buckets,
            candidates=np.asarray(candidates, np.int32) if candidates is not None else None,
            feature_name=feature_name,
            outputs="hidden" if retrieval is not None else "both",
        )
        self.cache = UserStateCache(cache_capacity)
        # versioned parameter generations (serve.promote): generation 0 is the
        # construction params; candidates publish through publish_candidate
        # and swap in atomically via promote()/rollback()
        self.store = (
            param_store
            if param_store is not None
            else ParamStore(self.engine.params, pipeline=retrieval)
        )
        # active canary routing: (candidate generation, traffic fraction);
        # None = all traffic on the stable generation. The epoch counts
        # begin_canary calls so accounting can tell THIS canary's traffic
        # from a previous candidate's late-landing in-flight requests
        self._canary: Optional[Tuple[int, float]] = None
        self._canary_epoch = 0
        self.breaker = breaker if breaker is not None else CircuitBreaker()
        # chain, don't clobber: a caller-supplied on_transition (alerting
        # hooks etc.) keeps firing after the service's event forwarding
        self._chained_transition = self.breaker.on_transition
        self.breaker.on_transition = self._on_breaker_transition
        self.fallback = fallback
        if max_queue_depth is None:
            max_queue_depth = 16 * max(self.engine.batch_buckets)
        self.batcher = MicroBatcher(
            dispatch=self._dispatch,
            capacity=max(self.engine.batch_buckets),
            max_wait=max_wait_ms / 1000.0,
            on_error=self._on_dispatch_error,
            max_depth=max_queue_depth if max_queue_depth else None,
        )
        self._count_lock = threading.Lock()
        self._requests = 0
        self._errors = 0
        self._shed = 0
        self._deadline_misses = 0
        self._cancelled = 0
        self._circuit_refusals = 0
        self._served_from: Dict[str, int] = {
            "hit": 0, "advance": 0, "cold": 0, "fallback": 0
        }
        self._served_by: Dict[str, int] = {"primary": 0, "cache_only": 0, "fallback": 0}
        # per-traffic-role accounting (stable vs canary candidate): the raw
        # material PromotionController folds into replay_canary_* gauges
        self._role_stats: Dict[str, Dict[str, float]] = {
            role: self._fresh_role_stats() for role in ROLES
        }
        # hot-swap staleness accounting: submit-time embedding misses (cached
        # state encoded by an older generation) and dispatch-time re-routes
        # (the generation moved between submit and batch build)
        self._generation_misses = 0
        self._generation_reroutes = 0
        # key -> (last_emit_time, pending_count, event, payload); pending
        # counts are flushed by the key's next post-window emit or at close()
        self._throttle: Dict[str, Tuple[float, int, str, Dict[str, Any]]] = {}
        self._queue_wait_sum = 0.0
        self._queue_wait_max = 0.0
        self._goodput_t0: Dict[str, float] = {}
        self._wall_t0 = 0.0
        self._started = False
        # live metrics plane (obs.metrics / obs.exporter / obs.slo): the
        # service's own event stream bridged into a scrapeable registry —
        # no new instrumentation hooks, the _emit fan-out IS the bridge
        self.metrics_registry = None
        self.metrics_exporter = None
        self._metrics_logger = None
        if metrics_port is not None or slo_rules:
            from replay_tpu.obs.exporter import MetricsExporter
            from replay_tpu.obs.metrics import MetricsLogger
            from replay_tpu.obs.slo import SLOWatchdog

            self._metrics_logger = MetricsLogger()
            self.metrics_registry = self._metrics_logger.registry
            if slo_rules:
                self._metrics_logger.watchdog = SLOWatchdog(
                    slo_rules, self.metrics_registry, emit=self._route_event
                )
            if metrics_port is not None:
                # the structured /healthz (format=json) serves the heartbeat
                # document, so a REMOTE fleet monitor can drive ReplicaHealth
                # from a pure scrape of this port (serve.remote)
                self.metrics_exporter = MetricsExporter(
                    self.metrics_registry,
                    port=metrics_port,
                    health_source=self.heartbeat,
                )
        # quality plane (obs.quality): same zero-new-hooks pattern — the
        # monitor watches resolved responses and emits on_quality_window /
        # on_drift_warning back through THIS service's _emit fan-out, so its
        # gauges ride the existing metrics bridge, exporter and SLO watchdog
        self.quality = quality
        if quality is not None:
            quality.bind(self._emit, self._emit_throttled)
        # flight recorder (obs.blackbox): same attach-the-sink pattern — the
        # _emit fan-out carries every serve event into the SIGKILL-proof ring
        self._blackbox = None
        flight_path = flight_path or os.environ.get("REPLAY_TPU_FLIGHT_PATH")
        if flight_path:
            from replay_tpu.obs.blackbox import BlackboxLogger

            try:
                self._blackbox = BlackboxLogger(
                    flight_path,
                    meta={"role": "serve", "pid": os.getpid(), "mode": self.mode},
                )
            except OSError as exc:
                logger.warning(
                    "flight recorder: cannot open %s (%s); service runs unrecorded",
                    flight_path, exc,
                )

    # -- lifecycle ---------------------------------------------------------- #
    def start(self) -> "ScoringService":
        if self._started:
            return self
        self._started = True
        self._goodput_t0 = self.tracer.snapshot()
        self._wall_t0 = self.tracer.wall_seconds()
        if self.metrics_exporter is not None:
            self.metrics_exporter.start()
        self.batcher.start()
        self._emit(
            "on_serve_start",
            {
                "mode": self.mode,
                "length_buckets": list(self.engine.length_buckets),
                "batch_buckets": list(self.engine.batch_buckets),
                "max_wait_ms": self.batcher.max_wait * 1000.0,
                "cache_capacity": self.cache.capacity,
                "max_queue_depth": self.batcher.max_depth,
                "default_deadline_ms": self.default_deadline_ms,
                "fallback": self.fallback is not None,
            },
        )
        return self

    def close(self) -> None:
        """Stop the service. Every still-pending future is RESOLVED before
        this returns — flushed through the engine when the worker is healthy,
        failed with a real exception when it is not (never left to hang)."""
        if not self._started:
            return
        self.batcher.stop()
        self._started = False
        if self.quality is not None:
            # partial windows land before the terminal event — the last
            # quality gauges are in the registry when on_serve_end snapshots
            try:
                self.quality.flush()
            except Exception:  # noqa: BLE001 — telemetry must not fail close
                pass
        self._flush_throttled()
        payload = dict(self.stats())
        snapshot = self.tracer.snapshot()
        diff = {
            name: snapshot.get(name, 0.0) - self._goodput_t0.get(name, 0.0)
            for name in set(snapshot) | set(self._goodput_t0)
        }
        payload["goodput"] = goodput_breakdown(
            diff,
            self.tracer.wall_seconds() - self._wall_t0,
            spans=SERVE_GOODPUT_SPANS,
        )
        self._emit("on_serve_end", payload)
        if self._blackbox is not None:
            # one msync after the terminal event — durability against machine
            # loss; SIGKILL durability never depended on this close landing
            self._blackbox.close()
        if self.metrics_exporter is not None:
            # after the terminal event: the final gauges (hit rate, shed
            # rate) land in the registry before the endpoint disappears, and
            # registry/snapshot stay readable on metrics_registry afterwards
            self.metrics_exporter.close()
        if self.trace_path and self.tracer.enabled:
            self.tracer.save(self.trace_path)

    def __enter__(self) -> "ScoringService":
        return self.start()

    def __exit__(self, *exc_info) -> None:
        self.close()

    # -- client API --------------------------------------------------------- #
    def submit(
        self,
        user_id: Hashable,
        history: Optional[Sequence[int]] = None,
        new_items: Sequence[int] = (),
        k: Optional[int] = None,
        candidates: Optional[Sequence[int]] = None,
        deadline_ms: Optional[float] = None,
        _role: Optional[str] = None,
        _trace: Optional[dict] = None,
    ) -> "Future[ScoreResponse]":
        """Enqueue one scoring request; resolves to a :class:`ScoreResponse`.

        Never blocks and never hangs: admission refusals (a full lane, an open
        breaker with no degraded mode available) fail the returned future
        immediately with :class:`RequestShed` / :class:`CircuitOpen`, and a
        ``deadline_ms`` budget (default: the service's ``default_deadline_ms``)
        drops the request at batch-build time once expired.

        ``_role`` forces the traffic-slice routing ("stable"/"candidate") —
        the shadow-stage probe seam; normal traffic routes by the canary's
        deterministic hash slice.

        ``_trace`` is the fleet router's distributed-trace context (the
        pure-JSON ``TraceContext.to_json()`` payload): when present, this
        request's replica-side spans — ``queue_wait``, its batch's
        build/score window, a fallback answer — carry its trace_id, so the
        merged fleet trace shows the request's replica time on its own
        timeline. ``None`` (untraced/direct traffic) changes nothing.
        """
        future: "Future[ScoreResponse]" = Future()
        if deadline_ms is None:
            deadline_ms = self.default_deadline_ms
        request = ScoreRequest(
            user_id=user_id,
            history=history,
            new_items=tuple(new_items),
            k=k,
            candidates=candidates,
            deadline_ms=deadline_ms,
        )
        role = _role if _role is not None else self._role_for(user_id)
        with self._count_lock:
            self._requests += 1
            self._role_stats[role]["requests"] += 1
        expires_at = (
            time.perf_counter() + deadline_ms / 1000.0
            if deadline_ms is not None  # 0.0 = already expired, NOT no-deadline
            else None
        )
        try:
            resolved = self._resolve(request, future, role, trace=_trace)
            if resolved is None:  # answered inline by the fallback floor
                return future
            lane, pending = resolved
            pending.expires_at = expires_at
            self._submit_pending(lane, pending)
        except CircuitOpen as exc:
            with self._count_lock:
                self._circuit_refusals += 1
            self._safe_fail(future, exc)
        except Exception as exc:  # noqa: BLE001 — surface through the future
            with self._count_lock:
                self._errors += 1
                self._role_stats[role]["errors"] += 1
            self._safe_fail(future, exc)
        return future

    def _submit_pending(self, lane, pending: PendingRequest) -> None:
        """Enqueue a resolved pending on its lane, walking the overload
        absorption ladder on a shed (shared by submit and the dispatch-time
        generation re-route)."""
        pending.canary_epoch = self._canary_epoch
        try:
            self.batcher.submit(lane, pending)
            self._emit_degraded(pending)
        except RequestShed as shed:
            if not self._absorb_overload(lane, pending, shed):
                with self._count_lock:
                    self._shed += 1
                    self._role_stats[pending.role]["shed"] += 1
                self._emit_throttled(
                    f"shed:{self._lane_name(lane)}",
                    "on_shed",
                    {
                        "lane": self._lane_name(lane),
                        "depth": shed.depth,
                        "max_depth": shed.max_depth,
                        "retry_after_s": shed.retry_after_s,
                    },
                )
                self._safe_fail(pending.future, shed)

    def score(self, user_id, timeout: Optional[float] = 60.0, **kwargs) -> ScoreResponse:
        """Synchronous :meth:`submit`.

        ``timeout`` doubles as the request's ``deadline_ms`` (unless one was
        passed explicitly), and a timed-out wait CANCELS the request so the
        batch builder skips it — an abandoned waiter never costs a scoring
        slot (the serving analog of the cache's stale-refresh drop).
        """
        if timeout is not None and "deadline_ms" not in kwargs:
            kwargs["deadline_ms"] = timeout * 1000.0
        future = self.submit(user_id, **kwargs)
        try:
            return future.result(timeout=timeout)
        except FutureTimeoutError:
            future.cancel()
            raise

    # -- promotion / hot-swap API (serve.promote) ---------------------------- #
    @staticmethod
    def _fresh_role_stats() -> Dict[str, float]:
        return {
            "requests": 0.0,
            "answered": 0.0,
            "errors": 0.0,
            "shed": 0.0,
            "queue_wait_ms_sum": 0.0,
            "queue_wait_ms_max": 0.0,
        }

    def _role_for(self, user_id: Hashable) -> str:
        canary = self._canary
        if canary is None:
            return "stable"
        _, fraction = canary
        return "candidate" if in_canary_slice(user_id, fraction) else "stable"

    def _generation_for(self, role: str):
        """The generation serving ``role`` RIGHT NOW. Candidate traffic under
        an active canary resolves the canary's PINNED generation — a
        publish_candidate racing the canary must not silently redirect the
        slice to an unvetted generation; outside a canary, the candidate role
        is the shadow-probe seam and resolves the store's candidate (falling
        back to stable)."""
        if role == "candidate":
            canary = self._canary
            if canary is not None:
                try:
                    return self.store.generation(canary[0])
                except KeyError:  # canary generation evicted: serve stable
                    return self.store.resolve("stable")
        return self.store.resolve(role)

    def publish_candidate(
        self, params, label: str = "", pipeline: Optional[CandidatePipeline] = None
    ) -> int:
        """Register a candidate parameter generation (not yet serving).

        Same-shape params (the common continual-finetune case) share the
        running executables — ZERO recompilation, the swap is a pointer move.
        A changed catalog shape (vocab surgery grew the item table) compiles a
        dedicated engine HERE, on the caller's thread, while the serve worker
        keeps answering from the current generation. Retrieval-mode services
        must pass the candidate's own :class:`CandidatePipeline` (its MIPS
        index embeds the item table, so it is per-generation by construction).
        """
        if self.mode == "retrieval" and pipeline is None:
            msg = (
                "retrieval-mode candidates need their own CandidatePipeline "
                "(the MIPS index embeds the generation's item table)"
            )
            raise ValueError(msg)
        import jax
        import jax.numpy as jnp

        # land the candidate on device ONCE at publish (uncommitted, dtypes
        # preserved) — every dispatch then passes resident arrays instead of
        # paying a host->device copy per batch
        params = jax.tree.map(jnp.asarray, params)
        mismatch = self.engine.validate_params(params)
        if mismatch is None:
            generation = self.store.publish(
                params, label=label, pipeline=pipeline, recompiled=False
            )
            reason = None
        else:
            # shape change: fresh executables, compiled off the serve worker
            engine = ScoringEngine(
                self._model,
                params,
                length_buckets=self.engine.length_buckets,
                batch_buckets=self.engine.batch_buckets,
                candidates=self.engine.candidates,
                feature_name=self._feature_name,
                outputs=self.engine.outputs,
            )
            generation = self.store.publish(
                params, label=label, pipeline=pipeline, engine=engine,
                recompiled=True,
            )
            reason = mismatch
        self._emit(
            "on_publish",
            {
                "generation": generation,
                "label": label,
                "recompiled": reason is not None,
                "recompile_reason": reason,
            },
        )
        return generation

    def begin_canary(self, generation: int, fraction: float) -> None:
        """Route the deterministic ``fraction`` slice of users to
        ``generation`` (which must be resident in the store)."""
        self.store.generation(generation)  # raises when not resident
        with self._count_lock:
            # a canary window starts with clean candidate counters AND a new
            # epoch, so its evaluations never read a previous candidate's
            # traffic — including in-flight requests stamped before the reset
            self._role_stats["candidate"] = self._fresh_role_stats()
            self._canary = (int(generation), float(fraction))
            self._canary_epoch += 1
        self._emit(
            "on_canary_start", {"generation": generation, "fraction": fraction}
        )

    def end_canary(self) -> None:
        with self._count_lock:
            self._canary = None

    def promote(self, generation: Optional[int] = None) -> Dict[str, Any]:
        """Atomically swap ``generation`` (default: the candidate) in as the
        stable serving params; the outgoing generation stays pinned as the
        rollback target. In-flight batches finish on the generation they
        resolved — no torn reads."""
        info = self.store.promote(generation)
        self.end_canary()
        self._swap_event("promote", info)
        return info

    def rollback(self) -> Dict[str, Any]:
        """Atomically restore the pinned previous generation (bad swap /
        breached canary)."""
        info = self.store.rollback()
        self.end_canary()
        self._swap_event("rollback", info)
        return info

    def _swap_event(self, reason: str, info: Dict[str, Any]) -> None:
        try:
            recompiled = self.store.generation(info["to_generation"]).recompiled
        except KeyError:
            recompiled = None
        self._emit(
            "on_swap",
            {
                "reason": reason,
                "from_generation": info["from_generation"],
                "to_generation": info["to_generation"],
                "recompiled": recompiled,
            },
        )

    def canary_stats(self) -> Dict[str, Dict[str, float]]:
        """Cumulative per-role counters (stable vs candidate) with derived
        mean queue wait — the PromotionController's evaluation input."""
        with self._count_lock:
            out = {role: dict(stats) for role, stats in self._role_stats.items()}
        for stats in out.values():
            answered = stats["answered"]
            stats["queue_wait_ms_mean"] = (
                stats["queue_wait_ms_sum"] / answered if answered else 0.0
            )
        return out

    def generation_history(self) -> List[Dict[str, Any]]:
        """The store's publish/promote/rollback log (pure JSON artifact)."""
        return self.store.history()

    # -- request resolution (client thread) --------------------------------- #
    def _resolve(
        self,
        request: ScoreRequest,
        future: "Future[ScoreResponse]",
        role: str = "stable",
        trace: Optional[dict] = None,
    ) -> Optional[Tuple[Hashable, PendingRequest]]:
        """Route a request to a (lane, pending) — or answer it inline
        (fallback floor, returning None). ``trace`` (the fleet's JSON trace
        context) rides on whatever pending this resolves to."""
        if request.candidates is not None and self.mode != "full":
            msg = (
                f"per-request candidates need the full-scoring service "
                f"(this one runs in {self.mode!r} mode)"
            )
            raise ValueError(msg)
        if request.k is not None and self.retrieval is not None:
            if request.k > self.retrieval.top_k:
                msg = (
                    f"k={request.k} exceeds the pipeline's compiled "
                    f"top_k={self.retrieval.top_k}"
                )
                raise ValueError(msg)
        max_len = self.engine.max_sequence_length

        if request.history is not None:
            # the exact-parity fallback: an explicit history always wins and
            # re-anchors the cached state
            items = list(request.history) + list(request.new_items)
            if not items:
                msg = "empty history"
                raise ValueError(msg)
            window, mask, length = make_window(items, max_len, self.pad_id)
            previous = self.cache.peek(request.user_id)
            state = UserState(
                window=window,
                mask=mask,
                length=length,
                embedding=None,
                generation=previous.generation + 1 if previous else 0,
            )
            self.cache.store(request.user_id, state)
            return self._encode_or_degrade(
                request, future, state, "cold", previous, role, trace=trace
            )

        if request.new_items:
            # atomic lookup+advance+store: concurrent appends for one user
            # must both land (an unlocked read-modify-write would let the
            # last store erase the other's interaction). The pre-advance
            # embedding is peeked first: it is the cache_only rung's material
            # if the encode path is down (the interaction still lands either
            # way — degradation never loses the event)
            previous = self.cache.peek(request.user_id)
            advanced = self.cache.advance_user(
                request.user_id, request.new_items, self.pad_id
            )
            if advanced is None:
                return self._cold_miss(request, future, role, trace=trace)
            return self._encode_or_degrade(
                request, future, advanced, "advance", previous, role, trace=trace
            )
        state = self.cache.lookup(request.user_id)
        if state is None:
            return self._cold_miss(request, future, role, trace=trace)
        if state.embedding is not None:
            # hot-swap staleness guard (serve.promote): an embedding encoded
            # by an older parameter generation must never be scored through
            # the current generation's scorer — a generation mismatch is a
            # MISS and the cached window re-encodes instead
            current_generation = self._generation_for(role).number
            if state.param_generation != current_generation:
                with self._count_lock:
                    self._generation_misses += 1
            else:
                pending = PendingRequest(
                    request=request,
                    future=future,
                    served_from="hit",
                    embedding=state.embedding,
                    length=state.length,
                    enqueued_at=self.tracer.now(),
                    extra=(state,),
                    role=role,
                    embedding_generation=state.param_generation,
                    trace=trace,
                )
                return ("hit", role), pending
        # cached window whose embedding is still in flight (or was raced
        # away, or certifies an older param generation): re-encode the cached
        # window — still no history re-send
        return self._encode_or_degrade(
            request, future, state, "advance", state, role, trace=trace
        )

    def _cold_miss(
        self,
        request: ScoreRequest,
        future: "Future[ScoreResponse]",
        role: str,
        trace: Optional[dict] = None,
    ) -> Optional[Tuple[Hashable, PendingRequest]]:
        """A state-less request with no history: error (the original
        contract) or the ladder floor (``cold_miss="fallback"`` — the fleet
        failover setting, where the user's cache lives on a replica that just
        died and a popularity answer beats an exception). ``new_items``
        requests ALWAYS error here, even under ``cold_miss="fallback"``:
        without a cached window the interaction cannot land, and a success
        response over a silently dropped event is worse than an explicit
        "re-anchor with history=" refusal (degradation never loses an event
        — docs/robustness.md "Fleet failover semantics")."""
        if request.new_items:
            msg = (
                f"user {request.user_id!r} has no cached state to advance; "
                "re-anchor with history= (the new_items interaction cannot "
                "land without a window)"
            )
            raise KeyError(msg)
        if self.cold_miss == "fallback" and self.fallback is not None:
            self._finish_fallback(
                request, future, reason="cold_miss", role=role, trace=trace
            )
            return None
        msg = (
            f"user {request.user_id!r} has no cached state; "
            "provide history= for the cold path"
        )
        raise KeyError(msg)

    def _encode_or_degrade(
        self,
        request: ScoreRequest,
        future: "Future[ScoreResponse]",
        state: UserState,
        served_from: str,
        previous: Optional[UserState],
        role: str = "stable",
        trace: Optional[dict] = None,
    ) -> Optional[Tuple[Hashable, PendingRequest]]:
        """The primary encode route, gated by the breaker; refused traffic
        walks the degradation ladder instead."""
        stale_embedding = previous.embedding if previous is not None else None
        stale_length = previous.length if previous is not None else 0
        stale_generation = previous.param_generation if previous is not None else 0
        if self.breaker.allow():
            lane, pending = self._encode_pending(
                request, future, state, served_from, role, trace=trace
            )
            pending.stale_embedding = stale_embedding
            pending.stale_length = stale_length
            pending.embedding_generation = stale_generation
            return lane, pending
        return self._degrade(
            request, future, stale_embedding, stale_length, stale_generation,
            role, reason="breaker_open", trace=trace,
        )

    def _cache_only_pending(
        self,
        request: ScoreRequest,
        future: "Future[ScoreResponse]",
        embedding: np.ndarray,
        length: int,
        reason: str,
        expires_at: Optional[float] = None,
        role: str = "stable",
        embedding_generation: int = 0,
        trace: Optional[dict] = None,
    ) -> PendingRequest:
        """The cache_only rung's pending: the stale cached state routed to the
        hit lane. The on_degrade emit happens at enqueue success, not here."""
        return PendingRequest(
            request=request,
            future=future,
            served_from="hit",
            embedding=embedding,
            length=length,
            enqueued_at=self.tracer.now(),
            expires_at=expires_at,
            served_by="cache_only",
            degrade_reason=reason,
            role=role,
            embedding_generation=embedding_generation,
            trace=trace,
        )

    def _emit_degraded(self, pending: PendingRequest) -> None:
        """Called once the degraded pending is SAFELY enqueued: a cache_only
        attempt that gets shed and re-rides the fallback floor must log one
        degrade event — for the rung that actually took it."""
        if pending.served_by == "cache_only" and pending.degrade_reason:
            self._emit_throttled(
                f"degrade:cache_only:{pending.degrade_reason}",
                "on_degrade",
                {"to": "cache_only", "reason": pending.degrade_reason},
            )

    def _degrade(
        self,
        request: ScoreRequest,
        future: "Future[ScoreResponse]",
        stale_embedding: Optional[np.ndarray],
        stale_length: int,
        stale_generation: int,
        role: str,
        reason: str,
        trace: Optional[dict] = None,
    ) -> Optional[Tuple[Hashable, PendingRequest]]:
        """Walk the ladder below primary: cache_only (hit lane on the stale
        cached state), then the fallback floor, then an explicit refusal."""
        if stale_embedding is not None:
            pending = self._cache_only_pending(
                request, future, stale_embedding, stale_length, reason,
                role=role, embedding_generation=stale_generation, trace=trace,
            )
            return ("hit", role), pending
        if self.fallback is not None:
            self._finish_fallback(
                request, future, reason=reason, role=role, trace=trace
            )
            return None
        raise CircuitOpen(self.breaker.retry_after_s())

    def _absorb_overload(
        self, lane, pending: PendingRequest, shed: RequestShed
    ) -> bool:
        """A shed encode-lane request may still ride a cheaper rung: the hit
        lane on its stale cached state, else the fallback floor. Returns
        whether the request was absorbed."""
        request = pending.request
        role = pending.role
        if lane[0] != "hit" and pending.stale_embedding is not None:
            degraded = self._cache_only_pending(
                request,
                pending.future,
                pending.stale_embedding,
                pending.stale_length,
                reason="overload",
                expires_at=pending.expires_at,
                role=role,
                embedding_generation=pending.embedding_generation,
                trace=pending.trace,
            )
            degraded.canary_epoch = pending.canary_epoch
            try:
                self.batcher.submit(("hit", role), degraded)
            except RequestShed:
                pass  # hit lane saturated too — next rung
            else:
                self._emit_degraded(degraded)
                return True
        if self.fallback is not None:
            self._finish_fallback(
                request, pending.future, reason="overload", role=role,
                trace=pending.trace,
            )
            return True
        return False

    def _finish_fallback(
        self,
        request: ScoreRequest,
        future: "Future[ScoreResponse]",
        reason: str,
        role: str = "stable",
        trace: Optional[dict] = None,
    ) -> None:
        response = self._fallback_response(request)
        response.role = role
        self._observe_quality(response, request)
        if self._safe_set_result(future, response):
            with self._count_lock:
                # under _count_lock: += on the scorer attribute is a
                # read-modify-write racing client threads otherwise
                self.fallback.served += 1
                self._served_by["fallback"] += 1
                self._served_from["fallback"] += 1
                self._role_stats[role]["answered"] += 1
            if trace:
                # the degradation ladder's floor, as a timeline marker: a
                # traced request answered inline by the host-side scorer shows
                # WHERE on its timeline it left the primary path, and why
                self.tracer.add_span(
                    "fallback", self.tracer.now(), 0.0,
                    trace_id=trace.get("trace_id"), served_by="fallback",
                    reason=reason,
                )
            self._emit_throttled(
                f"degrade:fallback:{reason}",
                "on_degrade",
                {"to": "fallback", "reason": reason},
            )

    def _fallback_response(self, request: ScoreRequest) -> ScoreResponse:
        """Host-side popularity answer shaped like the mode's primary one."""
        if self.retrieval is not None:
            k = request.k if request.k is not None else self.retrieval.top_k
            scores, item_ids = self.fallback.score(k=k)
        elif self.mode == "slate":
            scores, item_ids = self.fallback.score(
                candidates=np.asarray(self.engine.candidates, np.int64)
            )
            if request.k is not None:
                order = np.argsort(-scores, kind="stable")[: request.k]
                scores, item_ids = scores[order], item_ids[order]
        elif request.candidates is not None:
            scores, item_ids = self.fallback.score(candidates=request.candidates)
        elif request.k is not None:
            scores, item_ids = self.fallback.score(k=request.k)
        else:
            scores, item_ids = self.fallback.score()
        return ScoreResponse(
            user_id=request.user_id,
            scores=np.asarray(scores),
            item_ids=np.asarray(item_ids) if item_ids is not None else None,
            served_from="fallback",
            lane="fallback",
            queue_wait_s=0.0,
            batch_bucket=0,
            served_by="fallback",
            generation=-1,  # host-side floor: no device generation scored this
        )

    def _encode_pending(
        self,
        request: ScoreRequest,
        future: "Future[ScoreResponse]",
        state: UserState,
        served_from: str,
        role: str = "stable",
        trace: Optional[dict] = None,
    ) -> Tuple[Hashable, PendingRequest]:
        length_bucket = self.engine.route_length(state.length)
        pending = PendingRequest(
            request=request,
            future=future,
            served_from=served_from,
            window=state.window,
            mask=state.mask,
            length=state.length,
            enqueued_at=self.tracer.now(),
            extra=(state,),
            role=role,
            trace=trace,
        )
        return ("encode", length_bucket, role), pending

    # -- dispatch (serve-worker thread) ------------------------------------- #
    def _on_dispatch_error(self, lane, items: List[PendingRequest], exc: BaseException) -> None:
        role = self._lane_role(lane)
        failed = counted = 0
        for item in items:
            if self._safe_fail(item.future, exc):
                failed += 1
                if self._counts_for_role(role, item):
                    counted += 1
        with self._count_lock:
            self._errors += failed
            self._role_stats[role]["errors"] += counted

    def _counts_for_role(self, role: str, item: PendingRequest) -> bool:
        """Whether this outcome belongs in the role's canary accounting: a
        previous candidate's late-landing in-flight request (older canary
        epoch) must not pollute the CURRENT canary's evaluation window."""
        return role != "candidate" or item.canary_epoch == self._canary_epoch

    @staticmethod
    def _lane_role(lane) -> str:
        # both lane kinds carry the routing role last: ("hit", role) and
        # ("encode", L, role)
        return lane[-1]

    def _lane_name(self, lane) -> str:
        base = "hit" if lane[0] == "hit" else f"encode:L={lane[1]}"
        role = self._lane_role(lane)
        # stable lanes keep the PR-6 names; canary traffic is visibly its own
        # lane family (own queues, own shed keys, single-generation batches)
        return base if role == "stable" else f"{base}#canary"

    def _admit(
        self, items: List[PendingRequest]
    ) -> Tuple[List[PendingRequest], int, int]:
        """Batch-build admission: drop expired-deadline and client-abandoned
        requests BEFORE any device work, committing the survivors (their
        futures move to RUNNING, so a late ``cancel()`` no longer bites)."""
        now = time.perf_counter()
        live: List[PendingRequest] = []
        expired = abandoned = 0
        for item in items:
            future = item.future
            if future.done():
                abandoned += 1  # failed at close/crash, or cancelled+finalized
                continue
            if item.expires_at is not None and now >= item.expires_at:
                deadline_s = (item.request.deadline_ms or 0.0) / 1000.0
                waited = now - (item.expires_at - deadline_s)
                self._safe_fail(future, DeadlineExceeded(waited, deadline_s))
                expired += 1
                continue
            if not self._mark_running(future):
                abandoned += 1  # score(timeout=...) gave up on this waiter
                continue
            live.append(item)
        if expired or abandoned:
            with self._count_lock:
                self._deadline_misses += expired
                self._cancelled += abandoned
        return live, expired, abandoned

    def _dispatch(self, lane, items: List[PendingRequest]) -> None:
        role = self._lane_role(lane)
        # ONE generation resolution per dispatched batch: encoder, scorer and
        # retrieval pipeline below all use this immutable object — a
        # concurrent promote/rollback changes the NEXT batch, never tears
        # this one between its stages (canary batches resolve the canary's
        # PINNED generation, never a just-published unvetted candidate)
        gen = self._generation_for(role)
        if lane[0] == "hit":
            self._dispatch_hit(lane, role, gen, items)
        else:
            self._dispatch_encode(lane, role, gen, items)

    def _dispatch_hit(self, lane, role: str, gen, items: List[PendingRequest]) -> None:
        """The hit lane under hot swaps: an embedding is only ever scored by
        the generation that ENCODED it. Current-generation items ride the
        bulk path; items whose generation moved on mid-flight finish on the
        generation they started (still resident — the store pins it); items
        whose generation left the store re-encode (primary) or fall to the
        floor (cache_only has no encode to return to)."""
        current: List[PendingRequest] = []
        stale: Dict[int, List[PendingRequest]] = {}
        for item in items:
            if int(item.embedding_generation) == gen.number:
                current.append(item)
            else:
                stale.setdefault(int(item.embedding_generation), []).append(item)
        expired = abandoned = 0
        for number, group in sorted(stale.items()):
            try:
                stale_gen = self.store.generation(number)
            except KeyError:
                stale_gen = None
            if stale_gen is None:
                with self._count_lock:
                    self._generation_reroutes += len(group)
                for item in group:
                    if item.served_by == "primary":
                        self._requeue_encode(item, role)
                    elif self.fallback is not None:
                        self._finish_fallback(
                            item.request, item.future,
                            reason="generation_evicted", role=role,
                        )
                    else:
                        # same accounting as a submit-time CircuitOpen: this
                        # refusal must not vanish from stats()
                        with self._count_lock:
                            self._circuit_refusals += 1
                        self._safe_fail(
                            item.future, CircuitOpen(self.breaker.retry_after_s())
                        )
                continue
            group, group_expired, group_abandoned = self._admit(group)
            expired += group_expired
            abandoned += group_abandoned
            if group:
                self._score_hit_batch(lane, role, stale_gen, group, 0, 0)
        current, current_expired, current_abandoned = self._admit(current)
        expired += current_expired
        abandoned += current_abandoned
        if not current:
            if expired or abandoned:
                # a fully-dropped batch (deadline storm, mass abandonment) is
                # exactly the batch the drop accounting must not go dark on
                self._emit_batch(lane, 0, 0, [], expired, abandoned)
            return
        self._score_hit_batch(lane, role, gen, current, expired, abandoned)

    def _trace_args(self, item: PendingRequest) -> dict:
        """Span args keying a per-request span to its distributed trace.

        Empty (and allocation-free for the common case) when the request
        arrived untraced — the span renders as before; with a fleet-forwarded
        trace context the replica-side span joins the request's timeline."""
        if item.trace is None:
            return {}
        return {"trace_id": item.trace.get("trace_id"), "served_by": item.served_by}

    def _batch_trace_ids(self, items: List[PendingRequest]) -> dict:
        """Span args for a BATCH-scoped span (build/score/retrieve/rerank):
        every traced co-rider's trace_id, as one ``trace_ids`` list — the
        whole batch window is attributed to each traced request riding it."""
        traced = [
            item.trace["trace_id"]
            for item in items
            if item.trace is not None and "trace_id" in item.trace
        ]
        return {"trace_ids": traced} if traced else {}

    def _score_hit_batch(
        self,
        lane,
        role: str,
        gen,
        items: List[PendingRequest],
        expired: int,
        abandoned: int,
    ) -> None:
        waits = [
            lifecycle_span(
                self.tracer, "queue_wait", item.enqueued_at,
                lane=self._lane_name(lane), **self._trace_args(item),
            )
            for item in items
        ]
        rows = len(items)
        batch_trace = self._batch_trace_ids(items)
        engine = gen.engine if gen.engine is not None else self.engine
        bucket = engine.batch_bucket(rows)
        with self.tracer.span("batch_build", rows=rows, **batch_trace):
            hidden = np.stack([item.embedding for item in items]).astype(np.float32)
        if self.mode == "retrieval":
            engine.record_ranked_batch(rows, bucket)
            pipeline = gen.pipeline if gen.pipeline is not None else self.retrieval
            scores, ids = self._rank(pipeline, hidden, rows, bucket, batch_trace)
            logits = None
        else:
            with self.tracer.span("score", rows=rows, lane="hit", **batch_trace):
                logits = np.asarray(engine.score_hidden(hidden, params=gen.params))
            scores = ids = None
        self._resolve_batch_futures(
            items, waits, lane, bucket, gen.number, role, logits, scores, ids
        )
        self._emit_batch(lane, rows, bucket, waits, expired, abandoned)

    def _dispatch_encode(self, lane, role: str, gen, items: List[PendingRequest]) -> None:
        items, expired, abandoned = self._admit(items)
        if not items:
            if expired or abandoned:
                self._emit_batch(lane, 0, 0, [], expired, abandoned)
            return
        waits = [
            lifecycle_span(
                self.tracer, "queue_wait", item.enqueued_at,
                lane=self._lane_name(lane), **self._trace_args(item),
            )
            for item in items
        ]
        rows = len(items)
        batch_trace = self._batch_trace_ids(items)
        _, length_bucket, _ = lane
        engine = gen.engine if gen.engine is not None else self.engine
        bucket = engine.batch_bucket(rows)
        with self.tracer.span("batch_build", rows=rows, **batch_trace):
            ids_batch = np.stack([item.window[-length_bucket:] for item in items])
            mask_batch = np.stack([item.mask[-length_bucket:] for item in items])
        with self.tracer.span("score", rows=rows, lane=self._lane_name(lane), **batch_trace):
            # the breaker's raw material: one engine call = one outcome
            # (a batch-wide exception counts once, not once per rider)
            try:
                logits_dev, hidden_dev = engine.encode(
                    length_bucket, ids_batch, mask_batch, params=gen.params
                )
                hidden_np = np.asarray(hidden_dev)
                logits = np.asarray(logits_dev) if logits_dev is not None else None
            except Exception:
                self.breaker.record_failure()
                raise
            self.breaker.record_success()
        for item, embedding in zip(items, hidden_np):
            state = item.extra[0]
            self.cache.refresh_embedding(
                item.request.user_id, state, embedding, param_generation=gen.number
            )
        if self.mode == "retrieval":
            pipeline = gen.pipeline if gen.pipeline is not None else self.retrieval
            scores, ids = self._rank(pipeline, hidden_np, rows, bucket, batch_trace)
        else:
            scores = ids = None
        self._resolve_batch_futures(
            items, waits, lane, bucket, gen.number, role, logits, scores, ids
        )
        self._emit_batch(lane, rows, bucket, waits, expired, abandoned)

    def _requeue_encode(self, item: PendingRequest, role: str) -> None:
        """Dispatch-time generation re-route: the embedding's generation left
        the store between submit and batch build — re-encode the cached
        window rather than score old hidden states through new weights."""
        state = item.extra[0] if item.extra else None
        if state is None:
            self._safe_fail(
                item.future,
                RuntimeError("hit-lane pending carries no cached state to re-encode"),
            )
            return
        try:
            resolved = self._encode_or_degrade(
                item.request, item.future, state, "advance", state, role,
                trace=item.trace,
            )
        except CircuitOpen as exc:
            with self._count_lock:
                self._circuit_refusals += 1
            self._safe_fail(item.future, exc)
            return
        except Exception as exc:  # noqa: BLE001 — surface through the future
            with self._count_lock:
                self._errors += 1
                self._role_stats[role]["errors"] += 1
            self._safe_fail(item.future, exc)
            return
        if resolved is None:  # answered inline by the fallback floor
            return
        new_lane, pending = resolved
        pending.expires_at = item.expires_at
        self._submit_pending(new_lane, pending)

    def _resolve_batch_futures(
        self,
        items: List[PendingRequest],
        waits: List[float],
        lane,
        bucket: int,
        generation: int,
        role: str,
        logits: Optional[np.ndarray],
        scores: Optional[np.ndarray],
        ids: Optional[np.ndarray],
    ) -> None:
        lane_name = self._lane_name(lane)
        for row, (item, wait) in enumerate(zip(items, waits)):
            try:
                response = self._build_response(
                    item,
                    lane_name=lane_name,
                    batch_bucket=bucket,
                    queue_wait=wait,
                    logits_row=logits[row] if logits is not None else None,
                    ranked_scores=scores[row] if scores is not None else None,
                    ranked_ids=ids[row] if ids is not None else None,
                    generation=generation,
                    role=role,
                )
            except Exception as exc:  # noqa: BLE001
                if self._safe_fail(item.future, exc):
                    with self._count_lock:
                        self._errors += 1
                        if self._counts_for_role(role, item):
                            self._role_stats[role]["errors"] += 1
                continue
            # observe BEFORE resolving: a client that saw result() return is
            # guaranteed its response was already counted by the quality
            # monitor — the reconciliation contract the online/offline parity
            # test (and the bench's join accounting) leans on
            self._observe_quality(response, item.request)
            if not self._safe_set_result(item.future, response):
                with self._count_lock:
                    self._cancelled += 1
                continue
            with self._count_lock:
                self._served_from[item.served_from] += 1
                self._served_by[item.served_by] += 1
                self._queue_wait_sum += wait
                self._queue_wait_max = max(self._queue_wait_max, wait)
                if self._counts_for_role(role, item):
                    stats = self._role_stats[role]
                    stats["answered"] += 1
                    stats["queue_wait_ms_sum"] += wait * 1000.0
                    stats["queue_wait_ms_max"] = max(
                        stats["queue_wait_ms_max"], wait * 1000.0
                    )

    def _emit_batch(
        self, lane, rows: int, bucket: int, waits: List[float], expired: int, abandoned: int
    ) -> None:
        self._emit(
            "on_serve_batch",
            {
                "lane": self._lane_name(lane),
                "rows": rows,
                "bucket": bucket,
                "fill": rows / bucket if bucket else 0.0,
                "queue_wait_ms_max": max(waits) * 1000.0 if waits else 0.0,
                "dropped_expired": expired,
                "dropped_cancelled": abandoned,
            },
        )

    def _rank(
        self,
        pipeline: CandidatePipeline,
        hidden: np.ndarray,
        rows: int,
        bucket: int,
        span_args: Optional[dict] = None,
    ):
        """Run the fused retrieve→rerank path at the padded batch bucket —
        the pipeline's jitted programs then only ever see the bucket ladder's
        shapes (no per-fill retrace)."""
        if rows < bucket:
            hidden = np.concatenate([hidden, np.repeat(hidden[:1], bucket - rows, 0)])
        scores, ids = pipeline.rank(hidden, tracer=self.tracer, span_args=span_args)
        return scores[:rows], ids[:rows]

    def _build_response(
        self,
        item: PendingRequest,
        lane_name: str,
        batch_bucket: int,
        queue_wait: float,
        logits_row: Optional[np.ndarray],
        ranked_scores: Optional[np.ndarray],
        ranked_ids: Optional[np.ndarray],
        generation: int = 0,
        role: str = "stable",
    ) -> ScoreResponse:
        request = item.request
        if self.retrieval is not None:
            k = request.k if request.k is not None else self.retrieval.top_k
            scores, item_ids = ranked_scores[:k], ranked_ids[:k]
        elif self.mode == "slate":
            scores, item_ids = logits_row, np.asarray(self.engine.candidates)
            if request.k is not None:
                order = np.argsort(-scores, kind="stable")[: request.k]
                scores, item_ids = scores[order], item_ids[order]
        else:
            if request.candidates is not None:
                gathered = np.asarray(request.candidates, np.int64)
                scores, item_ids = logits_row[gathered], gathered
            elif request.k is not None:
                order = np.argsort(-logits_row, kind="stable")[: request.k]
                scores, item_ids = logits_row[order], order
            else:
                scores, item_ids = logits_row, None
        return ScoreResponse(
            user_id=request.user_id,
            scores=np.asarray(scores),
            item_ids=np.asarray(item_ids) if item_ids is not None else None,
            served_from=item.served_from,
            lane=lane_name,
            queue_wait_s=queue_wait,
            batch_bucket=batch_bucket,
            served_by=item.served_by,
            generation=generation,
            role=role,
        )

    # -- future resolution helpers (shared with the fleet: serve.futures) --- #
    _mark_running = staticmethod(mark_running)
    _safe_fail = staticmethod(safe_fail)
    _safe_set_result = staticmethod(safe_set_result)

    def _observe_quality(self, response: ScoreResponse, request: ScoreRequest) -> None:
        """Feed one resolved response to the quality monitor. A broken monitor
        detaches itself rather than poison the serving path: quality telemetry
        is strictly best-effort."""
        monitor = self.quality
        if monitor is None:
            return
        try:
            monitor.observe(response, request)
        except Exception:  # noqa: BLE001
            self.quality = None
            logger.exception(
                "quality monitor raised; detached — responses keep flowing "
                "unobserved"
            )

    # -- accounting --------------------------------------------------------- #
    def _route_event(self, event: TrainerEvent) -> None:
        """Fan one event out to the metrics bridge, the flight ring and the
        user sink (the SLO watchdog's emit target too, so violations land in
        all of them)."""
        if self._metrics_logger is not None:
            self._metrics_logger.log_event(event)
        if self._blackbox is not None:
            self._blackbox.log_event(event)
        if self.logger is not None:
            self.logger.log_event(event)

    def _emit(self, event: str, payload: Dict[str, Any]) -> None:
        if (
            self._metrics_logger is None
            and self._blackbox is None
            and self.logger is None
        ):
            return
        self._route_event(TrainerEvent(event=event, payload=payload))

    def _emit_throttled(
        self, key: str, event: str, payload: Dict[str, Any], min_interval: float = 0.5
    ) -> None:
        """Per-key rate-limited emit: the first occurrence always lands, then
        at most one event per ``min_interval`` carrying the coalesced
        ``count`` — an overload storm must not flood events.jsonl."""
        now = time.perf_counter()
        with self._count_lock:
            entry = self._throttle.get(key)
            last, pending_count = (entry[0], entry[1]) if entry else (None, 0)
            pending_count += 1
            if last is None or now - last >= min_interval:
                self._throttle[key] = (now, 0, event, payload)
                emit_count = pending_count
            else:
                self._throttle[key] = (last, pending_count, event, payload)
                emit_count = 0
        if emit_count:
            payload = dict(payload)
            payload["count"] = emit_count
            self._emit(event, payload)

    def _flush_throttled(self) -> None:
        """Emit every key's still-pending coalesced count (at close): a burst
        that ends inside a throttle window must not silently lose its tail —
        summing ``count`` over events.jsonl has to reproduce the totals."""
        with self._count_lock:
            pending = [
                (event, dict(payload), count)
                for (_, count, event, payload) in self._throttle.values()
                if count
            ]
            self._throttle = {}
        for event, payload, count in pending:
            payload["count"] = count
            self._emit(event, payload)

    def _on_breaker_transition(self, old: str, new: str, info: Dict[str, Any]) -> None:
        self._emit("on_breaker", {"from": old, "to": new, **info})
        if self._chained_transition is not None:
            try:
                self._chained_transition(old, new, info)
            except Exception:  # noqa: BLE001 — an alerting hook raising must
                pass  # not poison the dispatch path that recorded the outcome

    def heartbeat(self) -> Dict[str, Any]:
        """Cheap host-side liveness + load snapshot — the fleet monitor's
        raw material (``serve.fleet``). No device work, no engine call: the
        liveness bit is the batcher's worker state, the load signals are the
        same gauges the exporter already serves (lane depth, breaker state,
        cumulative request/error counters the monitor windows itself)."""
        with self._count_lock:
            requests = self._requests
            errors = self._errors
        return {
            "live": self._started and self.batcher.live,
            "queued": self.batcher.queued_depth(),
            "max_depth": self.batcher.max_depth,
            "breaker_state": self.breaker.state,
            "requests": requests,
            "errors": errors,
            "error_rate": errors / requests if requests else 0.0,
        }

    def stats(self) -> Dict[str, Any]:
        engine = self.engine.stats()
        cache = self.cache.stats()
        batcher = self.batcher.stats()
        with self._count_lock:
            served = dict(self._served_from)
            served_by = dict(self._served_by)
            requests = self._requests
            errors = self._errors
            shed = self._shed
            deadline_misses = self._deadline_misses
            cancelled = self._cancelled
            circuit_refusals = self._circuit_refusals
            wait_sum = self._queue_wait_sum
            wait_max = self._queue_wait_max
            roles = {role: dict(stats) for role, stats in self._role_stats.items()}
            generation_misses = self._generation_misses
            generation_reroutes = self._generation_reroutes
            canary = self._canary
        answered = sum(served.values())
        reused = served["hit"] + served["advance"]
        return {
            "mode": self.mode,
            "requests": requests,
            "answered": answered,
            "errors": errors,
            "served_from": served,
            "served_by": served_by,
            "shed": shed,
            "deadline_misses": deadline_misses,
            "cancelled": cancelled,
            "circuit_refusals": circuit_refusals,
            "degraded": served_by["cache_only"] + served_by["fallback"],
            # the rates obs.report renders and --compare gates (lower-better)
            "shed_rate": shed / requests if requests else 0.0,
            "deadline_miss_rate": deadline_misses / requests if requests else 0.0,
            "error_rate": errors / requests if requests else 0.0,
            # state reuse: requests served from cached state (pure hits +
            # one-step advances) over answered requests
            "cache_hit_rate": reused / answered if answered else 0.0,
            "pure_hit_rate": served["hit"] / answered if answered else 0.0,
            "batch_fill_ratio": engine["batch_fill_ratio"],
            "queue_wait_ms_mean": wait_sum / answered * 1000.0 if answered else 0.0,
            "queue_wait_ms_max": wait_max * 1000.0,
            "breaker": self.breaker.stats(),
            "engine": engine,
            "cache": cache,
            "batcher": batcher,
            # hot-swap / canary visibility (serve.promote)
            "generations": self.store.stats(),
            "roles": roles,
            "generation_misses": generation_misses,
            "generation_reroutes": generation_reroutes,
            "canary": (
                {"generation": canary[0], "fraction": canary[1]}
                if canary is not None
                else None
            ),
            # the quality plane (obs.quality): pure-JSON monitor snapshot —
            # per-role windowed telemetry + online prequential cumulatives +
            # PSI drift state. None when no monitor is attached
            "quality": self.quality.snapshot() if self.quality is not None else None,
        }
