from .base import Splitter, SplitterReturnType
from .strategies import (
    ColdUserRandomSplitter,
    KFolds,
    LastNSplitter,
    NewUsersSplitter,
    RandomNextNSplitter,
    RandomSplitter,
    RatioSplitter,
    TimeSplitter,
    TwoStageSplitter,
)

__all__ = [
    "ColdUserRandomSplitter",
    "KFolds",
    "LastNSplitter",
    "NewUsersSplitter",
    "RandomNextNSplitter",
    "RandomSplitter",
    "RatioSplitter",
    "Splitter",
    "SplitterReturnType",
    "TimeSplitter",
    "TwoStageSplitter",
]
