"""Splitter base class: cold-entity dropping and session-boundary handling.

Capability parity with the reference Splitter ABC (replay/splitters/base_splitter.py:25-200):
``split()`` → (train, test), optional dropping of cold users/items from test, optional
session-id integrity (a session crossing the split boundary is moved wholly to train or
test), and ``save``/``load`` of init args into a ``.replay`` directory.

Strategies mark rows with a boolean test mask over the interactions frame and let the
base class materialize train/test — a single seam instead of the reference's
per-backend ``_core_split_*`` triplets.
"""

from __future__ import annotations

import json
from abc import ABC, abstractmethod
from pathlib import Path
from typing import Optional

import numpy as np
import pandas as pd

SplitterReturnType = tuple[pd.DataFrame, pd.DataFrame]


class Splitter(ABC):
    """Base class of train/test splitting strategies."""

    _init_arg_names: list[str] = [
        "drop_cold_users",
        "drop_cold_items",
        "query_column",
        "item_column",
        "timestamp_column",
        "session_id_column",
        "session_id_processing_strategy",
    ]

    def __init__(
        self,
        drop_cold_items: bool = False,
        drop_cold_users: bool = False,
        query_column: str = "query_id",
        item_column: Optional[str] = "item_id",
        timestamp_column: Optional[str] = "timestamp",
        session_id_column: Optional[str] = None,
        session_id_processing_strategy: str = "test",
    ) -> None:
        if session_id_processing_strategy not in ("train", "test"):
            msg = "session_id_processing_strategy must be 'train' or 'test'"
            raise ValueError(msg)
        self.drop_cold_items = drop_cold_items
        self.drop_cold_users = drop_cold_users
        self.query_column = query_column
        self.item_column = item_column
        self.timestamp_column = timestamp_column
        self.session_id_column = session_id_column
        self.session_id_processing_strategy = session_id_processing_strategy

    # -- public API -------------------------------------------------------
    def split(self, interactions: pd.DataFrame) -> SplitterReturnType:
        """Split interactions into (train, test)."""
        test_mask = np.asarray(self._test_mask(interactions), dtype=bool)
        if self.session_id_column is not None:
            test_mask = self._recover_sessions(interactions, test_mask)
        train = interactions[~test_mask]
        test = interactions[test_mask]
        return self._drop_cold(train, test)

    @abstractmethod
    def _test_mask(self, interactions: pd.DataFrame) -> np.ndarray:
        """Return a boolean mask marking the test rows."""

    # -- shared mechanics -------------------------------------------------
    def _recover_sessions(self, interactions: pd.DataFrame, test_mask: np.ndarray) -> np.ndarray:
        """Move sessions straddling the boundary wholly to train or test."""
        keys = [self.query_column, self.session_id_column]
        mask = pd.Series(test_mask, index=interactions.index)
        grouped = mask.groupby([interactions[k] for k in keys])
        frac_test = grouped.transform("mean")
        straddling = (frac_test > 0) & (frac_test < 1)
        if self.session_id_processing_strategy == "train":
            mask[straddling] = False
        else:
            mask[straddling] = True
        return mask.to_numpy()

    def _drop_cold(self, train: pd.DataFrame, test: pd.DataFrame) -> SplitterReturnType:
        if self.drop_cold_users:
            test = test[test[self.query_column].isin(set(train[self.query_column].unique()))]
        if self.drop_cold_items and self.item_column is not None:
            test = test[test[self.item_column].isin(set(train[self.item_column].unique()))]
        return train, test

    # -- persistence ------------------------------------------------------
    @property
    def _init_args(self) -> dict:
        return {name: getattr(self, name) for name in self._init_arg_names}

    def save(self, path: str) -> None:
        base = Path(path).with_suffix(".replay").resolve()
        base.mkdir(parents=True, exist_ok=True)
        payload = {"_class_name": str(self), "init_args": self._init_args}
        (base / "init_args.json").write_text(json.dumps(payload, default=str))

    @classmethod
    def load(cls, path: str, **kwargs) -> "Splitter":
        import inspect

        base = Path(path).with_suffix(".replay").resolve()
        payload = json.loads((base / "init_args.json").read_text())
        accepted = set(inspect.signature(cls.__init__).parameters)
        args = {k: v for k, v in payload["init_args"].items() if k in accepted}
        return cls(**{**args, **kwargs})

    def __str__(self) -> str:
        return type(self).__name__
