"""Train/test splitting strategies.

Capability parity with the reference splitter zoo (replay/splitters/*.py): Ratio, Time,
LastN (interactions | timedelta), RandomNextN, Random, ColdUserRandom, NewUsers,
TwoStage, KFolds. Each strategy computes a boolean test mask over the interactions;
the base class applies session recovery and cold-entity dropping.
"""

from __future__ import annotations

from datetime import datetime
from typing import Literal, Optional, Union

import numpy as np
import pandas as pd

from .base import Splitter, SplitterReturnType


def _row_num(df: pd.DataFrame, group_col: str, ts_col: str) -> pd.Series:
    """1-based rank of each row inside its group, ordered by timestamp (stable)."""
    order = df.sort_values(ts_col, kind="stable").groupby(group_col, sort=False).cumcount() + 1
    return order.reindex(df.index)


class RatioSplitter(Splitter):
    """Per-group tail fraction goes to test (reference: replay/splitters/ratio_splitter.py:13).

    >>> import pandas as pd
    >>> log = pd.DataFrame({
    ...     "query_id": [1, 1, 1, 1], "item_id": [10, 11, 12, 13],
    ...     "timestamp": [0, 1, 2, 3],
    ... })
    >>> train, test = RatioSplitter(test_size=0.5).split(log)
    >>> train["item_id"].tolist(), test["item_id"].tolist()
    ([10, 11], [12, 13])
    """

    _init_arg_names = [
        *Splitter._init_arg_names,
        "test_size",
        "divide_column",
        "min_interactions_per_group",
        "split_by_fractions",
    ]

    def __init__(
        self,
        test_size: float,
        divide_column: str = "query_id",
        drop_cold_users: bool = False,
        drop_cold_items: bool = False,
        query_column: str = "query_id",
        item_column: str = "item_id",
        timestamp_column: str = "timestamp",
        min_interactions_per_group: Optional[int] = None,
        split_by_fractions: bool = True,
        session_id_column: Optional[str] = None,
        session_id_processing_strategy: str = "test",
    ) -> None:
        super().__init__(
            drop_cold_items=drop_cold_items,
            drop_cold_users=drop_cold_users,
            query_column=query_column,
            item_column=item_column,
            timestamp_column=timestamp_column,
            session_id_column=session_id_column,
            session_id_processing_strategy=session_id_processing_strategy,
        )
        if not 0 <= test_size <= 1:
            msg = "test_size must be in [0, 1]"
            raise ValueError(msg)
        self.test_size = test_size
        self.divide_column = divide_column
        self.min_interactions_per_group = min_interactions_per_group
        self.split_by_fractions = split_by_fractions

    def _test_mask(self, interactions: pd.DataFrame) -> np.ndarray:
        row_num = _row_num(interactions, self.divide_column, self.timestamp_column)
        count = interactions.groupby(self.divide_column)[self.divide_column].transform("size")
        if self.split_by_fractions:
            mask = row_num / count > 1 - self.test_size
        else:
            train_size = count - (count * self.test_size).astype(int)
            if self.min_interactions_per_group is None:
                # guarantee small-but-splittable groups at least one test row
                fractional = (count * self.test_size > 0) & (count * self.test_size < 1) & (train_size > 1)
                train_size = train_size.where(~fractional, train_size - 1)
            mask = row_num > train_size
        if self.min_interactions_per_group is not None:
            mask &= count >= self.min_interactions_per_group
        return mask.to_numpy()


class TimeSplitter(Splitter):
    """Split at a timestamp threshold; float threshold means a global row-count quantile.

    >>> import pandas as pd
    >>> log = pd.DataFrame({"query_id": [1, 1, 2, 2], "item_id": [10, 11, 10, 12],
    ...                     "timestamp": [0, 10, 5, 20]})
    >>> train, test = TimeSplitter(time_threshold=0.5).split(log)
    >>> sorted(test["item_id"].tolist())
    [11, 12]
    """

    _init_arg_names = [*Splitter._init_arg_names, "time_threshold", "time_column_format"]

    def __init__(
        self,
        time_threshold: Union[datetime, str, float, int],
        query_column: str = "query_id",
        drop_cold_users: bool = False,
        drop_cold_items: bool = False,
        item_column: str = "item_id",
        timestamp_column: str = "timestamp",
        session_id_column: Optional[str] = None,
        session_id_processing_strategy: str = "test",
        time_column_format: str = "%Y-%m-%d %H:%M:%S",
    ) -> None:
        super().__init__(
            drop_cold_items=drop_cold_items,
            drop_cold_users=drop_cold_users,
            query_column=query_column,
            item_column=item_column,
            timestamp_column=timestamp_column,
            session_id_column=session_id_column,
            session_id_processing_strategy=session_id_processing_strategy,
        )
        if isinstance(time_threshold, float) and not 0 <= time_threshold <= 1:
            msg = "float time_threshold is a ratio and must be in [0, 1]"
            raise ValueError(msg)
        if isinstance(time_threshold, str):
            time_threshold = datetime.strptime(time_threshold, time_column_format)
        self.time_threshold = time_threshold
        self.time_column_format = time_column_format

    def _test_mask(self, interactions: pd.DataFrame) -> np.ndarray:
        ts = interactions[self.timestamp_column]
        if isinstance(self.time_threshold, float):
            # threshold = timestamp at row int(n * (1 - ratio)) when sorted; ratio 0.0
            # lands past the end and yields an empty test split instead of crashing
            ordered = ts.sort_values(kind="stable")
            position = int(len(ordered) * (1 - self.time_threshold))
            if position >= len(ordered):
                # ratio 0.0: nothing is recent enough -> empty test split
                return np.zeros(len(ts), dtype=bool)
            threshold = ordered.iloc[position]
            return (ts >= threshold).to_numpy()
        threshold = self.time_threshold
        if np.issubdtype(ts.dtype, np.datetime64):
            if isinstance(threshold, (int, float)):
                # numeric thresholds against datetime columns are unix SECONDS
                threshold = pd.Timestamp(threshold, unit="s")
            else:
                threshold = pd.Timestamp(threshold)
            ts = pd.to_datetime(ts)
        return (ts >= threshold).to_numpy()


class LastNSplitter(Splitter):
    """Last N interactions (or last N seconds of history) per group go to test.

    >>> import pandas as pd
    >>> log = pd.DataFrame({"query_id": [1, 1, 1, 2, 2], "item_id": [10, 11, 12, 10, 13],
    ...                     "timestamp": [0, 1, 2, 0, 1]})
    >>> train, test = LastNSplitter(N=1, divide_column="query_id",
    ...                             strategy="interactions").split(log)
    >>> sorted(test["item_id"].tolist())   # last event of each query
    [12, 13]
    """

    _init_arg_names = [*Splitter._init_arg_names, "N", "divide_column", "strategy"]

    def __init__(
        self,
        N: int,  # noqa: N803 - reference-compatible name
        divide_column: str = "query_id",
        time_column_format: str = "%Y-%m-%d %H:%M:%S",
        strategy: Literal["interactions", "timedelta"] = "interactions",
        drop_cold_users: bool = False,
        drop_cold_items: bool = False,
        query_column: str = "query_id",
        item_column: str = "item_id",
        timestamp_column: str = "timestamp",
        session_id_column: Optional[str] = None,
        session_id_processing_strategy: str = "test",
    ) -> None:
        super().__init__(
            drop_cold_items=drop_cold_items,
            drop_cold_users=drop_cold_users,
            query_column=query_column,
            item_column=item_column,
            timestamp_column=timestamp_column,
            session_id_column=session_id_column,
            session_id_processing_strategy=session_id_processing_strategy,
        )
        if strategy not in ("interactions", "timedelta"):
            msg = "strategy must be 'interactions' or 'timedelta'"
            raise ValueError(msg)
        self.N = N
        self.divide_column = divide_column
        self.strategy = strategy
        self.time_column_format = time_column_format

    def _test_mask(self, interactions: pd.DataFrame) -> np.ndarray:
        if self.strategy == "interactions":
            row_num = _row_num(interactions, self.divide_column, self.timestamp_column)
            count = interactions.groupby(self.divide_column)[self.divide_column].transform("size")
            return (row_num > count - float(self.N)).to_numpy()
        ts = interactions[self.timestamp_column]
        if not np.issubdtype(ts.dtype, np.number):
            ts = pd.to_datetime(ts).astype("int64") // 10**9
        group_max = ts.groupby(interactions[self.divide_column]).transform("max")
        return ((group_max - ts) < self.N).to_numpy()


class RandomNextNSplitter(Splitter):
    """Cut each group's timeline at a random point; the next N rows are test, the rest dropped.

    Mirrors the reference semantics (replay/splitters/random_next_n_splitter.py:20): rows
    past ``cut + N`` are removed from both splits, so ``split`` is overridden to drop them.
    """

    _init_arg_names = [*Splitter._init_arg_names, "N", "divide_column", "seed"]

    def __init__(
        self,
        N: Optional[int] = 1,  # noqa: N803
        divide_column: str = "query_id",
        seed: Optional[int] = None,
        query_column: str = "query_id",
        drop_cold_users: bool = False,
        drop_cold_items: bool = False,
        item_column: str = "item_id",
        timestamp_column: str = "timestamp",
        session_id_column: Optional[str] = None,
        session_id_processing_strategy: str = "test",
    ) -> None:
        super().__init__(
            drop_cold_items=drop_cold_items,
            drop_cold_users=drop_cold_users,
            query_column=query_column,
            item_column=item_column,
            timestamp_column=timestamp_column,
            session_id_column=session_id_column,
            session_id_processing_strategy=session_id_processing_strategy,
        )
        if N is not None and N < 1:
            msg = "N must be >= 1 or None"
            raise ValueError(msg)
        self.N = N
        self.divide_column = divide_column
        self.seed = seed

    def split(self, interactions: pd.DataFrame) -> SplitterReturnType:
        rank = _row_num(interactions, self.divide_column, self.timestamp_column) - 1
        counts = interactions.groupby(self.divide_column, sort=False)[self.divide_column].agg("size")
        rng = np.random.RandomState(self.seed)
        cuts = pd.Series(rng.randint(0, counts.to_numpy()), index=counts.index)
        cut_per_row = interactions[self.divide_column].map(cuts)

        keep = interactions if self.N is None else interactions[rank < cut_per_row + self.N]
        rank = rank.loc[keep.index]
        cut_per_row = cut_per_row.loc[keep.index]
        test_mask = (rank >= cut_per_row).to_numpy()
        if self.session_id_column is not None:
            test_mask = self._recover_sessions(keep, test_mask)
        return self._drop_cold(keep[~test_mask], keep[test_mask])

    def _test_mask(self, interactions: pd.DataFrame) -> np.ndarray:  # pragma: no cover
        raise NotImplementedError


class RandomSplitter(Splitter):
    """Uniformly sample a fraction of rows into test."""

    _init_arg_names = [*Splitter._init_arg_names, "test_size", "seed"]

    def __init__(
        self,
        test_size: float,
        drop_cold_items: bool = False,
        drop_cold_users: bool = False,
        seed: Optional[int] = None,
        query_column: str = "query_id",
        item_column: str = "item_id",
    ) -> None:
        super().__init__(
            drop_cold_items=drop_cold_items,
            drop_cold_users=drop_cold_users,
            query_column=query_column,
            item_column=item_column,
        )
        if not 0 <= test_size <= 1:
            msg = "test_size must be in [0, 1]"
            raise ValueError(msg)
        self.test_size = test_size
        self.seed = seed

    def _test_mask(self, interactions: pd.DataFrame) -> np.ndarray:
        # positional mask: index-label based membership over-selects when the frame
        # carries duplicate index labels (common after concat)
        n = len(interactions)
        rng = np.random.default_rng(self.seed)
        order = rng.permutation(n)
        n_train = round(n * (1 - self.test_size))
        mask = np.ones(n, dtype=bool)
        mask[order[:n_train]] = False
        return mask


class ColdUserRandomSplitter(Splitter):
    """Randomly move whole users (all their interactions) into test."""

    _init_arg_names = [*Splitter._init_arg_names, "test_size", "seed"]

    def __init__(
        self,
        test_size: float,
        drop_cold_items: bool = False,
        seed: Optional[int] = None,
        query_column: str = "query_id",
        item_column: Optional[str] = "item_id",
    ) -> None:
        super().__init__(
            drop_cold_items=drop_cold_items,
            query_column=query_column,
            item_column=item_column,
        )
        if not 0 < test_size < 1:
            msg = "test_size must be in (0, 1)"
            raise ValueError(msg)
        self.test_size = test_size
        self.seed = seed

    def _test_mask(self, interactions: pd.DataFrame) -> np.ndarray:
        users = pd.Series(interactions[self.query_column].unique())
        train_users = users.sample(frac=1 - self.test_size, random_state=self.seed)
        return (~interactions[self.query_column].isin(set(train_users))).to_numpy()


class NewUsersSplitter(Splitter):
    """Test = full history of the ``test_size`` fraction of users who arrive latest.

    Train keeps only interactions strictly before the first new-user arrival
    (reference: replay/splitters/new_users_splitter.py:12).
    """

    _init_arg_names = [*Splitter._init_arg_names, "test_size"]

    def __init__(
        self,
        test_size: float,
        drop_cold_items: bool = False,
        query_column: str = "query_id",
        item_column: Optional[str] = "item_id",
        timestamp_column: Optional[str] = "timestamp",
        session_id_column: Optional[str] = None,
        session_id_processing_strategy: str = "test",
    ) -> None:
        super().__init__(
            drop_cold_items=drop_cold_items,
            query_column=query_column,
            item_column=item_column,
            timestamp_column=timestamp_column,
            session_id_column=session_id_column,
            session_id_processing_strategy=session_id_processing_strategy,
        )
        if not 0 < test_size < 1:
            msg = "test_size must be in (0, 1)"
            raise ValueError(msg)
        self.test_size = test_size

    def split(self, interactions: pd.DataFrame) -> SplitterReturnType:
        ts = interactions[self.timestamp_column]
        start_by_user = ts.groupby(interactions[self.query_column]).transform("min")
        user_starts = (
            interactions.assign(__start=start_by_user)
            .drop_duplicates(self.query_column)["__start"]
            .sort_values(ascending=False)
        )
        n_test_users = int(np.ceil(self.test_size * len(user_starts)))
        test_start = user_starts.iloc[max(n_test_users - 1, 0)]

        test_mask = (start_by_user >= test_start).to_numpy()
        if self.session_id_column is not None:
            test_mask = self._recover_sessions(interactions, test_mask)
        train = interactions[(ts < test_start).to_numpy() & ~test_mask]
        test = interactions[test_mask]
        return self._drop_cold(train, test)

    def _test_mask(self, interactions: pd.DataFrame) -> np.ndarray:  # pragma: no cover
        raise NotImplementedError


class TwoStageSplitter(Splitter):
    """First pick test users (fraction or count), then a fraction/count of each one's rows."""

    _init_arg_names = [
        *Splitter._init_arg_names,
        "first_divide_size",
        "second_divide_size",
        "first_divide_column",
        "second_divide_column",
        "shuffle",
        "seed",
    ]

    def __init__(
        self,
        first_divide_size: Union[float, int],
        second_divide_size: Union[float, int],
        first_divide_column: str = "query_id",
        second_divide_column: str = "item_id",
        shuffle: bool = False,
        drop_cold_items: bool = False,
        drop_cold_users: bool = False,
        seed: Optional[int] = None,
        query_column: str = "query_id",
        item_column: Optional[str] = "item_id",
        timestamp_column: Optional[str] = "timestamp",
    ) -> None:
        super().__init__(
            drop_cold_items=drop_cold_items,
            drop_cold_users=drop_cold_users,
            query_column=query_column,
            item_column=item_column,
            timestamp_column=timestamp_column,
        )
        self.first_divide_size = first_divide_size
        self.second_divide_size = second_divide_size
        self.first_divide_column = first_divide_column
        self.second_divide_column = second_divide_column
        self.shuffle = shuffle
        self.seed = seed

    def _test_mask(self, interactions: pd.DataFrame) -> np.ndarray:
        values = np.sort(interactions[self.first_divide_column].unique())
        n_values = len(values)
        if isinstance(self.first_divide_size, int):
            if not 1 <= self.first_divide_size < n_values:
                msg = f"first_divide_size must be in [1, {n_values}), got {self.first_divide_size}"
                raise ValueError(msg)
            n_test = self.first_divide_size
        else:
            if not 0 < self.first_divide_size < 1:
                msg = "fractional first_divide_size must be in (0, 1)"
                raise ValueError(msg)
            n_test = int(n_values * self.first_divide_size)
        rng = np.random.RandomState(self.seed)
        test_values = set(rng.permutation(values)[:n_test].tolist())

        in_test_group = interactions[self.first_divide_column].isin(test_values)
        if self.shuffle:
            order = interactions.sample(frac=1, random_state=self.seed)
        else:
            order = interactions.sort_values(self.timestamp_column, kind="stable")
        rank = order.groupby(self.first_divide_column, sort=False).cumcount() + 1
        rank = rank.reindex(interactions.index)
        count = interactions.groupby(self.first_divide_column)[self.first_divide_column].transform("size")
        if isinstance(self.second_divide_size, int):
            threshold = count - self.second_divide_size
        else:
            threshold = count - (count * self.second_divide_size).astype(int)
        return (in_test_group & (rank > threshold)).to_numpy()


class KFolds(Splitter):
    """Yield ``n_folds`` (train, test) pairs; each query's rows are dealt round-robin.

    >>> import pandas as pd
    >>> log = pd.DataFrame({"query_id": [1, 1, 1, 1], "item_id": [10, 11, 12, 13],
    ...                     "timestamp": [0, 1, 2, 3]})
    >>> folds = list(KFolds(n_folds=2, seed=0).split(log))
    >>> [len(test) for _, test in folds]
    [2, 2]
    """

    _init_arg_names = [*Splitter._init_arg_names, "n_folds", "strategy", "seed"]

    def __init__(
        self,
        n_folds: Optional[int] = 5,
        strategy: Literal["query"] = "query",
        drop_cold_items: bool = False,
        drop_cold_users: bool = False,
        seed: Optional[int] = None,
        query_column: str = "query_id",
        item_column: Optional[str] = "item_id",
        timestamp_column: Optional[str] = "timestamp",
        session_id_column: Optional[str] = None,
        session_id_processing_strategy: str = "test",
    ) -> None:
        super().__init__(
            drop_cold_items=drop_cold_items,
            drop_cold_users=drop_cold_users,
            query_column=query_column,
            item_column=item_column,
            timestamp_column=timestamp_column,
            session_id_column=session_id_column,
            session_id_processing_strategy=session_id_processing_strategy,
        )
        if strategy != "query":
            msg = f"Unknown strategy: {strategy}"
            raise ValueError(msg)
        self.n_folds = n_folds
        self.strategy = strategy
        self.seed = seed

    def split(self, interactions: pd.DataFrame):
        shuffled = interactions.sample(frac=1, random_state=self.seed)
        fold = (shuffled.groupby(self.query_column, sort=False).cumcount() + 1) % self.n_folds
        fold = fold.reindex(interactions.index)
        for i in range(self.n_folds):
            test_mask = (fold == i).to_numpy()
            if self.session_id_column is not None:
                test_mask = self._recover_sessions(interactions, test_mask)
            yield self._drop_cold(interactions[~test_mask], interactions[test_mask])

    def _test_mask(self, interactions: pd.DataFrame) -> np.ndarray:  # pragma: no cover
        raise NotImplementedError
