from .model_handler import (
    load,
    load_encoder,
    load_from_replay,
    load_splitter,
    save,
    save_encoder,
    save_splitter,
    save_to_replay,
)
from .distributions import item_distribution
from .time import get_item_recency, smoothe_time
from .checkpoint import CheckpointManager, load_metadata, restore_pytree, save_pytree
from .faults import KillAtStep, NaNInjector, SignalAtStep, inject_nan, truncate_file
from .profiling import StepTimer, trace
from .session import State, get_default_mesh, setup_logging
from .types import (
    OPTUNA_AVAILABLE,
    PANDAS_AVAILABLE,
    POLARS_AVAILABLE,
    PYSPARK_AVAILABLE,
    TORCH_AVAILABLE,
    DataFrameLike,
    PandasDataFrame,
    PolarsDataFrame,
    SparkDataFrame,
    df_backend,
)

__all__ = [
    "load_from_replay",
    "save_to_replay",
    "load_splitter",
    "save_splitter",
    "load_encoder",
    "save_encoder",
    "load",
    "save",
    "smoothe_time",
    "get_item_recency",
    "item_distribution",
    "OPTUNA_AVAILABLE",
    "PANDAS_AVAILABLE",
    "POLARS_AVAILABLE",
    "PYSPARK_AVAILABLE",
    "TORCH_AVAILABLE",
    "CheckpointManager",
    "DataFrameLike",
    "KillAtStep",
    "NaNInjector",
    "SignalAtStep",
    "inject_nan",
    "truncate_file",
    "PandasDataFrame",
    "PolarsDataFrame",
    "SparkDataFrame",
    "State",
    "StepTimer",
    "df_backend",
    "get_default_mesh",
    "load_metadata",
    "restore_pytree",
    "save_pytree",
    "setup_logging",
    "trace",
]
