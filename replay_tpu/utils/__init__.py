from .types import (
    OPTUNA_AVAILABLE,
    PANDAS_AVAILABLE,
    POLARS_AVAILABLE,
    PYSPARK_AVAILABLE,
    TORCH_AVAILABLE,
    DataFrameLike,
    PandasDataFrame,
    PolarsDataFrame,
    SparkDataFrame,
    df_backend,
)

__all__ = [
    "OPTUNA_AVAILABLE",
    "PANDAS_AVAILABLE",
    "POLARS_AVAILABLE",
    "PYSPARK_AVAILABLE",
    "TORCH_AVAILABLE",
    "DataFrameLike",
    "PandasDataFrame",
    "PolarsDataFrame",
    "SparkDataFrame",
    "df_backend",
]
