"""Pytree checkpointing: TrainState save/restore + step-managed directories.

Capability parity with the reference's checkpoint story (SURVEY.md §5): Lightning
ModelCheckpoint (step-numbered, keep-last-k, monitored metric history surviving
resume — ref nn/lightning/callback/metrics_callback.py:86-101) and the `.replay`
artifact convention (init_args.json + payloads, ref utils/model_handler.py:42).

TPU design: a checkpoint is the flattened leaf list of an arbitrary JAX pytree
(TrainState = params + optax state + PRNG key) stored as one ``.npz`` plus a JSON
sidecar. Restoration unflattens into a TEMPLATE pytree (the orbax restore(item=...)
pattern) so optax NamedTuple internals never need to be serialized structurally —
the template supplies the treedef, the npz supplies the arrays, and shapes are
validated leaf-by-leaf. Works for sharded arrays: leaves are gathered to host on
save and re-placed by the trainer's shardings on the next device_put.

Crash consistency (docs/robustness.md): every file is written to a ``.tmp``
sibling, fsynced and ``os.replace``d into place — a SIGKILL mid-save can leave
a stray temp file, never a half-written visible one. The JSON sidecar lands
LAST, so its presence is the commit marker: ``CheckpointManager`` treats a
payload without a sidecar (or with an unreadable one) as an aborted save and
skips it on resume instead of raising mid-restore.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import zipfile
from pathlib import Path
from typing import Any, Dict, List, Optional

import jax
import numpy as np

logger = logging.getLogger("replay_tpu")


def _atomic_replace(path: Path, write) -> None:
    """Write via ``write(fh)`` into ``<path>.tmp``, fsync, then rename into
    place — readers only ever observe absent or complete files."""
    tmp = path.with_name(path.name + ".tmp")
    with open(tmp, "wb") as fh:
        write(fh)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


def save_pytree(
    path: str, tree: Any, metadata: Optional[dict] = None, backend: Optional[str] = None
) -> None:
    """Write a pytree's leaves (+ optional JSON metadata) to ``<path>``.

    ``backend="npz"`` stores the flattened leaf list in one ``.npz``;
    ``backend="orbax"`` delegates the tree to orbax's StandardCheckpointer
    (sharded/async-capable storage for very large states) — both restore through
    the same template-driven :func:`restore_pytree`. ``backend=None`` (default)
    picks npz on one process and orbax under multi-host: npz gathers every leaf
    to host memory, which raises on leaves that are not fully addressable
    (e.g. vocab-sharded embeddings with process_count>1), while orbax writes
    each shard in place.
    """
    if backend is None:
        backend = "orbax" if jax.process_count() > 1 else "npz"
    target = Path(path)
    target.parent.mkdir(parents=True, exist_ok=True)
    leaves = jax.tree.leaves(tree)
    if backend == "orbax":
        import orbax.checkpoint as ocp

        checkpointer = ocp.StandardCheckpointer()
        # hand orbax the tree AS-IS: it understands (sharded) jax.Arrays, so no
        # host gather happens and multi-host saves write each shard in place
        checkpointer.save(
            (target.parent / (target.name + ".orbax")).absolute(), tree, force=True
        )
        checkpointer.wait_until_finished()
    elif backend == "npz":
        arrays = {f"leaf_{i}": np.asarray(leaf) for i, leaf in enumerate(leaves)}
        # tmp + os.replace: a preemption mid-write leaves a stray .tmp, never a
        # truncated .npz under the visible name (np.savez accepts a handle, so
        # no implicit-.npz-suffix surprises on the temp path)
        _atomic_replace(target.with_suffix(".npz"), lambda fh: np.savez(fh, **arrays))
    else:
        msg = f"Unknown checkpoint backend: {backend}"
        raise ValueError(msg)
    # reserved keys win over caller metadata: restore routes on "backend"
    meta = {**(metadata or {}), "num_leaves": len(leaves), "backend": backend}
    if jax.process_index() == 0:  # one writer for the shared-fs sidecar
        # the sidecar is the commit marker and therefore lands last, atomically
        _atomic_replace(
            target.with_suffix(".json"), lambda fh: fh.write(json.dumps(meta).encode())
        )
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        # nobody returns (and possibly restores) before the sidecar is on disk
        multihost_utils.sync_global_devices(f"save_pytree:{target.name}")


def restore_pytree(path: str, template: Any) -> Any:
    """Rebuild a pytree from ``save_pytree`` output using ``template``'s structure.

    Leaf count and shapes are validated against the template (the ItemTower
    cache-shape check of the reference, generalized).
    """
    target = Path(path)
    meta_path = target.with_suffix(".json")
    backend = "npz"
    if meta_path.exists():
        backend = json.loads(meta_path.read_text()).get("backend", "npz")
    if backend == "orbax":
        import orbax.checkpoint as ocp

        checkpointer = ocp.StandardCheckpointer()

        # abstract target: shapes/dtypes (+ shardings when the template leaves
        # are live jax.Arrays) — without shardings orbax falls back to
        # sharding-from-file, which is unsafe when restoring on a different
        # device topology than the save (the multi-host recovery scenario)
        def _abstract_leaf(leaf):
            if isinstance(leaf, jax.Array):
                return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype, sharding=leaf.sharding)
            return jax.eval_shape(lambda x: x, leaf)

        abstract = jax.tree.map(_abstract_leaf, template)
        restored = checkpointer.restore(
            (target.parent / (target.name + ".orbax")).absolute(), abstract
        )
        # multi-host restore yields GLOBAL arrays whose remote shards this
        # process cannot address — keep those as live jax.Arrays (they already
        # carry the template's shardings); only host-fetch what is local
        leaves = [
            leaf
            if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable
            else np.asarray(leaf)
            for leaf in jax.tree.leaves(restored)
        ]
    else:
        with np.load(str(target.with_suffix(".npz"))) as payload:
            leaves = [payload[f"leaf_{i}"] for i in range(len(payload.files))]
    template_leaves, treedef = jax.tree.flatten(template)
    if len(leaves) != len(template_leaves):
        msg = (
            f"Checkpoint has {len(leaves)} leaves, template expects "
            f"{len(template_leaves)} — incompatible model/optimizer config."
        )
        raise ValueError(msg)
    for i, (saved, expected) in enumerate(zip(leaves, template_leaves)):
        if hasattr(expected, "shape") and tuple(saved.shape) != tuple(np.shape(expected)):
            msg = (
                f"Leaf {i} shape {tuple(saved.shape)} does not match template "
                f"{tuple(np.shape(expected))}."
            )
            raise ValueError(msg)
        expected_dtype = getattr(expected, "dtype", None)
        # compare both sides as jax would see them (float64 host arrays mean
        # float32 under the default x64-disabled config, on the template AND in
        # an npz written from a host-numpy tree)
        if expected_dtype is not None and jax.dtypes.canonicalize_dtype(
            saved.dtype
        ) != jax.dtypes.canonicalize_dtype(expected_dtype):
            msg = (
                f"Leaf {i} dtype {saved.dtype} does not match template "
                f"{np.dtype(expected_dtype)} — checkpoint saved from a "
                "different-precision config."
            )
            raise ValueError(msg)
    return jax.tree.unflatten(treedef, leaves)


def load_metadata(path: str) -> dict:
    return json.loads(Path(path).with_suffix(".json").read_text())


class CheckpointManager:
    """Step-numbered checkpoints with keep-last-k retention and metric history.

    Layout: ``<directory>/step_<n>.npz/.json`` + ``history.json`` (the per-epoch
    metric records of Trainer.history, surviving restarts like the reference
    callback's state_dict).
    """

    def __init__(
        self, directory: str, max_to_keep: int = 3, backend: Optional[str] = None
    ) -> None:
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self.max_to_keep = max_to_keep
        self.backend = backend
        # steps whose files failed the last integrity scan (see valid_steps)
        self.skipped_steps: List[int] = []

    def _step_path(self, step: int) -> Path:
        return self.directory / f"step_{step}"

    def _proc_meta_path(self, step: int, process_index: int) -> Path:
        # its own subdirectory: all_steps() globs step_*.json in the root, and
        # per-process sidecars must never be mistaken for commit markers
        return self.directory / "proc_meta" / f"step_{step}.proc{process_index}.json"

    def _delete_step(self, step: int) -> None:
        self._step_path(step).with_suffix(".npz").unlink(missing_ok=True)
        self._step_path(step).with_suffix(".json").unlink(missing_ok=True)
        shutil.rmtree(self.directory / f"step_{step}.orbax", ignore_errors=True)
        for proc_file in (self.directory / "proc_meta").glob(f"step_{step}.proc*.json"):
            proc_file.unlink(missing_ok=True)

    def all_steps(self) -> List[int]:
        # the JSON sidecar exists for every backend
        return sorted(int(p.stem.split("_")[1]) for p in self.directory.glob("step_*.json"))

    def metadata(self, step: int) -> dict:
        """The JSON metadata saved alongside checkpoint ``step``."""
        return load_metadata(str(self._step_path(step)))

    def process_metadata(self, step: int, process_index: Optional[int] = None) -> dict:
        """THIS process's private sidecar for checkpoint ``step`` (``{}`` when
        absent or unreadable — the caller falls back to the shared metadata).

        The shared ``step_<n>.json`` sidecar has exactly one writer (process
        0), so anything per-process — a streaming batcher's cursor above all —
        needs its own file. Each process writes its own atomically in
        :meth:`save` (before the commit marker, so a committed step always has
        its process sidecars) and reads its own back on resume.
        """
        if process_index is None:
            process_index = jax.process_index()
        try:
            return json.loads(self._proc_meta_path(step, process_index).read_text())
        except (OSError, ValueError):
            return {}

    # -- integrity --------------------------------------------------------- #
    def _payload_ok(self, step: int) -> bool:
        """Cheap payload probe: an npz must open as a zip archive (a truncated
        half-written file has no central directory and fails immediately, no
        array reads); an orbax checkpoint must have its directory."""
        npz = self._step_path(step).with_suffix(".npz")
        if npz.exists():
            try:
                zipfile.ZipFile(npz).close()
                return True
            except (zipfile.BadZipFile, OSError):
                return False
        return (self.directory / f"step_{step}.orbax").exists()

    def _step_valid(self, step: int) -> bool:
        try:
            meta = self.metadata(step)
        except (OSError, ValueError):  # missing or unparseable sidecar
            return False
        return isinstance(meta, dict) and self._payload_ok(step)

    def valid_steps(self) -> List[int]:
        """``all_steps()`` minus incomplete or corrupt entries — those are
        reported (warning + :attr:`skipped_steps`) and skipped, so a save
        interrupted by preemption never breaks the next ``resume=True``."""
        good: List[int] = []
        bad: List[int] = []
        for step in self.all_steps():
            (good if self._step_valid(step) else bad).append(step)
        if bad:
            logger.warning(
                "skipping incomplete/corrupt checkpoint step(s) %s in %s",
                bad, self.directory,
            )
        self.skipped_steps = bad
        return good

    def latest_step(self) -> Optional[int]:
        steps = self.valid_steps()
        return steps[-1] if steps else None

    def save(
        self,
        step: int,
        state: Any,
        history: Optional[List[Dict[str, float]]] = None,
        metadata: Optional[dict] = None,
        process_metadata: Optional[dict] = None,
    ) -> None:
        if process_metadata is not None:
            # per-process sidecar FIRST: the shared sidecar is the commit
            # marker and must land after everything a resume will read
            path = self._proc_meta_path(step, jax.process_index())
            path.parent.mkdir(parents=True, exist_ok=True)
            _atomic_replace(
                path, lambda fh: fh.write(json.dumps(process_metadata).encode())
            )
        save_pytree(
            str(self._step_path(step)), state, {"step": step, **(metadata or {})},
            backend=self.backend,
        )
        if jax.process_index() != 0:
            return  # save_pytree already barriered; one process rotates/records
        if history is not None:
            # atomic like every other file here: a torn history.json would
            # crash the next resume's checkpoint_manager.history() read
            _atomic_replace(
                self.directory / "history.json",
                lambda fh: fh.write(json.dumps(history).encode()),
            )
        protected = self.best_step()
        for old in self.all_steps()[: -self.max_to_keep]:
            if old == protected:  # the monitored winner survives rotation
                continue
            self._delete_step(old)

    # -- monitored-best tracking ------------------------------------------- #
    def mark_best(self, step: int) -> None:
        """Record ``step`` as the monitored winner (survives rotation)."""
        _atomic_replace(
            self.directory / "best.json",
            lambda fh: fh.write(json.dumps({"step": step}).encode()),
        )

    def best_step(self) -> Optional[int]:
        path = self.directory / "best.json"
        if not path.exists():
            return None
        step = json.loads(path.read_text())["step"]
        # a best.json pointing at a deleted or corrupt step is stale, not fatal
        return step if self._step_valid(step) else None

    def restore_best(self, template: Any) -> Any:
        """Restore the monitored-best checkpoint (falls back to the latest)."""
        step = self.best_step()
        return self.restore(template, step=step)

    def restore(self, template: Any, step: Optional[int] = None) -> Any:
        """Restore checkpoint ``step`` (default: the latest VALID one).

        The step's metadata is validated before unflattening — a corrupt
        sidecar, a leaf-count mismatch against the template, or a truncated
        payload each raise a ``ValueError`` naming the offending step instead
        of a bare deserialization traceback."""
        step = step if step is not None else self.latest_step()
        if step is None:
            msg = f"No checkpoints found in {self.directory}"
            raise FileNotFoundError(msg)
        path = self._step_path(step)
        try:
            meta = load_metadata(str(path))
        except FileNotFoundError:
            msg = f"Checkpoint step_{step} not found in {self.directory}"
            raise FileNotFoundError(msg) from None
        except (OSError, ValueError) as exc:
            msg = (
                f"Checkpoint step_{step} in {self.directory} has an unreadable "
                f"metadata sidecar ({exc}) — the save was likely interrupted; "
                "delete the step files or restore an earlier step."
            )
            raise ValueError(msg) from exc
        num_leaves = meta.get("num_leaves") if isinstance(meta, dict) else None
        expected = len(jax.tree.leaves(template))
        if num_leaves is not None and num_leaves != expected:
            msg = (
                f"Checkpoint step_{step} records num_leaves={num_leaves} but the "
                f"template has {expected} leaves — saved from an incompatible "
                "model/optimizer config, or by an older replay_tpu version with "
                "a different TrainState layout."
            )
            raise ValueError(msg)
        try:
            return restore_pytree(str(path), template)
        except (zipfile.BadZipFile, EOFError, KeyError, OSError) as exc:
            msg = (
                f"Checkpoint step_{step} in {self.directory} is corrupt or "
                f"incomplete ({type(exc).__name__}: {exc}); delete it or "
                "restore an earlier step."
            )
            raise ValueError(msg) from exc

    def history(self) -> List[Dict[str, float]]:
        path = self.directory / "history.json"
        return json.loads(path.read_text()) if path.exists() else []

    def delete(self) -> None:
        shutil.rmtree(self.directory, ignore_errors=True)
