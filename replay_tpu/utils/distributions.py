"""Item-popularity distribution diagnostics.

Capability parity with the reference ``replay/utils/distributions.py:11-33``
(``item_distribution``), pandas-native: per-item distinct-user counts in the
historical log joined against per-item counts in the top-k recommendations.
"""

from __future__ import annotations

import pandas as pd


def item_distribution(
    log: pd.DataFrame,
    recommendations: pd.DataFrame,
    k: int,
    query_column: str = "query_id",
    item_column: str = "item_id",
    rating_column: str = "rating",
) -> pd.DataFrame:
    """Compare item exposure in history vs. a model's top-k recommendations.

    :param log: historical interactions (popularity source).
    :param recommendations: scored recommendations; the top ``k`` rows per
        query by ``rating_column`` are kept before counting.
    :param k: recommendation list length.
    :return: one row per item with ``user_count`` (distinct users in the log)
        and ``rec_count`` (appearances in the truncated recommendations),
        sorted by ``[user_count, item_column]``; items present on only one
        side get a zero count on the other.
    """
    hist = (
        log.groupby(item_column)[query_column]
        .nunique()
        .rename("user_count")
        .reset_index()
    )
    top = recommendations.sort_values(
        by=[rating_column], ascending=False, kind="stable"
    ).groupby(query_column, sort=False)
    top_recs = top.head(k)
    rec = (
        top_recs.groupby(item_column)[query_column]
        .nunique()
        .rename("rec_count")
        .reset_index()
    )
    res = hist.merge(rec, on=item_column, how="outer").fillna(0)
    res["user_count"] = res["user_count"].astype("int64")
    res["rec_count"] = res["rec_count"].astype("int64")
    return res.sort_values(["user_count", item_column], kind="stable").reset_index(drop=True)
