"""Deterministic fault injection for the resilience layer (docs/robustness.md).

No reference-stack counterpart (Lightning tests its fault-tolerant loop with
ad-hoc monkeypatching); here the failure modes the trainer AND the scoring
service must survive — NaN batches, preemption signals, truncated checkpoint
files, engine exceptions, latency spikes — are injected through one small
harness so every recovery path in ``tests/nn/test_fault_tolerance.py`` and
``tests/serve/`` is exercised reproducibly on the 8-device virtual CPU mesh:

* :class:`NaNInjector` poisons chosen batches of a stream (exercises the
  in-jit non-finite sentinel and ``RecoveryPolicy`` rollback);
* :class:`SignalAtStep` raises a real SIGTERM/SIGINT at a chosen batch index
  (exercises :class:`~replay_tpu.nn.train.PreemptionHandler` end-to-end,
  through the actual OS signal machinery);
* :class:`KillAtStep` SIGKILLs a whole worker PROCESS at a chosen batch index
  (or, via :meth:`KillAtStep.fire`, at an arbitrary moment) — the hard-kill
  injector the process-real chaos legs share: no handler runs, no cleanup
  happens, recovery must come entirely from on-disk atomicity
  (checkpoint + cursor sidecar) or peer-side failover;
* :func:`truncate_file` chops a checkpoint payload as a crash mid-write would
  (exercises ``CheckpointManager``'s skip-and-report integrity scan);
* :class:`EngineErrorAt` makes a wrapped callable (e.g.
  ``ScoringEngine.encode``) raise :class:`InjectedFault` at chosen call
  indices (exercises the serve circuit breaker and future-failure paths);
* :class:`LatencySpike` delays a wrapped callable at chosen call indices
  (exercises deadline enforcement, queue-bound shedding and the client-side
  ``score(timeout=...)`` abandonment drop).

Injection positions are 0-based GLOBAL indices (batch indices for the stream
injectors, call indices for the callable injectors) counted across every
``wrap`` call of one injector instance, so a multi-epoch ``fit`` stream — or
a long-lived serve worker — hits the same absolute positions regardless of
epoch/batch boundaries.
"""

from __future__ import annotations

import functools
import os
import signal
import time
from typing import Any, Callable, Dict, Iterable, Iterator, Optional, Sequence

import numpy as np


class InjectedFault(RuntimeError):
    """A fault raised by the chaos harness — never by real engine code, so
    tests and the chaos bench can tell injected failures from organic ones."""


def inject_nan(batch: Dict[str, Any], fields: Optional[Sequence[str]] = None) -> Dict[str, Any]:
    """A copy of ``batch`` with every float leaf replaced by all-NaN.

    Integer/bool leaves (ids, masks, labels) pass through untouched — a "NaN
    batch" means the continuous features are poisoned, which drives the loss
    AND every gradient non-finite in one forward/backward. ``fields`` narrows
    the poisoning to the given top-level batch keys. Raises if nothing was
    poisoned: a silently-clean "fault" would make a recovery test vacuous.
    """

    poisoned = 0

    def poison(value):
        nonlocal poisoned
        if isinstance(value, dict):
            return {key: poison(item) for key, item in value.items()}
        if isinstance(value, (list, tuple)):
            return type(value)(poison(item) for item in value)
        array = np.asarray(value)
        if np.issubdtype(array.dtype, np.floating):
            poisoned += 1
            return np.full_like(array, np.nan)
        return value

    out = {
        key: (poison(value) if fields is None or key in fields else value)
        for key, value in batch.items()
    }
    if not poisoned:
        msg = (
            "inject_nan found no float leaves to poison "
            f"(fields={list(fields) if fields is not None else 'all'}); "
            "the batch must carry at least one float feature for a NaN fault"
        )
        raise ValueError(msg)
    return out


class NaNInjector:
    """Poison the batches at the given global stream positions.

    >>> injector = NaNInjector(at_steps=(2, 5))
    >>> # trainer.fit(lambda epoch: injector.wrap(make_batches(epoch)), ...)
    """

    def __init__(self, at_steps: Iterable[int], fields: Optional[Sequence[str]] = None) -> None:
        self.at_steps = frozenset(int(s) for s in at_steps)
        self.fields = fields
        self.position = 0  # global batch index across wrap() calls
        self.injected_at: list = []

    def wrap(self, batches: Iterable[Dict[str, Any]]) -> Iterator[Dict[str, Any]]:
        for batch in batches:
            if self.position in self.at_steps:
                self.injected_at.append(self.position)
                batch = inject_nan(batch, self.fields)
            self.position += 1
            yield batch


class SignalAtStep:
    """Raise a real OS signal just before yielding batch ``at_step``.

    The default SIGTERM models a preemption notice arriving while the trainer
    is fetching data; with ``fit``'s PreemptionHandler installed the flag is
    set immediately and honored at the next step boundary. Fires at most once
    per instance.
    """

    def __init__(self, at_step: int, sig: int = signal.SIGTERM) -> None:
        self.at_step = int(at_step)
        self.sig = sig
        self.position = 0  # global batch index across wrap() calls
        self.raised = False

    def wrap(self, batches: Iterable[Dict[str, Any]]) -> Iterator[Dict[str, Any]]:
        for batch in batches:
            if self.position == self.at_step and not self.raised:
                self.raised = True
                signal.raise_signal(self.sig)
            self.position += 1
            yield batch


class KillAtStep:
    """SIGKILL a process just before yielding batch ``at_step``.

    The uncatchable upgrade of :class:`SignalAtStep`: SIGKILL never reaches a
    handler, so a wrapped training stream dies mid-epoch exactly as a
    preempted/OOM-killed worker would — whatever survives is what the atomic
    checkpoint + cursor sidecar design actually guarantees. By default the
    injector kills ITS OWN process (a worker wraps its own stream); ``pid``
    retargets it at another process, and :meth:`fire` sends the kill
    immediately — the fleet chaos path (``bench_fleet.py``) uses it to SIGKILL
    a replica server process mid-traffic:

    >>> # training worker: dies fetching global batch 4, no cleanup runs
    >>> # trainer.fit(lambda epoch: KillAtStep(4).wrap(batches(epoch)), ...)
    >>> # fleet chaos: hard-kill a replica server process
    >>> # KillAtStep(pid=server.pid).fire()
    """

    def __init__(
        self, at_step: int = 0, pid: Optional[int] = None, sig: int = signal.SIGKILL
    ) -> None:
        self.at_step = int(at_step)
        self.pid = pid
        self.sig = sig
        self.position = 0  # global batch index across wrap() calls
        self.fired = False

    def fire(self) -> None:
        """Send the kill now. Does not return when targeting ``os.getpid()``."""
        self.fired = True
        os.kill(self.pid if self.pid is not None else os.getpid(), self.sig)

    def wrap(self, batches: Iterable[Dict[str, Any]]) -> Iterator[Dict[str, Any]]:
        for batch in batches:
            if self.position == self.at_step and not self.fired:
                self.fire()
            self.position += 1
            yield batch


class _CallIndexInjector:
    """Shared scaffolding for the callable injectors: one GLOBAL call-index
    counter across every ``wrap()`` target (the callable analog of the stream
    injectors' global batch indices), ``injected_at`` recording the calls that
    fired, and a :meth:`_fire` hook run BEFORE the wrapped call — raising from
    it suppresses the call entirely, returning lets it proceed."""

    def __init__(self, at_calls: Iterable[int]) -> None:
        self.at_calls = frozenset(int(c) for c in at_calls)
        self.position = 0  # global call index across wrap() targets
        self.injected_at: list = []

    def _fire(self, position: int) -> None:
        raise NotImplementedError

    def wrap(self, fn: Callable) -> Callable:
        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            position = self.position
            self.position += 1
            if position in self.at_calls:
                self.injected_at.append(position)
                self._fire(position)
            return fn(*args, **kwargs)

        return wrapped


class EngineErrorAt(_CallIndexInjector):
    """Make a wrapped callable raise :class:`InjectedFault` at the given
    global call indices — the serve-side chaos injector.

    >>> injector = EngineErrorAt(at_calls=range(3))
    >>> # service.engine.encode = injector.wrap(service.engine.encode)
    >>> # the first 3 encodes now fail; consecutive failures trip the breaker

    The fault raises BEFORE the wrapped call, so an injected failure costs no
    device work — exactly like a transport/runtime error surfacing at
    dispatch. ``injected_at`` records the call indices that actually fired.
    """

    def _fire(self, position: int) -> None:
        msg = f"injected engine error at call {position}"
        raise InjectedFault(msg)


class LatencySpike(_CallIndexInjector):
    """Delay a wrapped callable by ``duration_s`` at the given global call
    indices — models a device stall / host GC pause / network hiccup without
    changing any result. ``injected_at`` records the calls that slept.
    """

    def __init__(self, at_calls: Iterable[int], duration_s: float = 0.05) -> None:
        super().__init__(at_calls)
        self.duration_s = float(duration_s)

    def _fire(self, position: int) -> None:
        time.sleep(self.duration_s)


def wrap_method(obj: Any, name: str, injector: Any) -> Any:
    """Instance-patch ``obj.name`` with ``injector.wrap`` (chaos entrypoint:
    ``wrap_method(service.engine, "encode", EngineErrorAt(...))``). Returns
    the original bound method so callers can restore it."""
    original = getattr(obj, name)
    setattr(obj, name, injector.wrap(original))
    return original


def truncate_file(path: str, keep_fraction: float = 0.5, keep_bytes: Optional[int] = None) -> int:
    """Truncate ``path`` in place — the on-disk state a crash mid-write leaves
    behind (for non-atomic writers) or a partially-synced copy. Returns the
    new size in bytes."""
    size = os.path.getsize(path)
    keep = int(size * keep_fraction) if keep_bytes is None else min(keep_bytes, size)
    with open(path, "r+b") as fh:
        fh.truncate(keep)
    return keep
