"""Generic persistence entry points for `.replay` artifacts.

Capability parity with the reference ``replay/utils/model_handler.py:42-170``
(``save``/``load``, ``save_encoder``/``load_encoder``,
``save_splitter``/``load_splitter``) and ``replay/utils/common.py:62-84``
(``save_to_replay``/``load_from_replay``): a caller can persist any framework
object and restore it WITHOUT knowing its concrete class — the class name is
read back from the artifact's ``init_args.json`` and resolved against the
package namespaces.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import TYPE_CHECKING, Optional

from .serde import json_default

if TYPE_CHECKING:  # pragma: no cover
    from replay_tpu.data.dataset_label_encoder import DatasetLabelEncoder
    from replay_tpu.splitters import Splitter


def _artifact_dir(path) -> Path:
    return Path(path).with_suffix(".replay")


def _check_overwrite(target: Path, overwrite: bool) -> None:
    if target.exists() and not overwrite:
        msg = f"Artifact {target} already exists; pass overwrite=True to replace it"
        raise FileExistsError(msg)


def _resolve_class(class_name: str):
    """Look the class up across the public model-bearing namespaces."""
    import importlib

    for module_name in (
        "replay_tpu.models",
        "replay_tpu.scenarios",
        "replay_tpu.experimental",
        "replay_tpu.splitters",
        "replay_tpu.preprocessing",
    ):
        module = importlib.import_module(module_name)
        cls = getattr(module, class_name, None)
        if cls is not None:
            return cls
    msg = f"Cannot resolve class {class_name!r} in replay_tpu namespaces"
    raise ValueError(msg)


def save(obj, path, overwrite: bool = False) -> None:
    """Persist any object exposing the ``.save(path)`` convention."""
    if not hasattr(obj, "save"):
        msg = f"{type(obj).__name__} has no .save() — nothing to persist"
        raise TypeError(msg)
    _check_overwrite(_artifact_dir(path), overwrite)
    obj.save(str(path))


def load(path, model_type: Optional[type] = None):
    """Restore an object saved with :func:`save` / its class ``.save``.

    The concrete class is read from the artifact unless ``model_type`` pins it.
    """
    source = _artifact_dir(path)
    args = json.loads((source / "init_args.json").read_text())
    cls = model_type if model_type is not None else _resolve_class(args["_class_name"])
    return cls.load(str(path))


# reference common.py aliases: any SavableObject roundtrips through these
save_to_replay = save
load_from_replay = load


def save_splitter(splitter: "Splitter", path, overwrite: bool = False) -> None:
    """Persist a splitter's init args (splitters are stateless beyond them)."""
    import datetime

    target = _artifact_dir(path)
    _check_overwrite(target, overwrite)

    def encode(value):
        if isinstance(value, datetime.datetime):
            # round-trip through the splitter's own str-threshold path, which
            # parses with time_column_format (isoformat's 'T' would not)
            fmt = getattr(splitter, "time_column_format", None)
            return value.strftime(fmt) if fmt else value.isoformat()
        return value

    payload = {
        "_class_name": type(splitter).__name__,
        **{name: encode(getattr(splitter, name)) for name in splitter._init_arg_names},
    }
    # serialize BEFORE mkdir: a failure must not leave an empty artifact dir
    # that trips the overwrite guard on retry
    serialized = json.dumps(payload, default=json_default)
    target.mkdir(parents=True, exist_ok=True)
    (target / "init_args.json").write_text(serialized)


def load_splitter(path) -> "Splitter":
    source = _artifact_dir(path)
    args = json.loads((source / "init_args.json").read_text())
    cls = _resolve_class(args.pop("_class_name"))
    return cls(**args)


def save_encoder(encoder: "DatasetLabelEncoder", path, overwrite: bool = False) -> None:
    """Persist a fitted DatasetLabelEncoder (options + per-column rules)."""
    target = _artifact_dir(path)
    _check_overwrite(target, overwrite)
    payload = {
        "_class_name": "DatasetLabelEncoder",
        "handle_unknown_rule": encoder._handle_unknown,
        "default_value_rule": encoder._default_value,
        "query_column_name": getattr(encoder, "_query_column_name", None),
        "item_column_name": getattr(encoder, "_item_column_name", None),
        "rules": [rule._as_dict() for rule in encoder._encoding_rules.values()],
    }
    serialized = json.dumps(payload, default=json_default)
    target.mkdir(parents=True, exist_ok=True)
    (target / "init_args.json").write_text(serialized)


def load_encoder(path) -> "DatasetLabelEncoder":
    from replay_tpu.data.dataset_label_encoder import DatasetLabelEncoder
    from replay_tpu.preprocessing.label_encoder import LabelEncodingRule

    source = _artifact_dir(path)
    payload = json.loads((source / "init_args.json").read_text())
    encoder = DatasetLabelEncoder(
        handle_unknown_rule=payload["handle_unknown_rule"],
        default_value_rule=payload["default_value_rule"],
    )
    if payload["query_column_name"] is not None:
        encoder._query_column_name = payload["query_column_name"]
    if payload["item_column_name"] is not None:
        encoder._item_column_name = payload["item_column_name"]
    rules = [LabelEncodingRule._from_dict(spec) for spec in payload["rules"]]
    encoder._encoding_rules = {rule.column: rule for rule in rules}
    return encoder
