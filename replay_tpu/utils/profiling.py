"""Profiling hooks (beyond-parity: the reference has none — SURVEY.md §5).

``trace(dir)`` wraps a region in a jax.profiler trace viewable in TensorBoard /
xprof; ``StepTimer`` measures steady-state steps/sec + samples/sec the way
bench.py does (block_until_ready fencing, warmup exclusion).

For per-step instantaneous rates, retrace counting and device-memory
telemetry see :mod:`replay_tpu.obs` (``StepTelemetry`` generalizes this
timer and feeds ``Trainer.fit``'s event stream).
"""

from __future__ import annotations

import contextlib
import time
from typing import Optional


@contextlib.contextmanager
def trace(log_dir: str, *, create_perfetto_link: bool = False,
          python_tracer_level: int = 0):
    """Capture a device trace of the enclosed region.

    ``python_tracer_level=0`` (the default) keeps python-frame events OUT of
    the capture: a busy host loop (the scan-chunked fit's feeder + accounting
    threads) emits millions of them, flooding the profiler's event cap and
    dropping the XLA op events that ``obs.profile``'s device-time attribution
    needs. jax's public ``start_trace`` pins the level to 1, so when the
    xla_client ProfileOptions API is available the session is driven directly
    (same export layout); otherwise this degrades to the public API.
    """
    import jax

    session = None
    if not create_perfetto_link:
        try:
            from jax._src.lib import xla_client

            options = xla_client.profiler.ProfileOptions()
            options.python_tracer_level = int(python_tracer_level)
            jax.devices()  # TPU: libtpu must initialize BEFORE the tracer
            session = xla_client.profiler.ProfilerSession(options)
        except Exception:
            session = None
    if session is None:
        jax.profiler.start_trace(log_dir, create_perfetto_link=create_perfetto_link)
    try:
        yield
    finally:
        if session is not None:
            session.export(session.stop(), str(log_dir))
        else:
            jax.profiler.stop_trace()


class StepTimer:
    """Steady-state throughput: call ``tick(result)`` once per step."""

    def __init__(self, warmup_steps: int = 3, samples_per_step: Optional[int] = None) -> None:
        self.warmup_steps = warmup_steps
        self.samples_per_step = samples_per_step
        self._count = 0
        self._start: Optional[float] = None

    def tick(self, result=None) -> None:
        self._count += 1
        if self._count == self.warmup_steps:
            if result is not None:
                import jax

                jax.block_until_ready(result)
            self._start = time.perf_counter()

    def finish(self, result=None) -> dict:
        """Steady-state record — shape-stable: always ``steps`` (measured,
        post-warmup), ``steps_per_sec`` and ``samples_per_sec``, NaN-filled
        when nothing was measured, so JSONL consumers never KeyError."""
        if result is not None:
            import jax

            jax.block_until_ready(result)
        measured = self._count - self.warmup_steps
        if self._start is None or measured <= 0:
            return {
                "steps": max(measured, 0),
                "steps_per_sec": float("nan"),
                "samples_per_sec": float("nan"),
            }
        elapsed = time.perf_counter() - self._start
        return {
            "steps": measured,
            "steps_per_sec": measured / elapsed,
            "samples_per_sec": (
                measured * self.samples_per_step / elapsed
                if self.samples_per_step
                else float("nan")
            ),
        }
