"""Shared JSON-serialization helpers for `.replay` artifacts."""

from __future__ import annotations

import numpy as np


def to_plain(value):
    """numpy scalars/arrays → plain Python for json.dumps."""
    if isinstance(value, np.generic):
        return value.item()
    if isinstance(value, np.ndarray):
        return value.tolist()
    return value
