"""Shared JSON-serialization helpers for `.replay` artifacts (the init_args.json
convention of replay/utils/model_handler.py:42 and every saver in this repo)."""

from __future__ import annotations

import numpy as np


def to_plain(value):
    """numpy scalars/arrays → plain Python (permissive: other values pass through)."""
    if isinstance(value, np.generic):
        return value.item()
    if isinstance(value, np.ndarray):
        return value.tolist()
    return value


def json_default(value):
    """``json.dumps(default=...)`` hook: convert numpy and datetimes, REJECT
    anything else with a clear diagnostic (the hook is only invoked for
    non-serializable objects, so returning the value unchanged would yield a
    confusing circular-ref error)."""
    import datetime

    if isinstance(value, (np.generic, np.ndarray)):
        return to_plain(value)
    if isinstance(value, (datetime.datetime, datetime.date)):
        # isoformat string: every consumer that accepts datetime (e.g.
        # TimeSplitter.time_threshold) documents str as equally valid
        return value.isoformat()
    msg = f"Cannot serialize {type(value).__name__} value in a .replay artifact"
    raise TypeError(msg)
