"""Runtime/session glue: logging, device state, env-var configuration.

Capability parity with replay/utils/session_handler.py:22-129 (State singleton +
logger configuration + env-driven knobs). The Spark session becomes JAX device
state: the singleton resolves the default device/mesh once, honoring
``REPLAY_TPU_PLATFORM`` (e.g. force cpu) and ``REPLAY_TPU_LOG_LEVEL``.
"""

from __future__ import annotations

import logging
import os
from typing import Optional


def setup_logging(level: Optional[str] = None) -> logging.Logger:
    """Configure the framework logger once (idempotent)."""
    logger = logging.getLogger("replay_tpu")
    if not logger.handlers:
        handler = logging.StreamHandler()
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(name)s %(levelname)s: %(message)s")
        )
        logger.addHandler(handler)
    logger.setLevel((level or os.environ.get("REPLAY_TPU_LOG_LEVEL", "INFO")).upper())
    return logger


class State:
    """Process-wide device state (the reference's Spark-session singleton,
    re-purposed: one resolved device list + default mesh per process)."""

    _instance: Optional["State"] = None

    def __new__(cls) -> "State":
        if cls._instance is None:
            cls._instance = super().__new__(cls)
            cls._instance._devices = None
            cls._instance._mesh = None
        return cls._instance

    @property
    def devices(self):
        if self._devices is None:
            import jax

            platform = os.environ.get("REPLAY_TPU_PLATFORM")
            self._devices = jax.devices(platform) if platform else jax.devices()
        return self._devices

    @property
    def mesh(self):
        if self._mesh is None:
            from replay_tpu.nn.train import make_mesh

            self._mesh = make_mesh(self.devices)
        return self._mesh

    def set_mesh(self, mesh) -> None:
        self._mesh = mesh

    @classmethod
    def reset(cls) -> None:
        cls._instance = None


def get_default_mesh():
    """The process-wide default mesh (all devices, data-parallel)."""
    return State().mesh
