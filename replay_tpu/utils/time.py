"""Time-dependent interaction weighting.

Capability parity with the reference ``replay/utils/time.py:10-254``
(``get_item_recency`` / ``smoothe_time``), re-expressed pandas-native (the host
engine here — the reference routes through Spark). Semantics are identical:
an ``age`` in days is computed against the newest timestamp in the log and
mapped through one of three smoothing kernels calibrated so that
``age == decay`` gives weight 0.5, floored at ``limit``:

- ``power``:  ``(age + 1) ** (log 0.5 / log decay)``
- ``exp``:    ``(0.5 ** (1/decay)) ** age``
- ``linear``: ``1 - age * 0.5 / decay``
"""

from __future__ import annotations

import numpy as np
import pandas as pd

_DAY_SECONDS = 86400.0
_KINDS = ("power", "exp", "linear")


def _to_epoch_seconds(ts: pd.Series) -> pd.Series:
    """Timestamps (strings, datetimes, or numerics) -> float epoch seconds."""
    if pd.api.types.is_numeric_dtype(ts):
        return ts.astype(np.float64)
    converted = pd.to_datetime(ts)
    # unit-agnostic (pandas may infer datetime64[us] or [ns])
    return (converted - pd.Timestamp(0)).dt.total_seconds()


def _weights(age_days: np.ndarray, decay: float, limit: float, kind: str) -> np.ndarray:
    if kind not in _KINDS:
        msg = f"parameter kind must be one of {list(_KINDS)}, got {kind}"
        raise ValueError(msg)
    if decay <= 1:
        msg = f"decay must be greater than 1, got {decay}"
        raise ValueError(msg)
    if kind == "power":
        weight = np.power(age_days + 1.0, np.log(0.5) / np.log(decay))
    elif kind == "exp":
        weight = np.power(np.exp(np.log(0.5) / decay), age_days)
    else:  # linear
        weight = 1.0 - age_days * (0.5 / decay)
    return np.maximum(weight, limit)


def smoothe_time(
    log: pd.DataFrame,
    decay: float = 30,
    limit: float = 0.1,
    kind: str = "exp",
    timestamp_column: str = "timestamp",
    rating_column: str = "rating",
) -> pd.DataFrame:
    """Reweigh ``rating_column`` with a time-dependent decay.

    The newest interaction keeps its rating; older interactions decay so that
    an interaction ``decay`` days older is halved, never dropping below
    ``limit``. Returns a new frame; the input is not mutated.

    >>> df = pd.DataFrame({
    ...     "item_id": [1, 2, 3],
    ...     "timestamp": ["2099-03-19", "2099-03-20", "2099-03-22"],
    ...     "rating": [10.0, 3.0, 0.1],
    ... })
    >>> smoothe_time(df)["rating"].round(4).tolist()
    [9.3303, 2.8645, 0.1]
    """
    out = log.copy()
    seconds = _to_epoch_seconds(out[timestamp_column])
    age_days = (seconds.max() - seconds).to_numpy(dtype=np.float64) / _DAY_SECONDS
    out[rating_column] = out[rating_column].to_numpy(dtype=np.float64) * _weights(
        age_days, decay, limit, kind
    )
    return out


def get_item_recency(
    log: pd.DataFrame,
    decay: float = 30,
    limit: float = 0.1,
    kind: str = "exp",
    item_column: str = "item_id",
    timestamp_column: str = "timestamp",
    rating_column: str = "rating",
) -> pd.DataFrame:
    """Per-item recency weight from the mean interaction timestamp.

    Each item's interactions are averaged to a single timestamp; the item's
    weight is the smoothing kernel applied to that mean age (rating values in
    ``log`` are ignored — only item age matters). Returns one row per item
    with columns ``[item_column, timestamp_column, rating_column]``.
    """
    numeric_input = pd.api.types.is_numeric_dtype(log[timestamp_column])
    seconds = _to_epoch_seconds(log[timestamp_column])
    mean_ts = (
        pd.DataFrame({item_column: log[item_column].to_numpy(), "_ts": seconds.to_numpy()})
        .groupby(item_column, sort=True)["_ts"]
        .mean()
    )
    age_days = (mean_ts.max() - mean_ts.to_numpy()) / _DAY_SECONDS
    return pd.DataFrame(
        {
            item_column: mean_ts.index.to_numpy(),
            # keep the caller's timestamp representation: numeric logs get the
            # mean epoch seconds back, datetime-like logs get datetimes
            timestamp_column: (
                mean_ts.to_numpy()
                if numeric_input
                else pd.to_datetime(mean_ts.to_numpy(), unit="s")
            ),
            rating_column: _weights(age_days, decay, limit, kind),
        }
    )
