"""Optional-dependency gates and dataframe typing.

Capability parity with ``replay/utils/types.py`` (reference: replay/utils/types.py:23-50):
the reference feature-gates pyspark/torch/ann/openvino/optuna/lightfm/obp/lightautoml.
Our TPU build's primary engine is pandas (+ JAX for compute); polars and pyspark are
optional input adapters, optuna gates HPO, torch is only used for interop tests.
"""

from importlib.util import find_spec
from typing import Union

PANDAS_AVAILABLE = find_spec("pandas") is not None
POLARS_AVAILABLE = find_spec("polars") is not None
PYSPARK_AVAILABLE = find_spec("pyspark") is not None
OPTUNA_AVAILABLE = find_spec("optuna") is not None
TORCH_AVAILABLE = find_spec("torch") is not None
HYPOTHESIS_AVAILABLE = find_spec("hypothesis") is not None

_frames = []

if PANDAS_AVAILABLE:
    import pandas as _pd

    PandasDataFrame = _pd.DataFrame
    _frames.append(_pd.DataFrame)
else:  # pragma: no cover - pandas is always present in our image
    PandasDataFrame = None

if POLARS_AVAILABLE:  # pragma: no cover - polars absent in our image
    import polars as _pl

    PolarsDataFrame = _pl.DataFrame
    _frames.append(_pl.DataFrame)
else:
    PolarsDataFrame = None

if PYSPARK_AVAILABLE:  # pragma: no cover - pyspark absent in our image
    from pyspark.sql import DataFrame as SparkDataFrame

    _frames.append(SparkDataFrame)
else:
    SparkDataFrame = None

DataFrameLike = Union[tuple(_frames)] if len(_frames) > 1 else PandasDataFrame


def df_backend(df) -> str:
    """Return the backend name ('pandas' | 'polars' | 'spark') of a dataframe."""
    if PANDAS_AVAILABLE and isinstance(df, PandasDataFrame):
        return "pandas"
    if POLARS_AVAILABLE and isinstance(df, PolarsDataFrame):  # pragma: no cover
        return "polars"
    if PYSPARK_AVAILABLE and isinstance(df, SparkDataFrame):  # pragma: no cover
        return "spark"
    msg = f"Unsupported dataframe type: {type(df)}"
    raise TypeError(msg)
