"""Conditional tier (ref tests/conditional, SURVEY.md §4): the polars adapter
branches execute for real when polars is installed; skipped otherwise.

This makes the PARITY claim "polars frames are converted at the boundary"
testable instead of permanently `pragma: no cover` — a CI environment with the
`polars` extra runs these.
"""

import numpy as np
import pandas as pd
import pytest

pl = pytest.importorskip("polars")

from replay_tpu.data import Dataset, FeatureHint, FeatureInfo, FeatureSchema, FeatureType
from replay_tpu.utils.types import df_backend

pytestmark = pytest.mark.core


def interactions_frame():
    return pd.DataFrame(
        {
            "query_id": [0, 0, 1, 1, 2],
            "item_id": [0, 1, 1, 2, 0],
            "rating": [1.0, 2.0, 3.0, 4.0, 5.0],
            "timestamp": [0, 1, 0, 1, 0],
        }
    )


def schema():
    return FeatureSchema(
        [
            FeatureInfo("query_id", FeatureType.CATEGORICAL, FeatureHint.QUERY_ID),
            FeatureInfo("item_id", FeatureType.CATEGORICAL, FeatureHint.ITEM_ID),
            FeatureInfo("rating", FeatureType.NUMERICAL, FeatureHint.RATING),
            FeatureInfo("timestamp", FeatureType.NUMERICAL, FeatureHint.TIMESTAMP),
        ]
    )


def test_polars_interactions_roundtrip():
    polars_frame = pl.from_pandas(interactions_frame())
    assert df_backend(polars_frame) == "polars"
    dataset = Dataset(feature_schema=schema(), interactions=polars_frame)
    assert dataset.is_polars
    back = dataset.to_pandas()
    assert back.is_pandas
    pd.testing.assert_frame_equal(
        back.interactions.reset_index(drop=True), interactions_frame()
    )
    again = back.to_polars()
    assert again.is_polars
    assert again.interactions.shape == (5, 4)


def test_polars_counts_match_pandas():
    pandas_ds = Dataset(feature_schema=schema(), interactions=interactions_frame())
    polars_ds = Dataset(
        feature_schema=schema(), interactions=pl.from_pandas(interactions_frame())
    )
    assert polars_ds.query_count == pandas_ds.query_count
    assert polars_ds.item_count == pandas_ds.item_count
