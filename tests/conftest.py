"""Test harness: force an 8-device virtual CPU platform BEFORE jax is imported.

Mirrors the reference's trick of faking torch.distributed (SURVEY.md §4): the multi-chip
sharding paths are validated on a host-only mesh, no TPUs required.
"""

import os

# FORCE cpu: the session env pins JAX_PLATFORMS=axon (the one real TPU); tests must
# never contend for that tunnel — they run on an 8-device virtual CPU platform.
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (xla_flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np  # noqa: E402
import pandas as pd  # noqa: E402
import pytest  # noqa: E402

from replay_tpu.data import Dataset, FeatureHint, FeatureInfo, FeatureSchema, FeatureType  # noqa: E402


@pytest.fixture
def interactions_pandas() -> pd.DataFrame:
    return pd.DataFrame(
        {
            "user_id": [0, 0, 0, 1, 1, 2, 2, 2, 2, 3],
            "item_id": [0, 1, 2, 0, 2, 3, 1, 2, 0, 3],
            "rating": [1.0, 2.0, 3.0, 4.0, 5.0, 1.0, 2.0, 3.0, 4.0, 5.0],
            "timestamp": [0, 1, 2, 3, 4, 5, 6, 7, 8, 9],
        }
    )


@pytest.fixture
def feature_schema() -> FeatureSchema:
    return FeatureSchema(
        [
            FeatureInfo("user_id", FeatureType.CATEGORICAL, FeatureHint.QUERY_ID),
            FeatureInfo("item_id", FeatureType.CATEGORICAL, FeatureHint.ITEM_ID),
            FeatureInfo("rating", FeatureType.NUMERICAL, FeatureHint.RATING),
            FeatureInfo("timestamp", FeatureType.NUMERICAL, FeatureHint.TIMESTAMP),
        ]
    )


@pytest.fixture
def dataset(feature_schema, interactions_pandas) -> Dataset:
    return Dataset(feature_schema=feature_schema, interactions=interactions_pandas)


@pytest.fixture
def rng() -> np.random.Generator:
    return np.random.default_rng(0)
