"""Test harness: force an 8-device virtual CPU platform BEFORE jax is imported.

Mirrors the reference's trick of faking torch.distributed (SURVEY.md §4): the multi-chip
sharding paths are validated on a host-only mesh, no TPUs required.

The environment injects a TPU-relay PJRT plugin into every interpreter via
``PYTHONPATH`` sitecustomize; its registration serializes on the single TPU grant and
can block for minutes. Tests must never contend for that tunnel, so if the plugin's
site dir is on PYTHONPATH we re-exec pytest once with a cleaned environment.
"""

import os
import sys


def pytest_configure(config):
    """Re-exec pytest with a cleaned environment if the TPU-relay site dir is active.

    Runs in ``pytest_configure`` (not at import) so the capture manager exists and can
    restore the real stdout/stderr fds before ``execve`` — otherwise the child writes
    into the dead parent's capture temp file and all output vanishes.
    """
    if ".axon_site" not in os.environ.get("PYTHONPATH", "") or os.environ.get(
        "REPLAY_TPU_CLEAN_REEXEC"
    ):
        return
    capman = config.pluginmanager.getplugin("capturemanager")
    if capman is not None:
        capman.stop_global_capturing()
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    clean_pythonpath = os.pathsep.join(
        [p for p in os.environ.get("PYTHONPATH", "").split(os.pathsep) if p and ".axon_site" not in p]
        + [repo_root]
    )
    env = {
        **os.environ,
        "PYTHONPATH": clean_pythonpath,
        "REPLAY_TPU_CLEAN_REEXEC": "1",
        "JAX_PLATFORMS": "cpu",
    }
    args = list(config.invocation_params.args)
    os.execve(sys.executable, [sys.executable, "-m", "pytest", *args], env)

# FORCE cpu: the session env pins JAX_PLATFORMS=axon (the one real TPU); tests must
# never contend for that tunnel — they run on an 8-device virtual CPU platform.
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (xla_flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np  # noqa: E402
import pandas as pd  # noqa: E402
import pytest  # noqa: E402

from replay_tpu.data import Dataset, FeatureHint, FeatureInfo, FeatureSchema, FeatureType  # noqa: E402


@pytest.fixture
def interactions_pandas() -> pd.DataFrame:
    return pd.DataFrame(
        {
            "user_id": [0, 0, 0, 1, 1, 2, 2, 2, 2, 3],
            "item_id": [0, 1, 2, 0, 2, 3, 1, 2, 0, 3],
            "rating": [1.0, 2.0, 3.0, 4.0, 5.0, 1.0, 2.0, 3.0, 4.0, 5.0],
            "timestamp": [0, 1, 2, 3, 4, 5, 6, 7, 8, 9],
        }
    )


@pytest.fixture
def feature_schema() -> FeatureSchema:
    return FeatureSchema(
        [
            FeatureInfo("user_id", FeatureType.CATEGORICAL, FeatureHint.QUERY_ID),
            FeatureInfo("item_id", FeatureType.CATEGORICAL, FeatureHint.ITEM_ID),
            FeatureInfo("rating", FeatureType.NUMERICAL, FeatureHint.RATING),
            FeatureInfo("timestamp", FeatureType.NUMERICAL, FeatureHint.TIMESTAMP),
        ]
    )


@pytest.fixture
def dataset(feature_schema, interactions_pandas) -> Dataset:
    return Dataset(feature_schema=feature_schema, interactions=interactions_pandas)


@pytest.fixture
def rng() -> np.random.Generator:
    return np.random.default_rng(0)


def pytest_collection_modifyitems(config, items):
    """Auto-mark tests: ``jax`` for device-touching paths, ``core`` for the rest.

    Mirrors the reference's core/torch marker split (its CI runs them as separate
    job families) so the fast dataframe tier stays seconds-fast.
    """
    import pytest as _pytest

    jax_paths = ("tests/nn", "tests/parallel", "tests/models/nn", "test_builder", "test_train")
    for item in items:
        if item.get_closest_marker("jax") or item.get_closest_marker("core"):
            continue  # explicitly marked
        path = str(item.fspath)
        if any(fragment in path for fragment in jax_paths):
            item.add_marker(_pytest.mark.jax)
        else:
            item.add_marker(_pytest.mark.core)
