"""Data→tensor bridge: tokenizer, sequential dataset, batcher, partitioning."""

import numpy as np
import pandas as pd
import pytest

from replay_tpu.data import Dataset, FeatureHint, FeatureInfo, FeatureSchema, FeatureSource, FeatureType
from replay_tpu.data.nn import (
    Partitioning,
    ReplicasInfo,
    SequenceBatcher,
    SequenceTokenizer,
    SequentialDataset,
    TensorFeatureInfo,
    TensorFeatureSource,
    TensorSchema,
    validation_batches,
)


@pytest.fixture
def rich_dataset() -> Dataset:
    interactions = pd.DataFrame(
        {
            "user_id": ["u1", "u1", "u1", "u2", "u2", "u3"],
            "item_id": ["a", "b", "c", "b", "a", "c"],
            "rating": [1.0, 2.0, 3.0, 4.0, 5.0, 1.5],
            # deliberately unsorted timestamps inside u1
            "timestamp": [2, 0, 1, 5, 4, 6],
        }
    )
    item_features = pd.DataFrame({"item_id": ["a", "b", "c"], "genre": ["x", "y", "x"]})
    query_features = pd.DataFrame({"user_id": ["u1", "u2", "u3"], "age": [10.0, 20.0, 30.0]})
    schema = FeatureSchema(
        [
            FeatureInfo("user_id", FeatureType.CATEGORICAL, FeatureHint.QUERY_ID),
            FeatureInfo("item_id", FeatureType.CATEGORICAL, FeatureHint.ITEM_ID),
            FeatureInfo("rating", FeatureType.NUMERICAL, FeatureHint.RATING),
            FeatureInfo("timestamp", FeatureType.NUMERICAL, FeatureHint.TIMESTAMP),
            FeatureInfo("genre", FeatureType.CATEGORICAL, feature_source=FeatureSource.ITEM_FEATURES),
            FeatureInfo("age", FeatureType.NUMERICAL, feature_source=FeatureSource.QUERY_FEATURES),
        ]
    )
    return Dataset(
        feature_schema=schema,
        interactions=interactions,
        item_features=item_features,
        query_features=query_features,
    )


@pytest.fixture
def tensor_schema_rich() -> TensorSchema:
    return TensorSchema(
        [
            TensorFeatureInfo(
                "item_id",
                FeatureType.CATEGORICAL,
                is_seq=True,
                feature_hint=FeatureHint.ITEM_ID,
                feature_sources=[TensorFeatureSource(FeatureSource.INTERACTIONS, "item_id")],
                embedding_dim=8,
            ),
            TensorFeatureInfo(
                "rating",
                FeatureType.NUMERICAL,
                is_seq=True,
                feature_sources=[TensorFeatureSource(FeatureSource.INTERACTIONS, "rating")],
                tensor_dim=1,
                embedding_dim=8,
            ),
            TensorFeatureInfo(
                "genre",
                FeatureType.CATEGORICAL,
                is_seq=True,
                feature_sources=[TensorFeatureSource(FeatureSource.ITEM_FEATURES, "genre")],
                embedding_dim=8,
            ),
            TensorFeatureInfo(
                "age",
                FeatureType.NUMERICAL,
                is_seq=False,
                feature_sources=[TensorFeatureSource(FeatureSource.QUERY_FEATURES, "age")],
                tensor_dim=1,
                embedding_dim=8,
            ),
        ]
    )


def make_item_seq_dataset(lengths, num_items=10):
    schema = TensorSchema(
        TensorFeatureInfo("item_id", FeatureType.CATEGORICAL, is_seq=True,
                          feature_hint=FeatureHint.ITEM_ID, cardinality=num_items)
    )
    frame = pd.DataFrame(
        {
            "query_id": np.arange(len(lengths)),
            "item_id": [np.arange(n) % num_items for n in lengths],
        }
    )
    return SequentialDataset(schema, "query_id", "item_id", frame)


class TestSequenceTokenizer:
    def test_fit_transform_sequences(self, rich_dataset, tensor_schema_rich):
        tokenizer = SequenceTokenizer(tensor_schema_rich)
        seq = tokenizer.fit_transform(rich_dataset)
        assert len(seq) == 3
        # cardinality assigned from the fitted encoder
        assert tensor_schema_rich["item_id"].cardinality == 3
        # padding defaults to cardinality for ITEM_ID (weight-tying alignment)
        assert tensor_schema_rich["item_id"].padding_value == 3
        # u1's items sorted by timestamp: b(0) < c(1) < a(2) in raw time order
        u1 = tokenizer.query_id_encoder.mapping["user_id"]["u1"]
        items_u1 = seq.get_sequence_by_query_id(u1, "item_id")
        item_map = tokenizer.item_id_encoder.mapping["item_id"]
        assert items_u1.tolist() == [item_map["b"], item_map["c"], item_map["a"]]
        # item-side sequential feature follows the item sequence
        genre_u1 = seq.get_sequence_by_query_id(u1, "genre")
        assert len(genre_u1) == 3
        # query-side scalar feature: one value per query
        age_u1 = seq.get_sequence_by_query_id(u1, "age")
        assert np.asarray(age_u1).reshape(-1)[0] == 10.0

    def test_unfitted_transform_raises(self, rich_dataset, tensor_schema_rich):
        with pytest.raises(RuntimeError, match="not fitted"):
            SequenceTokenizer(tensor_schema_rich).transform(rich_dataset)

    def test_save_load_roundtrip(self, tmp_path, rich_dataset, tensor_schema_rich):
        tokenizer = SequenceTokenizer(tensor_schema_rich)
        before = tokenizer.fit_transform(rich_dataset)
        tokenizer.save(str(tmp_path / "tok"))
        restored = SequenceTokenizer.load(str(tmp_path / "tok"))
        after = restored.transform(rich_dataset)
        assert len(before) == len(after)
        for i in range(len(before)):
            np.testing.assert_array_equal(
                before.get_sequence(i, "item_id"), after.get_sequence(i, "item_id")
            )
        assert restored.item_id_encoder.mapping == tokenizer.item_id_encoder.mapping
        # per-source sub-encoder views survive the artifact roundtrip
        assert (
            set(restored.item_features_encoder.mapping)
            == set(tokenizer.item_features_encoder.mapping)
        )
        assert (
            set(restored.interactions_encoder.mapping)
            == set(tokenizer.interactions_encoder.mapping)
        )


class TestSequentialDataset:
    def make(self, ids, schema=None):
        schema = schema or TensorSchema(
            TensorFeatureInfo(
                "item_id",
                FeatureType.CATEGORICAL,
                is_seq=True,
                feature_hint=FeatureHint.ITEM_ID,
                cardinality=10,
            )
        )
        frame = pd.DataFrame(
            {"query_id": ids, "item_id": [np.arange(i + 1) for i in range(len(ids))]}
        )
        return SequentialDataset(schema, "query_id", "item_id", frame)

    def test_lookup_and_lengths(self):
        ds = self.make([5, 7, 9])
        assert len(ds) == 3
        assert ds.get_query_id(1) == 7
        assert ds.get_sequence_length(2) == 3
        assert ds.get_max_sequence_length() == 3
        np.testing.assert_array_equal(ds.get_sequence_by_query_id(9, "item_id"), [0, 1, 2])

    def test_keep_common(self):
        left, right = self.make([1, 2, 3]), self.make([2, 3, 4])
        a, b = SequentialDataset.keep_common_query_ids(left, right)
        assert a.query_ids.tolist() == [2, 3] and b.query_ids.tolist() == [2, 3]

    def test_save_load(self, tmp_path):
        ds = self.make([1, 2, 3])
        ds.save(str(tmp_path / "seq"))
        restored = SequentialDataset.load(str(tmp_path / "seq"))
        assert len(restored) == 3
        np.testing.assert_array_equal(
            restored.get_sequence(2, "item_id"), ds.get_sequence(2, "item_id")
        )


class TestPartitioning:
    @pytest.mark.parametrize("n", [16, 17, 23])
    def test_disjoint_exhaustive(self, n):
        """8 fake replicas cover every row; overlap only from wrap-around padding."""
        shards = [
            Partitioning(ReplicasInfo(8, r)).generate(n) for r in range(8)
        ]
        sizes = {len(s) for s in shards}
        assert len(sizes) == 1  # every replica yields the same count
        union = np.concatenate(shards)
        assert set(union.tolist()) == set(range(n))
        padded_len = -(-n // 8) * 8
        assert len(union) == padded_len

    def test_shuffle_deterministic_and_epoch_dependent(self):
        p = Partitioning(ReplicasInfo(4, 1), shuffle=True, seed=3)
        a, b = p.generate(32, epoch=0), p.generate(32, epoch=0)
        np.testing.assert_array_equal(a, b)
        c = p.generate(32, epoch=1)
        assert not np.array_equal(a, c)

    def test_bad_replica_raises(self):
        with pytest.raises(ValueError):
            ReplicasInfo(4, 4)


class TestSequenceBatcher:
    def make_seq_dataset(self, lengths, num_items=10):
        return make_item_seq_dataset(lengths, num_items)

    def test_fixed_shapes_and_left_padding(self):
        ds = self.make_seq_dataset([3, 5, 2])
        batches = list(SequenceBatcher(ds, batch_size=2, max_sequence_length=4))
        assert len(batches) == 2
        for batch in batches:
            assert batch["item_id"].shape == (2, 4)
            assert batch["item_id_mask"].shape == (2, 4)
        first = batches[0]
        # left padding: row 0 (len 3) has one pad slot at position 0 with padding id 10
        assert first["item_id"][0, 0] == 10 and not first["item_id_mask"][0, 0]
        np.testing.assert_array_equal(first["item_id"][0, 1:], [0, 1, 2])
        # row 1 (len 5) keeps only the LAST 4 events in no-window mode
        np.testing.assert_array_equal(first["item_id"][1], [1, 2, 3, 4])
        # final batch padded with repeated row + valid mask
        last = batches[1]
        np.testing.assert_array_equal(last["valid"], [True, False])

    def test_window_expansion(self):
        ds = self.make_seq_dataset([10])
        batcher = SequenceBatcher(ds, batch_size=4, max_sequence_length=4, windows=True)
        batches = list(batcher)
        rows = np.concatenate([b["item_id"][b["valid"]] for b in batches])
        # stride=max_len: windows [0:4], [4:8], then the tail window [6:10]
        assert rows.shape == (3, 4)
        np.testing.assert_array_equal(rows[0], [0, 1, 2, 3])
        np.testing.assert_array_equal(rows[-1], [6, 7, 8, 9])

    def test_replica_sharded_batches_cover_all_rows(self):
        ds = self.make_seq_dataset([4] * 10)
        seen = []
        for r in range(4):
            batcher = SequenceBatcher(
                ds,
                batch_size=2,
                max_sequence_length=4,
                partitioning=Partitioning(ReplicasInfo(4, r)),
            )
            for batch in batcher:
                seen.extend(batch["query_id"][batch["valid"]].tolist())
        assert set(seen) == set(range(10))

    def test_validation_batches(self):
        train = self.make_seq_dataset([3, 4, 5])
        gt = self.make_seq_dataset([2, 2])  # only queries 0 and 1 have ground truth
        batches = list(validation_batches(train, gt, batch_size=2, max_sequence_length=4))
        assert len(batches) == 1
        batch = batches[0]
        assert set(batch["query_id"].tolist()) == {0, 1}
        assert batch["ground_truth"].shape[0] == 2
        assert (batch["ground_truth"] >= -1).all()
        assert batch["train"].shape[0] == 2
        # padding slots are -1
        assert (batch["ground_truth"][batch["ground_truth"] < 0] == -1).all()


class TestPrefetch:
    def test_order_and_completion(self):
        from replay_tpu.data.nn import prefetch

        items = list(prefetch(iter(range(20)), depth=3))
        assert items == list(range(20))

    def test_producer_exception_surfaces(self):
        from replay_tpu.data.nn import prefetch

        def gen():
            yield 1
            raise RuntimeError("boom")

        it = prefetch(gen(), depth=2)
        assert next(it) == 1
        with pytest.raises(RuntimeError, match="boom"):
            list(it)

    def test_bad_depth_raises_at_call_time(self):
        from replay_tpu.data.nn import prefetch

        with pytest.raises(ValueError):
            prefetch([1], depth=0)  # no consumption needed

    def test_overlaps_slow_producer(self):
        import time

        from replay_tpu.data.nn import prefetch

        def slow():
            for i in range(5):
                time.sleep(0.05)
                yield i

        start = time.perf_counter()
        for _ in slow():
            time.sleep(0.05)
        serial = time.perf_counter() - start

        start = time.perf_counter()
        for _ in prefetch(slow(), depth=4):
            time.sleep(0.05)  # consumer work overlaps producer work
        overlapped = time.perf_counter() - start
        assert overlapped < serial * 0.85  # measured baseline, load-tolerant

    def test_abandoned_iterator_releases_producer(self):
        import time

        from replay_tpu.data.nn import prefetch

        produced = {"n": 0}

        def endless():
            while True:
                produced["n"] += 1
                yield produced["n"]

        it = prefetch(endless(), depth=2)
        assert next(it) == 1
        it.close()  # GeneratorExit -> stop signal
        time.sleep(0.3)
        count_after_close = produced["n"]
        time.sleep(0.3)
        assert produced["n"] == count_after_close  # producer actually stopped

    def test_close_joins_producer_thread(self):
        """close() joins the producer (blocking-put protocol): abandoned
        iterators must not leak daemon threads."""
        import threading

        from replay_tpu.data.nn import prefetch

        def endless():
            while True:
                yield 1

        before = {t.ident for t in threading.enumerate()}
        it = prefetch(endless(), depth=2)
        assert next(it) == 1
        spawned = [
            t
            for t in threading.enumerate()
            if t.ident not in before and "prefetch" in t.name
        ]
        assert len(spawned) == 1
        it.close()
        assert not spawned[0].is_alive()  # joined, not abandoned


class TestDevicePrefetcher:
    def test_orders_and_applies_place_on_feeder_thread(self):
        import threading

        from replay_tpu.data.nn import DevicePrefetcher

        feeder_tids = set()

        def place(x):
            feeder_tids.add(threading.get_ident())
            return x * 10

        with DevicePrefetcher(iter(range(5)), place, depth=2) as feed:
            assert list(feed) == [(i, i * 10) for i in range(5)]
        assert feeder_tids and threading.get_ident() not in feeder_tids

    def test_place_errors_relay_to_consumer(self):
        from replay_tpu.data.nn import DevicePrefetcher

        def bad_place(x):
            if x == 2:
                raise RuntimeError("boom")
            return x

        got = []
        with pytest.raises(RuntimeError, match="boom"):
            for item, _ in DevicePrefetcher(iter(range(5)), bad_place, depth=1):
                got.append(item)
        assert got == [0, 1]

    def test_close_stops_and_joins_feeder(self):
        import threading
        import time

        from replay_tpu.data.nn import DevicePrefetcher

        placed = {"n": 0}

        def place(x):
            placed["n"] += 1
            return x

        def endless():
            while True:
                yield 1

        before = {t.ident for t in threading.enumerate()}
        feed = DevicePrefetcher(endless(), place, depth=2)
        next(feed)
        spawned = [
            t
            for t in threading.enumerate()
            if t.ident not in before and "device-feed" in t.name
        ]
        assert len(spawned) == 1
        feed.close()
        assert not spawned[0].is_alive()
        count_after_close = placed["n"]
        time.sleep(0.2)
        assert placed["n"] == count_after_close  # feeder fully stopped

    def test_bad_depth_raises(self):
        from replay_tpu.data.nn import DevicePrefetcher

        with pytest.raises(ValueError):
            DevicePrefetcher([1], place=lambda x: x, depth=0)


class TestBucketedBatching:
    def make_seq_dataset(self, lengths, num_items=30):
        return make_item_seq_dataset(lengths, num_items)

    def test_shapes_follow_buckets_and_coverage(self):
        lengths = [3, 4, 5, 12, 14, 15, 16, 2]
        ds = self.make_seq_dataset(lengths)
        batcher = SequenceBatcher(ds, batch_size=2, max_sequence_length=16,
                                  bucket_boundaries=(5, 16))
        batches = list(batcher)
        assert len(batches) == len(batcher)
        widths = sorted({b["item_id"].shape[1] for b in batches})
        assert widths == [5, 16]
        # short sequences pad only to 5, not 16
        seen = []
        for batch in batches:
            assert batch["item_id"].shape[0] == 2
            seen.extend(batch["query_id"][batch["valid"]].tolist())
        assert sorted(seen) == list(range(len(lengths)))  # every query exactly once
        # the padding waste shrinks vs unbucketed
        def waste(bs):
            return sum(int((~b["item_id_mask"][b["valid"]]).sum()) for b in bs)
        unbucketed = list(SequenceBatcher(ds, batch_size=2, max_sequence_length=16))
        assert waste(batches) < waste(unbucketed)

    def test_buckets_with_windows(self):
        ds = self.make_seq_dataset([40, 3])
        batcher = SequenceBatcher(ds, batch_size=1, max_sequence_length=16,
                                  windows=True, bucket_boundaries=(4, 16))
        rows = []
        for batch in batcher:
            width = batch["item_id"].shape[1]
            assert width in (4, 16)
            rows.extend(batch["item_id"][batch["valid"]][batch["item_id_mask"][batch["valid"]]].tolist())
        assert sorted(set(rows)) == sorted(set(np.arange(40) % 30) | {0, 1, 2})

    def test_bucket_guards(self):
        ds = self.make_seq_dataset([3, 8])
        # boundaries above max are dropped; max stays the top bucket
        batcher = SequenceBatcher(ds, batch_size=1, max_sequence_length=8,
                                  bucket_boundaries=(4, 100))
        assert batcher._buckets() == [4, 8]
        assert max(b["item_id"].shape[1] for b in batcher) == 8
        # multi-replica + buckets is rejected
        with pytest.raises(ValueError, match="multi-replica"):
            SequenceBatcher(ds, batch_size=1, max_sequence_length=8,
                            bucket_boundaries=(4,),
                            partitioning=Partitioning(ReplicasInfo(2, 0)))
