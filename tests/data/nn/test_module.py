"""DataModule: per-split sources + pipelines -> trainer-shaped batch streams."""

import numpy as np
import pandas as pd
import pytest

from replay_tpu.data import FeatureHint, FeatureType
from replay_tpu.data.nn import (
    DataModule,
    SequentialDataset,
    TensorFeatureInfo,
    TensorSchema,
    write_sequence_parquet,
)
from replay_tpu.nn.transform import RenameTransform, GroupTransform


@pytest.fixture
def sources(tmp_path):
    schema = TensorSchema(
        TensorFeatureInfo("item_id", FeatureType.CATEGORICAL, is_seq=True,
                          feature_hint=FeatureHint.ITEM_ID, cardinality=20)
    )
    paths = {}
    for split, n in (("train", 17), ("validate", 6)):
        frame = pd.DataFrame({
            "query_id": np.arange(n),
            "item_id": [np.arange(i % 5 + 1) for i in range(n)],
        })
        path = str(tmp_path / f"{split}.parquet")
        write_sequence_parquet(path, SequentialDataset(schema, "query_id", "item_id", frame))
        paths[split] = path
    return paths


@pytest.mark.jax
def test_per_split_streams(sources):
    module = DataModule(
        sources=sources,
        batch_size=4,
        metadata={"item_id": {"shape": 5, "padding": 20}},
        transforms={
            "train": [RenameTransform({"item_id_mask": "padding_mask"}),
                      GroupTransform({"feature_tensors": ["item_id"]})],
            "validate": [RenameTransform({"item_id_mask": "padding_mask"})],
        },
    )
    train = list(module.train_batches(epoch=0))
    assert len(train) == 5  # ceil(17/4)
    assert "feature_tensors" in train[0] and "padding_mask" in train[0]
    val = list(module.val_batches())
    assert len(val) == 2
    assert "padding_mask" in val[0] and "feature_tensors" not in val[0]
    # train shuffling advances with the epoch; validation order is stable
    epoch1 = [b["query_id"][b["valid"]] for b in module.train_batches(epoch=1)]
    epoch0 = [b["query_id"][b["valid"]] for b in module.train_batches(epoch=0)]
    assert not all(np.array_equal(a, b) for a, b in zip(epoch0, epoch1))
    with pytest.raises(KeyError, match="No source"):
        list(module.test_batches())
