"""Sequence packing: first-fit bin packing + the packed batcher's contract."""

import numpy as np
import pandas as pd
import pytest

from replay_tpu.data import FeatureHint, FeatureType
from replay_tpu.data.nn import (
    PackedSequenceBatcher,
    SequenceBatcher,
    SequentialDataset,
    TensorFeatureInfo,
    TensorSchema,
    first_fit_pack,
)
from replay_tpu.data.nn.packing import bucketed_length


class TestFirstFitPack:
    def test_capacity_respected(self):
        lengths = [3, 4, 5, 2, 6, 1]
        rows = first_fit_pack(lengths, 8)
        for members in rows:
            assert sum(lengths[i] for i in members) <= 8
        assert sorted(i for members in rows for i in members) == list(range(6))

    def test_first_fit_is_deterministic_and_orders_by_arrival(self):
        assert first_fit_pack([3, 4, 5, 2, 6, 1], 8) == first_fit_pack(
            [3, 4, 5, 2, 6, 1], 8
        )
        # 3 then 4 share a bin (3+4<=8, free 1); 5 opens the second; 2 rides
        # with 5 (first bin's free slot is too small)
        assert first_fit_pack([3, 4, 5, 2], 8) == [[0, 1], [2, 3]]

    def test_bucket_boundaries_round_slots_up(self):
        assert bucketed_length(3, 8, [4]) == 4
        assert bucketed_length(5, 8, [4]) == 8
        assert bucketed_length(9, 8, [4]) == 8  # clamped to capacity
        assert bucketed_length(3, 8, None) == 3
        # bucketed: 3 and 4 both cost a 4-slot; two fit per 8-row
        rows = first_fit_pack([3, 4, 3, 4], 8, bucket_boundaries=[4])
        assert all(len(members) == 2 for members in rows)

    def test_open_rows_bounds_the_window(self):
        # every entry fills a row; with open_rows=1 bins close in order
        rows = first_fit_pack([7, 7, 7, 2], 8, open_rows=1)
        assert sorted(i for members in rows for i in members) == list(range(4))

    def test_oversized_entries_clamp_to_capacity(self):
        rows = first_fit_pack([20, 1], 4)
        assert rows == [[0], [1]]

    def test_bad_capacity(self):
        with pytest.raises(ValueError, match="capacity"):
            first_fit_pack([1], 0)


@pytest.fixture
def ragged_dataset():
    schema = TensorSchema(
        TensorFeatureInfo(
            "item_id", FeatureType.CATEGORICAL, is_seq=True,
            feature_hint=FeatureHint.ITEM_ID, cardinality=50, embedding_dim=16,
        )
    )
    rng = np.random.default_rng(0)
    frame = pd.DataFrame(
        {
            "query_id": np.arange(40),
            "item_id": [
                rng.integers(1, 50, rng.integers(1, 6)).astype(np.int64)
                for _ in range(40)
            ],
        }
    )
    return SequentialDataset(schema, "query_id", "item_id", frame), frame


class TestPackedSequenceBatcher:
    def test_shapes_segments_masks(self, ragged_dataset):
        dataset, frame = ragged_dataset
        packer = PackedSequenceBatcher(
            dataset, batch_size=4, max_sequence_length=12, shuffle=True, seed=1
        )
        batches = list(packer)
        for batch in batches:
            assert batch["item_id"].shape == (4, 12)
            assert batch["segment_ids"].shape == (4, 12)
            assert batch["segment_ids"].dtype == np.int32
            np.testing.assert_array_equal(
                batch["item_id_mask"], batch["segment_ids"] > 0
            )
            # segments are 1..k contiguous from the left per row
            for row in batch["segment_ids"]:
                nonzero = row[row > 0]
                assert (np.diff(nonzero) >= 0).all()

    def test_every_token_appears_exactly_once(self, ragged_dataset):
        dataset, frame = ragged_dataset
        packer = PackedSequenceBatcher(
            dataset, batch_size=4, max_sequence_length=12, shuffle=True, seed=1
        )
        total_tokens = sum(len(s) for s in frame["item_id"])
        packed_tokens = sum(
            int((b["segment_ids"] > 0).sum()) for b in packer
        )
        assert packed_tokens == total_tokens

    def test_deterministic_and_epoch_reshuffles(self, ragged_dataset):
        dataset, _ = ragged_dataset

        def run(epoch):
            packer = PackedSequenceBatcher(
                dataset, batch_size=4, max_sequence_length=12, shuffle=True, seed=1
            )
            packer.set_epoch(epoch)
            return list(packer)

        first, again = run(0), run(0)
        for a, b in zip(first, again):
            for key in a:
                np.testing.assert_array_equal(a[key], b[key])
        other = run(1)
        assert any(
            not np.array_equal(a["item_id"], b["item_id"])
            for a, b in zip(first, other)
        ) or len(first) != len(other)

    def test_cuts_batches_and_padding_vs_unpacked(self, ragged_dataset):
        dataset, _ = ragged_dataset
        packer = PackedSequenceBatcher(
            dataset, batch_size=4, max_sequence_length=12, shuffle=False
        )
        unpacked = SequenceBatcher(
            dataset, batch_size=4, max_sequence_length=12, shuffle=False
        )
        assert len(packer) < len(unpacked)
        summary = packer.packing_summary()
        assert summary["padding_fraction"] < summary["unpacked_padding_fraction"]
        assert summary["segments_per_row"] > 1.5

    def test_max_segments_bounds_row_occupancy(self, ragged_dataset):
        dataset, _ = ragged_dataset
        packer = PackedSequenceBatcher(
            dataset, batch_size=4, max_sequence_length=12, max_segments=2
        )
        for batch in packer:
            assert batch["segment_ids"].max() <= 2

    def test_scan_compatible_with_slot_buckets(self, ragged_dataset):
        dataset, _ = ragged_dataset
        packer = PackedSequenceBatcher(
            dataset, batch_size=4, max_sequence_length=12, bucket_boundaries=(4, 8)
        )
        assert packer.scan_compatible  # slot rounding, NOT per-batch widths
        shapes = {b["item_id"].shape for b in packer}
        assert shapes == {(4, 12)}

    def test_recency_truncation_for_long_sequences(self):
        schema = TensorSchema(
            TensorFeatureInfo(
                "item_id", FeatureType.CATEGORICAL, is_seq=True,
                feature_hint=FeatureHint.ITEM_ID, cardinality=50,
            )
        )
        frame = pd.DataFrame(
            {"query_id": [0], "item_id": [np.arange(1, 21)]}  # longer than L
        )
        dataset = SequentialDataset(schema, "query_id", "item_id", frame)
        packer = PackedSequenceBatcher(dataset, batch_size=2, max_sequence_length=6)
        batch = next(iter(packer))
        # keeps the LAST 6 events, left-aligned in the row
        np.testing.assert_array_equal(batch["item_id"][0], np.arange(15, 21))
        np.testing.assert_array_equal(batch["segment_ids"][0], [1] * 6)
