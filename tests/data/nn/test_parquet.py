"""Streaming parquet pipeline + native ragged kernel."""

import numpy as np
import pandas as pd
import pytest

from replay_tpu.data import FeatureHint, FeatureType
from replay_tpu.data.nn import TensorFeatureInfo, TensorSchema, SequentialDataset
from replay_tpu.data.nn.parquet import ParquetBatcher, write_sequence_parquet
from replay_tpu.data.nn.partitioning import Partitioning, ReplicasInfo
from replay_tpu.native import gather_pad, native_available


class TestNativeRaggedKernel:
    def setup_method(self):
        # rows: [0,1,2], [3], [4,5,6,7,8]
        self.values = np.arange(9, dtype=np.int64)
        self.offsets = np.array([0, 3, 4, 9], np.int64)

    def test_gather_pad_semantics(self):
        out, mask = gather_pad(self.values, self.offsets, np.array([0, 1, 2]), 4, -7)
        np.testing.assert_array_equal(out[0], [-7, 0, 1, 2])  # left padding
        np.testing.assert_array_equal(mask[0], [False, True, True, True])
        np.testing.assert_array_equal(out[1], [-7, -7, -7, 3])
        np.testing.assert_array_equal(out[2], [5, 6, 7, 8])  # recency truncation
        assert mask[2].all()

    def test_native_matches_fallback(self):
        indices = np.array([2, 0, 1, 2], np.int64)
        native_out, native_mask = gather_pad(self.values, self.offsets, indices, 3, 0)
        # force the numpy fallback by calling the pure-python branch
        import replay_tpu.native as native_module

        saved = native_module._native
        native_module._native = None
        native_module._build_attempted = True
        try:
            fb_out, fb_mask = gather_pad(self.values, self.offsets, indices, 3, 0)
        finally:
            native_module._native = saved
            native_module._build_attempted = False
        np.testing.assert_array_equal(native_out, fb_out)
        np.testing.assert_array_equal(native_mask, fb_mask)

    def test_native_builds(self):
        # the in-image toolchain must actually produce the extension
        assert native_available()

    def test_out_of_range_raises(self):
        if not native_available():
            pytest.skip("native kernel unavailable")
        with pytest.raises(ValueError):
            gather_pad(self.values, self.offsets, np.array([5]), 3, 0)


@pytest.fixture
def sequence_parquet(tmp_path):
    schema = TensorSchema(
        TensorFeatureInfo("item_id", FeatureType.CATEGORICAL, is_seq=True,
                          feature_hint=FeatureHint.ITEM_ID, cardinality=50)
    )
    frame = pd.DataFrame(
        {
            "query_id": np.arange(23),
            "item_id": [np.arange(i % 7 + 1) for i in range(23)],
        }
    )
    dataset = SequentialDataset(schema, "query_id", "item_id", frame)
    path = str(tmp_path / "seqs.parquet")
    write_sequence_parquet(path, dataset)
    return path


class TestParquetBatcher:
    def test_fixed_shapes_and_masks(self, sequence_parquet):
        batcher = ParquetBatcher(
            sequence_parquet, batch_size=8,
            metadata={"item_id": {"shape": 5, "padding": 50}},
        )
        batches = list(batcher)
        assert len(batches) == 3  # ceil(23 / 8)
        for batch in batches:
            assert batch["item_id"].shape == (8, 5)
            assert batch["item_id_mask"].shape == (8, 5)
            assert batch["query_id"].shape == (8,)
        # padding id fills masked slots
        first = batches[0]
        assert (first["item_id"][~first["item_id_mask"]] == 50).all()
        # final batch flags its 23 % 8 = 7 real rows
        assert batches[-1]["valid"].sum() == 7
        # all 23 queries appear exactly once across valid rows
        seen = np.concatenate([b["query_id"][b["valid"]] for b in batches])
        assert sorted(seen.tolist()) == list(range(23))

    def test_replica_sharding_covers_all_rows(self, sequence_parquet):
        seen = []
        for replica in range(4):
            batcher = ParquetBatcher(
                sequence_parquet, batch_size=4,
                metadata={"item_id": {"shape": 5, "padding": 50}},
                partitioning=Partitioning(ReplicasInfo(4, replica)),
            )
            for batch in batcher:
                seen.extend(batch["query_id"][batch["valid"]].tolist())
        assert set(seen) == set(range(23))

    def test_small_slabs_exact_batches(self, sequence_parquet):
        """partition_size smaller than batch_size still yields exact batches."""
        batcher = ParquetBatcher(
            sequence_parquet, batch_size=8, partition_size=5,
            metadata={"item_id": {"shape": 5, "padding": 50}},
        )
        batches = list(batcher)
        assert all(b["item_id"].shape == (8, 5) for b in batches)
        seen = np.concatenate([b["query_id"][b["valid"]] for b in batches])
        assert sorted(seen.tolist()) == list(range(23))

    def test_shuffle_changes_order_not_content(self, sequence_parquet):
        def all_queries(shuffle, epoch=0):
            batcher = ParquetBatcher(
                sequence_parquet, batch_size=8, shuffle=shuffle, seed=3,
                metadata={"item_id": {"shape": 5, "padding": 50}},
            )
            batcher.set_epoch(epoch)
            return np.concatenate([b["query_id"][b["valid"]] for b in batcher])

        plain = all_queries(False)
        shuffled = all_queries(True)
        assert not np.array_equal(plain, shuffled)
        assert sorted(shuffled.tolist()) == sorted(plain.tolist())
        assert not np.array_equal(shuffled, all_queries(True, epoch=1))

    def test_missing_metadata_raises(self, sequence_parquet):
        with pytest.raises(ValueError, match="metadata"):
            list(ParquetBatcher(sequence_parquet, batch_size=4))

def test_gather_pad_spans_native_and_fallback():
    values = np.arange(12, dtype=np.int64)
    offsets = np.array([0, 5, 12], np.int64)
    rows = np.array([0, 1, 1], np.int64)
    starts = np.array([1, 0, 3], np.int64)
    stops = np.array([4, 7, 7], np.int64)
    from replay_tpu.native import gather_pad_spans

    out, mask = gather_pad_spans(values, offsets, rows, starts, stops, 4, -9)
    np.testing.assert_array_equal(out[0], [-9, 1, 2, 3])       # row 0 span [1:4]
    np.testing.assert_array_equal(out[1], [8, 9, 10, 11])      # [0:7] keeps LAST 4
    np.testing.assert_array_equal(out[2], [8, 9, 10, 11])      # row 1 span [3:7]
    assert mask[0].tolist() == [False, True, True, True]
    # float path round-trips exactly
    out_f, _ = gather_pad_spans(values.astype(np.float64) + 0.5, offsets, rows,
                                starts, stops, 4, -1.0)
    np.testing.assert_array_equal(out_f[0], [-1.0, 1.5, 2.5, 3.5])
    with pytest.raises(ValueError):
        gather_pad_spans(values, offsets, np.array([9]), np.array([0]), np.array([1]), 4, 0)


def _write_2d_parquet(path, rng, n_rows=37, width=3):
    import pyarrow as pa
    import pyarrow.parquet as pq

    items, feats = [], []
    for _ in range(n_rows):
        length = int(rng.integers(0, 9))
        items.append(rng.integers(0, 50, length).tolist())
        feats.append(rng.normal(size=(length, width)).tolist())
    table = pa.table({
        "query_id": np.arange(n_rows),
        "item_id": items,
        "step_features": feats,
    })
    pq.write_table(table, path)
    return items, feats


class TestArray2DColumns:
    def test_2d_column_fixed_shapes(self, tmp_path):
        rng = np.random.default_rng(0)
        path = str(tmp_path / "twod.parquet")
        items, feats = _write_2d_parquet(path, rng)
        batcher = ParquetBatcher(
            source=path,
            batch_size=8,
            metadata={
                "item_id": {"shape": 6, "padding": 0},
                "step_features": {"shape": [6, 3], "padding": 0.0},
            },
        )
        seen_rows = 0
        for batch in batcher:
            assert batch["step_features"].shape == (8, 6, 3)
            assert batch["step_features_mask"].shape == (8, 6)
            # the 2-D mask agrees with the 1-D mask of the aligned item column
            np.testing.assert_array_equal(
                batch["step_features_mask"], batch["item_id_mask"]
            )
            for row in range(8):
                if not batch["valid"][row]:
                    continue
                query = int(batch["query_id"][row])
                expected = np.asarray(feats[query], np.float64)[-6:]
                pad = 6 - len(expected)
                if len(expected):
                    np.testing.assert_allclose(
                        batch["step_features"][row, pad:], expected, rtol=1e-12
                    )
                assert (batch["step_features"][row, :pad] == 0.0).all()
                seen_rows += 1
        assert seen_rows == 37

    def test_2d_requires_2d_shape_metadata(self, tmp_path):
        rng = np.random.default_rng(1)
        path = str(tmp_path / "twod.parquet")
        _write_2d_parquet(path, rng, n_rows=5)
        batcher = ParquetBatcher(
            source=path, batch_size=4,
            metadata={"item_id": {"shape": 4}, "step_features": {"shape": 4}},
        )
        with pytest.raises(ValueError, match=r"\[L, D\]"):
            next(iter(batcher))

    def test_2d_rejects_ragged_inner_width(self, tmp_path):
        import pyarrow as pa
        import pyarrow.parquet as pq

        path = str(tmp_path / "ragged_inner.parquet")
        pq.write_table(
            pa.table({"query_id": [0, 1], "f": [[[1.0, 2.0]], [[1.0, 2.0, 3.0]]]}),
            path,
        )
        batcher = ParquetBatcher(
            source=path, batch_size=2, metadata={"f": {"shape": [2, 2]}}
        )
        with pytest.raises(ValueError, match="width"):
            next(iter(batcher))

    def test_1d_shape_accepts_singleton_list(self, tmp_path):
        rng = np.random.default_rng(2)
        path = str(tmp_path / "oned.parquet")
        _write_2d_parquet(path, rng, n_rows=9)
        batcher = ParquetBatcher(
            source=path, batch_size=4,
            metadata={"item_id": {"shape": [5]}, "step_features": {"shape": [5, 3]}},
        )
        batch = next(iter(batcher))
        assert batch["item_id"].shape == (4, 5)


def test_file_uri_source(tmp_path):
    """pyarrow.fs.FileSystem.from_uri path (ref parquet_dataset.py:133) —
    exercised with file:// (the same resolution code path as s3://)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    path = tmp_path / "uri.parquet"
    pq.write_table(pa.table({"query_id": np.arange(10), "item_id": [[1, 2]] * 10}), str(path))
    batcher = ParquetBatcher(
        source=f"file://{path}", batch_size=5, metadata={"item_id": {"shape": 3}}
    )
    batches = list(batcher)
    assert len(batches) == 2
    assert batches[0]["item_id"].shape == (5, 3)


class TestSlabEdges:
    def test_empty_parquet_yields_nothing(self, tmp_path):
        import pyarrow as pa
        import pyarrow.parquet as pq

        path = str(tmp_path / "empty.parquet")
        pq.write_table(
            pa.table({"query_id": pa.array([], pa.int64()),
                      "item_id": pa.array([], pa.list_(pa.int64()))}),
            path,
        )
        batcher = ParquetBatcher(source=path, batch_size=4,
                                 metadata={"item_id": {"shape": 3}})
        assert list(batcher) == []

    def test_total_rows_below_batch_size_pads_one_batch(self, tmp_path):
        import pyarrow as pa
        import pyarrow.parquet as pq

        path = str(tmp_path / "short.parquet")
        pq.write_table(
            pa.table({"query_id": [0, 1, 2], "item_id": [[1], [2, 3], [4]]}), path
        )
        batcher = ParquetBatcher(source=path, batch_size=8,
                                 metadata={"item_id": {"shape": 2}})
        batches = list(batcher)
        assert len(batches) == 1
        assert batches[0]["item_id"].shape == (8, 2)
        np.testing.assert_array_equal(
            batches[0]["valid"], [True] * 3 + [False] * 5
        )

    def test_short_final_slab_carries_into_padded_batch(self, tmp_path):
        """Rows spanning slab boundaries re-chunk into exact batches with ONE
        final padded batch (the reference compute_length contract)."""
        import pyarrow as pa
        import pyarrow.parquet as pq

        path = str(tmp_path / "carry.parquet")
        n = 13  # slabs of 5 -> 5+5+3; batch 4 -> 3 full + 1 padded
        pq.write_table(
            pa.table({"query_id": np.arange(n), "item_id": [[i] for i in range(n)]}),
            path,
        )
        batcher = ParquetBatcher(source=path, batch_size=4, partition_size=5,
                                 metadata={"item_id": {"shape": 1}})
        batches = list(batcher)
        assert len(batches) == 4
        assert sum(b["valid"].sum() for b in batches) == n
        seen = np.concatenate([b["query_id"][b["valid"]] for b in batches])
        np.testing.assert_array_equal(np.sort(seen), np.arange(n))
        assert all(b["item_id"].shape == (4, 1) for b in batches)
