"""Shard-aware streaming parquet: row-group sharding, cursors, epoch
determinism, the byte-budget sub-slab split and the streaming writer."""

import json
import os

import numpy as np
import pandas as pd
import pytest

from replay_tpu.data import FeatureHint, FeatureType
from replay_tpu.data.nn import (
    SequentialDataset,
    TensorFeatureInfo,
    TensorSchema,
    TransformedBatches,
)
from replay_tpu.data.nn.parquet import ParquetBatcher, StreamCursor, write_sequence_parquet
from replay_tpu.data.nn.partitioning import Partitioning, ReplicasInfo

N_ROWS = 57
GROUP_SIZE = 10  # 6 row groups for 57 rows


@pytest.fixture
def grouped_parquet(tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(0)
    path = str(tmp_path / "stream.parquet")
    table = pa.table(
        {
            "query_id": np.arange(N_ROWS),
            "item_id": [
                rng.integers(0, 50, rng.integers(1, 8)).tolist() for _ in range(N_ROWS)
            ],
        }
    )
    pq.write_table(table, path, row_group_size=GROUP_SIZE)
    return path


def make_batcher(path, **overrides):
    kwargs = dict(
        source=path,
        batch_size=8,
        shuffle=True,
        seed=3,
        shard="row_groups",
        metadata={"item_id": {"shape": 5, "padding": 50}},
    )
    kwargs.update(overrides)
    return ParquetBatcher(**kwargs)


def queries(batches):
    return np.concatenate([b["query_id"][b["valid"]] for b in batches])


class TestRowGroupSharding:
    def test_single_replica_coverage(self, grouped_parquet):
        batcher = make_batcher(grouped_parquet)
        batcher.set_epoch(1)
        batches = list(batcher)
        assert all(b["item_id"].shape == (8, 5) for b in batches)
        assert sorted(queries(batches).tolist()) == list(range(N_ROWS))

    def test_replicas_disjoint_cover_exactly_once_same_count(self, grouped_parquet):
        seen = []
        counts = []
        for replica in range(3):
            batcher = make_batcher(
                grouped_parquet,
                partitioning=Partitioning(ReplicasInfo(3, replica), shuffle=True, seed=3),
            )
            batcher.set_epoch(0)
            batches = list(batcher)
            counts.append(len(batches))
            seen.extend(queries(batches).tolist())
        # disjoint + exactly-once coverage, equal step counts on every replica
        assert sorted(seen) == list(range(N_ROWS))
        assert len(set(counts)) == 1

    def test_epoch_reshuffles_same_epoch_bit_identical(self, grouped_parquet):
        def epoch_batches(epoch):
            batcher = make_batcher(grouped_parquet)
            batcher.set_epoch(epoch)
            return list(batcher)

        first = epoch_batches(1)
        again = epoch_batches(1)
        assert len(first) == len(again)
        for a, b in zip(first, again):
            assert sorted(a) == sorted(b)
            for key in a:
                np.testing.assert_array_equal(a[key], b[key])
        other = epoch_batches(2)
        assert not np.array_equal(queries(first), queries(other))
        assert sorted(queries(other).tolist()) == list(range(N_ROWS))

    def test_group_order_shuffles_across_epochs(self, grouped_parquet):
        part = Partitioning(shuffle=True, seed=3)
        order1 = part.shard_items(6, epoch=1)
        order2 = part.shard_items(6, epoch=2)
        assert sorted(order1.tolist()) == list(range(6))
        assert not np.array_equal(order1, order2)
        # unshuffled: stable identity order
        plain = Partitioning().shard_items(6, epoch=5)
        np.testing.assert_array_equal(plain, np.arange(6))

    def test_shard_items_round_robin_disjoint(self):
        part = Partitioning(ReplicasInfo(4, 0), shuffle=True, seed=9)
        shares = [part.shard_items(10, epoch=3, replica_id=r) for r in range(4)]
        merged = np.concatenate(shares)
        assert sorted(merged.tolist()) == list(range(10))

    def test_too_few_groups_for_replicas_raises(self, tmp_path):
        import pyarrow as pa
        import pyarrow.parquet as pq

        path = str(tmp_path / "one_group.parquet")
        pq.write_table(
            pa.table({"query_id": np.arange(5), "item_id": [[1]] * 5}), path
        )
        batcher = ParquetBatcher(
            path, batch_size=2, shard="row_groups",
            metadata={"item_id": {"shape": 2}},
            partitioning=Partitioning(ReplicasInfo(4, 0)),
        )
        with pytest.raises(ValueError, match="row group"):
            list(batcher)


class TestMemoryBudgetAndReadAhead:
    def test_budget_splits_slabs_stream_unchanged(self, grouped_parquet):
        reference = make_batcher(grouped_parquet)
        reference.set_epoch(1)
        full = list(reference)
        budget = make_batcher(grouped_parquet, memory_budget_bytes=200)
        budget.set_epoch(1)
        slabs, _, _ = budget._plan(1)
        ref_slabs, _, _ = reference._plan(1)
        assert len(slabs) > len(ref_slabs)  # the budget forced sub-slabs
        assert max(s.rows for s in slabs) < max(s.rows for s in ref_slabs)
        assert sorted(queries(list(budget)).tolist()) == list(range(N_ROWS))

    def test_read_ahead_bit_identical_to_sync(self, grouped_parquet):
        sync = make_batcher(grouped_parquet, memory_budget_bytes=300)
        sync.set_epoch(2)
        ahead = make_batcher(grouped_parquet, memory_budget_bytes=300, read_ahead=3)
        ahead.set_epoch(2)
        sync_batches, ahead_batches = list(sync), list(ahead)
        assert len(sync_batches) == len(ahead_batches)
        for a, b in zip(sync_batches, ahead_batches):
            for key in a:
                np.testing.assert_array_equal(a[key], b[key])


class TestStreamCursor:
    def test_resume_bit_identical_at_every_boundary(self, grouped_parquet):
        batcher = make_batcher(grouped_parquet)
        batcher.set_epoch(1)
        full = list(batcher)
        for k in range(len(full) + 1):
            producer = make_batcher(grouped_parquet)
            producer.set_epoch(1)
            iterator = iter(producer)
            for _ in range(k):
                next(iterator)
            record = producer.cursor_for(k).to_metadata()
            json.dumps(record)  # checkpoint-sidecar (JSON) serializable
            resumed = make_batcher(grouped_parquet)
            resumed.set_epoch(1)
            resumed.restore_cursor(record)
            rest = list(resumed)
            assert len(rest) == len(full) - k
            for a, b in zip(full[k:], rest):
                for key in a:
                    np.testing.assert_array_equal(a[key], b[key])

    def test_resume_skips_consumed_slabs(self, grouped_parquet):
        """The point of the cursor: slabs before the resume point are never
        re-read (no rescan-from-start fast-forward)."""
        producer = make_batcher(grouped_parquet)
        producer.set_epoch(0)
        iterator = iter(producer)
        for _ in range(4):
            next(iterator)
        record = producer.cursor_for(4)
        assert record.slab > 0
        resumed = make_batcher(grouped_parquet)
        resumed.set_epoch(0)
        resumed.restore_cursor(record)
        reads = []
        original = type(resumed)._read_slab

        def counting_read(self, path, slab):
            reads.append((slab.group, slab.start))
            return original(self, path, slab)

        resumed._read_slab = counting_read.__get__(resumed)
        list(resumed)
        total_slabs, _, _ = producer._plan(0)
        assert 0 < len(reads) <= len(total_slabs) - record.slab + 1
        assert len(reads) < len(total_slabs)

    def test_epoch_mismatch_raises(self, grouped_parquet):
        producer = make_batcher(grouped_parquet)
        producer.set_epoch(1)
        next(iter(producer))
        cursor = producer.cursor_for(1)
        resumed = make_batcher(grouped_parquet)
        resumed.set_epoch(2)
        resumed.restore_cursor(cursor)
        with pytest.raises(ValueError, match="epoch"):
            next(iter(resumed))

    def test_resume_at_last_real_batch_still_emits_alignment_tail(
        self, grouped_parquet
    ):
        """A short replica checkpointed at its LAST real batch must rebuild
        the valid=False alignment tail from the cursor's pad_spec alone."""
        # 4 replicas over 6 row groups: the round-robin shares are uneven, so
        # at least one replica pads its tail to the global max batch count
        part, full, real = None, None, None
        for replica in range(4):
            candidate = Partitioning(ReplicasInfo(4, replica), shuffle=True, seed=3)
            producer = make_batcher(grouped_parquet, partitioning=candidate)
            producer.set_epoch(0)
            batches = list(producer)
            measured = sum(1 for b in batches if b["valid"].any())
            if measured < len(batches):
                part, full, real = candidate, batches, measured
                break
        assert part is not None, "no replica needed alignment pads"
        cursor = producer.cursor_for(real)
        assert cursor.pad_spec is not None
        resumed = make_batcher(grouped_parquet, partitioning=part)
        resumed.set_epoch(0)
        resumed.restore_cursor(cursor.to_metadata())
        tail = list(resumed)
        assert len(tail) == len(full) - real
        for a, b in zip(full[real:], tail):
            assert not b["valid"].any()
            for key in a:
                np.testing.assert_array_equal(a[key], b[key])

    def test_plan_mismatch_raises(self, grouped_parquet):
        producer = make_batcher(grouped_parquet)
        producer.set_epoch(0)
        next(iter(producer))
        record = producer.cursor_for(1).to_metadata()
        assert record["plan"]["num_replicas"] == 1
        other_layout = make_batcher(
            grouped_parquet, partitioning=Partitioning(ReplicasInfo(2, 0), shuffle=True, seed=3)
        )
        other_layout.set_epoch(0)
        with pytest.raises(ValueError, match="different epoch plan"):
            other_layout.restore_cursor(record)
        other_batch = make_batcher(grouped_parquet, batch_size=4)
        other_batch.set_epoch(0)
        with pytest.raises(ValueError, match="different epoch plan"):
            other_batch.restore_cursor(record)

    def test_rows_mode_has_no_cursor(self, grouped_parquet):
        batcher = ParquetBatcher(
            grouped_parquet, batch_size=8, metadata={"item_id": {"shape": 5}}
        )
        assert not batcher.supports_cursor
        with pytest.raises(ValueError, match="row_groups"):
            batcher.cursor_for(0)
        with pytest.raises(ValueError, match="row_groups"):
            batcher.restore_cursor(StreamCursor(0, 0, 0, 0))

    def test_carry_round_trips_through_json(self, grouped_parquet):
        """Cursors taken at slab boundaries serialize the cross-slab carry
        rows; the round trip through the JSON sidecar form is exact."""
        producer = make_batcher(grouped_parquet)
        producer.set_epoch(1)
        list(producer)
        carried = [
            cursor
            for cursor in producer._cursor_history.values()
            if cursor.carry is not None
        ]
        assert carried, "no slab-boundary cursor carried rows"
        for cursor in carried:
            rebuilt = StreamCursor.from_metadata(
                json.loads(json.dumps(cursor.to_metadata()))
            )
            assert rebuilt == cursor


def test_file_uri_source_row_groups(tmp_path):
    """shard='row_groups' resolves URI sources through the same arrow
    filesystem registry as the legacy mode (footer reads AND slab reads)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    path = tmp_path / "uri.parquet"
    pq.write_table(
        pa.table({"query_id": np.arange(20), "item_id": [[1, 2]] * 20}),
        str(path), row_group_size=5,
    )
    batcher = ParquetBatcher(
        source=f"file://{path}", batch_size=4, shard="row_groups",
        memory_budget_bytes=64,  # forces the sub-slab (iter_batches) read too
        metadata={"item_id": {"shape": 3}},
    )
    batcher.set_epoch(0)
    batches = list(batcher)
    assert sorted(queries(batches).tolist()) == list(range(20))


class TestLegacyEpochDeterminism:
    """Satellite: the legacy rows-mode batcher's set_epoch contract, incl.
    the cross-slab carry path (parquet.py _iter_rows)."""

    def test_same_epoch_bit_identical_across_slab_carry(self, grouped_parquet):
        def run(epoch):
            batcher = ParquetBatcher(
                grouped_parquet, batch_size=8, shuffle=True, seed=3,
                partition_size=GROUP_SIZE,  # slabs < batches -> carry path
                metadata={"item_id": {"shape": 5, "padding": 50}},
            )
            batcher.set_epoch(epoch)
            return list(batcher)

        first, again = run(4), run(4)
        assert len(first) == len(again)
        for a, b in zip(first, again):
            for key in a:
                np.testing.assert_array_equal(a[key], b[key])
        other = run(5)
        assert not np.array_equal(queries(first), queries(other))
        assert sorted(queries(other).tolist()) == sorted(queries(first).tolist())


class TestStreamingWriter:
    def make_dataset(self, n=23):
        schema = TensorSchema(
            TensorFeatureInfo(
                "item_id", FeatureType.CATEGORICAL, is_seq=True,
                feature_hint=FeatureHint.ITEM_ID, cardinality=50,
            )
        )
        frame = pd.DataFrame(
            {
                "query_id": np.arange(n),
                "item_id": [np.arange(i % 7 + 1) for i in range(n)],
            }
        )
        return SequentialDataset(schema, "query_id", "item_id", frame)

    def test_chunked_write_round_trips(self, tmp_path):
        import pyarrow.parquet as pq

        dataset = self.make_dataset()
        path = str(tmp_path / "chunked.parquet")
        write_sequence_parquet(path, dataset, rows_per_chunk=6)
        meta = pq.ParquetFile(path).metadata
        assert meta.num_rows == 23
        assert meta.num_row_groups == 4  # ceil(23 / 6): one group per chunk
        batches = list(
            ParquetBatcher(
                path, batch_size=8, metadata={"item_id": {"shape": 5, "padding": 50}}
            )
        )
        assert sorted(queries(batches).tolist()) == list(range(23))

    def test_chunked_write_matches_monolithic(self, tmp_path):
        dataset = self.make_dataset()
        chunked = str(tmp_path / "chunked.parquet")
        mono = str(tmp_path / "mono.parquet")
        write_sequence_parquet(chunked, dataset, rows_per_chunk=5)
        write_sequence_parquet(mono, dataset, rows_per_chunk=10_000)
        import pyarrow.parquet as pq

        a = pq.read_table(chunked).to_pydict()
        b = pq.read_table(mono).to_pydict()
        assert a == b

    def test_extra_columns_validated(self, tmp_path):
        dataset = self.make_dataset(5)
        with pytest.raises(ValueError, match="extra column"):
            write_sequence_parquet(
                str(tmp_path / "bad.parquet"), dataset, extra_columns={"w": [1, 2]}
            )
        path = str(tmp_path / "extra.parquet")
        write_sequence_parquet(
            path, dataset, extra_columns={"w": list(range(5))}, rows_per_chunk=2
        )
        batch = next(
            iter(
                ParquetBatcher(
                    path, batch_size=5, shard="row_groups",
                    metadata={"item_id": {"shape": 5, "padding": 50}},
                )
            )
        )
        assert sorted(batch["w"][batch["valid"]].tolist()) == list(range(5))

    def test_rows_per_chunk_validated(self, tmp_path):
        with pytest.raises(ValueError, match="rows_per_chunk"):
            write_sequence_parquet(
                str(tmp_path / "x.parquet"), self.make_dataset(3), rows_per_chunk=0
            )


class TestTransformedBatches:
    def test_forwards_stream_protocol(self, grouped_parquet):
        batcher = make_batcher(grouped_parquet)
        wrapped = TransformedBatches(batcher, lambda b: {**b, "extra": b["valid"]})
        assert wrapped.supports_cursor
        assert wrapped.scan_compatible
        wrapped.set_epoch(3)
        assert batcher.epoch == 3
        batches = list(wrapped)
        assert all("extra" in b for b in batches)
        cursor = wrapped.cursor_for(2)
        assert cursor.batches == 2
        resumed = TransformedBatches(
            make_batcher(grouped_parquet), lambda b: {**b, "extra": b["valid"]}
        )
        resumed.set_epoch(3)
        resumed.restore_cursor(cursor.to_metadata())
        rest = list(resumed)
        assert len(rest) == len(batches) - 2
        for a, b in zip(batches[2:], rest):
            for key in a:
                np.testing.assert_array_equal(a[key], b[key])
