"""Property-based invariants (hypothesis) for the input pipeline — the
reference's fragmented-parquet strategy (SURVEY.md §4) applied to partitioning,
the fixed-shape batcher, and the native kernels."""

import numpy as np
import pandas as pd
import pytest

hypothesis = pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st

from replay_tpu.data import FeatureHint, FeatureType
from replay_tpu.data.nn import (
    Partitioning,
    ReplicasInfo,
    SequenceBatcher,
    SequentialDataset,
    TensorFeatureInfo,
    TensorSchema,
)
from replay_tpu.native import gather_pad


@settings(max_examples=40, deadline=None)
@given(
    n=st.integers(min_value=0, max_value=200),
    num_replicas=st.integers(min_value=1, max_value=9),
    shuffle=st.booleans(),
    seed=st.integers(min_value=0, max_value=5),
)
def test_partitioning_invariants(n, num_replicas, shuffle, seed):
    shards = [
        Partitioning(ReplicasInfo(num_replicas, r), shuffle=shuffle, seed=seed).generate(n)
        for r in range(num_replicas)
    ]
    sizes = {len(s) for s in shards}
    assert len(sizes) == 1  # every replica sees the same number of rows
    union = np.concatenate(shards) if n else np.zeros(0)
    if n:
        assert set(union.tolist()) == set(range(n))  # exhaustive
        assert len(union) == -(-n // num_replicas) * num_replicas  # minimal padding
    else:
        assert len(union) == 0


@settings(max_examples=25, deadline=None)
@given(
    lengths=st.lists(st.integers(min_value=1, max_value=23), min_size=1, max_size=30),
    batch_size=st.integers(min_value=1, max_value=7),
    max_len=st.integers(min_value=2, max_value=9),
    windows=st.booleans(),
)
def test_batcher_invariants(lengths, batch_size, max_len, windows):
    schema = TensorSchema(
        TensorFeatureInfo("item_id", FeatureType.CATEGORICAL, is_seq=True,
                          feature_hint=FeatureHint.ITEM_ID, cardinality=1000)
    )
    frame = pd.DataFrame(
        {
            "query_id": np.arange(len(lengths)),
            # globally unique values so coverage is checkable
            "item_id": [
                np.arange(sum(lengths[:i]), sum(lengths[: i + 1])) for i in range(len(lengths))
            ],
        }
    )
    dataset = SequentialDataset(schema, "query_id", "item_id", frame)
    batcher = SequenceBatcher(dataset, batch_size=batch_size, max_sequence_length=max_len,
                              windows=windows)
    batches = list(batcher)
    assert len(batches) == len(batcher)
    seen_values = []
    for batch in batches:
        assert batch["item_id"].shape == (batch_size, max_len)
        assert batch["item_id_mask"].shape == (batch_size, max_len)
        valid_rows = batch["valid"]
        # masks are LEFT-padded: once True, stays True
        mask = batch["item_id_mask"][valid_rows]
        assert (np.diff(mask.astype(int), axis=1) >= 0).all()
        seen_values.append(batch["item_id"][valid_rows][mask])
    covered = set(np.concatenate(seen_values).tolist()) if seen_values else set()
    if windows:
        # window mode covers EVERY event of every sequence
        assert covered == set(range(sum(lengths)))
    else:
        # no-window mode covers exactly the last max_len events per sequence
        expected = set()
        for i, n in enumerate(lengths):
            start = sum(lengths[:i])
            expected.update(range(start + max(0, n - max_len), start + n))
        assert covered == expected


@settings(max_examples=40, deadline=None)
@given(
    row_lengths=st.lists(st.integers(min_value=0, max_value=12), min_size=1, max_size=10),
    max_len=st.integers(min_value=1, max_value=8),
    data=st.data(),
)
def test_gather_pad_matches_python_reference(row_lengths, max_len, data):
    values = np.arange(sum(row_lengths), dtype=np.int64)
    offsets = np.concatenate([[0], np.cumsum(row_lengths)]).astype(np.int64)
    indices = np.asarray(
        data.draw(
            st.lists(
                st.integers(min_value=0, max_value=len(row_lengths) - 1),
                min_size=1, max_size=8,
            )
        ),
        np.int64,
    )
    out, mask = gather_pad(values, offsets, indices, max_len, -1)
    for b, row in enumerate(indices):
        expected = values[offsets[row]: offsets[row + 1]][-max_len:]
        pad = max_len - len(expected)
        np.testing.assert_array_equal(out[b, pad:], expected)
        assert (out[b, :pad] == -1).all()
        assert mask[b].sum() == len(expected)

@settings(max_examples=40, deadline=None)
@given(
    row_lengths=st.lists(st.integers(min_value=0, max_value=9), min_size=1, max_size=8),
    max_len=st.integers(min_value=1, max_value=6),
    width=st.integers(min_value=1, max_value=4),
    floating=st.booleans(),
    data=st.data(),
)
def test_gather_pad_2d_matches_python_reference(row_lengths, max_len, width, floating, data):
    from replay_tpu.native import gather_pad_2d

    total = sum(row_lengths)
    values = np.arange(total * width, dtype=np.float64 if floating else np.int64).reshape(
        total, width
    )
    offsets = np.concatenate([[0], np.cumsum(row_lengths)]).astype(np.int64)
    indices = np.asarray(
        data.draw(
            st.lists(
                st.integers(min_value=0, max_value=len(row_lengths) - 1),
                min_size=1, max_size=6,
            )
        ),
        np.int64,
    )
    out, mask = gather_pad_2d(values, offsets, indices, max_len, width, -1)
    assert out.shape == (len(indices), max_len, width)
    assert out.dtype == values.dtype
    for b, row in enumerate(indices):
        expected = values[offsets[row]: offsets[row + 1]][-max_len:]
        pad = max_len - len(expected)
        np.testing.assert_array_equal(out[b, pad:], expected)
        assert (out[b, :pad] == -1).all()
        np.testing.assert_array_equal(mask[b], [False] * pad + [True] * len(expected))


def test_gather_pad_2d_rejects_bad_rows():
    from replay_tpu.native import gather_pad_2d

    values = np.arange(6, dtype=np.int64).reshape(3, 2)
    offsets = np.asarray([0, 1, 3], np.int64)
    with pytest.raises(ValueError):
        gather_pad_2d(values, offsets, np.asarray([5], np.int64), 4, 2, 0)


# --------------------------------------------------------------------------- #
# fragmented-parquet invariants (the reference's hypothesis strategy over
# random file sizes — tests/data/nn/parquet/test_parquet_dataset.py:12-49)
# --------------------------------------------------------------------------- #
def _write_fragments(root, file_rows, seq_width, start=0):
    """k parquet files with random row counts; globally unique scalar ids and
    fixed-width list rows derived from them (checkable coverage)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    next_id = start
    for i, n in enumerate(file_rows):
        ids = np.arange(next_id, next_id + n, dtype=np.int64)
        next_id += n
        table = pa.table(
            {
                "row_id": ids,
                "items": [
                    (np.arange(seq_width, dtype=np.int64) + rid).tolist() for rid in ids
                ],
            }
        )
        pq.write_table(table, f"{root}/part_{i}.parquet")
    return next_id - start


@settings(max_examples=20, deadline=None)
@given(
    file_rows=st.lists(st.integers(min_value=1, max_value=40), min_size=1, max_size=5),
    batch_size=st.integers(min_value=1, max_value=9),
    partition_size=st.integers(min_value=1, max_value=50),
    shuffle=st.booleans(),
)
def test_parquet_batcher_single_replica_exactness(
    file_rows, batch_size, partition_size, shuffle
):
    """Fixed shapes, ceil(n/B) batches, every written row delivered exactly once."""
    import tempfile

    from replay_tpu.data.nn import ParquetBatcher

    seq_width = 3
    with tempfile.TemporaryDirectory() as root:
        total = _write_fragments(root, file_rows, seq_width)
        batcher = ParquetBatcher(
            root, batch_size=batch_size,
            metadata={"items": {"shape": seq_width, "padding": -1}},
            partition_size=partition_size, shuffle=shuffle, seed=1,
        )
        batches = list(batcher)
        assert len(batches) == -(-total // batch_size)
        seen = []
        for batch in batches:
            assert batch["row_id"].shape == (batch_size,)
            assert batch["items"].shape == (batch_size, seq_width)
            assert batch["valid"].shape == (batch_size,)
            rows = batch["row_id"][batch["valid"]]
            np.testing.assert_array_equal(
                batch["items"][batch["valid"]],
                rows[:, None] + np.arange(seq_width)[None, :],
            )
            seen.append(rows)
        delivered = np.concatenate(seen)
        assert len(delivered) == total  # exactly once, no dupes, no drops
        assert set(delivered.tolist()) == set(range(total))


@settings(max_examples=15, deadline=None)
@given(
    file_rows=st.lists(st.integers(min_value=1, max_value=30), min_size=1, max_size=4),
    batch_size=st.integers(min_value=1, max_value=6),
    partition_size=st.integers(min_value=2, max_value=40),
    num_replicas=st.integers(min_value=2, max_value=4),
)
def test_parquet_batcher_replica_sharding_invariants(
    file_rows, batch_size, partition_size, num_replicas
):
    """Replicas emit identical batch counts (the collective-step invariant) and
    together cover every row; per-slab padding may duplicate, never drop."""
    import tempfile

    from replay_tpu.data.nn import ParquetBatcher, Partitioning, ReplicasInfo

    with tempfile.TemporaryDirectory() as root:
        total = _write_fragments(root, file_rows, seq_width=2)
        per_replica = []
        counts = []
        for r in range(num_replicas):
            batcher = ParquetBatcher(
                root, batch_size=batch_size,
                metadata={"items": {"shape": 2, "padding": -1}},
                partition_size=partition_size,
                partitioning=Partitioning(ReplicasInfo(num_replicas, r)),
            )
            batches = list(batcher)
            counts.append(len(batches))
            rows = [b["row_id"][b["valid"]] for b in batches]
            per_replica.append(np.concatenate(rows) if rows else np.zeros(0, np.int64))
            for b in batches:
                assert b["row_id"].shape == (batch_size,)
        assert len(set(counts)) == 1
        union = np.concatenate(per_replica)
        assert set(union.tolist()) == set(range(total))
        # padding duplicates at most (replicas - 1) rows per slab
        n_slabs = sum(-(-n // partition_size) for n in file_rows)
        assert len(union) - total <= (num_replicas - 1) * n_slabs


# --------------------------------------------------------------------------- #
# SequenceTokenizer -> SequenceBatcher path (VERDICT r4 weak #4): random logs
# through the full dataframe->tensor bridge
# --------------------------------------------------------------------------- #
def _random_log(seed: int, n_users: int, max_len: int):
    """String-keyed log with per-user shuffled timestamps and global row shuffle
    (exercises encoding AND the bridge's per-user timestamp sort)."""
    rng = np.random.default_rng(seed)
    rows = []
    for u in range(n_users):
        length = int(rng.integers(1, max_len + 1))
        times = rng.permutation(length)  # unsorted inside the user
        for t in times:
            rows.append((f"u{u}", f"i{rng.integers(0, 30)}", int(t)))
    frame = pd.DataFrame(rows, columns=["user_id", "item_id", "timestamp"])
    return frame.sample(frac=1.0, random_state=seed).reset_index(drop=True)


def _bridge(log):
    from replay_tpu.data import Dataset, FeatureHint, FeatureInfo, FeatureSchema
    from replay_tpu.data.nn import SequenceTokenizer, TensorFeatureSource
    from replay_tpu.data.schema import FeatureSource

    schema = FeatureSchema(
        [
            FeatureInfo("user_id", FeatureType.CATEGORICAL, FeatureHint.QUERY_ID),
            FeatureInfo("item_id", FeatureType.CATEGORICAL, FeatureHint.ITEM_ID),
            FeatureInfo("timestamp", FeatureType.NUMERICAL, FeatureHint.TIMESTAMP),
        ]
    )
    tensor_schema = TensorSchema(
        TensorFeatureInfo(
            "item_id",
            FeatureType.CATEGORICAL,
            is_seq=True,
            feature_hint=FeatureHint.ITEM_ID,
            feature_sources=[TensorFeatureSource(FeatureSource.INTERACTIONS, "item_id")],
            embedding_dim=4,
        )
    )
    tokenizer = SequenceTokenizer(tensor_schema)
    sequential = tokenizer.fit_transform(Dataset(feature_schema=schema, interactions=log))
    item_map = tokenizer.item_id_encoder.mapping["item_id"]
    user_map = tokenizer.query_id_encoder.mapping["user_id"]
    expected = {}
    for user, group in log.groupby("user_id"):
        ordered = group.sort_values("timestamp", kind="stable")["item_id"]
        expected[user_map[user]] = [item_map[i] for i in ordered]
    return sequential, expected


@settings(max_examples=25, deadline=None)
@given(
    seed=st.integers(min_value=0, max_value=10_000),
    n_users=st.integers(min_value=1, max_value=10),
    max_len=st.integers(min_value=1, max_value=14),
    batch_size=st.integers(min_value=1, max_value=5),
    seq_len=st.integers(min_value=1, max_value=12),
    shuffle=st.booleans(),
)
def test_tokenizer_batcher_last_window_roundtrip(
    seed, n_users, max_len, batch_size, seq_len, shuffle
):
    """windows=False (the predict path): each user appears exactly once across
    valid rows, left-padded with the padding id, and the unpadded row equals
    the LAST min(len, L) events of that user's time-ordered encoded history."""
    sequential, expected = _bridge(_random_log(seed, n_users, max_len))
    padding_id = sequential.schema["item_id"].padding_value
    batcher = SequenceBatcher(
        sequential, batch_size=batch_size, max_sequence_length=seq_len,
        windows=False, shuffle=shuffle, seed=seed,
    )
    seen_users = []
    for batch in batcher:
        assert batch["item_id"].shape == (batch_size, seq_len)
        assert batch["item_id_mask"].shape == (batch_size, seq_len)
        valid = batch.get("valid", np.ones(batch_size, bool))
        for b in np.flatnonzero(valid):
            mask = batch["item_id_mask"][b]
            row = batch["item_id"][b]
            assert (row[~mask] == padding_id).all()
            assert not mask[:-1][~mask[1:]].any()  # left padding: mask is a suffix
            user = int(batch["query_id"][b])
            seen_users.append(user)
            want = expected[user][-seq_len:]
            assert row[mask].tolist() == want
    assert sorted(seen_users) == sorted(expected)  # exactly once each


@settings(max_examples=25, deadline=None)
@given(
    seed=st.integers(min_value=0, max_value=10_000),
    n_users=st.integers(min_value=1, max_value=8),
    max_len=st.integers(min_value=1, max_value=14),
    seq_len=st.integers(min_value=2, max_value=10),
)
def test_tokenizer_batcher_windows_cover_history(seed, n_users, max_len, seq_len):
    """windows=True (the train path): every window is a contiguous slice of the
    user's encoded history, no window exceeds L, and the union of windows
    covers every event of every user."""
    sequential, expected = _bridge(_random_log(seed, n_users, max_len))
    batcher = SequenceBatcher(
        sequential, batch_size=3, max_sequence_length=seq_len, windows=True,
    )
    covered = {user: np.zeros(len(seq), bool) for user, seq in expected.items()}
    for batch in batcher:
        valid = batch.get("valid", np.ones(len(batch["item_id"]), bool))
        for b in np.flatnonzero(valid):
            row = batch["item_id"][b][batch["item_id_mask"][b]].tolist()
            assert 0 < len(row) <= seq_len
            user = int(batch["query_id"][b])
            history = expected[user]
            # contiguous slice: find it and mark coverage
            starts = [
                s for s in range(len(history) - len(row) + 1)
                if history[s : s + len(row)] == row
            ]
            assert starts, (row, history)
            covered[user][starts[0] : starts[0] + len(row)] = True
    for user, flags in covered.items():
        assert flags.all(), f"user {user} events not covered by any window"


@settings(max_examples=15, deadline=None)
@given(
    seed=st.integers(min_value=0, max_value=10_000),
    n_users=st.integers(min_value=2, max_value=8),
    max_len=st.integers(min_value=1, max_value=14),
    boundary=st.integers(min_value=2, max_value=8),
)
def test_tokenizer_batcher_bucketing_preserves_content(seed, n_users, max_len, boundary):
    """Length bucketing changes only the padded WIDTH: every batch is padded to
    the smallest bucket covering its rows, and the multiset of unpadded rows
    equals the unbucketed batcher's."""
    seq_len = 10
    sequential, _ = _bridge(_random_log(seed, n_users, max_len))
    plain = SequenceBatcher(sequential, batch_size=2, max_sequence_length=seq_len)
    bucketed = SequenceBatcher(
        sequential, batch_size=2, max_sequence_length=seq_len,
        bucket_boundaries=(boundary,),
    )

    def rows(batcher, widths):
        out = []
        for batch in batcher:
            widths.append(batch["item_id"].shape[1])
            valid = batch.get("valid", np.ones(len(batch["item_id"]), bool))
            longest = 0
            for b in np.flatnonzero(valid):
                row = batch["item_id"][b][batch["item_id_mask"][b]]
                longest = max(longest, len(row))
                out.append((int(batch["query_id"][b]), tuple(row.tolist())))
            assert longest <= batch["item_id"].shape[1]
        return sorted(out)

    plain_widths, bucket_widths = [], []
    assert rows(plain, plain_widths) == rows(bucketed, bucket_widths)
    assert set(plain_widths) == {seq_len}
    assert set(bucket_widths) <= {min(boundary, seq_len), seq_len}
