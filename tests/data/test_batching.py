"""Uniform batch arithmetic."""

import pytest

from replay_tpu.data import UniformBatching, uniform_batch_count


def test_counts_and_limits():
    batching = UniformBatching(total=10, batch_size=4)
    assert len(batching) == 3 == uniform_batch_count(10, 4)
    assert [batching.start(i) for i in range(3)] == [0, 4, 8]
    assert [batching.limit(i) for i in range(3)] == [4, 4, 2]
    with pytest.raises(IndexError):
        batching.limit(3)
    with pytest.raises(ValueError):
        UniformBatching(total=1, batch_size=0)
    assert uniform_batch_count(0, 4) == 0
