import numpy as np
import pandas as pd
import pytest

from replay_tpu.data import Dataset, FeatureHint, FeatureInfo, FeatureSchema, FeatureSource, FeatureType


def test_counts(dataset):
    assert dataset.query_count == 4
    assert dataset.item_count == 4
    assert dataset.is_pandas and not dataset.is_polars and not dataset.is_spark


def test_ids(dataset):
    assert list(dataset.query_ids["user_id"]) == [0, 1, 2, 3]
    assert list(dataset.item_ids["item_id"]) == [0, 1, 2, 3]


def test_unlabeled_column_warns(feature_schema, interactions_pandas):
    df = interactions_pandas.assign(extra=1.0)
    with pytest.warns(UserWarning, match="extra"):
        ds = Dataset(feature_schema=feature_schema, interactions=df)
    assert ds.feature_schema["extra"].feature_type == FeatureType.NUMERICAL
    assert ds.feature_schema["extra"].feature_source == FeatureSource.INTERACTIONS


def test_missing_ids_rejected(interactions_pandas):
    schema = FeatureSchema([FeatureInfo("item_id", FeatureType.CATEGORICAL, FeatureHint.ITEM_ID)])
    with pytest.raises(ValueError, match="Query id"):
        Dataset(feature_schema=schema, interactions=interactions_pandas)


def test_feature_frame_consistency(feature_schema, interactions_pandas):
    item_features = pd.DataFrame({"item_id": [0, 1], "price": [1.0, 2.0]})
    with pytest.raises(ValueError, match="absent"):
        Dataset(
            feature_schema=feature_schema
            + FeatureSchema([FeatureInfo("price", FeatureType.NUMERICAL, None, FeatureSource.ITEM_FEATURES)]),
            interactions=interactions_pandas,
            item_features=item_features,
        )


def test_encoded_check(feature_schema, interactions_pandas):
    bad = interactions_pandas.copy()
    bad["item_id"] = bad["item_id"].astype(float)
    with pytest.raises(ValueError, match="integer"):
        Dataset(feature_schema=feature_schema, interactions=bad, categorical_encoded=True)
    ok = Dataset(feature_schema=feature_schema, interactions=interactions_pandas, categorical_encoded=True)
    assert ok.is_categorical_encoded
    assert ok.item_count == 4


def test_save_load_roundtrip(dataset, tmp_path):
    path = str(tmp_path / "ds")
    dataset.save(path)
    loaded = Dataset.load(path)
    assert loaded.query_count == dataset.query_count
    pd.testing.assert_frame_equal(
        loaded.interactions.reset_index(drop=True), dataset.interactions.reset_index(drop=True)
    )


def test_subset(feature_schema, interactions_pandas):
    item_features = pd.DataFrame({"item_id": [0, 1, 2, 3], "price": [1.0, 2.0, 3.0, 4.0]})
    schema = feature_schema + FeatureSchema(
        [FeatureInfo("price", FeatureType.NUMERICAL, None, FeatureSource.ITEM_FEATURES)]
    )
    ds = Dataset(feature_schema=schema, interactions=interactions_pandas, item_features=item_features)
    sub = ds.subset(["rating"])
    assert "timestamp" not in sub.interactions.columns
    assert sub.item_features is None
    assert "price" not in sub.feature_schema


def test_to_pandas_noop(dataset):
    assert dataset.to_pandas() is dataset
