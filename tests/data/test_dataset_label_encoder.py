import pandas as pd
import pytest

from replay_tpu.data import (
    Dataset,
    DatasetLabelEncoder,
    FeatureHint,
    FeatureInfo,
    FeatureSchema,
    FeatureSource,
    FeatureType,
)


@pytest.fixture
def string_dataset():
    interactions = pd.DataFrame(
        {
            "user_id": ["u1", "u1", "u2", "u3"],
            "item_id": ["i2", "i1", "i2", "i3"],
            "rating": [1.0, 2.0, 3.0, 4.0],
        }
    )
    item_features = pd.DataFrame({"item_id": ["i1", "i2", "i3", "i4"], "genre": ["g1", "g2", "g1", "g3"]})
    schema = FeatureSchema(
        [
            FeatureInfo("user_id", FeatureType.CATEGORICAL, FeatureHint.QUERY_ID),
            FeatureInfo("item_id", FeatureType.CATEGORICAL, FeatureHint.ITEM_ID),
            FeatureInfo("rating", FeatureType.NUMERICAL, FeatureHint.RATING),
            FeatureInfo("genre", FeatureType.CATEGORICAL, None, FeatureSource.ITEM_FEATURES),
        ]
    )
    return Dataset(feature_schema=schema, interactions=interactions, item_features=item_features)


def test_fit_transform(string_dataset):
    encoder = DatasetLabelEncoder()
    encoded = encoder.fit_transform(string_dataset)
    assert encoded.is_categorical_encoded
    assert encoded.interactions["user_id"].tolist() == [0, 0, 1, 2]
    assert encoded.interactions["item_id"].tolist() == [0, 1, 0, 2]
    # item features frame sees ids fitted on interactions first, then extended: i4 -> 3
    assert encoded.item_features["item_id"].tolist() == [1, 0, 2, 3]
    assert encoded.item_features["genre"].tolist() == [0, 1, 0, 2]


def test_sub_encoders(string_dataset):
    encoder = DatasetLabelEncoder().fit(string_dataset)
    q = encoder.query_id_encoder
    assert q.mapping["user_id"] == {"u1": 0, "u2": 1, "u3": 2}
    i = encoder.item_id_encoder
    assert i.mapping["item_id"]["i4"] == 3
    both = encoder.query_and_item_id_encoder
    assert set(both.mapping) == {"user_id", "item_id"}


def test_get_encoder(string_dataset):
    encoder = DatasetLabelEncoder().fit(string_dataset)
    assert encoder.get_encoder(["nope"]) is None
    sub = encoder.get_encoder(["genre"])
    assert list(sub.mapping) == ["genre"]
