import pandas as pd
import pytest

from replay_tpu.data import (
    Dataset,
    DatasetLabelEncoder,
    FeatureHint,
    FeatureInfo,
    FeatureSchema,
    FeatureSource,
    FeatureType,
)


@pytest.fixture
def string_dataset():
    interactions = pd.DataFrame(
        {
            "user_id": ["u1", "u1", "u2", "u3"],
            "item_id": ["i2", "i1", "i2", "i3"],
            "rating": [1.0, 2.0, 3.0, 4.0],
        }
    )
    item_features = pd.DataFrame({"item_id": ["i1", "i2", "i3", "i4"], "genre": ["g1", "g2", "g1", "g3"]})
    schema = FeatureSchema(
        [
            FeatureInfo("user_id", FeatureType.CATEGORICAL, FeatureHint.QUERY_ID),
            FeatureInfo("item_id", FeatureType.CATEGORICAL, FeatureHint.ITEM_ID),
            FeatureInfo("rating", FeatureType.NUMERICAL, FeatureHint.RATING),
            FeatureInfo("genre", FeatureType.CATEGORICAL, None, FeatureSource.ITEM_FEATURES),
        ]
    )
    return Dataset(feature_schema=schema, interactions=interactions, item_features=item_features)


def test_fit_transform(string_dataset):
    encoder = DatasetLabelEncoder()
    encoded = encoder.fit_transform(string_dataset)
    assert encoded.is_categorical_encoded
    assert encoded.interactions["user_id"].tolist() == [0, 0, 1, 2]
    assert encoded.interactions["item_id"].tolist() == [0, 1, 0, 2]
    # item features frame sees ids fitted on interactions first, then extended: i4 -> 3
    assert encoded.item_features["item_id"].tolist() == [1, 0, 2, 3]
    assert encoded.item_features["genre"].tolist() == [0, 1, 0, 2]


def test_sub_encoders(string_dataset):
    encoder = DatasetLabelEncoder().fit(string_dataset)
    q = encoder.query_id_encoder
    assert q.mapping["user_id"] == {"u1": 0, "u2": 1, "u3": 2}
    i = encoder.item_id_encoder
    assert i.mapping["item_id"]["i4"] == 3
    both = encoder.query_and_item_id_encoder
    assert set(both.mapping) == {"user_id", "item_id"}


def test_get_encoder(string_dataset):
    encoder = DatasetLabelEncoder().fit(string_dataset)
    assert encoder.get_encoder(["nope"]) is None
    sub = encoder.get_encoder(["genre"])
    assert list(sub.mapping) == ["genre"]


def test_per_source_encoders(string_dataset):
    """Reference sub-encoder views (sequence_tokenizer.py:130-148): one encoder
    per SOURCE frame; a column in several frames appears in each view."""
    encoder = DatasetLabelEncoder().fit(string_dataset)
    inter = encoder.interactions_encoder
    assert set(inter.mapping) == {"user_id", "item_id"}
    item = encoder.item_features_encoder
    assert set(item.mapping) == {"item_id", "genre"}  # item_id rides both frames
    assert encoder.query_features_encoder is None  # no query-features frame


def test_per_source_encoders_survive_partial_fit(string_dataset):
    """A source frame first seen in partial_fit joins the per-source views."""
    interactions_only = Dataset(
        feature_schema=string_dataset.feature_schema.copy(),
        interactions=string_dataset.interactions,
    )
    encoder = DatasetLabelEncoder().fit(interactions_only)
    assert encoder.item_features_encoder is None
    encoder.partial_fit(string_dataset)  # now brings item_features
    # partial_fit extends EXISTING rules only (genre was never fitted, so no
    # rule appears for it), but item_id now registers its item-features source
    assert set(encoder.item_features_encoder.mapping) == {"item_id"}
    assert encoder.item_id_encoder.mapping["item_id"]["i4"] == 3
