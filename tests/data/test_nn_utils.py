"""groupby_sequences / ensure_pandas / create_activation parity helpers."""

import numpy as np
import pandas as pd
import pytest

from replay_tpu.data.nn import ensure_pandas, groupby_sequences


class TestGroupbySequences:
    def test_orders_within_group(self):
        log = pd.DataFrame(
            {"user": [1, 1, 2, 1], "item": [5, 6, 7, 8], "ts": [2, 1, 3, 0]}
        )
        out = groupby_sequences(log, "user", sort_col="ts")
        assert out["user"].tolist() == [1, 2]
        assert out["item"].tolist() == [[8, 6, 5], [7]]
        assert out["ts"].tolist() == [[0, 1, 2], [3]]

    def test_without_sort_keeps_frame_order(self):
        log = pd.DataFrame({"user": [2, 1, 2], "item": ["a", "b", "c"]})
        out = groupby_sequences(log, "user")
        assert out[out["user"] == 2]["item"].iloc[0] == ["a", "c"]

    def test_ndarray_columns_survive(self):
        # array-valued cells must be excluded from tie-breaker sort keys
        # (unhashable/unsortable), like every other Iterable
        log = pd.DataFrame(
            {"user": [1, 1], "emb": [np.array([1, 2]), np.array([3, 4])], "ts": [1, 0]}
        )
        out = groupby_sequences(log, "user", sort_col="ts")
        assert [a.tolist() for a in out["emb"].iloc[0]] == [[3, 4], [1, 2]]

    def test_string_columns_are_not_tiebreakers(self):
        # equal sort_col values keep frame order; string columns must not
        # reorder them (the reference excludes every Iterable from the keys)
        log = pd.DataFrame({"user": [1, 1], "name": ["b", "a"], "ts": [0, 0]})
        out = groupby_sequences(log, "user", sort_col="ts")
        assert out["name"].iloc[0] == ["b", "a"]

    def test_list_columns_survive(self):
        log = pd.DataFrame(
            {"user": [1, 1], "tags": [["x"], ["y", "z"]], "ts": [1, 0]}
        )
        out = groupby_sequences(log, "user", sort_col="ts")
        assert out["tags"].iloc[0] == [["y", "z"], ["x"]]


class TestEnsurePandas:
    def test_pandas_passthrough(self):
        df = pd.DataFrame({"a": [1]})
        assert ensure_pandas(df) is df

    def test_unknown_type_rejected(self):
        with pytest.raises(TypeError, match="Unsupported dataframe"):
            ensure_pandas([1, 2, 3])


class TestCreateActivation:
    def test_known_names(self):
        import jax.numpy as jnp

        from replay_tpu.nn import create_activation

        x = jnp.asarray([-1.0, 0.0, 1.0])
        assert np.asarray(create_activation("relu")(x)).tolist() == [0.0, 0.0, 1.0]
        assert float(create_activation("sigmoid")(x)[1]) == pytest.approx(0.5)
        assert callable(create_activation("gelu")) and callable(create_activation("silu"))

    def test_unknown_rejected(self):
        from replay_tpu.nn import create_activation

        with pytest.raises(ValueError, match="activation"):
            create_activation("tanh")
