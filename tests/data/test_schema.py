import pytest

from replay_tpu.data import FeatureHint, FeatureInfo, FeatureSchema, FeatureSource, FeatureType


def make_schema():
    return FeatureSchema(
        [
            FeatureInfo("user_id", FeatureType.CATEGORICAL, FeatureHint.QUERY_ID),
            FeatureInfo("item_id", FeatureType.CATEGORICAL, FeatureHint.ITEM_ID),
            FeatureInfo("rating", FeatureType.NUMERICAL, FeatureHint.RATING, FeatureSource.INTERACTIONS),
            FeatureInfo("timestamp", FeatureType.NUMERICAL, FeatureHint.TIMESTAMP, FeatureSource.INTERACTIONS),
            FeatureInfo("genres", FeatureType.CATEGORICAL_LIST, None, FeatureSource.ITEM_FEATURES),
            FeatureInfo("age", FeatureType.NUMERICAL, None, FeatureSource.QUERY_FEATURES),
        ]
    )


def test_id_columns():
    schema = make_schema()
    assert schema.query_id_column == "user_id"
    assert schema.item_id_column == "item_id"
    assert schema.interactions_rating_column == "rating"
    assert schema.interactions_timestamp_column == "timestamp"


def test_filter_and_drop():
    schema = make_schema()
    cats = schema.categorical_features
    assert set(cats.columns) == {"user_id", "item_id", "genres"}
    nums = schema.numerical_features
    assert set(nums.columns) == {"rating", "timestamp", "age"}
    dropped = schema.drop(feature_hint=FeatureHint.QUERY_ID)
    assert "user_id" not in dropped
    only_item_features = schema.item_features
    assert only_item_features.columns == ["genres"]


def test_interaction_features_excludes_ids():
    schema = make_schema()
    inter = schema.interaction_features
    assert set(inter.columns) == {"rating", "timestamp"}


def test_subset_and_item():
    schema = make_schema()
    sub = schema.subset(["rating", "nonexistent"])
    assert sub.columns == ["rating"]
    assert sub.item().column == "rating"
    with pytest.raises(ValueError):
        schema.item()


def test_add_and_len():
    schema = make_schema()
    extra = FeatureSchema([FeatureInfo("price", FeatureType.NUMERICAL)])
    combined = schema + extra
    assert len(combined) == len(schema) + 1


def test_duplicate_columns_rejected():
    with pytest.raises(ValueError, match="Duplicate"):
        FeatureSchema(
            [
                FeatureInfo("x", FeatureType.NUMERICAL),
                FeatureInfo("x", FeatureType.NUMERICAL),
            ]
        )


def test_two_item_ids_rejected():
    with pytest.raises(ValueError, match="ITEM_ID"):
        FeatureSchema(
            [
                FeatureInfo("a", FeatureType.CATEGORICAL, FeatureHint.ITEM_ID),
                FeatureInfo("b", FeatureType.CATEGORICAL, FeatureHint.ITEM_ID),
            ]
        )


def test_cardinality_rules():
    info = FeatureInfo("x", FeatureType.CATEGORICAL, cardinality=5)
    assert info.cardinality == 5
    with pytest.raises(ValueError):
        FeatureInfo("y", FeatureType.NUMERICAL, cardinality=5)
    num = FeatureInfo("z", FeatureType.NUMERICAL)
    with pytest.raises(RuntimeError):
        _ = num.cardinality


def test_lazy_cardinality_callback():
    info = FeatureInfo("x", FeatureType.CATEGORICAL)
    info._set_cardinality_callback(lambda col: 42)
    assert info.cardinality == 42
    info.reset_cardinality()
    assert info.cardinality == 42


def test_copy_resets_cardinality():
    schema = FeatureSchema([FeatureInfo("x", FeatureType.CATEGORICAL, cardinality=7)])
    copied = schema.copy()
    assert copied["x"]._cardinality is None


def test_spark_schema_gated():
    import pytest as _pytest

    from replay_tpu.data.spark_schema import get_schema
    from replay_tpu.utils.types import PYSPARK_AVAILABLE

    if PYSPARK_AVAILABLE:  # pragma: no cover - pyspark absent in this image
        assert get_schema() is not None
    else:
        with _pytest.raises(ImportError, match="input adapter"):
            get_schema()
