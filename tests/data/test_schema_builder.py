"""TensorSchemaBuilder fluent construction."""

import pytest

from replay_tpu.data.nn import TensorFeatureSource, TensorSchemaBuilder
from replay_tpu.data.schema import FeatureHint, FeatureSource, FeatureType


class TestTensorSchemaBuilder:
    def test_builds_all_feature_kinds(self):
        schema = (
            TensorSchemaBuilder()
            .categorical(
                "item_id",
                cardinality=100,
                is_seq=True,
                feature_source=TensorFeatureSource(FeatureSource.INTERACTIONS, "item_id"),
                feature_hint=FeatureHint.ITEM_ID,
                embedding_dim=32,
            )
            .categorical_list("genres", cardinality=20, is_seq=True)
            .numerical("age", tensor_dim=1)
            .numerical_list("ctx", tensor_dim=4, is_seq=True)
            .build()
        )
        assert [f.name for f in schema.all_features] == ["item_id", "genres", "age", "ctx"]
        item = schema["item_id"]
        assert item.feature_type == FeatureType.CATEGORICAL
        assert item.cardinality == 100
        assert item.embedding_dim == 32
        assert item.feature_hint == FeatureHint.ITEM_ID
        assert schema["genres"].feature_type == FeatureType.CATEGORICAL_LIST
        assert schema["age"].feature_type == FeatureType.NUMERICAL
        assert schema["age"].tensor_dim == 1
        assert schema["ctx"].feature_type == FeatureType.NUMERICAL_LIST

    def test_same_name_overwrites(self):
        schema = (
            TensorSchemaBuilder()
            .categorical("x", cardinality=5)
            .categorical("x", cardinality=9)
            .build()
        )
        assert len(schema.all_features) == 1
        assert schema["x"].cardinality == 9

    def test_chaining_returns_builder(self):
        builder = TensorSchemaBuilder()
        assert builder.categorical("a", cardinality=2) is builder
