"""Experimental tier: MultVAE, NeuroMF, NeuralTS, DT4Rec, TiSASRec."""

import numpy as np
import pandas as pd
import pytest

from replay_tpu.data import Dataset, FeatureHint, FeatureInfo, FeatureSchema, FeatureType
from replay_tpu.data.schema import FeatureSource
from replay_tpu.experimental import DT4Rec, MultVAE, NeuralTS, NeuroMF

pytestmark = pytest.mark.jax


def block_log(num_users=16, group_size=8):
    rng = np.random.default_rng(0)
    rows = []
    for user in range(num_users):
        liked = np.arange(group_size) + (user % 2) * group_size
        for t, item in enumerate(rng.choice(liked, 5, replace=False)):
            rows.append((user, int(item), 1.0, t))
    return pd.DataFrame(rows, columns=["query_id", "item_id", "rating", "timestamp"])


def make_dataset(log, query_features=None):
    schema = [
        FeatureInfo("query_id", FeatureType.CATEGORICAL, FeatureHint.QUERY_ID),
        FeatureInfo("item_id", FeatureType.CATEGORICAL, FeatureHint.ITEM_ID),
        FeatureInfo("rating", FeatureType.NUMERICAL, FeatureHint.RATING),
        FeatureInfo("timestamp", FeatureType.NUMERICAL, FeatureHint.TIMESTAMP),
    ]
    if query_features is not None:
        schema += [
            FeatureInfo(c, FeatureType.NUMERICAL, feature_source=FeatureSource.QUERY_FEATURES)
            for c in query_features.columns if c != "query_id"
        ]
    return Dataset(feature_schema=FeatureSchema(schema), interactions=log,
                   query_features=query_features)


def in_group_rate(recs):
    return np.mean(
        [(row["query_id"] % 2) * 8 <= row["item_id"] < (row["query_id"] % 2 + 1) * 8
         for _, row in recs.iterrows()]
    )


def test_mult_vae_learns_groups(tmp_path):
    dataset = make_dataset(block_log())
    model = MultVAE(latent_dim=8, hidden_dims=(32,), epochs=60, batch_size=16, seed=0)
    recs = model.fit_predict(dataset, k=2)
    assert in_group_rate(recs) > 0.8
    model.save(str(tmp_path / "vae"))
    restored = MultVAE.load(str(tmp_path / "vae"))
    pd.testing.assert_frame_equal(
        recs.reset_index(drop=True), restored.predict(dataset, k=2).reset_index(drop=True)
    )


def test_neuro_mf_learns_groups():
    dataset = make_dataset(block_log())
    model = NeuroMF(epochs=150, learning_rate=5e-3, seed=0)
    recs = model.fit_predict(dataset, k=2)
    assert in_group_rate(recs) > 0.7


def test_neural_ts():
    log = block_log()
    query_features = pd.DataFrame(
        {"query_id": np.arange(16), "bias": 1.0,
         "taste": np.where(np.arange(16) % 2 == 0, -1.0, 1.0)}
    )
    dataset = make_dataset(log, query_features)
    model = NeuralTS(noise_scale=0.05, seed=0)
    recs = model.fit_predict(dataset, k=3, filter_seen_items=False)
    assert in_group_rate(recs) > 0.7
    # nonlinear random-feature lift also runs
    lifted = NeuralTS(hidden_dim=16, noise_scale=0.05, seed=0).fit(dataset)
    assert lifted.theta.shape[1] == 16


def test_dt4rec_trains_and_infers():
    import jax
    import jax.numpy as jnp

    from replay_tpu.data.nn import TensorFeatureInfo, TensorSchema
    from replay_tpu.nn import OptimizerFactory, Trainer
    from replay_tpu.nn.loss import CE

    NUM_ITEMS, L, B = 10, 6, 8
    schema = TensorSchema(
        TensorFeatureInfo("item_id", FeatureType.CATEGORICAL, is_seq=True,
                          feature_hint=FeatureHint.ITEM_ID, cardinality=NUM_ITEMS,
                          embedding_dim=16)
    )
    model = DT4Rec(schema=schema, embedding_dim=16, num_blocks=1,
                   max_sequence_length=L)
    trainer = Trainer(model=model, loss=CE(),
                      optimizer=OptimizerFactory(learning_rate=1e-2))
    rng = np.random.default_rng(0)

    def batch():
        items = np.zeros((B, L), np.int32)
        for b in range(B):
            start = rng.integers(0, NUM_ITEMS)
            items[b] = (start + np.arange(L)) % NUM_ITEMS
        return {
            "feature_tensors": {"item_id": items},
            "padding_mask": np.ones((B, L), bool),
            "returns_to_go": np.ones((B, L), np.float32),
            # rtg token t predicts item t: labels are the items themselves
            "positive_labels": items[:, :, None],
            "target_padding_mask": np.ones((B, L, 1), bool),
        }

    state, losses = None, []
    for _ in range(30):
        b = batch()
        if state is None:
            state = trainer.init_state(b)
        state, loss_value = trainer.train_step(state, b)
        losses.append(float(loss_value))
    assert np.mean(losses[-5:]) < np.mean(losses[:5]) * 0.7

    logits = trainer.predict_logits(
        state,
        {
            "feature_tensors": {"item_id": np.tile(np.arange(L, dtype=np.int32), (B, 1))},
            "padding_mask": np.ones((B, L), bool),
        },
    )
    assert logits.shape == (B, NUM_ITEMS)
    assert np.isfinite(np.asarray(logits)).all()


def test_tisasrec_uses_time_intervals():
    import jax
    import jax.numpy as jnp

    from replay_tpu.data.nn import TensorFeatureInfo, TensorSchema, TensorFeatureSource
    from replay_tpu.nn.sequential.sasrec.ti_model import TiSasRec

    NUM_ITEMS, L, B = 10, 6, 4
    schema = TensorSchema(
        [
            TensorFeatureInfo("item_id", FeatureType.CATEGORICAL, is_seq=True,
                              feature_hint=FeatureHint.ITEM_ID, cardinality=NUM_ITEMS,
                              embedding_dim=16),
            TensorFeatureInfo("timestamp", FeatureType.NUMERICAL, is_seq=True,
                              tensor_dim=1, embedding_dim=16),
        ]
    )
    model = TiSasRec(schema=schema, embedding_dim=16, num_blocks=1,
                     max_sequence_length=L, time_span=16)
    rng = np.random.default_rng(0)
    items = rng.integers(0, NUM_ITEMS, (B, L)).astype(np.int32)
    mask = np.ones((B, L), bool)
    ts1 = np.cumsum(rng.integers(1, 5, (B, L)), axis=1).astype(np.float32)
    params = model.init(jax.random.PRNGKey(0),
                        {"item_id": items, "timestamp": ts1}, mask)["params"]
    out1 = model.apply({"params": params}, {"item_id": items, "timestamp": ts1}, mask)
    # different intervals must change the output (the bias table is consulted)
    ts2 = np.cumsum(rng.integers(50, 99, (B, L)), axis=1).astype(np.float32)
    out2 = model.apply({"params": params}, {"item_id": items, "timestamp": ts2}, mask)
    assert out1.shape == (B, L, 16)
    assert not np.allclose(np.asarray(out1), np.asarray(out2))
    # inference path
    logits = model.apply({"params": params}, {"item_id": items, "timestamp": ts1}, mask,
                         method=TiSasRec.forward_inference)
    assert logits.shape == (B, NUM_ITEMS)

def test_tisasrec_trains_through_trainer():
    import jax
    from replay_tpu.data.nn import TensorFeatureInfo, TensorSchema
    from replay_tpu.nn import OptimizerFactory, Trainer
    from replay_tpu.nn.loss import CE
    from replay_tpu.nn.sequential.sasrec.ti_model import TiSasRec

    NUM_ITEMS, L, B = 10, 6, 8
    schema = TensorSchema(
        [
            TensorFeatureInfo("item_id", FeatureType.CATEGORICAL, is_seq=True,
                              feature_hint=FeatureHint.ITEM_ID, cardinality=NUM_ITEMS,
                              embedding_dim=16),
            TensorFeatureInfo("timestamp", FeatureType.NUMERICAL, is_seq=True,
                              tensor_dim=1, embedding_dim=16),
        ]
    )
    model = TiSasRec(schema=schema, embedding_dim=16, num_blocks=1,
                     max_sequence_length=L, time_span=16)
    trainer = Trainer(model=model, loss=CE(), optimizer=OptimizerFactory(learning_rate=2e-2))
    rng = np.random.default_rng(0)

    def batch():
        items = ((rng.integers(0, NUM_ITEMS, (B, 1)) + np.arange(L + 1)) % NUM_ITEMS).astype(np.int32)
        ts = np.cumsum(rng.integers(1, 9, (B, L)), axis=1).astype(np.float32)
        mask = np.ones((B, L), bool)
        return {
            "feature_tensors": {"item_id": items[:, :-1], "timestamp": ts},
            "padding_mask": mask,
            "positive_labels": items[:, 1:, None],
            "target_padding_mask": mask[:, :, None],
        }

    state, losses = None, []
    for _ in range(25):
        b = batch()
        if state is None:
            state = trainer.init_state(b)
        state, loss_value = trainer.train_step(state, b)
        losses.append(float(loss_value))
    assert np.mean(losses[-5:]) < np.mean(losses[:5]) * 0.7
    logits = trainer.predict_logits(state, {k: batch()[k] for k in
                                            ("feature_tensors", "padding_mask")})
    assert logits.shape == (B, NUM_ITEMS)
