"""Experimental tier round 3: CQL, DDPG, ADMM SLIM, ULinUCB, Hierarchical."""

import numpy as np
import pandas as pd
import pytest

from replay_tpu.data import Dataset, FeatureHint, FeatureInfo, FeatureSchema, FeatureType
from replay_tpu.data.schema import FeatureSource
from replay_tpu.experimental import (
    ADMMSLIM,
    CQL,
    DDPG,
    HierarchicalRecommender,
    MdpDatasetBuilder,
    ULinUCB,
)

pytestmark = pytest.mark.jax


def block_log(num_users=20, group=10, per_user=7, seed=0):
    rng = np.random.default_rng(seed)
    rows = []
    for user in range(num_users):
        liked = np.arange(group) + (user % 2) * group
        for t, item in enumerate(rng.choice(liked, per_user, replace=False)):
            rows.append((user, int(item), float(1 + rng.integers(0, 5)), t))
    return pd.DataFrame(rows, columns=["query_id", "item_id", "rating", "timestamp"])


def base_schema():
    return [
        FeatureInfo("query_id", FeatureType.CATEGORICAL, FeatureHint.QUERY_ID),
        FeatureInfo("item_id", FeatureType.CATEGORICAL, FeatureHint.ITEM_ID),
        FeatureInfo("rating", FeatureType.NUMERICAL, FeatureHint.RATING),
        FeatureInfo("timestamp", FeatureType.NUMERICAL, FeatureHint.TIMESTAMP),
    ]


def make_dataset(log, item_features=None):
    schema = base_schema()
    if item_features is not None:
        schema += [
            FeatureInfo(c, FeatureType.NUMERICAL, feature_source=FeatureSource.ITEM_FEATURES)
            for c in item_features.columns
            if c != "item_id"
        ]
    return Dataset(
        feature_schema=FeatureSchema(schema), interactions=log, item_features=item_features
    )


def grouped_item_features(n_items=20):
    return pd.DataFrame(
        {
            "item_id": np.arange(n_items),
            "f0": np.where(np.arange(n_items) < n_items // 2, 1.0, -1.0),
            "f1": (np.arange(n_items) % (n_items // 2)) / float(n_items // 2),
        }
    )


def in_group_rate(recs, group=10):
    return np.mean(
        [
            (row.query_id % 2) * group <= row.item_id < (row.query_id % 2 + 1) * group
            for row in recs.itertuples()
        ]
    )


# --------------------------------------------------------------------------- #
# MDP builder
# --------------------------------------------------------------------------- #
def test_mdp_builder_semantics():
    log = block_log()
    mdp = MdpDatasetBuilder(top_k=2).build(
        log.rename(columns={"query_id": "q", "item_id": "i"}),
        "q", "i", "rating", "timestamp", seed=0,
    )
    n_users = log["query_id"].nunique()
    assert mdp["observations"].shape == (len(log), 2)
    assert mdp["actions"].shape == (len(log), 1)
    # one terminal per user, at their latest interaction
    assert mdp["terminals"].sum() == n_users
    frame = pd.DataFrame(
        {
            "q": mdp["observations"][:, 0],
            "r": mdp["rewards"],
            "t": mdp["terminals"],
        }
    )
    assert (frame.groupby("q")["r"].sum() == 2).all()  # exactly top_k rewarded
    assert (frame.groupby("q")["t"].apply(lambda s: s.to_numpy()[-1]) == 1).all()
    with pytest.raises(ValueError, match="positive"):
        MdpDatasetBuilder(top_k=1, action_randomization_scale=0.0)


# --------------------------------------------------------------------------- #
# CQL
# --------------------------------------------------------------------------- #
def test_cql_trains_and_roundtrips(tmp_path):
    dataset = make_dataset(block_log())
    model = CQL(top_k=2, n_steps=400, batch_size=32, hidden_dims=(32, 32), seed=0)
    recs = model.fit_predict(dataset, k=3)
    assert set(recs.columns) == {"query_id", "item_id", "rating"}
    assert recs.groupby("query_id").size().eq(3).all()
    # the DEFINING CQL behavior: the conservative gap (logsumexp over sampled
    # actions minus Q on data actions) is pushed down over training
    gap = model.loss_history[:, 3]
    assert gap[-100:].mean() < gap[:100].mean()
    assert np.isfinite(model.loss_history).all()
    # seen items are filtered
    seen = set(map(tuple, dataset.interactions[["query_id", "item_id"]].to_numpy()))
    assert not (set(map(tuple, recs[["query_id", "item_id"]].to_numpy())) & seen)
    model.save(str(tmp_path / "cql"))
    restored = CQL.load(str(tmp_path / "cql"))
    pd.testing.assert_frame_equal(
        recs.reset_index(drop=True), restored.predict(dataset, k=3).reset_index(drop=True)
    )


def test_cql_scores_cold_queries():
    dataset = make_dataset(block_log())
    model = CQL(top_k=2, n_steps=50, batch_size=16, hidden_dims=(16,), seed=0)
    model.fit(dataset)
    recs = model.predict(dataset, k=2, queries=[999], filter_seen_items=False)
    assert len(recs) == 2  # the policy generalizes over the observation space


# --------------------------------------------------------------------------- #
# DDPG
# --------------------------------------------------------------------------- #
def test_ddpg_trains_and_roundtrips(tmp_path):
    dataset = make_dataset(block_log(num_users=16, group=8, per_user=6))
    model = DDPG(epochs=3, batch_size=64, user_batch_size=8, trajectory_len=6, seed=0)
    recs = model.fit_predict(dataset, k=3)
    assert recs.groupby("query_id").size().eq(3).all()
    assert len(model.loss_history) > 0  # updates actually ran
    assert np.isfinite(model.loss_history).all()
    # memory tracks rewarded (related) items per user
    assert model.memory.shape == (16, model.memory_size)
    model.save(str(tmp_path / "ddpg"))
    restored = DDPG.load(str(tmp_path / "ddpg"))
    pd.testing.assert_frame_equal(
        recs.reset_index(drop=True), restored.predict(dataset, k=3).reset_index(drop=True)
    )


def test_ddpg_rejects_bad_noise():
    with pytest.raises(ValueError, match="noise_type"):
        DDPG(noise_type="brown")


def test_ddpg_ou_noise_runs():
    dataset = make_dataset(block_log(num_users=8, group=6, per_user=4))
    model = DDPG(
        noise_type="ou", epochs=1, batch_size=16, user_batch_size=4,
        trajectory_len=4, seed=0,
    )
    recs = model.fit_predict(dataset, k=2)
    assert recs.groupby("query_id").size().eq(2).all()


# --------------------------------------------------------------------------- #
# ADMM SLIM
# --------------------------------------------------------------------------- #
def test_admm_slim_learns_groups(tmp_path):
    dataset = make_dataset(block_log())
    model = ADMMSLIM(lambda_1=0.5, lambda_2=50.0, seed=0)
    recs = model.fit_predict(dataset, k=3)
    assert in_group_rate(recs) > 0.9
    assert 0 < model.num_fit_iterations <= model.max_iteration
    # zero diagonal: an item must not recommend itself through self-similarity
    assert np.abs(np.diag(model.similarity)).max() < 1e-4
    model.save(str(tmp_path / "admm"))
    restored = ADMMSLIM.load(str(tmp_path / "admm"))
    pd.testing.assert_frame_equal(
        recs.reset_index(drop=True), restored.predict(dataset, k=3).reset_index(drop=True)
    )


def test_admm_slim_validates_params():
    with pytest.raises(ValueError, match="regularization"):
        ADMMSLIM(lambda_1=-1.0)
    with pytest.raises(ValueError, match="regularization"):
        ADMMSLIM(lambda_2=0.0)


# --------------------------------------------------------------------------- #
# ULinUCB
# --------------------------------------------------------------------------- #
def test_u_lin_ucb_fit_predict(tmp_path):
    log = block_log()
    dataset = make_dataset(log, grouped_item_features())
    model = ULinUCB(alpha=-2.0)
    recs = model.fit_predict(dataset, k=3)
    assert model.ucb.shape == (20, 20)
    assert recs.groupby("query_id").size().eq(3).all()
    model.save(str(tmp_path / "ulinucb"))
    restored = ULinUCB.load(str(tmp_path / "ulinucb"))
    pd.testing.assert_frame_equal(
        recs.reset_index(drop=True), restored.predict(dataset, k=3).reset_index(drop=True)
    )


def test_u_lin_ucb_matches_sequential_reference():
    """The lax.scan sweep equals a straight numpy transcription of the math."""
    log = block_log(num_users=6, group=4, per_user=3)
    feats = grouped_item_features(8)
    dataset = make_dataset(log, feats)
    model = ULinUCB(alpha=0.5).fit(dataset)

    # the model's item universe is fit_items (items present in the log)
    i_index = pd.Index(model.fit_items)
    F = feats.set_index("item_id").loc[i_index][["f0", "f1"]].to_numpy(float)
    A = np.eye(2)
    b = np.zeros(2)
    expected = np.zeros((len(model.fit_queries), len(i_index)))
    for row, user in enumerate(model.fit_queries):
        sub = log[log.query_id == user]
        fu = F[i_index.get_indexer(sub.item_id)]
        A = A + fu.T @ fu
        b = b + fu.T @ sub.rating.to_numpy(float)
        theta = np.linalg.solve(A, b)
        spread = np.sqrt(np.sum(F.T * np.linalg.solve(A, F.T), axis=0))
        expected[row] = F @ theta + 0.5 * spread
    np.testing.assert_allclose(model.ucb, expected, rtol=1e-4, atol=1e-5)


def test_u_lin_ucb_needs_item_features():
    dataset = make_dataset(block_log())
    with pytest.raises(ValueError, match="item_features"):
        ULinUCB().fit(dataset)


# --------------------------------------------------------------------------- #
# HierarchicalRecommender
# --------------------------------------------------------------------------- #
def test_hierarchical_routes_through_tree():
    dataset = make_dataset(block_log(), grouped_item_features())
    model = HierarchicalRecommender(depth=2, num_clusters=2)
    recs = model.fit_predict(dataset, k=3)
    assert recs.groupby("query_id").size().le(3).all()
    assert len(recs) > 0
    # tree structure: root has one child per cluster, children are leaves
    assert model.root.children is not None
    assert all(child.is_leaf for child in model.root.children)


def test_hierarchical_depth_one_is_flat():
    dataset = make_dataset(block_log(), grouped_item_features())
    model = HierarchicalRecommender(depth=1)
    recs = model.fit_predict(dataset, k=2)
    assert model.root.is_leaf
    assert recs.groupby("query_id").size().le(2).all()


def test_hierarchical_custom_cluster_model():
    from sklearn.cluster import AgglomerativeClustering

    dataset = make_dataset(block_log(), grouped_item_features())
    model = HierarchicalRecommender(
        depth=2, cluster_model=AgglomerativeClustering(n_clusters=2)
    )
    recs = model.fit_predict(dataset, k=2)
    assert len(recs) > 0

    with pytest.raises(ValueError, match="depth"):
        HierarchicalRecommender(depth=0)

    with pytest.raises(ValueError, match="item_features"):
        HierarchicalRecommender(depth=1).fit(make_dataset(block_log()))


def test_cql_respects_custom_column_names():
    """Regression: rating/timestamp columns under non-default names."""
    log = block_log().rename(columns={"rating": "relevance", "timestamp": "ts"})
    schema = FeatureSchema(
        [
            FeatureInfo("query_id", FeatureType.CATEGORICAL, FeatureHint.QUERY_ID),
            FeatureInfo("item_id", FeatureType.CATEGORICAL, FeatureHint.ITEM_ID),
            FeatureInfo("relevance", FeatureType.NUMERICAL, FeatureHint.RATING),
            FeatureInfo("ts", FeatureType.NUMERICAL, FeatureHint.TIMESTAMP),
        ]
    )
    dataset = Dataset(feature_schema=schema, interactions=log)
    model = CQL(top_k=2, n_steps=30, batch_size=16, hidden_dims=(8,), seed=0)
    recs = model.fit_predict(dataset, k=2)
    assert recs.groupby("query_id").size().eq(2).all()


def test_mdp_builder_tied_timestamps_keep_terminal_last():
    """Regression: ties at a user's max timestamp must not leave the terminal
    mid-episode (which chains rows into the next user's Bellman targets)."""
    log = pd.DataFrame(
        {
            "query_id": [0, 0, 0, 1, 1],
            "item_id": [0, 1, 2, 3, 4],
            "rating": [1.0, 2.0, 3.0, 1.0, 2.0],
            "timestamp": [0, 5, 5, 0, 1],
        }
    )
    mdp = MdpDatasetBuilder(top_k=1).build(
        log, "query_id", "item_id", "rating", "timestamp", seed=0
    )
    terminals = mdp["terminals"]
    users = mdp["observations"][:, 0]
    # the terminal of each user is on their LAST row in episode order
    for user in (0, 1):
        rows = np.where(users == user)[0]
        assert terminals[rows[-1]] == 1
        assert terminals[rows[:-1]].sum() == 0


def test_u_lin_ucb_unknown_queries_score_zero():
    """Regression: unseen users keep a zero UCB row (reference semantics) so
    tree routing never silently drops them."""
    dataset = make_dataset(block_log(), grouped_item_features())
    model = ULinUCB(alpha=-2.0).fit(dataset)
    recs = model.predict(dataset, k=2, queries=[777], filter_seen_items=False)
    assert len(recs) == 2
    assert (recs["rating"] == 0).all()
