"""Cross-check the on-device MetricsBuilder against the dataframe metric battery."""

import numpy as np
import pandas as pd
import pytest

from replay_tpu.metrics import MAP, MRR, NDCG, HitRate, MetricsBuilder, Novelty, Precision, Recall, metrics_to_df


@pytest.fixture
def batch(rng):
    n_users, n_items, k, gt_max, train_max = 32, 100, 10, 7, 12
    preds = np.stack([rng.choice(n_items, size=k, replace=False) for _ in range(n_users)])
    gt = np.full((n_users, gt_max), -1, dtype=np.int64)
    train = np.full((n_users, train_max), -2, dtype=np.int64)
    for u in range(n_users):
        n_gt = rng.integers(1, gt_max + 1)
        gt[u, :n_gt] = rng.choice(n_items, size=n_gt, replace=False)
        n_tr = rng.integers(1, train_max + 1)
        train[u, :n_tr] = rng.choice(n_items, size=n_tr, replace=False)
    return preds, gt, train


def _frames(preds, gt, train):
    rows = [
        {"query_id": u, "item_id": int(item), "rating": float(preds.shape[1] - i)}
        for u in range(preds.shape[0])
        for i, item in enumerate(preds[u])
    ]
    recs = pd.DataFrame(rows)
    gt_df = pd.DataFrame(
        [{"query_id": u, "item_id": int(i)} for u in range(gt.shape[0]) for i in gt[u] if i >= 0]
    )
    train_df = pd.DataFrame(
        [{"query_id": u, "item_id": int(i)} for u in range(train.shape[0]) for i in train[u] if i >= 0]
    )
    return recs, gt_df, train_df


def test_builder_matches_dataframe_metrics(batch):
    preds, gt, train = batch
    recs, gt_df, train_df = _frames(preds, gt, train)
    ks = [1, 5, 10]

    builder = MetricsBuilder(
        metrics=["recall", "precision", "ndcg", "map", "mrr", "hitrate", "novelty", "coverage"],
        top_k=ks,
        item_count=100,
    )
    builder.add_prediction(preds, gt, train)
    device_metrics = builder.get_metrics()

    for k in ks:
        assert device_metrics[f"recall@{k}"] == pytest.approx(Recall(k)(recs, gt_df)[f"Recall@{k}"], abs=1e-5)
        assert device_metrics[f"precision@{k}"] == pytest.approx(
            Precision(k)(recs, gt_df)[f"Precision@{k}"], abs=1e-5
        )
        assert device_metrics[f"ndcg@{k}"] == pytest.approx(NDCG(k)(recs, gt_df)[f"NDCG@{k}"], abs=1e-5)
        assert device_metrics[f"map@{k}"] == pytest.approx(MAP(k)(recs, gt_df)[f"MAP@{k}"], abs=1e-5)
        assert device_metrics[f"mrr@{k}"] == pytest.approx(MRR(k)(recs, gt_df)[f"MRR@{k}"], abs=1e-5)
        assert device_metrics[f"hitrate@{k}"] == pytest.approx(HitRate(k)(recs, gt_df)[f"HitRate@{k}"], abs=1e-5)
        assert device_metrics[f"novelty@{k}"] == pytest.approx(Novelty(k)(recs, train_df)[f"Novelty@{k}"], abs=1e-5)


def test_builder_accumulates_batches(batch):
    preds, gt, train = batch
    one_shot = MetricsBuilder(metrics=["ndcg", "recall"], top_k=[5])
    one_shot.add_prediction(preds, gt, train)
    split = MetricsBuilder(metrics=["ndcg", "recall"], top_k=[5])
    split.add_prediction(preds[:16], gt[:16], train[:16])
    split.add_prediction(preds[16:], gt[16:], train[16:])
    for key in one_shot.get_metrics():
        assert one_shot.get_metrics()[key] == pytest.approx(split.get_metrics()[key], abs=1e-6)


def test_builder_reset(batch):
    preds, gt, train = batch
    builder = MetricsBuilder(metrics=["recall"], top_k=[5])
    builder.add_prediction(preds, gt)
    builder.reset()
    assert builder.get_metrics() == {}


def test_builder_coverage_requires_item_count():
    with pytest.raises(ValueError, match="item_count"):
        MetricsBuilder(metrics=["coverage"])


def test_metrics_to_df(batch):
    preds, gt, train = batch
    builder = MetricsBuilder(metrics=["recall", "ndcg"], top_k=[1, 5])
    builder.add_prediction(preds, gt)
    frame = metrics_to_df(builder.get_metrics())
    assert frame.shape == (2, 2)
    assert list(frame.columns) == ["@1", "@5"]
