"""Differential property tests: vectorized ranking metrics vs naive loops.

The 25 reference-generated golden cases (test_golden_duplicates.py) pin the
duplicate semantics at fixed points; these tests cover the space: random rec /
ground-truth lists — duplicates, empties, missing users, extra users — scored
by BOTH the repo's exploded-join hit-matrix formulation and an independent
per-user python loop written straight from the reference formulas
(replay/metrics/ndcg.py:82-93, map.py:64-78, precision.py:62-69,
rocauc.py:75-95). Any vectorization bug shows up as a disagreement.
"""

import math

import numpy as np
import pytest

hypothesis = pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st

from replay_tpu.metrics import MAP, MRR, NDCG, HitRate, PerUser, Precision, Recall, RocAuc

pytestmark = pytest.mark.core


# --------------------------------------------------------------------------- #
# naive reference-semantics implementations (per-user python loops)
# --------------------------------------------------------------------------- #
def naive_hitrate(pred, gt, k):
    return 1.0 if set(pred[:k]) & set(gt) else 0.0


def naive_precision(pred, gt, k):
    if not gt or not pred[:k]:
        return 0.0
    return len(set(pred[:k]) & set(gt)) / k


def naive_recall(pred, gt, k):
    distinct_gt = set(gt)
    if not distinct_gt:
        return 0.0
    return len(set(pred[:k]) & distinct_gt) / len(distinct_gt)


def naive_mrr(pred, gt, k):
    gt_set = set(gt)
    for i, p in enumerate(pred[:k]):
        if p in gt_set:
            return 1.0 / (i + 1)
    return 0.0


def naive_map(pred, gt, k):
    gt_set = set(gt)
    tp, total = 0, 0.0
    for i, p in enumerate(pred[:k]):
        if p in gt_set:  # occurrence semantics: every relevant position counts
            tp += 1
            total += tp / (i + 1)
    denom = min(len(gt), k)  # RAW ground-truth length
    return total / denom if denom > 0 else 0.0


def naive_ndcg(pred, gt, k):
    gt_set = set(gt)
    dcg = sum(1.0 / math.log2(i + 2) for i, p in enumerate(pred[:k]) if p in gt_set)
    idcg = sum(1.0 / math.log2(i + 2) for i in range(min(len(gt), k)))
    return dcg / idcg if idcg > 0 else 0.0


def naive_rocauc(pred, gt, k):
    window = pred[:k]
    gt_set = set(gt)
    pos = [i for i, p in enumerate(window) if p in gt_set]
    neg = [i for i, p in enumerate(window) if p not in gt_set]
    if not window or not pos:
        return 0.0
    if not neg:
        return 1.0
    concordant = sum(1 for i in pos for j in neg if i < j)
    return concordant / (len(pos) * len(neg))


NAIVE = {
    HitRate: naive_hitrate,
    Precision: naive_precision,
    Recall: naive_recall,
    MRR: naive_mrr,
    MAP: naive_map,
    NDCG: naive_ndcg,
    RocAuc: naive_rocauc,
}

item = st.integers(min_value=0, max_value=7)
rec_list = st.lists(item, min_size=0, max_size=10)  # duplicates very likely
gt_list = st.lists(item, min_size=0, max_size=6)


@settings(max_examples=60, deadline=None)
@given(
    recs=st.dictionaries(st.integers(min_value=0, max_value=5), rec_list, max_size=6),
    ground_truth=st.dictionaries(
        st.integers(min_value=0, max_value=5), gt_list, min_size=1, max_size=6
    ),
    ks=st.lists(st.integers(min_value=1, max_value=9), min_size=1, max_size=3, unique=True),
)
@pytest.mark.filterwarnings("ignore::replay_tpu.metrics.MetricDuplicatesWarning")
def test_vectorized_metrics_match_naive_loops(recs, ground_truth, ks):
    for metric_cls, naive in NAIVE.items():
        got = metric_cls(list(ks), mode=PerUser())(recs, ground_truth)
        for k in ks:
            per_user = got[f"{metric_cls.__name__}-PerUser@{k}"]
            assert set(per_user) == set(ground_truth)
            for user, gt in ground_truth.items():
                want = naive(list(recs.get(user, [])), list(gt), k)
                assert per_user[user] == pytest.approx(want, abs=1e-12), (
                    metric_cls.__name__, k, user, recs.get(user), gt,
                )
