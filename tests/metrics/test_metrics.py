"""Golden-value tests: numbers come from the reference implementation's doctests
(replay/metrics/*.py docstrings evaluated on the replay/conftest.py fixtures)."""

import numpy as np
import pandas as pd
import pytest

from replay_tpu.metrics import (
    MAP,
    MRR,
    NDCG,
    CategoricalDiversity,
    ConfidenceInterval,
    Coverage,
    Experiment,
    HitRate,
    Median,
    MetricDuplicatesWarning,
    Novelty,
    OfflineMetrics,
    PerUser,
    Precision,
    Recall,
    RocAuc,
    Surprisal,
    Unexpectedness,
)

RECS = pd.DataFrame(
    [
        (1, 3, 0.6), (1, 7, 0.5), (1, 10, 0.4), (1, 11, 0.3), (1, 2, 0.2),
        (2, 5, 0.6), (2, 8, 0.5), (2, 11, 0.4), (2, 1, 0.3), (2, 3, 0.2),
        (3, 4, 1.0), (3, 9, 0.5), (3, 2, 0.1),
    ],
    columns=["query_id", "item_id", "rating"],
)
GT = pd.DataFrame(
    [
        (1, 5), (1, 6), (1, 7), (1, 8), (1, 9), (1, 10),
        (2, 6), (2, 7), (2, 4), (2, 10), (2, 11),
        (3, 1), (3, 2), (3, 3), (3, 4), (3, 5),
    ],
    columns=["query_id", "item_id"],
)
TRAIN = pd.DataFrame(
    [
        (1, 5), (1, 6), (1, 8), (1, 9), (1, 2),
        (2, 5), (2, 8), (2, 11), (2, 1), (2, 3),
        (3, 4), (3, 9), (3, 2),
    ],
    columns=["query_id", "item_id"],
)
BASE_RECS = pd.DataFrame(
    [
        (1, 3, 0.5), (1, 7, 0.5), (1, 2, 0.7),
        (2, 5, 0.6), (2, 8, 0.6), (2, 3, 0.3),
        (3, 4, 1.0), (3, 9, 0.5),
    ],
    columns=["query_id", "item_id", "rating"],
)


def test_ndcg_golden():
    assert NDCG(2)(RECS, GT) == pytest.approx({"NDCG@2": 0.3333333333333333})
    per_user = NDCG(2, mode=PerUser())(RECS, GT)["NDCG-PerUser@2"]
    assert per_user[1] == pytest.approx(0.38685280723454163)
    assert per_user[2] == 0.0
    assert per_user[3] == pytest.approx(0.6131471927654584)
    assert NDCG(2, mode=Median())(RECS, GT)["NDCG-Median@2"] == pytest.approx(0.38685280723454163)
    assert NDCG(2, mode=ConfidenceInterval(0.95))(RECS, GT)["NDCG-ConfidenceInterval@2"] == pytest.approx(
        0.3508565839953337
    )


def test_map_golden():
    assert MAP(2)(RECS, GT) == pytest.approx({"MAP@2": 0.25})
    assert MAP(2, mode=PerUser())(RECS, GT)["MAP-PerUser@2"] == pytest.approx({1: 0.25, 2: 0.0, 3: 0.5})
    assert MAP(2, mode=ConfidenceInterval(0.95))(RECS, GT)["MAP-ConfidenceInterval@2"] == pytest.approx(
        0.282896433519043
    )


def test_coverage_golden():
    assert Coverage(2)(RECS, TRAIN) == pytest.approx({"Coverage@2": 0.5555555555555556})


def test_surprisal_golden():
    assert Surprisal(2)(RECS, TRAIN) == pytest.approx({"Surprisal@2": 0.6845351232142715})
    per_user = Surprisal(2, mode=PerUser())(RECS, TRAIN)["Surprisal-PerUser@2"]
    assert per_user == pytest.approx({1: 1.0, 2: 0.3690702464285426, 3: 0.6845351232142713})


def test_novelty_golden():
    assert Novelty(2)(RECS, TRAIN) == pytest.approx({"Novelty@2": 0.3333333333333333})
    assert Novelty(2, mode=PerUser())(RECS, TRAIN)["Novelty-PerUser@2"] == pytest.approx({1: 1.0, 2: 0.0, 3: 0.0})


def test_categorical_diversity_golden():
    cat_recs = RECS.rename(columns={"item_id": "category_id"})
    out = CategoricalDiversity([3, 5])(cat_recs)
    assert out == pytest.approx({"CategoricalDiversity@3": 1.0, "CategoricalDiversity@5": 0.8666666666666667})


def test_unexpectedness_golden():
    out = Unexpectedness([1, 2])(RECS, BASE_RECS)
    assert out == pytest.approx({"Unexpectedness@1": 0.6666666666666666, "Unexpectedness@2": 0.16666666666666666})


def test_hitrate_precision_recall_mrr():
    assert HitRate(2)(RECS, GT)["HitRate@2"] == pytest.approx(2 / 3)
    assert Precision(2)(RECS, GT)["Precision@2"] == pytest.approx(1 / 3)
    # user1: {7}; user2: {}; user3: {4} of gt sizes 6, 5, 5
    assert Recall(2)(RECS, GT)["Recall@2"] == pytest.approx((1 / 6 + 0 + 1 / 5) / 3)
    assert MRR(2)(RECS, GT)["MRR@2"] == pytest.approx((1 / 2 + 0 + 1) / 3)


def test_rocauc():
    out = RocAuc(5)(RECS, GT)["RocAuc@5"]
    assert 0.0 <= out <= 1.0


def test_dict_inputs():
    recs_dict = {
        q: list(zip(df.sort_values("rating", ascending=False)["item_id"], df.sort_values("rating", ascending=False)["rating"]))
        for q, df in RECS.groupby("query_id")
    }
    gt_dict = {q: df["item_id"].tolist() for q, df in GT.groupby("query_id")}
    assert NDCG(2)(recs_dict, gt_dict)["NDCG@2"] == pytest.approx(0.3333333333333333)


def test_duplicates_warn():
    dup = pd.concat([RECS, RECS.iloc[:1]])
    with pytest.warns(MetricDuplicatesWarning):
        NDCG(2)(dup, GT)


def test_offline_metrics_battery():
    metrics = [Precision(2), NDCG(2), Coverage(2), Novelty(2)]
    out = OfflineMetrics(metrics)(RECS, GT, train=TRAIN)
    assert out["Precision@2"] == pytest.approx(1 / 3)
    assert out["Coverage@2"] == pytest.approx(0.5555555555555556)


def test_offline_metrics_named_bases():
    out = OfflineMetrics([Precision(2), Unexpectedness([1, 2])])(
        RECS, GT, base_recommendations={"ALS": BASE_RECS, "KNN": RECS}
    )
    assert out["Unexpectedness_ALS@1"] == pytest.approx(0.6666666666666666)
    assert out["Unexpectedness_KNN@1"] == 0.0
    assert out["Precision@2"] == pytest.approx(1 / 3)


def test_offline_metrics_requires_train():
    with pytest.raises(ValueError, match="train"):
        OfflineMetrics([Coverage(2)])(RECS, GT)


def test_experiment():
    exp = Experiment([NDCG(2), HitRate(2)], GT)
    exp.add_result("modelA", RECS)
    exp.add_result("modelB", BASE_RECS)
    assert exp.results.shape == (2, 2)
    assert exp.results.loc["modelA", "NDCG@2"] == pytest.approx(0.3333333333333333)
    cmp = exp.compare("modelA")
    assert cmp.loc["modelA"].tolist() == [0.0, 0.0]
