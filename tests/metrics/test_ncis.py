"""NCIS-weighted metrics (counterfactual evaluation)."""

import numpy as np
import pandas as pd
import pytest

from replay_tpu.metrics import NCISPrecision, Precision


def frame(rows, columns=("query_id", "item_id", "rating")):
    return pd.DataFrame(rows, columns=list(columns))


@pytest.fixture
def recs():
    return frame([(1, "a", 3.0), (1, "b", 2.0), (1, "c", 1.0)])


@pytest.fixture
def gt():
    return frame([(1, "a", 1.0), (1, "c", 1.0)])


class TestNCISPrecision:
    def test_hand_computed_weights(self, recs, gt):
        prev = frame([(1, "a", 1.0), (1, "b", 0.5)])
        # weights: a -> 3/1 = 3, b -> 2/0.5 = 4, c missing -> threshold 10
        # precision@3 = (3*1 + 4*0 + 10*1) / (3 + 4 + 10)
        res = NCISPrecision(topk=3, prev_policy_weights=prev, threshold=10.0)(recs, gt)
        assert res["NCISPrecision@3"] == pytest.approx(13.0 / 17.0)

    def test_clipping(self, recs, gt):
        prev = frame([(1, "a", 300.0), (1, "b", 2.0), (1, "c", 0.001)])
        # ratios: 0.01 -> clip to 1/2; 1.0; 1000 -> clip to 2
        res = NCISPrecision(topk=3, prev_policy_weights=prev, threshold=2.0)(recs, gt)
        assert res["NCISPrecision@3"] == pytest.approx((0.5 * 1 + 1.0 * 0 + 2.0 * 1) / 3.5)

    def test_uniform_weights_match_plain_precision(self, recs, gt):
        # identical policies -> every weight is 1 -> plain precision
        prev = frame([(1, "a", 3.0), (1, "b", 2.0), (1, "c", 1.0)])
        ncis = NCISPrecision(topk=[1, 2, 3], prev_policy_weights=prev)(recs, gt)
        plain = Precision(topk=[1, 2, 3])(recs, gt)
        for k in (1, 2, 3):
            assert ncis[f"NCISPrecision@{k}"] == pytest.approx(plain[f"Precision@{k}"])

    def test_sigmoid_activation(self, recs, gt):
        prev = frame([(1, "a", 3.0), (1, "b", 2.0), (1, "c", 1.0)])
        res = NCISPrecision(
            topk=3, prev_policy_weights=prev, activation="sigmoid"
        )(recs, gt)
        # same scores both sides -> sigmoid ratio 1 -> plain precision
        assert res["NCISPrecision@3"] == pytest.approx(2.0 / 3.0)

    def test_softmax_activation(self, recs, gt):
        prev = frame([(1, "a", 1.0), (1, "b", 1.0), (1, "c", 1.0)])
        res = NCISPrecision(
            topk=3, prev_policy_weights=prev, activation="softmax", threshold=100.0
        )(recs, gt)
        cur = np.exp([3.0, 2.0, 1.0])
        cur = cur / cur.sum()
        w = cur / (1.0 / 3.0)
        expected = (w[0] + w[2]) / w.sum()
        assert res["NCISPrecision@3"] == pytest.approx(expected)

    def test_softmax_ignores_missing_pairs(self, recs, gt):
        # items b, c unlogged: their filler zeros must NOT deflate item a's
        # logged propensity (softmax over logged entries only); a's weight is
        # softmax(cur)[a] / 1.0, b and c get the max-surprise threshold
        prev = frame([(1, "a", 5.0)])
        res = NCISPrecision(
            topk=3, prev_policy_weights=prev, activation="softmax", threshold=10.0
        )(recs, gt)
        cur = np.exp([3.0, 2.0, 1.0])
        cur = cur / cur.sum()
        w = np.clip([cur[0] / 1.0, 10.0, 10.0], 0.1, 10.0)
        expected = (w[0] + w[2]) / w.sum()
        assert res["NCISPrecision@3"] == pytest.approx(expected)

    def test_user_without_recs_scores_zero(self, recs):
        prev = frame([(1, "a", 1.0)])
        gt2 = frame([(1, "a", 1.0), (2, "z", 1.0)])
        res = NCISPrecision(topk=1, prev_policy_weights=prev)(recs, gt2)
        # user 1: hit at rank 1 -> 1.0; user 2 has no recs -> 0.0
        assert res["NCISPrecision@1"] == pytest.approx(0.5)

    def test_bad_threshold(self):
        with pytest.raises(ValueError, match="threshold"):
            NCISPrecision(topk=1, prev_policy_weights=frame([]), threshold=0.0)

    def test_bad_activation(self):
        with pytest.raises(ValueError, match="activation"):
            NCISPrecision(topk=1, prev_policy_weights=frame([]), activation="relu")

    def test_per_user_mode(self, recs, gt):
        from replay_tpu.metrics import PerUser

        prev = frame([(1, "a", 3.0), (1, "b", 2.0), (1, "c", 1.0)])
        gt2 = pd.concat([gt, frame([(2, "z", 1.0)])])
        res = NCISPrecision(topk=3, prev_policy_weights=prev, mode=PerUser())(recs, gt2)
        per_user = res["NCISPrecision-PerUser@3"]
        assert per_user[1] == pytest.approx(2.0 / 3.0)
        assert per_user[2] == 0.0

    def test_dict_recs_rejected(self, gt):
        metric = NCISPrecision(topk=1, prev_policy_weights=frame([]))
        with pytest.raises(TypeError, match="DataFrame"):
            metric({1: ["a"]}, gt)
