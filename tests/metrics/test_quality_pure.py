"""The offline↔online seam (metrics/beyond_accuracy.py pure functions): the
per-slate math the online quality monitor runs MUST be bitwise the math the
offline wrapper classes aggregate — pinned against the reference's golden
values and cross-checked wrapper-vs-pure on the same fixtures."""

import pandas as pd
import pytest

from replay_tpu.metrics import Coverage, Novelty, PerUser, Surprisal
from replay_tpu.metrics.beyond_accuracy import (
    coverage_of,
    novelty_of_slate,
    surprisal_of_slate,
    surprisal_weights,
    weighted_surprisal,
)

RECS = pd.DataFrame(
    [
        (1, 3, 0.6), (1, 7, 0.5), (1, 10, 0.4), (1, 11, 0.3), (1, 2, 0.2),
        (2, 5, 0.6), (2, 8, 0.5), (2, 11, 0.4), (2, 1, 0.3), (2, 3, 0.2),
        (3, 4, 1.0), (3, 9, 0.5), (3, 2, 0.1),
    ],
    columns=["query_id", "item_id", "rating"],
)
TRAIN = pd.DataFrame(
    [
        (1, 5), (1, 6), (1, 8), (1, 9), (1, 2),
        (2, 5), (2, 8), (2, 11), (2, 1), (2, 3),
        (3, 4), (3, 9), (3, 2),
    ],
    columns=["query_id", "item_id"],
)

# the same fixtures as plain dicts (score-desc slates) — the representation
# the online monitor sees
SLATES = {1: [3, 7, 10, 11, 2], 2: [5, 8, 11, 1, 3], 3: [4, 9, 2]}
TRAIN_DICT = {1: [5, 6, 8, 9, 2], 2: [5, 8, 11, 1, 3], 3: [4, 9, 2]}


def test_novelty_pure_reproduces_the_golden_wrapper_value():
    per_slate = [
        novelty_of_slate(SLATES[user], set(TRAIN_DICT[user]), 2) for user in (1, 2, 3)
    ]
    assert sum(per_slate) / 3 == pytest.approx(0.3333333333333333)
    assert Novelty(2)(RECS, TRAIN) == pytest.approx({"Novelty@2": sum(per_slate) / 3})
    # per-user: the wrapper's values ARE the pure function's, user by user
    per_user = Novelty(2, mode=PerUser())(RECS, TRAIN)["Novelty-PerUser@2"]
    for user in (1, 2, 3):
        assert per_user[user] == pytest.approx(
            novelty_of_slate(SLATES[user], set(TRAIN_DICT[user]), 2)
        )


def test_surprisal_pure_reproduces_the_golden_wrapper_value():
    weights = surprisal_weights(TRAIN_DICT)
    per_slate = [surprisal_of_slate(SLATES[user], weights, 2) for user in (1, 2, 3)]
    assert sum(per_slate) / 3 == pytest.approx(0.6845351232142715)
    assert Surprisal(2)(RECS, TRAIN) == pytest.approx(
        {"Surprisal@2": sum(per_slate) / 3}
    )


def test_surprisal_unseen_items_weigh_one():
    weights = surprisal_weights(TRAIN_DICT)
    assert 999 not in weights
    assert surprisal_of_slate([999, 999], weights, 2) == pytest.approx(1.0)
    assert weighted_surprisal([1.0, 1.0], 2) == pytest.approx(1.0)


def test_coverage_pure_reproduces_the_golden_wrapper_value():
    recommended = set()
    for slate in SLATES.values():
        recommended.update(slate[:2])
    train_items = {item for items in TRAIN_DICT.values() for item in items}
    assert coverage_of(recommended, train_items) == pytest.approx(0.5555555555555556)
    assert Coverage(2)(RECS, TRAIN) == pytest.approx(
        {"Coverage@2": coverage_of(recommended, train_items)}
    )


def test_pure_function_degenerates():
    assert novelty_of_slate([], [1, 2], 3) == 1.0  # empty head = maximally novel
    assert surprisal_of_slate([], {}, 3) == 0.0
    assert coverage_of([1, 2], []) == 0.0
