"""Whole-zoo behavioral contract matrix (reference tests/models/test_all_models.py:37-70).

Every classical model class — all 15 — goes through the same three contracts
the reference enforces across its zoo: cold/new-query predict, predict_pairs
scoring, and save/load round-trip equality. Models whose math runs through jnp
(ALS/SLIM/Word2Vec/ClusterRec/LinUCB) share the matrix via the jax marker on
this module; the host-side zoo runs in the same parametrization.
"""

import numpy as np
import pandas as pd
import pytest

from replay_tpu.data import Dataset, FeatureHint, FeatureInfo, FeatureSchema, FeatureType
from replay_tpu.data.schema import FeatureSource
from replay_tpu.models import (
    ALS,
    KLUCB,
    SLIM,
    UCB,
    AssociationRulesItemRec,
    CatPopRec,
    ClusterRec,
    ItemKNN,
    LinUCB,
    PopRec,
    QueryPopRec,
    RandomRec,
    ThompsonSampling,
    Wilson,
    Word2VecRec,
)

pytestmark = pytest.mark.jax

K = 3
NUM_USERS = 16
NUM_ITEMS = 12
COLD_QUERY = 999


def interaction_log(seed=0):
    rng = np.random.default_rng(seed)
    rows = []
    for user in range(NUM_USERS):
        items = rng.choice(NUM_ITEMS, size=rng.integers(3, 7), replace=False)
        for t, item in enumerate(items):
            rows.append((user, int(item), int(rng.random() < 0.6), t))
    return pd.DataFrame(rows, columns=["query_id", "item_id", "rating", "timestamp"])


@pytest.fixture(scope="module")
def dataset():
    schema = [
        FeatureInfo("query_id", FeatureType.CATEGORICAL, FeatureHint.QUERY_ID),
        FeatureInfo("item_id", FeatureType.CATEGORICAL, FeatureHint.ITEM_ID),
        FeatureInfo("rating", FeatureType.NUMERICAL, FeatureHint.RATING),
        FeatureInfo("timestamp", FeatureType.NUMERICAL, FeatureHint.TIMESTAMP),
        FeatureInfo("bias", FeatureType.NUMERICAL, feature_source=FeatureSource.QUERY_FEATURES),
        FeatureInfo("taste", FeatureType.NUMERICAL, feature_source=FeatureSource.QUERY_FEATURES),
        FeatureInfo("category", FeatureType.CATEGORICAL, feature_source=FeatureSource.ITEM_FEATURES),
    ]
    query_features = pd.DataFrame(
        {
            "query_id": np.arange(NUM_USERS),
            "bias": 1.0,
            "taste": np.where(np.arange(NUM_USERS) < NUM_USERS // 2, -1.0, 1.0),
        }
    )
    item_features = pd.DataFrame(
        {"item_id": np.arange(NUM_ITEMS), "category": np.arange(NUM_ITEMS) % 3}
    )
    return Dataset(
        feature_schema=FeatureSchema(schema),
        interactions=interaction_log(),
        query_features=query_features,
        item_features=item_features,
    )


# one instance per class = the 15-row inventory of SURVEY §2.5
ZOO = [
    PopRec(),
    QueryPopRec(),
    CatPopRec(category_column="category"),
    RandomRec(seed=7),
    Wilson(),
    UCB(),
    KLUCB(),
    ThompsonSampling(seed=3),
    ItemKNN(num_neighbours=4),
    AssociationRulesItemRec(num_neighbours=6),
    ALS(rank=4, seed=0, num_iterations=2),
    Word2VecRec(rank=8, seed=0, num_iterations=5),
    SLIM(seed=0, num_iterations=10),
    ClusterRec(num_clusters=2, seed=0),
    LinUCB(alpha=0.1),
]
IDS = [type(m).__name__ for m in ZOO]

# models conditioning on per-query FEATURE rows: a cold query additionally
# lacks its feature vector, so empty output or a clear refusal is the contract
QUERY_FEATURE_MODELS = (ClusterRec, LinUCB)


@pytest.fixture(scope="module")
def fitted(dataset):
    return {type(m).__name__: m.fit(dataset) for m in ZOO}


@pytest.mark.parametrize("name", IDS)
def test_known_query_topk(fitted, dataset, name):
    model = fitted[name]
    recs = model.predict(dataset, k=K, filter_seen_items=False)
    assert set(recs.columns) >= {"query_id", "item_id", "rating"}
    assert (recs.groupby("query_id").size() <= K).all()
    assert np.isfinite(recs["rating"]).all()


@pytest.mark.parametrize("name", IDS)
def test_cold_query_predict(fitted, dataset, name):
    """Reference cold-query contract (base_rec cold filtering keyed on
    ``can_predict_cold_queries``): non-personalized models produce k recs for a
    never-seen query; history-conditioned models DROP it (empty frame, no
    garbage); query-feature models may refuse for lack of a feature row."""
    model = fitted[name]
    if isinstance(model, QUERY_FEATURE_MODELS):
        try:
            recs = model.predict(
                dataset, k=K, queries=[COLD_QUERY], filter_seen_items=False
            )
        except (ValueError, KeyError):
            return  # refusal for a query with no feature row is acceptable
        assert len(recs) <= K
        if len(recs):
            assert np.isfinite(recs["rating"]).all()
        return
    recs = model.predict(dataset, k=K, queries=[COLD_QUERY], filter_seen_items=False)
    if model.can_predict_cold_queries:
        assert set(recs["query_id"]) == {COLD_QUERY}
        assert len(recs) == K
        assert np.isfinite(recs["rating"]).all()
    else:
        assert recs.empty  # dropped, exactly like the reference's cold filter


@pytest.mark.parametrize("name", IDS)
def test_predict_pairs(fitted, dataset, name):
    model = fitted[name]
    pairs = pd.DataFrame({"query_id": [0, 0, 1], "item_id": [1, 2, 3]})
    scored = model.predict_pairs(pairs, dataset)
    assert len(scored) <= 3
    assert set(scored.columns) >= {"query_id", "item_id", "rating"}
    if len(scored):
        assert np.isfinite(scored["rating"]).all()


@pytest.mark.parametrize("name", IDS)
def test_save_load_roundtrip(fitted, dataset, name, tmp_path):
    model = fitted[name]
    before = model.predict(dataset, k=K, filter_seen_items=False)
    model.save(str(tmp_path / name))
    restored = type(model).load(str(tmp_path / name))
    after = restored.predict(dataset, k=K, filter_seen_items=False)
    pd.testing.assert_frame_equal(
        before.reset_index(drop=True),
        after.reset_index(drop=True),
        check_dtype=False,
    )
