"""Exact-MIPS index (incl. mesh-sharded search) and AOT compiled inference."""

import numpy as np
import pandas as pd
import pytest

import jax
import jax.numpy as jnp

from replay_tpu.data import Dataset, FeatureHint, FeatureInfo, FeatureSchema, FeatureType
from replay_tpu.data.nn import TensorFeatureInfo, TensorSchema
from replay_tpu.models import ALS, MIPSIndex
from replay_tpu.nn import make_mesh
from replay_tpu.nn.compiled import CompiledInference, export_inference, import_inference
from replay_tpu.nn.sequential.sasrec import SasRec

pytestmark = pytest.mark.jax


class TestMIPSIndex:
    def test_exact_topk_single_device(self):
        rng = np.random.default_rng(0)
        items = rng.normal(size=(40, 8)).astype(np.float32)
        queries = rng.normal(size=(5, 8)).astype(np.float32)
        scores, idx = MIPSIndex(items).search(queries, k=7)
        brute = queries @ items.T
        want_idx = np.argsort(-brute, axis=1)[:, :7]
        np.testing.assert_array_equal(np.sort(idx, axis=1), np.sort(want_idx, axis=1))
        np.testing.assert_allclose(scores, np.take_along_axis(brute, idx, 1), rtol=1e-5)

    def test_sharded_equals_unsharded(self):
        rng = np.random.default_rng(1)
        items = rng.normal(size=(64, 8)).astype(np.float32)  # 64 % 8 devices == 0
        queries = rng.normal(size=(3, 8)).astype(np.float32)
        mesh = make_mesh()
        s_scores, s_idx = MIPSIndex(items, mesh=mesh).search(queries, k=5)
        u_scores, u_idx = MIPSIndex(items).search(queries, k=5)
        np.testing.assert_allclose(np.sort(s_scores, 1), np.sort(u_scores, 1), rtol=1e-5)
        np.testing.assert_array_equal(np.sort(s_idx, 1), np.sort(u_idx, 1))

    def test_k_too_large(self):
        with pytest.raises(ValueError, match="exceeds"):
            MIPSIndex(np.ones((4, 2), np.float32)).search(np.ones((1, 2), np.float32), k=9)

    def test_shard_padding_rows_never_reach_topk(self):
        """Regression: a catalog that does not divide the mesh axis pads some
        shards with zero rows — those rows must never surface in top-k, even
        when k exceeds a shard's UNPADDED row count (here shards 5-7 hold zero
        real rows and every shard holds at most 2)."""
        rng = np.random.default_rng(7)
        num_items = 9  # 8-device mesh -> padded to 16, shard_size 2
        # strictly negative vectors: any padded zero-row would WIN on score
        # (dot products with a negative query come out positive), so a
        # padding leak is guaranteed visible, not just possible
        items = -np.abs(rng.normal(size=(num_items, 6))).astype(np.float32) - 0.1
        queries = np.abs(rng.normal(size=(4, 6))).astype(np.float32) + 0.1
        index = MIPSIndex(items, mesh=make_mesh())
        for k in (1, 3, num_items):  # k=9 > every shard's 0-2 real rows
            scores, idx = index.search(queries, k=k)
            assert idx.max() < num_items, f"padded row leaked into top-{k}"
            brute = queries @ items.T
            want_idx = np.argsort(-brute, axis=1, kind="stable")[:, :k]
            np.testing.assert_array_equal(np.sort(idx, 1), np.sort(want_idx, 1))
            np.testing.assert_allclose(
                np.sort(scores, 1),
                np.sort(np.take_along_axis(brute, want_idx, 1), 1),
                rtol=1e-5,
            )

    def test_search_jax_returns_device_arrays_equal_to_search(self):
        rng = np.random.default_rng(2)
        items = rng.normal(size=(12, 4)).astype(np.float32)
        queries = rng.normal(size=(3, 4)).astype(np.float32)
        index = MIPSIndex(items)
        dev_scores, dev_idx = index.search_jax(jnp.asarray(queries), k=4)
        assert isinstance(dev_scores, jax.Array) and isinstance(dev_idx, jax.Array)
        host_scores, host_idx = index.search(queries, k=4)
        np.testing.assert_array_equal(np.asarray(dev_idx), host_idx)
        np.testing.assert_array_equal(np.asarray(dev_scores), host_scores)


def test_als_ann_predict_matches_exact():
    rng = np.random.default_rng(0)
    rows = [(u, int(i), 1.0, t) for u in range(8) for t, i in
            enumerate(rng.choice(16, 5, replace=False))]
    log = pd.DataFrame(rows, columns=["query_id", "item_id", "rating", "timestamp"])
    ds = Dataset(feature_schema=FeatureSchema([
        FeatureInfo("query_id", FeatureType.CATEGORICAL, FeatureHint.QUERY_ID),
        FeatureInfo("item_id", FeatureType.CATEGORICAL, FeatureHint.ITEM_ID),
        FeatureInfo("rating", FeatureType.NUMERICAL, FeatureHint.RATING),
        FeatureInfo("timestamp", FeatureType.NUMERICAL, FeatureHint.TIMESTAMP)]),
        interactions=log)
    model = ALS(rank=4, num_iterations=4, seed=0).fit(ds)
    recs = model.predict_ann(ds, k=3)
    # index scores equal factor dot products
    brute = model.user_factors @ model.item_factors.T
    for _, row in recs.iterrows():
        q = list(model.fit_queries).index(row["query_id"])
        i = list(model.fit_items).index(row["item_id"])
        assert abs(brute[q, i] - row["rating"]) < 1e-5
    nn_frame = model.get_nearest_items_ann([model.fit_items[0]], k=3)
    assert len(nn_frame) == 3
    assert (nn_frame["neighbour_item_idx"] != model.fit_items[0]).all()


NUM_ITEMS, SEQ_LEN = 20, 6


@pytest.fixture(scope="module")
def sasrec_with_params():
    schema = TensorSchema(
        TensorFeatureInfo("item_id", FeatureType.CATEGORICAL, is_seq=True,
                          feature_hint=FeatureHint.ITEM_ID, cardinality=NUM_ITEMS,
                          embedding_dim=8)
    )
    model = SasRec(schema=schema, embedding_dim=8, num_blocks=1, max_sequence_length=SEQ_LEN)
    ids = np.zeros((2, SEQ_LEN), np.int32)
    params = model.init(jax.random.PRNGKey(0), {"item_id": ids},
                        np.ones((2, SEQ_LEN), bool))["params"]
    return model, params


class TestCompiledInference:
    def test_batch_mode_and_padding(self, sasrec_with_params):
        model, params = sasrec_with_params
        compiled = CompiledInference.compile(model, params, SEQ_LEN, batch_size=4, mode="batch")
        rng = np.random.default_rng(0)
        ids = rng.integers(0, NUM_ITEMS, (3, SEQ_LEN)).astype(np.int32)  # < bucket
        mask = np.ones((3, SEQ_LEN), bool)
        logits = compiled(ids, mask)
        assert logits.shape == (3, NUM_ITEMS)
        # equals the uncompiled forward
        want = model.apply({"params": params}, {"item_id": ids}, mask,
                           method=SasRec.forward_inference)
        np.testing.assert_allclose(np.asarray(logits), np.asarray(want), rtol=1e-4, atol=1e-6)

    def test_dynamic_buckets(self, sasrec_with_params):
        model, params = sasrec_with_params
        compiled = CompiledInference.compile(
            model, params, SEQ_LEN, mode="dynamic_batch_size", dynamic_buckets=(1, 4)
        )
        for batch in (1, 2, 4):
            ids = np.zeros((batch, SEQ_LEN), np.int32)
            out = compiled(ids, np.ones((batch, SEQ_LEN), bool))
            assert out.shape == (batch, NUM_ITEMS)
        with pytest.raises(ValueError, match="largest compiled bucket"):
            compiled(np.zeros((5, SEQ_LEN), np.int32), np.ones((5, SEQ_LEN), bool))

    def test_wrong_length_rejected(self, sasrec_with_params):
        model, params = sasrec_with_params
        compiled = CompiledInference.compile(model, params, SEQ_LEN, batch_size=2)
        with pytest.raises(ValueError, match="Sequence length"):
            compiled(np.zeros((2, SEQ_LEN + 1), np.int32), np.ones((2, SEQ_LEN + 1), bool))

    def test_export_roundtrip(self, sasrec_with_params):
        model, params = sasrec_with_params
        payload = export_inference(model, params, SEQ_LEN, batch_size=2)
        assert isinstance(payload, (bytes, bytearray))
        served = import_inference(bytes(payload))
        ids = np.zeros((2, SEQ_LEN), np.int32)
        mask = np.ones((2, SEQ_LEN), bool)
        got = served(ids, mask)
        want = model.apply({"params": params}, {"item_id": ids}, mask,
                           method=SasRec.forward_inference)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-6)

class TestCompiledInferenceSerialization:
    def test_serialize_roundtrip_identical_scores_every_bucket(self, sasrec_with_params):
        """StableHLO bytes → fresh CompiledInference → identical scores (the
        serving-process handoff: no model code, no params pytree needed)."""
        model, params = sasrec_with_params
        compiled = CompiledInference.compile(
            model, params, SEQ_LEN, mode="dynamic_batch_size", dynamic_buckets=(1, 4)
        )
        payload = compiled.serialize()
        assert isinstance(payload, bytes)
        served = CompiledInference.deserialize(payload)
        assert served.buckets == compiled.buckets
        assert served.mode == compiled.mode
        assert served.max_sequence_length == SEQ_LEN
        rng = np.random.default_rng(5)
        for batch in (1, 2, 4):
            ids = rng.integers(0, NUM_ITEMS, (batch, SEQ_LEN)).astype(np.int32)
            mask = np.ones((batch, SEQ_LEN), bool)
            np.testing.assert_array_equal(
                np.asarray(served(ids, mask)), np.asarray(compiled(ids, mask))
            )

    def test_serialize_roundtrip_with_candidates_and_reserialize(self, sasrec_with_params):
        model, params = sasrec_with_params
        compiled = CompiledInference.compile(
            model, params, SEQ_LEN, batch_size=2, candidates_count=4
        )
        served = CompiledInference.deserialize(compiled.serialize())
        ids = np.zeros((2, SEQ_LEN), np.int32)
        mask = np.ones((2, SEQ_LEN), bool)
        cands = np.asarray([1, 3, 5, 7], np.int32)
        np.testing.assert_array_equal(
            np.asarray(served(ids, mask, candidates=cands)),
            np.asarray(compiled(ids, mask, candidates=cands)),
        )
        # a deserialized instance can re-serialize (it keeps the raw blobs)
        twice = CompiledInference.deserialize(served.serialize())
        np.testing.assert_array_equal(
            np.asarray(twice(ids, mask, candidates=cands)),
            np.asarray(compiled(ids, mask, candidates=cands)),
        )
        # the padding/validation path survives the round trip too
        with pytest.raises(ValueError, match="candidates shape"):
            served(ids, mask, candidates=[1, 2])

    def test_serialize_both_outputs_mode(self, sasrec_with_params):
        model, params = sasrec_with_params
        compiled = CompiledInference.compile(
            model, params, SEQ_LEN, batch_size=2, outputs="both"
        )
        served = CompiledInference.deserialize(compiled.serialize())
        ids = np.zeros((2, SEQ_LEN), np.int32)
        mask = np.ones((2, SEQ_LEN), bool)
        logits_a, hidden_a = compiled(ids, mask)
        logits_b, hidden_b = served(ids, mask)
        np.testing.assert_array_equal(np.asarray(logits_a), np.asarray(logits_b))
        np.testing.assert_array_equal(np.asarray(hidden_a), np.asarray(hidden_b))

    def test_bad_payload_rejected(self):
        with pytest.raises(ValueError, match="bad magic"):
            CompiledInference.deserialize(b"not a payload")

    def test_routing_only_instance_cannot_serialize(self):
        chooser = CompiledInference(dict.fromkeys((1, 4)), SEQ_LEN, "dynamic_batch_size")
        with pytest.raises(ValueError, match="no executables"):
            chooser.serialize()


class TestBucketsIntrospection:
    def test_buckets_property_exposes_compiled_sizes(self, sasrec_with_params):
        model, params = sasrec_with_params
        compiled = CompiledInference.compile(
            model, params, SEQ_LEN, mode="dynamic_batch_size", dynamic_buckets=(8, 1, 4)
        )
        assert compiled.buckets == (1, 4, 8)  # ascending, whatever the input order
        single = CompiledInference.compile(model, params, SEQ_LEN, batch_size=3)
        assert single.buckets == (3,)

    def test_outputs_mode_validation(self, sasrec_with_params):
        model, params = sasrec_with_params
        with pytest.raises(ValueError, match="outputs"):
            CompiledInference.compile(model, params, SEQ_LEN, outputs="everything")
        with pytest.raises(ValueError, match="hidden"):
            CompiledInference.compile(
                model, params, SEQ_LEN, outputs="hidden", candidates_count=3
            )

    def test_hidden_outputs_mode_returns_last_state(self, sasrec_with_params):
        model, params = sasrec_with_params
        compiled = CompiledInference.compile(
            model, params, SEQ_LEN, batch_size=2, outputs="hidden"
        )
        rng = np.random.default_rng(0)
        ids = rng.integers(0, NUM_ITEMS, (2, SEQ_LEN)).astype(np.int32)
        mask = np.ones((2, SEQ_LEN), bool)
        hidden = np.asarray(compiled(ids, mask))
        assert hidden.shape == (2, 8)
        want = model.apply({"params": params}, {"item_id": ids}, mask,
                           method=SasRec.__call__)[:, -1, :]
        np.testing.assert_allclose(hidden, np.asarray(want), rtol=1e-5, atol=1e-6)


class TestCompiledInferenceEdges:
    def test_candidate_scoring_and_validation(self, sasrec_with_params):
        model, params = sasrec_with_params
        compiled = CompiledInference.compile(
            model, params, SEQ_LEN, batch_size=2, candidates_count=5
        )
        rng = np.random.default_rng(1)
        ids = rng.integers(0, NUM_ITEMS, (2, SEQ_LEN)).astype(np.int32)
        mask = np.ones((2, SEQ_LEN), bool)
        candidates = np.asarray([1, 3, 5, 7, 9])
        # float/list candidate inputs coerce to int32 and score correctly
        got = compiled(ids, mask, candidates=[1.0, 3.0, 5.0, 7.0, 9.0])
        assert got.shape == (2, 5)
        want = model.apply(
            {"params": params}, {"item_id": ids}, mask,
            candidates_to_score=np.asarray(candidates, np.int32),
            method=SasRec.forward_inference,
        )
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-6)
        # wrong candidate count is a clear error, not an XLA shape crash
        with pytest.raises(ValueError, match="candidates shape"):
            compiled(ids, mask, candidates=[1, 2, 3])
        # compiled WITH candidates requires them
        with pytest.raises(ValueError, match="none given"):
            compiled(ids, mask)

    def test_candidates_without_compiling_for_them(self, sasrec_with_params):
        model, params = sasrec_with_params
        compiled = CompiledInference.compile(model, params, SEQ_LEN, batch_size=2)
        with pytest.raises(ValueError, match="candidates_count"):
            compiled(np.zeros((2, SEQ_LEN), np.int32), np.ones((2, SEQ_LEN), bool),
                     candidates=[1, 2])

    def test_one_query_mode(self, sasrec_with_params):
        model, params = sasrec_with_params
        compiled = CompiledInference.compile(model, params, SEQ_LEN, mode="one_query")
        out = compiled(np.zeros((1, SEQ_LEN), np.int32), np.ones((1, SEQ_LEN), bool))
        assert out.shape == (1, NUM_ITEMS)
        with pytest.raises(ValueError, match="largest compiled bucket"):
            compiled(np.zeros((2, SEQ_LEN), np.int32), np.ones((2, SEQ_LEN), bool))

    def test_unknown_mode_rejected(self, sasrec_with_params):
        model, params = sasrec_with_params
        with pytest.raises(ValueError, match="mode"):
            CompiledInference.compile(model, params, SEQ_LEN, mode="streaming")
