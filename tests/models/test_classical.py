"""Behavioral contract tests for the classical model zoo (modeled on the
reference's parameterized all-model tests — cold users, predict_pairs, save/load)."""

import numpy as np
import pandas as pd
import pytest

from replay_tpu.data import Dataset, FeatureHint, FeatureInfo, FeatureSchema, FeatureType
from replay_tpu.models import (
    AssociationRulesItemRec,
    CatPopRec,
    ItemKNN,
    KLUCB,
    PopRec,
    QueryPopRec,
    RandomRec,
    ThompsonSampling,
    UCB,
    Wilson,
)

K = 3
NUM_USERS = 12
NUM_ITEMS = 8


def binary_log(seed=0):
    rng = np.random.default_rng(seed)
    rows = []
    for user in range(NUM_USERS):
        n = rng.integers(2, 6)
        items = rng.choice(NUM_ITEMS, size=n, replace=False)
        for t, item in enumerate(items):
            # popular items succeed more often -> bandits have signal
            rows.append((user, int(item), int(rng.random() < (0.3 + 0.08 * item)), t))
    return pd.DataFrame(rows, columns=["query_id", "item_id", "rating", "timestamp"])


def make_dataset(log=None, item_features=None):
    schema = [
        FeatureInfo("query_id", FeatureType.CATEGORICAL, FeatureHint.QUERY_ID),
        FeatureInfo("item_id", FeatureType.CATEGORICAL, FeatureHint.ITEM_ID),
        FeatureInfo("rating", FeatureType.NUMERICAL, FeatureHint.RATING),
        FeatureInfo("timestamp", FeatureType.NUMERICAL, FeatureHint.TIMESTAMP),
    ]
    from replay_tpu.data.schema import FeatureSource

    if item_features is not None:
        schema.append(
            FeatureInfo("category", FeatureType.CATEGORICAL, feature_source=FeatureSource.ITEM_FEATURES)
        )
    return Dataset(
        feature_schema=FeatureSchema(schema),
        interactions=log if log is not None else binary_log(),
        item_features=item_features,
    )


MODELS = [
    PopRec(),
    PopRec(use_rating=True),
    RandomRec(seed=7),
    RandomRec(distribution="popular_based", alpha=1.0, seed=7),
    Wilson(),
    UCB(),
    KLUCB(),
    ThompsonSampling(seed=3),
    ItemKNN(num_neighbours=4),
    ItemKNN(num_neighbours=4, weighting="tf_idf"),
    ItemKNN(num_neighbours=4, weighting="bm25", use_rating=True),
    AssociationRulesItemRec(num_neighbours=6),
    AssociationRulesItemRec(num_neighbours=6, use_lift=True),
]


@pytest.mark.parametrize("model", MODELS, ids=lambda m: f"{type(m).__name__}-{id(m) % 100}")
def test_fit_predict_contract(model):
    dataset = make_dataset()
    recs = model.fit_predict(dataset, k=K)
    assert set(recs.columns) >= {"query_id", "item_id", "rating"}
    per_user = recs.groupby("query_id").size()
    assert (per_user <= K).all()
    # no seen items recommended
    seen = set(map(tuple, dataset.interactions[["query_id", "item_id"]].to_numpy()))
    assert not seen.intersection(map(tuple, recs[["query_id", "item_id"]].to_numpy()))
    # scores are finite and sorted within each user
    assert np.isfinite(recs["rating"]).all()
    for _, group in recs.groupby("query_id"):
        assert (np.diff(group["rating"].to_numpy()) <= 1e-9).all()


@pytest.mark.parametrize("model", [PopRec(), Wilson(), ItemKNN(num_neighbours=4)],
                         ids=lambda m: type(m).__name__)
def test_save_load_same_predictions(model, tmp_path):
    dataset = make_dataset()
    recs_before = model.fit_predict(dataset, k=K)
    model.save(str(tmp_path / "model"))
    restored = type(model).load(str(tmp_path / "model"))
    recs_after = restored.predict(dataset, k=K)
    pd.testing.assert_frame_equal(
        recs_before.reset_index(drop=True), recs_after.reset_index(drop=True)
    )


def test_predict_pairs():
    dataset = make_dataset()
    model = PopRec().fit(dataset)
    pairs = pd.DataFrame({"query_id": [0, 0, 1], "item_id": [1, 2, 3]})
    scored = model.predict_pairs(pairs, dataset)
    assert len(scored) == 3
    assert "rating" in scored.columns
    # same item gets the same popularity for different users
    same_item = model.predict_pairs(
        pd.DataFrame({"query_id": [0, 5], "item_id": [2, 2]}), dataset
    )
    assert same_item["rating"].iloc[0] == same_item["rating"].iloc[1]


def test_pop_rec_cold_items_and_users():
    dataset = make_dataset()
    model = PopRec().fit(dataset)
    # cold user (not in training): still gets recommendations (non-personalized)
    recs = model.predict(dataset, k=K, queries=[999], filter_seen_items=False)
    assert set(recs["query_id"]) == {999}
    assert len(recs) == K
    # cold item in the pool: gets the cold fill value, not NaN
    recs2 = model.predict(dataset, k=NUM_ITEMS + 1, queries=[999],
                          items=np.arange(NUM_ITEMS + 1), filter_seen_items=False)
    assert np.isfinite(recs2["rating"]).all()
    cold_score = recs2[recs2["item_id"] == NUM_ITEMS]["rating"].iloc[0]
    assert cold_score == pytest.approx(model._fill_value)


def test_pop_rec_values():
    log = pd.DataFrame(
        {
            "query_id": [0, 1, 2, 0, 1, 0],
            "item_id": [0, 0, 0, 1, 1, 2],
            "rating": [1.0] * 6,
            "timestamp": range(6),
        }
    )
    model = PopRec().fit(make_dataset(log))
    pop = model.item_popularity.set_index("item_id")["rating"]
    assert pop[0] == pytest.approx(1.0)  # all 3 users
    assert pop[1] == pytest.approx(2 / 3)
    assert pop[2] == pytest.approx(1 / 3)


def test_query_pop_rec():
    log = pd.DataFrame(
        {
            "query_id": [0, 0, 0, 1, 1],
            "item_id": [5, 5, 6, 6, 7],
            "rating": [1.0] * 5,
            "timestamp": range(5),
        }
    )
    model = QueryPopRec().fit(make_dataset(log))
    recs = model.predict(make_dataset(log), k=1)
    by_user = recs.set_index("query_id")["item_id"]
    assert by_user[0] == 5  # user 0's most repeated item
    assert by_user[1] in (6, 7)


def test_cat_pop_rec():
    log = binary_log()
    item_features = pd.DataFrame(
        {"item_id": np.arange(NUM_ITEMS), "category": ["a", "a", "a", "a", "b", "b", "b", "b"]}
    )
    model = CatPopRec().fit(make_dataset(log, item_features))
    per_cat = model.predict_for_categories(["a", "b"], k=2)
    assert set(per_cat["category"]) == {"a", "b"}
    assert (per_cat.groupby("category").size() == 2).all()
    # items recommended for a category belong to it
    assert set(per_cat[per_cat["category"] == "a"]["item_id"]) <= {0, 1, 2, 3}


def test_item_knn_neighbours_and_scores():
    # users 0..3 all take items (0,1) together; item 2 is solo
    log = pd.DataFrame(
        {
            "query_id": [0, 0, 1, 1, 2, 2, 3],
            "item_id": [0, 1, 0, 1, 0, 1, 2],
            "rating": [1.0] * 7,
            "timestamp": range(7),
        }
    )
    model = ItemKNN(num_neighbours=2).fit(make_dataset(log))
    nearest = model.get_nearest_items([0], k=1)
    assert nearest["neighbour_item_idx"].iloc[0] == 1
    # a user who saw item 0 gets item 1 recommended above item 2
    recs = model.predict(make_dataset(log), k=2, queries=[3], filter_seen_items=True)
    assert recs.empty or 2 not in set(recs["item_id"])  # item 2 is what they saw


def test_bandit_scores_ordering():
    # strongly different success rates -> Wilson/UCB/KLUCB must rank accordingly
    rows = []
    for u in range(30):
        rows.append((u, 0, 1, 0))  # item 0 always succeeds
        rows.append((u, 1, int(u % 5 == 0), 1))  # item 1 rarely succeeds
    log = pd.DataFrame(rows, columns=["query_id", "item_id", "rating", "timestamp"])
    for model in (Wilson(), UCB(), KLUCB()):
        model.fit(make_dataset(log))
        pop = model.item_popularity.set_index("item_id")["rating"]
        assert pop[0] > pop[1], type(model).__name__
    with pytest.raises(ValueError, match="binary"):
        Wilson().fit(make_dataset(binary_log().assign(rating=2.5)))


def test_random_rec_deterministic_with_seed():
    dataset = make_dataset()
    a = RandomRec(seed=5).fit_predict(dataset, k=K)
    b = RandomRec(seed=5).fit_predict(dataset, k=K)
    pd.testing.assert_frame_equal(a, b)
    c = RandomRec(seed=6).fit_predict(dataset, k=K)
    assert not a["item_id"].equals(c["item_id"])


def test_unfitted_predict_raises():
    with pytest.raises(RuntimeError, match="not fitted"):
        PopRec().predict(make_dataset(), k=1)

def test_cat_pop_rec_category_tree():
    """set_cat_tree (ref cat_pop_rec.py:85): a parent category recommends its
    whole subtree's items with popularity re-normalized in the subtree."""
    log = binary_log()
    item_features = pd.DataFrame(
        {"item_id": np.arange(NUM_ITEMS), "category": ["a", "a", "a", "a", "b", "b", "b", "b"]}
    )
    model = CatPopRec().fit(make_dataset(log, item_features))
    model.set_cat_tree(pd.DataFrame({"category": ["a", "b"], "parent_cat": ["root", "root"]}))
    per_root = model.predict_for_categories(["root"], k=NUM_ITEMS)
    assert set(per_root["category"]) == {"root"}
    # the root subtree covers BOTH leaf categories' items
    assert set(per_root["item_id"]) == set(range(NUM_ITEMS))
    # subtree ratings renormalize to 1 over the whole pool
    assert per_root["rating"].sum() == pytest.approx(1.0)
    # leaf requests still work and only return their own items
    per_leaf = model.predict_for_categories(["a"], k=NUM_ITEMS)
    assert set(per_leaf["item_id"]) <= {0, 1, 2, 3}


def test_bandit_refit_matches_full_fit():
    """refit (ref ucb.py:147): counters accumulate across slices — two-slice
    refit == one-shot fit on the concatenated log, for the whole family."""
    from replay_tpu.models import UCB, Wilson

    rows = []
    rng = np.random.default_rng(3)
    for u in range(40):
        for i in range(NUM_ITEMS):
            rows.append((u, i, int(rng.random() < (i + 1) / (NUM_ITEMS + 1)), u * NUM_ITEMS + i))
    log = pd.DataFrame(rows, columns=["query_id", "item_id", "rating", "timestamp"])
    first, second = log.iloc[: len(log) // 2], log.iloc[len(log) // 2 :]

    for cls in (UCB, Wilson):
        incremental = cls().fit(make_dataset(first)).refit(make_dataset(second))
        oneshot = cls().fit(make_dataset(log))
        merged = incremental.item_popularity.merge(
            oneshot.item_popularity, on="item_id", suffixes=("_inc", "_one")
        )
        np.testing.assert_allclose(merged["rating_inc"], merged["rating_one"], rtol=1e-12)
        assert incremental.items_count == oneshot.items_count
        assert incremental.queries_count == oneshot.queries_count


def test_association_rules_get_similarity():
    log = binary_log()
    model = AssociationRulesItemRec().fit(make_dataset(log))
    sim = model.get_similarity()
    assert sim.shape == (model.items_count, model.items_count)


def test_cat_pop_rec_tree_internal_nodes_cycles_and_save(tmp_path):
    """Items on INTERNAL categories stay reachable, cycles raise, and the
    tree-expansion data survives save/load."""
    from replay_tpu.utils import load, save

    log = binary_log()
    item_features = pd.DataFrame(
        # item 7 attaches directly to the INTERNAL category "mid"
        {"item_id": np.arange(NUM_ITEMS),
         "category": ["a", "a", "a", "a", "b", "b", "b", "mid"]}
    )
    model = CatPopRec().fit(make_dataset(log, item_features))
    tree = pd.DataFrame(
        {"category": ["mid", "a", "b"], "parent_cat": ["root", "mid", "mid"]}
    )
    model.set_cat_tree(tree)
    per_mid = model.predict_for_categories(["mid"], k=NUM_ITEMS)
    assert 7 in set(per_mid["item_id"])  # the internal node's own item
    assert set(per_mid["item_id"]) == set(range(NUM_ITEMS))
    assert per_mid["rating"].sum() == pytest.approx(1.0)

    with pytest.raises(ValueError, match="cycle"):
        model.set_cat_tree(pd.DataFrame(
            {"category": ["x", "y"], "parent_cat": ["y", "x"]}
        ))

    save(model, str(tmp_path / "catpop"))
    loaded = load(str(tmp_path / "catpop"))
    loaded.set_cat_tree(tree)
    reloaded = loaded.predict_for_categories(["mid"], k=NUM_ITEMS)
    pd.testing.assert_frame_equal(
        reloaded.reset_index(drop=True), per_mid.reset_index(drop=True)
    )


def test_bandit_refit_after_save_load(tmp_path):
    from replay_tpu.models import UCB
    from replay_tpu.utils import load, save

    log = binary_log()
    model = UCB().fit(make_dataset(log))
    save(model, str(tmp_path / "ucb"))
    loaded = load(str(tmp_path / "ucb"))
    refitted = loaded.refit(make_dataset(binary_log(seed=5)))
    oneshot = UCB().fit(
        make_dataset(pd.concat([binary_log(), binary_log(seed=5)], ignore_index=True))
    )
    merged = refitted.item_popularity.merge(
        oneshot.item_popularity, on="item_id", suffixes=("_inc", "_one")
    )
    np.testing.assert_allclose(merged["rating_inc"], merged["rating_one"], rtol=1e-12)
