"""JAX-backed classical models: ALS, SLIM, Word2Vec, ClusterRec, LinUCB."""

import numpy as np
import pandas as pd
import pytest

from replay_tpu.data import Dataset, FeatureHint, FeatureInfo, FeatureSchema, FeatureType
from replay_tpu.data.schema import FeatureSource
from replay_tpu.models import ALS, SLIM, ClusterRec, LinUCB, Word2VecRec

pytestmark = pytest.mark.jax


def block_log(num_users=16, group_size=10):
    """Two disjoint taste groups: users 0..7 like items 0..group_size-1, users
    8..15 like the other half; each user sees 4, leaving unseen in-group items."""
    rows = []
    rng = np.random.default_rng(0)
    for user in range(num_users):
        group = user // (num_users // 2)
        liked = np.arange(group_size) + group * group_size
        chosen = rng.choice(liked, size=4, replace=False)
        for t, item in enumerate(chosen):
            rows.append((user, int(item), 1.0, t))
    return pd.DataFrame(rows, columns=["query_id", "item_id", "rating", "timestamp"])


def make_dataset(log, query_features=None):
    schema = [
        FeatureInfo("query_id", FeatureType.CATEGORICAL, FeatureHint.QUERY_ID),
        FeatureInfo("item_id", FeatureType.CATEGORICAL, FeatureHint.ITEM_ID),
        FeatureInfo("rating", FeatureType.NUMERICAL, FeatureHint.RATING),
        FeatureInfo("timestamp", FeatureType.NUMERICAL, FeatureHint.TIMESTAMP),
    ]
    if query_features is not None:
        for column in query_features.columns:
            if column != "query_id":
                schema.append(
                    FeatureInfo(column, FeatureType.NUMERICAL,
                                feature_source=FeatureSource.QUERY_FEATURES)
                )
    return Dataset(
        feature_schema=FeatureSchema(schema), interactions=log, query_features=query_features
    )


@pytest.mark.parametrize("implicit", [True, False], ids=["implicit", "explicit"])
def test_als_learns_block_structure(implicit):
    log = block_log()
    model = ALS(rank=4, implicit_prefs=implicit, num_iterations=8, seed=0)
    recs = model.fit_predict(make_dataset(log), k=3)
    # recommendations stay within the user's taste group overwhelmingly
    in_group = 0
    for _, row in recs.iterrows():
        group = row["query_id"] // 8
        in_group += group * 10 <= row["item_id"] < (group + 1) * 10
    assert in_group / len(recs) > 0.8
    assert model.user_factors.shape == (16, 4) and model.item_factors.shape == (20, 4)


def test_als_save_load(tmp_path):
    model = ALS(rank=4, num_iterations=4, seed=0)
    dataset = make_dataset(block_log())
    before = model.fit_predict(dataset, k=2)
    model.save(str(tmp_path / "als"))
    after = ALS.load(str(tmp_path / "als")).predict(dataset, k=2)
    pd.testing.assert_frame_equal(before.reset_index(drop=True), after.reset_index(drop=True))


def test_slim_learns_cooccurrence():
    model = SLIM(beta=0.01, lambda_=0.001, num_iterations=200)
    recs = model.fit_predict(make_dataset(block_log()), k=2)
    in_group = np.mean(
        [(row["query_id"] // 8) * 10 <= row["item_id"] < (row["query_id"] // 8 + 1) * 10
         for _, row in recs.iterrows()]
    )
    assert in_group > 0.8
    # diagonal is zero and weights are non-negative (SLIM constraints)
    assert (np.diag(model.similarity) == 0).all()
    assert (model.similarity >= 0).all()


def test_word2vec_group_similarity():
    model = Word2VecRec(rank=16, num_iterations=80, window_size=3, seed=0)
    model.fit(make_dataset(block_log(num_users=32)))
    vectors = model.item_vectors / np.linalg.norm(model.item_vectors, axis=1, keepdims=True)
    sims = vectors @ vectors.T
    within = np.mean([sims[i, j] for i in range(10) for j in range(10) if i != j])
    across = np.mean([sims[i, j] for i in range(10) for j in range(10, 20)])
    assert within > across
    recs = model.predict(make_dataset(block_log(num_users=32)), k=2)
    assert (recs.groupby("query_id").size() <= 2).all()


def test_cluster_rec():
    log = block_log()
    query_features = pd.DataFrame(
        {"query_id": np.arange(16), "feat": np.where(np.arange(16) < 8, 0.0, 10.0)}
    )
    dataset = make_dataset(log, query_features)
    model = ClusterRec(num_clusters=2, seed=0)
    recs = model.fit_predict(dataset, k=2, filter_seen_items=False)
    for _, row in recs.iterrows():
        group = row["query_id"] // 8
        assert group * 10 <= row["item_id"] < (group + 1) * 10
    with pytest.raises(ValueError, match="query_features"):
        ClusterRec().fit(make_dataset(log))


def test_lin_ucb():
    # context dimension separates the groups: reward = context matches item group
    log = block_log()
    query_features = pd.DataFrame(
        {"query_id": np.arange(16), "bias": 1.0,
         "taste": np.where(np.arange(16) < 8, -1.0, 1.0)}
    )
    dataset = make_dataset(log, query_features)
    model = LinUCB(alpha=0.1).fit(dataset)
    recs = model.predict(dataset, k=3, filter_seen_items=False)
    in_group = np.mean(
        [(row["query_id"] // 8) * 10 <= row["item_id"] < (row["query_id"] // 8 + 1) * 10
         for _, row in recs.iterrows()]
    )
    assert in_group > 0.7
    model.save(str(__import__("tempfile").mkdtemp() + "/linucb"))