"""JAX-backed classical models: ALS, SLIM, Word2Vec, ClusterRec, LinUCB."""

import numpy as np
import pandas as pd
import pytest

from replay_tpu.data import Dataset, FeatureHint, FeatureInfo, FeatureSchema, FeatureType
from replay_tpu.data.schema import FeatureSource
from replay_tpu.models import ALS, SLIM, ClusterRec, LinUCB, Word2VecRec

pytestmark = pytest.mark.jax


def block_log(num_users=16, group_size=10):
    """Two disjoint taste groups: users 0..7 like items 0..group_size-1, users
    8..15 like the other half; each user sees 4, leaving unseen in-group items."""
    rows = []
    rng = np.random.default_rng(0)
    for user in range(num_users):
        group = user // (num_users // 2)
        liked = np.arange(group_size) + group * group_size
        chosen = rng.choice(liked, size=4, replace=False)
        for t, item in enumerate(chosen):
            rows.append((user, int(item), 1.0, t))
    return pd.DataFrame(rows, columns=["query_id", "item_id", "rating", "timestamp"])


def make_dataset(log, query_features=None):
    schema = [
        FeatureInfo("query_id", FeatureType.CATEGORICAL, FeatureHint.QUERY_ID),
        FeatureInfo("item_id", FeatureType.CATEGORICAL, FeatureHint.ITEM_ID),
        FeatureInfo("rating", FeatureType.NUMERICAL, FeatureHint.RATING),
        FeatureInfo("timestamp", FeatureType.NUMERICAL, FeatureHint.TIMESTAMP),
    ]
    if query_features is not None:
        for column in query_features.columns:
            if column != "query_id":
                schema.append(
                    FeatureInfo(column, FeatureType.NUMERICAL,
                                feature_source=FeatureSource.QUERY_FEATURES)
                )
    return Dataset(
        feature_schema=FeatureSchema(schema), interactions=log, query_features=query_features
    )


@pytest.mark.parametrize("implicit", [True, False], ids=["implicit", "explicit"])
def test_als_learns_block_structure(implicit):
    log = block_log()
    model = ALS(rank=4, implicit_prefs=implicit, num_iterations=8, seed=0)
    recs = model.fit_predict(make_dataset(log), k=3)
    # recommendations stay within the user's taste group overwhelmingly
    in_group = 0
    for _, row in recs.iterrows():
        group = row["query_id"] // 8
        in_group += group * 10 <= row["item_id"] < (group + 1) * 10
    assert in_group / len(recs) > 0.8
    assert model.user_factors.shape == (16, 4) and model.item_factors.shape == (20, 4)


def test_als_save_load(tmp_path):
    model = ALS(rank=4, num_iterations=4, seed=0)
    dataset = make_dataset(block_log())
    before = model.fit_predict(dataset, k=2)
    model.save(str(tmp_path / "als"))
    after = ALS.load(str(tmp_path / "als")).predict(dataset, k=2)
    pd.testing.assert_frame_equal(before.reset_index(drop=True), after.reset_index(drop=True))


def test_slim_learns_cooccurrence():
    model = SLIM(beta=0.01, lambda_=0.001, num_iterations=200)
    recs = model.fit_predict(make_dataset(block_log()), k=2)
    in_group = np.mean(
        [(row["query_id"] // 8) * 10 <= row["item_id"] < (row["query_id"] // 8 + 1) * 10
         for _, row in recs.iterrows()]
    )
    assert in_group > 0.8
    # diagonal is zero and weights are non-negative (SLIM constraints)
    assert (np.diag(model.similarity) == 0).all()
    assert (model.similarity >= 0).all()


def test_word2vec_group_similarity():
    model = Word2VecRec(rank=16, num_iterations=80, window_size=3, seed=0)
    model.fit(make_dataset(block_log(num_users=32)))
    vectors = model.item_vectors / np.linalg.norm(model.item_vectors, axis=1, keepdims=True)
    sims = vectors @ vectors.T
    within = np.mean([sims[i, j] for i in range(10) for j in range(10) if i != j])
    across = np.mean([sims[i, j] for i in range(10) for j in range(10, 20)])
    assert within > across
    recs = model.predict(make_dataset(block_log(num_users=32)), k=2)
    assert (recs.groupby("query_id").size() <= 2).all()


def test_cluster_rec():
    log = block_log()
    query_features = pd.DataFrame(
        {"query_id": np.arange(16), "feat": np.where(np.arange(16) < 8, 0.0, 10.0)}
    )
    dataset = make_dataset(log, query_features)
    model = ClusterRec(num_clusters=2, seed=0)
    recs = model.fit_predict(dataset, k=2, filter_seen_items=False)
    for _, row in recs.iterrows():
        group = row["query_id"] // 8
        assert group * 10 <= row["item_id"] < (group + 1) * 10
    with pytest.raises(ValueError, match="query_features"):
        ClusterRec().fit(make_dataset(log))


def test_lin_ucb():
    # context dimension separates the groups: reward = context matches item group
    log = block_log()
    query_features = pd.DataFrame(
        {"query_id": np.arange(16), "bias": 1.0,
         "taste": np.where(np.arange(16) < 8, -1.0, 1.0)}
    )
    dataset = make_dataset(log, query_features)
    model = LinUCB(alpha=0.1).fit(dataset)
    recs = model.predict(dataset, k=3, filter_seen_items=False)
    in_group = np.mean(
        [(row["query_id"] // 8) * 10 <= row["item_id"] < (row["query_id"] // 8 + 1) * 10
         for _, row in recs.iterrows()]
    )
    assert in_group > 0.7
    model.save(str(__import__("tempfile").mkdtemp() + "/linucb"))

def _hybrid_dataset():
    log = block_log()
    query_features = pd.DataFrame(
        {"query_id": np.arange(16), "bias": 1.0,
         "taste": np.where(np.arange(16) < 8, -1.0, 1.0)}
    )
    item_features = pd.DataFrame(
        {"item_id": np.arange(20),
         "group": np.where(np.arange(20) < 10, -1.0, 1.0),
         "pos": (np.arange(20) % 10) / 10.0}
    )
    schema = [
        FeatureInfo("query_id", FeatureType.CATEGORICAL, FeatureHint.QUERY_ID),
        FeatureInfo("item_id", FeatureType.CATEGORICAL, FeatureHint.ITEM_ID),
        FeatureInfo("rating", FeatureType.NUMERICAL, FeatureHint.RATING),
        FeatureInfo("timestamp", FeatureType.NUMERICAL, FeatureHint.TIMESTAMP),
        FeatureInfo("bias", FeatureType.NUMERICAL, feature_source=FeatureSource.QUERY_FEATURES),
        FeatureInfo("taste", FeatureType.NUMERICAL, feature_source=FeatureSource.QUERY_FEATURES),
        FeatureInfo("group", FeatureType.NUMERICAL, feature_source=FeatureSource.ITEM_FEATURES),
        FeatureInfo("pos", FeatureType.NUMERICAL, feature_source=FeatureSource.ITEM_FEATURES),
    ]
    return Dataset(
        feature_schema=FeatureSchema(schema), interactions=log,
        query_features=query_features, item_features=item_features,
    ), log, query_features, item_features


def test_lin_ucb_hybrid_matches_naive_reference(tmp_path):
    """The Kronecker-structured batched hybrid solve equals a direct per-arm
    transcription of Li et al. Algorithm 2 (ref models/lin_ucb.py:56-97,242-288)."""
    dataset, log, query_features, item_features = _hybrid_dataset()
    model = LinUCB(alpha=0.7, reg=1.3, is_hybrid=True).fit(dataset)

    X_all = query_features[["bias", "taste"]].to_numpy(float)
    F_all = item_features[["group", "pos"]].to_numpy(float)
    d, d_item = X_all.shape[1], F_all.shape[1]
    k = d * d_item
    n_items = len(model.fit_items)
    item_pos = {item: i for i, item in enumerate(model.fit_items)}

    # --- naive per-arm accumulation (scipy-free transcription) ---
    A = [1.3 * np.eye(d) for _ in range(n_items)]
    B = [np.zeros((d, k)) for _ in range(n_items)]
    b = [np.zeros(d) for _ in range(n_items)]
    A0 = np.eye(k)
    b0 = np.zeros(k)
    for i, item in enumerate(model.fit_items):
        sub = log[log.item_id == item]
        if sub.empty:
            continue
        X = X_all[sub.query_id.to_numpy()]
        r = sub.rating.to_numpy(float)
        Z = np.stack([np.kron(x, F_all[item]) for x in X])
        A[i] += X.T @ X
        B[i] += X.T @ Z
        b[i] += X.T @ r
        A0 += Z.T @ Z - B[i].T @ np.linalg.inv(A[i]) @ B[i]
        b0 += Z.T @ r - B[i].T @ np.linalg.inv(A[i]) @ b[i]
    beta = np.linalg.solve(A0, b0)
    np.testing.assert_allclose(model.beta.reshape(-1), beta, rtol=1e-8, atol=1e-10)
    theta = [np.linalg.solve(A[i], b[i] - B[i] @ beta) for i in range(n_items)]
    np.testing.assert_allclose(model.theta, np.stack(theta), rtol=1e-8, atol=1e-10)

    # --- naive scores for a couple of users over all arms ---
    A0_inv = np.linalg.inv(A0)
    recs = model.predict(dataset, k=n_items, queries=[0, 9], filter_seen_items=False)
    for user in (0, 9):
        x = X_all[user]
        for i, item in enumerate(model.fit_items):
            A_inv = np.linalg.inv(A[i])
            z = np.kron(x, F_all[item])
            mean = x @ theta[i] + z @ beta
            s = x @ A_inv @ x + z @ A0_inv @ z
            s -= 2 * z @ A0_inv @ B[i].T @ A_inv @ x
            s += x @ A_inv @ B[i] @ A0_inv @ B[i].T @ A_inv @ x
            expected = mean + 0.7 * np.sqrt(max(s, 0.0))
            got = recs[(recs.query_id == user) & (recs.item_id == item)]["rating"].iloc[0]
            np.testing.assert_allclose(got, expected, rtol=1e-7, atol=1e-9)

    # save/load roundtrip keeps hybrid state
    model.save(str(tmp_path / "hybrid"))
    restored = LinUCB.load(str(tmp_path / "hybrid"))
    pd.testing.assert_frame_equal(
        model.predict(dataset, k=3).reset_index(drop=True),
        restored.predict(dataset, k=3).reset_index(drop=True),
    )


def test_lin_ucb_hybrid_needs_item_features():
    log = block_log()
    query_features = pd.DataFrame({"query_id": np.arange(16), "bias": 1.0})
    dataset = make_dataset(log, query_features)
    with pytest.raises(ValueError, match="item_features"):
        LinUCB(is_hybrid=True).fit(dataset)
