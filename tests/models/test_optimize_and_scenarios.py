"""HPO (random-search backend) and the Fallback scenario."""

import numpy as np
import pandas as pd
import pytest

from replay_tpu.data import Dataset, FeatureHint, FeatureInfo, FeatureSchema, FeatureType
from replay_tpu.models import ItemKNN, PopRec
from replay_tpu.scenarios import Fallback
from replay_tpu.splitters import RatioSplitter


def make_dataset(log):
    return Dataset(
        feature_schema=FeatureSchema(
            [
                FeatureInfo("query_id", FeatureType.CATEGORICAL, FeatureHint.QUERY_ID),
                FeatureInfo("item_id", FeatureType.CATEGORICAL, FeatureHint.ITEM_ID),
                FeatureInfo("rating", FeatureType.NUMERICAL, FeatureHint.RATING),
                FeatureInfo("timestamp", FeatureType.NUMERICAL, FeatureHint.TIMESTAMP),
            ]
        ),
        interactions=log,
    )


def grouped_log(num_users=20, group_size=8):
    rng = np.random.default_rng(0)
    rows = []
    for u in range(num_users):
        liked = np.arange(group_size) + (u % 2) * group_size
        for t, i in enumerate(rng.choice(liked, 5, replace=False)):
            rows.append((u, int(i), 1.0, t))
    return pd.DataFrame(rows, columns=["query_id", "item_id", "rating", "timestamp"])


def test_optimize_random_search():
    log = grouped_log()
    train, test = RatioSplitter(test_size=0.4, divide_column="query_id").split(log)
    model = ItemKNN()
    best = model.optimize(
        make_dataset(train), make_dataset(test), budget=4, k=3, seed=0
    )
    assert set(best) == {"num_neighbours", "shrink", "weighting"}
    # the winning params are applied and the model is refit
    assert model.num_neighbours == best["num_neighbours"]
    assert model.similarity is not None


def test_optimize_no_space_raises():
    with pytest.raises(ValueError, match="search space"):
        PopRec().optimize(make_dataset(grouped_log()), make_dataset(grouped_log()))


def test_optimize_tpe_itemknn():
    """The native TPE sampler drives the full optimize loop on a real model."""
    log = grouped_log()
    train, test = RatioSplitter(test_size=0.4, divide_column="query_id").split(log)
    model = ItemKNN()
    best = model.optimize(
        make_dataset(train), make_dataset(test), budget=8, k=3, seed=0, sampler="tpe"
    )
    assert set(best) == {"num_neighbours", "shrink", "weighting"}
    assert model.num_neighbours == best["num_neighbours"]
    assert model.similarity is not None


def test_optimize_unknown_sampler_raises():
    with pytest.raises(ValueError, match="sampler"):
        ItemKNN().optimize(
            make_dataset(grouped_log()), make_dataset(grouped_log()), sampler="grid"
        )


def _run_tpe(budget: int, seed: int) -> list:
    """Maximize a known objective over a mixed space; return trial values."""
    from replay_tpu.models.optimization import TPESampler

    space = {
        "x": {"type": "uniform", "args": [0.0, 1.0]},
        "lr": {"type": "loguniform", "args": [1e-4, 1.0]},
        "n": {"type": "int", "args": [1, 32]},
        "mode": {"type": "categorical", "args": ["a", "b", "c"]},
    }

    def objective(p):
        return (
            -((p["x"] - 0.73) ** 2)
            - (np.log10(p["lr"]) + 2.0) ** 2 * 0.1
            - abs(p["n"] - 20) * 0.01
            + (0.3 if p["mode"] == "b" else 0.0)
        )

    rng = np.random.default_rng(seed)
    tpe = TPESampler()
    history = []
    for _ in range(budget):
        params = tpe.suggest(rng, space, history)
        history.append((objective(params), params))
    return [v for v, _ in history]


def test_tpe_sampler_converges_1d():
    """On 1-D smooth objectives the Parzen machinery must actually converge —
    uniform, loguniform and categorical kinds each home in on the optimum."""
    from replay_tpu.models.optimization import TPESampler

    # uniform: maximize -(x - 0.73)^2
    rng = np.random.default_rng(2)
    tpe = TPESampler(explore=0.0)
    hist = []
    for _ in range(30):
        p = tpe.suggest(rng, {"x": {"type": "uniform", "args": [0.0, 1.0]}}, hist)
        hist.append((-((p["x"] - 0.73) ** 2), p))
    assert abs(max(hist)[1]["x"] - 0.73) < 0.05
    assert abs(np.mean([p["x"] for _, p in hist[-10:]]) - 0.73) < 0.1

    # loguniform: maximize -(log10(lr) + 2)^2, optimum lr = 1e-2
    rng = np.random.default_rng(3)
    hist = []
    for _ in range(30):
        p = tpe.suggest(rng, {"lr": {"type": "loguniform", "args": [1e-5, 1.0]}}, hist)
        hist.append((-((np.log10(p["lr"]) + 2.0) ** 2), p))
    assert abs(np.log10(max(hist)[1]["lr"]) + 2.0) < 0.5

    # categorical: +1 for 'b'; post-startup proposals lock onto it
    rng = np.random.default_rng(4)
    hist = []
    for _ in range(25):
        p = tpe.suggest(rng, {"m": {"type": "categorical", "args": ["a", "b", "c"]}}, hist)
        hist.append(((1.0 if p["m"] == "b" else 0.0), p))
    post = [p["m"] for _, p in hist[5:]]
    assert post.count("b") / len(post) > 0.8


def test_tpe_sampler_improves_over_startup():
    """On the mixed 4-d space the guided phase must (a) keep improving past the
    random startup and (b) concentrate: its mean objective beats the startup
    mean on every seed. (A best-of-N race against pure random is deliberately
    NOT asserted: at budget 30 on a bounded smooth objective, best-of-30 random
    is a near-optimal strategy — Bergstra & Bengio 2012 — and the outcome is a
    coin flip either way.)"""
    for seed in range(5):
        tpe_vals = _run_tpe(budget=30, seed=seed)
        # guided proposals find something strictly better than the best of the
        # random startup phase (trials 0-4)
        assert max(tpe_vals[5:]) > max(tpe_vals[:5])
        assert np.mean(tpe_vals[5:]) > np.mean(tpe_vals[:5])


def test_fallback_tops_up_sparse_main():
    log = grouped_log()
    dataset = make_dataset(log)
    # ItemKNN with tiny neighbourhood can return < k items for some users
    scenario = Fallback(main=ItemKNN(num_neighbours=1), fallback=PopRec())
    scenario.fit(dataset)
    recs = scenario.predict(dataset, k=5)
    per_user = recs.groupby("query_id").size()
    assert (per_user == 5).all()  # every user topped up to exactly k
    # seen items still filtered
    seen = set(map(tuple, log[["query_id", "item_id"]].to_numpy()))
    assert not seen.intersection(map(tuple, recs[["query_id", "item_id"]].to_numpy()))


def test_fallback_cold_query_served():
    dataset = make_dataset(grouped_log())
    scenario = Fallback(main=ItemKNN(num_neighbours=2)).fit(dataset)
    recs = scenario.predict(dataset, k=3, queries=[777], filter_seen_items=False)
    assert set(recs["query_id"]) == {777}
    assert len(recs) == 3  # fully served by the popularity fallback

def test_fallback_save_load_roundtrip(tmp_path):
    dataset = make_dataset(grouped_log())
    scenario = Fallback(main=ItemKNN(num_neighbours=3), fallback=PopRec()).fit(dataset)
    before = scenario.predict(dataset, k=4)
    scenario.save(str(tmp_path / "fb"))
    restored = Fallback.load(str(tmp_path / "fb"))
    after = restored.predict(dataset, k=4)
    pd.testing.assert_frame_equal(
        before.reset_index(drop=True), after.reset_index(drop=True)
    )
    assert type(restored.main).__name__ == "ItemKNN"
    assert type(restored.fallback).__name__ == "PopRec"
