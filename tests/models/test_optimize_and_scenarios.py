"""HPO (random-search backend) and the Fallback scenario."""

import numpy as np
import pandas as pd
import pytest

from replay_tpu.data import Dataset, FeatureHint, FeatureInfo, FeatureSchema, FeatureType
from replay_tpu.models import ItemKNN, PopRec
from replay_tpu.scenarios import Fallback
from replay_tpu.splitters import RatioSplitter


def make_dataset(log):
    return Dataset(
        feature_schema=FeatureSchema(
            [
                FeatureInfo("query_id", FeatureType.CATEGORICAL, FeatureHint.QUERY_ID),
                FeatureInfo("item_id", FeatureType.CATEGORICAL, FeatureHint.ITEM_ID),
                FeatureInfo("rating", FeatureType.NUMERICAL, FeatureHint.RATING),
                FeatureInfo("timestamp", FeatureType.NUMERICAL, FeatureHint.TIMESTAMP),
            ]
        ),
        interactions=log,
    )


def grouped_log(num_users=20, group_size=8):
    rng = np.random.default_rng(0)
    rows = []
    for u in range(num_users):
        liked = np.arange(group_size) + (u % 2) * group_size
        for t, i in enumerate(rng.choice(liked, 5, replace=False)):
            rows.append((u, int(i), 1.0, t))
    return pd.DataFrame(rows, columns=["query_id", "item_id", "rating", "timestamp"])


def test_optimize_random_search():
    log = grouped_log()
    train, test = RatioSplitter(test_size=0.4, divide_column="query_id").split(log)
    model = ItemKNN()
    best = model.optimize(
        make_dataset(train), make_dataset(test), budget=4, k=3, seed=0
    )
    assert set(best) == {"num_neighbours", "shrink", "weighting"}
    # the winning params are applied and the model is refit
    assert model.num_neighbours == best["num_neighbours"]
    assert model.similarity is not None


def test_optimize_no_space_raises():
    with pytest.raises(ValueError, match="search space"):
        PopRec().optimize(make_dataset(grouped_log()), make_dataset(grouped_log()))


def test_fallback_tops_up_sparse_main():
    log = grouped_log()
    dataset = make_dataset(log)
    # ItemKNN with tiny neighbourhood can return < k items for some users
    scenario = Fallback(main=ItemKNN(num_neighbours=1), fallback=PopRec())
    scenario.fit(dataset)
    recs = scenario.predict(dataset, k=5)
    per_user = recs.groupby("query_id").size()
    assert (per_user == 5).all()  # every user topped up to exactly k
    # seen items still filtered
    seen = set(map(tuple, log[["query_id", "item_id"]].to_numpy()))
    assert not seen.intersection(map(tuple, recs[["query_id", "item_id"]].to_numpy()))


def test_fallback_cold_query_served():
    dataset = make_dataset(grouped_log())
    scenario = Fallback(main=ItemKNN(num_neighbours=2)).fit(dataset)
    recs = scenario.predict(dataset, k=3, queries=[777], filter_seen_items=False)
    assert set(recs["query_id"]) == {777}
    assert len(recs) == 3  # fully served by the popularity fallback

def test_fallback_save_load_roundtrip(tmp_path):
    dataset = make_dataset(grouped_log())
    scenario = Fallback(main=ItemKNN(num_neighbours=3), fallback=PopRec()).fit(dataset)
    before = scenario.predict(dataset, k=4)
    scenario.save(str(tmp_path / "fb"))
    restored = Fallback.load(str(tmp_path / "fb"))
    after = restored.predict(dataset, k=4)
    pd.testing.assert_frame_equal(
        before.reset_index(drop=True), after.reset_index(drop=True)
    )
    assert type(restored.main).__name__ == "ItemKNN"
    assert type(restored.fallback).__name__ == "PopRec"
