"""Randomized property tests for the serving seams (VERDICT round-4 item 8).

Two invariants that single-case tests cannot pin down:

- ``CompiledInference`` bucket selection: every request size ≤ the largest
  bucket maps to the SMALLEST covering bucket, and the padded execution equals
  the uncompiled forward for every batch size (ref compiled-model contract,
  replay/models/nn/sequential/compiled/base_compiled_model.py:19-55).
- ``MIPSIndex`` shard-merge: mesh-sharded top-k == unsharded top-k for random
  catalogs, ks and query counts — including catalogs that do not divide the
  shard count (padding rows must never win).
"""

import numpy as np
import pytest

hypothesis = pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st

import jax

from replay_tpu.data import FeatureHint, FeatureType
from replay_tpu.data.nn import TensorFeatureInfo, TensorSchema
from replay_tpu.models import MIPSIndex
from replay_tpu.nn import make_mesh
from replay_tpu.nn.compiled import CompiledInference
from replay_tpu.nn.sequential.sasrec import SasRec

pytestmark = [pytest.mark.jax, pytest.mark.smoke]

NUM_ITEMS, SEQ_LEN = 20, 6


@settings(max_examples=60, deadline=None)
@given(
    buckets=st.lists(st.integers(min_value=1, max_value=512), min_size=1, max_size=6, unique=True),
    data=st.data(),
)
def test_bucket_selection_is_smallest_covering(buckets, data):
    """Pure bucket-routing invariant over random bucket sets and request sizes."""
    chooser = CompiledInference(dict.fromkeys(buckets), SEQ_LEN, "dynamic_batch_size")
    batch = data.draw(st.integers(min_value=1, max_value=max(buckets)))
    got = chooser._bucket_for(batch)
    assert got == min(b for b in buckets if b >= batch)
    oversized = max(buckets) + 1
    with pytest.raises(ValueError, match="largest compiled bucket"):
        chooser._bucket_for(oversized)


@pytest.fixture(scope="module")
def compiled_and_model():
    schema = TensorSchema(
        TensorFeatureInfo("item_id", FeatureType.CATEGORICAL, is_seq=True,
                          feature_hint=FeatureHint.ITEM_ID, cardinality=NUM_ITEMS,
                          embedding_dim=8)
    )
    model = SasRec(schema=schema, embedding_dim=8, num_blocks=1, max_sequence_length=SEQ_LEN)
    ids = np.zeros((2, SEQ_LEN), np.int32)
    params = model.init(jax.random.PRNGKey(0), {"item_id": ids},
                        np.ones((2, SEQ_LEN), bool))["params"]
    compiled = CompiledInference.compile(
        model, params, SEQ_LEN, mode="dynamic_batch_size", dynamic_buckets=(2, 3, 8)
    )
    return compiled, model, params


def test_every_batch_size_matches_uncompiled(compiled_and_model):
    """All sizes 1..max bucket run through padding and equal the plain forward —
    batches with padding rows, ragged masks, exact-bucket hits, everything."""
    compiled, model, params = compiled_and_model
    rng = np.random.default_rng(0)
    for batch in range(1, 9):
        ids = rng.integers(0, NUM_ITEMS, (batch, SEQ_LEN)).astype(np.int32)
        lengths = rng.integers(1, SEQ_LEN + 1, batch)
        mask = np.arange(SEQ_LEN)[None, :] >= (SEQ_LEN - lengths[:, None])
        got = compiled(ids, mask)
        assert got.shape == (batch, NUM_ITEMS)
        want = model.apply({"params": params}, {"item_id": ids}, mask,
                           method=SasRec.forward_inference)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-6)


@settings(max_examples=8, deadline=None)
@given(
    num_items=st.integers(min_value=9, max_value=70),
    dim=st.integers(min_value=2, max_value=12),
    num_queries=st.integers(min_value=1, max_value=6),
    k=st.integers(min_value=1, max_value=8),
    seed=st.integers(min_value=0, max_value=2**31 - 1),
)
def test_sharded_topk_equals_unsharded(num_items, dim, num_queries, k, seed):
    """Shard-merge invariant: per-shard top-k + global merge == brute force,
    for catalogs that mostly do NOT divide the 8-device mesh."""
    hypothesis.assume(k <= num_items)
    rng = np.random.default_rng(seed)
    items = rng.normal(size=(num_items, dim)).astype(np.float32)
    queries = rng.normal(size=(num_queries, dim)).astype(np.float32)
    s_scores, s_idx = MIPSIndex(items, mesh=make_mesh()).search(queries, k=k)
    brute = queries @ items.T
    want_idx = np.argsort(-brute, axis=1, kind="stable")[:, :k]
    # continuous gaussians: ties have measure zero, so indices match exactly
    np.testing.assert_array_equal(np.sort(s_idx, axis=1), np.sort(want_idx, axis=1))
    np.testing.assert_allclose(
        np.sort(s_scores, axis=1),
        np.sort(np.take_along_axis(brute, want_idx, 1), axis=1),
        rtol=1e-5,
    )
