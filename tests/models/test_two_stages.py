"""TwoStages scenario: generators -> features -> learned reranker."""

import numpy as np
import pandas as pd
import pytest

from replay_tpu.data import Dataset, FeatureHint, FeatureInfo, FeatureSchema, FeatureType
from replay_tpu.models import ALS, ItemKNN, PopRec
from replay_tpu.scenarios import TwoStages

pytestmark = pytest.mark.jax


def make_dataset():
    rng = np.random.default_rng(0)
    rows = []
    for u in range(24):
        liked = np.arange(10) + (u % 2) * 10
        for t, i in enumerate(rng.choice(liked, 6, replace=False)):
            rows.append((u, int(i), 1.0, t))
    log = pd.DataFrame(rows, columns=["query_id", "item_id", "rating", "timestamp"])
    return Dataset(feature_schema=FeatureSchema([
        FeatureInfo("query_id", FeatureType.CATEGORICAL, FeatureHint.QUERY_ID),
        FeatureInfo("item_id", FeatureType.CATEGORICAL, FeatureHint.ITEM_ID),
        FeatureInfo("rating", FeatureType.NUMERICAL, FeatureHint.RATING),
        FeatureInfo("timestamp", FeatureType.NUMERICAL, FeatureHint.TIMESTAMP)]),
        interactions=log)


def test_two_stages_end_to_end():
    dataset = make_dataset()
    scenario = TwoStages(
        first_level_models=[PopRec(), ItemKNN(num_neighbours=5),
                            ALS(rank=4, num_iterations=4, seed=0)],
        num_candidates=8,
        seed=1,
    )
    recs = scenario.fit(dataset).predict(dataset, k=3)
    assert set(recs.columns) >= {"query_id", "item_id", "rating"}
    assert (recs.groupby("query_id").size() <= 3).all()
    # probabilities in [0, 1] and no seen items
    assert recs["rating"].between(0, 1).all()
    seen = set(map(tuple, dataset.interactions[["query_id", "item_id"]].to_numpy()))
    assert not seen.intersection(map(tuple, recs[["query_id", "item_id"]].to_numpy()))
    # the trained reranker should keep in-group recommendations dominant
    in_group = np.mean(
        [(row["query_id"] % 2) * 10 <= row["item_id"] < (row["query_id"] % 2 + 1) * 10
         for _, row in recs.iterrows()]
    )
    assert in_group > 0.7
