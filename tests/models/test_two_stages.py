"""TwoStages scenario: generators -> features -> learned reranker."""

import numpy as np
import pandas as pd
import pytest

from replay_tpu.data import Dataset, FeatureHint, FeatureInfo, FeatureSchema, FeatureType
from replay_tpu.models import ALS, ItemKNN, PopRec
from replay_tpu.scenarios import TwoStages

pytestmark = pytest.mark.jax


def make_dataset():
    rng = np.random.default_rng(0)
    rows = []
    for u in range(24):
        liked = np.arange(10) + (u % 2) * 10
        for t, i in enumerate(rng.choice(liked, 6, replace=False)):
            rows.append((u, int(i), 1.0, t))
    log = pd.DataFrame(rows, columns=["query_id", "item_id", "rating", "timestamp"])
    return Dataset(feature_schema=FeatureSchema([
        FeatureInfo("query_id", FeatureType.CATEGORICAL, FeatureHint.QUERY_ID),
        FeatureInfo("item_id", FeatureType.CATEGORICAL, FeatureHint.ITEM_ID),
        FeatureInfo("rating", FeatureType.NUMERICAL, FeatureHint.RATING),
        FeatureInfo("timestamp", FeatureType.NUMERICAL, FeatureHint.TIMESTAMP)]),
        interactions=log)


def test_two_stages_end_to_end():
    dataset = make_dataset()
    scenario = TwoStages(
        first_level_models=[PopRec(), ItemKNN(num_neighbours=5),
                            ALS(rank=4, num_iterations=4, seed=0)],
        num_candidates=8,
        seed=1,
    )
    recs = scenario.fit(dataset).predict(dataset, k=3)
    assert set(recs.columns) >= {"query_id", "item_id", "rating"}
    assert (recs.groupby("query_id").size() <= 3).all()
    # probabilities in [0, 1] and no seen items
    assert recs["rating"].between(0, 1).all()
    seen = set(map(tuple, dataset.interactions[["query_id", "item_id"]].to_numpy()))
    assert not seen.intersection(map(tuple, recs[["query_id", "item_id"]].to_numpy()))
    # the trained reranker should keep in-group recommendations dominant
    in_group = np.mean(
        [(row["query_id"] % 2) * 10 <= row["item_id"] < (row["query_id"] % 2 + 1) * 10
         for _, row in recs.iterrows()]
    )
    assert in_group > 0.7


def test_reranker_adds_value_over_weak_generator():
    """The learned reranker must BEAT the candidate generator's own ordering on
    held-out data — the quality claim of the scenario, not just its plumbing.

    Setup: a RandomRec generator surfaces candidates with meaningless scores;
    the HistoryBasedFeaturesProcessor popularity features are predictive
    (preferences follow global popularity), so logistic reranking should
    recover the popular-first ordering the generator scrambles.
    """
    from replay_tpu.metrics import NDCG
    from replay_tpu.models import RandomRec

    rng = np.random.default_rng(7)
    n_users, n_items = 40, 24
    popularity = np.linspace(1.0, 0.05, n_items)
    rows = []
    for u in range(n_users):
        p = popularity / popularity.sum()
        chosen = rng.choice(n_items, size=8, replace=False, p=p)
        for t, i in enumerate(chosen):
            rows.append((u, int(i), 1.0, t))
    log = pd.DataFrame(rows, columns=["query_id", "item_id", "rating", "timestamp"])
    train = log.groupby("query_id").head(6)
    test = log.groupby("query_id").tail(2)
    schema = FeatureSchema([
        FeatureInfo("query_id", FeatureType.CATEGORICAL, FeatureHint.QUERY_ID),
        FeatureInfo("item_id", FeatureType.CATEGORICAL, FeatureHint.ITEM_ID),
        FeatureInfo("rating", FeatureType.NUMERICAL, FeatureHint.RATING),
        FeatureInfo("timestamp", FeatureType.NUMERICAL, FeatureHint.TIMESTAMP)])
    dataset = Dataset(feature_schema=schema, interactions=train)

    generator = RandomRec(seed=3)
    scenario = TwoStages(
        first_level_models=[RandomRec(seed=3)], num_candidates=16, seed=1,
    )
    scenario.fit(dataset)
    reranked = scenario.predict(dataset, k=8)
    generator_only = generator.fit(dataset).predict(dataset, k=8)

    truth = {u: g["item_id"].tolist() for u, g in test.groupby("query_id")}
    metric = NDCG([8])

    def score(recs):
        frame = {u: g["item_id"].tolist() for u, g in recs.groupby("query_id")}
        return metric(frame, truth)["NDCG@8"]

    assert score(reranked) > score(generator_only) * 1.3
