import numpy as np
import pytest

from replay_tpu.data import FeatureHint, FeatureSource, FeatureType
from replay_tpu.data.nn import TensorFeatureInfo, TensorFeatureSource, TensorSchema

NUM_ITEMS = 20
SEQ_LEN = 8
BATCH = 4


@pytest.fixture
def tensor_schema() -> TensorSchema:
    return TensorSchema(
        [
            TensorFeatureInfo(
                "item_id",
                FeatureType.CATEGORICAL,
                is_seq=True,
                feature_hint=FeatureHint.ITEM_ID,
                feature_sources=[TensorFeatureSource(FeatureSource.INTERACTIONS, "item_id")],
                cardinality=NUM_ITEMS,
                padding_value=NUM_ITEMS,
                embedding_dim=16,
            ),
            TensorFeatureInfo(
                "cat_feature",
                FeatureType.CATEGORICAL,
                is_seq=True,
                cardinality=5,
                padding_value=5,
                embedding_dim=16,
            ),
            TensorFeatureInfo(
                "num_feature",
                FeatureType.NUMERICAL,
                is_seq=True,
                tensor_dim=1,
                embedding_dim=16,
            ),
        ]
    )


@pytest.fixture
def item_only_schema() -> TensorSchema:
    return TensorSchema(
        TensorFeatureInfo(
            "item_id",
            FeatureType.CATEGORICAL,
            is_seq=True,
            feature_hint=FeatureHint.ITEM_ID,
            cardinality=NUM_ITEMS,
            padding_value=NUM_ITEMS,
            embedding_dim=16,
        )
    )


@pytest.fixture
def batch(rng):
    lengths = rng.integers(2, SEQ_LEN + 1, size=BATCH)
    items = np.full((BATCH, SEQ_LEN), NUM_ITEMS, dtype=np.int64)  # left-padded
    for b, n in enumerate(lengths):
        items[b, SEQ_LEN - n :] = rng.integers(0, NUM_ITEMS, size=n)
    padding_mask = items != NUM_ITEMS
    features = {
        "item_id": items,
        "cat_feature": np.where(padding_mask, rng.integers(0, 5, size=items.shape), 5),
        "num_feature": rng.normal(size=(BATCH, SEQ_LEN)).astype(np.float32),
    }
    return features, padding_mask
