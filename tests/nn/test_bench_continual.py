"""bench_continual.py emits one parseable JSON record: continual (tail
fine-tune with mid-stream catalog growth) vs full-retrain NDCG, prequentially
scored on the next day's events."""

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.jax

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def test_bench_continual_one_json_line(tmp_path):
    env = {
        **os.environ,
        "PYTHONPATH": os.pathsep.join(
            p for p in os.environ.get("PYTHONPATH", "").split(os.pathsep)
            if p and ".axon_site" not in p
        ),
        "JAX_PLATFORMS": "cpu",
        "REPLAY_TPU_CONTINUAL_FALLBACK": "1",  # skip the backend probe
        "REPLAY_TPU_CONTINUAL_DAYS": "3",
        "REPLAY_TPU_CONTINUAL_USERS": "24",
        "REPLAY_TPU_CONTINUAL_ITEMS": "24",
        "REPLAY_TPU_CONTINUAL_GROW_ITEMS": "8",
        "REPLAY_TPU_CONTINUAL_GROW_EVERY": "2",
        "REPLAY_TPU_CONTINUAL_SEQ_LEN": "8",
        "REPLAY_TPU_CONTINUAL_EMBEDDING_DIM": "8",
        "REPLAY_TPU_CONTINUAL_BATCH": "16",
        "REPLAY_TPU_CONTINUAL_TAIL_EPOCHS": "1",
        "REPLAY_TPU_CONTINUAL_RETRAIN_EPOCHS": "1",
    }
    out = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "bench_continual.py")],
        capture_output=True,
        timeout=300,
        env=env,
        cwd=str(tmp_path),
        check=False,
    )
    assert out.returncode == 0, out.stderr.decode()
    record = json.loads(out.stdout.decode().strip().splitlines()[-1])
    assert record["metric"] == "continual_vs_retrain_ndcg_cpu_fallback"
    assert record["unit"] == "ratio"
    assert record["value"] is not None and record["value"] > 0
    for key in ("continual_ndcg", "retrain_ndcg"):
        assert 0.0 <= record[key] <= 1.0, key
    assert record["continual_fit_seconds"] > 0
    assert record["retrain_fit_seconds"] > 0
    # the catalog actually GREW mid-stream (day 2 is a growth day) and the
    # continual model absorbed it via optimizer-state-safe surgery
    assert record["catalog_end"] > record["catalog_start"]
    assert len(record["per_day"]) == 2
    assert record["shape_override"]["days"] == 3
