"""BERT4Rec: MLM training through the trainer, mask-append inference."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from replay_tpu.data import FeatureHint, FeatureType
from replay_tpu.data.nn import TensorFeatureInfo, TensorSchema
from replay_tpu.nn import OptimizerFactory, Trainer, make_mesh
from replay_tpu.nn.loss import CE
from replay_tpu.nn.sequential.bert4rec import Bert4Rec
from replay_tpu.nn.transform import Compose
from replay_tpu.nn.transform.template import make_default_bert4rec_transforms

NUM_ITEMS = 12
SEQ_LEN = 8
BATCH = 8


@pytest.fixture(scope="module")
def schema() -> TensorSchema:
    return TensorSchema(
        TensorFeatureInfo(
            "item_id",
            FeatureType.CATEGORICAL,
            is_seq=True,
            feature_hint=FeatureHint.ITEM_ID,
            cardinality=NUM_ITEMS,
            embedding_dim=16,
        )
    )


def make_raw_batch(rng: np.random.Generator):
    """Cyclic next-item pattern (learnable bidirectionally)."""
    lengths = rng.integers(4, SEQ_LEN + 1, size=BATCH)
    items = np.full((BATCH, SEQ_LEN), NUM_ITEMS, dtype=np.int32)
    for b, n in enumerate(lengths):
        start = rng.integers(0, NUM_ITEMS)
        items[b, SEQ_LEN - n :] = (start + np.arange(n)) % NUM_ITEMS
    return {"item_id": items, "item_id_mask": items != NUM_ITEMS}


@pytest.fixture(scope="module")
def trained(schema):
    rng = np.random.default_rng(0)
    pipeline = Compose(make_default_bert4rec_transforms(schema, mask_prob=0.3)["train"])
    model = Bert4Rec(schema=schema, embedding_dim=16, num_blocks=1, num_heads=2,
                     max_sequence_length=SEQ_LEN)
    trainer = Trainer(model=model, loss=CE(),
                      optimizer=OptimizerFactory(learning_rate=1e-2), mesh=make_mesh())
    key = jax.random.PRNGKey(0)
    state, losses = None, []
    raw_batches = [make_raw_batch(rng) for _ in range(6)]
    for epoch in range(20):
        for raw in raw_batches:
            key, sub = jax.random.split(key)
            batch = pipeline(dict(raw), sub)
            if state is None:
                state = trainer.init_state(batch)
            state, loss_value = trainer.train_step(state, batch)
            losses.append(float(loss_value))
    return trainer, state, losses, raw_batches


@pytest.mark.jax
def test_mlm_batch_contract(schema):
    rng = np.random.default_rng(1)
    raw = make_raw_batch(rng)
    batch = Compose(make_default_bert4rec_transforms(schema, mask_prob=0.3)["train"])(
        raw, jax.random.PRNGKey(1)
    )
    assert batch["positive_labels"].shape == (BATCH, SEQ_LEN, 1)
    assert batch["target_padding_mask"].shape == (BATCH, SEQ_LEN, 1)
    target = np.asarray(batch["target_padding_mask"][..., 0])
    token_mask = np.asarray(batch["token_mask"])
    padding = np.asarray(batch["padding_mask"])
    # targets are exactly the masked-out REAL positions
    np.testing.assert_array_equal(target, padding & ~token_mask)
    assert target.any()  # something is masked
    # token_mask is False somewhere real, and padding slots are never targets
    assert not target[~padding].any()


@pytest.mark.jax
def test_mlm_loss_decreases(trained):
    _, _, losses, _ = trained
    assert np.mean(losses[-12:]) < np.mean(losses[:12]) * 0.7


@pytest.mark.jax
def test_inference_shapes_and_quality(trained):
    trainer, state, _, raw_batches = trained
    raw = raw_batches[0]
    batch = {
        "feature_tensors": {"item_id": raw["item_id"]},
        "padding_mask": raw["item_id_mask"],
    }
    logits = trainer.predict_logits(state, batch)
    assert logits.shape == (BATCH, NUM_ITEMS)
    # candidate scoring agrees with full-catalog scoring
    candidates = jnp.array([0, 3, 7])
    restricted = trainer.predict_logits(state, batch, candidates)
    np.testing.assert_allclose(
        np.asarray(restricted), np.asarray(logits)[:, [0, 3, 7]], rtol=1e-5
    )
    # the learned cyclic pattern: true next item should rank in the top 3 usually
    last_real = raw["item_id"][np.arange(BATCH), -1]
    expected_next = (last_real + 1) % NUM_ITEMS
    top3 = np.asarray(jax.lax.top_k(logits, 3)[1])
    hit = np.mean([expected_next[b] in top3[b] for b in range(BATCH)])
    assert hit >= 0.5, f"top-3 hit rate {hit}"
