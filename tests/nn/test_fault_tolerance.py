"""Fault-tolerant training: every recovery path exercised deterministically.

The resilience layer (docs/robustness.md) under injected faults from
``replay_tpu.utils.faults`` on the 8-device virtual CPU mesh:

* the in-jit non-finite sentinel skips NaN batches bit-for-bit and reports the
  exact injected step indices through ``on_anomaly`` events;
* ``RecoveryPolicy`` rolls back to the last checkpoint with LR backoff, bounded
  by its max-restarts budget;
* a real SIGTERM mid-epoch checkpoints at the step boundary, and
  ``fit(resume=True)`` reproduces the uninterrupted run's final loss and
  parameters bit-for-bit (the acceptance gate for this layer).

The smoke tests double as the CI artifact source: their ``events.jsonl``
(anomaly + recovery events) lands in ``REPLAY_TPU_RUN_DIR`` and ships from the
``jax and smoke`` workflow job.
"""

import json
import os

import numpy as np
import pytest

import jax

from replay_tpu.data import FeatureHint, FeatureType
from replay_tpu.data.nn import TensorFeatureInfo, TensorSchema
from replay_tpu.nn import OptimizerFactory, RecoveryPolicy, Trainer, make_mesh
from replay_tpu.nn.loss import CE
from replay_tpu.nn.sequential.sasrec import SasRec
from replay_tpu.obs import JsonlLogger
from replay_tpu.utils.checkpoint import CheckpointManager
from replay_tpu.utils.faults import NaNInjector, SignalAtStep, inject_nan, truncate_file

NUM_ITEMS = 12
SEQ_LEN = 8
BATCH = 8  # divisible by the 8-device data axis


def _run_dir(tmp_path, name):
    """CI exports REPLAY_TPU_RUN_DIR so the smoke run's recovery telemetry
    ships as a workflow artifact; locally the run log lands in tmp_path."""
    base = os.environ.get("REPLAY_TPU_RUN_DIR")
    return os.path.join(base, name) if base else str(tmp_path / name)


def make_schema() -> TensorSchema:
    # the numerical feature is the NaN-injection surface: integer ids cannot
    # carry a NaN, a poisoned float feature drives loss AND grads non-finite
    return TensorSchema(
        [
            TensorFeatureInfo(
                "item_id",
                FeatureType.CATEGORICAL,
                is_seq=True,
                feature_hint=FeatureHint.ITEM_ID,
                cardinality=NUM_ITEMS,
                embedding_dim=16,
            ),
            TensorFeatureInfo(
                "num_feature", FeatureType.NUMERICAL, is_seq=True, tensor_dim=1,
                embedding_dim=16,
            ),
        ]
    )


def make_batch(seed: int) -> dict:
    rng = np.random.default_rng(seed)
    items = rng.integers(0, NUM_ITEMS, size=(BATCH, SEQ_LEN + 1)).astype(np.int32)
    mask = np.ones((BATCH, SEQ_LEN), dtype=bool)
    return {
        "feature_tensors": {
            "item_id": items[:, :-1],
            "num_feature": rng.normal(size=(BATCH, SEQ_LEN)).astype(np.float32),
        },
        "padding_mask": mask,
        "positive_labels": items[:, 1:, None],
        "target_padding_mask": mask[:, :, None],
    }


def make_trainer() -> Trainer:
    model = SasRec(
        schema=make_schema(), embedding_dim=16, num_blocks=1, num_heads=1,
        max_sequence_length=SEQ_LEN,
    )
    return Trainer(
        model=model, loss=CE(), optimizer=OptimizerFactory(learning_rate=1e-2),
        mesh=make_mesh(),
    )


class EventSink:
    def __init__(self):
        self.events = []

    def log_event(self, event):
        self.events.append(event)

    def named(self, name):
        return [e for e in self.events if e.event == name]


def assert_trees_equal(a, b):
    jax.tree.map(
        lambda x, y: np.testing.assert_array_equal(np.asarray(x), np.asarray(y)), a, b
    )


# --------------------------------------------------------------------------- #
# non-finite sentinel
# --------------------------------------------------------------------------- #
@pytest.mark.jax
@pytest.mark.smoke
def test_sentinel_keeps_state_bit_for_bit_on_nan_batch():
    """A NaN batch must not move a single parameter or optimizer bit; step and
    rng still advance so the batch-stream alignment survives."""
    trainer = make_trainer()
    state = trainer.init_state(make_batch(0))
    state, _ = trainer.train_step(state, make_batch(0))
    params_before = jax.tree.map(np.asarray, state.params)
    opt_before = jax.tree.map(np.asarray, state.opt_state)
    rng_before = np.asarray(state.rng)

    state, loss = trainer.train_step(state, inject_nan(make_batch(1)))
    assert not np.isfinite(float(loss))
    assert not bool(trainer.last_step_metrics["good"])
    assert not np.isfinite(float(trainer.last_step_metrics["grad_norm"]))
    assert_trees_equal(params_before, state.params)
    assert_trees_equal(opt_before, state.opt_state)
    assert int(state.step) == 2  # the skipped step still consumed a step id
    assert int(state.bad_steps) == 1
    assert not np.array_equal(rng_before, np.asarray(state.rng))  # rng advanced

    # and training continues finite right after the poisoned batch
    state, loss = trainer.train_step(state, make_batch(2))
    assert np.isfinite(float(loss))
    assert int(state.bad_steps) == 1


@pytest.mark.jax
@pytest.mark.smoke
def test_nan_injection_reports_exact_steps_and_finishes_finite(tmp_path):
    """Acceptance: a seeded run injected with NaN batches at fixed steps ends
    with finite loss and on_anomaly events at exactly the injected indices."""
    injector = NaNInjector(at_steps=(2, 5))  # 0-based global batch positions
    trainer = make_trainer()
    run_dir = _run_dir(tmp_path, "fault_smoke")
    # mode="w": REPLAY_TPU_RUN_DIR is a fixed path in CI — a re-run must not
    # append a second event stream and break the counts below
    with JsonlLogger(run_dir, mode="w") as sink:
        state = trainer.fit(
            lambda epoch: injector.wrap([make_batch(epoch * 10 + i) for i in range(4)]),
            epochs=2,
            loggers=sink,
        )

    assert injector.injected_at == [2, 5]
    assert int(state.bad_steps) == 2
    lines = [json.loads(line) for line in open(os.path.join(run_dir, "events.jsonl"))]
    anomalies = [line for line in lines if line["event"] == "on_anomaly"]
    # state.step is 1-based: global batch positions 2 and 5 are steps 3 and 6
    assert [a["step"] for a in anomalies] == [3, 6]
    assert all(a["loss"] is None for a in anomalies)  # non-finite → JSON null
    steps = [line for line in lines if line["event"] == "on_train_step"]
    assert len(steps) == 8
    bad = {s["step"]: s for s in steps if s["loss"] is None}
    assert sorted(bad) == [3, 6]  # only the injected steps lost their loss
    # the epoch records average sentinel-approved steps only: finite throughout
    assert all(np.isfinite(r["train_loss"]) for r in trainer.history)
    fit_end = lines[-1]
    assert fit_end["event"] == "on_fit_end" and fit_end["bad_steps"] == 2


@pytest.mark.jax
def test_detect_anomalies_defaults_off_without_loggers_or_recovery():
    """log_every-only runs stay per-step-sync-free: no anomaly events, but the
    sentinel still protects the state and counts the skipped step."""
    injector = NaNInjector(at_steps=(1,))
    trainer = make_trainer()
    state = trainer.fit(
        lambda epoch: injector.wrap([make_batch(i) for i in range(3)]), epochs=1,
    )
    assert int(state.bad_steps) == 1
    assert np.isfinite(trainer.history[-1]["train_loss"])


# --------------------------------------------------------------------------- #
# RecoveryPolicy
# --------------------------------------------------------------------------- #
@pytest.mark.jax
@pytest.mark.smoke
def test_recovery_rolls_back_to_checkpoint_with_lr_backoff(tmp_path):
    injector = NaNInjector(at_steps=(3, 4, 5))  # >= max_consecutive_bad in a row
    trainer = make_trainer()
    manager = CheckpointManager(str(tmp_path / "run"), max_to_keep=10)
    sink = EventSink()
    state = trainer.fit(
        lambda epoch: injector.wrap([make_batch(i) for i in range(8)]),
        epochs=1,
        checkpoint_manager=manager,
        checkpoint_every=2,
        recovery=RecoveryPolicy(max_consecutive_bad=3, max_restarts=2, lr_backoff=0.5),
        loggers=sink,
    )
    assert len(sink.named("on_anomaly")) == 3
    recoveries = sink.named("on_recovery")
    assert len(recoveries) == 1
    payload = recoveries[0].payload
    # checkpoint_every=2 saved steps 2 and 4 before the third bad step hit;
    # sentinel-protected, so even the step-4 checkpoint holds good params
    assert payload["reason"] == "consecutive_bad_steps"
    assert payload["restored_step"] == 4
    assert payload["lr_scale"] == pytest.approx(0.5)
    assert trainer._lr_scale == pytest.approx(0.5)
    assert np.isfinite(trainer.history[-1]["train_loss"])
    assert int(state.step) > 4  # training continued past the rollback


@pytest.mark.jax
def test_recovery_budget_exhausted_raises():
    """Restarts are bounded: a run that keeps producing bad steps raises
    instead of burning the remaining budget (no checkpoint manager → rollback
    targets the initial-state snapshot)."""
    injector = NaNInjector(at_steps=range(2, 10))
    trainer = make_trainer()
    with pytest.raises(RuntimeError, match="budget exhausted"):
        trainer.fit(
            lambda epoch: injector.wrap([make_batch(i) for i in range(12)]),
            epochs=1,
            recovery=RecoveryPolicy(max_consecutive_bad=2, max_restarts=1),
        )


@pytest.mark.jax
def test_recovery_metric_blowup_triggers_rollback(tmp_path):
    """An epoch whose monitored loss goes non-finite (every step sentinel-
    skipped → nothing measured) rolls back at the epoch boundary instead of
    checkpointing the diverged epoch — max_consecutive_bad is set high enough
    that the per-step trigger stays out of the way."""
    injector = NaNInjector(at_steps=(3, 4, 5))  # all of epoch 1's batches

    def train_batches(epoch: int):
        return injector.wrap([make_batch(epoch * 10 + i) for i in range(3)])

    trainer = make_trainer()
    manager = CheckpointManager(str(tmp_path / "run"), max_to_keep=10)
    sink = EventSink()
    trainer.fit(
        train_batches,
        epochs=3,
        checkpoint_manager=manager,
        monitor="train_loss",
        mode="min",
        recovery=RecoveryPolicy(max_consecutive_bad=10, max_restarts=2, blowup_factor=1.5),
        loggers=sink,
    )
    recoveries = sink.named("on_recovery")
    assert len(recoveries) == 1
    assert recoveries[0].payload["reason"] == "metric_blowup"
    assert recoveries[0].epoch == 1
    # the poisoned epoch's record is in history (NaN), but the diverged epoch
    # never became a checkpoint: the rollback target was epoch 0's save
    assert recoveries[0].payload["restored_step"] == 3
    assert not np.isfinite(trainer.history[1]["train_loss"])
    assert np.isfinite(trainer.history[-1]["train_loss"])


@pytest.mark.jax
def test_recovery_triggers_even_with_detect_anomalies_off():
    """detect_anomalies=False silences the on_anomaly events, never the
    rollback trigger: the policy still counts bad steps and still bounds the
    restart budget."""
    injector = NaNInjector(at_steps=range(2, 10))
    trainer = make_trainer()
    sink = EventSink()
    with pytest.raises(RuntimeError, match="budget exhausted"):
        trainer.fit(
            lambda epoch: injector.wrap([make_batch(i) for i in range(12)]),
            epochs=1,
            recovery=RecoveryPolicy(max_consecutive_bad=2, max_restarts=1),
            detect_anomalies=False,
            loggers=sink,
        )
    assert sink.named("on_anomaly") == []  # silenced
    assert len(sink.named("on_recovery")) == 2  # trigger + exhausted


@pytest.mark.jax
def test_recovery_policy_validates():
    with pytest.raises(ValueError, match="max_consecutive_bad"):
        RecoveryPolicy(max_consecutive_bad=0)
    with pytest.raises(ValueError, match="lr_backoff"):
        RecoveryPolicy(lr_backoff=0.0)
    with pytest.raises(ValueError, match="blowup_factor"):
        RecoveryPolicy(blowup_factor=1.0)


# --------------------------------------------------------------------------- #
# preemption
# --------------------------------------------------------------------------- #
@pytest.mark.jax
@pytest.mark.smoke
def test_sigterm_mid_epoch_then_resume_is_bit_for_bit(tmp_path):
    """Acceptance: SIGTERM mid-epoch → position-stamped checkpoint + clean
    exit; fit(resume=True) reproduces the uninterrupted run's final loss and
    parameters bit-for-bit."""

    def stream(epoch: int):
        return [make_batch(epoch * 100 + i) for i in range(5)]

    trainer_a = make_trainer()
    manager_a = CheckpointManager(str(tmp_path / "a"), max_to_keep=100)
    state_a = trainer_a.fit(stream, epochs=2, checkpoint_manager=manager_a)

    # the signal fires through the real OS machinery while batch 2 is fetched
    trainer_b = make_trainer()
    manager_b = CheckpointManager(str(tmp_path / "b"), max_to_keep=100)
    sig = SignalAtStep(2)
    sink = EventSink()
    state_mid = trainer_b.fit(
        lambda epoch: sig.wrap(stream(epoch)), epochs=2,
        checkpoint_manager=manager_b, loggers=sink,
    )
    assert sig.raised
    assert int(state_mid.step) < int(state_a.step)
    preempt = sink.named("on_preemption")
    assert len(preempt) == 1 and preempt[0].payload["signal"] == "SIGTERM"
    assert sink.events[-1].event == "on_fit_end" and sink.events[-1].payload["preempted"]
    meta = manager_b.metadata(manager_b.latest_step())
    assert meta["preempted"] and meta["mid_epoch"] and meta["epoch"] == 0

    # a fresh process resumes from the preemption checkpoint
    trainer_c = make_trainer()
    state_c = trainer_c.fit(stream, epochs=2, checkpoint_manager=manager_b, resume=True)
    assert int(state_c.step) == int(state_a.step)
    assert_trees_equal(state_a.params, state_c.params)
    assert_trees_equal(state_a.opt_state, state_c.opt_state)
    np.testing.assert_array_equal(np.asarray(state_a.rng), np.asarray(state_c.rng))
    # the final (fully-measured) epoch's loss is bit-identical
    assert trainer_a.history[-1]["train_loss"] == trainer_c.history[-1]["train_loss"]


@pytest.mark.jax
def test_preemption_saves_trace_and_flight_ring(tmp_path, monkeypatch):
    """A preempted traced fit must not lose its span tree: ``trace.json`` is
    flushed eagerly at the ``on_preemption`` emission — BEFORE the shutdown-
    window checkpoint save, so even a save that dies cannot take the trace
    with it — and the flight ring (``REPLAY_TPU_FLIGHT_PATH``) holds the
    preemption as its final records."""
    from replay_tpu.obs.blackbox import read_flight
    from replay_tpu.obs.report import load_trace_events

    trace_path = str(tmp_path / "trace.json")
    ring_path = str(tmp_path / "flight.ring")
    monkeypatch.setenv("REPLAY_TPU_FLIGHT_PATH", ring_path)

    sig = SignalAtStep(2)
    trainer = make_trainer()
    manager = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=100)
    state = trainer.fit(
        lambda epoch: sig.wrap([make_batch(epoch * 100 + i) for i in range(5)]),
        epochs=2, checkpoint_manager=manager,
        tracer=True, trace_path=trace_path,
    )
    assert sig.raised and int(state.step) < 10  # preempted well short of 2 epochs

    # the trace survived the preemption with real spans in it
    events = load_trace_events(trace_path)
    assert any(event["name"] == "train_step" for event in events)

    # the ring's story ends with the preemption sequence, readable post-exit
    log = read_flight(ring_path)
    assert not log.torn_tail
    names = [r["event"] for r in log.records]
    assert "on_preemption" in names
    preempt = next(r for r in log.records if r["event"] == "on_preemption")
    assert preempt["signal"] == "SIGTERM"
    assert names[-1] == "on_fit_end"
    assert log.records[-1]["preempted"] is True


@pytest.mark.jax
def test_lr_backoff_survives_preemption_and_resume(tmp_path):
    """A run that rolled back (LR scale 0.5) and is then preempted must resume
    at the backed-off rate, not rerun the divergence at full LR."""
    injector = NaNInjector(at_steps=(2, 3))  # trigger one rollback...
    sig = SignalAtStep(6)  # ...then preempt later in the same epoch

    def stream(epoch: int):
        return sig.wrap(injector.wrap([make_batch(epoch * 100 + i) for i in range(9)]))

    trainer_a = make_trainer()
    manager = CheckpointManager(str(tmp_path / "run"), max_to_keep=100)
    policy = RecoveryPolicy(max_consecutive_bad=2, max_restarts=3, lr_backoff=0.5)
    trainer_a.fit(
        stream, epochs=1, checkpoint_manager=manager, checkpoint_every=2,
        recovery=policy,
    )
    assert trainer_a._lr_scale == pytest.approx(0.5)
    assert manager.metadata(manager.latest_step())["lr_scale"] == pytest.approx(0.5)

    trainer_b = make_trainer()
    assert trainer_b._lr_scale == 1.0
    trainer_b.fit(
        lambda epoch: [make_batch(epoch * 100 + i) for i in range(9)],
        epochs=1, checkpoint_manager=manager, recovery=policy, resume=True,
    )
    assert trainer_b._lr_scale == pytest.approx(0.5)  # restored from metadata


@pytest.mark.jax
def test_second_signal_restores_previous_handler():
    """The handler context restores whatever was installed before fit."""
    import signal as _signal

    from replay_tpu.nn import PreemptionHandler

    sentinel = []
    previous = _signal.signal(_signal.SIGTERM, lambda *a: sentinel.append("previous"))
    try:
        with PreemptionHandler() as handler:
            _signal.raise_signal(_signal.SIGTERM)
            assert handler.requested and handler.signal_name == "SIGTERM"
            _signal.raise_signal(_signal.SIGTERM)  # second: previous handler
            assert sentinel == ["previous"]
        # context exit restored the pre-fit handler
        _signal.raise_signal(_signal.SIGTERM)
        assert sentinel == ["previous", "previous"]
    finally:
        _signal.signal(_signal.SIGTERM, previous)


# --------------------------------------------------------------------------- #
# corrupt / truncated checkpoints
# --------------------------------------------------------------------------- #
@pytest.mark.jax
def test_truncated_latest_checkpoint_skipped_and_reported(tmp_path):
    def stream(epoch: int):
        return [make_batch(epoch * 10 + i) for i in range(3)]

    trainer_a = make_trainer()
    manager = CheckpointManager(str(tmp_path / "run"), max_to_keep=100)
    state_a = trainer_a.fit(stream, epochs=2, checkpoint_manager=manager)
    latest = manager.latest_step()
    truncate_file(str(tmp_path / "run" / f"step_{latest}.npz"), keep_fraction=0.4)

    # latest_step skips the torn file and reports it instead of raising
    assert manager.latest_step() == 3  # the epoch-0 checkpoint
    assert manager.skipped_steps == [latest]

    # resume re-trains epoch 1 from the surviving checkpoint: same final state
    trainer_b = make_trainer()
    state_b = trainer_b.fit(stream, epochs=2, checkpoint_manager=manager, resume=True)
    assert int(state_b.step) == int(state_a.step)
    assert_trees_equal(state_a.params, state_b.params)


@pytest.mark.jax
def test_restore_of_corrupt_step_names_the_step(tmp_path):
    """Satellite: an explicit restore of a torn/corrupt step raises a clear
    error naming it, not a bare deserialization traceback."""
    manager = CheckpointManager(str(tmp_path / "run"), max_to_keep=10)
    tree = {"w": np.arange(64, dtype=np.float32)}
    manager.save(3, tree)
    truncate_file(str(tmp_path / "run" / "step_3.npz"), keep_fraction=0.3)
    with pytest.raises(ValueError, match="step_3"):
        manager.restore({"w": np.zeros(64, np.float32)}, step=3)

    manager.save(5, tree)
    (tmp_path / "run" / "step_5.json").write_text("{not json")
    with pytest.raises(ValueError, match="step_5"):
        manager.restore({"w": np.zeros(64, np.float32)}, step=5)

    manager.save(7, tree)
    with pytest.raises(ValueError, match="step_7.*num_leaves|num_leaves.*step_7"):
        manager.restore({"w": np.zeros(64, np.float32), "b": np.zeros(2)}, step=7)


@pytest.mark.jax
def test_interrupted_save_invisible_to_resume(tmp_path):
    """A payload without its sidecar (killed between the two writes) and a
    sidecar without its payload are both treated as aborted saves."""
    manager = CheckpointManager(str(tmp_path / "run"), max_to_keep=10)
    manager.save(5, {"w": np.ones(4, np.float32)})
    # payload landed, commit marker (sidecar) did not:
    (tmp_path / "run" / "step_7.npz").write_bytes(b"torn half-write")
    # sidecar landed without payload (or payload deleted under us):
    (tmp_path / "run" / "step_9.json").write_text(json.dumps({"step": 9, "backend": "npz"}))

    assert manager.all_steps() == [5, 9]  # sidecars drive enumeration
    assert manager.latest_step() == 5
    assert manager.skipped_steps == [9]
    restored = manager.restore({"w": np.zeros(4, np.float32)})
    np.testing.assert_array_equal(restored["w"], np.ones(4))


@pytest.mark.jax
def test_atomic_save_leaves_no_temp_files(tmp_path):
    manager = CheckpointManager(str(tmp_path / "run"), max_to_keep=10)
    for step in (1, 2):
        manager.save(step, {"w": np.ones(8, np.float32)})
    leftovers = [p.name for p in (tmp_path / "run").glob("*.tmp")]
    assert leftovers == []
