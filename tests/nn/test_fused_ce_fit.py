"""CEFused / CEFusedTP in the production ``fit`` scan path.

The memory-wall head is only useful if the PRODUCTION loop runs it:
``Trainer.fit(scan_chunk=K, device_feed=True, loss=CEFused())`` must be
bitwise-identical to the per-step CEFused fit (the scan invariant), agree with
plain CE to f32 softmax precision, preserve exact anomaly indices through the
sentinel, and keep the health pipeline honest — logits stats streamed over
catalog chunks for tying heads, or explicitly flagged skipped, never silently
absent (docs/performance.md "Breaking the memory wall").

The smoke test leaves ``REPLAY_TPU_RUN_DIR/fused_ce_smoke/events.jsonl`` for
the CI ``fused_ce_smoke`` gate.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from replay_tpu.data import FeatureHint, FeatureType
from replay_tpu.data.nn import TensorFeatureInfo, TensorSchema
from replay_tpu.nn import OptimizerFactory, Trainer, make_mesh
from replay_tpu.nn.loss import CE, CEFused, CEFusedTP, GBCE
from replay_tpu.nn.sequential.sasrec import SasRec
from replay_tpu.obs import HealthConfig, JsonlLogger
from replay_tpu.utils.faults import NaNInjector

NUM_ITEMS = 37  # not divisible by the dryrun-style n_tp=2 shard grid
SEQ_LEN = 8
BATCH = 8  # divisible by the 8-device data axis


def make_schema() -> TensorSchema:
    # the numerical feature is the NaN-injection surface (ids can't carry NaN)
    return TensorSchema(
        [
            TensorFeatureInfo(
                "item_id",
                FeatureType.CATEGORICAL,
                is_seq=True,
                feature_hint=FeatureHint.ITEM_ID,
                cardinality=NUM_ITEMS,
                embedding_dim=16,
            ),
            TensorFeatureInfo(
                "num_feature", FeatureType.NUMERICAL, is_seq=True, tensor_dim=1,
                embedding_dim=16,
            ),
        ]
    )


def make_batch(seed: int) -> dict:
    rng = np.random.default_rng(seed)
    items = rng.integers(0, NUM_ITEMS, size=(BATCH, SEQ_LEN + 1)).astype(np.int32)
    mask = np.ones((BATCH, SEQ_LEN), dtype=bool)
    return {
        "feature_tensors": {
            "item_id": items[:, :-1],
            "num_feature": rng.normal(size=(BATCH, SEQ_LEN)).astype(np.float32),
        },
        "padding_mask": mask,
        "positive_labels": items[:, 1:, None],
        "target_padding_mask": mask[:, :, None],
        "negative_labels": rng.integers(0, NUM_ITEMS, size=(8,)).astype(np.int32),
    }


def make_trainer(loss, **kwargs) -> Trainer:
    model = SasRec(
        schema=make_schema(), embedding_dim=16, num_blocks=1, num_heads=1,
        max_sequence_length=SEQ_LEN,
    )
    kwargs.setdefault("mesh", make_mesh())
    return Trainer(
        model=model, loss=loss, optimizer=OptimizerFactory(learning_rate=1e-2),
        **kwargs,
    )


class EventSink:
    def __init__(self):
        self.events = []

    def log_event(self, event):
        self.events.append(event)

    def named(self, name):
        return [e for e in self.events if e.event == name]


def assert_params_bitwise_equal(a, b):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        assert np.asarray(x).tobytes() == np.asarray(y).tobytes()


@pytest.mark.jax
@pytest.mark.smoke
def test_fused_chunked_fit_bitwise_matches_per_step_and_ce():
    """The scan invariant for the fused head: fit(scan_chunk=3, device_feed)
    with CEFused is bitwise the per-step CEFused fit (params, losses, rng),
    runs through ONE compiled scan program, and its step losses agree with
    plain CE to f32 softmax precision. Leaves the CI smoke artifact."""
    batches = [make_batch(i) for i in range(7)]

    def run(loss, scan_chunk):
        trainer = make_trainer(loss)
        sink = EventSink()
        state = trainer.fit(
            batches, epochs=1, loggers=sink, log_every=0, scan_chunk=scan_chunk
        )
        losses = [e.payload["loss"] for e in sink.named("on_train_step")]
        return trainer, state, losses

    per_step, state_a, losses_a = run(CEFused(tile=8), None)
    chunked, state_b, losses_b = run(CEFused(tile=8), 3)
    _, _, losses_ce = run(CE(), 3)

    assert_params_bitwise_equal(state_a.params, state_b.params)
    assert np.array_equal(np.asarray(state_a.rng), np.asarray(state_b.rng))
    assert losses_a == losses_b  # host floats: bitwise step-loss parity
    assert per_step.history == chunked.history
    np.testing.assert_allclose(losses_b, losses_ce, rtol=1e-5)
    compile_report = chunked.compile_tracker.report()
    assert compile_report["train_scan"]["traces"] == 1
    assert compile_report["train_step"]["traces"] == 1

    base = os.environ.get("REPLAY_TPU_RUN_DIR")
    if base:  # CI artifact: the fused chunked fit's telemetry, re-runnable
        run_dir = os.path.join(base, "fused_ce_smoke")
        logger = JsonlLogger(run_dir, mode="w")
        trainer = make_trainer(CEFused(tile=8))
        trainer.fit(batches, epochs=1, loggers=logger, scan_chunk=3, log_every=0)
        logger.close()


@pytest.mark.jax
@pytest.mark.smoke
def test_fused_tp_chunked_fit_matches_ce_on_dp_tp_mesh():
    """CEFusedTP through fit(scan_chunk=...) on the 4×2 DP×TP mesh with the
    vocab-sharded table (37 items → non-divisible shard padding): per-step
    losses equal plain CE's to the shard-combine's f32 reassociation."""
    mesh = make_mesh(model_parallel=2)
    batches = [make_batch(i) for i in range(5)]

    def run(loss):
        trainer = make_trainer(loss, mesh=mesh, shard_vocab=True)
        sink = EventSink()
        trainer.fit(batches, epochs=1, loggers=sink, log_every=0, scan_chunk=2)
        return [e.payload["loss"] for e in sink.named("on_train_step")]

    np.testing.assert_allclose(run(CEFusedTP(tile=8)), run(CE()), rtol=1e-5)


@pytest.mark.jax
def test_fused_anomaly_indices_exact_with_nan_mid_chunk():
    """The sentinel semantics survive the fused head bitwise: a NaN landing
    mid-chunk reports the same step index, bad_steps total and per-step losses
    as the per-step CEFused fit — and the same indices as plain CE."""

    def run(loss, scan_chunk):
        injector = NaNInjector(at_steps=(4,))
        trainer = make_trainer(loss)
        sink = EventSink()
        state = trainer.fit(
            lambda epoch: injector.wrap([make_batch(i) for i in range(7)]),
            epochs=1,
            loggers=sink,
            scan_chunk=scan_chunk,
            log_every=0,
        )
        anomalies = [
            (e.step, e.payload["bad_steps_total"]) for e in sink.named("on_anomaly")
        ]
        return trainer, state, anomalies

    per_step, state_a, anomalies_a = run(CEFused(tile=8), None)
    chunked, state_b, anomalies_b = run(CEFused(tile=8), 3)
    _, state_c, anomalies_ce = run(CE(), 3)

    assert_params_bitwise_equal(state_a.params, state_b.params)
    assert int(state_a.bad_steps) == int(state_b.bad_steps) == int(state_c.bad_steps) == 1
    assert anomalies_a == anomalies_b == anomalies_ce == [(5, 1)]


@pytest.mark.jax
@pytest.mark.smoke
def test_fused_health_streams_logits_stats():
    """Health's logits-stats collector must not materialize [B, I] on the
    fused path: the streamed per-chunk stats match the full-logits stats the
    plain-CE health step reports (same catalog, same params trajectory is NOT
    required — compare against a directly computed reference)."""
    trainer = make_trainer(CEFused(tile=8), health=HealthConfig(cadence=1))
    batch = make_batch(0)
    state = trainer.init_state(batch)
    # the step donates the state: keep the pre-update params for the reference
    params = jax.tree.map(lambda x: x.copy(), state.params)
    trainer.train_step(state, batch)
    record = jax.device_get(trainer.last_step_metrics["health"])
    stats = record["logits"]
    assert set(stats) == {"mean", "absmax", "std"}

    # reference: full last-position logits from the model's own scoring head
    # (health computes its stats from the PRE-update params)
    hidden = trainer.model.apply(
        {"params": params},
        batch["feature_tensors"],
        jnp.asarray(batch["padding_mask"]),
        deterministic=True,
    )
    logits = trainer.model.apply(
        {"params": params}, hidden[:, -1, :], None,
        method=type(trainer.model).get_logits,
    )
    np.testing.assert_allclose(float(stats["mean"]), float(jnp.mean(logits)), rtol=1e-5)
    np.testing.assert_allclose(
        float(stats["absmax"]), float(jnp.max(jnp.abs(logits))), rtol=1e-5
    )
    np.testing.assert_allclose(float(stats["std"]), float(jnp.std(logits)), rtol=1e-4)


@pytest.mark.jax
def test_health_flags_skipped_without_tying_head(caplog):
    """A no-full-logits loss on a model WITHOUT a tying head cannot stream —
    the record must carry an explicit numeric skipped flag, never silently
    drop the logits block."""
    import flax.linen as nn

    class PlainModel(nn.Module):
        @nn.compact
        def __call__(self, feature_tensors, padding_mask, deterministic=True):
            embed = nn.Embed(NUM_ITEMS + 1, 16, name="embedding_item_id")
            return embed(feature_tensors["item_id"])

        def get_logits(self, hidden, candidates_to_score=None):
            # a fixed non-param projection: deliberately NOT a tying head and
            # no get_item_weights — the stream path has nothing to stream
            weights = jnp.linspace(0.0, 1.0, NUM_ITEMS * 16).reshape(NUM_ITEMS, 16)
            if candidates_to_score is None:
                return hidden @ weights.T
            if candidates_to_score.ndim == 1:
                return hidden @ weights[candidates_to_score].T
            return jnp.einsum("...e,...ke->...k", hidden, weights[candidates_to_score])

    trainer = Trainer(
        model=PlainModel(),
        loss=GBCE(catalog_size=NUM_ITEMS),
        health=HealthConfig(cadence=1),
        mesh=make_mesh(),
    )
    batch = make_batch(0)
    state = trainer.init_state(batch)
    trainer.train_step(state, batch)
    record = jax.device_get(trainer.last_step_metrics["health"])
    assert float(record["logits"]["skipped"]) == 1.0


@pytest.mark.jax
def test_cefused_unbound_callback_names_the_fix():
    loss = CEFused(tile=8)
    with pytest.raises(AttributeError, match="get_item_weights"):
        loss(
            jnp.zeros((2, 4, 8)), {}, jnp.zeros((2, 4, 1), jnp.int32), None,
            jnp.ones((2, 4), bool), jnp.ones((2, 4, 1), bool),
        )


@pytest.mark.jax
def test_cefused_rejects_mismatched_narrow_floats():
    """bf16 hidden against an f16 table is a call-site bug: named, not
    silently papered over by the kernel's f32 accumulation. The sanctioned
    flax split (narrow compute dtype vs f32 param table) still passes."""
    loss = CEFused(tile=8)
    table = jnp.zeros((NUM_ITEMS, 8), jnp.float16)
    loss.item_embeddings_callback = lambda: table
    args = (
        jnp.zeros((2, 4, 8), jnp.bfloat16), {}, jnp.zeros((2, 4, 1), jnp.int32),
        None, jnp.ones((2, 4), bool), jnp.ones((2, 4, 1), bool),
    )
    with pytest.raises(ValueError, match="bfloat16.*float16"):
        loss(*args)
    loss.item_embeddings_callback = lambda: table.astype(jnp.float32)
    assert np.isfinite(float(loss(*args)))  # bf16 hidden + f32 params: sanctioned


@pytest.mark.jax
def test_cefused_tp_without_mesh_names_the_fix():
    loss = CEFusedTP(tile=8)
    loss.item_embeddings_callback = lambda: jnp.zeros((NUM_ITEMS, 8), jnp.float32)
    with pytest.raises(AttributeError, match="loss.mesh"):
        loss(
            jnp.zeros((2, 4, 8)), {}, jnp.zeros((2, 4, 1), jnp.int32), None,
            jnp.ones((2, 4), bool), jnp.ones((2, 4, 1), bool),
        )
