"""GBCE — the gBCE calibrated sampled loss ("Turning Dross Into Gold").

Protocol conformance, calibration parity vs BCESampled at the β extremes, the
β formula itself, and the million-item claim: a Trainer fit at a synthetic
1M-item catalog touching ONLY the embedding table (never [B, L, I] logits),
with finite loss and health metrics streamed — the drop-in sampled peer of
the fused-CE heads (docs/performance.md "Breaking the memory wall").
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from replay_tpu.nn.loss import BCESampled, GBCE

B, L, E, I = 2, 4, 8, 12
RNG = np.random.default_rng(0)
EMB = jnp.asarray(RNG.normal(size=(B, L, E)), dtype=jnp.float32)
ITEMS = jnp.asarray(RNG.normal(size=(I, E)), dtype=jnp.float32)
POS = jnp.asarray(RNG.integers(0, I, size=(B, L, 1)))
NEG = jnp.asarray(RNG.integers(0, I, size=(5,)))
PAD = jnp.asarray([[True] * L, [False, False, True, True]])
TGT = PAD[..., None]

pytestmark = pytest.mark.jax


def make(loss):
    def callback(embeddings, ids=None):
        if ids is None:
            return embeddings @ ITEMS.T
        if ids.ndim == 1:
            return embeddings @ ITEMS[ids].T
        return jnp.einsum("...e,...ke->...k", embeddings, ITEMS[ids])

    loss.logits_callback = callback
    return loss


def call(loss, pos=POS, neg=NEG, tgt=TGT):
    return loss(EMB, {}, pos, neg, PAD, tgt)


def test_beta_formula():
    """β = α(t(1−1/α)+1/α): t=0 → 1 (plain BCE), t=1 → α (full calibration)."""
    loss = GBCE(catalog_size=101, t=0.0)
    assert loss.resolved_beta(25) == pytest.approx(1.0)
    loss = GBCE(catalog_size=101, t=1.0)
    assert loss.resolved_beta(25) == pytest.approx(25 / 100)
    loss = GBCE(catalog_size=101, t=0.5)
    alpha = 25 / 100
    assert loss.resolved_beta(25) == pytest.approx(alpha * (0.5 * (1 - 1 / alpha) + 1 / alpha))


def test_t_zero_is_bitwise_bce_sampled():
    """β=1: GBCE must be BCESampled exactly — the scale is the IEEE identity."""
    plain = float(call(make(BCESampled())))
    calibrated = float(call(make(GBCE(catalog_size=I, t=0.0))))
    assert plain == calibrated  # bitwise, not approx


def test_full_calibration_shrinks_positive_term():
    """β=α<1 scales only the −log σ(s⁺) term down: the loss must drop."""
    plain = float(call(make(BCESampled())))
    calibrated = float(call(make(GBCE(catalog_size=I, t=1.0))))
    assert calibrated < plain


def test_beta_override_and_negative_shapes():
    loss = make(GBCE(beta=0.5))
    v1 = call(loss, neg=NEG)
    v2 = call(loss, neg=jnp.broadcast_to(NEG, (B, 5)))
    v3 = call(loss, neg=jnp.broadcast_to(NEG, (B, L, 5)))
    assert float(v1) == pytest.approx(float(v2), rel=1e-5)
    assert float(v1) == pytest.approx(float(v3), rel=1e-5)


def test_ignore_index_negatives_excluded():
    loss = make(GBCE(catalog_size=I, t=0.5))
    # padded negatives change the STATIC negative count (and thus β): compare
    # against an explicit-β loss to isolate the masking behavior
    fixed = make(GBCE(beta=0.7))
    padded = call(fixed, neg=jnp.concatenate([NEG, jnp.array([-100, -100])]))
    plain = call(fixed, neg=NEG)
    assert float(padded) == pytest.approx(float(plain), rel=1e-5)
    assert np.isfinite(float(call(loss)))


def test_constructor_validation():
    with pytest.raises(ValueError, match="exactly one"):
        GBCE()
    with pytest.raises(ValueError, match="exactly one"):
        GBCE(catalog_size=10, beta=0.5)
    with pytest.raises(ValueError, match="t must be"):
        GBCE(catalog_size=10, t=1.5)
    with pytest.raises(ValueError, match="catalog_size"):
        GBCE(catalog_size=1)


@pytest.mark.smoke
def test_million_item_trainer_fit_embedding_table_only():
    """The million-item claim, executed: a SasRec with a 1,000,000-item
    catalog fits through the production loop with GBCE — the only [I, ...]
    tensor anywhere is the embedding table (32 MB at E=8; full logits would
    be 2 GB per batch) — with finite loss and health metrics whose logits
    stats STREAMED over the catalog (obs.health.streamed_logits_stats)."""
    from replay_tpu.data import FeatureHint, FeatureType
    from replay_tpu.data.nn import TensorFeatureInfo, TensorSchema
    from replay_tpu.nn import OptimizerFactory, Trainer, make_mesh
    from replay_tpu.nn.sequential.sasrec import SasRec
    from replay_tpu.obs import HealthConfig

    num_items, length, batch_size = 1_000_000, 6, 8
    schema = TensorSchema(
        TensorFeatureInfo(
            "item_id", FeatureType.CATEGORICAL, is_seq=True,
            feature_hint=FeatureHint.ITEM_ID, cardinality=num_items,
            embedding_dim=8,
        )
    )
    rng = np.random.default_rng(0)

    def make_batch(seed):
        r = np.random.default_rng(seed)
        items = r.integers(0, num_items, size=(batch_size, length + 1)).astype(np.int32)
        return {
            "feature_tensors": {"item_id": items[:, :-1]},
            "padding_mask": np.ones((batch_size, length), bool),
            "positive_labels": items[:, 1:, None],
            "target_padding_mask": np.ones((batch_size, length, 1), bool),
            "negative_labels": r.integers(0, num_items, size=(64,)).astype(np.int32),
        }

    model = SasRec(
        schema=schema, embedding_dim=8, num_blocks=1, num_heads=1,
        max_sequence_length=length, dropout_rate=0.0,
    )
    trainer = Trainer(
        model=model,
        loss=GBCE(catalog_size=num_items, t=0.75),
        optimizer=OptimizerFactory(learning_rate=1e-2),
        mesh=make_mesh(),
        health=HealthConfig(cadence=1, attention_entropy=False, activation_stats=False),
    )
    trainer.fit([make_batch(i) for i in range(2)], epochs=1, log_every=0)
    assert np.isfinite(trainer.history[-1]["train_loss"])
    health = trainer.last_health
    assert health is not None
    stats = health["logits"]
    assert set(stats) == {"mean", "absmax", "std"}
    assert all(np.isfinite(v) for v in stats.values())
    assert np.isfinite(health["grad_norm_global"])
    # sampled loss at a million items: the batch touches a vanishing fraction
    # of embedding rows — the coverage signal must reflect that
    assert 0.0 < health["embedding_coverage"] < 1e-3
