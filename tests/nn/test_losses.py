import jax
import jax.numpy as jnp
import numpy as np
import pytest

from replay_tpu.nn.loss import (
    BCE,
    CE,
    BCESampled,
    CESampled,
    CESampledWeighted,
    CEWeighted,
    LogInCE,
    LogInCESampled,
    LogOutCE,
    LogOutCEWeighted,
    SCEParams,
    ScalableCrossEntropyLoss,
)

B, L, E, I = 2, 4, 8, 12
RNG = np.random.default_rng(0)
EMB = jnp.asarray(RNG.normal(size=(B, L, E)), dtype=jnp.float32)
ITEMS = jnp.asarray(RNG.normal(size=(I, E)), dtype=jnp.float32)
POS = jnp.asarray(RNG.integers(0, I, size=(B, L, 1)))
NEG = jnp.asarray(RNG.integers(0, I, size=(5,)))
PAD = jnp.asarray([[True] * L, [False, False, True, True]])
TGT = PAD[..., None]


def full_logits_callback(embeddings, ids=None):
    if ids is None:
        return embeddings @ ITEMS.T
    if ids.ndim == 1:
        return embeddings @ ITEMS[ids].T
    return jnp.einsum("...e,...ke->...k", embeddings, ITEMS[ids])


def make(loss):
    loss.logits_callback = full_logits_callback
    return loss


def call(loss, pos=POS, neg=NEG, tgt=TGT):
    return loss(EMB, {}, pos, neg, PAD, tgt)


def test_ce_matches_manual():
    loss = make(CE())
    value = call(loss)
    logits = np.asarray(full_logits_callback(EMB))
    log_probs = logits - np.log(np.exp(logits).sum(-1, keepdims=True))
    manual = []
    for b in range(B):
        for t in range(L):
            if bool(PAD[b, t]):
                manual.append(-log_probs[b, t, int(POS[b, t, 0])])
    assert float(value) == pytest.approx(float(np.mean(manual)), rel=1e-4)


def test_ce_multipositive_rejected():
    loss = make(CE())
    with pytest.raises(NotImplementedError):
        call(loss, pos=jnp.zeros((B, L, 2), dtype=jnp.int32), tgt=jnp.ones((B, L, 2), dtype=bool))


def test_ce_weighted_changes_value():
    base = call(make(CE()))
    weights = jnp.ones(I).at[int(POS[0, 0, 0])].set(10.0)
    weighted = call(make(CEWeighted(weights)))
    assert float(base) != pytest.approx(float(weighted))


def test_ce_sampled_all_negative_shapes():
    loss = make(CESampled())
    v1 = call(loss, neg=NEG)  # [N]
    v2 = call(loss, neg=jnp.broadcast_to(NEG, (B, 5)))  # [B, N]
    v3 = call(loss, neg=jnp.broadcast_to(NEG, (B, L, 5)))  # [B, L, N]
    assert float(v1) == pytest.approx(float(v2), rel=1e-5)
    assert float(v1) == pytest.approx(float(v3), rel=1e-5)


def test_ce_sampled_ignore_index():
    loss = make(CESampled())
    padded_negs = jnp.concatenate([NEG, jnp.array([-100, -100])])
    v_padded = call(loss, neg=padded_negs)
    v_plain = call(loss, neg=NEG)
    assert float(v_padded) == pytest.approx(float(v_plain), rel=1e-5)


def test_ce_sampled_multipositive():
    pos2 = jnp.asarray(RNG.integers(0, I, size=(B, L, 3)))
    tgt2 = jnp.broadcast_to(PAD[..., None], (B, L, 3))
    value = call(make(CESampled()), pos=pos2, tgt=tgt2)
    assert np.isfinite(float(value))


def test_ce_sampled_weighted():
    weights = jnp.linspace(0.1, 2.0, I)
    value = call(make(CESampledWeighted(weights)))
    assert np.isfinite(float(value))


def test_bce_losses():
    assert np.isfinite(float(call(make(BCE()))))
    assert np.isfinite(float(call(make(BCESampled()))))


def test_login_ce():
    full = call(make(LogInCE(cardinality=I)))
    sampled = call(make(LogInCESampled()))
    assert np.isfinite(float(full)) and np.isfinite(float(sampled))
    # sampled negatives are a subset of the catalog -> lower or equal denominator
    assert float(sampled) <= float(full) + 1e-4


def test_logout_ce():
    value = call(make(LogOutCE(cardinality=I)))
    assert np.isfinite(float(value))
    weighted = call(make(LogOutCEWeighted(cardinality=I, weight=jnp.ones(I))))
    assert float(weighted) == pytest.approx(float(value), rel=1e-5)


def test_logout_ce_single_positive_close_to_ce():
    # with P=1, logout-CE only removes the positive itself from the negatives pool
    ce = float(call(make(CE())))
    lo = float(call(make(LogOutCE(cardinality=I))))
    # removing the positive from the denominator lowers (or at f32 precision, ties) the loss
    assert lo <= ce + 1e-6


def test_sce_loss():
    sce = ScalableCrossEntropyLoss(SCEParams(n_buckets=4, bucket_size_x=4, bucket_size_y=6))
    value = sce(
        EMB,
        POS[..., 0],
        ITEMS,
        PAD,
        rng=jax.random.PRNGKey(0),
    )
    assert np.isfinite(float(value))
    assert float(value) > 0


def test_missing_callback_raises():
    loss = CE()
    with pytest.raises(AttributeError):
        _ = loss.logits_callback
