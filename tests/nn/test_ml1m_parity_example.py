"""The ml1m_parity harness runs its full pipeline on synthetic data in CI."""

import os
import subprocess
import sys
from pathlib import Path

import pytest

pytestmark = pytest.mark.jax

REPO = Path(__file__).resolve().parents[2]


def test_ml1m_parity_synthetic_pipeline():
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [str(REPO)] + [p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p]
    )
    proc = subprocess.run(
        [sys.executable, str(REPO / "examples" / "ml1m_parity.py"), "--epochs", "1"],
        capture_output=True, text=True, timeout=600, check=False, cwd=str(REPO), env=env,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "synthetic pipeline + learnability OK" in proc.stdout
    assert "reference 0.0712" in proc.stdout  # parity targets are reported
