import jax
import jax.numpy as jnp
import numpy as np
import pytest

from replay_tpu.nn import (
    CategoricalEmbedding,
    CategoricalListEmbedding,
    ConcatAggregator,
    EmbeddingTyingHead,
    MultiHeadAttention,
    MultiHeadDifferentialAttention,
    PointWiseFeedForward,
    PositionAwareAggregator,
    SequenceEmbedding,
    SumAggregator,
    SwiGLUEncoder,
    bidirectional_attention_mask,
    causal_attention_mask,
    padding_mask_from_ids,
)

KEY = jax.random.PRNGKey(0)


def test_categorical_embedding_and_item_weights():
    module = CategoricalEmbedding(cardinality=10, embedding_dim=8, padding_value=10)
    variables = module.init(KEY, jnp.zeros((2, 3), dtype=jnp.int32))
    out = module.apply(variables, jnp.array([[0, 9, 10]]))
    assert out.shape == (1, 3, 8)
    weights = module.apply(variables, method=CategoricalEmbedding.item_weights)
    assert weights.shape == (10, 8)
    table = variables["params"]["table"]["embedding"]
    np.testing.assert_allclose(weights, table[:10])


def test_categorical_list_embedding_pooling():
    for pooling in ("sum", "mean", "max"):
        module = CategoricalListEmbedding(cardinality=6, embedding_dim=4, padding_value=6, pooling=pooling)
        ids = jnp.array([[[0, 1, 6], [6, 6, 6]]])  # [B=1, L=2, list=3]
        variables = module.init(KEY, ids)
        out = module.apply(variables, ids)
        assert out.shape == (1, 2, 4)
        # fully-padded list position embeds to zero for sum/mean/max
        np.testing.assert_allclose(out[0, 1], np.zeros(4), atol=1e-6)


def test_sequence_embedding(tensor_schema, batch):
    features, _ = batch
    module = SequenceEmbedding(schema=tensor_schema)
    variables = module.init(KEY, features)
    out = module.apply(variables, features)
    assert set(out) == {"item_id", "cat_feature", "num_feature"}
    assert all(v.shape == (4, 8, 16) for v in out.values())
    item_w = module.apply(variables, method=SequenceEmbedding.get_item_weights)
    assert item_w.shape == (20, 16)


def test_aggregators(tensor_schema, batch):
    features, _ = batch
    emb = SequenceEmbedding(schema=tensor_schema)
    variables = emb.init(KEY, features)
    embedded = emb.apply(variables, features)

    agg = SumAggregator()
    out = agg.apply(agg.init(KEY, embedded), embedded)
    assert out.shape == (4, 8, 16)

    cat = ConcatAggregator(output_dim=16)
    out = cat.apply(cat.init(KEY, embedded), embedded)
    assert out.shape == (4, 8, 16)

    pos = PositionAwareAggregator(embedding_dim=16, max_sequence_length=8, dropout_rate=0.5)
    out_det = pos.apply(pos.init(KEY, embedded), embedded, deterministic=True)
    assert out_det.shape == (4, 8, 16)
    out_rng = pos.apply(
        pos.init(KEY, embedded), embedded, deterministic=False, rngs={"dropout": KEY}
    )
    assert not np.allclose(out_det, out_rng)


def test_positional_table_tail():
    # shorter sequences use the TAIL of the positional table
    emb = {"x": jnp.ones((1, 3, 4))}
    pos = PositionAwareAggregator(embedding_dim=4, max_sequence_length=10)
    variables = pos.init(KEY, emb)
    out = pos.apply(variables, emb)
    table = variables["params"]["positional_embedding"]
    expected = jnp.ones((1, 3, 4)) * 2.0 + table[7:]
    np.testing.assert_allclose(out, expected, rtol=1e-5)


def test_causal_mask_semantics():
    padding = jnp.array([[False, True, True]])
    mask = causal_attention_mask(padding, deterministic=False)
    assert mask.shape == (1, 1, 3, 3)
    m = np.asarray(mask[0, 0])
    assert m[1, 2] == -np.inf  # future masked
    assert m[1, 1] == 0  # self allowed
    assert m[2, 1] == 0  # past allowed
    assert m[1, 0] == -np.inf  # padded key masked
    assert m[0, 0] == 0  # diagonal rescue on padded row
    eval_mask = causal_attention_mask(padding, deterministic=True)
    assert np.asarray(eval_mask[0, 0])[1, 2] == np.finfo(np.float32).min


def test_bidirectional_mask():
    padding = jnp.array([[False, True, True]])
    mask = bidirectional_attention_mask(padding, deterministic=False)
    m = np.asarray(mask[0, 0])
    assert m[1, 2] == 0  # future allowed
    assert m[1, 0] == -np.inf  # padding masked


def test_mha_respects_mask():
    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 5, 16)), dtype=jnp.float32)
    padding = jnp.ones((2, 5), dtype=bool)
    mask = causal_attention_mask(padding)
    module = MultiHeadAttention(num_heads=2)
    variables = module.init(KEY, x, mask)
    out = module.apply(variables, x, mask)
    assert out.shape == (2, 5, 16)
    # causality: output at position 0 must not change when future positions change
    x2 = x.at[:, 3:].set(0.0)
    out2 = module.apply(variables, x2, mask)
    np.testing.assert_allclose(out[:, :3], out2[:, :3], atol=1e-5)


def test_diff_attention_shapes():
    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 5, 16)), dtype=jnp.float32)
    mask = causal_attention_mask(jnp.ones((2, 5), dtype=bool))
    module = MultiHeadDifferentialAttention(num_heads=2)
    variables = module.init(KEY, x, mask)
    out = module.apply(variables, x, mask)
    assert out.shape == (2, 5, 16)
    assert np.isfinite(np.asarray(out)).all()


def test_ffn_and_swiglu():
    x = jnp.ones((2, 3, 8))
    ffn = PointWiseFeedForward(hidden_dim=16)
    out = ffn.apply(ffn.init(KEY, x), x)
    assert out.shape == x.shape
    enc = SwiGLUEncoder(num_blocks=2, hidden_dim=16, output_dim=4)
    out = enc.apply(enc.init(KEY, x), x)
    assert out.shape == (2, 3, 4)


def test_tying_head_dispatch():
    head = EmbeddingTyingHead()
    hidden_ble = jnp.ones((2, 3, 4))
    items = jnp.ones((7, 4))
    assert head(hidden_ble, items).shape == (2, 3, 7)
    hidden_be = jnp.ones((2, 4))
    per_query = jnp.ones((2, 5, 4))
    assert head(hidden_be, per_query).shape == (2, 5)
    assert head(hidden_ble, jnp.ones((2, 3, 4))).shape == (2, 3)


def test_padding_mask_from_ids():
    ids = jnp.array([[3, 0, 1]])
    np.testing.assert_array_equal(padding_mask_from_ids(ids, 0), [[True, False, True]])
