"""Packed training: segment-mask correctness (no cross-sequence attention or
loss leakage) and packed-vs-unpacked fit parity."""

import numpy as np
import pandas as pd
import pytest

import jax
import jax.numpy as jnp

from replay_tpu.data import FeatureHint, FeatureType
from replay_tpu.data.nn import (
    PackedSequenceBatcher,
    SequenceBatcher,
    SequentialDataset,
    TensorFeatureInfo,
    TensorSchema,
    TransformedBatches,
)
from replay_tpu.nn import OptimizerFactory, Trainer, make_mesh
from replay_tpu.nn.loss import CE
from replay_tpu.nn.mask import attention_mask_for_route, segment_attention_mask
from replay_tpu.nn.sequential.sasrec import SasRec
from replay_tpu.nn.transform import Compose
from replay_tpu.nn.transform.template import (
    make_default_sasrec_transforms,
    make_packed_sasrec_transforms,
)
from replay_tpu.nn.transform.transforms import SegmentBoundaryMaskTransform

NUM_ITEMS = 30
EMBED = 16


def make_schema(cardinality=NUM_ITEMS):
    return TensorSchema(
        TensorFeatureInfo(
            "item_id", FeatureType.CATEGORICAL, is_seq=True,
            feature_hint=FeatureHint.ITEM_ID, cardinality=cardinality,
            embedding_dim=EMBED,
        )
    )


def make_model(schema, seq_len, **kwargs):
    return SasRec(
        schema=schema, embedding_dim=EMBED, num_blocks=2, num_heads=2,
        max_sequence_length=seq_len, dropout_rate=0.0, **kwargs,
    )


class TestSegmentMask:
    def test_mask_is_block_diagonal_causal(self):
        padding = np.array([[True] * 6])
        segments = np.array([[1, 1, 1, 2, 2, 2]], np.int32)
        mask = np.asarray(segment_attention_mask(jnp.asarray(padding), jnp.asarray(segments)))
        allowed = mask[0, 0] == 0.0
        for q in range(6):
            for k in range(6):
                expect = k <= q and segments[0, q] == segments[0, k]
                expect = expect or q == k  # diagonal rescue
                assert allowed[q, k] == expect, (q, k)

    def test_padding_positions_attend_only_to_self(self):
        padding = np.array([[True, True, False, False]])
        segments = np.array([[1, 1, 0, 0]], np.int32)
        mask = np.asarray(segment_attention_mask(jnp.asarray(padding), jnp.asarray(segments)))
        allowed = mask[0, 0] == 0.0
        assert allowed[2].tolist() == [False, False, True, False]
        assert allowed[3].tolist() == [False, False, False, True]

    def test_flash_routes_reject_segments(self):
        padding = jnp.ones((1, 4), bool)
        segments = jnp.ones((1, 4), jnp.int32)
        with pytest.raises(ValueError, match="flash"):
            attention_mask_for_route(
                "tiled", padding, segment_ids=segments
            )
        with pytest.raises(ValueError, match="flash"):
            attention_mask_for_route(True, padding, segment_ids=segments)


@pytest.mark.smoke
class TestNoCrossSegmentLeakage:
    def test_adversarial_neighbor_segment_cannot_move_hidden_states(self):
        """Two co-packed sequences: rewriting segment 1's tokens (adversarial
        extremes included) must leave segment 2's hidden states BITWISE
        unchanged, and vice versa for the causal direction."""
        seq_len = 12
        schema = make_schema()
        model = make_model(schema, seq_len)
        segments = np.zeros((2, seq_len), np.int32)
        segments[0, :5] = 1
        segments[0, 5:9] = 2
        segments[1, :7] = 1
        padding = segments > 0
        rng = np.random.default_rng(0)
        items = rng.integers(1, NUM_ITEMS, (2, seq_len)).astype(np.int32) * padding
        params = model.init(
            jax.random.PRNGKey(0), {"item_id": items}, padding, segment_ids=segments
        )["params"]

        def hidden(item_tensor):
            return np.asarray(
                model.apply(
                    {"params": params}, {"item_id": item_tensor}, padding,
                    segment_ids=segments,
                )
            )

        base = hidden(items)
        seg1 = segments[0] == 1
        seg2 = segments[0] == 2
        for adversarial_id in (1, NUM_ITEMS - 1):
            perturbed = items.copy()
            perturbed[0, seg1] = adversarial_id
            out = hidden(perturbed)
            np.testing.assert_array_equal(base[0][seg2], out[0][seg2])
            np.testing.assert_array_equal(base[1], out[1])  # other rows too
            assert not np.array_equal(base[0][seg1], out[0][seg1])
        # and the reverse: segment 2 cannot reach back into segment 1
        perturbed = items.copy()
        perturbed[0, seg2] = NUM_ITEMS - 1
        out = hidden(perturbed)
        np.testing.assert_array_equal(base[0][seg1], out[0][seg1])

    def test_packed_segment_matches_solo_forward_bitwise(self):
        """A segment packed at row offset 0 must produce bitwise the same
        hidden states as the same sequence alone in the row at the same
        positions — packing is invisible to the math inside a segment."""
        seq_len = 10
        schema = make_schema()
        model = make_model(schema, seq_len)
        rng = np.random.default_rng(1)
        a_len, b_len = 4, 5
        row = np.zeros((1, seq_len), np.int32)
        row[0, :a_len] = rng.integers(1, NUM_ITEMS, a_len)
        row[0, a_len : a_len + b_len] = rng.integers(1, NUM_ITEMS, b_len)
        segments = np.zeros((1, seq_len), np.int32)
        segments[0, :a_len] = 1
        segments[0, a_len : a_len + b_len] = 2
        padding = segments > 0
        params = model.init(
            jax.random.PRNGKey(0), {"item_id": row}, padding, segment_ids=segments
        )["params"]
        packed = np.asarray(
            model.apply(
                {"params": params}, {"item_id": row}, padding, segment_ids=segments
            )
        )
        solo_items = np.zeros((1, seq_len), np.int32)
        solo_items[0, :a_len] = row[0, :a_len]
        solo_segments = np.zeros((1, seq_len), np.int32)
        solo_segments[0, :a_len] = 1
        solo = np.asarray(
            model.apply(
                {"params": params}, {"item_id": solo_items}, solo_segments > 0,
                segment_ids=solo_segments,
            )
        )
        np.testing.assert_array_equal(packed[0, :a_len], solo[0, :a_len])


class TestPackedTransforms:
    def test_boundary_labels_masked(self):
        schema = make_schema()
        pipeline = Compose(make_packed_sasrec_transforms(schema)["train"])
        segments = np.array([[1, 1, 1, 2, 2, 0]], np.int32)
        items = np.array([[5, 6, 7, 8, 9, 0]], np.int64)
        batch = pipeline(
            {
                "item_id": jnp.asarray(items),
                "item_id_mask": jnp.asarray(segments > 0),
                "segment_ids": jnp.asarray(segments),
                "valid": jnp.asarray([True]),
            }
        )
        # inputs trimmed to L-1; target mask: label position must stay in the
        # SAME segment — positions 2 (label from seg 2) and 4 (label is pad)
        # are masked; segment_ids now input-aligned
        np.testing.assert_array_equal(
            np.asarray(batch["target_padding_mask"])[0, :, 0],
            [True, True, False, True, False],
        )
        np.testing.assert_array_equal(
            np.asarray(batch["segment_ids"])[0], [1, 1, 1, 2, 2]
        )
        assert "segment_ids" not in batch["feature_tensors"]

    def test_misordered_pipeline_fails_loudly(self):
        transform = SegmentBoundaryMaskTransform()
        trimmed = {
            "segment_ids": jnp.asarray([[1, 1]], jnp.int32),
            "target_padding_mask": jnp.asarray([[True, True]]),
        }
        with pytest.raises(ValueError, match="FULL-length"):
            transform(trimmed)


def ragged_dataset(n_rows=48, seed=0, max_len=6):
    schema = make_schema()
    rng = np.random.default_rng(seed)
    frame = pd.DataFrame(
        {
            "query_id": np.arange(n_rows),
            "item_id": [
                rng.integers(1, NUM_ITEMS, rng.integers(2, max_len)).astype(np.int64)
                for _ in range(n_rows)
            ],
        }
    )
    return schema, SequentialDataset(schema, "query_id", "item_id", frame)


@pytest.mark.smoke
def test_packed_fit_loss_parity_with_unpacked():
    """Packed training is loss-parity-safe: the same data through the packed
    and unpacked input paths trains to train_loss within the PARITY_REPORT-
    style 10% band (never bitwise: packing moves positions and drops the few
    cross-boundary labels)."""
    seq_len = 12
    schema, dataset = ragged_dataset()

    def fit(packed):
        model = make_model(schema, seq_len)
        trainer = Trainer(
            model=model, loss=CE(),
            optimizer=OptimizerFactory(learning_rate=5e-2),
            mesh=make_mesh(jax.devices()[:1]), seed=0,
        )
        if packed:
            batcher = PackedSequenceBatcher(
                dataset, batch_size=8, max_sequence_length=seq_len + 1,
                shuffle=True, seed=0,
            )
            pipeline = Compose(make_packed_sasrec_transforms(schema)["train"])
        else:
            batcher = SequenceBatcher(
                dataset, batch_size=8, max_sequence_length=seq_len + 1,
                shuffle=True, seed=0,
            )
            pipeline = Compose(make_default_sasrec_transforms(schema)["train"])
        trainer.fit(TransformedBatches(batcher, pipeline), epochs=4, log_every=0)
        return float(trainer.history[-1]["train_loss"])

    unpacked_loss = fit(packed=False)
    packed_loss = fit(packed=True)
    assert np.isfinite(packed_loss) and np.isfinite(unpacked_loss)
    assert abs(packed_loss - unpacked_loss) <= 0.1 * abs(unpacked_loss), (
        packed_loss, unpacked_loss,
    )


def test_packed_batch_rejected_for_models_without_segment_support():
    """A packed batch fed to a model whose forward takes no segment_ids must
    fail loudly — signature filtering silently dropping the key would train
    with cross-segment attention and loss."""
    seq_len = 10
    schema, dataset = ragged_dataset(n_rows=16)
    model = make_model(schema, seq_len)
    trainer = Trainer(
        model=model, loss=CE(), optimizer=OptimizerFactory(learning_rate=1e-2),
        mesh=make_mesh(jax.devices()[:1]),
    )
    # simulate a model without the parameter (TwoTower-style forward)
    trainer._forward_params = [p for p in trainer._forward_params if p != "segment_ids"]
    batcher = PackedSequenceBatcher(
        dataset, batch_size=8, max_sequence_length=seq_len + 1, shuffle=True, seed=0
    )
    pipeline = Compose(make_packed_sasrec_transforms(schema)["train"])
    batch = pipeline(next(iter(batcher)))
    state = trainer.init_state(batch)
    with pytest.raises(ValueError, match="segment_ids"):
        trainer.train_step(state, batch)


def test_packed_fit_scan_chunked_runs_one_program():
    """PackedSequenceBatcher is scan-compatible: the chunked fit accepts it
    and runs ONE compiled scan program."""
    seq_len = 10
    schema, dataset = ragged_dataset(n_rows=32)
    model = make_model(schema, seq_len)
    trainer = Trainer(
        model=model, loss=CE(), optimizer=OptimizerFactory(learning_rate=1e-2),
        mesh=make_mesh(jax.devices()[:1]),
    )
    batcher = PackedSequenceBatcher(
        dataset, batch_size=8, max_sequence_length=seq_len + 1, shuffle=True, seed=0
    )
    pipeline = Compose(make_packed_sasrec_transforms(schema)["train"])
    state = trainer.fit(
        TransformedBatches(batcher, pipeline), epochs=1, scan_chunk=2, log_every=0
    )
    assert np.isfinite(float(trainer.history[-1]["train_loss"]))
    report = trainer.compile_tracker.report()
    assert int(report.get("train_scan", {}).get("traces", 0)) <= 1
