"""End-to-end: raw log → split → tokenize → batch → train SASRec over the mesh →
validate → predict top-k. The notebook-09 flow (SURVEY.md §3.2) in one test."""

import numpy as np
import pandas as pd
import pytest

from replay_tpu.data import Dataset, FeatureHint, FeatureInfo, FeatureSchema, FeatureType
from replay_tpu.data.nn import (
    SequenceBatcher,
    SequenceTokenizer,
    SequentialDataset,
    TensorFeatureInfo,
    TensorFeatureSource,
    TensorSchema,
    validation_batches,
)
from replay_tpu.data.schema import FeatureSource
from replay_tpu.nn import OptimizerFactory, SeenItemsFilter, Trainer
from replay_tpu.nn.loss import CE
from replay_tpu.nn.sequential.sasrec import SasRec
from replay_tpu.nn.transform import Compose
from replay_tpu.nn.transform.template import make_default_sasrec_transforms
from replay_tpu.splitters import LastNSplitter

NUM_USERS = 24
NUM_ITEMS = 30  # > max history length + k, so unseen top-5 always exists
SEQ_LEN = 8
BATCH = 8


def synthetic_log(rng: np.random.Generator) -> pd.DataFrame:
    """Each user walks the catalog cyclically from a random start — a learnable
    next-item pattern with user-specific histories."""
    rows = []
    for user in range(NUM_USERS):
        start = rng.integers(0, NUM_ITEMS)
        length = rng.integers(6, 14)
        for t in range(length):
            rows.append((f"user{user}", f"item{(start + t) % NUM_ITEMS}", t))
    return pd.DataFrame(rows, columns=["user_id", "item_id", "timestamp"])


@pytest.fixture(scope="module")
def pipeline_run():
    rng = np.random.default_rng(0)
    log = synthetic_log(rng)
    train_log, val_log = LastNSplitter(
        N=2, divide_column="user_id", query_column="user_id", timestamp_column="timestamp"
    ).split(log)

    schema = FeatureSchema(
        [
            FeatureInfo("user_id", FeatureType.CATEGORICAL, FeatureHint.QUERY_ID),
            FeatureInfo("item_id", FeatureType.CATEGORICAL, FeatureHint.ITEM_ID),
            FeatureInfo("timestamp", FeatureType.NUMERICAL, FeatureHint.TIMESTAMP),
        ]
    )
    tensor_schema = TensorSchema(
        TensorFeatureInfo(
            "item_id",
            FeatureType.CATEGORICAL,
            is_seq=True,
            feature_hint=FeatureHint.ITEM_ID,
            feature_sources=[TensorFeatureSource(FeatureSource.INTERACTIONS, "item_id")],
            embedding_dim=16,
        )
    )
    tokenizer = SequenceTokenizer(tensor_schema, handle_unknown_rule="drop")
    train_seq = tokenizer.fit_transform(
        Dataset(feature_schema=schema, interactions=train_log)
    )
    val_seq = tokenizer.transform(Dataset(feature_schema=schema, interactions=val_log))

    num_items = tensor_schema["item_id"].cardinality
    pipelines = {
        split: Compose(t) for split, t in make_default_sasrec_transforms(tensor_schema).items()
    }
    model = SasRec(schema=tensor_schema, embedding_dim=16, num_blocks=1,
                   max_sequence_length=SEQ_LEN)
    trainer = Trainer(model=model, loss=CE(),
                      optimizer=OptimizerFactory(learning_rate=3e-2))

    def train_iter(epoch=0):
        batcher = SequenceBatcher(train_seq, batch_size=BATCH, max_sequence_length=SEQ_LEN,
                                  windows=True, shuffle=True, seed=1)
        batcher.set_epoch(epoch)
        return (pipelines["train"](b) for b in batcher)

    state, losses = None, []
    for epoch in range(5):
        for batch in train_iter(epoch):
            if state is None:
                state = trainer.init_state(batch)
            state, loss_value = trainer.train_step(state, batch)
            losses.append(float(loss_value))

    return {
        "trainer": trainer, "state": state, "losses": losses,
        "train_seq": train_seq, "val_seq": val_seq, "pipelines": pipelines,
        "tokenizer": tokenizer, "num_items": num_items,
    }


@pytest.mark.jax
@pytest.mark.smoke
def test_loss_decreases(pipeline_run):
    losses = pipeline_run["losses"]
    assert np.mean(losses[-5:]) < np.mean(losses[:5]) * 0.85


@pytest.mark.jax
def test_validation_metrics(pipeline_run):
    trainer, state = pipeline_run["trainer"], pipeline_run["state"]

    def val_iter():
        for batch in validation_batches(
            pipeline_run["train_seq"], pipeline_run["val_seq"],
            batch_size=BATCH, max_sequence_length=SEQ_LEN,
        ):
            yield pipeline_run["pipelines"]["validate"](batch)

    metrics = trainer.validate(
        state, val_iter(), metrics=("ndcg", "recall", "coverage"),
        top_k=(1, 5, 10), item_count=pipeline_run["num_items"],
    )
    # the next-item pattern is deterministic: a trained model must beat random
    assert metrics["recall@5"] > 0.3, metrics
    assert 0 < metrics["coverage@10"] <= 1.0


@pytest.mark.jax
def test_predict_with_seen_filter_and_decode(pipeline_run):
    trainer, state = pipeline_run["trainer"], pipeline_run["state"]
    tokenizer = pipeline_run["tokenizer"]
    num_items = pipeline_run["num_items"]

    train_seq = pipeline_run["train_seq"]
    full_max = train_seq.get_max_sequence_length()

    def predict_iter():
        batcher = SequenceBatcher(train_seq, batch_size=BATCH, max_sequence_length=SEQ_LEN)
        for batch in batcher:
            out = pipeline_run["pipelines"]["predict"](batch)
            # the seen filter needs FULL histories, not just the model's window
            seen = np.full((len(batch["query_id"]), full_max), -1, dtype=np.int64)
            for b, query_id in enumerate(batch["query_id"]):
                history = train_seq.get_sequence_by_query_id(query_id, "item_id")
                seen[b, : len(history)] = history
            out["seen_ids"] = seen
            yield out

    frame = trainer.predict_dataframe(
        state, predict_iter(), k=5,
        postprocessors=[SeenItemsFilter(seen_field="seen_ids")],
    )
    assert len(frame) == NUM_USERS * 5
    assert frame["item_id"].between(0, num_items - 1).all()
    # decode item ids back to raw labels through the tokenizer's encoder
    inverse = tokenizer.item_id_encoder.inverse_mapping["item_id"]
    decoded = frame["item_id"].map(inverse)
    assert decoded.str.startswith("item").all()
    # no recommended item was seen in that user's history
    train_seq = pipeline_run["train_seq"]
    for query_id, group in frame.groupby("query_id"):
        seen = set(train_seq.get_sequence_by_query_id(query_id, "item_id").tolist())
        assert not seen.intersection(group["item_id"].tolist())
