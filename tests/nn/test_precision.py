"""The precision ladder's bf16 rung in the production fit path.

The policy is only sanctioned if the PRODUCTION loop runs it: ``Trainer(
precision="bf16")`` through ``fit(scan_chunk=K, device_feed=True)`` with the
``CEFused`` memory-wall head must (a) keep master params / optimizer state /
loss accumulation f32, (b) pass the f32 fit-parity gate at the
PARITY_REPORT-style threshold (same data/seed, eval metric within tolerance,
loss curves tracked — never bitwise-claimed), (c) preserve the scan
invariant bitwise WITHIN the rung, and (d) keep the health plane finite and
f32-accumulated so watchers don't false-positive on dtype alone
(docs/performance.md "The precision ladder").

The smoke test leaves ``REPLAY_TPU_RUN_DIR/precision_smoke/`` (events.jsonl +
parity_gate.json) for the CI ``precision_smoke`` gate.
"""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from replay_tpu.data import FeatureHint, FeatureType
from replay_tpu.data.nn import TensorFeatureInfo, TensorSchema
from replay_tpu.nn import (
    HealthConfig,
    HealthWatcher,
    OptimizerFactory,
    Precision,
    Trainer,
    fit_parity_record,
    make_mesh,
)
from replay_tpu.nn.loss import CEFused, CESampled
from replay_tpu.nn.sequential.sasrec import SasRec
from replay_tpu.obs import JsonlLogger

NUM_ITEMS = 37
SEQ_LEN = 8
BATCH = 16


def make_schema() -> TensorSchema:
    return TensorSchema(
        [
            TensorFeatureInfo(
                "item_id",
                FeatureType.CATEGORICAL,
                is_seq=True,
                feature_hint=FeatureHint.ITEM_ID,
                cardinality=NUM_ITEMS,
                embedding_dim=16,
            ),
            # a float feature exercises NumericalEmbedding's compute-dtype cast
            TensorFeatureInfo(
                "num_feature", FeatureType.NUMERICAL, is_seq=True, tensor_dim=1,
                embedding_dim=16,
            ),
        ]
    )


def make_batch(seed: int, negatives: int = 0) -> dict:
    """Learnable next-is-plus-one sequences (the parity gate needs a metric a
    2-epoch fit actually moves, not noise)."""
    rng = np.random.default_rng(seed)
    starts = rng.integers(0, NUM_ITEMS, size=(BATCH, 1))
    items = ((starts + np.arange(SEQ_LEN + 1)) % NUM_ITEMS).astype(np.int32)
    mask = np.ones((BATCH, SEQ_LEN), dtype=bool)
    batch = {
        "feature_tensors": {
            "item_id": items[:, :-1],
            "num_feature": rng.normal(size=(BATCH, SEQ_LEN)).astype(np.float32),
        },
        "padding_mask": mask,
        "positive_labels": items[:, 1:, None],
        "target_padding_mask": mask[:, :, None],
    }
    if negatives:
        batch["negative_labels"] = rng.integers(
            0, NUM_ITEMS, size=(negatives,)
        ).astype(np.int32)
    return batch


def make_val_batch(seed: int) -> dict:
    batch = make_batch(seed)
    last = batch["feature_tensors"]["item_id"][:, -1]
    return {
        "feature_tensors": batch["feature_tensors"],
        "padding_mask": batch["padding_mask"],
        "ground_truth": ((last + 1) % NUM_ITEMS)[:, None].astype(np.int32),
    }


def make_trainer(precision, loss=None, **kwargs) -> Trainer:
    model = SasRec(
        schema=make_schema(), embedding_dim=16, num_blocks=1, num_heads=1,
        max_sequence_length=SEQ_LEN,
    )
    kwargs.setdefault("mesh", make_mesh())
    return Trainer(
        model=model,
        loss=loss if loss is not None else CEFused(tile=8),
        optimizer=OptimizerFactory(learning_rate=1e-2),
        precision=precision,
        **kwargs,
    )


class EventSink:
    def __init__(self):
        self.events = []

    def log_event(self, event):
        self.events.append(event)

    def named(self, name):
        return [e for e in self.events if e.event == name]


def assert_params_bitwise_equal(a, b):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        assert np.asarray(x).tobytes() == np.asarray(y).tobytes()


# --------------------------------------------------------------------------- #
# policy mechanics
# --------------------------------------------------------------------------- #
@pytest.mark.jax
def test_resolve_and_identity():
    assert Precision.resolve(None) is None
    policy = Precision.resolve("bf16")
    assert policy.name == "bf16"
    assert jnp.dtype(policy.compute_dtype) == jnp.dtype(jnp.bfloat16)
    assert jnp.dtype(policy.param_dtype) == jnp.dtype(jnp.float32)
    assert Precision.resolve(policy) is policy
    identity = Precision.resolve("f32")
    assert identity.is_identity and not policy.is_identity
    with pytest.raises(ValueError, match="Unknown precision"):
        Precision.resolve("fp8")
    with pytest.raises(TypeError, match="precision"):
        Precision.resolve(16)


@pytest.mark.jax
def test_f32_rung_is_the_identity():
    """Precision('f32') must never clone/retouch the model: the pre-precision
    trainer and the f32-rung trainer are the same program."""
    model = SasRec(
        schema=make_schema(), embedding_dim=16, num_blocks=1, num_heads=1,
        max_sequence_length=SEQ_LEN,
    )
    assert Precision.f32().apply_to_model(model) is model
    trainer = Trainer(
        model=model, loss=CEFused(tile=8),
        optimizer=OptimizerFactory(learning_rate=1e-2), mesh=make_mesh(),
        precision="f32",
    )
    assert trainer.model is model


@pytest.mark.jax
def test_bf16_clones_model_and_keeps_f32_master_state():
    trainer = make_trainer("bf16")
    assert jnp.dtype(trainer.model.dtype) == jnp.dtype(jnp.bfloat16)
    state = trainer.init_state(make_batch(0))
    for leaf in jax.tree.leaves(state.params):
        assert leaf.dtype == jnp.float32, leaf.dtype
    for leaf in jax.tree.leaves(state.opt_state):
        if jnp.issubdtype(leaf.dtype, jnp.floating):
            assert leaf.dtype == jnp.float32, leaf.dtype


@pytest.mark.jax
def test_bf16_rejects_model_without_dtype_field():
    import flax.linen as nn

    class PlainModel(nn.Module):
        @nn.compact
        def __call__(self, feature_tensors, padding_mask, deterministic=True):
            embed = nn.Embed(NUM_ITEMS + 1, 16, name="embedding_item_id")
            return embed(feature_tensors["item_id"])

    with pytest.raises(ValueError, match="dtype"):
        Trainer(
            model=PlainModel(), loss="ce", mesh=make_mesh(), precision="bf16"
        )


@pytest.mark.jax
def test_wrap_logits_callback_casts_to_accum():
    policy = Precision.bf16()
    assert policy.casts_logits
    wrapped = policy.wrap_logits_callback(
        lambda x: jnp.zeros((2, 3), jnp.bfloat16) + x
    )
    assert wrapped(1.0).dtype == jnp.float32
    assert not Precision.f32().casts_logits


# --------------------------------------------------------------------------- #
# the production fit: parity gate, scan invariant, events
# --------------------------------------------------------------------------- #
@pytest.mark.jax
@pytest.mark.smoke
def test_bf16_production_fit_passes_parity_gate():
    """The tentpole gate: same data/seed through the PRODUCTION path
    (scan_chunk + device feed + CEFused) at f32 and bf16 — eval ndcg@10
    within the PARITY_REPORT-style tolerance, loss curves tracked. Leaves the
    CI precision_smoke artifact."""
    batches = [make_batch(i) for i in range(6)]
    val = [make_val_batch(100)]

    def run(precision, logger=None):
        trainer = make_trainer(precision)
        trainer.fit(
            batches, epochs=2, scan_chunk=3, log_every=0,
            val_batches=lambda: val, metrics=("ndcg", "recall"), top_k=(10,),
            loggers=logger,
        )
        return trainer

    f32_trainer = run(None)
    base = os.environ.get("REPLAY_TPU_RUN_DIR")
    run_dir = os.path.join(base, "precision_smoke") if base else None
    logger = JsonlLogger(run_dir, mode="w") if run_dir else None
    bf16_trainer = run("bf16", logger=logger)
    if logger is not None:
        logger.close()

    record = fit_parity_record(
        f32_trainer.history, bf16_trainer.history, metric="ndcg@10"
    )
    assert record["passed"], record
    # the learnable pattern moved the metric: the gate is not vacuous
    assert record["f32"] > 0.2, record
    assert len(record["loss_curve_f32"]) == len(record["loss_curve_bf16"]) == 2
    assert all(np.isfinite(record["loss_curve_bf16"]))
    # loss curves track each other well inside the gate tolerance
    np.testing.assert_allclose(
        record["loss_curve_bf16"], record["loss_curve_f32"], rtol=2e-2
    )

    if run_dir:  # CI artifact: the gate record itself, machine-checkable
        static = {
            name: trainer.analyze_programs().get("train_scan", {}).get("hbm_peak_bytes")
            for name, trainer in (("f32", f32_trainer), ("bf16", bf16_trainer))
        }
        with open(os.path.join(run_dir, "parity_gate.json"), "w") as fh:
            json.dump(
                {**record, "hbm_peak_bytes": static, "backend": jax.default_backend()},
                fh, indent=1,
            )


@pytest.mark.jax
@pytest.mark.smoke
def test_bf16_scan_chunk_bitwise_matches_per_step():
    """The scan invariant holds WITHIN the bf16 rung: fit(scan_chunk=3) is
    bitwise the per-step bf16 fit (params, rng, step losses)."""
    batches = [make_batch(i) for i in range(7)]

    def run(scan_chunk):
        trainer = make_trainer("bf16")
        sink = EventSink()
        state = trainer.fit(
            batches, epochs=1, loggers=sink, log_every=0, scan_chunk=scan_chunk
        )
        return state, [e.payload["loss"] for e in sink.named("on_train_step")]

    state_a, losses_a = run(None)
    state_b, losses_b = run(3)
    assert_params_bitwise_equal(state_a.params, state_b.params)
    assert np.array_equal(np.asarray(state_a.rng), np.asarray(state_b.rng))
    assert losses_a == losses_b


@pytest.mark.jax
def test_on_fit_start_event_carries_precision():
    trainer = make_trainer("bf16")
    sink = EventSink()
    trainer.fit([make_batch(0)], epochs=1, loggers=sink, log_every=0)
    payload = sink.named("on_fit_start")[0].payload
    assert payload["precision"] == "bf16"
    assert payload["compute_dtype"] == "bfloat16"
    assert payload["param_dtype"] == "float32"
    # the f32 / no-policy fit advertises nothing (byte-identical programs)
    sink32 = EventSink()
    make_trainer(None).fit([make_batch(0)], epochs=1, loggers=sink32, log_every=0)
    assert "precision" not in sink32.named("on_fit_start")[0].payload


@pytest.mark.jax
def test_sampled_loss_accumulates_f32_under_bf16():
    """CESampled's candidate logits are a bf16×bf16 einsum under the rung —
    the policy's logits wrap must land the loss math in f32, keeping the loss
    value within the bf16 input-rounding band of the f32 run."""
    losses = {}
    for name, precision in (("f32", None), ("bf16", "bf16")):
        trainer = make_trainer(precision, loss=CESampled())
        batch = make_batch(0, negatives=8)
        state = trainer.init_state(batch)
        _, loss_value = trainer.train_step(state, batch)
        losses[name] = float(loss_value)
        # the loss scalar itself must be f32 — bf16 accumulation would
        # surface here as a bf16 scalar
        assert trainer.last_step_metrics["loss"].dtype == jnp.float32
    assert np.isfinite(losses["bf16"])
    np.testing.assert_allclose(losses["bf16"], losses["f32"], rtol=2e-2)


# --------------------------------------------------------------------------- #
# health under bf16 (satellite: watchers must not false-positive on dtype)
# --------------------------------------------------------------------------- #
@pytest.mark.jax
@pytest.mark.smoke
def test_bf16_health_stays_finite_and_f32_accumulated():
    trainer = make_trainer("bf16", health=HealthConfig(cadence=1))
    batch = make_batch(0)
    state = trainer.init_state(batch)
    state, _ = trainer.train_step(state, batch)
    health_tree = trainer.last_step_metrics["health"]
    # every health leaf is f32 ON DEVICE — norms/ratios/stats accumulate in
    # f32 regardless of the bf16 activations they were computed from
    for leaf in jax.tree.leaves(health_tree):
        assert leaf.dtype == jnp.float32, leaf.dtype
    record = jax.device_get(health_tree)
    values = [
        float(v)
        for v in jax.tree.leaves(
            jax.tree.map(lambda x: np.asarray(x, np.float64).reshape(-1).tolist(), record)
        )
    ]
    assert values and all(np.isfinite(values)), record
    # streamed logits stats exist (CEFused avoids full logits; the tying-head
    # stream path must keep working under bf16 hidden states)
    assert set(record["logits"]) == {"mean", "absmax", "std"}


@pytest.mark.jax
def test_health_watcher_no_false_positive_on_bf16():
    """A steady bf16 fit must not trip the EWMA watcher: dtype alone is not a
    blowup. (A genuine 10× norm jump still is — sanity-checked last.)"""
    watcher = HealthWatcher(alpha=0.5, blowup_factor=3.0, warmup=2)
    trainer = make_trainer("bf16", health=HealthConfig(cadence=1))
    batch = make_batch(0)
    state = trainer.init_state(batch)
    for _ in range(5):
        state, _ = trainer.train_step(state, batch)
        record = jax.tree.map(
            lambda x: x.tolist() if getattr(x, "ndim", 0) else float(x),
            jax.device_get(trainer.last_step_metrics["health"]),
        )
        record["grad_norm_global"] = float(
            trainer.last_step_metrics["grad_norm"]
        )
        assert watcher.observe(record) is None, record
    blown = dict(record)
    blown["grad_norm_global"] = 100.0 * record["grad_norm_global"]
    assert watcher.observe(blown) is not None
