"""Cross-framework quality parity (VERDICT r3 missing #1).

Runs ``examples/reference_parity.py`` — the reference's own torch SasRec vs the
JAX SasRec on an identical Markov log with identical batches, notebook-09's
Lightning optimizer settings (adam betas (0.9, 0.98)), init-matched embeddings
(xavier-normal both sides) and one shared evaluation — as a subprocess and
requires it to reach its PARITY OK verdict: both models beat 2x the popularity
baseline and the final ndcg@10 gap stays within a two-sided 10% at 10 epochs
(measured gap 8.1%, jax ahead — PARITY_REPORT.md)."""

import os
import subprocess
import sys
from pathlib import Path

import pytest

pytestmark = pytest.mark.jax

REPO = Path(__file__).resolve().parents[2]
REFERENCE = Path("/root/reference")


@pytest.mark.skipif(not REFERENCE.exists(), reason="reference checkout not present")
def test_reference_parity_verdict():
    env = dict(os.environ)
    env["PYTHONPATH"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [
            sys.executable,
            str(REPO / "examples" / "reference_parity.py"),
            "--epochs", "10",
            "--tolerance", "0.10",  # committed 10-epoch gap: 8.1% (jax ahead)
        ],
        capture_output=True,
        text=True,
        timeout=1500,
        check=False,
        cwd=str(REPO),
    )
    assert proc.returncode == 0, proc.stdout[-3000:] + proc.stderr[-3000:]
    assert "PARITY OK" in proc.stdout
