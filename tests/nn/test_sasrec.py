import jax
import jax.numpy as jnp
import numpy as np
import pytest

from replay_tpu.nn.loss import CE, CESampled
from replay_tpu.nn.sequential import SasRec

KEY = jax.random.PRNGKey(0)


@pytest.fixture
def model(item_only_schema):
    return SasRec(
        schema=item_only_schema,
        embedding_dim=16,
        num_blocks=2,
        num_heads=2,
        max_sequence_length=8,
        dropout_rate=0.1,
    )


def test_forward_shapes(model, batch):
    features, padding_mask = batch
    features = {"item_id": features["item_id"]}
    variables = model.init(KEY, features, padding_mask)
    hidden = model.apply(variables, features, padding_mask)
    assert hidden.shape == (4, 8, 16)

    scores = model.apply(
        variables, features, padding_mask, method=SasRec.forward_inference
    )
    assert scores.shape == (4, 20)

    candidates = jnp.array([1, 5, 7])
    cand_scores = model.apply(
        variables, features, padding_mask, candidates, method=SasRec.forward_inference
    )
    assert cand_scores.shape == (4, 3)
    np.testing.assert_allclose(cand_scores, np.asarray(scores)[:, [1, 5, 7]], rtol=2e-5)


def test_diff_encoder(item_only_schema, batch):
    features, padding_mask = batch
    features = {"item_id": features["item_id"]}
    model = SasRec(schema=item_only_schema, embedding_dim=16, num_heads=2, encoder_type="diff", max_sequence_length=8)
    variables = model.init(KEY, features, padding_mask)
    hidden = model.apply(variables, features, padding_mask)
    assert np.isfinite(np.asarray(hidden)).all()


def test_training_step_decreases_loss(model, batch):
    import optax

    features, padding_mask = batch
    features = {"item_id": features["item_id"]}
    variables = model.init(KEY, features, padding_mask)
    params = variables["params"]

    # next-token labels: shift items left; last target = padding (masked)
    items = jnp.asarray(features["item_id"])
    labels = jnp.concatenate([items[:, 1:], jnp.full((4, 1), 20)], axis=1)[..., None]
    target_mask = (labels != 20) & padding_mask[..., None]

    loss_obj = CE()
    optimizer = optax.adam(1e-2)
    opt_state = optimizer.init(params)

    def loss_fn(p, rng):
        hidden = model.apply(
            {"params": p}, features, padding_mask, deterministic=False, rngs={"dropout": rng}
        )
        loss_obj.logits_callback = lambda emb, ids=None: model.apply(
            {"params": p}, emb, ids, method=SasRec.get_logits
        )
        return loss_obj(hidden, features, labels, None, padding_mask, target_mask)

    @jax.jit
    def step(p, opt_state, rng):
        loss, grads = jax.value_and_grad(loss_fn)(p, rng)
        updates, opt_state = optimizer.update(grads, opt_state, p)
        return optax.apply_updates(p, updates), opt_state, loss

    rng = KEY
    losses = []
    for i in range(30):
        rng, step_rng = jax.random.split(rng)
        params, opt_state, loss = step(params, opt_state, step_rng)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.7, losses[::10]


def test_sampled_loss_with_model(model, batch):
    features, padding_mask = batch
    features = {"item_id": features["item_id"]}
    variables = model.init(KEY, features, padding_mask)
    items = jnp.asarray(features["item_id"])
    labels = jnp.concatenate([items[:, 1:], jnp.full((4, 1), 20)], axis=1)[..., None]
    target_mask = (labels != 20) & padding_mask[..., None]
    negatives = jnp.array([0, 3, 9])

    hidden = model.apply(variables, features, padding_mask)
    loss_obj = CESampled()
    loss_obj.logits_callback = lambda emb, ids=None: model.apply(
        variables, emb, ids, method=SasRec.get_logits
    )
    value = loss_obj(hidden, features, jnp.clip(labels, 0, 19), negatives, padding_mask, target_mask)
    assert np.isfinite(float(value))
