"""Scan-chunked ``Trainer.fit`` (docs/performance.md "Closing the dispatch gap").

``fit(scan_chunk=K)`` dispatches K optimizer steps as ONE ``lax.scan`` program
behind a device-feed stage, and must be indistinguishable from the per-step fit
in everything but dispatch count: bitwise-identical final parameters, per-step
losses, sentinel ``bad_steps`` accounting, exact ``on_anomaly`` step indices
(including a NaN landing mid-chunk), health cadence under the interleave, and
recovery rollbacks — all on the 8-device virtual CPU mesh.
"""

import numpy as np
import pytest

import jax

from replay_tpu.data import FeatureHint, FeatureType
from replay_tpu.data.nn import TensorFeatureInfo, TensorSchema
from replay_tpu.nn import OptimizerFactory, RecoveryPolicy, Trainer, make_mesh
from replay_tpu.nn.loss import CE
from replay_tpu.nn.sequential.sasrec import SasRec
from replay_tpu.obs import HealthConfig
from replay_tpu.utils.faults import NaNInjector, SignalAtStep

NUM_ITEMS = 12
SEQ_LEN = 8
BATCH = 8  # divisible by the 8-device data axis


def make_schema() -> TensorSchema:
    # the numerical feature is the NaN-injection surface (ids can't carry NaN)
    return TensorSchema(
        [
            TensorFeatureInfo(
                "item_id",
                FeatureType.CATEGORICAL,
                is_seq=True,
                feature_hint=FeatureHint.ITEM_ID,
                cardinality=NUM_ITEMS,
                embedding_dim=16,
            ),
            TensorFeatureInfo(
                "num_feature", FeatureType.NUMERICAL, is_seq=True, tensor_dim=1,
                embedding_dim=16,
            ),
        ]
    )


def make_batch(seed: int) -> dict:
    rng = np.random.default_rng(seed)
    items = rng.integers(0, NUM_ITEMS, size=(BATCH, SEQ_LEN + 1)).astype(np.int32)
    mask = np.ones((BATCH, SEQ_LEN), dtype=bool)
    return {
        "feature_tensors": {
            "item_id": items[:, :-1],
            "num_feature": rng.normal(size=(BATCH, SEQ_LEN)).astype(np.float32),
        },
        "padding_mask": mask,
        "positive_labels": items[:, 1:, None],
        "target_padding_mask": mask[:, :, None],
    }


def make_trainer(**kwargs) -> Trainer:
    model = SasRec(
        schema=make_schema(), embedding_dim=16, num_blocks=1, num_heads=1,
        max_sequence_length=SEQ_LEN,
    )
    return Trainer(
        model=model, loss=CE(), optimizer=OptimizerFactory(learning_rate=1e-2),
        mesh=make_mesh(), **kwargs,
    )


class EventSink:
    def __init__(self):
        self.events = []

    def log_event(self, event):
        self.events.append(event)

    def named(self, name):
        return [e for e in self.events if e.event == name]


def assert_params_bitwise_equal(a, b):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        assert np.asarray(x).tobytes() == np.asarray(y).tobytes()


def step_records(sink):
    """(step, loss) pairs from on_train_step events, NaN-tolerant compare."""
    out = []
    for event in sink.named("on_train_step"):
        loss = event.payload["loss"]
        out.append((event.step, None if not np.isfinite(loss) else float(loss)))
    return out


# --------------------------------------------------------------------------- #
# bitwise parity with the per-step fit
# --------------------------------------------------------------------------- #
@pytest.mark.jax
@pytest.mark.smoke
def test_chunked_fit_bitwise_parity_including_tail():
    """7 batches x 2 epochs with K=3: two scans + a per-step tail per epoch
    produce the exact per-step results — final params, per-step losses, epoch
    averages — through ONE compiled scan program + ONE per-step program."""
    batches = [make_batch(i) for i in range(7)]

    per_step = make_trainer()
    sink_a = EventSink()
    state_a = per_step.fit(batches, epochs=2, loggers=sink_a, log_every=0)

    chunked = make_trainer()
    sink_b = EventSink()
    state_b = chunked.fit(batches, epochs=2, loggers=sink_b, log_every=0, scan_chunk=3)

    assert_params_bitwise_equal(state_a.params, state_b.params)
    assert int(state_a.step) == int(state_b.step) == 14
    assert int(state_a.bad_steps) == int(state_b.bad_steps) == 0
    assert np.array_equal(np.asarray(state_a.rng), np.asarray(state_b.rng))
    assert per_step.history == chunked.history
    assert step_records(sink_a) == step_records(sink_b)
    # exactly one extra compiled variant: the K=3 scan next to the per-step
    # program that handles the tail — no chunk-length zoo
    compile_report = chunked.compile_tracker.report()
    assert compile_report["train_scan"]["traces"] == 1
    assert compile_report["train_step"]["traces"] == 1


@pytest.mark.jax
def test_device_feed_off_matches_on():
    """device_feed=False places chunks synchronously on the fit thread —
    slower, but the math and accounting must be identical."""
    batches = [make_batch(i) for i in range(6)]
    fed = make_trainer()
    state_a = fed.fit(batches, epochs=1, log_every=0, scan_chunk=2, device_feed=True)
    unfed = make_trainer()
    state_b = unfed.fit(batches, epochs=1, log_every=0, scan_chunk=2, device_feed=False)
    assert_params_bitwise_equal(state_a.params, state_b.params)
    assert fed.history == unfed.history


@pytest.mark.jax
@pytest.mark.smoke
def test_anomaly_indices_exact_with_nan_mid_chunk():
    """A NaN batch landing MID-chunk (position 4 → step 5, inside the K=3
    chunk covering steps 4-6) reports the exact per-step anomaly index,
    per-step bad_steps totals and losses — identical to the per-step fit."""

    def run(scan_chunk):
        injector = NaNInjector(at_steps=(4,))
        trainer = make_trainer()
        sink = EventSink()
        state = trainer.fit(
            lambda epoch: injector.wrap([make_batch(epoch * 10 + i) for i in range(7)]),
            epochs=2,
            loggers=sink,
            scan_chunk=scan_chunk,
            log_every=0,
        )
        return trainer, state, sink

    per_step, state_a, sink_a = run(None)
    chunked, state_b, sink_b = run(3)

    assert_params_bitwise_equal(state_a.params, state_b.params)
    assert int(state_a.bad_steps) == int(state_b.bad_steps) == 1
    anomalies_a = [(e.step, e.payload["bad_steps_total"]) for e in sink_a.named("on_anomaly")]
    anomalies_b = [(e.step, e.payload["bad_steps_total"]) for e in sink_b.named("on_anomaly")]
    assert anomalies_a == anomalies_b == [(5, 1)]
    assert step_records(sink_a) == step_records(sink_b)
    assert per_step.history == chunked.history


# --------------------------------------------------------------------------- #
# recovery rollback
# --------------------------------------------------------------------------- #
@pytest.mark.jax
def test_recovery_trigger_at_chunk_boundary_bitwise_parity():
    """The consecutive-bad trigger landing exactly at a chunk END (steps 5 and
    6 bad, K=3 chunk covers 4-6) rolls back at the same point as the per-step
    fit — bitwise-identical continuation."""

    def run(scan_chunk):
        injector = NaNInjector(at_steps=(4, 5))
        trainer = make_trainer()
        sink = EventSink()
        state = trainer.fit(
            lambda epoch: injector.wrap([make_batch(i) for i in range(9)]),
            epochs=1,
            loggers=sink,
            scan_chunk=scan_chunk,
            log_every=0,
            recovery=RecoveryPolicy(max_consecutive_bad=2, max_restarts=2, lr_backoff=0.5),
        )
        return trainer, state, sink

    per_step, state_a, sink_a = run(None)
    chunked, state_b, sink_b = run(3)
    assert len(sink_a.named("on_recovery")) == len(sink_b.named("on_recovery")) == 1
    assert_params_bitwise_equal(state_a.params, state_b.params)
    assert per_step._lr_scale == chunked._lr_scale == pytest.approx(0.5)
    assert step_records(sink_a) == step_records(sink_b)


@pytest.mark.jax
def test_recovery_mid_chunk_discards_rest_of_chunk():
    """A trigger firing MID-chunk rolls back at chunk granularity: the
    remaining (already-executed, pre-rollback) steps of the chunk are consumed
    but not accounted, and the run continues finite on the restored state."""
    injector = NaNInjector(at_steps=(3, 4))  # steps 4, 5 — mid-chunk of 4-6
    trainer = make_trainer()
    sink = EventSink()
    state = trainer.fit(
        lambda epoch: injector.wrap([make_batch(i) for i in range(7)]),
        epochs=1,
        loggers=sink,
        scan_chunk=3,
        log_every=0,
        recovery=RecoveryPolicy(max_consecutive_bad=2, max_restarts=2, lr_backoff=0.5),
    )
    recoveries = sink.named("on_recovery")
    assert len(recoveries) == 1
    assert recoveries[0].payload["reason"] == "consecutive_bad_steps"
    # rollback restored the initial snapshot (no checkpoints): step 6's update
    # belonged to the discarded trajectory, only the step-7 tail ran after —
    # and its event carries the restored trajectory's step id
    assert int(state.step) == 1
    assert int(state.bad_steps) == 0  # the rollback restored the clean snapshot
    assert np.isfinite(trainer.history[-1]["train_loss"])
    # step 6 (rest of the rolled-back chunk) emitted no on_train_step event
    emitted_steps = [e.step for e in sink.named("on_train_step")]
    assert 6 not in emitted_steps


# --------------------------------------------------------------------------- #
# health interleave
# --------------------------------------------------------------------------- #
@pytest.mark.jax
def test_health_cadence_interleaves_single_steps():
    """HealthConfig + scan_chunk: every cadence-th step runs the health
    program (no silent health loss), the rest still run through ONE scan
    program, and the math matches a plain per-step fit bitwise."""
    batches = [make_batch(i) for i in range(8)]
    # cadence ≡ 1 (mod K): chunks (1,2), (3,4), health single 5, (6,7), tail 8
    chunked = make_trainer(health=HealthConfig(cadence=5))
    sink = EventSink()
    state_a = chunked.fit(batches, epochs=1, loggers=sink, log_every=0, scan_chunk=2)

    health_steps = [
        e.step for e in sink.named("on_train_step") if "health" in e.payload
    ]
    assert health_steps == [5]
    assert chunked.last_health is not None
    compile_report = chunked.compile_tracker.report()
    assert compile_report["train_scan"]["traces"] == 1
    assert compile_report["train_step"]["traces"] == 1  # the health variant

    plain = make_trainer()
    state_b = plain.fit(batches, epochs=1, log_every=0)
    assert_params_bitwise_equal(state_a.params, state_b.params)


# --------------------------------------------------------------------------- #
# chunk-boundary checkpointing + preemption
# --------------------------------------------------------------------------- #
@pytest.mark.jax
def test_checkpoint_every_saves_at_chunk_boundaries(tmp_path):
    """A checkpoint_every boundary crossed INSIDE a chunk saves once at the
    chunk end with the chunk-end stream position — resume-consistent."""
    from replay_tpu.utils.checkpoint import CheckpointManager

    manager = CheckpointManager(str(tmp_path / "run"), max_to_keep=10)
    trainer = make_trainer()
    trainer.fit(
        [make_batch(i) for i in range(7)],
        epochs=1,
        checkpoint_manager=manager,
        checkpoint_every=2,  # boundaries at 2, 4, 6 — all inside K=3 chunks
        scan_chunk=3,
        log_every=0,
    )
    mid_epoch = sorted(
        step for step in manager.valid_steps() if manager.metadata(step).get("mid_epoch")
    )
    # chunk ends at 3 and 6 covered boundaries 2 and (4, 6); the position
    # stamped is the chunk end, where the saved state actually exists
    assert mid_epoch == [3, 6]
    for step in mid_epoch:
        assert manager.metadata(step)["step_in_epoch"] == step


@pytest.mark.jax
def test_preemption_mid_chunked_fit_resumes_bit_for_bit(tmp_path):
    """A SIGTERM during a chunked fit checkpoints at a chunk boundary and
    fit(resume=True, scan_chunk=...) reproduces the uninterrupted run."""
    from replay_tpu.utils.checkpoint import CheckpointManager

    batches = [make_batch(i) for i in range(9)]

    uninterrupted = make_trainer()
    final_a = uninterrupted.fit(batches, epochs=1, log_every=0, scan_chunk=3)

    preempted = make_trainer()
    manager = CheckpointManager(str(tmp_path / "run"), max_to_keep=10)
    harness = SignalAtStep(at_step=2)
    mid = preempted.fit(
        lambda epoch: harness.wrap(iter(batches)),
        epochs=1,
        checkpoint_manager=manager,
        scan_chunk=3,
        log_every=0,
    )
    assert int(mid.step) < 9  # actually exited early, at a chunk boundary
    resumed_trainer = make_trainer()
    final_b = resumed_trainer.fit(
        batches,
        epochs=1,
        checkpoint_manager=manager,
        resume=True,
        scan_chunk=3,
        log_every=0,
    )
    assert int(final_b.step) == int(final_a.step) == 9
    assert_params_bitwise_equal(final_a.params, final_b.params)


# --------------------------------------------------------------------------- #
# guards
# --------------------------------------------------------------------------- #
@pytest.mark.jax
def test_bucketed_batcher_rejected_at_fit_start():
    import pandas as pd

    from replay_tpu.data.nn import SequenceBatcher, SequentialDataset

    schema = TensorSchema(
        TensorFeatureInfo(
            "item_id", FeatureType.CATEGORICAL, is_seq=True,
            feature_hint=FeatureHint.ITEM_ID, cardinality=NUM_ITEMS,
        )
    )
    frame = pd.DataFrame(
        {"query_id": np.arange(6), "item_id": [np.arange(1 + i) for i in range(6)]}
    )
    dataset = SequentialDataset(schema, "query_id", "item_id", frame)
    bucketed = SequenceBatcher(
        dataset, batch_size=2, max_sequence_length=6, bucket_boundaries=(3,)
    )
    assert not bucketed.scan_compatible
    trainer = make_trainer()
    with pytest.raises(ValueError, match="bucket_boundaries"):
        trainer.fit(bucketed, epochs=1, scan_chunk=2)
    # a factory callable hides the batcher from the fit-start check; the
    # epoch-start check rejects what it returns before any step runs
    with pytest.raises(ValueError, match="bucket_boundaries"):
        trainer.fit(lambda: bucketed, epochs=1, scan_chunk=2)


@pytest.mark.jax
def test_scan_chunk_must_be_positive():
    trainer = make_trainer()
    with pytest.raises(ValueError, match="scan_chunk"):
        trainer.fit([make_batch(0)], epochs=1, scan_chunk=0)
