"""Out-of-core streaming fit: a parquet dataset bigger than the memory
budget trains through the chunked fit, mid-epoch SIGTERM + ``resume=True``
reproduces the uninterrupted run bit-for-bit by SEEKING the stream cursor
(no rescan), and the feed-efficiency/starvation telemetry lands in the run
artifact the CI ``stream_smoke`` job gates on.
"""

import json
import os
import signal

import numpy as np
import pandas as pd
import pytest

import jax

from replay_tpu.data import FeatureHint, FeatureType
from replay_tpu.data.nn import (
    ParquetBatcher,
    SequentialDataset,
    TensorFeatureInfo,
    TensorSchema,
    TransformedBatches,
    write_sequence_parquet,
)
from replay_tpu.nn import OptimizerFactory, Trainer, make_mesh
from replay_tpu.nn.loss import CE
from replay_tpu.nn.sequential.sasrec import SasRec
from replay_tpu.nn.transform import Compose
from replay_tpu.nn.transform.template import make_default_sasrec_transforms
from replay_tpu.obs import JsonlLogger, SLORule, Tracer
from replay_tpu.utils.checkpoint import CheckpointManager

NUM_ITEMS = 30
SEQ_LEN = 7  # -> [B, 6] training batches
BATCH = 8
BUDGET_BYTES = 256  # smaller than a row group: forces out-of-core sub-slabs


def _run_dir(tmp_path, name):
    """CI exports REPLAY_TPU_RUN_DIR so the streaming smoke telemetry ships
    as a workflow artifact; locally the run log lands in tmp_path."""
    base = os.environ.get("REPLAY_TPU_RUN_DIR")
    return os.path.join(base, name) if base else str(tmp_path / name)


def make_schema():
    return TensorSchema(
        TensorFeatureInfo(
            "item_id", FeatureType.CATEGORICAL, is_seq=True,
            feature_hint=FeatureHint.ITEM_ID, cardinality=NUM_ITEMS,
            embedding_dim=8,
        )
    )


@pytest.fixture(scope="module")
def stream_parquet(tmp_path_factory):
    schema = make_schema()
    rng = np.random.default_rng(0)
    n_rows = 61
    frame = pd.DataFrame(
        {
            "query_id": np.arange(n_rows),
            "item_id": [
                rng.integers(1, NUM_ITEMS, rng.integers(2, SEQ_LEN + 2)).astype(np.int64)
                for _ in range(n_rows)
            ],
        }
    )
    dataset = SequentialDataset(schema, "query_id", "item_id", frame)
    path = str(tmp_path_factory.mktemp("stream") / "seqs.parquet")
    write_sequence_parquet(path, dataset, rows_per_chunk=10)
    return path


def make_trainer():
    schema = make_schema()
    model = SasRec(
        schema=schema, embedding_dim=8, num_blocks=1, num_heads=1,
        max_sequence_length=SEQ_LEN - 1, dropout_rate=0.0,
    )
    return Trainer(
        model=model, loss=CE(),
        optimizer=OptimizerFactory(learning_rate=1e-2),
        mesh=make_mesh(), seed=0,
    )


def make_stream(path, **batcher_overrides):
    schema = make_schema()
    pipeline = Compose(make_default_sasrec_transforms(schema)["train"])
    kwargs = dict(
        source=path, batch_size=BATCH, shuffle=True, seed=0,
        shard="row_groups", memory_budget_bytes=BUDGET_BYTES, read_ahead=2,
        metadata={"item_id": {"shape": SEQ_LEN, "padding": 0}},
    )
    kwargs.update(batcher_overrides)
    batcher = ParquetBatcher(**kwargs)
    return batcher, TransformedBatches(
        batcher,
        lambda raw: pipeline(
            {
                "item_id": raw["item_id"],
                "item_id_mask": raw["item_id_mask"],
                "valid": raw["valid"],
            }
        ),
    )


class _SigtermAt:
    """Stream wrapper raising a REAL SIGTERM while batch ``at`` is fetched,
    forwarding the streaming protocol so the cursor machinery stays active."""

    def __init__(self, inner, at):
        self.inner = inner
        self.at = at
        self.position = 0
        self.raised = False

    def __iter__(self):
        for batch in self.inner:
            if self.position == self.at and not self.raised:
                self.raised = True
                signal.raise_signal(signal.SIGTERM)
            self.position += 1
            yield batch

    def set_epoch(self, epoch):
        self.inner.set_epoch(epoch)

    @property
    def supports_cursor(self):
        return self.inner.supports_cursor

    def cursor_for(self, k):
        return self.inner.cursor_for(k)

    def restore_cursor(self, cursor):
        self.inner.restore_cursor(cursor)

    @property
    def scan_compatible(self):
        return True


def assert_trees_equal(a, b):
    for left, right in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(left), np.asarray(right))


@pytest.mark.smoke
def test_out_of_core_dataset_exceeds_budget(stream_parquet):
    """The smoke dataset genuinely exceeds the memory budget: the epoch plan
    splits it into several bounded sub-slabs (out-of-core streaming)."""
    batcher, _ = make_stream(stream_parquet)
    batcher.set_epoch(0)
    slabs, _, _ = batcher._plan(0)
    total_bytes = os.path.getsize(stream_parquet)
    assert total_bytes > BUDGET_BYTES
    assert len(slabs) > 3
    unbudgeted, _, _ = make_stream(stream_parquet, memory_budget_bytes=None)[0]._plan(0)
    assert len(slabs) > len(unbudgeted)


@pytest.mark.smoke
def test_stream_fit_sigterm_resume_bit_for_bit(stream_parquet, tmp_path):
    """Acceptance: mid-epoch SIGTERM on the out-of-core chunked fit →
    position-stamped checkpoint WITH the stream cursor in the sidecar;
    ``resume=True`` seeks (slabs before the cursor are never re-read) and
    reproduces the uninterrupted run bit-for-bit — params, optimizer state,
    rng, step count and the final epoch's loss."""
    # uninterrupted reference: 2 epochs, scan-chunked + device-fed, with the
    # smoke artifact (events + trace + starvation SLO) for the CI job
    run_dir = _run_dir(tmp_path, "stream_smoke")
    trainer_a = make_trainer()
    _, stream_a = make_stream(stream_parquet)
    with JsonlLogger(run_dir, mode="w") as sink:
        state_a = trainer_a.fit(
            stream_a, epochs=2, scan_chunk=2, log_every=0, loggers=sink,
            tracer=True,
            # the device-feed path must keep I/O overlapped: starvation above
            # 90% of the stepping pipeline for 3 consecutive steps would fire
            slo_rules=[
                SLORule("replay_input_starvation", ">", 0.9, for_steps=3)
            ],
        )
    events = [json.loads(line) for line in open(os.path.join(run_dir, "events.jsonl"))]
    fit_end = [e for e in events if e.get("event") == "on_fit_end"][-1]
    assert 0.0 <= fit_end["input"]["padding_fraction"] < 1.0
    assert fit_end["input"]["tokens_real"] > 0
    assert not [e for e in events if e.get("event") == "on_slo_violation"]
    step_events = [e for e in events if e.get("event") == "on_train_step"]
    assert any("padding_fraction" in e for e in step_events)

    # preempted run: SIGTERM while batch 5 of epoch 0 is fetched
    trainer_b = make_trainer()
    manager = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=100)
    batcher_b, stream_b = make_stream(stream_parquet)
    sig = _SigtermAt(stream_b, at=5)
    state_mid = trainer_b.fit(
        sig, epochs=2, scan_chunk=2, log_every=0, checkpoint_manager=manager,
    )
    assert sig.raised
    assert int(state_mid.step) < int(state_a.step)
    meta = manager.metadata(manager.latest_step())
    assert meta["preempted"] and meta["mid_epoch"]
    cursor = meta["stream_cursor"]
    assert cursor["batches"] == meta["step_in_epoch"]

    # resume: the stream cursor seeks — count the slab reads to prove the
    # skipped prefix is never touched again
    trainer_c = make_trainer()
    batcher_c, stream_c = make_stream(stream_parquet)
    reads = []
    original = type(batcher_c)._read_slab

    def counting_read(self, path, slab):
        reads.append((slab.group, slab.start))
        return original(self, path, slab)

    batcher_c._read_slab = counting_read.__get__(batcher_c)
    state_c = trainer_c.fit(
        stream_c, epochs=2, scan_chunk=2, log_every=0,
        checkpoint_manager=manager, resume=True,
    )
    assert int(state_c.step) == int(state_a.step)
    assert_trees_equal(state_a.params, state_c.params)
    assert_trees_equal(state_a.opt_state, state_c.opt_state)
    np.testing.assert_array_equal(np.asarray(state_a.rng), np.asarray(state_c.rng))
    assert trainer_a.history[-1]["train_loss"] == trainer_c.history[-1]["train_loss"]
    total_slabs = len(batcher_c._plan(0)[0]) + len(batcher_c._plan(1)[0])
    skipped = int(cursor["slab"])
    assert skipped > 0  # the preemption landed past the first slab
    assert len(reads) <= total_slabs - skipped + 1


@pytest.mark.jax
def test_resume_without_cursor_falls_back_to_fast_forward(stream_parquet, tmp_path):
    """A sidecar without a stream cursor (older checkpoint, or a source that
    cannot seek) still resumes bit-for-bit through the consume-and-drop
    fast-forward path."""
    trainer_a = make_trainer()
    _, stream_a = make_stream(stream_parquet)
    state_a = trainer_a.fit(stream_a, epochs=2, scan_chunk=2, log_every=0)

    trainer_b = make_trainer()
    manager = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=100)
    _, stream_b = make_stream(stream_parquet)
    sig = _SigtermAt(stream_b, at=4)
    trainer_b.fit(
        sig, epochs=2, scan_chunk=2, log_every=0, checkpoint_manager=manager
    )
    step = manager.latest_step()
    meta = manager.metadata(step)
    assert "stream_cursor" in meta
    # strip the cursor, as an older-version checkpoint would look
    sidecar = manager._step_path(step).with_suffix(".json")
    stripped = {k: v for k, v in json.loads(sidecar.read_text()).items() if k != "stream_cursor"}
    sidecar.write_text(json.dumps(stripped))

    trainer_c = make_trainer()
    _, stream_c = make_stream(stream_parquet)
    state_c = trainer_c.fit(
        stream_c, epochs=2, scan_chunk=2, log_every=0,
        checkpoint_manager=manager, resume=True,
    )
    assert int(state_c.step) == int(state_a.step)
    assert_trees_equal(state_a.params, state_c.params)
    # the final (fully-measured) epoch's loss is bit-identical
    assert trainer_a.history[-1]["train_loss"] == trainer_c.history[-1]["train_loss"]


@pytest.mark.jax
def test_per_step_path_also_carries_cursor(stream_parquet, tmp_path):
    """The cursor contract holds on the un-chunked per-step fit too (the
    prefetch stage may read ahead of the executed step)."""
    trainer_a = make_trainer()
    _, stream_a = make_stream(stream_parquet)
    state_a = trainer_a.fit(stream_a, epochs=1, log_every=0, prefetch=2)

    trainer_b = make_trainer()
    manager = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=100)
    _, stream_b = make_stream(stream_parquet)
    sig = _SigtermAt(stream_b, at=3)
    trainer_b.fit(
        sig, epochs=1, log_every=0, prefetch=2, checkpoint_manager=manager
    )
    meta = manager.metadata(manager.latest_step())
    assert meta["stream_cursor"]["batches"] == meta["step_in_epoch"]

    trainer_c = make_trainer()
    _, stream_c = make_stream(stream_parquet)
    state_c = trainer_c.fit(
        stream_c, epochs=1, log_every=0, prefetch=2,
        checkpoint_manager=manager, resume=True,
    )
    assert int(state_c.step) == int(state_a.step)
    assert_trees_equal(state_a.params, state_c.params)


@pytest.mark.jax
def test_fit_reports_effective_tokens_in_step_events(stream_parquet):
    """Per-step events carry the feed-efficiency numbers and they are
    consistent with the batch shapes."""

    class Sink:
        def __init__(self):
            self.events = []

        def log_event(self, event):
            self.events.append(event)

    trainer = make_trainer()
    _, stream = make_stream(stream_parquet)
    sink = Sink()
    trainer.fit(stream, epochs=1, log_every=0, loggers=sink)
    steps = [e for e in sink.events if e.event == "on_train_step"]
    assert steps
    fractions = [
        e.payload["padding_fraction"]
        for e in steps
        if np.isfinite(e.payload.get("padding_fraction", float("nan")))
    ]
    assert fractions and all(0.0 <= f < 1.0 for f in fractions)
    fit_end = [e for e in sink.events if e.event == "on_fit_end"][-1]
    record = fit_end.payload["input"]
    assert record["tokens_grid"] % (BATCH * (SEQ_LEN - 1)) == 0
    assert 0 < record["tokens_real"] <= record["tokens_grid"]
