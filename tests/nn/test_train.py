"""Trainer end-to-end: SASRec trains through the template pipeline on the 8-device
CPU mesh (the reference's Lightning fit/validate/predict flow, SURVEY.md §3.2-3.3)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from replay_tpu.data import FeatureHint, FeatureType
from replay_tpu.data.nn import TensorFeatureInfo, TensorSchema
from replay_tpu.nn import (
    LRSchedulerFactory,
    OptimizerFactory,
    SeenItemsFilter,
    Trainer,
    make_mesh,
)
from replay_tpu.nn.loss import CE
from replay_tpu.nn.sequential.sasrec import SasRec
from replay_tpu.nn.transform import Compose
from replay_tpu.nn.transform.template import make_default_sasrec_transforms

NUM_ITEMS = 12
SEQ_LEN = 8
BATCH = 8  # divisible by the 8-device data axis


@pytest.fixture(scope="module")
def schema() -> TensorSchema:
    return TensorSchema(
        TensorFeatureInfo(
            "item_id",
            FeatureType.CATEGORICAL,
            is_seq=True,
            feature_hint=FeatureHint.ITEM_ID,
            cardinality=NUM_ITEMS,
            embedding_dim=16,
        )
    )


def make_raw_batch(rng: np.random.Generator):
    """Left-padded sequences following a deterministic next-item pattern
    (item i -> item (i+1) % N) so the model has signal to learn."""
    lengths = rng.integers(3, SEQ_LEN + 1, size=BATCH)
    items = np.full((BATCH, SEQ_LEN), NUM_ITEMS, dtype=np.int32)
    for b, n in enumerate(lengths):
        start = rng.integers(0, NUM_ITEMS)
        items[b, SEQ_LEN - n :] = (start + np.arange(n)) % NUM_ITEMS
    mask = items != NUM_ITEMS
    return {"item_id": items, "item_id_mask": mask}


@pytest.fixture(scope="module")
def pipelines(schema):
    return {
        split: Compose(transforms)
        for split, transforms in make_default_sasrec_transforms(schema).items()
    }


@pytest.fixture(scope="module")
def trained(schema, pipelines):
    """Train a small SASRec for a few steps; shared across assertions below."""
    rng = np.random.default_rng(7)
    model = SasRec(schema=schema, embedding_dim=16, num_blocks=1, num_heads=1,
                   max_sequence_length=SEQ_LEN)
    trainer = Trainer(
        model=model,
        loss=CE(),
        optimizer=OptimizerFactory(name="adam", learning_rate=5e-2),
        mesh=make_mesh(),
    )
    batches = [pipelines["train"](make_raw_batch(rng)) for _ in range(6)]
    state = None
    losses = []
    for epoch in range(4):
        for batch in batches:
            if state is None:
                state = trainer.init_state(batch)
            state, loss_value = trainer.train_step(state, batch)
            losses.append(float(loss_value))
    return trainer, state, losses


@pytest.mark.jax
@pytest.mark.smoke
def test_loss_decreases(trained):
    _, _, losses = trained
    assert np.mean(losses[-6:]) < np.mean(losses[:6]) * 0.8


@pytest.mark.jax
def test_validate_metrics(trained, pipelines):
    trainer, state, _ = trained
    rng = np.random.default_rng(3)
    raw = make_raw_batch(rng)
    eval_batch = pipelines["validate"](dict(raw))
    # ground truth = the true next item of each sequence; train = seen items
    items = raw["item_id"]
    last = items[np.arange(BATCH), -1]
    gt = ((last + 1) % NUM_ITEMS)[:, None].astype(np.int32)
    eval_batch["ground_truth"] = gt
    eval_batch["train"] = np.where(raw["item_id_mask"], items, -1)
    metrics = trainer.validate(state, [eval_batch], metrics=("ndcg", "recall", "hitrate"),
                               top_k=(1, 5))
    assert set(metrics) == {"ndcg@1", "ndcg@5", "recall@1", "recall@5", "hitrate@1", "hitrate@5"}
    # the pattern is deterministic; a trained model should rank the true next item highly
    assert metrics["recall@5"] > 0.5
    assert 0.0 <= metrics["ndcg@5"] <= 1.0


@pytest.mark.jax
def test_predict_top_k_and_seen_filter(trained, pipelines):
    trainer, state, _ = trained
    rng = np.random.default_rng(5)
    raw = make_raw_batch(rng)
    batch = pipelines["predict"](dict(raw))
    batch["query_id"] = np.arange(BATCH)
    queries, items, scores = trainer.predict_top_k(state, [batch], k=4)
    assert items.shape == (BATCH, 4) and scores.shape == (BATCH, 4)
    assert (np.diff(scores, axis=1) <= 1e-6).all()  # ranked descending
    assert ((items >= 0) & (items < NUM_ITEMS)).all()
    # seen filter: no recommended item may appear in the query's history;
    # seen ids for the filter: the raw input sequence (padding redirected out of range)
    batch["seen_ids"] = np.where(raw["item_id_mask"], raw["item_id"], NUM_ITEMS)
    _, f_items, _ = trainer.predict_top_k(
        state, [batch], k=4, postprocessors=[SeenItemsFilter(seen_field="seen_ids")]
    )
    for b in range(BATCH):
        seen = set(raw["item_id"][b][raw["item_id_mask"][b]].tolist())
        assert not seen.intersection(f_items[b].tolist())


@pytest.mark.jax
def test_predict_dataframe(trained, pipelines):
    trainer, state, _ = trained
    rng = np.random.default_rng(11)
    raw = make_raw_batch(rng)
    batch = pipelines["predict"](dict(raw))
    batch["query_id"] = np.arange(100, 100 + BATCH)
    frame = trainer.predict_dataframe(state, [batch], k=3)
    assert list(frame.columns) == ["query_id", "item_id", "rating"]
    assert len(frame) == BATCH * 3
    assert set(frame["query_id"]) == set(range(100, 100 + BATCH))


@pytest.mark.jax
def test_candidates_restricted_scoring(trained, pipelines):
    trainer, state, _ = trained
    rng = np.random.default_rng(13)
    raw = make_raw_batch(rng)
    batch = pipelines["predict"](dict(raw))
    candidates = jnp.array([1, 3, 5])
    _, items, _ = trainer.predict_top_k(state, [batch], k=2, candidates=candidates)
    assert set(items.reshape(-1).tolist()) <= {1, 3, 5}


def test_scheduler_factories():
    for kind in ("constant", "step", "warmup_linear", "warmup_cosine"):
        schedule = LRSchedulerFactory(kind=kind, warmup_steps=5, total_steps=20).create(1e-3)
        assert np.isfinite(float(schedule(0))) and np.isfinite(float(schedule(10)))
    with pytest.raises(ValueError):
        LRSchedulerFactory(kind="nope").create(1e-3)
    with pytest.raises(ValueError):
        OptimizerFactory(name="nope").create()


@pytest.mark.jax
def test_bfloat16_training_smoke(schema, pipelines):
    """The bench configuration (bf16 compute dtype) trains to finite losses."""
    import jax.numpy as jnp

    rng = np.random.default_rng(17)
    model = SasRec(schema=schema, embedding_dim=16, num_blocks=1,
                   max_sequence_length=SEQ_LEN, dtype=jnp.bfloat16)
    trainer = Trainer(model=model, loss=CE(),
                      optimizer=OptimizerFactory(learning_rate=1e-2))
    state, losses = None, []
    for _ in range(6):
        batch = pipelines["train"](make_raw_batch(rng))
        if state is None:
            state = trainer.init_state(batch)
        state, loss_value = trainer.train_step(state, batch)
        losses.append(float(loss_value))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]
    # parameters stay float32 (mixed precision: bf16 compute, f32 params)
    import jax
    assert all(l.dtype == jnp.float32 for l in jax.tree.leaves(state.params))


@pytest.mark.jax
def test_sce_loss_through_trainer(schema, pipelines):
    """Large-catalog SCE loss plugs into the trainer and converges."""
    from replay_tpu.nn.loss import SCE, SCEParams

    rng = np.random.default_rng(23)
    model = SasRec(schema=schema, embedding_dim=16, num_blocks=1,
                   max_sequence_length=SEQ_LEN)
    trainer = Trainer(
        model=model,
        loss=SCE(SCEParams(n_buckets=4, bucket_size_x=8, bucket_size_y=6)),
        optimizer=OptimizerFactory(learning_rate=2e-2),
    )
    batches = [pipelines["train"](make_raw_batch(rng)) for _ in range(5)]
    state, losses = None, []
    for _ in range(6):
        for batch in batches:
            if state is None:
                state = trainer.init_state(batch)
            state, loss_value = trainer.train_step(state, batch)
            losses.append(float(loss_value))
    assert all(np.isfinite(losses))
    assert np.mean(losses[-5:]) < np.mean(losses[:5])
    # the trained model still ranks the deterministic next item well
    raw = make_raw_batch(np.random.default_rng(29))
    logits = trainer.predict_logits(
        state, {"feature_tensors": {"item_id": raw["item_id"]},
                "padding_mask": raw["item_id_mask"]})
    assert logits.shape == (BATCH, NUM_ITEMS)


@pytest.mark.jax
def test_fit_multiple_validation_streams(schema, pipelines):
    """A dict of validation factories yields per-stream prefixed metrics
    (the reference's sequential CombinedLoader over several val paths)."""
    rng = np.random.default_rng(31)
    model = SasRec(schema=schema, embedding_dim=16, num_blocks=1, max_sequence_length=SEQ_LEN)
    trainer = Trainer(model=model, loss=CE(), optimizer=OptimizerFactory(learning_rate=1e-2))

    def make_val():
        raw = make_raw_batch(rng)
        batch = pipelines["validate"](dict(raw))
        last = raw["item_id"][np.arange(BATCH), -1]
        batch["ground_truth"] = ((last + 1) % NUM_ITEMS)[:, None].astype(np.int32)
        return [batch]

    state = trainer.fit(
        lambda e: [pipelines["train"](make_raw_batch(rng))],
        epochs=1,
        val_batches={"val_a": make_val, "val_b": make_val},
        metrics=("recall",), top_k=(5,),
    )
    record = trainer.history[-1]
    assert "val_a/recall@5" in record and "val_b/recall@5" in record


@pytest.mark.jax
def test_monitor_early_stopping_and_best_state(schema, pipelines):
    """fit(monitor=..., patience=...) returns the BEST state and stops early."""
    rng = np.random.default_rng(41)
    model = SasRec(schema=schema, embedding_dim=16, num_blocks=1, max_sequence_length=SEQ_LEN)
    # a big lr makes late epochs noisy, so train_loss (mode=min) has a real best
    trainer = Trainer(model=model, loss=CE(), optimizer=OptimizerFactory(learning_rate=1e-2))
    batches = [pipelines["train"](make_raw_batch(rng)) for _ in range(3)]
    state = trainer.fit(lambda e: batches, epochs=12, monitor="train_loss",
                        mode="min", patience=3)
    losses = [h["train_loss"] for h in trainer.history]
    best_epoch = int(np.argmin(losses))
    # stopped no later than best + patience
    assert len(losses) <= best_epoch + 1 + 3
    # the RETURNED state is the best epoch's snapshot: right step, live buffers
    assert int(state.step) == (best_epoch + 1) * 3
    assert np.isfinite(np.asarray(jax.tree.leaves(state.params)[0])).all()
    logits = trainer.predict_logits(
        state,
        {"feature_tensors": {"item_id": np.zeros((BATCH, SEQ_LEN), np.int32)},
         "padding_mask": np.ones((BATCH, SEQ_LEN), bool)},
    )
    assert logits.shape == (BATCH, NUM_ITEMS)
    with pytest.raises(KeyError, match="monitor"):
        trainer.fit(lambda e: batches, epochs=1, monitor="ndcg@10")
    with pytest.raises(ValueError, match="mode"):
        trainer.fit(lambda e: batches, epochs=1, monitor="train_loss", mode="sideways")


@pytest.mark.jax
@pytest.mark.parametrize("loss_name", ["CE", "CESampled", "BCE", "BCESampled",
                                       "LogInCE", "LogInCESampled", "LogOutCE", "SCE"])
def test_every_loss_trains_through_trainer(loss_name, schema, pipelines):
    """The trainer × loss matrix: every protocol loss runs a finite, decreasing
    training step stream on the same template batches."""
    from replay_tpu.nn import loss as loss_module
    from replay_tpu.nn.loss import SCE, SCEParams
    from replay_tpu.nn.transform import Compose, UniformNegativeSamplingTransform
    from replay_tpu.nn.transform.template import make_default_sasrec_transforms

    if loss_name == "SCE":
        loss = SCE(SCEParams(n_buckets=4, bucket_size_x=8, bucket_size_y=6))
    elif loss_name in ("LogInCE", "LogOutCE"):
        loss = getattr(loss_module, loss_name)(cardinality=NUM_ITEMS)
    else:
        loss = getattr(loss_module, loss_name)()
    sampled = "Sampled" in loss_name
    transforms = make_default_sasrec_transforms(schema)["train"]
    if sampled:
        transforms = transforms + [
            UniformNegativeSamplingTransform(cardinality=NUM_ITEMS, num_negative_samples=4)
        ]
    pipeline = Compose(transforms)
    model = SasRec(schema=schema, embedding_dim=16, num_blocks=1, max_sequence_length=SEQ_LEN)
    trainer = Trainer(model=model, loss=loss, optimizer=OptimizerFactory(learning_rate=2e-2))
    rng = np.random.default_rng(3)
    key = jax.random.PRNGKey(0)
    state, losses = None, []
    for _ in range(10):
        key, sub = jax.random.split(key)
        batch = pipeline(make_raw_batch(rng), sub if pipeline.needs_rng else None)
        if state is None:
            state = trainer.init_state(batch)
        state, loss_value = trainer.train_step(state, batch)
        losses.append(float(loss_value))
    assert all(np.isfinite(losses))
    assert np.mean(losses[-3:]) < np.mean(losses[:3])
