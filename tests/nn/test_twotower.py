"""TwoTower: in-batch-negative training, catalog scoring vs brute force, reader."""

import numpy as np
import pandas as pd
import pytest

import jax
import jax.numpy as jnp

from replay_tpu.data import FeatureHint, FeatureType
from replay_tpu.data.nn import TensorFeatureInfo, TensorSchema
from replay_tpu.nn import OptimizerFactory, Trainer
from replay_tpu.nn.loss import CESampled
from replay_tpu.nn.sequential.twotower import FeaturesReader, TwoTower
from replay_tpu.nn.transform import Compose
from replay_tpu.nn.transform.template import make_default_twotower_transforms

NUM_ITEMS = 12
SEQ_LEN = 6
BATCH = 8


@pytest.fixture(scope="module")
def schema() -> TensorSchema:
    return TensorSchema(
        TensorFeatureInfo(
            "item_id",
            FeatureType.CATEGORICAL,
            is_seq=True,
            feature_hint=FeatureHint.ITEM_ID,
            cardinality=NUM_ITEMS,
            embedding_dim=16,
        )
    )


@pytest.fixture(scope="module")
def item_schema() -> TensorSchema:
    return TensorSchema(
        TensorFeatureInfo("category", FeatureType.CATEGORICAL, cardinality=3, embedding_dim=16)
    )


@pytest.fixture(scope="module")
def item_feature_tensors():
    return {"category": (np.arange(NUM_ITEMS) % 3).astype(np.int32)}


def make_raw_batch(rng: np.random.Generator):
    items = np.full((BATCH, SEQ_LEN), NUM_ITEMS, dtype=np.int32)
    for b in range(BATCH):
        n = rng.integers(3, SEQ_LEN + 1)
        start = rng.integers(0, NUM_ITEMS)
        items[b, SEQ_LEN - n :] = (start + np.arange(n)) % NUM_ITEMS
    return {"item_id": items, "item_id_mask": items != NUM_ITEMS}


@pytest.fixture(scope="module")
def trained(schema, item_schema, item_feature_tensors):
    rng = np.random.default_rng(0)
    pipeline = Compose(make_default_twotower_transforms(schema)["train"])
    model = TwoTower(schema=schema, item_schema=item_schema, embedding_dim=16,
                     num_blocks=1, max_sequence_length=SEQ_LEN)
    trainer = Trainer(model=model, loss=CESampled(),
                      optimizer=OptimizerFactory(learning_rate=1e-2))
    state, losses = None, []
    raws = [make_raw_batch(rng) for _ in range(6)]
    for _ in range(10):
        for raw in raws:
            batch = pipeline(dict(raw))
            batch["item_feature_tensors"] = item_feature_tensors
            if state is None:
                state = trainer.init_state(batch)
            state, loss_value = trainer.train_step(state, batch)
            losses.append(float(loss_value))
    return trainer, state, losses, raws


@pytest.mark.jax
def test_template_emits_in_batch_negatives(schema):
    raw = make_raw_batch(np.random.default_rng(1))
    batch = Compose(make_default_twotower_transforms(schema)["train"])(raw)
    negatives = np.asarray(batch["negative_labels"])
    positives = np.asarray(batch["positive_labels"])
    assert negatives.shape == (BATCH,)
    np.testing.assert_array_equal(negatives, positives[:, -1, 0])


@pytest.mark.jax
def test_in_batch_training_loss_decreases(trained):
    _, _, losses, _ = trained
    assert np.mean(losses[-6:]) < np.mean(losses[:6]) * 0.9


@pytest.mark.jax
def test_retrieval_matches_brute_force(trained, item_feature_tensors):
    """Top-k through forward_inference must equal brute-force query·item scores."""
    trainer, state, _, raws = trained
    raw = raws[0]
    batch = {
        "feature_tensors": {"item_id": raw["item_id"]},
        "padding_mask": raw["item_id_mask"],
        "item_feature_tensors": item_feature_tensors,
    }
    logits = np.asarray(trainer.predict_logits(state, batch))
    assert logits.shape == (BATCH, NUM_ITEMS)

    model = trainer.model
    queries = model.apply(
        {"params": state.params},
        batch["feature_tensors"],
        batch["padding_mask"],
        method=TwoTower.get_query_embeddings,
    )
    items = model.apply(
        {"params": state.params},
        item_feature_tensors=item_feature_tensors,
        method=TwoTower.encode_items,
    )
    brute = np.asarray(queries) @ np.asarray(items).T
    np.testing.assert_allclose(logits, brute, rtol=1e-4, atol=1e-5)
    # and top-k selection agrees
    np.testing.assert_array_equal(
        np.asarray(jax.lax.top_k(jnp.asarray(logits), 3)[1]),
        np.asarray(jax.lax.top_k(jnp.asarray(brute), 3)[1]),
    )


@pytest.mark.jax
def test_item_features_change_scores(trained, item_feature_tensors):
    """The fused catalog features must actually influence the item tower."""
    trainer, state, _, raws = trained
    raw = raws[0]
    base = {
        "feature_tensors": {"item_id": raw["item_id"]},
        "padding_mask": raw["item_id_mask"],
        "item_feature_tensors": item_feature_tensors,
    }
    shuffled = dict(base)
    shuffled["item_feature_tensors"] = {
        "category": ((np.arange(NUM_ITEMS) + 1) % 3).astype(np.int32)
    }
    a = np.asarray(trainer.predict_logits(state, base))
    b = np.asarray(trainer.predict_logits(state, shuffled))
    assert not np.allclose(a, b)


def test_features_reader():
    item_schema = TensorSchema(
        TensorFeatureInfo("category", FeatureType.CATEGORICAL, cardinality=3, embedding_dim=8)
    )
    frame = pd.DataFrame({"item_id": [2, 0, 1], "category": [2, 0, 1]})
    tensors = FeaturesReader(item_schema, num_items=4).read(frame)
    np.testing.assert_array_equal(tensors["category"], [0, 1, 2, 0])  # id 3 missing -> 0
    with pytest.raises(ValueError, match="Duplicate"):
        FeaturesReader(item_schema).read(pd.DataFrame({"item_id": [0, 0], "category": [1, 2]}))
    with pytest.raises(ValueError, match="encoded"):
        FeaturesReader(item_schema, num_items=2).read(
            pd.DataFrame({"item_id": [0, 5], "category": [1, 2]})
        )


@pytest.mark.jax
def test_predict_uses_cached_catalog(trained, item_feature_tensors):
    """predict_top_k's cached-catalog path returns the same ranking as
    per-batch forward_inference, and encodes the catalog only once."""
    trainer, state, _, raws = trained
    raw = raws[0]
    batch = {
        "feature_tensors": {"item_id": raw["item_id"]},
        "padding_mask": raw["item_id_mask"],
        "item_feature_tensors": item_feature_tensors,
        "query_id": np.arange(BATCH),
    }
    _, items_cached, scores_cached = trainer.predict_top_k(state, [dict(batch)], k=4)
    per_batch = np.asarray(trainer.predict_logits(state, dict(batch)))
    order = np.argsort(-per_batch, axis=1)[:, :4]
    np.testing.assert_array_equal(items_cached, order)
    np.testing.assert_allclose(
        scores_cached, np.take_along_axis(per_batch, order, 1), rtol=1e-4, atol=1e-5
    )
    calls = {"n": 0}
    original = trainer._catalog_fn

    def counting(params, features):
        calls["n"] += 1
        return original(params, features)

    trainer._catalog_fn = counting
    trainer.predict_top_k(state, [dict(batch), dict(batch), dict(batch)], k=4)
    trainer._catalog_fn = original
    assert calls["n"] == 1  # one catalog encode for three batches


class _GateMerger(__import__("flax").linen.Module):
    """Context merger: gates the hidden state by a learned projection of the
    last item id embedding-index parity (a minimal ContextMergerProto)."""

    @__import__("flax").linen.compact
    def __call__(self, hidden, feature_tensors):
        import flax.linen as nn
        import jax.numpy as jnp

        signal = (feature_tensors["item_id"] % 2).astype(hidden.dtype)[..., None]
        gate = nn.Dense(hidden.shape[-1], name="gate")(signal)
        return hidden * jax.nn.sigmoid(gate)


def test_context_merger_changes_outputs_and_trains(schema):
    """context_merger (ref model.py:431,516) fuses input features into the
    query hidden states in BOTH training and inference paths."""
    rng = np.random.default_rng(3)
    batch = make_raw_batch(rng)
    plain = TwoTower(schema=schema, embedding_dim=16, max_sequence_length=SEQ_LEN)
    merged = TwoTower(
        schema=schema, embedding_dim=16, max_sequence_length=SEQ_LEN,
        context_merger=_GateMerger(),
    )
    feats = {"item_id": batch["item_id"]}
    mask = batch["item_id_mask"]
    # init through forward_inference so BOTH towers' params are created
    p_plain = plain.init(jax.random.PRNGKey(0), feats, mask, method=TwoTower.forward_inference)
    p_merged = merged.init(jax.random.PRNGKey(0), feats, mask, method=TwoTower.forward_inference)
    # the merger registers its own parameters
    assert "context_merger" in p_merged["params"]
    out_plain = plain.apply(p_plain, feats, mask)
    out_merged = merged.apply(p_merged, feats, mask)
    assert out_plain.shape == out_merged.shape
    assert not np.allclose(np.asarray(out_plain), np.asarray(out_merged))
    # inference path goes through the merger too
    scores = merged.apply(p_merged, feats, mask, method=TwoTower.forward_inference)
    assert scores.shape == (BATCH, NUM_ITEMS)
    # and it trains end-to-end through the shared Trainer
    trainer = Trainer(
        model=merged,
        loss=CESampled(),
        optimizer=OptimizerFactory(learning_rate=1e-2),
    )
    pipeline = Compose(make_default_twotower_transforms(schema)["train"])
    state, losses = None, []
    for i in range(4):
        batch = pipeline(dict(make_raw_batch(np.random.default_rng(i))))
        if state is None:
            state = trainer.init_state(batch)
        state, loss = trainer.train_step(state, batch)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
