"""Vocabulary surgery: catalog growth on trained parameters."""

import numpy as np
import pytest

import jax

from replay_tpu.data import FeatureHint, FeatureType
from replay_tpu.data.nn import TensorFeatureInfo, TensorSchema
from replay_tpu.nn import OptimizerFactory, Trainer
from replay_tpu.nn.loss import CE
from replay_tpu.nn.sequential.sasrec import SasRec
from replay_tpu.nn.vocabulary import append_item_embeddings, resize_item_embeddings, set_item_embeddings

pytestmark = pytest.mark.jax

NUM_ITEMS, SEQ_LEN, BATCH = 8, 5, 4


def make_schema(cardinality=NUM_ITEMS):
    return TensorSchema(
        TensorFeatureInfo("item_id", FeatureType.CATEGORICAL, is_seq=True,
                          feature_hint=FeatureHint.ITEM_ID, cardinality=cardinality,
                          embedding_dim=8)
    )


def make_batch(num_items, rng):
    items = rng.integers(0, num_items, (BATCH, SEQ_LEN + 1)).astype(np.int32)
    mask = np.ones((BATCH, SEQ_LEN), bool)
    return {
        "feature_tensors": {"item_id": items[:, :-1]},
        "padding_mask": mask,
        "positive_labels": items[:, 1:, None],
        "target_padding_mask": mask[:, :, None],
    }


def test_grow_shrink_and_replace():
    schema = make_schema()
    model = SasRec(schema=schema, embedding_dim=8, num_blocks=1, max_sequence_length=SEQ_LEN)
    rng = np.random.default_rng(0)
    params = model.init(jax.random.PRNGKey(0), {"item_id": np.zeros((2, SEQ_LEN), np.int32)},
                        np.ones((2, SEQ_LEN), bool))["params"]
    params = jax.tree.map(np.asarray, params)
    old_table = params["body"]["embedder"]["embedding_item_id"]["table"]["embedding"].copy()

    grown = resize_item_embeddings(params, schema, NUM_ITEMS + 3)
    new_table = grown["body"]["embedder"]["embedding_item_id"]["table"]["embedding"]
    assert new_table.shape == (NUM_ITEMS + 4, 8)
    np.testing.assert_array_equal(new_table[:NUM_ITEMS], old_table[:NUM_ITEMS])
    np.testing.assert_array_equal(new_table[-1], old_table[-1])  # padding row moved last
    np.testing.assert_allclose(new_table[NUM_ITEMS], old_table[:NUM_ITEMS].mean(0), rtol=1e-6)
    assert schema["item_id"].cardinality == NUM_ITEMS + 3
    assert schema["item_id"].padding_value == NUM_ITEMS + 3

    appended = append_item_embeddings(grown, schema, np.ones((2, 8)))
    table2 = appended["body"]["embedder"]["embedding_item_id"]["table"]["embedding"]
    assert table2.shape == (NUM_ITEMS + 6, 8)
    np.testing.assert_array_equal(table2[NUM_ITEMS + 3], np.ones(8))

    replaced = set_item_embeddings(appended, schema, np.full((4, 8), 2.0))
    table3 = replaced["body"]["embedder"]["embedding_item_id"]["table"]["embedding"]
    assert table3.shape == (5, 8)
    assert schema["item_id"].cardinality == 4


def test_trainer_resize_then_train():
    """Growth mid-lifecycle: the resized state trains and scores the new items."""
    schema = make_schema()
    model = SasRec(schema=schema, embedding_dim=8, num_blocks=1, max_sequence_length=SEQ_LEN)
    trainer = Trainer(model=model, loss=CE(), optimizer=OptimizerFactory(learning_rate=1e-2))
    rng = np.random.default_rng(0)
    state = trainer.init_state(make_batch(NUM_ITEMS, rng))
    for _ in range(3):
        state, _ = trainer.train_step(state, make_batch(NUM_ITEMS, rng))

    new_items = NUM_ITEMS + 4
    state = trainer.resize_vocabulary(state, new_items)
    # trains on batches that contain the NEW item ids
    for _ in range(3):
        state, loss_value = trainer.train_step(state, make_batch(new_items, rng))
    assert np.isfinite(float(loss_value))
    logits = trainer.predict_logits(
        state,
        {"feature_tensors": {"item_id": np.zeros((2, SEQ_LEN), np.int32)},
         "padding_mask": np.ones((2, SEQ_LEN), bool)},
    )
    assert logits.shape == (2, new_items)

def test_reference_named_wrappers_and_old_logits_identical():
    """set_item_embeddings_by_size (xavier rows, ref lightning.py:507) and
    get_item_embeddings: after growth, OLD-item logits are bit-identical —
    inputs embed the same rows and the tied head's first columns are the
    untouched fitted rows."""
    from replay_tpu.nn.vocabulary import (
        get_item_embeddings,
        set_item_embeddings_by_size,
        set_item_embeddings_by_tensor,
    )

    schema = make_schema()
    model = SasRec(schema=schema, embedding_dim=8, num_blocks=1,
                   max_sequence_length=SEQ_LEN)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, NUM_ITEMS, (3, SEQ_LEN)).astype(np.int32)
    mask = np.ones((3, SEQ_LEN), bool)
    params = model.init(jax.random.PRNGKey(0), {"item_id": ids}, mask)["params"]
    params = jax.tree.map(np.asarray, params)
    before = np.asarray(model.apply({"params": params}, {"item_id": ids}, mask,
                                    method=SasRec.forward_inference))
    fitted = get_item_embeddings(params, schema)
    assert fitted.shape == (NUM_ITEMS, 8)

    with pytest.raises(ValueError, match="greater"):
        set_item_embeddings_by_size(params, schema, NUM_ITEMS)
    grown = set_item_embeddings_by_size(params, schema, NUM_ITEMS + 5,
                                        rng=jax.random.PRNGKey(7))
    grown_model = SasRec(schema=schema, embedding_dim=8, num_blocks=1,
                         max_sequence_length=SEQ_LEN)
    after = np.asarray(grown_model.apply({"params": grown}, {"item_id": ids}, mask,
                                         method=SasRec.forward_inference))
    assert after.shape == (3, NUM_ITEMS + 5)
    np.testing.assert_array_equal(after[:, :NUM_ITEMS], before)
    new_rows = get_item_embeddings(grown, schema)[NUM_ITEMS:]
    assert np.abs(new_rows).max() > 0  # xavier, not zeros
    assert not np.allclose(new_rows, fitted.mean(0))  # NOT the mean-init path

    replacement = np.full((NUM_ITEMS + 5, 8), 2.0, np.float32)
    replaced = set_item_embeddings_by_tensor(grown, schema, replacement)
    np.testing.assert_array_equal(get_item_embeddings(replaced, schema), replacement)


def test_bert4rec_surgery_and_warm_start_state():
    """Surgery works on Bert4Rec too, and Trainer.init_state(params=...) seeds
    a fresh optimizer around existing weights (the retrain-after-surgery flow
    without Trainer.resize_vocabulary)."""
    from replay_tpu.nn.sequential.bert4rec import Bert4Rec
    from replay_tpu.nn.vocabulary import get_item_embeddings, set_item_embeddings_by_size

    schema = make_schema()
    model = Bert4Rec(schema=schema, embedding_dim=8, num_blocks=1, num_heads=2,
                     max_sequence_length=SEQ_LEN)
    params = model.init(jax.random.PRNGKey(0),
                        {"item_id": np.zeros((2, SEQ_LEN), np.int32)},
                        np.ones((2, SEQ_LEN), bool))["params"]
    params = jax.tree.map(np.asarray, params)
    grown = set_item_embeddings_by_size(params, schema, NUM_ITEMS + 2)
    assert get_item_embeddings(grown, schema).shape == (NUM_ITEMS + 2, 8)

    new_model = Bert4Rec(schema=schema, embedding_dim=8, num_blocks=1, num_heads=2,
                         max_sequence_length=SEQ_LEN)
    trainer = Trainer(model=new_model, loss=CE(),
                      optimizer=OptimizerFactory(name="sgd", learning_rate=0.1))
    rng = np.random.default_rng(1)
    batch = make_batch(NUM_ITEMS + 2, rng)
    state = trainer.init_state(batch, params=grown)
    np.testing.assert_array_equal(
        get_item_embeddings(jax.tree.map(np.asarray, state.params), schema),
        get_item_embeddings(grown, schema),
    )
    losses = []
    for _ in range(6):
        state, loss_value = trainer.train_step(state, batch)
        losses.append(float(loss_value))
    assert losses[-1] < losses[0]


# --------------------------------------------------------------------------- #
# optimizer-state-safe surgery (continual training, docs/robustness.md)
# --------------------------------------------------------------------------- #
def _item_moments(opt_state):
    """Every optimizer-state leaf mirroring the item table, as numpy."""
    from replay_tpu.nn.vocabulary import _find_moment_leaves

    return [
        np.asarray(leaf)
        for _, leaf in _find_moment_leaves(
            jax.tree.map(np.asarray, opt_state), "item_id"
        )
    ]


def _trained_state(trainer, rng, steps=3, num_items=NUM_ITEMS):
    state = trainer.init_state(make_batch(num_items, rng))
    for _ in range(steps):
        state, _ = trainer.train_step(state, make_batch(num_items, rng))
    return state


def test_resize_vocabulary_carries_adam_moments_in_lockstep():
    """Mid-run growth: trained rows keep their mu/nu, cold rows start at
    zero, the padding row's moments move to the new end with it."""
    schema = make_schema()
    model = SasRec(schema=schema, embedding_dim=8, num_blocks=1, max_sequence_length=SEQ_LEN)
    trainer = Trainer(model=model, loss=CE(), optimizer=OptimizerFactory(learning_rate=1e-2))
    state = _trained_state(trainer, np.random.default_rng(0))
    before = _item_moments(state.opt_state)
    assert len(before) >= 2  # adam: mu and nu at least
    assert any(np.abs(m).max() > 0 for m in before)  # the moments are TRAINED

    grown = trainer.resize_vocabulary(state, NUM_ITEMS + 4)  # carry_opt_state default
    after = _item_moments(grown.opt_state)
    assert len(after) == len(before)
    for old, new in zip(before, after):
        assert new.shape == (NUM_ITEMS + 5, 8)
        np.testing.assert_array_equal(new[:NUM_ITEMS], old[:NUM_ITEMS])
        np.testing.assert_array_equal(new[NUM_ITEMS:-1], 0.0)  # cold rows: fresh
        np.testing.assert_array_equal(new[-1], old[-1])  # padding moments moved last
    # step/rng carry over and the state still trains on the new ids
    rng = np.random.default_rng(7)
    grown, loss_value = trainer.train_step(grown, make_batch(NUM_ITEMS + 4, rng))
    assert np.isfinite(float(loss_value))


def test_resize_item_embeddings_opt_state_roundtrip_and_out_of_sync_guard():
    from replay_tpu.nn.vocabulary import resize_optimizer_state

    schema = make_schema()
    model = SasRec(schema=schema, embedding_dim=8, num_blocks=1, max_sequence_length=SEQ_LEN)
    trainer = Trainer(model=model, loss=CE(), optimizer=OptimizerFactory(learning_rate=1e-2))
    state = _trained_state(trainer, np.random.default_rng(1))
    params = jax.tree.map(np.asarray, state.params)
    opt_host = jax.tree.map(np.asarray, state.opt_state)

    params2, opt2 = resize_item_embeddings(
        params, schema, NUM_ITEMS + 2, opt_state=opt_host
    )
    table = params2["body"]["embedder"]["embedding_item_id"]["table"]["embedding"]
    assert table.shape == (NUM_ITEMS + 3, 8)
    for moment in _item_moments(opt2):
        assert moment.shape == (NUM_ITEMS + 3, 8)

    # resizing AGAIN with the schema already moved but the OLD opt state is
    # the out-of-sync case: the error names the path, not an optax traceback
    with pytest.raises(ValueError, match="out of sync"):
        resize_optimizer_state(opt_host, "item_id", NUM_ITEMS + 2, NUM_ITEMS + 4)


def test_fit_rejects_resumed_state_with_stale_opt_state():
    """The satellite guard: params grown without their moments must fail at
    fit start with an error NAMING the table path."""
    schema = make_schema()
    model = SasRec(schema=schema, embedding_dim=8, num_blocks=1, max_sequence_length=SEQ_LEN)
    trainer = Trainer(model=model, loss=CE(), optimizer=OptimizerFactory(learning_rate=1e-2))
    rng = np.random.default_rng(2)
    state = _trained_state(trainer, rng)
    grown_params = resize_item_embeddings(
        jax.tree.map(np.asarray, state.params), schema, NUM_ITEMS + 4
    )
    stale = state.replace(params=grown_params)  # opt_state NOT resized
    with pytest.raises(ValueError, match="embedding_item_id"):
        trainer.fit([make_batch(NUM_ITEMS + 4, rng)], epochs=1, state=stale)


def test_validate_optimizer_state_passes_on_consistent_pair():
    from replay_tpu.nn.vocabulary import validate_optimizer_state

    schema = make_schema()
    model = SasRec(schema=schema, embedding_dim=8, num_blocks=1, max_sequence_length=SEQ_LEN)
    trainer = Trainer(model=model, loss=CE(), optimizer=OptimizerFactory(learning_rate=1e-2))
    state = _trained_state(trainer, np.random.default_rng(3), steps=1)
    validate_optimizer_state(state.params, state.opt_state, schema)  # no raise
    grown = trainer.resize_vocabulary(state, NUM_ITEMS + 4)
    validate_optimizer_state(grown.params, grown.opt_state, schema)  # still in sync


def test_finetune_entry_grows_then_fits_from_trained_state():
    """Trainer.finetune: the continual-training seam — optional xavier-grown
    catalog, optimizer moments carried, then a plain fit on the fresh tail."""
    schema = make_schema()
    model = SasRec(schema=schema, embedding_dim=8, num_blocks=1, max_sequence_length=SEQ_LEN)
    trainer = Trainer(model=model, loss=CE(), optimizer=OptimizerFactory(learning_rate=1e-2))
    rng = np.random.default_rng(4)
    state = _trained_state(trainer, rng)
    old_table = np.asarray(
        jax.tree.map(np.asarray, state.params)
        ["body"]["embedder"]["embedding_item_id"]["table"]["embedding"]
    ).copy()

    tail = [make_batch(NUM_ITEMS + 4, rng) for _ in range(2)]
    tuned = trainer.finetune(state, tail, new_cardinality=NUM_ITEMS + 4)
    table = np.asarray(
        jax.tree.map(np.asarray, tuned.params)
        ["body"]["embedder"]["embedding_item_id"]["table"]["embedding"]
    )
    assert table.shape == (NUM_ITEMS + 5, 8)
    assert schema["item_id"].cardinality == NUM_ITEMS + 4
    # the fit actually trained (params moved) and shrink is refused
    assert np.abs(table[:NUM_ITEMS] - old_table[:NUM_ITEMS]).max() > 0
    with pytest.raises(ValueError, match="shrink"):
        trainer.finetune(tuned, tail, new_cardinality=NUM_ITEMS)
