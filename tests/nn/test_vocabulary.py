"""Vocabulary surgery: catalog growth on trained parameters."""

import numpy as np
import pytest

import jax

from replay_tpu.data import FeatureHint, FeatureType
from replay_tpu.data.nn import TensorFeatureInfo, TensorSchema
from replay_tpu.nn import OptimizerFactory, Trainer
from replay_tpu.nn.loss import CE
from replay_tpu.nn.sequential.sasrec import SasRec
from replay_tpu.nn.vocabulary import append_item_embeddings, resize_item_embeddings, set_item_embeddings

pytestmark = pytest.mark.jax

NUM_ITEMS, SEQ_LEN, BATCH = 8, 5, 4


def make_schema(cardinality=NUM_ITEMS):
    return TensorSchema(
        TensorFeatureInfo("item_id", FeatureType.CATEGORICAL, is_seq=True,
                          feature_hint=FeatureHint.ITEM_ID, cardinality=cardinality,
                          embedding_dim=8)
    )


def make_batch(num_items, rng):
    items = rng.integers(0, num_items, (BATCH, SEQ_LEN + 1)).astype(np.int32)
    mask = np.ones((BATCH, SEQ_LEN), bool)
    return {
        "feature_tensors": {"item_id": items[:, :-1]},
        "padding_mask": mask,
        "positive_labels": items[:, 1:, None],
        "target_padding_mask": mask[:, :, None],
    }


def test_grow_shrink_and_replace():
    schema = make_schema()
    model = SasRec(schema=schema, embedding_dim=8, num_blocks=1, max_sequence_length=SEQ_LEN)
    rng = np.random.default_rng(0)
    params = model.init(jax.random.PRNGKey(0), {"item_id": np.zeros((2, SEQ_LEN), np.int32)},
                        np.ones((2, SEQ_LEN), bool))["params"]
    params = jax.tree.map(np.asarray, params)
    old_table = params["body"]["embedder"]["embedding_item_id"]["table"]["embedding"].copy()

    grown = resize_item_embeddings(params, schema, NUM_ITEMS + 3)
    new_table = grown["body"]["embedder"]["embedding_item_id"]["table"]["embedding"]
    assert new_table.shape == (NUM_ITEMS + 4, 8)
    np.testing.assert_array_equal(new_table[:NUM_ITEMS], old_table[:NUM_ITEMS])
    np.testing.assert_array_equal(new_table[-1], old_table[-1])  # padding row moved last
    np.testing.assert_allclose(new_table[NUM_ITEMS], old_table[:NUM_ITEMS].mean(0), rtol=1e-6)
    assert schema["item_id"].cardinality == NUM_ITEMS + 3
    assert schema["item_id"].padding_value == NUM_ITEMS + 3

    appended = append_item_embeddings(grown, schema, np.ones((2, 8)))
    table2 = appended["body"]["embedder"]["embedding_item_id"]["table"]["embedding"]
    assert table2.shape == (NUM_ITEMS + 6, 8)
    np.testing.assert_array_equal(table2[NUM_ITEMS + 3], np.ones(8))

    replaced = set_item_embeddings(appended, schema, np.full((4, 8), 2.0))
    table3 = replaced["body"]["embedder"]["embedding_item_id"]["table"]["embedding"]
    assert table3.shape == (5, 8)
    assert schema["item_id"].cardinality == 4


def test_trainer_resize_then_train():
    """Growth mid-lifecycle: the resized state trains and scores the new items."""
    schema = make_schema()
    model = SasRec(schema=schema, embedding_dim=8, num_blocks=1, max_sequence_length=SEQ_LEN)
    trainer = Trainer(model=model, loss=CE(), optimizer=OptimizerFactory(learning_rate=1e-2))
    rng = np.random.default_rng(0)
    state = trainer.init_state(make_batch(NUM_ITEMS, rng))
    for _ in range(3):
        state, _ = trainer.train_step(state, make_batch(NUM_ITEMS, rng))

    new_items = NUM_ITEMS + 4
    state = trainer.resize_vocabulary(state, new_items)
    # trains on batches that contain the NEW item ids
    for _ in range(3):
        state, loss_value = trainer.train_step(state, make_batch(new_items, rng))
    assert np.isfinite(float(loss_value))
    logits = trainer.predict_logits(
        state,
        {"feature_tensors": {"item_id": np.zeros((2, SEQ_LEN), np.int32)},
         "padding_mask": np.ones((2, SEQ_LEN), bool)},
    )
    assert logits.shape == (2, new_items)