"""Worker for the real-SIGKILL flight-recorder round trip.

Writes ``count`` records into the ring at ``sys.argv[1]``, then — without
any flush, close, or atexit — delivers ``SIGKILL`` to itself. The parent
test (tests/obs/test_blackbox.py) reads the ring back and must recover every
record: the whole point of the page-cache durability story.

The module is loaded straight from ``replay_tpu/obs/blackbox.py`` by file
path (stdlib-only), so the subprocess never pays a jax import.
"""

import importlib.util
import os
import signal
import sys
from pathlib import Path

_BLACKBOX = Path(__file__).resolve().parents[2] / "replay_tpu" / "obs" / "blackbox.py"


def load_blackbox():
    spec = importlib.util.spec_from_file_location("blackbox", _BLACKBOX)
    module = importlib.util.module_from_spec(spec)
    # dataclasses resolves the defining module through sys.modules
    sys.modules[spec.name] = module
    spec.loader.exec_module(module)
    return module


def main() -> None:
    ring_path, count = sys.argv[1], int(sys.argv[2])
    blackbox = load_blackbox()
    recorder = blackbox.FlightRecorder(ring_path, capacity=64)
    for step in range(count):
        recorder.record({"event": "on_train_step", "step": step, "loss": 0.5 - step / 100.0})
    # no flush, no close: the dirty pages in the OS page cache are all the
    # durability a SIGKILL leaves — and all the recorder needs
    os.kill(os.getpid(), signal.SIGKILL)
    raise AssertionError("survived SIGKILL")  # pragma: no cover


if __name__ == "__main__":
    main()
