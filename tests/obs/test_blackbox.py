"""The black box: ring semantics, torn-tail forensics, and a real SIGKILL.

Core tier: pure-python ring mechanics plus the CRC fuzz — every byte offset
of the final record corrupted and every truncation point cut, with
``read_flight`` required to never raise, never return a corrupt record, and
to report ``torn_tail`` exactly when the ring is damaged. The SIGKILL round
trip spawns a stdlib-only subprocess (no jax import) that dies by real
``kill -9`` mid-recording. The jax-marked smoke closes the loop through
``Trainer.fit(flight_path=...)``.
"""

import json
import os
import signal
import struct
import subprocess
import sys
from pathlib import Path

import pytest

from replay_tpu.obs.blackbox import (
    HEADER_SIZE,
    RECORD_HEADER,
    BlackboxLogger,
    FlightRecorder,
    read_flight,
)
from replay_tpu.obs.events import TrainerEvent

WORKER = Path(__file__).with_name("flight_kill_worker.py")


# -- ring mechanics ---------------------------------------------------------- #
def test_roundtrip_preserves_records_in_seqno_order(tmp_path):
    ring = str(tmp_path / "flight.ring")
    with FlightRecorder(ring, capacity=16) as rec:
        for step in range(5):
            assert rec.record({"event": "on_train_step", "step": step}) == step + 1
    log = read_flight(ring)
    assert log.recovered == 5
    assert log.last_seqno == 5
    assert not log.torn_tail
    assert [r["step"] for r in log.records] == list(range(5))
    assert [r["seqno"] for r in log.records] == [1, 2, 3, 4, 5]


def test_ring_wraps_keeping_the_last_capacity_records(tmp_path):
    ring = str(tmp_path / "flight.ring")
    with FlightRecorder(ring, capacity=8) as rec:
        for step in range(20):
            rec.record({"event": "on_train_step", "step": step})
    log = read_flight(ring)
    assert log.recovered == 8  # one full lap of evidence, never more
    assert log.last_seqno == 20
    assert [r["step"] for r in log.records] == list(range(12, 20))
    assert not log.torn_tail
    # the file never grows past its preallocated size — O(1) stores, no append
    assert os.path.getsize(ring) == HEADER_SIZE + 8 * log.record_size


def test_reopen_resumes_after_the_dead_writers_last_seqno(tmp_path):
    ring = str(tmp_path / "flight.ring")
    with FlightRecorder(ring, capacity=16, record_size=192) as rec:
        rec.record({"event": "on_serve_start"})
        rec.record({"event": "on_serve_batch", "rows": 4})
    # a respawned process reopens the same path: geometry is adopted from the
    # file (ctor args ignored) and recording continues — the predecessor's
    # records are evidence, never clobbered
    with FlightRecorder(ring, capacity=4, record_size=64) as rec:
        assert rec.capacity == 16
        assert rec.record_size == 192
        assert rec.record({"event": "on_serve_start", "respawn": True}) == 3
    log = read_flight(ring)
    assert log.recovered == 3
    assert [r["event"] for r in log.records] == [
        "on_serve_start", "on_serve_batch", "on_serve_start",
    ]


def test_oversized_payload_is_whittled_never_refused(tmp_path):
    ring = str(tmp_path / "flight.ring")
    with FlightRecorder(ring, capacity=4, record_size=128) as rec:
        rec.record({
            "event": "on_epoch_end",
            "step": 7,
            "blob": "x" * 10_000,
            "loss": 0.25,
        })
    log = read_flight(ring)
    assert log.recovered == 1
    record = log.records[0]
    assert record["event"] == "on_epoch_end"
    assert record["step"] == 7  # kept to the end while the blob went first
    assert "blob" not in record
    assert not log.torn_tail


def test_record_after_close_is_dropped_not_raised(tmp_path):
    rec = FlightRecorder(str(tmp_path / "flight.ring"), capacity=4)
    rec.record({"event": "on_serve_start"})
    rec.close()
    assert rec.record({"event": "late"}) == 1  # no-op, returns last seqno
    rec.flush()  # also safe


def test_non_rings_raise_loudly(tmp_path):
    missing = tmp_path / "nope.ring"
    with pytest.raises((OSError, ValueError)):
        read_flight(str(missing))
    garbage = tmp_path / "garbage.ring"
    garbage.write_bytes(b"not a flight ring at all" * 10)
    with pytest.raises(ValueError, match="magic"):
        read_flight(str(garbage))


# -- the RunLogger bridge ---------------------------------------------------- #
def test_blackbox_logger_bridges_trainer_events(tmp_path):
    ring = str(tmp_path / "flight.ring")
    with BlackboxLogger(ring, capacity=32, meta={"role": "test", "pid": 123}) as sink:
        sink.log_event(TrainerEvent(
            event="on_train_step", step=3, epoch=0,
            payload={"loss": 0.5, "grad_norm": 1.25},
        ))
        sink.log_event(TrainerEvent(
            event="on_serve_shed",
            payload={"reason": "queue_full", "queued": 512,
                     "telemetry": {"a": 1, "b": 2}},
        ))
    log = read_flight(ring)
    assert [r["event"] for r in log.records] == [
        "flight_open", "on_train_step", "on_serve_shed",
    ]
    assert log.records[0]["role"] == "test"
    step = log.records[1]
    assert step["step"] == 3 and step["loss"] == 0.5 and step["grad_norm"] == 1.25
    shed = log.records[2]
    assert shed["reason"] == "queue_full" and shed["queued"] == 512
    assert shed["telemetry"] == "<2 keys>"  # containers shrink, never dropped


# -- torn-ring forensics: the CRC fuzz --------------------------------------- #
def _pristine_ring(tmp_path, records=4, capacity=8, record_size=128):
    """A clean closed ring plus the byte geometry of its FINAL record."""
    ring = str(tmp_path / "pristine.ring")
    with FlightRecorder(ring, capacity=capacity, record_size=record_size) as rec:
        for step in range(records):
            rec.record({"event": "on_train_step", "step": step})
    raw = Path(ring).read_bytes()
    final_slot = (records - 1) % capacity
    final_offset = HEADER_SIZE + final_slot * record_size
    _, _, length, _ = RECORD_HEADER.unpack_from(raw, final_offset)
    content_end = final_offset + RECORD_HEADER.size + length
    baseline = read_flight(ring)
    assert baseline.recovered == records and not baseline.torn_tail
    return ring, raw, final_offset, content_end, baseline


def test_truncation_fuzz_every_byte_of_the_final_record(tmp_path):
    ring, raw, final_offset, content_end, baseline = _pristine_ring(tmp_path)
    final_seqno = baseline.last_seqno
    prior = [r for r in baseline.records if r["seqno"] != final_seqno]
    target = str(tmp_path / "cut.ring")
    for cut in range(final_offset, len(raw) + 1):
        Path(target).write_bytes(raw[:cut])
        log = read_flight(target)  # must never raise for a valid header
        # records it does return are byte-faithful — never partially decoded
        assert [r for r in log.records if r["seqno"] != final_seqno] == prior, cut
        final = [r for r in log.records if r["seqno"] == final_seqno]
        if cut >= len(raw):
            assert not log.torn_tail and final == [baseline.records[-1]]
            continue
        # any cut below the preallocated size is reported as torn...
        assert log.torn_tail and log.truncated, cut
        # ...and the final record survives it exactly when the cut spared its
        # actual content (the zero padding past `length` is not evidence)
        if cut >= content_end:
            assert final == [baseline.records[-1]], cut
        else:
            assert final == [], cut


def test_corruption_fuzz_every_byte_of_the_final_record(tmp_path):
    ring, raw, final_offset, content_end, baseline = _pristine_ring(tmp_path)
    final_seqno = baseline.last_seqno
    prior = [r for r in baseline.records if r["seqno"] != final_seqno]
    target = str(tmp_path / "flip.ring")
    for offset in range(final_offset, content_end):
        mutated = bytearray(raw)
        mutated[offset] ^= 0xFF
        Path(target).write_bytes(bytes(mutated))
        log = read_flight(target)  # must never raise
        # every untouched record is returned intact
        assert [r for r in log.records if r["seqno"] != final_seqno] == prior, offset
        final = [r for r in log.records if r["seqno"] == final_seqno]
        # the flipped record either fails verification (reported torn) or —
        # never — sneaks through changed: no corrupt record ever escapes
        if final:
            assert final == [baseline.records[-1]], offset
        else:
            assert log.torn_tail and log.dropped >= 1, offset


def test_torn_tail_of_a_simulated_mid_store_kill(tmp_path):
    """The exact SIGKILL shape: the final slot holds a half-written frame."""
    ring, raw, final_offset, _, baseline = _pristine_ring(tmp_path)
    torn = bytearray(raw)
    # the writer died 10 bytes into the final record's in-place store
    for offset in range(final_offset + 10, final_offset + baseline.record_size):
        torn[offset] = 0
    target = str(tmp_path / "torn.ring")
    Path(target).write_bytes(bytes(torn))
    log = read_flight(target)
    assert log.torn_tail and log.dropped == 1
    assert log.recovered == baseline.recovered - 1
    assert log.records == baseline.records[:-1]


# -- the real thing ---------------------------------------------------------- #
def test_real_sigkill_leaves_every_record_readable(tmp_path):
    ring = str(tmp_path / "killed.ring")
    proc = subprocess.run(
        [sys.executable, str(WORKER), ring, "25"],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == -signal.SIGKILL, proc.stderr[-500:]
    log = read_flight(ring)
    # no flush ever ran in the worker: the page cache alone preserved this
    assert log.recovered == 25
    assert log.last_seqno == 25
    assert not log.torn_tail  # the kill landed between stores, not inside one
    assert [r["step"] for r in log.records] == list(range(25))


def test_sigkilled_writers_ring_is_resumable_without_losing_evidence(tmp_path):
    ring = str(tmp_path / "killed.ring")
    proc = subprocess.run(
        [sys.executable, str(WORKER), ring, "10"],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == -signal.SIGKILL
    # the respawn (same path) continues after the corpse's last seqno
    with FlightRecorder(ring) as rec:
        assert rec.record({"event": "on_fit_start", "respawn": True}) == 11
    log = read_flight(ring)
    assert log.recovered == 11
    assert log.records[-1]["respawn"] is True


# -- Trainer.fit integration (jax tier) -------------------------------------- #
@pytest.mark.jax
@pytest.mark.smoke
def test_fit_records_into_the_flight_ring(tmp_path, monkeypatch):
    import numpy as np

    from replay_tpu.data import FeatureHint, FeatureType
    from replay_tpu.data.nn import TensorFeatureInfo, TensorSchema
    from replay_tpu.nn import OptimizerFactory, Trainer, make_mesh
    from replay_tpu.nn.loss import CE
    from replay_tpu.nn.sequential.sasrec import SasRec

    num_items, seq_len, batch = 12, 8, 8
    schema = TensorSchema(
        TensorFeatureInfo(
            "item_id", FeatureType.CATEGORICAL, is_seq=True,
            feature_hint=FeatureHint.ITEM_ID, cardinality=num_items,
            embedding_dim=16,
        )
    )
    model = SasRec(
        schema=schema, embedding_dim=16, num_blocks=1, num_heads=1,
        max_sequence_length=seq_len,
    )
    trainer = Trainer(
        model=model, loss=CE(), optimizer=OptimizerFactory(learning_rate=1e-2),
        mesh=make_mesh(),
    )
    rng = np.random.default_rng(0)
    items = rng.integers(0, num_items, size=(batch, seq_len + 1)).astype(np.int32)
    batch_dict = {
        "feature_tensors": {"item_id": items[:, :-1]},
        "padding_mask": np.ones((batch, seq_len), bool),
        "positive_labels": items[:, 1:, None],
        "target_padding_mask": np.ones((batch, seq_len, 1), bool),
    }

    # the env hand-off: launch_workers sets REPLAY_TPU_FLIGHT_PATH; fit picks
    # it up with no explicit argument — worker scripts need no change
    ring = str(tmp_path / "fit.ring")
    monkeypatch.setenv("REPLAY_TPU_FLIGHT_PATH", ring)
    trainer.fit(lambda epoch: [batch_dict] * 3, epochs=1, log_every=0)

    log = read_flight(ring)
    events = [r["event"] for r in log.records]
    assert events[0] == "flight_open"
    assert "on_train_step" in events
    assert events[-1] == "on_fit_end"
    assert not log.torn_tail
    # loss lands one step late (async dispatch): every loss that IS present
    # bridged through as a plain float, and at least one made it
    losses = [r["loss"] for r in log.records
              if r["event"] == "on_train_step" and "loss" in r]
    assert losses and all(isinstance(loss, float) for loss in losses)
    open_record = log.records[0]
    assert open_record["role"] == "fit"
    assert open_record["pid"] == os.getpid()
