"""Metrics exporter (obs.exporter): scrape endpoints under concurrent load.

Core tier, no jax: a stdlib HTTP server over a stdlib registry. The
concurrency test is the satellite's contract — scrapes racing writers must
never see a torn line, a non-monotone counter, or deadlock.
"""

import json
import threading
import urllib.error
import urllib.request

import pytest

from replay_tpu.obs.exporter import MetricsExporter
from replay_tpu.obs.metrics import MetricsRegistry

pytestmark = pytest.mark.core


def _get(url, timeout=10.0):
    with urllib.request.urlopen(url, timeout=timeout) as response:
        return response.status, response.read().decode()


@pytest.fixture
def served_registry():
    registry = MetricsRegistry()
    exporter = MetricsExporter(registry, port=0).start()
    assert exporter.port is not None
    yield registry, exporter
    exporter.close()


def test_metrics_and_snapshot_endpoints(served_registry):
    registry, exporter = served_registry
    registry.inc("requests_total", 3)
    registry.set("loss", 0.5)
    registry.observe("wait", 0.2, buckets=[0.1, 1.0])
    status, text = _get(f"{exporter.url}/metrics")
    assert status == 200
    assert "# TYPE requests_total counter" in text
    assert "requests_total 3" in text
    assert "loss 0.5" in text
    assert 'wait_bucket{le="+Inf"} 1' in text
    status, body = _get(f"{exporter.url}/snapshot")
    snapshot = json.loads(body)
    assert snapshot["requests_total"]["value"] == 3
    assert snapshot["wait"]["count"] == 1
    status, body = _get(f"{exporter.url}/healthz")
    assert status == 200 and body == "ok\n"


def test_healthz_json_negotiation(served_registry):
    """Structured health: ``?format=json`` or an ``Accept: application/json``
    header gets the health document; the plain-text probe shape survives."""
    registry, _ = served_registry
    heartbeat = {
        "live": True, "queued": 2, "max_depth": 64,
        "breaker_state": "closed", "requests": 10, "errors": 1,
        "error_rate": 0.1,
    }
    exporter = MetricsExporter(registry, port=0, health_source=lambda: heartbeat)
    exporter.start()
    try:
        # default stays byte-identical for existing probes
        status, body = _get(f"{exporter.url}/healthz")
        assert status == 200 and body == "ok\n"
        status, body = _get(f"{exporter.url}/healthz?format=json")
        assert status == 200
        document = json.loads(body)
        # the health source's fields survive verbatim; the exporter's identity
        # block (process_index/pid/start_unix) rides along for federation
        assert document.items() >= heartbeat.items()
        assert document["pid"] > 0 and "start_unix" in document
        assert document["process_index"] == 0
        request = urllib.request.Request(
            f"{exporter.url}/healthz", headers={"Accept": "application/json"}
        )
        with urllib.request.urlopen(request, timeout=10.0) as response:
            assert json.loads(response.read().decode()).items() >= heartbeat.items()
    finally:
        exporter.close()


def test_healthz_json_without_source_is_live(served_registry):
    _, exporter = served_registry
    status, body = _get(f"{exporter.url}/healthz?format=json")
    assert status == 200
    document = json.loads(body)
    assert document["live"] is True
    # even sourceless health carries the identity block
    assert {"process_index", "pid", "start_unix"} <= document.keys()


def test_healthz_json_raising_source_is_503():
    """A broken heartbeat is the signal — 503 + the error, never a happy 200."""
    def broken():
        raise RuntimeError("engine wedged")

    exporter = MetricsExporter(MetricsRegistry(), port=0, health_source=broken)
    exporter.start()
    try:
        with pytest.raises(urllib.error.HTTPError) as err:
            _get(f"{exporter.url}/healthz?format=json")
        assert err.value.code == 503
        payload = json.loads(err.value.read().decode())
        assert payload["live"] is False
        assert "engine wedged" in payload["error"]
        # the plain probe still reports process liveness
        status, body = _get(f"{exporter.url}/healthz")
        assert status == 200 and body == "ok\n"
    finally:
        exporter.close()


def test_unknown_path_is_404(served_registry):
    _, exporter = served_registry
    with pytest.raises(urllib.error.HTTPError) as err:
        _get(f"{exporter.url}/nope")
    assert err.value.code == 404


def test_busy_port_degrades_to_noop(served_registry, caplog):
    _, exporter = served_registry
    second = MetricsExporter(MetricsRegistry(), port=exporter.port).start()
    try:
        assert second.port is None and second.url is None
        second.close()  # safe on a never-bound exporter
        # the original endpoint is untouched
        status, _ = _get(f"{exporter.url}/healthz")
        assert status == 200
    finally:
        second.close()


def test_close_is_idempotent_and_releases_the_port():
    registry = MetricsRegistry()
    exporter = MetricsExporter(registry, port=0).start()
    port = exporter.port
    exporter.close()
    exporter.close()
    assert exporter.port is None
    # the port is actually free again: a new exporter can take it
    reuse = MetricsExporter(registry, port=port).start()
    assert reuse.port == port
    reuse.close()


def test_concurrent_scrapes_against_writers(served_registry):
    """The satellite's load test: writer threads hammer every metric type
    while scraper threads pull /metrics and /snapshot. Every scrape must be a
    complete, parseable exposition with monotone counters; nothing deadlocks."""
    registry, exporter = served_registry
    stop = threading.Event()
    failures = []

    def writer(i):
        n = 0
        while not stop.is_set():
            n += 1
            registry.inc("w_total")
            registry.set("g", float(n), labels={"writer": str(i)})
            registry.observe("h", (n % 100) / 100.0, buckets=[0.25, 0.5, 0.75, 1.0])

    def scraper():
        last_total = -1.0
        try:
            for _ in range(25):
                _, text = _get(f"{exporter.url}/metrics")
                assert text.endswith("\n"), "torn exposition"
                totals = [
                    float(line.rsplit(" ", 1)[1])
                    for line in text.splitlines()
                    if line.startswith("w_total ")
                ]
                assert len(totals) == 1, text.splitlines()[:5]
                assert totals[0] >= last_total, "counter went backwards"
                last_total = totals[0]
                # every line is "name{labels} value" or a comment
                for line in text.splitlines():
                    assert line.startswith("#") or len(line.rsplit(" ", 1)) == 2
                snapshot = json.loads(_get(f"{exporter.url}/snapshot")[1])
                h = snapshot.get("h")
                if h:
                    assert sum(h["buckets"].values()) + h["overflow"] == h["count"]
        except Exception as exc:  # noqa: BLE001 — surfaced to the main thread
            failures.append(exc)

    writers = [threading.Thread(target=writer, args=(i,), daemon=True) for i in range(3)]
    scrapers = [threading.Thread(target=scraper, daemon=True) for _ in range(3)]
    for t in writers + scrapers:
        t.start()
    for t in scrapers:
        t.join(timeout=60)
        assert not t.is_alive(), "scraper deadlocked"
    stop.set()
    for t in writers:
        t.join(timeout=10)
    assert not failures, failures[0]
