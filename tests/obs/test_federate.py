"""Federation merge semantics: exact sums, labeled gauges, lossless buckets.

Core tier: every claim in :mod:`replay_tpu.obs.federate`'s module doc gets a
direct check against real :class:`MetricsRegistry` snapshots — counters sum
EXACTLY, gauges keep one labeled series per process, histograms bucket-merge
with zero loss (count/sum/min/max/overflow and re-estimated quantiles), and
mismatched bucket ladders raise :class:`FederationError` naming the metric.
The HTTP path runs against two real in-process exporters on ephemeral ports;
the two-real-OS-process variant lives in tests/serve/test_remote.py.
"""

import urllib.request

import pytest

from replay_tpu.obs.exporter import MetricsExporter
from replay_tpu.obs.federate import (
    FederationError,
    FleetFederator,
    federate_snapshots,
    parse_metric_key,
    scrape_snapshot,
)
from replay_tpu.obs.metrics import MetricsRegistry

pytestmark = pytest.mark.core


def _registry(requests: int, latencies, process_index: int) -> dict:
    registry = MetricsRegistry()
    for _ in range(requests):
        registry.inc("replay_serve_requests_total")
    registry.inc("replay_serve_shed_total", 3.0)
    registry.set("replay_serve_qps", 10.0 * (process_index + 1))
    for value in latencies:
        registry.observe("replay_serve_queue_wait_ms", value, buckets=(1.0, 5.0, 25.0))
    snapshot = registry.snapshot()
    snapshot["__identity__"] = {"process_index": process_index, "pid": 1000 + process_index}
    return snapshot


def test_parse_metric_key_roundtrip():
    assert parse_metric_key("plain_name") == ("plain_name", {})
    name, labels = parse_metric_key('replay_serve_qps{process="2",host="a"}')
    assert name == "replay_serve_qps"
    assert labels == {"process": "2", "host": "a"}


def test_counters_sum_exactly():
    merged = federate_snapshots([
        _registry(7, [], 0), _registry(11, [], 1), _registry(23, [], 2),
    ])
    snapshot = merged.snapshot()
    assert snapshot["replay_serve_requests_total"]["value"] == 41.0
    assert snapshot["replay_serve_shed_total"]["value"] == 9.0


def test_gauges_keep_one_labeled_series_per_process():
    merged = federate_snapshots([_registry(1, [], 0), _registry(1, [], 4)])
    snapshot = merged.snapshot()
    # no unlabeled collapsed series: last-write-wins scalars never add
    assert "replay_serve_qps" not in snapshot
    assert snapshot['replay_serve_qps{process="0"}']["value"] == 10.0
    assert snapshot['replay_serve_qps{process="4"}']["value"] == 50.0


def test_histograms_bucket_merge_losslessly():
    a = [0.5, 0.7, 3.0, 100.0]
    b = [0.9, 4.0, 20.0, 30.0, 200.0]
    merged = federate_snapshots([_registry(1, a, 0), _registry(1, b, 1)])

    # ground truth: one registry observing the union of both streams
    union = MetricsRegistry()
    for value in a + b:
        union.observe("replay_serve_queue_wait_ms", value, buckets=(1.0, 5.0, 25.0))
    got = merged.snapshot()["replay_serve_queue_wait_ms"]
    want = union.snapshot()["replay_serve_queue_wait_ms"]
    for field in ("count", "sum", "min", "max", "buckets", "overflow"):
        assert got[field] == want[field], field
    # quantiles re-estimated over MERGED counts equal the union's estimates —
    # never an average of per-process percentiles
    assert got["quantiles"] == want["quantiles"]


def test_mismatched_bucket_ladders_raise_naming_the_metric():
    one = MetricsRegistry()
    one.observe("replay_serve_queue_wait_ms", 1.0, buckets=(1.0, 5.0))
    other = MetricsRegistry()
    other.observe("replay_serve_queue_wait_ms", 1.0, buckets=(2.0, 10.0))
    with pytest.raises(FederationError, match="replay_serve_queue_wait_ms"):
        federate_snapshots([one.snapshot(), other.snapshot()])


def test_process_label_falls_back_to_scrape_order():
    bare = _registry(1, [], 0)
    del bare["__identity__"]
    merged = federate_snapshots([bare, bare])
    snapshot = merged.snapshot()
    assert 'replay_serve_qps{process="0"}' in snapshot
    assert 'replay_serve_qps{process="1"}' in snapshot


def test_federator_scrapes_real_exporters_and_serves_the_merge():
    reg_a, reg_b = MetricsRegistry(), MetricsRegistry()
    reg_a.inc("replay_serve_requests_total", 5.0)
    reg_b.inc("replay_serve_requests_total", 8.0)
    exp_a = MetricsExporter(reg_a, port=0, identity={"process_index": 0}).start()
    exp_b = MetricsExporter(reg_b, port=0, identity={"process_index": 1}).start()
    try:
        fed = FleetFederator([exp_a.url, exp_b.url], port=0)
        with fed:
            scrape = fed.scrape()
            assert scrape.reachable == 2 and not scrape.errors
            assert {m["process_index"] for m in scrape.members} == {0, 1}
            merged = scrape.registry.snapshot()
            assert merged["replay_serve_requests_total"]["value"] == 13.0
            assert merged["replay_federation_reachable"]["value"] == 2.0
            # the federated /metrics endpoint serves the merged registry
            with urllib.request.urlopen(f"{fed.exporter.url}/metrics", timeout=10) as r:
                text = r.read().decode()
            assert "replay_serve_requests_total 13" in text
            assert "replay_federation_members 2" in text
    finally:
        exp_a.close()
        exp_b.close()


def test_dead_member_degrades_to_the_reachable_subset():
    registry = MetricsRegistry()
    registry.inc("replay_serve_requests_total", 4.0)
    exporter = MetricsExporter(registry, port=0, identity={"process_index": 0}).start()
    try:
        fed = FleetFederator([exporter.url, "http://127.0.0.1:1"], port=0, timeout_s=2.0)
        scrape = fed.scrape()
        assert scrape.reachable == 1
        assert "http://127.0.0.1:1" in scrape.errors
        merged = scrape.registry.snapshot()
        assert merged["replay_serve_requests_total"]["value"] == 4.0
        assert merged["replay_federation_members"]["value"] == 2.0
        assert merged["replay_federation_reachable"]["value"] == 1.0
        assert merged['replay_federation_errors_total{target="http://127.0.0.1:1"}'][
            "value"
        ] == 1.0
        fed.close()
    finally:
        exporter.close()


def test_scrape_snapshot_carries_the_identity_block():
    registry = MetricsRegistry()
    registry.inc("anything_total")
    exporter = MetricsExporter(registry, port=0, identity={"process_index": 7}).start()
    try:
        snapshot = scrape_snapshot(exporter.url)
        assert snapshot["__identity__"]["process_index"] == 7
        assert snapshot["__identity__"]["pid"] > 0
        assert snapshot["__identity__"]["start_unix"] > 0
    finally:
        exporter.close()
