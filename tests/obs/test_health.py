"""Model-health diagnostics (replay_tpu.obs.health).

The acceptance gates for this layer:

* a health-enabled ``fit`` on the 8-device virtual mesh produces per-group
  grad/param/update norms + update ratios, activation RMS, attention entropy,
  logits stats and embedding coverage in ``events.jsonl`` with exactly ONE
  ``train_step`` compile (no retraces after step 1), and ``obs.report``
  renders the model-health section from that run;
* the health-DISABLED step lowers to the same HLO as the pre-health trainer
  (golden comparison against an in-test reimplementation of the original
  step math);
* ``HealthWatcher`` fires ``on_health_warning`` well before the non-finite
  sentinel on an lr-blowup divergence run, and can trigger the
  RecoveryPolicy rollback path.

The smoke test doubles as the CI artifact source: its events.jsonl lands in
``REPLAY_TPU_RUN_DIR/health_smoke`` and ships from the ``jax and smoke`` job,
which also runs ``obs.report`` over it.
"""

import json
import math
import os
from functools import partial

import numpy as np
import pytest

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax

from replay_tpu.data import FeatureHint, FeatureType
from replay_tpu.data.nn import TensorFeatureInfo, TensorSchema
from replay_tpu.nn import (
    HealthConfig,
    HealthWatcher,
    OptimizerFactory,
    RecoveryPolicy,
    Trainer,
    make_mesh,
)
from replay_tpu.nn.loss import CE
from replay_tpu.nn.sequential.sasrec import SasRec
from replay_tpu.nn.train import TrainState
from replay_tpu.obs import JsonlLogger, TensorBoardLogger
from replay_tpu.obs.health import flatten_health, param_group_key
from replay_tpu.obs.report import render, summarize_run

NUM_ITEMS = 12
SEQ_LEN = 8
BATCH = 8  # divisible by the 8-device data axis


def _run_dir(tmp_path, name):
    """CI exports REPLAY_TPU_RUN_DIR so the smoke run's health telemetry
    ships as a workflow artifact; locally the run log lands in tmp_path."""
    base = os.environ.get("REPLAY_TPU_RUN_DIR")
    return os.path.join(base, name) if base else str(tmp_path / name)


def make_schema() -> TensorSchema:
    return TensorSchema(
        TensorFeatureInfo(
            "item_id",
            FeatureType.CATEGORICAL,
            is_seq=True,
            feature_hint=FeatureHint.ITEM_ID,
            cardinality=NUM_ITEMS,
            embedding_dim=16,
        )
    )


def make_batch(seed: int) -> dict:
    rng = np.random.default_rng(seed)
    items = rng.integers(0, NUM_ITEMS, size=(BATCH, SEQ_LEN + 1)).astype(np.int32)
    mask = np.ones((BATCH, SEQ_LEN), dtype=bool)
    return {
        "feature_tensors": {"item_id": items[:, :-1]},
        "padding_mask": mask,
        "positive_labels": items[:, 1:, None],
        "target_padding_mask": mask[:, :, None],
    }


def make_trainer(**kwargs) -> Trainer:
    model = SasRec(
        schema=make_schema(), embedding_dim=16, num_blocks=2, num_heads=2,
        max_sequence_length=SEQ_LEN,
    )
    kwargs.setdefault("optimizer", OptimizerFactory(name="adam", learning_rate=1e-2))
    return Trainer(model=model, loss=CE(), mesh=make_mesh(), **kwargs)


class EventSink:
    def __init__(self):
        self.events = []

    def log_event(self, event):
        self.events.append(event)

    def named(self, name):
        return [e for e in self.events if e.event == name]


# --------------------------------------------------------------------------- #
# the acceptance smoke: health-enabled fit, one compile, full payload, report
# --------------------------------------------------------------------------- #
@pytest.mark.jax
@pytest.mark.smoke
def test_health_enabled_fit_single_compile_full_payload(tmp_path):
    trainer = make_trainer(health=HealthConfig(cadence=2))
    batches = [make_batch(i) for i in range(4)]
    run_dir = _run_dir(tmp_path, "health_smoke")
    # mode="w": REPLAY_TPU_RUN_DIR is a fixed path in CI — a re-run must not
    # append a second event stream and break the counts below
    with JsonlLogger(run_dir, mode="w") as sink:
        trainer.fit(lambda: iter(batches), epochs=2, loggers=sink, log_every=0)

    # the retrace guard: enabling health is exactly ONE compiled train step
    assert trainer.compile_tracker.traces["train_step"] == 1

    lines = [json.loads(line) for line in open(os.path.join(run_dir, "events.jsonl"))]
    steps = [line for line in lines if line["event"] == "on_train_step"]
    health_steps = [line for line in steps if "health" in line]
    # cadence=2 over 8 steps: every second step event carries the record
    assert len(steps) == 8 and len(health_steps) == 4

    health = health_steps[-1]["health"]
    groups = {"embeddings", "block_0", "block_1", "head"}
    for key in ("grad_norm", "param_norm", "update_norm", "update_ratio"):
        assert set(health[key]) == groups, key
        for group, value in health[key].items():
            assert value is not None and math.isfinite(value) and value >= 0, (key, group)
    # adam's update norms are not degenerate: ratios strictly positive
    assert all(v > 0 for v in health["update_ratio"].values())
    assert math.isfinite(health["grad_norm_global"])
    # sowed per-stage activation stats from the SASRec body + encoder blocks
    assert {"embed", "block_0", "block_1", "final_norm"} <= set(health["activations"])
    for stats in health["activations"].values():
        assert math.isfinite(stats["rms"]) and stats["rms"] > 0
        assert math.isfinite(stats["absmax"]) and stats["absmax"] >= stats["rms"]
    # per-head attention entropy: one [num_heads] vector per block, in nats
    assert set(health["attention_entropy"]) == {"block_0", "block_1"}
    for per_head in health["attention_entropy"].values():
        assert len(per_head) == 2  # num_heads
        assert all(0 <= v <= math.log(SEQ_LEN) + 1e-3 for v in per_head)
    assert math.isfinite(health["attention_entropy_mean"])
    assert 0 < health["embedding_coverage"] <= 1.0
    assert math.isfinite(health["logits"]["absmax"]) and health["logits"]["std"] > 0

    # the epoch-end rollups ride the same stream (report --compare gates)
    epoch_ends = [line for line in lines if line["event"] == "on_epoch_end"]
    assert all(e["bad_steps"] == 0 for e in epoch_ends)
    assert all(math.isfinite(e["grad_norm"]) for e in epoch_ends)
    assert all("health" in e for e in epoch_ends)

    # and the run-report CLI renders the model-health section from the artifact
    summary = summarize_run(run_dir)
    assert summary["health"] is not None and summary["health_warnings"] == 0
    assert summary["bad_steps"] == 0 and math.isfinite(summary["last_grad_norm"])
    text = render(summary)
    assert "model health" in text and "group grad norms" in text and "activations" in text


@pytest.mark.jax
def test_health_payload_on_bert4rec_body(tmp_path):
    """The BERT4Rec body sows the same stage/entropy sites (bidirectional
    encoder, token-mask forward)."""
    from replay_tpu.nn.sequential.bert4rec import Bert4Rec

    model = Bert4Rec(
        schema=make_schema(), embedding_dim=16, num_blocks=1, num_heads=2,
        max_sequence_length=SEQ_LEN,
    )
    trainer = Trainer(
        model=model, loss=CE(), optimizer=OptimizerFactory(learning_rate=1e-2),
        mesh=make_mesh(), health=HealthConfig(cadence=1),
    )
    rng = np.random.default_rng(0)
    batch = make_batch(0)
    batch["token_mask"] = rng.random((BATCH, SEQ_LEN)) > 0.2
    sink = EventSink()
    trainer.fit(lambda: iter([batch, batch]), epochs=1, loggers=sink, log_every=0)
    health = sink.named("on_train_step")[-1].payload["health"]
    assert {"embed", "block_0", "final_norm"} <= set(health["activations"])
    assert "block_0" in health["attention_entropy"]
    assert len(health["attention_entropy"]["block_0"]) == 2
    assert trainer.compile_tracker.traces["train_step"] == 1


@pytest.mark.jax
def test_attention_entropy_weighted_by_valid_positions():
    """Padded query rows are forced one-hot by the mask's diagonal rescue
    (entropy 0); the sowed per-head entropy must average over VALID rows only,
    or heavily padded batches read as collapsed attention."""
    from replay_tpu.nn import MultiHeadAttention
    from replay_tpu.nn.mask import causal_attention_mask

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(4, 8, 16)).astype(np.float32))
    padding_mask = np.zeros((4, 8), bool)
    padding_mask[:, 4:] = True  # left-padded: half the rows are invalid
    mask = causal_attention_mask(jnp.asarray(padding_mask))
    module = MultiHeadAttention(num_heads=2)
    params = module.init(jax.random.PRNGKey(0), x, mask)["params"]

    def sowed_entropy(pad):
        _, variables = module.apply(
            {"params": params}, x, mask, padding_mask=pad, mutable=["intermediates"]
        )
        return np.asarray(variables["intermediates"]["attention_entropy"][0])

    weighted = sowed_entropy(jnp.asarray(padding_mask))
    diluted = sowed_entropy(None)  # same weights, unweighted mean
    # identical attention weights, so the only difference is the averaging:
    # dropping the zero-entropy padded rows must raise the reported value
    assert (weighted > diluted).all(), (weighted, diluted)
    assert (weighted <= math.log(8) + 1e-3).all()


@pytest.mark.jax
def test_last_health_scoped_per_fit():
    """A second fit whose first fetch has not happened yet must not attach the
    previous fit's record to its epoch-end events."""
    trainer = make_trainer(health=HealthConfig(cadence=10))  # > steps per fit
    sink = EventSink()
    trainer.fit(lambda: iter([make_batch(0) for _ in range(10)]), epochs=1,
                loggers=sink, log_every=0)
    assert trainer.last_health is not None  # fetch happened at step 10
    second = EventSink()
    trainer.fit(lambda: iter([make_batch(1) for _ in range(2)]), epochs=1,
                loggers=second, log_every=0)
    assert "health" not in second.named("on_epoch_end")[0].payload


# --------------------------------------------------------------------------- #
# golden HLO: the health-disabled step is byte-identical to the pre-health one
# --------------------------------------------------------------------------- #
def _strip_module_name(text: str) -> str:
    # the first line carries the jitted function's name (@jit_train_step vs
    # @jit_golden_step); everything below is the program
    return "\n".join(text.splitlines()[1:])


@pytest.mark.jax
def test_health_disabled_step_lowers_to_golden_hlo():
    """Golden comparison: with health=None the trainer's step must lower to
    the same HLO as a literal reimplementation of the original (pre-health)
    train-step math — the sow guards and the health branch may not leak a
    single op into the disabled path."""
    trainer = make_trainer()
    model, loss, tx = trainer.model, trainer.loss, trainer._tx
    batch = make_batch(0)
    state = trainer.init_state(batch)
    placed = trainer._put_batch(batch)

    def golden_step(state, batch):
        rng, dropout_rng, loss_rng = jax.random.split(state.rng, 3)
        target_mask = batch["target_padding_mask"]
        if "valid" in batch:
            target_mask = target_mask & batch["valid"][
                (slice(None),) + (None,) * (target_mask.ndim - 1)
            ]

        def loss_fn(params):
            kwargs = {
                name: batch[name]
                for name in ("feature_tensors", "padding_mask", "deterministic")
                if name in batch
            }
            kwargs["deterministic"] = False
            with jax.named_scope("forward"):
                hidden = model.apply(
                    {"params": params}, rngs={"dropout": dropout_rng}, **kwargs
                )
            loss.logits_callback = partial(
                model.apply, {"params": params}, method=type(model).get_logits
            )
            with jax.named_scope("loss"):
                return loss(
                    hidden,
                    batch.get("feature_tensors", {}),
                    batch["positive_labels"],
                    batch.get("negative_labels"),
                    batch["padding_mask"],
                    target_mask,
                )

        loss_value, grads = jax.value_and_grad(loss_fn)(state.params)
        grad_norm = optax.global_norm(grads)
        good = jnp.isfinite(loss_value) & jnp.isfinite(grad_norm)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)

        def keep(new, old):
            return jnp.where(good, new, old)

        new_state = TrainState(
            step=state.step + 1,
            params=jax.tree.map(keep, params, state.params),
            opt_state=jax.tree.map(keep, opt_state, state.opt_state),
            rng=rng,
            bad_steps=state.bad_steps + (~good).astype(jnp.int32),
        )
        return new_state, {"loss": loss_value, "good": good, "grad_norm": grad_norm}

    # the golden traces under the SAME rule-table sharding scope the trainer
    # installs around its programs (parallel.sharding): the model bodies'
    # shard_activation constraints are part of the production step by design
    # — what this golden pins is that the HEALTH machinery adds nothing
    from replay_tpu.parallel.sharding import sharding_scope

    with sharding_scope(trainer.sharding_rules, trainer.mesh):
        golden = _strip_module_name(
            jax.jit(golden_step, donate_argnums=0).lower(state, placed).as_text()
        )
    disabled = _strip_module_name(
        jax.jit(trainer._build_train_step(None), donate_argnums=0)
        .lower(state, placed)
        .as_text()
    )
    assert disabled == golden

    # sanity: the health-enabled variant IS a different program (the one
    # sanctioned extra compiled variant), with the health scope present
    enabled = jax.jit(
        trainer._build_train_step(HealthConfig()), donate_argnums=0
    ).lower(state, placed).as_text()
    assert _strip_module_name(enabled) != golden
    assert "health" in enabled and "health" not in disabled


@pytest.mark.jax
def test_health_step_math_identical_to_plain_step():
    """The health variant's loss/params must equal the plain step's bit for
    bit — diagnostics may observe the update, never change it."""
    plain = make_trainer(seed=3)
    health = make_trainer(seed=3, health=HealthConfig(cadence=1))
    batch = make_batch(7)
    state_a = plain.init_state(batch)
    state_b = health.init_state(batch)
    for seed in (1, 2, 3):
        state_a, loss_a = plain.train_step(state_a, make_batch(seed))
        state_b, loss_b = health.train_step(state_b, make_batch(seed))
        assert float(loss_a) == float(loss_b)
    jax.tree.map(
        lambda x, y: np.testing.assert_array_equal(np.asarray(x), np.asarray(y)),
        state_a.params,
        state_b.params,
    )


# --------------------------------------------------------------------------- #
# divergence: the watcher warns BEFORE the sentinel, and can trigger recovery
# --------------------------------------------------------------------------- #
class ToyTying(nn.Module):
    """Norm-free tying model: under an oversized SGD rate its parameter norm
    grows geometrically for dozens of steps before anything overflows — the
    textbook silent-divergence window the watcher exists for (a LayerNorm'd
    encoder bounds its activations and hides the growth from the loss)."""

    vocab: int
    dim: int = 8
    logits_via_item_weights = True

    def setup(self):
        self.embedding_item = nn.Embed(self.vocab, self.dim, name="embedding_item")

    def __call__(self, feature_tensors, padding_mask, deterministic=True):
        return self.embedding_item(feature_tensors["item_id"])

    def get_logits(self, hidden, candidates_to_score=None):
        weights = self.embedding_item.embedding
        if candidates_to_score is not None:
            weights = weights[candidates_to_score]
        return hidden @ weights.T

    def forward_inference(self, feature_tensors, padding_mask, candidates_to_score=None):
        hidden = self(feature_tensors, padding_mask)[:, -1, :]
        return self.get_logits(hidden, candidates_to_score)

    def get_item_weights(self):
        return self.embedding_item.embedding


def _toy_trainer(watcher: HealthWatcher) -> Trainer:
    return Trainer(
        model=ToyTying(vocab=NUM_ITEMS),
        loss=CE(),
        optimizer=OptimizerFactory(name="sgd", learning_rate=20.0),  # lr blowup
        mesh=make_mesh(),
        health=HealthConfig(cadence=1, watcher=watcher),
    )


@pytest.mark.jax
@pytest.mark.smoke
def test_watcher_warns_before_nonfinite_sentinel():
    K = 5  # the early-warning margin the acceptance criterion demands
    trainer = _toy_trainer(HealthWatcher(alpha=0.3, blowup_factor=5.0, warmup=3))
    sink = EventSink()
    trainer.fit(
        lambda epoch: [make_batch(i) for i in range(60)],
        epochs=1, loggers=sink, log_every=0,
    )
    warnings = sink.named("on_health_warning")
    anomalies = sink.named("on_anomaly")
    assert warnings, "divergence produced no health warning"
    assert anomalies, "the lr blowup never reached the sentinel (test setup broken)"
    first_warning, first_anomaly = warnings[0].step, anomalies[0].step
    assert first_warning + K <= first_anomaly, (first_warning, first_anomaly)
    payload = warnings[0].payload
    assert payload["signal"] in ("grad_norm", "update_ratio_max")
    assert payload["factor"] > payload["blowup_factor"] >= 5.0
    assert math.isfinite(payload["value"]) and math.isfinite(payload["ewma"])


@pytest.mark.jax
def test_watcher_triggers_recovery_rollback():
    """trigger_recovery=True routes the warning into the existing rollback
    path: on_recovery(reason='health_warning') fires while everything is
    still finite, and the restored state is the pre-blowup snapshot."""
    trainer = _toy_trainer(
        HealthWatcher(alpha=0.3, blowup_factor=5.0, warmup=3, trigger_recovery=True)
    )
    sink = EventSink()
    with pytest.raises(RuntimeError, match="budget exhausted"):
        # lr stays absurd after each backoff, so the budget eventually runs
        # out — by then several health-triggered rollbacks must have fired
        trainer.fit(
            lambda epoch: [make_batch(i) for i in range(60)],
            epochs=1, loggers=sink, log_every=0,
            recovery=RecoveryPolicy(max_consecutive_bad=50, max_restarts=2, lr_backoff=0.9),
        )
    recoveries = sink.named("on_recovery")
    assert recoveries and recoveries[0].payload["reason"] == "health_warning"
    # every trigger came from the watcher, not the sentinel: the rollback
    # happened BEFORE any non-finite step could accumulate
    assert all(r.payload["reason"] == "health_warning" for r in recoveries if "reason" in r.payload)


# --------------------------------------------------------------------------- #
# unit: watcher, grouping, flatten (host-only)
# --------------------------------------------------------------------------- #
@pytest.mark.core
def test_watcher_ewma_blowup_and_reset():
    watcher = HealthWatcher(alpha=0.5, blowup_factor=3.0, warmup=2)
    clean = {"grad_norm_global": 1.0, "update_ratio": {"head": 0.01}}
    assert watcher.observe(clean) is None
    assert watcher.observe(clean) is None
    warning = watcher.observe({"grad_norm_global": 50.0, "update_ratio": {"head": 0.01}})
    assert warning is not None and warning["signal"] == "grad_norm"
    assert warning["factor"] == pytest.approx(50.0)
    # the blowup did not poison the baseline: a clean step after it is clean
    assert watcher.observe(clean) is None
    watcher.reset()
    # post-reset: warmup starts over, the same blowup is not yet a warning
    assert watcher.observe({"grad_norm_global": 50.0}) is None


@pytest.mark.core
def test_watcher_simultaneous_blowups_poison_no_baseline():
    """When BOTH signals blow up on one fetch, the first becomes the warning
    but neither value may enter its EWMA — otherwise the second signal's
    baseline chases the blowup and masks its next real warning."""
    watcher = HealthWatcher(alpha=0.5, blowup_factor=3.0, warmup=2)
    clean = {"grad_norm_global": 1.0, "update_ratio": {"head": 0.01}}
    watcher.observe(clean)
    watcher.observe(clean)
    blown = {"grad_norm_global": 100.0, "update_ratio": {"head": 1.0}}
    warning = watcher.observe(blown)
    assert warning is not None and warning["signal"] == "grad_norm"
    # the update-ratio baseline stayed pre-blowup: a ratio-only blowup on the
    # next fetch still warns instead of being absorbed
    warning = watcher.observe({"grad_norm_global": 1.0, "update_ratio": {"head": 1.0}})
    assert warning is not None and warning["signal"] == "update_ratio_max"


@pytest.mark.core
def test_watcher_ignores_nonfinite_and_validates():
    watcher = HealthWatcher(warmup=1)
    watcher.observe({"grad_norm_global": 1.0})
    watcher.observe({"grad_norm_global": 1.0})
    assert watcher.observe({"grad_norm_global": float("nan")}) is None
    assert watcher.observe({"grad_norm_global": float("inf")}) is None
    with pytest.raises(ValueError, match="alpha"):
        HealthWatcher(alpha=0.0)
    with pytest.raises(ValueError, match="blowup_factor"):
        HealthWatcher(blowup_factor=1.0)
    with pytest.raises(ValueError, match="cadence"):
        HealthConfig(cadence=0)


@pytest.mark.core
def test_param_group_keys():
    assert param_group_key("['body']['embedder']['embedding_item_id']['embedding']") == "embeddings"
    assert param_group_key("['body']['encoder']['block_3']['ffn']['kernel']") == "block_3"
    assert param_group_key("['body']['final_norm']['scale']") == "head"
    assert param_group_key("['body']['aggregator']['positional_embedding']") == "embeddings"


@pytest.mark.core
def test_flatten_health_shapes_for_tensorboard():
    record = {
        "grad_norm": {"embeddings": 0.5, "head": 0.1},
        "attention_entropy": {"block_0": [1.0, 1.2]},
        "embedding_coverage": 0.9,
    }
    flat = flatten_health(record)
    assert flat["health/grad_norm/embeddings"] == 0.5
    assert flat["health/attention_entropy/block_0"] == [1.0, 1.2]
    assert flat["health/embedding_coverage"] == 0.9


# --------------------------------------------------------------------------- #
# TensorBoard routing: scalars + real histograms, no-op fallback preserved
# --------------------------------------------------------------------------- #
class FakeWriter:
    def __init__(self):
        self.scalars = {}
        self.histograms = {}

    def add_scalar(self, tag, value, global_step=0):
        self.scalars[tag] = (value, global_step)

    def add_histogram(self, tag, values, global_step=0):
        self.histograms[tag] = (np.asarray(values), global_step)

    def close(self):
        pass


@pytest.mark.core
def test_tensorboard_health_scalars_and_histograms(tmp_path):
    from replay_tpu.obs import TrainerEvent

    sink = TensorBoardLogger(str(tmp_path / "tb"))
    sink._writer = FakeWriter()  # backend-independent
    sink.log_event(TrainerEvent(
        event="on_train_step", step=7,
        payload={
            "loss": 1.5,
            "health": {
                "grad_norm": {"embeddings": 0.5},
                "attention_entropy": {"block_0": [1.0, 1.2, float("nan")]},
                "embedding_coverage": 0.9,
            },
        },
    ))
    writer = sink._writer
    assert writer.scalars["loss"] == (1.5, 7)
    assert writer.scalars["health/grad_norm/embeddings"] == (0.5, 7)
    assert writer.scalars["health/embedding_coverage"] == (0.9, 7)
    tag, (values, step) = next(iter(writer.histograms.items()))
    assert tag == "health/attention_entropy/block_0" and step == 7
    np.testing.assert_allclose(values, [1.0, 1.2])  # non-finite dropped
    # the health subtree is not double-logged through the scalar flattener
    assert "health/attention_entropy" not in writer.scalars


@pytest.mark.core
def test_tensorboard_log_histogram_noop_without_backend(tmp_path):
    sink = TensorBoardLogger(str(tmp_path / "tb"))
    sink._writer = None  # simulate a missing backend
    sink.log_histogram("health/x", [1.0, 2.0], step=1)  # must not raise

    class AncientWriter:
        def add_scalar(self, *a, **k):
            pass

    sink._writer = AncientWriter()  # no add_histogram attr
    sink.log_histogram("health/x", [1.0, 2.0], step=1)  # must not raise


# --------------------------------------------------------------------------- #
# report: health section + anomaly-count compare gates (host-only)
# --------------------------------------------------------------------------- #
def _write_health_run(path, bad_steps=0, warnings=0):
    os.makedirs(path, exist_ok=True)
    health = {
        "grad_norm": {"embeddings": 0.4, "block_0": 0.2, "head": 0.1},
        "param_norm": {"embeddings": 3.0, "block_0": 13.0, "head": 3.9},
        "update_norm": {"embeddings": 0.05, "block_0": 0.18, "head": 0.02},
        "update_ratio": {"embeddings": 0.016, "block_0": 0.013, "head": 0.005},
        "grad_norm_global": 0.64,
        "activations": {"embed": {"rms": 0.97, "absmax": 2.9}},
        "attention_entropy": {"block_0": [1.18, 1.11]},
        "attention_entropy_mean": 1.14,
        "embedding_coverage": 0.95,
        "logits": {"mean": -0.35, "absmax": 1.37, "std": 0.36},
    }
    events = [
        {"event": "on_fit_start", "time": 1.0, "epoch": 0, "epochs": 1},
        {"event": "on_train_step", "time": 2.0, "step": 1, "epoch": 0, "loss": 2.0,
         "lr": 1e-2, "samples_per_sec": 100.0, "steps_per_sec": 12.5,
         "step_seconds": 0.08, "health": health},
        *({"event": "on_health_warning", "time": 2.5, "step": 2, "epoch": 0,
           "signal": "grad_norm", "value": 10.0, "ewma": 1.0, "factor": 10.0,
           "blowup_factor": 5.0} for _ in range(warnings)),
        {"event": "on_epoch_end", "time": 3.0, "step": 2, "epoch": 0,
         "record": {"epoch": 0, "train_loss": 1.9}, "bad_steps": bad_steps,
         "grad_norm": 0.64, "health": health},
        {"event": "on_fit_end", "time": 4.0, "step": 2,
         "telemetry": {"steps": 1.0, "elapsed_seconds": 0.1, "steps_per_sec": 10.0,
                       "samples_per_sec": 80.0},
         "compile": {"train_step": {"traces": 1, "compile_seconds": 0.5}},
         "peak_memory_bytes": None, "history_len": 1, "bad_steps": bad_steps},
    ]
    with open(os.path.join(path, "events.jsonl"), "w") as fh:
        for event in events:
            fh.write(json.dumps(event) + "\n")
    return path


@pytest.mark.core
def test_report_renders_model_health_section(tmp_path):
    run = _write_health_run(str(tmp_path / "run"), warnings=2)
    summary = summarize_run(run)
    assert summary["health_warnings"] == 2
    assert summary["health"]["embedding_coverage"] == 0.95
    assert summary["last_grad_norm"] == pytest.approx(0.64)
    text = render(summary)
    assert "model health" in text
    assert "grad_norm 0.64" in text and "warnings 2" in text
    assert "emb coverage 95%" in text and "attn entropy 1.140 nats" in text
    assert "group grad norms" in text and "block_0" in text
    assert "activations" in text and "embed rms 0.97" in text


@pytest.mark.core
def test_compare_gates_on_anomaly_counts(tmp_path):
    from replay_tpu.obs.report import compare_runs

    baseline = summarize_run(_write_health_run(str(tmp_path / "base"), bad_steps=0))
    candidate = summarize_run(
        _write_health_run(str(tmp_path / "cand"), bad_steps=3, warnings=1)
    )
    lines, regressions = compare_runs(candidate, baseline)
    assert any("bad_steps: 3 vs 0" in line for line in lines)
    assert any("bad_steps increased 0 -> 3" in r for r in regressions)
    assert any("health warnings increased 0 -> 1" in r for r in regressions)
    # same counts in both directions is NOT a regression
    lines, regressions = compare_runs(baseline, baseline)
    assert not regressions
