"""Live metrics plane end-to-end: a scraped chunked fit with an SLO watchdog.

The smoke here is CI's acceptance gate for the metrics plane (see
.github/workflows/main.yml "live metrics plane"): a chunked
``fit(metrics_port=0)`` is scraped over HTTP *mid-fit* — the Prometheus text
must carry finite step-time / samples-per-sec / goodput gauges — a
fault-injected NaN step must trip the ``bad_steps`` SLO rule exactly once,
and the ``/snapshot`` JSON lands in the run directory as the artifact CI
uploads.
"""

import json
import math
import os
import urllib.request

import numpy as np
import pytest

from replay_tpu.data import FeatureHint, FeatureType
from replay_tpu.data.nn import TensorFeatureInfo, TensorSchema
from replay_tpu.nn import OptimizerFactory, Trainer, make_mesh
from replay_tpu.nn.loss import CE
from replay_tpu.nn.sequential.sasrec import SasRec
from replay_tpu.obs import JsonlLogger, SLORule
from replay_tpu.obs.report import summarize_run
from replay_tpu.utils.faults import NaNInjector

pytestmark = [pytest.mark.jax, pytest.mark.smoke]

NUM_ITEMS = 12
SEQ_LEN = 8
BATCH = 8  # divisible by the 8-device data axis


def _run_dir(tmp_path, name):
    """CI exports REPLAY_TPU_RUN_DIR so the scrape + snapshot artifacts ship
    with the workflow; locally everything lands in tmp_path."""
    base = os.environ.get("REPLAY_TPU_RUN_DIR")
    return os.path.join(base, name) if base else str(tmp_path / name)


def make_schema() -> TensorSchema:
    # the float feature is the NaN-injection surface (integer ids can't
    # carry a NaN) — same recipe as tests/nn/test_fault_tolerance.py
    return TensorSchema(
        [
            TensorFeatureInfo(
                "item_id", FeatureType.CATEGORICAL, is_seq=True,
                feature_hint=FeatureHint.ITEM_ID, cardinality=NUM_ITEMS,
                embedding_dim=16,
            ),
            TensorFeatureInfo(
                "num_feature", FeatureType.NUMERICAL, is_seq=True, tensor_dim=1,
                embedding_dim=16,
            ),
        ]
    )


def make_batch(seed: int) -> dict:
    rng = np.random.default_rng(seed)
    items = rng.integers(0, NUM_ITEMS, size=(BATCH, SEQ_LEN + 1)).astype(np.int32)
    mask = np.ones((BATCH, SEQ_LEN), dtype=bool)
    return {
        "feature_tensors": {
            "item_id": items[:, :-1],
            "num_feature": rng.normal(size=(BATCH, SEQ_LEN)).astype(np.float32),
        },
        "padding_mask": mask,
        "positive_labels": items[:, 1:, None],
        "target_padding_mask": mask[:, :, None],
    }


def make_trainer() -> Trainer:
    model = SasRec(
        schema=make_schema(), embedding_dim=16, num_blocks=1, num_heads=1,
        max_sequence_length=SEQ_LEN,
    )
    return Trainer(
        model=model, loss=CE(), optimizer=OptimizerFactory(learning_rate=1e-2),
        mesh=make_mesh(),
    )


class MidFitScraper:
    """A RunLogger that scrapes the live exporter when the fit reaches
    ``at_step`` — proof the endpoint answers WHILE the loop is running."""

    def __init__(self, trainer: Trainer, at_step: int) -> None:
        self.trainer = trainer
        self.at_step = at_step
        self.metrics_text = None
        self.snapshot = None
        self._steps_seen = 0

    def log_event(self, event) -> None:
        if event.event != "on_train_step" or self.metrics_text is not None:
            return
        self._steps_seen += 1
        if self._steps_seen < self.at_step:
            return
        url = self.trainer.metrics_exporter.url
        with urllib.request.urlopen(f"{url}/metrics", timeout=10) as response:
            self.metrics_text = response.read().decode()
        with urllib.request.urlopen(f"{url}/snapshot", timeout=10) as response:
            self.snapshot = json.loads(response.read())


def _gauge_value(text: str, name: str) -> float:
    lines = [line for line in text.splitlines() if line.startswith(name + " ")]
    assert lines, f"{name} missing from the scrape"
    return float(lines[0].rsplit(" ", 1)[1])


def test_chunked_fit_scraped_mid_fit_with_nan_slo(tmp_path):
    run_dir = _run_dir(tmp_path, "metrics_smoke")
    trainer = make_trainer()
    injector = NaNInjector(at_steps=(3,))  # 0-based: step 4 of epoch 0
    scraper = MidFitScraper(trainer, at_step=12)  # inside epoch 1
    rules = [SLORule("replay_train_bad_steps", ">", 0, name="bad_steps")]

    with JsonlLogger(run_dir, mode="w") as sink:
        state = trainer.fit(
            lambda epoch: injector.wrap([make_batch(epoch * 10 + i) for i in range(8)]),
            epochs=2,
            scan_chunk=2,
            loggers=[sink, scraper],
            metrics_port=0,
            slo_rules=rules,
            tracer=True,  # goodput fractions reach the registry at epoch end
            log_every=0,
        )

    assert injector.injected_at == [3]
    assert int(state.bad_steps) == 1

    # -- the mid-fit scrape: live training gauges, present and finite ------- #
    text = scraper.metrics_text
    assert text is not None, "the exporter never answered mid-fit"
    for gauge in (
        "replay_train_loss",
        "replay_train_samples_per_sec",
        "replay_train_steps_per_sec",
    ):
        assert math.isfinite(_gauge_value(text, gauge)), gauge
    # the scraper sink precedes the metrics bridge in the fan-out, so at the
    # scrape instant the registry has bridged at_step - 1 steps
    assert _gauge_value(text, "replay_train_steps_total") >= scraper.at_step - 1
    assert _gauge_value(text, "replay_train_up") == 1.0
    assert _gauge_value(text, "replay_train_bad_steps") == 1.0
    assert "replay_train_step_seconds_bucket" in text
    # epoch 0 closed before the scrape: its goodput fractions are live gauges
    goodput_lines = [
        line for line in text.splitlines()
        if line.startswith("replay_goodput_fraction{")
    ]
    assert goodput_lines, "no goodput gauges in the mid-fit scrape"
    assert all(math.isfinite(float(l.rsplit(" ", 1)[1])) for l in goodput_lines)
    assert math.isfinite(_gauge_value(text, "replay_input_starvation"))

    # -- the NaN step tripped the bad_steps rule EXACTLY once --------------- #
    lines = [json.loads(line) for line in open(os.path.join(run_dir, "events.jsonl"))]
    violations = [line for line in lines if line["event"] == "on_slo_violation"]
    assert len(violations) == 1
    assert violations[0]["rule"] == "bad_steps"
    assert violations[0]["value"] == 1.0
    assert not [line for line in lines if line["event"] == "on_slo_recovery"]
    registry = trainer.metrics_registry
    assert registry.value(
        "replay_slo_violations_total", labels={"rule": "bad_steps"}
    ) == 1
    assert registry.value("replay_slo_breached", labels={"rule": "bad_steps"}) == 1.0

    # -- post-fit: exporter stopped, registry readable, report renders ------ #
    assert trainer.metrics_exporter is None
    assert registry.value("replay_train_steps_total") == 16
    assert registry.value("replay_train_up") == 0.0

    with open(os.path.join(run_dir, "metrics.txt"), "w") as fh:
        fh.write(text)
    with open(os.path.join(run_dir, "snapshot.json"), "w") as fh:
        json.dump(scraper.snapshot, fh, indent=2)

    summary = summarize_run(run_dir)
    assert summary["slo_violations"] == 1
    assert summary["slo_rules_fired"] == ["bad_steps"]
    assert summary["bad_steps"] == 1


def test_metrics_without_port_keeps_registry_only(tmp_path):
    """slo_rules alone (no exporter): the watchdog still runs on the bridged
    registry and violations still reach the sinks; no HTTP server appears."""
    trainer = make_trainer()
    injector = NaNInjector(at_steps=(1,))
    events = []

    class Sink:
        def log_event(self, event):
            events.append(event)

    trainer.fit(
        lambda epoch: injector.wrap([make_batch(i) for i in range(4)]),
        epochs=1,
        loggers=Sink(),
        slo_rules=[SLORule("replay_train_bad_steps", ">", 0, name="bad_steps")],
        log_every=0,
    )
    assert trainer.metrics_exporter is None
    assert [e.event for e in events if e.event == "on_slo_violation"] == [
        "on_slo_violation"
    ]
    assert trainer.metrics_registry.value("replay_train_bad_steps") == 1


def test_busy_metrics_port_never_fails_the_fit(tmp_path):
    """The graceful no-op: a port someone else owns logs a warning and the
    fit completes unobserved (registry still fills via the bridge)."""
    from replay_tpu.obs import MetricsExporter, MetricsRegistry

    squatter = MetricsExporter(MetricsRegistry(), port=0).start()
    trainer = make_trainer()
    try:
        state = trainer.fit(
            lambda epoch: [make_batch(i) for i in range(3)],
            epochs=1,
            metrics_port=squatter.port,
            log_every=0,
        )
        assert int(state.step) == 3
        assert trainer.metrics_registry.value("replay_train_steps_total") == 3
        assert trainer.metrics_exporter is None
    finally:
        squatter.close()
