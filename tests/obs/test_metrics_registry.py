"""Live metrics registry (obs.metrics): types, quantiles, the event bridge.

Core tier, no jax: the registry and the bridge are stdlib-only by contract
(the exporter must be able to serve from any process, including the report
CLI's import-light world).
"""

import math
import threading

import numpy as np
import pytest

from replay_tpu.obs.events import TrainerEvent
from replay_tpu.obs.metrics import (
    FILL_BUCKETS,
    Histogram,
    MetricsLogger,
    MetricsRegistry,
)

pytestmark = pytest.mark.core


# --------------------------------------------------------------------------- #
# registry primitives
# --------------------------------------------------------------------------- #
def test_counter_monotone_and_gauge_last_write():
    registry = MetricsRegistry()
    registry.inc("c_total")
    registry.inc("c_total", 2.5)
    assert registry.value("c_total") == 3.5
    with pytest.raises(ValueError, match="monotone"):
        registry.inc("c_total", -1)
    registry.set("g", 1.0)
    registry.set("g", -7.25)
    assert registry.value("g") == -7.25


def test_type_collision_raises():
    registry = MetricsRegistry()
    registry.inc("m")
    with pytest.raises(ValueError, match="counter"):
        registry.set("m", 1.0)
    with pytest.raises(ValueError, match="counter"):
        registry.observe("m", 1.0)


def test_labeled_series_are_independent():
    registry = MetricsRegistry()
    registry.inc("shed_total", 2, labels={"lane": "hit"})
    registry.inc("shed_total", 3, labels={"lane": "encode:L=16"})
    assert registry.value("shed_total", labels={"lane": "hit"}) == 2
    assert registry.value("shed_total", labels={"lane": "encode:L=16"}) == 3
    assert registry.value("shed_total") is None  # the unlabeled series is absent
    text = registry.render_prometheus()
    assert 'shed_total{lane="hit"} 2' in text
    assert 'shed_total{lane="encode:L=16"} 3' in text


def test_missing_metric_reads_none():
    registry = MetricsRegistry()
    assert registry.value("nope") is None
    assert registry.value("nope:p99") is None


def test_histogram_stat_refs_and_errors():
    registry = MetricsRegistry()
    for v in (1.0, 2.0, 3.0, 4.0):
        registry.observe("h", v, buckets=[1, 2, 3, 4])
    assert registry.value("h:count") == 4
    assert registry.value("h:sum") == 10.0
    assert registry.value("h:mean") == 2.5
    assert registry.value("h:max") == 4.0
    assert registry.value("h:min") == 1.0
    with pytest.raises(ValueError, match="unknown histogram stat"):
        registry.value("h:pXX")
    registry.set("g", 1.0)
    with pytest.raises(ValueError, match="suffix is for histograms"):
        registry.value("g:p50")


# --------------------------------------------------------------------------- #
# histogram quantile accuracy against numpy (the satellite's contract)
# --------------------------------------------------------------------------- #
@pytest.mark.parametrize(
    "name,sampler",
    [
        ("uniform", lambda rng, n: rng.uniform(0.0, 10.0, n)),
        ("normal", lambda rng, n: rng.normal(5.0, 1.5, n)),
        ("exponential", lambda rng, n: rng.exponential(2.0, n)),
    ],
)
def test_quantiles_track_numpy_on_known_distributions(name, sampler):
    rng = np.random.default_rng(0)
    data = sampler(rng, 20_000)
    # fine uniform ladder over the support: the estimate's error is bounded
    # by one bucket width, so the tolerance below is the ladder pitch
    lo, hi = float(np.min(data)), float(np.max(data))
    pitch = (hi - lo) / 200.0
    histogram = Histogram(buckets=np.linspace(lo, hi, 201))
    for value in data:
        histogram.observe(float(value))
    for q in (0.5, 0.9, 0.99):
        estimate = histogram.quantile(q)
        exact = float(np.quantile(data, q))
        assert estimate == pytest.approx(exact, abs=2 * pitch), (name, q)
    assert histogram.quantile(0.0) == pytest.approx(lo, abs=2 * pitch)
    assert histogram.quantile(1.0) == pytest.approx(hi, abs=2 * pitch)
    assert histogram.mean() == pytest.approx(float(np.mean(data)), rel=0.02)


def test_quantile_clamps_to_observed_range_and_overflow():
    histogram = Histogram(buckets=[1.0, 2.0])
    for value in (0.5, 0.6, 5.0):  # 5.0 lands in the +Inf bucket
        histogram.observe(value)
    assert histogram.quantile(0.99) == 5.0  # the best finite tail statement
    assert histogram.quantile(0.01) >= 0.5  # clamped to the observed min
    assert histogram.counts[-1] == 1
    histogram.observe(float("nan"))  # ignored, never poisons sum/count
    assert histogram.total == 3 and math.isfinite(histogram.sum)


def test_empty_histogram_quantile_is_none():
    assert Histogram(buckets=[1.0]).quantile(0.5) is None


# --------------------------------------------------------------------------- #
# prometheus rendering
# --------------------------------------------------------------------------- #
def test_prometheus_text_shape():
    registry = MetricsRegistry()
    registry.inc("req_total", 7)
    registry.set("loss", 0.25)
    registry.observe("lat", 0.3, buckets=[0.1, 0.5, 1.0])
    registry.observe("lat", 0.7, buckets=[0.1, 0.5, 1.0])
    text = registry.render_prometheus()
    assert "# TYPE req_total counter" in text
    assert "# TYPE loss gauge" in text
    assert "# TYPE lat histogram" in text
    assert 'lat_bucket{le="0.5"} 1' in text
    assert 'lat_bucket{le="1"} 2' in text
    assert 'lat_bucket{le="+Inf"} 2' in text
    assert "lat_count 2" in text and "lat_sum 1" in text
    assert text.endswith("\n")


def test_concurrent_writers_never_tear_a_render():
    registry = MetricsRegistry()
    stop = threading.Event()

    def hammer(i):
        while not stop.is_set():
            registry.inc("w_total")
            registry.observe("h", 0.1 * i, buckets=[0.1, 0.5, 1.0])
            registry.set("g", float(i))

    workers = [threading.Thread(target=hammer, args=(i,), daemon=True) for i in range(4)]
    for w in workers:
        w.start()
    last_total = -1.0
    try:
        for _ in range(50):
            text = registry.render_prometheus()
            # every render parses and counters are monotone across renders
            totals = [
                float(line.split()[-1])
                for line in text.splitlines()
                if line.startswith("w_total ")
            ]
            assert len(totals) == 1
            assert totals[0] >= last_total
            last_total = totals[0]
            snap = registry.snapshot()
            h = snap.get("h")
            if h:
                # the snapshot is internally consistent: buckets + overflow
                # account for every observation
                assert sum(h["buckets"].values()) + h["overflow"] == h["count"]
    finally:
        stop.set()
        for w in workers:
            w.join(timeout=5)


# --------------------------------------------------------------------------- #
# the event bridge
# --------------------------------------------------------------------------- #
def _step_event(step, loss=0.5, step_seconds=0.01):
    return TrainerEvent(
        "on_train_step",
        step=step,
        payload={
            "loss": loss,
            "lr": 1e-3,
            "samples_per_sec": 800.0,
            "steps_per_sec": 100.0,
            "step_seconds": step_seconds,
        },
    )


def test_bridge_train_events():
    bridge = MetricsLogger()
    for i in range(1, 4):
        bridge.log_event(_step_event(i))
    bridge.log_event(_step_event(4, loss=float("nan")))  # sentinel-skipped step
    registry = bridge.registry
    assert registry.value("replay_train_steps_total") == 4
    assert registry.value("replay_train_loss") == 0.5  # NaN never overwrites
    assert registry.value("replay_train_step_seconds:count") == 4
    bridge.log_event(
        TrainerEvent("on_anomaly", step=4, payload={"bad_steps_total": 1})
    )
    assert registry.value("replay_train_anomalies_total") == 1
    assert registry.value("replay_train_bad_steps") == 1
    bridge.log_event(
        TrainerEvent(
            "on_epoch_end",
            epoch=0,
            payload={
                "record": {"train_loss": 0.4},
                "bad_steps": 1,
                "goodput": {
                    "fractions": {"train_step": 0.9, "data_wait": 0.1},
                    "input_starvation": 0.1,
                },
            },
        )
    )
    assert registry.value("replay_train_loss_epoch") == 0.4
    assert registry.value(
        "replay_goodput_fraction", labels={"phase": "train_step"}
    ) == 0.9
    assert registry.value("replay_input_starvation") == 0.1


def test_bridge_serve_events_and_qps_window():
    clock = [0.0]
    bridge = MetricsLogger(qps_window_seconds=10.0, clock=lambda: clock[0])
    registry = bridge.registry
    bridge.log_event(TrainerEvent("on_serve_start", payload={}))
    assert registry.value("replay_serve_up") == 1.0
    for i in range(5):
        clock[0] = float(i)
        bridge.log_event(
            TrainerEvent(
                "on_serve_batch",
                payload={
                    "lane": "hit",
                    "rows": 8,
                    "bucket": 8,
                    "fill": 1.0,
                    "queue_wait_ms_max": 2.0,
                    "dropped_expired": 0,
                    "dropped_cancelled": 0,
                },
            )
        )
    assert registry.value("replay_serve_rows_total") == 40
    assert registry.value("replay_serve_batches_total") == 5
    # 40 rows over the 4-second window span
    assert registry.value("replay_serve_qps") == pytest.approx(10.0)
    assert registry.value("replay_serve_batch_fill:count") == 5
    bridge.log_event(
        TrainerEvent(
            "on_shed",
            payload={"lane": "hit", "depth": 9, "max_depth": 8, "count": 4},
        )
    )
    assert registry.value("replay_serve_shed_total") == 4
    assert registry.value("replay_serve_lane_depth", labels={"lane": "hit"}) == 9
    bridge.log_event(
        TrainerEvent("on_breaker", payload={"from": "closed", "to": "open"})
    )
    assert registry.value("replay_serve_breaker_state") == 2.0
    bridge.log_event(
        TrainerEvent("on_degrade", payload={"to": "fallback", "reason": "overload"})
    )
    assert (
        registry.value("replay_serve_degraded_total", labels={"to": "fallback"}) == 1
    )
    bridge.log_event(
        TrainerEvent(
            "on_serve_end",
            payload={"cache_hit_rate": 0.9, "shed_rate": 0.1, "requests": 50},
        )
    )
    assert registry.value("replay_serve_cache_hit_rate") == 0.9
    assert registry.value("replay_serve_shed_rate") == pytest.approx(0.1)
    assert registry.value("replay_serve_up") == 0.0


def test_bridge_empty_batch_skips_fill_and_wait():
    """A fully-dropped batch (rows=0) must not pollute the fill/wait
    histograms with zeros — only the drop counters move."""
    bridge = MetricsLogger()
    bridge.log_event(
        TrainerEvent(
            "on_serve_batch",
            payload={
                "lane": "hit", "rows": 0, "bucket": 0, "fill": 0.0,
                "queue_wait_ms_max": 0.0, "dropped_expired": 3,
                "dropped_cancelled": 1,
            },
        )
    )
    registry = bridge.registry
    assert registry.value("replay_serve_expired_total") == 3
    assert registry.value("replay_serve_cancelled_total") == 1
    assert registry.value("replay_serve_batch_fill:count") is None
    assert registry.value("replay_serve_queue_wait_ms:count") is None


def test_fill_buckets_cover_the_unit_interval():
    assert FILL_BUCKETS[-1] == 1.0


def test_bridge_promotion_events():
    """The serve.promote event family replays from events into the same
    replay_canary_* / swap / rollback series the live controller maintains."""
    logger = MetricsLogger()
    registry = logger.registry
    logger.log_event(TrainerEvent(event="on_publish", payload={
        "generation": 1, "label": "v1", "recompiled": True,
        "recompile_reason": "leaf 'x' has shape (5, 2)",
    }))
    assert registry.value("replay_publish_total") == 1.0
    assert registry.value("replay_publish_recompiled_total") == 1.0
    logger.log_event(TrainerEvent(event="on_canary_start", payload={
        "generation": 1, "fraction": 0.25,
    }))
    assert registry.value("replay_canary_stage") == 2.0
    assert registry.value("replay_canary_generation") == 1.0
    logger.log_event(TrainerEvent(event="on_canary_eval", payload={
        "generation": 1, "error_rate": 0.125, "clean_evals": 2,
        "window": {"requests": 16.0},
    }))
    assert registry.value("replay_canary_error_rate") == 0.125
    assert registry.value("replay_canary_clean_evals") == 2.0
    assert registry.value("replay_canary_requests") == 16.0
    logger.log_event(TrainerEvent(event="on_swap", payload={
        "reason": "promote", "from_generation": 0, "to_generation": 1,
        "recompiled": True,
    }))
    assert registry.value("replay_swap_total") == 1.0
    assert registry.value("replay_param_generation") == 1.0
    logger.log_event(TrainerEvent(event="on_promotion", payload={
        "generation": 1, "from_generation": 0, "clean_evals": 3, "evals": 3,
    }))
    assert registry.value("replay_promotions_total") == 1.0
    assert registry.value("replay_canary_stage") == 3.0
    logger.log_event(TrainerEvent(event="on_rollback", payload={
        "generation": 2, "restored_generation": 1, "rules": ["canary_errors"],
    }))
    assert registry.value("replay_rollbacks_total") == 1.0
    assert registry.value("replay_canary_stage") == -1.0
    assert registry.value("replay_param_generation") == 1.0  # restored gen
