"""Run-telemetry subsystem: event sinks, collectors, MFU math, trainer wiring.

Core tier covers the host-side pieces (loggers, telemetry math, the peak-TFLOPs
table); the jax tier covers retrace counting and device memory; the smoke test
drives ``Trainer.fit`` end-to-end with a ``JsonlLogger`` and asserts the
static-shapes invariant (exactly one train-step compile across epochs) plus the
bench driver's JSON-line contract with the new observability fields.
"""

import json
import logging
import math
import time

import numpy as np
import pytest

from replay_tpu.obs import (
    CompileTracker,
    ConsoleLogger,
    JsonlLogger,
    MemoryMonitor,
    MultiLogger,
    RunLogger,
    StepTelemetry,
    TensorBoardLogger,
    TrainerEvent,
    flops_per_step,
    mfu,
    peak_tflops,
)
from replay_tpu.obs import events as events_module
from replay_tpu.utils import StepTimer


class RecordingLogger(RunLogger):
    def __init__(self):
        self.events = []
        self.closed = False

    def log_event(self, event):
        self.events.append(event)

    def close(self):
        self.closed = True


# --------------------------------------------------------------------------- #
# event layer (core)
# --------------------------------------------------------------------------- #
def test_trainer_event_to_record_coerces_numpy():
    event = TrainerEvent(
        "on_train_step",
        step=np.int64(7),
        epoch=1,
        payload={
            "loss": np.float32(1.5),
            "arr": np.arange(3),
            "nested": {"lr": np.float64(0.1), "flag": True},
            "none": None,
        },
    )
    record = event.to_record()
    assert record["event"] == "on_train_step"
    assert record["step"] == 7 and isinstance(record["step"], int)
    assert record["loss"] == 1.5 and isinstance(record["loss"], float)
    assert record["arr"] == [0, 1, 2]
    assert record["nested"] == {"lr": 0.1, "flag": True}
    assert record["none"] is None
    json.dumps(record)  # fully JSON-able


def test_jsonl_logger_roundtrip(tmp_path):
    run_dir = tmp_path / "run"
    with JsonlLogger(str(run_dir)) as sink:
        sink.log_event(TrainerEvent("on_fit_start", payload={"epochs": 2}))
        sink.log_event(
            TrainerEvent("on_train_step", step=1, payload={"loss": float("nan")})
        )
        sink.log_record({"event": "custom", "value": np.float32(3.0)})
    lines = [json.loads(line) for line in open(sink.path)]
    assert [line["event"] for line in lines] == ["on_fit_start", "on_train_step", "custom"]
    assert lines[0]["epochs"] == 2
    # strict JSON: NaN serializes as null, but the key stays (shape-stable)
    assert "loss" in lines[1] and lines[1]["loss"] is None
    assert lines[2]["value"] == 3.0
    # append mode: a second logger on the same file extends the stream
    more = JsonlLogger(str(run_dir))
    more.log_event(TrainerEvent("on_fit_end"))
    more.close()
    more.close()  # idempotent
    assert len(open(sink.path).readlines()) == 4


def test_multi_logger_fans_out_and_closes():
    sinks = [RecordingLogger(), RecordingLogger()]
    multi = MultiLogger(sinks)
    multi.log_event(TrainerEvent("on_fit_start"))
    multi.log_event(TrainerEvent("on_fit_end"))
    for sink in sinks:
        assert [e.event for e in sink.events] == ["on_fit_start", "on_fit_end"]
    multi.close()
    assert all(sink.closed for sink in sinks)


def test_tensorboard_logger_missing_backend_is_noop(tmp_path, monkeypatch):
    monkeypatch.setattr(events_module, "_load_summary_writer", lambda: None)
    sink = TensorBoardLogger(str(tmp_path / "tb"))
    sink.log_event(TrainerEvent("on_train_step", step=1, payload={"loss": 1.0}))
    sink.close()  # never raises without a backend


def test_tensorboard_logger_writes_scalars(tmp_path, monkeypatch):
    calls = []

    class FakeWriter:
        def __init__(self, log_dir):
            calls.append(("init", log_dir))

        def add_scalar(self, tag, value, global_step=0):
            calls.append((tag, value, global_step))

        def close(self):
            calls.append(("close",))

    monkeypatch.setattr(events_module, "_load_summary_writer", lambda: FakeWriter)
    sink = TensorBoardLogger(str(tmp_path / "tb"))
    sink.log_event(
        TrainerEvent(
            "on_train_step",
            step=5,
            payload={"loss": 2.0, "note": "skipped", "flag": True},
        )
    )
    # the trainer nests epoch/validation metrics under a dict-valued "record"
    sink.log_event(
        TrainerEvent(
            "on_epoch_end", step=5, payload={"record": {"train_loss": 1.2, "ndcg@5": 0.5}}
        )
    )
    sink.close()
    assert ("loss", 2.0, 5) in calls  # train-step scalars keep bare tags
    assert ("on_epoch_end/record/train_loss", 1.2, 5) in calls
    assert ("on_epoch_end/record/ndcg@5", 0.5, 5) in calls
    assert not any(tag in ("note", "flag") for tag, *_ in calls)
    assert calls[-1] == ("close",)


def test_console_logger_cadence(caplog):
    sink = ConsoleLogger(every=2)
    with caplog.at_level(logging.INFO, logger="replay_tpu"):
        for step in range(1, 5):
            sink.log_event(
                TrainerEvent("on_train_step", step=step, epoch=0, payload={"loss": 1.0})
            )
        sink.log_event(
            TrainerEvent("on_epoch_end", epoch=0, payload={"record": {"train_loss": 1.0}})
        )
    step_lines = [r for r in caplog.records if "step" in r.message]
    assert len(step_lines) == 2  # every 2nd received event
    assert any("epoch 0:" in r.getMessage() for r in caplog.records)


# --------------------------------------------------------------------------- #
# collectors (core where possible)
# --------------------------------------------------------------------------- #
def test_step_telemetry_rates_and_summary():
    telemetry = StepTelemetry(warmup_steps=1, samples_per_step=4)
    telemetry.mark()
    first = telemetry.tick()
    assert np.isfinite(first["samples_per_sec"])  # finite from the very first tick
    time.sleep(0.01)
    tick = telemetry.tick(samples=8, steps=2)
    assert tick["steps_per_sec"] == pytest.approx(2 / (tick["step_seconds"] * 2))
    assert tick["samples_per_sec"] == pytest.approx(tick["steps_per_sec"] * 4)
    summary = telemetry.summary()
    assert set(summary) == {"steps", "elapsed_seconds", "steps_per_sec", "samples_per_sec"}
    assert summary["steps"] == 2 and np.isfinite(summary["samples_per_sec"])


def test_step_telemetry_multi_step_first_tick_not_inflated():
    """A first tick covering many steps (sparse log_every cadence) prorates
    across the warmup boundary: counting its steps while starting the clock at
    its end would double the reported steady-state rate; discarding it outright
    would NaN short runs."""
    telemetry = StepTelemetry(warmup_steps=1)
    telemetry.mark()
    time.sleep(0.02)
    telemetry.tick(steps=100, samples=400)  # spans warmup: 99 steps prorated in
    time.sleep(0.02)
    telemetry.tick(steps=100, samples=400)
    summary = telemetry.summary()
    assert summary["steps"] == 199
    # ~199 steps over ~0.04 s of prorated window: no 2x inflation
    assert summary["steps_per_sec"] == pytest.approx(100 / 0.02, rel=0.5)


def test_step_telemetry_summary_window_ends_at_last_tick():
    """summary() after a long gap (validation, checkpointing) must not dilute
    the steady-state rate with non-training wall time."""
    telemetry = StepTelemetry(warmup_steps=0)
    telemetry.mark()
    time.sleep(0.02)
    telemetry.tick(steps=10, samples=10)
    rate = telemetry.summary()["steps_per_sec"]
    time.sleep(0.05)  # "validation" happens here
    assert telemetry.summary()["steps_per_sec"] == pytest.approx(rate, rel=0.05)


def test_step_telemetry_mark_discounts_pauses():
    """Re-marking after a pause (the trainer re-marks per epoch, after
    validation/checkpointing) resumes the window without the gap."""
    telemetry = StepTelemetry(warmup_steps=0)
    telemetry.mark()
    time.sleep(0.02)
    telemetry.tick(steps=10)
    time.sleep(0.06)  # inter-epoch validation
    telemetry.mark()
    time.sleep(0.02)
    telemetry.tick(steps=10)
    summary = telemetry.summary()
    assert summary["steps"] == 20
    # ~20 steps / ~0.04 s of TRAINING time; with the pause counted the rate
    # would be ~2.5x lower and fall outside the tolerance
    assert summary["steps_per_sec"] == pytest.approx(20 / 0.04, rel=0.4)


def test_step_telemetry_summary_shape_stable_when_unmeasured():
    summary = StepTelemetry().summary()
    assert set(summary) == {"steps", "elapsed_seconds", "steps_per_sec", "samples_per_sec"}
    assert summary["steps"] == 0
    assert all(
        math.isnan(summary[k])
        for k in ("elapsed_seconds", "steps_per_sec", "samples_per_sec")
    )


def test_step_timer_finish_shape_stable():
    # the satellite fix: measured <= 0 must not change the record's shape
    empty = StepTimer(warmup_steps=5, samples_per_step=8)
    empty.tick()
    record = empty.finish()
    assert set(record) == {"steps", "steps_per_sec", "samples_per_sec"}
    assert record["steps"] == 0  # measured steps, not the raw tick count
    assert math.isnan(record["steps_per_sec"]) and math.isnan(record["samples_per_sec"])
    # no samples_per_step: the key is still present (NaN), never missing
    timer = StepTimer(warmup_steps=1)
    for _ in range(3):
        timer.tick()
    record = timer.finish()
    assert record["steps"] == 2 and record["steps_per_sec"] > 0
    assert math.isnan(record["samples_per_sec"])


def test_peak_tflops_table_and_mfu():
    assert peak_tflops("TPU v5 lite") == 197.0
    assert peak_tflops("TPU v5p chip") == 459.0
    assert peak_tflops("cpu") is None and peak_tflops("") is None
    assert mfu(19.7, "TPU v5e") == pytest.approx(0.1)
    assert mfu(19.7, "TPU v5e", device_count=2) == pytest.approx(0.05)
    assert mfu(10.0, "cpu") is None  # unknown peak -> no made-up MFU


@pytest.mark.jax
def test_compile_tracker_counts_retraces():
    import jax
    import jax.numpy as jnp

    tracker = CompileTracker()
    jitted = jax.jit(tracker.wrap(lambda x: x * 2, "double"))
    with tracker.observe("double"):
        jitted(jnp.ones((3,)))
    jitted(jnp.ones((3,)))  # cache hit: no retrace
    with tracker.observe("double"):
        jitted(jnp.ones((4,)))  # shape-unstable call: retrace
    assert tracker.traces["double"] == 2
    assert tracker.compile_seconds["double"] > 0
    report = tracker.report()
    assert report["double"]["traces"] == 2
    assert tracker.total_compile_seconds == pytest.approx(
        tracker.compile_seconds["double"]
    )


@pytest.mark.jax
def test_compile_tracker_observe_skips_cache_hits():
    import jax
    import jax.numpy as jnp

    tracker = CompileTracker()
    jitted = jax.jit(tracker.wrap(lambda x: x + 1, "inc"))
    jitted(jnp.ones((2,)))  # compile outside observe
    with tracker.observe("inc"):
        jitted(jnp.ones((2,)))  # cache hit: no compile time attributed
    assert tracker.compile_seconds.get("inc", 0.0) == 0.0


@pytest.mark.jax
def test_memory_monitor_degrades_on_cpu():
    monitor = MemoryMonitor()
    snapshot = monitor.snapshot()
    assert isinstance(snapshot, dict)  # CPU: usually {} (no allocator stats)
    peak = monitor.peak_bytes()
    assert peak is None or (isinstance(peak, int) and peak > 0)
    assert monitor.bytes_in_use() is None or monitor.bytes_in_use() >= 0


@pytest.mark.jax
def test_flops_per_step_normalizes_cost_analysis():
    import jax
    import jax.numpy as jnp

    jitted = jax.jit(lambda a, b: a @ b)
    flops = flops_per_step(jitted, jnp.ones((8, 8)), jnp.ones((8, 8)))
    assert flops is None or flops > 0  # backend-dependent, but never raises
    assert (
        flops_per_step(jitted, jnp.ones((8, 8)), jnp.ones((8, 8)), extra_flops=10.0)
        == pytest.approx(flops + 10.0)
        if flops
        else True
    )
