"""Device-time attribution (obs.profile): parsing, the scope join, and the
profiled-fit smoke that produces the CI artifact.

Core tier covers the stdlib-only pieces on synthetic captures/HLO text (no
jax): capture discovery, op-time aggregation, metadata parsing, scope
extraction through transform wrappers, and the attribution invariants
(attributed + unattributed == total). The jax smoke drives
``Trainer.fit(profile_steps=...)`` end-to-end on the virtual 8-device mesh
and asserts the capture parses, the named-scope attribution sums to ≤ the
total step device time with finite fractions, and the ``device_time`` /
``roofline`` payloads land on ``on_fit_end`` (the run_logs/profile_smoke
artifact CI renders and uploads).
"""

import gzip
import json
import math
import os

import numpy as np
import pytest

from replay_tpu.obs.profile import (
    NAMED_SCOPES,
    attribute_capture,
    device_op_times,
    latest_capture,
    load_capture,
    parse_op_metadata,
    scope_of,
)

_HLO_TEXT = """
HloModule jit_train_step

%fused_computation (param_0: f32[8,16]) -> f32[8,16] {
  ROOT %tanh.0 = f32[8,16] tanh(f32[8,16] %param_0), metadata={op_name="jit(train_step)/jit(main)/jvp(forward)/jvp(encoder)/tanh" source_file="model.py" source_line=1}
}

ENTRY %main {
  %dot.5 = f32[8,16]{1,0} dot(f32[8,32]{1,0} %Arg_0.1, f32[32,16]{1,0} %Arg_1.2), lhs_contracting_dims={1}, rhs_contracting_dims={0}, metadata={op_name="jit(train_step)/jit(main)/jvp(forward)/jvp(embed)/dot_general" source_file="model.py" source_line=2}
  %loss_fusion = f32[8]{0} fusion(f32[8,16]{1,0} %dot.5), kind=kLoop, calls=%fused_computation, metadata={op_name="jit(train_step)/jit(main)/transpose(jvp(loss))/reduce_sum" source_file="loss.py" source_line=3}
  ROOT %dot.12 = f32[8,32]{1,0} dot(f32[8,16]{1,0} %loss_fusion, f32[32,16]{1,0} %Arg_1.2), lhs_contracting_dims={1}, rhs_contracting_dims={1}, metadata={op_name="jit(train_step)/jit(main)/transpose(jvp(forward))/jvp(encoder)/dot_general" source_file="model.py" source_line=2}
}
"""


def _write_capture(root, events, run="2026_01_01_00_00_00", host="testhost"):
    directory = os.path.join(root, "plugins", "profile", run)
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, f"{host}.trace.json.gz")
    with gzip.open(path, "wt") as fh:
        json.dump({"traceEvents": events}, fh)
    return path


def _op_event(name, dur_us, module="jit_train_step", tid=1):
    return {
        "ph": "X", "pid": 7, "tid": tid, "ts": 0.0, "dur": dur_us,
        "name": name, "args": {"hlo_module": module, "hlo_op": name},
    }


# --------------------------------------------------------------------------- #
# core: parsing + scope extraction
# --------------------------------------------------------------------------- #
@pytest.mark.core
def test_scope_of_sees_through_transform_wrappers():
    assert scope_of("jit(f)/jit(main)/jvp(forward)/dot_general") == "forward"
    assert scope_of("jit(f)/jit(main)/transpose(jvp(loss))/add_any") == "loss"
    assert scope_of("jit(f)/remat(encoder)/dot_general") == "encoder"
    # the deepest (rightmost) scope wins: embed nests inside forward
    assert scope_of("jit(f)/jvp(forward)/jvp(embed)/gather") == "embed"
    assert scope_of("jit(f)/jit(main)/broadcast") is None
    # substrings must not match ("forward_inference" is not "forward")
    assert scope_of("jit(f)/forward_inference/dot") is None


@pytest.mark.core
def test_parse_op_metadata_maps_instruction_to_op_path():
    mapping = parse_op_metadata(_HLO_TEXT)
    assert mapping["dot.5"].endswith("jvp(embed)/dot_general")
    assert mapping["loss_fusion"].endswith("transpose(jvp(loss))/reduce_sum")
    assert mapping["dot.12"].endswith("jvp(encoder)/dot_general")  # ROOT line parses
    assert mapping["tanh.0"].endswith("jvp(encoder)/tanh")


@pytest.mark.core
def test_device_op_times_filters_to_hlo_events():
    events = [
        _op_event("dot.5", 100.0),
        _op_event("dot.5", 50.0, tid=2),  # same op, another executor thread
        {"ph": "X", "pid": 7, "tid": 1, "ts": 0, "dur": 999.0, "name": "python-frame"},
        {"ph": "M", "pid": 7, "name": "process_name", "args": {"name": "/host:CPU"}},
    ]
    totals = device_op_times(events)
    assert totals == {("jit_train_step", "dot.5"): pytest.approx(150e-6)}


@pytest.mark.core
def test_latest_capture_picks_newest_and_handles_missing(tmp_path):
    assert latest_capture(str(tmp_path)) is None
    older = _write_capture(str(tmp_path), [], run="2026_01_01_00_00_00")
    newer = _write_capture(str(tmp_path), [], run="2026_01_02_00_00_00")
    os.utime(older, (1, 1))
    assert latest_capture(str(tmp_path)) == newer
    assert load_capture(newer) == []


@pytest.mark.core
def test_attribute_capture_joins_scopes_and_balances(tmp_path):
    _write_capture(
        str(tmp_path),
        [
            _op_event("dot.5", 100.0),       # embed
            _op_event("loss_fusion", 40.0),  # loss
            _op_event("dot.12", 60.0),       # encoder (bwd)
            _op_event("unknown_op.3", 30.0), # no metadata -> unattributed
        ],
    )
    record = attribute_capture(str(tmp_path), _HLO_TEXT)
    assert record["total_device_seconds"] == pytest.approx(230e-6)
    scopes = record["scopes"]
    assert scopes["embed"]["seconds"] == pytest.approx(100e-6)
    assert scopes["loss"]["seconds"] == pytest.approx(40e-6)
    assert scopes["encoder"]["seconds"] == pytest.approx(60e-6)
    assert record["unattributed_seconds"] == pytest.approx(30e-6)
    assert record["attributed_seconds"] + record["unattributed_seconds"] == pytest.approx(
        record["total_device_seconds"]
    )
    fractions = sum(entry["fraction"] for entry in scopes.values())
    assert 0.0 < fractions <= 1.0 + 1e-9
    # display order follows NAMED_SCOPES
    assert list(scopes) == [s for s in NAMED_SCOPES if s in scopes]


@pytest.mark.core
def test_attribution_join_is_module_keyed(tmp_path):
    """Instruction names are module-local counters: the SAME name in two
    programs must resolve through its OWN module's op path, not first-wins."""
    step_hlo = (
        "HloModule jit_step, is_scheduled=true\n"
        "ENTRY %main {\n"
        '  %fusion.3 = f32[8]{0} fusion(%p0), kind=kLoop, calls=%fc, metadata={op_name="jit(step)/jvp(encoder)/add" source_file="m.py" source_line=1}\n'
        "}\n"
    )
    scan_hlo = (
        "HloModule jit_scan, is_scheduled=true\n"
        "ENTRY %main {\n"
        '  %fusion.3 = f32[8]{0} fusion(%p0), kind=kLoop, calls=%fc, metadata={op_name="jit(scan)/transpose(jvp(loss))/add" source_file="l.py" source_line=2}\n'
        "}\n"
    )
    _write_capture(
        str(tmp_path),
        [
            _op_event("fusion.3", 100.0, module="jit_step"),
            _op_event("fusion.3", 40.0, module="jit_scan"),
        ],
    )
    record = attribute_capture(
        str(tmp_path), {"train_step": step_hlo, "train_scan": scan_hlo}
    )
    assert record["scopes"]["encoder"]["seconds"] == pytest.approx(100e-6)
    assert record["scopes"]["loss"]["seconds"] == pytest.approx(40e-6)


@pytest.mark.core
def test_attribute_capture_without_capture_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        attribute_capture(str(tmp_path / "nowhere"))


@pytest.mark.core
def test_attribute_capture_without_hlo_attributes_nothing(tmp_path):
    _write_capture(str(tmp_path), [_op_event("dot.5", 10.0)])
    record = attribute_capture(str(tmp_path), None)
    assert record["scopes"] == {}
    assert record["unattributed_seconds"] == pytest.approx(record["total_device_seconds"])


# --------------------------------------------------------------------------- #
# jax smoke: the profiled fit end-to-end (CI's profile_smoke artifact)
# --------------------------------------------------------------------------- #
def _run_dir(tmp_path, name):
    base = os.environ.get("REPLAY_TPU_RUN_DIR")
    return os.path.join(base, name) if base else str(tmp_path / name)


def _make_trainer(num_items=50, seq_len=8, dim=16):
    from replay_tpu.data import FeatureHint, FeatureType
    from replay_tpu.data.nn import TensorFeatureInfo, TensorSchema
    from replay_tpu.nn import OptimizerFactory, Trainer, make_mesh
    from replay_tpu.nn.loss import CE
    from replay_tpu.nn.sequential.sasrec import SasRec

    schema = TensorSchema(
        TensorFeatureInfo(
            "item_id", FeatureType.CATEGORICAL, is_seq=True,
            feature_hint=FeatureHint.ITEM_ID, cardinality=num_items,
            embedding_dim=dim,
        )
    )
    model = SasRec(schema=schema, embedding_dim=dim, num_blocks=1, num_heads=1,
                   max_sequence_length=seq_len)
    return Trainer(model=model, loss=CE(),
                   optimizer=OptimizerFactory(learning_rate=1e-2), mesh=make_mesh())


def _make_batches(n, num_items=50, seq_len=8, batch=8, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        items = rng.integers(0, num_items, size=(batch, seq_len + 1)).astype(np.int32)
        mask = np.ones((batch, seq_len), dtype=bool)
        out.append({
            "feature_tensors": {"item_id": items[:, :-1]},
            "padding_mask": mask,
            "positive_labels": items[:, 1:, None],
            "target_padding_mask": mask[:, :, None],
        })
    return out


@pytest.mark.jax
@pytest.mark.smoke
def test_profiled_fit_attributes_device_time(tmp_path, monkeypatch):
    from replay_tpu.obs import JsonlLogger

    # classify against an assumed chip on the CPU mesh (arithmetic, flagged)
    monkeypatch.setenv("REPLAY_TPU_ROOFLINE_ASSUME_KIND", "v5e")
    trainer = _make_trainer()
    batches = _make_batches(5)
    run_dir = _run_dir(tmp_path, "profile_smoke")
    # mode="w": REPLAY_TPU_RUN_DIR is a fixed path in CI — re-runs must not append
    with JsonlLogger(run_dir, mode="w") as sink:
        trainer.fit(batches, epochs=1, loggers=sink, log_every=0,
                    profile_steps=(1, 4), scan_chunk=2)

    profile_dir = os.path.join(run_dir, "profile")
    assert latest_capture(profile_dir) is not None, "no parseable capture produced"

    events = [json.loads(line) for line in open(os.path.join(run_dir, "events.jsonl"))]
    fit_end = [e for e in events if e["event"] == "on_fit_end"][-1]
    device_time = fit_end["device_time"]
    total = device_time["total_device_seconds"]
    assert total > 0.0
    scopes = device_time["scopes"]
    assert scopes, "no named scope resolved from the capture"
    # the attribution must not over-claim: scope sum <= total step device time
    attributed = sum(entry["seconds"] for entry in scopes.values())
    assert attributed <= total * (1.0 + 1e-9)
    assert device_time["attributed_seconds"] == pytest.approx(attributed)
    for entry in scopes.values():
        assert math.isfinite(entry["fraction"]) and 0.0 <= entry["fraction"] <= 1.0
    # the model-body scopes landed in PR 3 are now READ back
    assert {"encoder", "loss"} <= set(scopes)

    # the roofline payload rides the same event: both dispatched programs
    # classified, the full-CE step memory-bound under the assumed v5e peaks
    roofline = fit_end["roofline"]
    assert {"train_step", "train_scan"} <= set(roofline)
    for record in roofline.values():
        assert record["hbm_peak_bytes"] > 0
        classification = record["roofline"]
        assert classification["bound"] == "memory"
        assert classification["peak_assumed"] == "v5e"
        assert 0.0 < classification["ceiling_tflops"] <= classification["peak_tflops"]


@pytest.mark.jax
def test_profiled_per_step_fit_attribution_and_window(tmp_path):
    """The per-step (unchunked) path: window [1, 3) opens/closes inside the
    fit and the attribution still resolves scopes."""
    trainer = _make_trainer()
    batches = _make_batches(4)
    profile_dir = str(tmp_path / "prof")
    trainer.fit(batches, epochs=1, log_every=0, profile_steps=(1, 3),
                profile_dir=profile_dir)
    record = attribute_capture(profile_dir, trainer.lowered_hlo("train_step"))
    assert record["total_device_seconds"] > 0.0
    assert record["scopes"], record


@pytest.mark.jax
def test_analyze_programs_and_lowered_hlo_roundtrip():
    trainer = _make_trainer()
    batches = _make_batches(1)
    state = trainer.init_state(batches[0])
    trainer.train_step(state, batches[0])
    hlo = trainer.lowered_hlo("train_step")
    assert "op_name" in hlo  # metadata survives for the attribution join
    with pytest.raises(KeyError):
        trainer.lowered_hlo("train_scan")  # never dispatched
    records = trainer.analyze_programs()
    assert "train_step" in records
    assert records["train_step"]["hbm_peak_bytes"] > 0
    assert records["train_step"]["collectives"]["count"] >= 0
