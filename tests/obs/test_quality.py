"""The quality plane's pure core (obs.quality): PSI math, drift detection,
the popularity descriptor artifact, the prequential per-slate formulas and
the SLO cookbook — everything that runs jax-free. The monitor-through-service
half (online/offline reconciliation, the drift SLO through the watchdog, the
quality-gated canary) lives in tests/serve/test_quality_service.py.
"""

import math

import pytest

from replay_tpu.obs.quality import (
    QUALITY_SLOS,
    DriftDetector,
    PopularityDescriptor,
    QualityMonitor,
    canary_quality_rules,
    population_stability_index,
    prequential_scores,
)

pytestmark = pytest.mark.core


# ---------------------------------------------------------------------------
# population stability index
# ---------------------------------------------------------------------------


class TestPSI:
    def test_identical_distributions_are_stable(self):
        values = [i / 100.0 for i in range(100)]
        edges = [0.0, 0.25, 0.5, 0.75, 1.0]
        assert population_stability_index(values, list(values), edges) == pytest.approx(
            0.0, abs=1e-9
        )

    def test_shifted_distribution_scores_high(self):
        edges = [0.0, 0.25, 0.5, 0.75, 1.0]
        reference = [i / 100.0 for i in range(100)]
        shifted = [0.9] * 100  # everything lands in the top bin
        psi = population_stability_index(reference, shifted, edges)
        assert psi > 1.0

    def test_out_of_range_values_clamp_into_boundary_bins(self):
        edges = [0.0, 0.5, 1.0]
        reference = [0.25] * 50 + [0.75] * 50
        # a distribution far outside the edges must land in the tails, not
        # vanish — PSI sees the shift instead of reporting empty bins
        psi = population_stability_index(reference, [100.0] * 50, edges)
        assert psi > 0.5

    def test_degenerate_inputs_are_zero(self):
        assert population_stability_index([], [1.0], [0.0, 1.0]) == 0.0
        assert population_stability_index([1.0], [], [0.0, 1.0]) == 0.0
        assert population_stability_index([1.0], [1.0], [0.0]) == 0.0

    def test_symmetry(self):
        edges = [0.0, 0.25, 0.5, 0.75, 1.0]
        a = [0.1] * 60 + [0.6] * 40
        b = [0.1] * 20 + [0.6] * 80
        assert population_stability_index(a, b, edges) == pytest.approx(
            population_stability_index(b, a, edges)
        )


# ---------------------------------------------------------------------------
# drift detector
# ---------------------------------------------------------------------------


class TestDriftDetector:
    def test_not_ready_before_reference_and_min_window(self):
        detector = DriftDetector(bins=4, reference_size=10, window=10, min_window=5)
        for i in range(10):
            assert detector.psi() is None
            detector.observe(i / 10.0)
        # reference frozen; the window is still empty
        assert detector.psi() is None
        for i in range(4):
            detector.observe(i / 10.0)
        assert detector.psi() is None  # 4 < min_window
        detector.observe(0.5)
        assert detector.psi() is not None

    def test_same_distribution_stays_low_shift_detected(self):
        detector = DriftDetector(bins=5, reference_size=50, window=25, min_window=25)
        for i in range(50):
            detector.observe((i % 10) / 10.0)
        for i in range(25):
            detector.observe((i % 10) / 10.0)
        stable_psi = detector.psi()
        assert stable_psi is not None and stable_psi < 0.25
        for _ in range(25):  # the window slides fully onto the shifted regime
            detector.observe(0.95)
        assert detector.psi() > 1.0

    def test_constant_reference_does_not_crash(self):
        detector = DriftDetector(bins=4, reference_size=5, window=5, min_window=2)
        for _ in range(5):
            detector.observe(1.0)
        detector.observe(2.0)
        detector.observe(2.0)
        assert detector.psi() > 0.0

    def test_non_finite_observations_are_dropped(self):
        detector = DriftDetector(bins=4, reference_size=4, window=4, min_window=2)
        for value in (0.0, float("nan"), 1.0, float("inf"), 0.5, 0.25):
            detector.observe(value)
        assert detector.state()["reference"] == 4

    def test_rejects_degenerate_bins(self):
        with pytest.raises(ValueError):
            DriftDetector(bins=1)


# ---------------------------------------------------------------------------
# popularity descriptor
# ---------------------------------------------------------------------------


TRAIN = {
    "u0": [0, 1, 2],
    "u1": [0, 1],
    "u2": [0],
    "u3": [3],
}


class TestPopularityDescriptor:
    def test_matches_offline_surprisal_weights(self):
        from replay_tpu.metrics.beyond_accuracy import surprisal_weights

        descriptor = PopularityDescriptor.from_train(TRAIN, num_items=10)
        offline = surprisal_weights(TRAIN)
        for item, weight in offline.items():
            assert descriptor.surprisal_weight(item) == pytest.approx(float(weight))
        # unseen items weigh 1.0 in BOTH formulations
        assert descriptor.surprisal_weight(9) == 1.0

    def test_popularity_fractions_and_deciles(self):
        descriptor = PopularityDescriptor.from_train(TRAIN, num_items=10)
        assert descriptor.popularity(0) == pytest.approx(3 / 4)
        assert descriptor.popularity(1) == pytest.approx(2 / 4)
        assert descriptor.popularity(9) == 0.0
        # item 0 is the head; an unseen item is tail by definition
        assert descriptor.decile(0) == 0
        assert descriptor.decile(9) == 9

    def test_json_round_trip_is_exact(self):
        descriptor = PopularityDescriptor.from_train(TRAIN, num_items=10)
        clone = PopularityDescriptor.from_json(descriptor.to_json())
        assert clone.consumers == descriptor.consumers
        assert clone.n_users == descriptor.n_users
        assert clone.num_items == descriptor.num_items
        assert clone.train_items == descriptor.train_items
        for item in range(10):
            assert clone.surprisal_weight(item) == descriptor.surprisal_weight(item)
            assert clone.popularity(item) == descriptor.popularity(item)
            assert clone.decile(item) == descriptor.decile(item)


# ---------------------------------------------------------------------------
# prequential per-slate formulas (the metrics/ranking.py per-user math)
# ---------------------------------------------------------------------------


class TestPrequentialScores:
    def test_hit_at_rank_three(self):
        hit, rr, ndcg = prequential_scores([7, 8, 9, 10], [9], k=4)
        assert hit == 1.0
        assert rr == pytest.approx(1.0 / 3.0)
        # one relevant item at rank 3 (0-based 2): dcg = 1/log2(4), idcg = 1
        assert ndcg == pytest.approx((1.0 / math.log2(4.0)) / 1.0)

    def test_miss_is_all_zero(self):
        assert prequential_scores([1, 2, 3], [9], k=3) == (0.0, 0.0, 0.0)

    def test_k_truncates_the_slate(self):
        # the relevant item sits at rank 3 but k=2 cuts it off
        assert prequential_scores([1, 2, 9], [9], k=2) == (0.0, 0.0, 0.0)

    def test_idcg_truncates_ground_truth_at_k(self):
        # 3 relevant items, k=2, both slate slots hit: NDCG must be 1.0
        # (IDCG truncates the raw ground-truth length at k)
        hit, rr, ndcg = prequential_scores([5, 6], [5, 6, 7], k=2)
        assert (hit, rr) == (1.0, 1.0)
        assert ndcg == pytest.approx(1.0)

    def test_empty_inputs(self):
        assert prequential_scores([], [1], k=3) == (0.0, 0.0, 0.0)
        assert prequential_scores([1], [], k=3) == (0.0, 0.0, 0.0)


# ---------------------------------------------------------------------------
# SLO cookbook
# ---------------------------------------------------------------------------


class TestQualityRules:
    def test_cookbook_rules_are_well_formed(self):
        names = [rule.label for rule in QUALITY_SLOS]
        assert "drift_psi" in names
        assert "canary_online_hitrate" in names
        assert len(set(names)) == len(names)

    def test_canary_rules_only_for_passed_thresholds(self):
        assert canary_quality_rules() == ()
        rules = canary_quality_rules(
            min_online_hitrate=0.05, min_coverage=0.01, max_popularity=0.9
        )
        by_name = {rule.label: rule for rule in rules}
        assert set(by_name) == {
            "canary_online_hitrate",
            "canary_coverage",
            "canary_popularity_bias",
        }
        # every rule gates the CANDIDATE slice of the labeled gauges
        for rule in rules:
            assert rule.labels == {"role": "candidate"}
        assert by_name["canary_popularity_bias"].op == ">"
        assert by_name["canary_online_hitrate"].op == "<"

    def test_alarmed_series_exclude_coverage(self):
        # coverage PSI is one aggregate observation per emitted window —
        # dashboard signal, never the alarm (it would flap on traffic mix)
        assert "coverage" not in QualityMonitor.ALARMED_SERIES
        assert set(QualityMonitor.ALARMED_SERIES) == {
            "score",
            "popularity",
            "interactions",
        }


def test_obs_package_imports_without_jax():
    """`import replay_tpu.obs` must stay jax-free: the quality plane reaches
    the offline per-slate math through a lazy seam, not a module import."""
    import subprocess
    import sys

    code = (
        "import sys\n"
        "import replay_tpu.obs\n"
        "assert 'jax' not in sys.modules, 'obs import pulled jax'\n"
    )
    probe = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True, timeout=120
    )
    assert probe.returncode == 0, probe.stderr
