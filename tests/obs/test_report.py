"""Run-report CLI (obs.report): summaries, compare mode, exit codes.

Core tier, no jax: the CLI is import-light by contract. Fixtures mimic the
three artifact shapes it must digest — a fit run's ``events.jsonl`` (+
``trace.json``), a ``dryrun_multichip`` record, and a single-record bench
JSON — plus a malformed stream that must fail loudly (CI's "our artifacts
still parse" gate).
"""

import json
import os
import subprocess
import sys

import pytest

from replay_tpu.obs.report import compare_runs, load_events, main, summarize_run

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _write_fit_run(path, samples_per_sec=1000.0, retraces=0, goodput_train=0.8):
    os.makedirs(path, exist_ok=True)
    spans = {
        "data_wait": 0.05,
        "h2d": 0.02,
        "compile": 0.05,
        "train_step": goodput_train,
        "validation": 0.0,
        "checkpoint": 0.0,
        "recovery": 0.0,
    }
    goodput = {
        "wall_seconds": 1.0,
        "fractions": {**spans, "other": 1.0 - sum(spans.values())},
        "input_starvation": 0.05,
    }
    events = [
        {"event": "on_fit_start", "time": 1.0, "epoch": 0, "epochs": 1},
        *(
            {
                "event": "on_train_step", "time": 1.0 + i, "step": i + 1, "epoch": 0,
                "loss": 2.5 - 0.1 * i, "lr": 1e-3,
                "samples_per_sec": samples_per_sec, "steps_per_sec": samples_per_sec / 8,
                "step_seconds": 8 / samples_per_sec,
            }
            for i in range(3)
        ),
        {"event": "on_anomaly", "time": 4.5, "step": 3, "epoch": 0, "loss": None,
         "grad_norm": None, "consecutive_bad": 1},
        {"event": "on_epoch_end", "time": 5.0, "step": 3, "epoch": 0,
         "record": {"epoch": 0, "train_loss": 2.31}, "goodput": goodput},
        {"event": "on_fit_end", "time": 6.0, "step": 3,
         "telemetry": {"steps": 2.0, "elapsed_seconds": 0.5,
                       "steps_per_sec": samples_per_sec / 8,
                       "samples_per_sec": samples_per_sec},
         "compile": {"train_step": {"traces": 1 + retraces, "compile_seconds": 0.9}},
         "peak_memory_bytes": None, "history_len": 1, "bad_steps": 1,
         "goodput": goodput},
    ]
    with open(os.path.join(path, "events.jsonl"), "w") as fh:
        for event in events:
            fh.write(json.dumps(event) + "\n")
    return path


def _write_trace(path, names=("data_wait", "train_step")):
    payload = {
        "traceEvents": [
            {"name": name, "cat": "host", "ph": "X", "ts": 10.0 * i, "dur": 5.0,
             "pid": 1, "tid": 1}
            for i, name in enumerate(names)
        ],
        "displayTimeUnit": "ms",
    }
    with open(os.path.join(path, "trace.json"), "w") as fh:
        json.dump(payload, fh)


# --------------------------------------------------------------------------- #
# summaries
# --------------------------------------------------------------------------- #
def test_summarize_fit_run(tmp_path):
    run = _write_fit_run(str(tmp_path / "run"))
    _write_trace(run)
    summary = summarize_run(run)
    assert summary["kind"] == "fit"
    assert summary["samples_per_sec"] == pytest.approx(1000.0)
    assert summary["throughput_source"] == "telemetry"
    assert summary["final_train_loss"] == pytest.approx(2.31)
    assert summary["retraces"] == 0 and summary["bad_steps"] == 1
    assert summary["anomalies"] == 1
    assert summary["goodput"]["fractions"]["train_step"] == pytest.approx(0.8)
    assert summary["trace"]["train_step"]["count"] == 1


def test_report_cli_renders_fit_run(tmp_path, capsys):
    run = _write_fit_run(str(tmp_path / "run"))
    _write_trace(run)
    assert main([run]) == 0
    out = capsys.readouterr().out
    assert "throughput" in out and "1000.0 samples/sec" in out
    assert "goodput" in out and "input starvation" in out
    assert "trace.json" in out


def test_report_cli_renders_dryrun_record(tmp_path, capsys):
    run = tmp_path / "dry"
    run.mkdir()
    record = {
        "event": "dryrun_multichip", "time": 1.0, "backend": "cpu",
        "mesh": {"data": 4, "model": 2}, "losses": [3.9, 3.7], "psum": 28.0,
        "sp_ring_err": 3.6e-07,
        "compile": {"train_step": {"traces": 1, "compile_seconds": 0.77}},
        "peak_memory_bytes": None,
        "spans": {"train_step": {"count": 2, "seconds": 1.4, "self_seconds": 0.5}},
    }
    (run / "events.jsonl").write_text(json.dumps(record) + "\n")
    assert main([str(run)]) == 0
    out = capsys.readouterr().out
    assert "dryrun_multichip" in out and "mesh={'data': 4, 'model': 2}" in out
    assert "dryrun spans" in out


def test_report_cli_reads_bench_json(tmp_path, capsys):
    bench = tmp_path / "BENCH.json"
    bench.write_text(json.dumps({
        "metric": "sasrec_train_samples_per_sec", "value": 5668.0,
        "unit": "samples/sec", "vs_baseline": 1.0, "backend": "tpu",
        "mfu": 0.41, "compile_seconds": 12.0, "device_kind": "TPU v5e",
    }))
    assert main([str(bench)]) == 0
    out = capsys.readouterr().out
    assert "5668.0 samples/sec" in out and "[bench]" in out
    assert "MFU 0.410" in out


def test_report_json_flag_emits_json(tmp_path, capsys):
    run = _write_fit_run(str(tmp_path / "run"))
    assert main([run, "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["samples_per_sec"] == pytest.approx(1000.0)


# --------------------------------------------------------------------------- #
# failure modes: a report that cannot parse its own artifacts must exit non-zero
# --------------------------------------------------------------------------- #
def test_report_malformed_events_fails(tmp_path, capsys):
    run = tmp_path / "bad"
    run.mkdir()
    (run / "events.jsonl").write_text('{"event": "on_fit_start"}\nnot json{{{\n')
    assert main([str(run)]) == 1
    assert "cannot parse" in capsys.readouterr().err


def test_report_missing_run_fails(tmp_path, capsys):
    assert main([str(tmp_path / "nope")]) == 1


def test_report_invalid_trace_fails(tmp_path, capsys):
    run = _write_fit_run(str(tmp_path / "run"))
    with open(os.path.join(run, "trace.json"), "w") as fh:
        json.dump({"traceEvents": [{"ph": "X", "ts": 0}]}, fh)  # no name
    assert main([run]) == 1
    assert "name/ph/ts" in capsys.readouterr().err


def test_load_events_rejects_empty(tmp_path):
    path = tmp_path / "empty.jsonl"
    path.write_text("\n\n")
    with pytest.raises(ValueError, match="no records"):
        load_events(str(path))


# --------------------------------------------------------------------------- #
# compare mode
# --------------------------------------------------------------------------- #
def test_compare_flags_throughput_regression(tmp_path, capsys):
    baseline = _write_fit_run(str(tmp_path / "base"), samples_per_sec=1000.0)
    candidate = _write_fit_run(str(tmp_path / "cand"), samples_per_sec=700.0)
    rc = main([candidate, "--compare", baseline])
    captured = capsys.readouterr()
    assert rc != 0  # ≥20% throughput regression must fail the invocation
    assert "REGRESSION" in captured.err and "samples_per_sec" in captured.err


def test_compare_passes_within_threshold(tmp_path, capsys):
    baseline = _write_fit_run(str(tmp_path / "base"), samples_per_sec=1000.0)
    candidate = _write_fit_run(str(tmp_path / "cand"), samples_per_sec=950.0)
    assert main([candidate, "--compare", baseline]) == 0


def test_compare_threshold_is_tunable(tmp_path):
    baseline = _write_fit_run(str(tmp_path / "base"), samples_per_sec=1000.0)
    candidate = _write_fit_run(str(tmp_path / "cand"), samples_per_sec=700.0)
    assert main([candidate, "--compare", baseline, "--threshold", "0.5"]) == 0


def test_compare_improvement_passes(tmp_path):
    baseline = _write_fit_run(str(tmp_path / "base"), samples_per_sec=700.0)
    candidate = _write_fit_run(str(tmp_path / "cand"), samples_per_sec=1000.0)
    assert main([candidate, "--compare", baseline]) == 0


def test_compare_flags_new_retraces(tmp_path, capsys):
    baseline = _write_fit_run(str(tmp_path / "base"))
    candidate = _write_fit_run(str(tmp_path / "cand"), retraces=3)
    rc = main([candidate, "--compare", baseline])
    assert rc != 0
    assert "retraces increased" in capsys.readouterr().err


def test_compare_against_bench_json(tmp_path):
    """The --compare operand may be a bench record, not a run directory."""
    candidate = _write_fit_run(str(tmp_path / "cand"), samples_per_sec=700.0)
    bench = tmp_path / "BENCH.json"
    bench.write_text(json.dumps({
        "metric": "sasrec_train_samples_per_sec_cpu_fallback", "value": 1000.0,
        "unit": "samples/sec", "vs_baseline": 0.18, "backend": "cpu",
    }))
    assert main([candidate, "--compare", str(bench)]) != 0


def test_compare_runs_api_reports_goodput_shift(tmp_path):
    baseline = summarize_run(_write_fit_run(str(tmp_path / "base"), goodput_train=0.8))
    candidate = summarize_run(_write_fit_run(str(tmp_path / "cand"), goodput_train=0.5))
    lines, regressions = compare_runs(candidate, baseline)
    assert any("goodput/train_step" in line for line in lines)
    assert regressions == []  # goodput shifts inform; throughput/mfu/retraces gate


# --------------------------------------------------------------------------- #
# module entrypoint
# --------------------------------------------------------------------------- #
def test_python_dash_m_entrypoint(tmp_path):
    run = _write_fit_run(str(tmp_path / "run"))
    proc = subprocess.run(
        [sys.executable, "-m", "replay_tpu.obs.report", run],
        capture_output=True, text=True, timeout=120, cwd=REPO, check=False,
    )
    assert proc.returncode == 0, proc.stderr
    assert "Run report" in proc.stdout


# --------------------------------------------------------------------------- #
# fit-loop bench fields (scan-chunked fit, docs/performance.md "Closing the
# dispatch gap") + h2d-overlap surfacing
# --------------------------------------------------------------------------- #
def _bench_record(**extra):
    return {
        "metric": "sasrec_train_samples_per_sec", "value": 5668.0,
        "unit": "samples/sec", "vs_baseline": 1.0, "backend": "tpu",
        "step_ms": 4.1, "dispatch_step_ms": 10.5, "scan_k": 32,
        **extra,
    }


def _fit_fields(samples=5000.0, chunk=32, feed=True):
    return {
        "fit_samples_per_sec": samples, "fit_step_ms": 5.0,
        "fit_scan_chunk": chunk, "fit_device_feed": feed,
        "dispatch_gap_closed": 0.86,
    }


def test_bench_fit_loop_fields_summarize_and_render(tmp_path, capsys):
    bench = tmp_path / "BENCH.json"
    bench.write_text(json.dumps(_bench_record(**_fit_fields())))
    summary = summarize_run(str(bench))
    assert summary["fit_samples_per_sec"] == pytest.approx(5000.0)
    assert summary["bench"]["fit_scan_chunk"] == 32
    assert summary["bench"]["fit_device_feed"] is True
    assert main([str(bench)]) == 0
    out = capsys.readouterr().out
    assert "fit loop: 5000.0 samples/sec" in out
    assert "scan_chunk=32" in out and "device_feed=True" in out
    assert "dispatch gap closed 86%" in out


def test_compare_gates_on_end_to_end_fit_throughput(tmp_path, capsys):
    cand = tmp_path / "cand.json"
    base = tmp_path / "base.json"
    # microbench value holds; only the PRODUCTION fit loop regressed
    cand.write_text(json.dumps(_bench_record(**_fit_fields(samples=2000.0))))
    base.write_text(json.dumps(_bench_record(**_fit_fields(samples=5000.0))))
    assert main([str(cand), "--compare", str(base)]) == 2
    err = capsys.readouterr().err
    assert "fit_samples_per_sec" in err


def test_compare_skips_fit_gate_across_variants(tmp_path, capsys):
    cand = tmp_path / "cand.json"
    base = tmp_path / "base.json"
    # a different chunk size is a VARIANT run: its fit number must neither
    # gate nor masquerade as the baseline
    cand.write_text(json.dumps(_bench_record(**_fit_fields(samples=2000.0, chunk=4))))
    base.write_text(json.dumps(_bench_record(**_fit_fields(samples=5000.0, chunk=32))))
    assert main([str(cand), "--compare", str(base)]) == 0
    out = capsys.readouterr().out
    assert "variant flags differ" in out


def test_h2d_overlap_surfaced_from_trace(tmp_path, capsys):
    run = _write_fit_run(str(tmp_path / "run"))
    _write_trace(run, names=("data_wait", "train_step", "h2d", "h2d"))
    summary = summarize_run(run)
    assert summary["h2d_seconds"] == pytest.approx(2 * 5.0 / 1e6)
    assert main([run]) == 0
    out = capsys.readouterr().out
    assert "h2d:" in out and "overlapped" in out and "input starvation" in out


# --------------------------------------------------------------------------- #
# serving summaries (replay_tpu.serve / bench_serve.py)
# --------------------------------------------------------------------------- #
def _write_serve_run(path, qps=250.0, p99_ms=4.5, fill=0.8, hit_rate=0.9,
                     with_bench_record=True):
    os.makedirs(path, exist_ok=True)
    serve_goodput = {
        "wall_seconds": 2.0,
        "fractions": {"queue_wait": 0.5, "batch_build": 0.05, "score": 0.2,
                      "retrieve": 0.1, "rerank": 0.1, "other": 0.05},
        "input_starvation": None,
    }
    events = [
        {"event": "on_serve_start", "time": 1.0, "mode": "retrieval",
         "length_buckets": [8], "batch_buckets": [1, 4], "max_wait_ms": 2.0,
         "cache_capacity": 100},
        {"event": "on_serve_batch", "time": 1.1, "lane": "encode:L=8", "rows": 3,
         "bucket": 4, "fill": 0.75, "queue_wait_ms_max": 2.2},
        {"event": "on_serve_batch", "time": 1.2, "lane": "hit", "rows": 4,
         "bucket": 4, "fill": 1.0, "queue_wait_ms_max": 1.1},
        {"event": "on_serve_end", "time": 3.0, "mode": "retrieval", "requests": 7,
         "answered": 7, "errors": 0, "cache_hit_rate": hit_rate,
         "pure_hit_rate": 0.5, "batch_fill_ratio": fill,
         "queue_wait_ms_mean": 1.4, "queue_wait_ms_max": 2.2,
         "served_from": {"hit": 4, "advance": 1, "cold": 2},
         "goodput": serve_goodput},
    ]
    if with_bench_record:
        events.append(
            {"metric": "serve_qps", "value": qps, "unit": "req/s", "qps": qps,
             "p50_ms": 1.2, "p95_ms": 3.1, "p99_ms": p99_ms,
             "batch_fill_ratio": fill, "cache_hit_rate": hit_rate,
             "closed_loop_qps": qps * 1.1, "mode": "retrieval", "backend": "cpu"}
        )
    with open(os.path.join(path, "events.jsonl"), "w") as fh:
        for event in events:
            fh.write(json.dumps(event) + "\n")
    return path


def test_serve_run_summarizes_and_renders(tmp_path, capsys):
    run = _write_serve_run(str(tmp_path / "serve"))
    summary = summarize_run(run)
    assert summary["serve"]["qps"] == 250.0
    assert summary["serve"]["p99_ms"] == 4.5
    assert summary["serve"]["requests"] == 7
    assert summary["serve"]["batches"] == 2
    assert summary["serve"]["cache_hit_rate"] == 0.9
    # the serve goodput is picked up by the generic goodput scan
    assert summary["goodput"]["fractions"]["queue_wait"] == 0.5
    assert main([run]) == 0
    out = capsys.readouterr().out
    assert "serving [retrieval]:" in out
    assert "250.0 qps" in out
    assert "latency p50/p95/p99" in out
    assert "batch fill 80%" in out
    assert "cache hits 90%" in out
    assert "queue_wait 50.0%" in out  # serve-span fractions render too
    assert "input starvation" not in out  # meaningless for a serve run


def test_serve_events_only_still_renders_section(tmp_path, capsys):
    run = _write_serve_run(str(tmp_path / "serve"), with_bench_record=False)
    summary = summarize_run(run)
    assert summary["kind"] == "serve"
    assert "qps" not in summary["serve"]  # no bench record in this run
    assert summary["serve"]["requests"] == 7
    assert main([run]) == 0
    assert "serving" in capsys.readouterr().out


def test_compare_flags_serve_qps_regression(tmp_path, capsys):
    baseline = _write_serve_run(str(tmp_path / "base"), qps=250.0)
    candidate = _write_serve_run(str(tmp_path / "cand"), qps=150.0)
    assert main([candidate, "--compare", baseline]) == 2
    assert "serve_qps regressed" in capsys.readouterr().err


def test_compare_flags_serve_p99_regression_latency_is_lower_better(tmp_path, capsys):
    baseline = _write_serve_run(str(tmp_path / "base"), p99_ms=4.0)
    candidate = _write_serve_run(str(tmp_path / "cand"), p99_ms=9.0)
    assert main([candidate, "--compare", baseline]) == 2
    assert "serve_p99_ms regressed" in capsys.readouterr().err


def test_compare_serve_improvement_passes(tmp_path):
    baseline = _write_serve_run(str(tmp_path / "base"), qps=200.0, p99_ms=5.0)
    candidate = _write_serve_run(str(tmp_path / "cand"), qps=260.0, p99_ms=3.0)
    assert main([candidate, "--compare", baseline]) == 0


def test_compare_serve_within_threshold_passes(tmp_path):
    baseline = _write_serve_run(str(tmp_path / "base"), qps=250.0, p99_ms=4.0)
    candidate = _write_serve_run(str(tmp_path / "cand"), qps=240.0, p99_ms=4.3)
    assert main([candidate, "--compare", baseline]) == 0


# --------------------------------------------------------------------------- #
# serving resilience: shed / deadline-miss / error rates, breaker, chaos
# --------------------------------------------------------------------------- #
def _write_resilient_serve_run(path, error_rate=0.0, deadline_miss_rate=0.0,
                               shed_rate=0.0, overload=True, chaos=True):
    os.makedirs(path, exist_ok=True)
    events = [
        {"event": "on_serve_start", "time": 1.0, "mode": "retrieval",
         "max_queue_depth": 64, "default_deadline_ms": 250.0, "fallback": True},
        {"event": "on_shed", "time": 1.2, "lane": "encode:L=8", "depth": 64,
         "max_depth": 64, "retry_after_s": 0.05, "count": 17},
        {"event": "on_breaker", "time": 1.3, "from": "closed", "to": "open",
         "consecutive_failures": 5, "opens": 1},
        {"event": "on_degrade", "time": 1.35, "to": "cache_only",
         "reason": "breaker_open", "count": 3},
        {"event": "on_breaker", "time": 1.6, "from": "open", "to": "half_open",
         "consecutive_failures": 5, "opens": 1},
        {"event": "on_breaker", "time": 1.7, "from": "half_open", "to": "closed",
         "consecutive_failures": 0, "opens": 1},
        {"event": "on_serve_end", "time": 3.0, "mode": "retrieval",
         "requests": 100, "answered": 80, "errors": int(error_rate * 100),
         "cache_hit_rate": 0.9, "batch_fill_ratio": 0.8,
         "served_from": {"hit": 60, "advance": 10, "cold": 10},
         "served_by": {"primary": 70, "cache_only": 8, "fallback": 2},
         "shed": int(shed_rate * 100), "deadline_misses": 4, "cancelled": 1,
         "circuit_refusals": 2, "degraded": 10,
         "shed_rate": shed_rate, "deadline_miss_rate": deadline_miss_rate,
         "error_rate": error_rate},
    ]
    record = {
        "metric": "serve_qps", "value": 200.0, "unit": "req/s", "qps": 200.0,
        "p50_ms": 1.2, "p95_ms": 3.1, "p99_ms": 4.5, "batch_fill_ratio": 0.8,
        "cache_hit_rate": 0.9, "mode": "retrieval", "backend": "cpu",
        "serve_shed_rate": shed_rate,
        "serve_deadline_miss_rate": deadline_miss_rate,
        "serve_error_rate": error_rate,
        "served_by": {"primary": 70, "cache_only": 8, "fallback": 2},
        "breaker": {"state": "closed", "opens": 1, "closes": 1},
        "hung_requests": 0,
    }
    if overload:
        record["overload"] = {
            "rate": 800.0, "p99_ms": 40.0, "shed_rate": shed_rate,
            "deadline_miss_rate": deadline_miss_rate, "hung_requests": 0,
        }
    if chaos:
        record["chaos"] = {
            "injected_engine_errors": 5, "breaker_opens": 1,
            "breaker_state_final": "closed", "recovered": True,
            "hung_requests": 0, "storm_deadline_missed": 12,
        }
    events.append(record)
    with open(os.path.join(path, "events.jsonl"), "w") as fh:
        for event in events:
            fh.write(json.dumps(event) + "\n")
    return path


def test_serve_resilience_summarizes_and_renders(tmp_path, capsys):
    run = _write_resilient_serve_run(
        str(tmp_path / "serve"), error_rate=0.01, deadline_miss_rate=0.04,
        shed_rate=0.2,
    )
    summary = summarize_run(run)
    serve = summary["serve"]
    assert serve["shed_rate"] == 0.2
    assert serve["deadline_miss_rate"] == 0.04
    assert serve["error_rate"] == 0.01
    assert serve["served_by"] == {"primary": 70, "cache_only": 8, "fallback": 2}
    assert serve["breaker"]["opens"] == 1
    assert serve["shed_events"] == 1
    assert serve["breaker_events"] == 3
    assert serve["degrade_events"] == 1
    assert serve["overload"] is True
    assert serve["overload_p99_ms"] == 40.0
    assert serve["chaos"]["breaker_opens"] == 1
    assert main([run]) == 0
    out = capsys.readouterr().out
    assert "serving resilience:" in out
    assert "shed rate 20.00%" in out
    assert "deadline-miss rate 4.00%" in out
    assert "error rate 1.00%" in out
    assert "degraded 10 (cache_only:8/fallback:2)" in out
    assert "breaker closed (1 open(s))" in out
    assert "hung 0" in out
    assert "serving overload:" in out
    assert "serving chaos:" in out
    assert "5 injected error(s)" in out


def test_compare_gates_on_serve_error_rate_rise(tmp_path, capsys):
    baseline = _write_resilient_serve_run(str(tmp_path / "base"), error_rate=0.0)
    candidate = _write_resilient_serve_run(str(tmp_path / "cand"), error_rate=0.05)
    # the absolute floor matters: relative-only would never fire on 0 -> 0.05
    assert main([candidate, "--compare", baseline]) == 2
    assert "serve_error_rate regressed" in capsys.readouterr().err


def test_compare_gates_on_serve_deadline_miss_rate_rise(tmp_path, capsys):
    baseline = _write_resilient_serve_run(
        str(tmp_path / "base"), deadline_miss_rate=0.01
    )
    candidate = _write_resilient_serve_run(
        str(tmp_path / "cand"), deadline_miss_rate=0.10
    )
    assert main([candidate, "--compare", baseline]) == 2
    assert "serve_deadline_miss_rate regressed" in capsys.readouterr().err


def test_compare_gates_shed_rate_only_when_both_ran_overload(tmp_path, capsys):
    baseline = _write_resilient_serve_run(
        str(tmp_path / "base"), shed_rate=0.1, overload=True
    )
    worse = _write_resilient_serve_run(
        str(tmp_path / "cand"), shed_rate=0.5, overload=True
    )
    assert main([worse, "--compare", baseline]) == 2
    assert "serve_shed_rate regressed" in capsys.readouterr().err
    # candidate without the overload phase: surfaced, NOT gated
    no_overload = _write_resilient_serve_run(
        str(tmp_path / "cand2"), shed_rate=0.5, overload=False
    )
    assert main([no_overload, "--compare", baseline]) == 0
    assert "not gated: both sides must run overload" in capsys.readouterr().out


def test_compare_skips_rate_gates_when_phases_mismatch(tmp_path, capsys):
    """The run-wide rates are dominated by the opt-in phases: a chaos run's
    injected errors (or an overload run's designed deadline misses) must not
    gate against a baseline that never ran the phase."""
    baseline = _write_resilient_serve_run(
        str(tmp_path / "base"), error_rate=0.0, deadline_miss_rate=0.0,
        overload=False, chaos=False,
    )
    candidate = _write_resilient_serve_run(
        str(tmp_path / "cand"), error_rate=0.03, deadline_miss_rate=0.08,
        overload=True, chaos=True,
    )
    assert main([candidate, "--compare", baseline]) == 0
    out = capsys.readouterr().out
    assert "serve_error_rate" in out and "chaos phase ran on one side only" in out
    assert "overload phase ran on one side only" in out


def test_compare_resilience_rates_within_floor_pass(tmp_path):
    baseline = _write_resilient_serve_run(
        str(tmp_path / "base"), error_rate=0.0, deadline_miss_rate=0.01,
        shed_rate=0.1,
    )
    candidate = _write_resilient_serve_run(
        str(tmp_path / "cand"), error_rate=0.004, deadline_miss_rate=0.012,
        shed_rate=0.1,
    )
    assert main([candidate, "--compare", baseline]) == 0


def test_compare_resilience_improvement_passes(tmp_path):
    baseline = _write_resilient_serve_run(
        str(tmp_path / "base"), error_rate=0.05, deadline_miss_rate=0.1,
        shed_rate=0.4,
    )
    candidate = _write_resilient_serve_run(
        str(tmp_path / "cand"), error_rate=0.0, deadline_miss_rate=0.0,
        shed_rate=0.1,
    )
    assert main([candidate, "--compare", baseline]) == 0


# --------------------------------------------------------------------------- #
# resource gates: peak memory + compile time (lower-better), bench-row skips
# --------------------------------------------------------------------------- #
def _write_resource_run(path, peak_memory=1_000_000, compile_seconds=2.0):
    os.makedirs(path, exist_ok=True)
    events = [
        {"event": "on_fit_start", "time": 1.0, "epoch": 0, "epochs": 1},
        {"event": "on_train_step", "time": 2.0, "step": 1, "epoch": 0, "loss": 1.0,
         "samples_per_sec": 500.0, "steps_per_sec": 62.5},
        {"event": "on_fit_end", "time": 3.0, "step": 1,
         "telemetry": {"steps": 1.0, "elapsed_seconds": 0.1,
                       "steps_per_sec": 62.5, "samples_per_sec": 500.0},
         "compile": {"train_step": {"traces": 1, "compile_seconds": compile_seconds}},
         "peak_memory_bytes": peak_memory, "history_len": 1, "bad_steps": 0},
    ]
    with open(os.path.join(path, "events.jsonl"), "w") as fh:
        for event in events:
            fh.write(json.dumps(event) + "\n")
    return path


def test_compare_gates_on_peak_memory_growth(tmp_path, capsys):
    baseline = _write_resource_run(str(tmp_path / "base"), peak_memory=1_000_000)
    candidate = _write_resource_run(str(tmp_path / "cand"), peak_memory=1_300_000)
    assert main([candidate, "--compare", baseline]) == 2
    assert "peak_memory_bytes regressed" in capsys.readouterr().err


def test_compare_peak_memory_within_threshold_passes(tmp_path):
    baseline = _write_resource_run(str(tmp_path / "base"), peak_memory=1_000_000)
    candidate = _write_resource_run(str(tmp_path / "cand"), peak_memory=1_050_000)
    assert main([candidate, "--compare", baseline]) == 0


def test_compare_memory_threshold_is_tunable(tmp_path):
    baseline = _write_resource_run(str(tmp_path / "base"), peak_memory=1_000_000)
    candidate = _write_resource_run(str(tmp_path / "cand"), peak_memory=1_300_000)
    assert main([candidate, "--compare", baseline, "--memory-threshold", "0.5"]) == 0


def test_compare_gates_on_compile_time_growth(tmp_path, capsys):
    baseline = _write_resource_run(str(tmp_path / "base"), compile_seconds=2.0)
    # compile gate defaults to max(threshold, 0.5): +60% trips it
    candidate = _write_resource_run(str(tmp_path / "cand"), compile_seconds=3.2)
    assert main([candidate, "--compare", baseline]) == 2
    assert "compile_seconds regressed" in capsys.readouterr().err


def test_compare_compile_noise_within_default_threshold_passes(tmp_path):
    baseline = _write_resource_run(str(tmp_path / "base"), compile_seconds=2.0)
    candidate = _write_resource_run(str(tmp_path / "cand"), compile_seconds=2.8)
    assert main([candidate, "--compare", baseline]) == 0


def test_compare_memory_shrink_and_missing_are_fine(tmp_path):
    baseline = _write_resource_run(str(tmp_path / "base"), peak_memory=2_000_000)
    candidate = _write_resource_run(str(tmp_path / "cand"), peak_memory=1_000_000)
    assert main([candidate, "--compare", baseline]) == 0
    # null peaks (CPU fits) stay "not comparable", never a regression
    base2 = _write_resource_run(str(tmp_path / "b2"), peak_memory=None)
    cand2 = _write_resource_run(str(tmp_path / "c2"), peak_memory=None)
    assert main([cand2, "--compare", base2]) == 0


def _write_suite_run(path, rows):
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, "events.jsonl"), "w") as fh:
        for row in rows:
            fh.write(json.dumps({"event": "bench_row", "time": 1.0, **row}) + "\n")
    return path


def test_compare_skips_error_bench_rows(tmp_path, capsys):
    """The by-design 1M plain-CE OOM row must not trip the gate — on either
    side — while measured rows still gate per name."""
    baseline = _write_suite_run(str(tmp_path / "base"), [
        {"row": "scale_1m_ce", "error": "RESOURCE_EXHAUSTED: oom"},
        {"row": "scale_1m_fused", "samples_per_sec": 1000.0},
    ])
    candidate = _write_suite_run(str(tmp_path / "cand"), [
        {"row": "scale_1m_ce", "error": "RESOURCE_EXHAUSTED: oom"},
        {"row": "scale_1m_fused", "samples_per_sec": 980.0},
    ])
    assert main([candidate, "--compare", baseline]) == 0
    out = capsys.readouterr().out
    assert "skipped (baseline error row)" in out
    assert "bench_row[scale_1m_fused].samples_per_sec" in out


def test_compare_flags_bench_row_regression_and_new_errors(tmp_path, capsys):
    baseline = _write_suite_run(str(tmp_path / "base"), [
        {"row": "scale_1m_fused", "samples_per_sec": 1000.0},
        {"row": "scale_27k_tp", "samples_per_sec": 500.0},
    ])
    candidate = _write_suite_run(str(tmp_path / "cand"), [
        {"row": "scale_1m_fused", "samples_per_sec": 500.0},  # -50%: regression
        {"row": "scale_27k_tp", "error": "XlaRuntimeError: boom"},  # NEW error
    ])
    assert main([candidate, "--compare", baseline]) == 2
    err = capsys.readouterr().err
    assert "bench_row[scale_1m_fused].samples_per_sec regressed" in err
    assert "errored in the candidate" in err


# --------------------------------------------------------------------------- #
# device attribution + roofline sections
# --------------------------------------------------------------------------- #
def _write_profiled_run(path):
    os.makedirs(path, exist_ok=True)
    device_time = {
        "capture": "profile/plugins/profile/x/host.trace.json.gz",
        "total_device_seconds": 0.010,
        "modules": {"jit_train_step": 0.010},
        "scopes": {
            "encoder": {"seconds": 0.006, "fraction": 0.6},
            "loss": {"seconds": 0.002, "fraction": 0.2},
        },
        "attributed_seconds": 0.008,
        "unattributed_seconds": 0.002,
    }
    roofline = {
        "train_step": {
            "roofline": {
                "flops": 1e9, "bytes_accessed": 1e8,
                "arithmetic_intensity": 10.0, "critical_intensity": 240.5,
                "bound": "memory", "ceiling_tflops": 8.19,
                "peak_tflops": 197.0, "peak_hbm_gbps": 819.0,
                "min_step_seconds": 1.2e-4, "peak_assumed": "v5e",
            },
            "hbm_peak_bytes": 50_000_000, "collective_bytes": 1_000_000,
        }
    }
    events = [
        {"event": "on_fit_start", "time": 1.0, "epoch": 0, "epochs": 1},
        {"event": "on_fit_end", "time": 2.0, "step": 3,
         "telemetry": {"steps": 3.0, "elapsed_seconds": 0.3,
                       "steps_per_sec": 10.0, "samples_per_sec": 80.0},
         "compile": {"train_step": {"traces": 1, "compile_seconds": 1.0}},
         "peak_memory_bytes": None, "history_len": 1, "bad_steps": 0,
         "device_time": device_time, "roofline": roofline},
    ]
    with open(os.path.join(path, "events.jsonl"), "w") as fh:
        for event in events:
            fh.write(json.dumps(event) + "\n")
    return path


def test_device_attribution_and_roofline_sections_render(tmp_path, capsys):
    run = _write_profiled_run(str(tmp_path / "run"))
    summary = summarize_run(run)
    assert summary["device_time"]["scopes"]["encoder"]["fraction"] == pytest.approx(0.6)
    assert summary["roofline"]["train_step"]["roofline"]["bound"] == "memory"
    assert main([run]) == 0
    out = capsys.readouterr().out
    assert "device attribution" in out
    assert "encoder 60.0%" in out and "unattributed 20.0%" in out
    assert "roofline:" in out
    assert "memory-bound (assumed v5e peaks)" in out
    assert "ceiling 8.19 TFLOP/s" in out
    assert "peak HBM 50.0 MB" in out


def test_bench_rows_render_roofline_fields(tmp_path, capsys):
    run = _write_suite_run(str(tmp_path / "suite"), [
        {"row": "scale_27k_fused", "samples_per_sec": 900.0, "step_ms": 2.0,
         "num_items": 27278, "loss": "CEFused", "roofline_bound": "memory",
         "of_roofline_ceiling": 0.42, "hbm_peak_bytes": 64_000_000,
         "collective_bytes": 2_000_000},
    ])
    assert main([run]) == 0
    out = capsys.readouterr().out
    assert "memory-bound (42% of ceiling)" in out
    assert "HBM 64.0 MB" in out and "coll 2.00 MB" in out


# --------------------------------------------------------------------------- #
# the precision ladder (prec_* bench rows + serving quant block)
# --------------------------------------------------------------------------- #
def test_prec_rows_gate_hbm_lower_better(tmp_path, capsys):
    """A prec_* row whose hbm_peak_bytes grew past --memory-threshold fails
    even at held throughput — the precision regression that only moves bytes;
    non-prec rows keep their throughput-only gate."""
    baseline = _write_suite_run(str(tmp_path / "base"), [
        {"row": "prec_bf16_fused", "samples_per_sec": 900.0,
         "hbm_peak_bytes": 50_000_000, "precision": "bf16"},
        {"row": "scale_27k_fused", "samples_per_sec": 900.0,
         "hbm_peak_bytes": 50_000_000},
    ])
    candidate = _write_suite_run(str(tmp_path / "cand"), [
        {"row": "prec_bf16_fused", "samples_per_sec": 910.0,
         "hbm_peak_bytes": 80_000_000, "precision": "bf16"},
        # same 60% HBM growth on a NON-prec row: surfaced, not gated
        {"row": "scale_27k_fused", "samples_per_sec": 910.0,
         "hbm_peak_bytes": 80_000_000},
    ])
    rc = main([candidate, "--compare", baseline])
    err = capsys.readouterr().err
    assert rc != 0
    assert "bench_row[prec_bf16_fused].hbm_peak_bytes" in err
    assert "scale_27k_fused].hbm_peak_bytes" not in err


def test_prec_rows_hbm_gate_respects_memory_threshold(tmp_path):
    baseline = _write_suite_run(str(tmp_path / "base"), [
        {"row": "prec_bf16_ce", "samples_per_sec": 900.0,
         "hbm_peak_bytes": 50_000_000},
    ])
    candidate = _write_suite_run(str(tmp_path / "cand"), [
        {"row": "prec_bf16_ce", "samples_per_sec": 900.0,
         "hbm_peak_bytes": 56_000_000},
    ])
    # 12% growth: fails the default 10% memory threshold, passes at 20%
    assert main([candidate, "--compare", baseline]) != 0
    assert main([candidate, "--compare", baseline, "--memory-threshold", "0.2"]) == 0


def test_precision_pairs_summarize_and_render(tmp_path, capsys):
    run = _write_suite_run(str(tmp_path / "suite"), [
        {"row": "prec_f32_fused", "samples_per_sec": 900.0, "step_ms": 4.0,
         "precision": "f32", "hbm_peak_bytes": 100_000_000, "backend": "tpu"},
        {"row": "prec_bf16_fused", "samples_per_sec": 1200.0, "step_ms": 3.0,
         "precision": "bf16", "hbm_peak_bytes": 60_000_000, "backend": "tpu"},
    ])
    summary = summarize_run(run)
    pair = summary["precision_pairs"]["fused"]
    assert pair["f32_hbm_peak_bytes"] == 100_000_000
    assert pair["bf16_hbm_peak_bytes"] == 60_000_000
    assert pair["hbm_saved_fraction"] == pytest.approx(0.4)
    assert main([run]) == 0
    out = capsys.readouterr().out
    assert "precision ladder [fused]" in out
    assert "HBM 100.0→60.0 MB" in out and "+40.0% saved" in out
    assert "prec bf16" in out  # the per-row precision tag renders too


def _write_quant_serve_run(path, recall=0.996, topk_match=1.0):
    os.makedirs(path, exist_ok=True)
    record = {
        "metric": "serve_qps", "value": 250.0, "unit": "req/s", "qps": 250.0,
        "p50_ms": 2.0, "p95_ms": 3.5, "p99_ms": 4.5, "batch_fill_ratio": 0.8,
        "cache_hit_rate": 0.9, "requests": 512, "mode": "retrieval",
        "quant": {
            "candidates": 100, "top_k": 10,
            "recall_at_candidates": recall, "topk_match_rate": topk_match,
            "f32_rank_ms": 0.9, "int8_rank_ms": 0.7,
            "int8_table_bytes": 4000, "f32_table_bytes": 12800,
            "bytes_ratio": 0.3125,
        },
    }
    with open(os.path.join(path, "events.jsonl"), "w") as fh:
        fh.write(json.dumps(record) + "\n")
    return path


def test_serve_quant_summarizes_and_renders(tmp_path, capsys):
    run = _write_quant_serve_run(str(tmp_path / "serve"))
    summary = summarize_run(run)
    quant = summary["serve"]["quant"]
    assert quant["recall_at_candidates"] == pytest.approx(0.996)
    assert quant["bytes_ratio"] == pytest.approx(0.3125)
    assert main([run]) == 0
    out = capsys.readouterr().out
    assert "serving quant (int8 retrieval)" in out
    assert "int8 recall@100 0.9960" in out and "table bytes" in out


def test_serve_quant_recall_gates_higher_better(tmp_path, capsys):
    baseline = _write_quant_serve_run(str(tmp_path / "base"), recall=0.996)
    candidate = _write_quant_serve_run(str(tmp_path / "cand"), recall=0.95)
    rc = main([candidate, "--compare", baseline])
    assert rc != 0
    assert "serve_quant_recall_at_candidates" in capsys.readouterr().err
    # within the absolute 0.005 band: measurement noise, not a regression
    near = _write_quant_serve_run(str(tmp_path / "near"), recall=0.993)
    assert main([near, "--compare", baseline]) == 0


def test_serve_quant_topk_match_gates(tmp_path, capsys):
    baseline = _write_quant_serve_run(str(tmp_path / "base"), topk_match=1.0)
    candidate = _write_quant_serve_run(str(tmp_path / "cand"), topk_match=0.9)
    assert main([candidate, "--compare", baseline]) != 0
    assert "serve_quant_topk_match_rate" in capsys.readouterr().err


def _write_ann_serve_run(path, recall=0.995, agreement=1.0, ivf_qps=2500.0):
    os.makedirs(path, exist_ok=True)
    record = {
        "metric": "serve_qps", "value": 250.0, "unit": "req/s", "qps": 250.0,
        "p50_ms": 2.0, "p95_ms": 3.5, "p99_ms": 4.5, "batch_fill_ratio": 0.8,
        "cache_hit_rate": 0.9, "requests": 512, "mode": "retrieval",
        "ann": {
            "items": 10_000_000, "dim": 64, "nlist": 4096, "nprobe": 16,
            "cmax": 4688, "scanned_fraction": 0.0075,
            "recall_at_100": recall, "topk_agreement": agreement,
            "brute_qps": 180.0, "ivf_qps": ivf_qps,
            "speedup": ivf_qps / 180.0, "build_s": 310.0,
            "recall_at_100_int8": 0.994, "recall_at_100_pq": 0.993,
            "index_total_bytes": 2_900_000_000,
        },
    }
    with open(os.path.join(path, "events.jsonl"), "w") as fh:
        fh.write(json.dumps(record) + "\n")
    return path


def test_serve_ann_summarizes_and_renders(tmp_path, capsys):
    run = _write_ann_serve_run(str(tmp_path / "serve"))
    summary = summarize_run(run)
    ann = summary["serve"]["ann"]
    assert ann["recall_at_100"] == pytest.approx(0.995)
    assert ann["nlist"] == 4096 and ann["nprobe"] == 16
    assert main([run]) == 0
    out = capsys.readouterr().out
    assert "serving ann (ivf retrieval)" in out
    assert "recall@100 0.9950" in out
    assert "vs IVF" in out  # the brute-vs-IVF speedup line


def test_serve_ann_recall_gates_higher_better(tmp_path, capsys):
    baseline = _write_ann_serve_run(str(tmp_path / "base"), recall=0.995)
    candidate = _write_ann_serve_run(str(tmp_path / "cand"), recall=0.95)
    assert main([candidate, "--compare", baseline]) != 0
    assert "serve_ann_recall_at_100" in capsys.readouterr().err
    # within the absolute 0.005 band: measurement noise, not a regression
    near = _write_ann_serve_run(str(tmp_path / "near"), recall=0.992)
    assert main([near, "--compare", baseline]) == 0


def test_serve_ann_agreement_and_qps_gate(tmp_path, capsys):
    baseline = _write_ann_serve_run(str(tmp_path / "base"), agreement=1.0)
    candidate = _write_ann_serve_run(str(tmp_path / "cand"), agreement=0.9)
    assert main([candidate, "--compare", baseline]) != 0
    assert "serve_ann_topk_agreement" in capsys.readouterr().err
    slow = _write_ann_serve_run(str(tmp_path / "slow"), ivf_qps=1000.0)
    fast = _write_ann_serve_run(str(tmp_path / "fast"), ivf_qps=2500.0)
    assert main([slow, "--compare", fast]) != 0
    assert "serve_ann_qps" in capsys.readouterr().err


# --------------------------------------------------------------------------- #
# promotion: canary lifecycle summary, rollback + swap_p99_ms compare gates
# --------------------------------------------------------------------------- #
def _write_promotion_run(path, rollbacks=0, promotions=1, swap_p99_ms=None,
                         qps=250.0):
    os.makedirs(path, exist_ok=True)
    events = [
        {"event": "on_serve_start", "time": 1.0, "mode": "full",
         "length_buckets": [8], "batch_buckets": [1, 4], "max_wait_ms": 2.0,
         "cache_capacity": 100},
        {"event": "on_publish", "time": 1.1, "generation": 1,
         "label": "candidate-a", "recompiled": False, "recompile_reason": None},
        {"event": "on_canary_start", "time": 1.2, "generation": 1, "fraction": 0.1},
        {"event": "on_canary_eval", "time": 1.3, "stage": "canary",
         "generation": 1, "action": None, "error_rate": 0.0,
         "window": {"requests": 8.0, "answered": 8.0, "errors": 0.0, "shed": 0.0},
         "clean_evals": 1, "evals": 1, "breached_rules": []},
    ]
    for _ in range(promotions):
        events += [
            {"event": "on_promotion", "time": 1.4, "generation": 1,
             "from_generation": 0, "clean_evals": 2, "evals": 2},
            {"event": "on_swap", "time": 1.4, "reason": "promote",
             "from_generation": 0, "to_generation": 1, "recompiled": False},
        ]
    for _ in range(rollbacks):
        events += [
            {"event": "on_rollback", "time": 1.5, "generation": 2,
             "restored_generation": 1, "rules": ["canary_error_rate"], "evals": 3},
            {"event": "on_swap", "time": 1.5, "reason": "rollback",
             "from_generation": 2, "to_generation": 1, "recompiled": False},
        ]
    events.append(
        {"event": "on_serve_end", "time": 3.0, "mode": "full", "requests": 20,
         "answered": 20, "errors": 0, "cache_hit_rate": 0.5,
         "batch_fill_ratio": 0.8, "queue_wait_ms_mean": 1.0,
         "queue_wait_ms_max": 2.0,
         "served_from": {"hit": 10, "advance": 5, "cold": 5}},
    )
    record = {"metric": "serve_qps", "value": qps, "unit": "req/s", "qps": qps,
              "p50_ms": 1.2, "p95_ms": 3.1, "p99_ms": 4.0,
              "batch_fill_ratio": 0.8, "cache_hit_rate": 0.5, "mode": "full",
              "backend": "cpu"}
    if swap_p99_ms is not None:
        record["swap"] = {"swaps": 3, "p99_ms": swap_p99_ms, "errors": 0,
                          "generations_seen": 4, "recompiled_swaps": 1}
    events.append(record)
    with open(os.path.join(path, "events.jsonl"), "w") as fh:
        for event in events:
            fh.write(json.dumps(event) + "\n")
    return path


def test_promotion_summary_and_render(tmp_path, capsys):
    run = _write_promotion_run(str(tmp_path / "promo"), rollbacks=1,
                               swap_p99_ms=6.5)
    summary = summarize_run(run)
    assert summary["rollbacks"] == 1
    assert summary["promotions"] == 1
    assert summary["swaps"] == 2
    promotion = summary["promotion"]
    assert promotion["publishes"] == 1
    assert promotion["canaries"] == 1
    assert promotion["canary_evals"] == 1
    assert promotion["rollback_rules"] == ["canary_error_rate"]
    assert summary["serve"]["swap"] is True
    assert summary["serve"]["swap_p99_ms"] == 6.5
    assert main([run]) == 0
    out = capsys.readouterr().out
    assert "promotion:" in out
    assert "1 rolled back" in out
    assert "serving swap:" in out
    assert "rollback rule(s): canary_error_rate" in out


def test_compare_gates_on_rollback_increase(tmp_path, capsys):
    baseline = _write_promotion_run(str(tmp_path / "base"), rollbacks=0)
    candidate = _write_promotion_run(str(tmp_path / "cand"), rollbacks=1)
    assert main([candidate, "--compare", baseline]) == 2
    assert "rollbacks increased" in capsys.readouterr().err


def test_compare_rollbacks_equal_passes(tmp_path):
    baseline = _write_promotion_run(str(tmp_path / "base"), rollbacks=1)
    candidate = _write_promotion_run(str(tmp_path / "cand"), rollbacks=1)
    assert main([candidate, "--compare", baseline]) == 0


def test_compare_gates_swap_p99_when_both_ran_swaps(tmp_path, capsys):
    baseline = _write_promotion_run(str(tmp_path / "base"), swap_p99_ms=5.0)
    candidate = _write_promotion_run(str(tmp_path / "cand"), swap_p99_ms=9.0)
    assert main([candidate, "--compare", baseline]) == 2
    assert "swap_p99_ms regressed" in capsys.readouterr().err


def test_compare_surfaces_swap_p99_ungated_on_phase_mismatch(tmp_path, capsys):
    baseline = _write_promotion_run(str(tmp_path / "base"), swap_p99_ms=None)
    candidate = _write_promotion_run(str(tmp_path / "cand"), swap_p99_ms=50.0)
    assert main([candidate, "--compare", baseline]) == 0
    out = capsys.readouterr().out
    assert "swap_p99_ms" in out and "not gated" in out


def test_compare_swap_p99_improvement_passes(tmp_path):
    baseline = _write_promotion_run(str(tmp_path / "base"), swap_p99_ms=9.0)
    candidate = _write_promotion_run(str(tmp_path / "cand"), swap_p99_ms=5.0)
    assert main([candidate, "--compare", baseline]) == 0
