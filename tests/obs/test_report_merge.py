"""Multi-process report merge, shard rotation, and the new --compare gates.

Core tier, no jax: synthetic per-process shards with engineered skew drive
the straggler math; JsonlLogger's size rotation feeds the shard reader.
"""

import json
import logging
import os

import pytest

from replay_tpu.obs.events import ConsoleLogger, JsonlLogger, TrainerEvent
from replay_tpu.obs.report import (
    compare_runs,
    render,
    straggler_summary,
    summarize_run,
)

pytestmark = pytest.mark.core


def _step_record(step, process_index, step_seconds, loss=0.5):
    return {
        "event": "on_train_step",
        "time": 1000.0 + step,
        "step": step,
        "loss": loss,
        "samples_per_sec": 100.0,
        "step_seconds": step_seconds,
        "process_index": process_index,
    }


def _write_jsonl(path, records):
    with open(path, "w") as fh:
        for record in records:
            fh.write(json.dumps(record) + "\n")


def _write_multiprocess_run(run_dir, step_times):
    """One shard per process (process 0 owns events.jsonl), each stamped with
    its own synthetic per-step time."""
    os.makedirs(run_dir, exist_ok=True)
    for pid, step_seconds in enumerate(step_times):
        records = [_step_record(s, pid, step_seconds) for s in range(1, 5)]
        if pid == 0:
            records.append({"event": "on_fit_end", "time": 2000.0, "bad_steps": 0})
        name = "events.jsonl" if pid == 0 else f"events.p{pid}.jsonl"
        _write_jsonl(os.path.join(run_dir, name), records)


# --------------------------------------------------------------------------- #
# straggler math
# --------------------------------------------------------------------------- #
def test_straggler_summary_math():
    summary = straggler_summary({0: 0.10, 1: 0.10, 2: 0.10, 3: 0.25})
    assert summary["max_step_seconds"] == 0.25
    assert summary["median_step_seconds"] == pytest.approx(0.10)
    assert summary["straggler"] == "3"
    assert summary["straggler_index"] == pytest.approx(2.5)
    assert summary["skew"] == pytest.approx(1.5)
    balanced = straggler_summary({0: 0.1})
    assert balanced["straggler_index"] == 1.0 and balanced["skew"] == 0.0
    with pytest.raises(ValueError):
        straggler_summary({})


# --------------------------------------------------------------------------- #
# shard merging
# --------------------------------------------------------------------------- #
def test_merges_per_process_shards_and_computes_skew(tmp_path):
    run_dir = str(tmp_path / "run")
    _write_multiprocess_run(run_dir, step_times=[0.10, 0.11, 0.40, 0.10])
    summary = summarize_run(run_dir)
    assert summary["train_steps"] == 16  # 4 steps x 4 processes, one stream
    processes = summary["processes"]
    assert processes["count"] == 4
    assert processes["straggler"] == "2"
    assert processes["straggler_index"] == pytest.approx(0.40 / 0.105, rel=1e-6)
    assert processes["step_seconds"]["2"] == pytest.approx(0.40)
    text = render(summary)
    assert "processes: 4 host(s)" in text and "straggler index" in text


def test_unstamped_shard_inherits_its_filename_index(tmp_path):
    run_dir = str(tmp_path / "run")
    os.makedirs(run_dir)
    _write_jsonl(
        os.path.join(run_dir, "events.jsonl"),
        [_step_record(s, 0, 0.1) for s in range(1, 4)],
    )
    records = [_step_record(s, 0, 0.3) for s in range(1, 4)]
    for record in records:
        del record["process_index"]
    _write_jsonl(os.path.join(run_dir, "events.p1.jsonl"), records)
    processes = summarize_run(run_dir)["processes"]
    assert processes["count"] == 2
    assert processes["step_seconds"]["1"] == pytest.approx(0.3)


def test_single_process_run_has_no_processes_section(tmp_path):
    run_dir = str(tmp_path / "run")
    os.makedirs(run_dir)
    records = [_step_record(s, 0, 0.1) for s in range(1, 4)]
    for record in records:
        del record["process_index"]
    _write_jsonl(os.path.join(run_dir, "events.jsonl"), records)
    summary = summarize_run(run_dir)
    assert summary["processes"] is None
    assert "processes:" not in render(summary)


# --------------------------------------------------------------------------- #
# size rotation (JsonlLogger satellite) read back in order
# --------------------------------------------------------------------------- #
def test_jsonl_rotation_and_ordered_readback(tmp_path):
    run_dir = str(tmp_path / "run")
    sink = JsonlLogger(run_dir, max_bytes=600, rotate=5)
    for step in range(1, 25):
        sink.log_event(
            TrainerEvent(
                "on_train_step",
                step=step,
                payload={"loss": 0.5, "step_seconds": 0.1, "samples_per_sec": 10.0},
            )
        )
    sink.close()
    shards = sorted(os.listdir(run_dir))
    assert "events.jsonl" in shards
    assert any(name.startswith("events.jsonl.") for name in shards)
    # every shard stays under the bound (one record never splits)
    for name in shards:
        assert os.path.getsize(os.path.join(run_dir, name)) <= 600
    summary = summarize_run(run_dir)
    assert summary["train_steps"] == 24  # nothing evicted at rotate=5
    # the merged stream is in write order: steps ascend across shards
    from replay_tpu.obs.report import _collect_event_files, load_events

    steps = [
        record["step"]
        for path, _ in _collect_event_files(run_dir)
        for record in load_events(path)
    ]
    assert steps == sorted(steps)


def test_jsonl_rotation_drops_oldest_beyond_rotate(tmp_path):
    run_dir = str(tmp_path / "run")
    sink = JsonlLogger(run_dir, max_bytes=200, rotate=2)
    for step in range(60):
        sink.log_record({"event": "e", "step": step, "pad": "x" * 40})
    sink.close()
    names = sorted(os.listdir(run_dir))
    assert names == ["events.jsonl", "events.jsonl.1", "events.jsonl.2"]


def test_jsonl_process_index_filename(tmp_path):
    sink = JsonlLogger(str(tmp_path), process_index=2)
    sink.log_record({"event": "e"})
    sink.close()
    assert os.path.exists(tmp_path / "events.p2.jsonl")
    zero = JsonlLogger(str(tmp_path), process_index=0)
    zero.log_record({"event": "e"})
    zero.close()
    assert os.path.exists(tmp_path / "events.jsonl")


# --------------------------------------------------------------------------- #
# --compare gates: slo_violations and the straggler index
# --------------------------------------------------------------------------- #
def _write_slo_run(run_dir, violations):
    os.makedirs(run_dir, exist_ok=True)
    records = [_step_record(s, 0, 0.1) for s in range(1, 4)]
    for record in records:
        del record["process_index"]
    for i in range(violations):
        records.append(
            {
                "event": "on_slo_violation",
                "time": 1500.0 + i,
                "rule": "bad_steps",
                "metric": "replay_train_bad_steps",
                "value": 1.0,
                "threshold": 0.0,
            }
        )
    _write_jsonl(os.path.join(run_dir, "events.jsonl"), records)


def test_slo_violations_gate_zero_baseline_fires_on_any(tmp_path):
    clean = str(tmp_path / "clean")
    dirty = str(tmp_path / "dirty")
    _write_slo_run(clean, violations=0)
    _write_slo_run(dirty, violations=2)
    assert summarize_run(dirty)["slo_violations"] == 2
    assert "SLO: 2 violation(s)" in render(summarize_run(dirty))
    _, regressions = compare_runs(summarize_run(dirty), summarize_run(clean))
    assert any("SLO violations increased 0 -> 2" in r for r in regressions)
    # and the clean candidate passes against the dirty baseline
    _, regressions = compare_runs(summarize_run(clean), summarize_run(dirty))
    assert not any("SLO" in r for r in regressions)


def test_straggler_gate_only_between_multiprocess_runs(tmp_path):
    balanced = str(tmp_path / "balanced")
    skewed = str(tmp_path / "skewed")
    single = str(tmp_path / "single")
    _write_multiprocess_run(balanced, step_times=[0.10, 0.10, 0.11, 0.10])
    _write_multiprocess_run(skewed, step_times=[0.10, 0.10, 0.40, 0.10])
    _write_multiprocess_run(single, step_times=[0.10])
    lines, regressions = compare_runs(
        summarize_run(skewed), summarize_run(balanced), threshold=0.1
    )
    assert any("straggler_index regressed" in r for r in regressions)
    # balanced vs skewed baseline: an improvement, no regression
    _, regressions = compare_runs(summarize_run(balanced), summarize_run(skewed))
    assert not any("straggler" in r for r in regressions)
    # one side single-process: surfaced, never gated
    lines, regressions = compare_runs(summarize_run(skewed), summarize_run(single))
    assert not any("straggler" in r for r in regressions)
    assert any("not gated: both runs must be multi-process" in line for line in lines)


# --------------------------------------------------------------------------- #
# ConsoleLogger: warning-class events get a visible single-line render
# --------------------------------------------------------------------------- #
def test_console_renders_warning_class_events(caplog):
    console = ConsoleLogger(every=1000)  # step cadence irrelevant here
    with caplog.at_level(logging.INFO, logger="replay_tpu"):
        console.log_event(
            TrainerEvent(
                "on_slo_violation",
                step=7,
                payload={
                    "rule": "bad_steps", "metric": "replay_train_bad_steps",
                    "op": ">", "threshold": 0.0, "value": 1.0, "consecutive": 1,
                },
            )
        )
        console.log_event(
            TrainerEvent(
                "on_slo_recovery",
                step=9,
                payload={
                    "rule": "bad_steps", "metric": "replay_train_bad_steps",
                    "value": 0.0, "breach_seconds": 2.0,
                    "breached_evaluations": 2,
                },
            )
        )
        console.log_event(
            TrainerEvent(
                "on_shed",
                payload={"lane": "hit", "depth": 9, "max_depth": 8, "count": 3},
            )
        )
        console.log_event(
            TrainerEvent(
                "on_breaker",
                payload={"from": "closed", "to": "open", "consecutive_failures": 5},
            )
        )
        console.log_event(
            TrainerEvent(
                "on_degrade", payload={"to": "fallback", "reason": "overload"}
            )
        )
    text = caplog.text
    assert "SLO violation [bad_steps]" in text and "step 7" in text
    assert "SLO recovered [bad_steps]" in text
    assert "3 request(s) shed on lane hit" in text
    assert "circuit breaker closed -> open" in text
    assert "rerouted to fallback" in text
    warning_count = sum(1 for r in caplog.records if r.levelno == logging.WARNING)
    assert warning_count == 4  # recovery is INFO, the rest WARN
