"""Post-mortem reconstruction over a synthetic dead-fleet run directory.

Core tier: builds the exact artifact layout a SIGKILL chaos run leaves
behind — a survivor's events shard, a dead rank's torn shard + flight ring +
``meta.json`` death declaration, a preempted checkpoint sidecar — and
asserts :func:`build_postmortem` merges it into per-process last-known-
activity timelines without ever raising for the damage it exists to explain.
"""

import json
import os
import time

import pytest

from replay_tpu.obs import report
from replay_tpu.obs.blackbox import FlightRecorder
from replay_tpu.obs.postmortem import (
    _load_events_tolerant,
    build_postmortem,
    discover_rings,
    render_postmortem,
)

pytestmark = pytest.mark.core


def _dead_fleet_run(tmp_path):
    run = tmp_path / "run"
    run.mkdir()
    now = time.time()
    # rank 0 survived: clean shard, no ring damage
    with open(run / "events.jsonl", "w") as fh:
        for step in range(6):
            fh.write(json.dumps(
                {"event": "on_train_step", "step": step, "time": now + step}
            ) + "\n")
    # rank 1 died: shard torn mid-line, ring written up to step 4, SIGKILL meta
    with open(run / "events.p1.jsonl", "w") as fh:
        for step in range(4):
            fh.write(json.dumps(
                {"event": "on_train_step", "step": step, "time": now + step}
            ) + "\n")
        fh.write('{"event": "on_train_st')  # the torn line a dying write leaves
    rank1 = run / "workers" / "rank1"
    rank1.mkdir(parents=True)
    with FlightRecorder(str(rank1 / "flight.ring"), capacity=32) as rec:
        rec.record({"event": "flight_open", "role": "fit", "process_index": 1})
        for step in range(5):
            rec.record({"event": "on_train_step", "step": step}, when=now + step)
    with open(rank1 / "meta.json", "w") as fh:
        json.dump({"rank": 1, "returncode": -9, "killed_by": 9, "reaped": False}, fh)
    # the last checkpoint that durably landed (a preempted mid-epoch save)
    with open(run / "step_3.json", "w") as fh:
        json.dump({"epoch": 0, "mid_epoch": True, "preempted": True}, fh)
    return str(run)


def test_postmortem_merges_all_four_evidence_kinds(tmp_path):
    run = _dead_fleet_run(tmp_path)
    post = build_postmortem(run)

    rank1 = post["processes"]["rank1"]
    assert rank1["dead"] is True
    assert rank1["flight_records_recovered"] == 6  # flight_open + 5 steps
    assert rank1["last_flight_record"]["event"] == "on_train_step"
    assert rank1["last_flight_record"]["step"] == 4
    assert rank1["death"]["killed_by"] == 9
    assert rank1["shard_torn_lines"] == 1
    # the named gap: final flight record -> death declaration
    assert rank1["gap_s"] >= 0.0

    rank0 = post["processes"]["rank0"]
    assert rank0["dead"] is False
    assert rank0["last_shard_event"]["step"] == 5

    assert post["checkpoints"][-1]["step"] == 3
    assert post["checkpoints"][-1]["preempted"] is True
    assert post["unreadable_rings"] == 0


def test_postmortem_render_names_the_dead_and_the_gap(tmp_path):
    post = build_postmortem(_dead_fleet_run(tmp_path))
    text = render_postmortem(post)
    assert "rank1: DEAD" in text
    assert "rank0: survived" in text
    assert "signal 9" in text
    assert "unaccounted gap" in text
    assert "last checkpoint: step 3 (preempted save)" in text


def test_postmortem_cli_writes_postmortem_json_and_exits_zero(tmp_path, capsys):
    run = _dead_fleet_run(tmp_path)
    assert report.main([run, "--postmortem"]) == 0
    out = capsys.readouterr().out
    assert "rank1: DEAD" in out
    with open(os.path.join(run, "postmortem.json")) as fh:
        post = json.load(fh)
    assert post["processes"]["rank1"]["dead"] is True


def test_torn_and_unreadable_rings_are_reported_never_fatal(tmp_path):
    run = tmp_path / "run"
    (run / "workers" / "rank0").mkdir(parents=True)
    ring = run / "workers" / "rank0" / "flight.ring"
    with FlightRecorder(str(ring), capacity=8) as rec:
        for step in range(3):
            rec.record({"event": "on_train_step", "step": step})
    # tear the final record mid-store and truncate the file: double damage
    raw = bytearray(ring.read_bytes())
    raw[-200:] = b""
    raw[len(raw) - 40 :] = b"\xff" * 40
    ring.write_bytes(bytes(raw))
    # plus a ring that is not a ring at all
    (run / "flight.bogus.ring").write_bytes(b"junk" * 64)

    post = build_postmortem(str(run))  # never raises for damaged evidence
    assert post["unreadable_rings"] == 1
    readable = [r for r in post["rings"] if r.get("readable")]
    assert len(readable) == 1
    assert readable[0]["torn_tail"] is True
    assert post["torn_tails"] == 1
    assert render_postmortem(post)  # and it still renders


def test_tolerant_loader_counts_damage_instead_of_raising(tmp_path):
    shard = tmp_path / "events.jsonl"
    shard.write_text(
        '{"event": "a"}\n'
        "not json at all\n"
        '{"event": "b"}\n'
        "[1, 2, 3]\n"
        '{"event": "c"'  # torn final line
    )
    records, skipped = _load_events_tolerant(str(shard))
    assert [r["event"] for r in records] == ["a", "b"]
    assert skipped == 3
    # the strict report loader refuses the same shard — the split is the point
    with pytest.raises(ValueError, match="invalid JSON"):
        report.load_events(str(shard))


def test_discover_rings_orders_root_then_ranks(tmp_path):
    run = tmp_path / "run"
    (run / "workers" / "rank0").mkdir(parents=True)
    (run / "workers" / "rank1").mkdir(parents=True)
    for path in (
        run / "flight.s0.ring",
        run / "flight.s1.ring",
        run / "workers" / "rank0" / "flight.ring",
        run / "workers" / "rank1" / "flight.ring",
    ):
        with FlightRecorder(str(path), capacity=4) as rec:
            rec.record({"event": "on_serve_start"})
    rings = discover_rings(str(run))
    names = [os.path.relpath(r, str(run)) for r in rings]
    assert names == [
        "flight.s0.ring",
        "flight.s1.ring",
        os.path.join("workers", "rank0", "flight.ring"),
        os.path.join("workers", "rank1", "flight.ring"),
    ]


def test_missing_run_dir_is_the_only_fatal_input(tmp_path):
    with pytest.raises(FileNotFoundError):
        build_postmortem(str(tmp_path / "nope"))
