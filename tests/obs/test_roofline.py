"""Roofline analysis (obs.roofline): peak tables, classification math, the
compiled-program record, and the MemoryMonitor chunk-boundary sampling hook.

Core tier is pure arithmetic (no jax): bandwidth lookups, memory- vs
compute-bound classification against real and assumed chips, the ceiling
formula, degradation to None for unclassifiable inputs. The jax tier runs
``analyze_program`` / ``Trainer.analyze_programs`` on real compiled programs
and checks the memory/collective fields, and verifies the scan-chunked fit
samples device memory at chunk boundaries (CPU-safe no-op).
"""

import numpy as np
import pytest

from replay_tpu.obs import MemoryMonitor
from replay_tpu.obs.mfu import peak_tflops
from replay_tpu.obs.roofline import (
    PEAK_HBM_GBPS,
    classify,
    of_ceiling,
    peak_bandwidth,
)


# --------------------------------------------------------------------------- #
# core: tables + classification arithmetic
# --------------------------------------------------------------------------- #
@pytest.mark.core
def test_peak_bandwidth_table_mirrors_flops_table_keys():
    from replay_tpu.obs.mfu import PEAK_BF16_TFLOPS

    assert set(PEAK_HBM_GBPS) == set(PEAK_BF16_TFLOPS)
    assert peak_bandwidth("TPU v5 lite") == 819.0
    assert peak_bandwidth("TPU v5p chip") == 2765.0
    assert peak_bandwidth("cpu") is None
    assert peak_bandwidth("") is None


@pytest.mark.core
def test_classify_memory_vs_compute_bound():
    # v5e: critical intensity = 197e12 / 819e9 ~ 240.5 flops/byte
    low = classify(flops=1e9, bytes_accessed=1e9, device_kind="TPU v5e")  # 1 flop/B
    assert low["bound"] == "memory"
    assert low["ceiling_tflops"] == pytest.approx(819e9 * 1.0 / 1e12)
    assert low["min_step_seconds"] == pytest.approx(1e9 / 819e9)

    high = classify(flops=1000e9, bytes_accessed=1e9, device_kind="TPU v5e")
    assert high["bound"] == "compute"
    assert high["ceiling_tflops"] == pytest.approx(197.0)
    assert high["critical_intensity"] == pytest.approx(197e12 / 819e9)


@pytest.mark.core
def test_classify_unknown_chip_uses_assumed_kind_and_flags_it(monkeypatch):
    monkeypatch.delenv("REPLAY_TPU_ROOFLINE_ASSUME_KIND", raising=False)
    monkeypatch.delenv("REPLAY_TPU_BENCH_ASSUME_KIND", raising=False)
    assert classify(1e9, 1e9, "cpu") is None  # no peaks, no assumption -> None
    monkeypatch.setenv("REPLAY_TPU_ROOFLINE_ASSUME_KIND", "v5e")
    record = classify(1e9, 1e9, "cpu")
    assert record["bound"] == "memory"
    assert record["peak_assumed"] == "v5e"
    # a REAL chip kind never carries the assumed flag
    real = classify(1e9, 1e9, "TPU v4")
    assert "peak_assumed" not in real
    assert real["peak_tflops"] == peak_tflops("TPU v4")


@pytest.mark.core
def test_classify_degenerate_inputs_return_none():
    assert classify(0.0, 1e9, "TPU v5e") is None
    assert classify(1e9, 0.0, "TPU v5e") is None
    assert classify(None, None, "TPU v5e") is None


@pytest.mark.core
def test_of_ceiling():
    record = classify(1e9, 1e9, "TPU v5e")
    assert of_ceiling(record["ceiling_tflops"] / 2, record) == pytest.approx(0.5)
    assert of_ceiling(None, record) is None
    assert of_ceiling(1.0, None) is None


# --------------------------------------------------------------------------- #
# core: MemoryMonitor chunk-boundary sampling (fake devices, no jax)
# --------------------------------------------------------------------------- #
class _FakeDevice:
    def __init__(self, stats):
        self._stats = stats

    def memory_stats(self):
        return self._stats

    def __str__(self):
        return f"fake:{id(self)}"


@pytest.mark.core
def test_memory_monitor_observe_tracks_windowed_peak():
    device = _FakeDevice({"peak_bytes_in_use": 100, "bytes_in_use": 50})
    monitor = MemoryMonitor(devices=[device])
    assert monitor.observe() == 100
    device._stats = {"peak_bytes_in_use": 300}
    assert monitor.observe() == 300
    device._stats = {"peak_bytes_in_use": 200}  # peak never regresses
    assert monitor.observe() == 200
    assert monitor.observed_peak_bytes == 300
    assert monitor.observed_samples == 3


@pytest.mark.core
def test_memory_monitor_observe_is_a_noop_without_allocator_stats():
    monitor = MemoryMonitor(devices=[_FakeDevice(None)])
    assert monitor.observe() is None
    assert monitor.observed_peak_bytes is None
    assert monitor.observed_samples == 0


# --------------------------------------------------------------------------- #
# jax tier: compiled-program records + the fit sampling hook
# --------------------------------------------------------------------------- #
def _tiny_trainer(num_items=50, seq_len=8, dim=16):
    from replay_tpu.data import FeatureHint, FeatureType
    from replay_tpu.data.nn import TensorFeatureInfo, TensorSchema
    from replay_tpu.nn import OptimizerFactory, Trainer, make_mesh
    from replay_tpu.nn.loss import CE
    from replay_tpu.nn.sequential.sasrec import SasRec

    schema = TensorSchema(
        TensorFeatureInfo(
            "item_id", FeatureType.CATEGORICAL, is_seq=True,
            feature_hint=FeatureHint.ITEM_ID, cardinality=num_items,
            embedding_dim=dim,
        )
    )
    model = SasRec(schema=schema, embedding_dim=dim, num_blocks=1, num_heads=1,
                   max_sequence_length=seq_len)
    return Trainer(model=model, loss=CE(),
                   optimizer=OptimizerFactory(learning_rate=1e-2), mesh=make_mesh())


def _tiny_batches(n, num_items=50, seq_len=8, batch=8, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        items = rng.integers(0, num_items, size=(batch, seq_len + 1)).astype(np.int32)
        mask = np.ones((batch, seq_len), dtype=bool)
        out.append({
            "feature_tensors": {"item_id": items[:, :-1]},
            "padding_mask": mask,
            "positive_labels": items[:, 1:, None],
            "target_padding_mask": mask[:, :, None],
        })
    return out


@pytest.mark.jax
def test_analyze_program_on_compiled_matmul(monkeypatch):
    import jax
    import jax.numpy as jnp

    from replay_tpu.obs.roofline import analyze_program

    monkeypatch.setenv("REPLAY_TPU_ROOFLINE_ASSUME_KIND", "v5e")
    jitted = jax.jit(lambda a, b: jnp.tanh(a @ b).sum())
    record = analyze_program(jitted, jnp.ones((64, 32)), jnp.ones((32, 32)))
    assert record is not None
    assert record["hbm_peak_bytes"] >= record["argument_bytes"]
    assert record["collective_bytes"] == 0  # single-program, no mesh
    classification = record["roofline"]
    assert classification is not None and classification["bound"] in ("memory", "compute")
    # extra_flops shifts the intensity (the pallas-opacity compensation path)
    boosted = analyze_program(
        jitted, jnp.ones((64, 32)), jnp.ones((32, 32)), extra_flops=1e12
    )
    assert (
        boosted["roofline"]["arithmetic_intensity"]
        > classification["arithmetic_intensity"]
    )


@pytest.mark.jax
@pytest.mark.smoke
def test_chunked_fit_samples_memory_at_chunk_boundaries(monkeypatch):
    """The scan fit path calls MemoryMonitor.observe() once per chunk —
    verified through a recording stand-in (CPU reports no allocator stats, so
    the real observe is a no-op there by design)."""
    import replay_tpu.nn.train as train_module

    observed = []

    class RecordingMonitor(MemoryMonitor):
        def observe(self):
            observed.append(True)
            return super().observe()

    monkeypatch.setattr(train_module, "MemoryMonitor", RecordingMonitor)
    trainer = _tiny_trainer()
    trainer.fit(_tiny_batches(5), epochs=1, log_every=0, scan_chunk=2)
    # 5 batches at K=2 -> two scan chunks (the tail runs per-step)
    assert len(observed) == 2


@pytest.mark.jax
def test_compiled_inference_roofline_per_bucket(monkeypatch):
    from replay_tpu.nn.compiled import CompiledInference

    monkeypatch.setenv("REPLAY_TPU_ROOFLINE_ASSUME_KIND", "v5e")
    trainer = _tiny_trainer()
    batch = _tiny_batches(1)[0]
    state = trainer.init_state(batch)
    compiled = CompiledInference.compile(
        trainer.model, state.params, max_sequence_length=8,
        mode="dynamic_batch_size", dynamic_buckets=(1, 8),
    )
    records = compiled.roofline()
    assert set(records) == {1, 8}
    for record in records.values():
        assert record["hbm_peak_bytes"] > 0
        assert record["roofline"]["bound"] in ("memory", "compute")
