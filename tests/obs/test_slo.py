"""SLO watchdog (obs.slo): the breach→recovery state machine.

Core tier, no jax — rules evaluate pure registry reads with an injectable
clock, so every transition is deterministic.
"""

import pytest

from replay_tpu.obs.metrics import MetricsRegistry
from replay_tpu.obs.slo import SLORule, SLOWatchdog

pytestmark = pytest.mark.core


class FakeClock:
    def __init__(self):
        self.now = 0.0

    def __call__(self):
        return self.now


@pytest.fixture
def harness():
    registry = MetricsRegistry()
    clock = FakeClock()
    events = []

    def build(*rules):
        return SLOWatchdog(rules, registry, emit=events.append, clock=clock)

    return registry, clock, events, build


def test_rule_validation():
    with pytest.raises(ValueError, match="unknown op"):
        SLORule("m", "~", 1.0)
    with pytest.raises(ValueError, match="for_steps"):
        SLORule("m", ">", 1.0, for_steps=0)
    assert SLORule("m", ">", 0.5).label == "m>0.5"
    assert SLORule("m", ">", 0.5, name="latency budget").label == "latency budget"


def test_duplicate_rule_labels_rejected():
    with pytest.raises(ValueError, match="duplicate"):
        SLOWatchdog([SLORule("m", ">", 1.0), SLORule("m", ">", 1.0)], MetricsRegistry())


def test_fires_once_then_recovers_with_duration(harness):
    registry, clock, events, build = harness
    watchdog = build(SLORule("g", ">", 5.0))
    registry.set("g", 10.0)
    clock.now = 1.0
    watchdog.evaluate(step=1)
    assert [e.event for e in events] == ["on_slo_violation"]
    assert events[0].payload["value"] == 10.0 and events[0].step == 1
    # still breaching: no re-fire, but the active set reflects it
    clock.now = 2.0
    watchdog.evaluate(step=2)
    assert len(events) == 1
    assert watchdog.active == ["g>5"]
    assert registry.value("replay_slo_breached", labels={"rule": "g>5"}) == 1.0
    # recovery carries the breach duration and the eval count
    clock.now = 7.5
    registry.set("g", 1.0)
    watchdog.evaluate(step=3)
    assert [e.event for e in events] == ["on_slo_violation", "on_slo_recovery"]
    recovery = events[1].payload
    assert recovery["breach_seconds"] == pytest.approx(6.5)
    assert recovery["breached_evaluations"] == 2
    assert watchdog.active == []
    assert registry.value("replay_slo_breached", labels={"rule": "g>5"}) == 0.0
    # a fresh breach fires again (a NEW incident, not a re-fire)
    registry.set("g", 6.0)
    watchdog.evaluate(step=4)
    assert [e.event for e in events][-1] == "on_slo_violation"
    assert watchdog.stats()["g>5"]["fired"] == 2


def test_for_steps_debounces_transient_spikes(harness):
    registry, clock, events, build = harness
    watchdog = build(SLORule("g", ">", 5.0, for_steps=3, name="sustained"))
    # a 2-evaluation spike never fires (the transient case)
    registry.set("g", 9.0)
    watchdog.evaluate()
    watchdog.evaluate()
    registry.set("g", 1.0)
    watchdog.evaluate()
    assert events == []
    assert watchdog.stats()["sustained"]["consecutive"] == 0
    # a sustained breach fires on exactly the third consecutive evaluation
    registry.set("g", 9.0)
    watchdog.evaluate()
    watchdog.evaluate()
    assert events == []
    watchdog.evaluate()
    assert [e.event for e in events] == ["on_slo_violation"]
    assert events[0].payload["consecutive"] == 3


def test_missing_metric_is_no_data_not_a_transition(harness):
    registry, clock, events, build = harness
    watchdog = build(SLORule("absent", ">", 0.0))
    watchdog.evaluate()
    assert events == [] and watchdog.active == []
    # a rule mid-breach must not "recover" just because the metric vanished
    # (registry metrics never vanish, but a histogram stat can read None when
    # empty — same code path)
    registry.set("absent", 1.0)
    watchdog.evaluate()
    assert [e.event for e in events] == ["on_slo_violation"]


def test_histogram_stat_rules(harness):
    registry, clock, events, build = harness
    watchdog = build(SLORule("wait:p99", ">", 0.5, name="p99 budget"))
    for value in (0.1, 0.2, 0.1):
        registry.observe("wait", value, buckets=[0.25, 0.5, 1.0])
    watchdog.evaluate()
    assert events == []
    for _ in range(50):
        registry.observe("wait", 0.9, buckets=[0.25, 0.5, 1.0])
    watchdog.evaluate()
    assert [e.event for e in events] == ["on_slo_violation"]
    assert events[0].payload["metric"] == "wait:p99"


def test_bad_steps_rule_fires_exactly_once_per_incident(harness):
    """The CI acceptance shape: ONE injected NaN step → the bad_steps gauge
    jumps to 1 and stays — the rule must fire exactly once over the run."""
    registry, clock, events, build = harness
    watchdog = build(SLORule("replay_train_bad_steps", ">", 0, name="bad_steps"))
    registry.set("replay_train_bad_steps", 0.0)
    for _ in range(5):
        watchdog.evaluate()
    assert events == []
    registry.set("replay_train_bad_steps", 1.0)
    for _ in range(20):
        watchdog.evaluate()
    violations = [e for e in events if e.event == "on_slo_violation"]
    assert len(violations) == 1
    assert violations[0].payload["rule"] == "bad_steps"


def test_labeled_metric_rules_select_one_series(harness):
    """A metric that only exists labeled (degraded_total{to=...}) is readable
    by a rule carrying the label set; the unlabeled read stays no-data."""
    registry, clock, events, build = harness
    labeled = SLORule(
        "replay_serve_degraded_total", ">", 0, labels={"to": "fallback"}
    )
    assert labeled.label == "replay_serve_degraded_total{to=fallback}>0"
    blind = SLORule("replay_serve_degraded_total", ">", 0, name="blind")
    watchdog = build(labeled, blind)

    registry.inc("replay_serve_degraded_total", labels={"to": "cache_only"})
    assert watchdog.evaluate() == []  # wrong series: still no data for either

    registry.inc("replay_serve_degraded_total", labels={"to": "fallback"})
    emitted = watchdog.evaluate()
    assert [e.payload["rule"] for e in emitted] == [labeled.label]
    # the label-less rule never saw data — dead rules must not fake health
    assert watchdog.stats()["blind"]["consecutive"] == 0
