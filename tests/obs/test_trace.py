"""Span tracing + goodput accounting (obs.trace).

Core tier: the Tracer is pure host code — nesting/exclusive-time math, Chrome
trace-event export, thread safety, and the input-starvation accounting against
a deliberately slow (and a fast) fake batcher. The jax smoke test drives a
traced ``Trainer.fit`` end-to-end: valid ``trace.json``, goodput fractions
summing to 1.0 on every epoch-end/fit-end event — the PR's acceptance gate.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from replay_tpu.obs import GOODPUT_SPANS, Tracer, goodput_breakdown, traced_iterator


# --------------------------------------------------------------------------- #
# tracer core (host-only)
# --------------------------------------------------------------------------- #
def test_nested_spans_split_inclusive_and_exclusive_time():
    tracer = Tracer()
    with tracer.span("outer"):
        time.sleep(0.02)
        with tracer.span("inner"):
            time.sleep(0.02)
    summary = tracer.summary()
    assert summary["outer"]["count"] == 1 and summary["inner"]["count"] == 1
    # inclusive outer covers the inner; exclusive outer does not
    assert summary["outer"]["seconds"] >= summary["inner"]["seconds"]
    assert summary["outer"]["self_seconds"] == pytest.approx(
        summary["outer"]["seconds"] - summary["inner"]["seconds"], abs=1e-6
    )
    assert summary["inner"]["self_seconds"] == pytest.approx(
        summary["inner"]["seconds"], abs=1e-9
    )


def test_disabled_tracer_records_nothing_and_reuses_null_context():
    tracer = Tracer(enabled=False)
    ctx_a = tracer.span("x")
    ctx_b = tracer.span("y", attr=1)
    assert ctx_a is ctx_b  # one shared null context: near-zero overhead
    with ctx_a:
        pass
    tracer.add_span("z", 0.0, 1.0)
    assert tracer.summary() == {}
    assert tracer.to_chrome_trace()["traceEvents"] == []


def test_span_args_reach_chrome_trace(tmp_path):
    tracer = Tracer()
    with tracer.span("step", index=3, phase="train"):
        pass
    path = tracer.save(str(tmp_path / "trace.json"))
    payload = json.load(open(path))
    (event,) = payload["traceEvents"]
    assert event["name"] == "step" and event["ph"] == "X"
    assert event["args"] == {"index": 3, "phase": "train"}


def test_chrome_trace_is_valid(tmp_path):
    tracer = Tracer()
    for i in range(3):
        with tracer.span("step"):
            with tracer.span("inner"):
                pass
    tracer.add_span("synthetic", 0.0, 0.001)
    path = tracer.save(str(tmp_path / "trace.json"))
    payload = json.load(open(path))
    events = payload["traceEvents"]
    assert len(events) == 7
    for event in events:
        # the acceptance contract: name/ph/ts present, durations non-negative
        assert "name" in event and "ph" in event and "ts" in event
        assert event["ph"] == "X"
        assert event["dur"] >= 0 and event["ts"] >= 0
    # events are time-sorted for chrome/perfetto friendliness
    assert [e["ts"] for e in events] == sorted(e["ts"] for e in events)
    assert payload["displayTimeUnit"] == "ms"


def test_threaded_spans_all_recorded():
    tracer = Tracer()

    def work(i):
        for _ in range(25):
            with tracer.span(f"thread_{i}"):
                with tracer.span("inner"):
                    pass

    threads = [threading.Thread(target=work, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    summary = tracer.summary()
    assert summary["inner"]["count"] == 100
    for i in range(4):
        assert summary[f"thread_{i}"]["count"] == 25
        # nesting stacks are per-thread: each thread's inner nested under ITS span
        assert summary[f"thread_{i}"]["self_seconds"] <= summary[f"thread_{i}"]["seconds"]


def test_carve_reattributes_self_time():
    tracer = Tracer()
    with tracer.span("train_step") as span:
        time.sleep(0.03)
    before = tracer.summary()["train_step"]
    tracer.carve(span, "compile", 0.02)
    summary = tracer.summary()
    assert summary["compile"]["self_seconds"] == pytest.approx(0.02, abs=1e-9)
    assert summary["train_step"]["self_seconds"] == pytest.approx(
        before["self_seconds"] - 0.02, abs=1e-9
    )
    # inclusive step duration unchanged: the carved span nests inside it
    assert summary["train_step"]["seconds"] == pytest.approx(before["seconds"], abs=1e-9)
    # carving more than the span's remaining self time clamps, never negative
    tracer.carve(span, "compile", 99.0)
    assert tracer.summary()["train_step"]["self_seconds"] >= 0.0


# --------------------------------------------------------------------------- #
# goodput math (host-only)
# --------------------------------------------------------------------------- #
def test_goodput_fractions_sum_to_one():
    spans = {"data_wait": 0.2, "train_step": 0.5, "compile": 0.1, "unrelated": 9.0}
    record = goodput_breakdown(spans, wall_seconds=1.0)
    fractions = record["fractions"]
    assert set(fractions) == {*GOODPUT_SPANS, "other"}
    assert sum(fractions.values()) == pytest.approx(1.0, abs=1e-9)
    assert fractions["other"] == pytest.approx(0.2, abs=1e-9)  # unrelated excluded
    assert record["input_starvation"] == pytest.approx(0.2 / 0.8, abs=1e-9)


def test_goodput_overlapping_spans_renormalize():
    # concurrent-thread spans can exceed the wall window; the sum-to-1.0
    # contract must survive
    record = goodput_breakdown({"data_wait": 2.0, "train_step": 2.0}, wall_seconds=1.0)
    assert sum(record["fractions"].values()) == pytest.approx(1.0, abs=1e-9)
    assert record["fractions"]["other"] == pytest.approx(0.0, abs=1e-9)


def test_goodput_zero_wall_degrades():
    record = goodput_breakdown({}, wall_seconds=0.0)
    assert record["fractions"]["other"] == 1.0
    assert record["input_starvation"] == 0.0


def _goodput_of_loop(batch_delay: float, step_delay: float, n: int = 8):
    """The fit loop's accounting shape, minus jax: a traced iterator feeding a
    fake train step, folded through the same helpers Trainer.fit uses."""
    tracer = Tracer()

    def batcher():
        for _ in range(n):
            if batch_delay:
                time.sleep(batch_delay)
            yield {}

    start = time.perf_counter()
    for _ in traced_iterator(batcher(), tracer):
        with tracer.span("train_step"):
            time.sleep(step_delay)
    return goodput_breakdown(tracer.snapshot(), time.perf_counter() - start)


def test_slow_batcher_shows_input_starvation():
    """A batcher injecting 20ms/batch against a 2ms step must attribute the
    bulk of the pipeline to data_wait — the 'is the TPU idle because of the
    host?' one-liner."""
    record = _goodput_of_loop(batch_delay=0.02, step_delay=0.002)
    expected = 0.02 / (0.02 + 0.002)  # ≈ 0.91 of the stepping pipeline
    assert record["input_starvation"] > 0.7
    assert record["input_starvation"] == pytest.approx(expected, abs=0.15)
    assert record["fractions"]["data_wait"] > 0.6
    assert sum(record["fractions"].values()) == pytest.approx(1.0, abs=1e-9)


def test_fast_batcher_shows_no_starvation():
    record = _goodput_of_loop(batch_delay=0.0, step_delay=0.01)
    assert record["input_starvation"] < 0.1
    assert record["fractions"]["train_step"] > 0.6


def test_same_thread_batch_build_counts_as_input_time():
    """A batcher sharing the consumer's tracer nests batch_build inside
    data_wait; that assembly time must count toward starvation (input side),
    not leak into 'other'."""
    tracer = Tracer()

    def batcher():
        for _ in range(6):
            with tracer.span("batch_build"):
                time.sleep(0.01)
            yield {}

    start = time.perf_counter()
    for _ in traced_iterator(batcher(), tracer):
        with tracer.span("train_step"):
            time.sleep(0.002)
    record = goodput_breakdown(tracer.snapshot(), time.perf_counter() - start)
    assert record["fractions"]["other"] < 0.2
    assert record["input_starvation"] > 0.6  # ≈ 10/12 of the pipeline
    assert sum(record["fractions"].values()) == pytest.approx(1.0, abs=1e-9)


def test_snapshot_only_current_thread_excludes_worker_spans():
    tracer = Tracer()
    with tracer.span("train_step"):
        pass
    def record_span():
        with tracer.span("batch_build"):
            pass

    worker = threading.Thread(target=record_span)
    worker.start()
    worker.join()
    assert "batch_build" in tracer.snapshot()
    assert "batch_build" not in tracer.snapshot(only_current_thread=True)
    assert "train_step" in tracer.snapshot(only_current_thread=True)


def test_sequence_batcher_records_batch_build_spans():
    """SequenceBatcher(tracer=...) times every batch assembly."""
    import pandas as pd

    from replay_tpu.data import FeatureHint, FeatureType
    from replay_tpu.data.nn import (
        SequenceBatcher,
        SequentialDataset,
        TensorFeatureInfo,
        TensorSchema,
    )

    schema = TensorSchema(
        TensorFeatureInfo("item_id", FeatureType.CATEGORICAL, is_seq=True,
                          feature_hint=FeatureHint.ITEM_ID, cardinality=100)
    )
    frame = pd.DataFrame(
        {"query_id": np.arange(7), "item_id": [np.arange(1 + i) for i in range(7)]}
    )
    dataset = SequentialDataset(schema, "query_id", "item_id", frame)
    tracer = Tracer()
    batcher = SequenceBatcher(dataset, batch_size=2, max_sequence_length=4, tracer=tracer)
    batches = list(batcher)
    summary = tracer.summary()
    assert summary["batch_build"]["count"] == len(batches) == 4
    # tracing must not perturb the batches themselves
    plain = list(SequenceBatcher(dataset, batch_size=2, max_sequence_length=4))
    for traced, untraced in zip(batches, plain):
        np.testing.assert_array_equal(traced["item_id"], untraced["item_id"])


# --------------------------------------------------------------------------- #
# traced fit end-to-end (jax smoke) — the CI trace.json artifact producer
# --------------------------------------------------------------------------- #
def _run_dir(tmp_path, name):
    base = os.environ.get("REPLAY_TPU_RUN_DIR")
    return os.path.join(base, name) if base else str(tmp_path / name)


@pytest.mark.jax
@pytest.mark.smoke
def test_traced_fit_writes_valid_trace_and_goodput(tmp_path):
    from replay_tpu.data import FeatureHint, FeatureType
    from replay_tpu.data.nn import TensorFeatureInfo, TensorSchema
    from replay_tpu.nn import OptimizerFactory, Trainer, make_mesh
    from replay_tpu.nn.loss import CE
    from replay_tpu.nn.sequential.sasrec import SasRec
    from replay_tpu.obs import JsonlLogger

    num_items, seq_len, batch_size = 12, 8, 8
    schema = TensorSchema(
        TensorFeatureInfo("item_id", FeatureType.CATEGORICAL, is_seq=True,
                          feature_hint=FeatureHint.ITEM_ID, cardinality=num_items,
                          embedding_dim=16)
    )
    model = SasRec(schema=schema, embedding_dim=16, num_blocks=1, num_heads=1,
                   max_sequence_length=seq_len)
    trainer = Trainer(model=model, loss=CE(),
                      optimizer=OptimizerFactory(learning_rate=1e-2), mesh=make_mesh())
    rng = np.random.default_rng(0)

    def make_batch():
        items = rng.integers(0, num_items, size=(batch_size, seq_len + 1)).astype(np.int32)
        mask = np.ones((batch_size, seq_len), dtype=bool)
        return {
            "feature_tensors": {"item_id": items[:, :-1]},
            "padding_mask": mask,
            "positive_labels": items[:, 1:, None],
            "target_padding_mask": mask[:, :, None],
        }

    batches = [make_batch() for _ in range(3)]

    def val_batches():
        batch = dict(batches[0])
        batch["ground_truth"] = batches[0]["positive_labels"][:, -1, :].astype(np.int32)
        return [batch]

    run_dir = _run_dir(tmp_path, "trace_smoke")
    # mode="w": REPLAY_TPU_RUN_DIR is a fixed path in CI — re-runs must not append
    with JsonlLogger(run_dir, mode="w") as sink:
        trainer.fit(lambda: iter(batches), epochs=2, loggers=sink, tracer=True,
                    val_batches=val_batches, metrics=("ndcg",), top_k=(5,))

    # trace.json: valid Chrome trace-event JSON next to events.jsonl
    trace_path = os.path.join(run_dir, "trace.json")
    payload = json.load(open(trace_path))
    events = payload["traceEvents"]
    assert events, "traced fit recorded no spans"
    for event in events:
        assert "name" in event and "ph" in event and "ts" in event
        assert event["dur"] >= 0
    names = {event["name"] for event in events}
    assert {"data_wait", "h2d", "train_step", "compile", "validation"} <= names

    # goodput: every epoch-end and the fit-end carry fractions summing to 1.0
    lines = [json.loads(line) for line in open(os.path.join(run_dir, "events.jsonl"))]
    epoch_ends = [line for line in lines if line["event"] == "on_epoch_end"]
    fit_end = lines[-1]
    assert fit_end["event"] == "on_fit_end"
    assert len(epoch_ends) == 2
    for record in (*epoch_ends, fit_end):
        goodput = record["goodput"]
        fractions = goodput["fractions"]
        assert sum(fractions.values()) == pytest.approx(1.0, abs=0.05)
        assert all(value >= 0 for value in fractions.values())
        assert 0.0 <= goodput["input_starvation"] <= 1.0
    # the first epoch pays the train-step compile; the second must not
    assert epoch_ends[0]["goodput"]["fractions"]["compile"] > 0
    assert epoch_ends[1]["goodput"]["fractions"]["compile"] == pytest.approx(0.0, abs=1e-9)
    # span summaries mirrored into the event stream
    assert fit_end["spans"]["train_step"]["count"] == 6
    # tracing leaves the static-shapes invariant intact
    assert trainer.compile_tracker.traces["train_step"] == 1


def _tiny_trainer(embedding_dim=8):
    from replay_tpu.data import FeatureHint, FeatureType
    from replay_tpu.data.nn import TensorFeatureInfo, TensorSchema
    from replay_tpu.nn import OptimizerFactory, Trainer, make_mesh
    from replay_tpu.nn.loss import CE
    from replay_tpu.nn.sequential.sasrec import SasRec

    num_items, seq_len = 12, 8
    schema = TensorSchema(
        TensorFeatureInfo("item_id", FeatureType.CATEGORICAL, is_seq=True,
                          feature_hint=FeatureHint.ITEM_ID, cardinality=num_items,
                          embedding_dim=embedding_dim)
    )
    model = SasRec(schema=schema, embedding_dim=embedding_dim, num_blocks=1,
                   num_heads=1, max_sequence_length=seq_len)
    trainer = Trainer(model=model, loss=CE(),
                      optimizer=OptimizerFactory(learning_rate=1e-2), mesh=make_mesh())

    def make_batch(seed):
        rng = np.random.default_rng(seed)
        items = rng.integers(0, num_items, size=(8, seq_len + 1)).astype(np.int32)
        mask = np.ones((8, seq_len), dtype=bool)
        return {
            "feature_tensors": {"item_id": items[:, :-1]},
            "padding_mask": mask,
            "positive_labels": items[:, 1:, None],
            "target_padding_mask": mask[:, :, None],
        }

    return trainer, make_batch


class _Recorder:
    def __init__(self):
        self.events = []

    def log_event(self, event):
        self.events.append(event)


@pytest.mark.jax
def test_fit_argument_tracer_scopes_to_that_fit():
    """fit(tracer=True) must not leave the trainer permanently tracing: the
    next fit runs untraced (no per-step loss fence, no goodput payloads)."""
    trainer, make_batch = _tiny_trainer()
    trainer.fit(lambda: iter([make_batch(0), make_batch(1)]), epochs=1, tracer=True)
    assert trainer.tracer is None  # detached at fit end
    recorder = _Recorder()
    trainer.fit(lambda: iter([make_batch(2), make_batch(3)]), epochs=1, loggers=recorder)
    for event in recorder.events:
        assert "goodput" not in event.payload and "spans" not in event.payload


@pytest.mark.jax
def test_preattached_tracer_reports_per_fit_spans():
    """A Trainer-attached tracer accumulates across fits (one timeline), but
    each fit-end `spans` payload covers only THAT fit's spans."""
    trainer, make_batch = _tiny_trainer()
    trainer.tracer = Tracer()
    first, second = _Recorder(), _Recorder()
    trainer.fit(lambda: iter([make_batch(0), make_batch(1)]), epochs=1, loggers=first)
    trainer.fit(lambda: iter([make_batch(2), make_batch(3)]), epochs=1, loggers=second)
    assert trainer.tracer is not None  # preattached: stays for every fit
    spans_a = first.events[-1].payload["spans"]
    spans_b = second.events[-1].payload["spans"]
    assert spans_a["train_step"]["count"] == 2
    assert spans_b["train_step"]["count"] == 2  # not 4: earlier fits subtracted
    # the shared timeline still holds everything
    assert trainer.tracer.summary()["train_step"]["count"] == 4


@pytest.mark.jax
def test_epoch_end_checkpoint_bills_to_next_epoch_window(tmp_path):
    """Goodput windows tile the fit: epoch N's end-of-epoch checkpoint save
    must show up in epoch N+1's `checkpoint` fraction, not vanish between
    windows."""
    from replay_tpu.utils.checkpoint import CheckpointManager

    trainer, make_batch = _tiny_trainer()
    manager = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=10)
    recorder = _Recorder()
    trainer.fit(lambda epoch: [make_batch(10 * epoch + i) for i in range(2)],
                epochs=2, loggers=recorder, tracer=True, checkpoint_manager=manager)
    epoch_ends = [e for e in recorder.events if e.event == "on_epoch_end"]
    assert len(epoch_ends) == 2
    # epoch 0's save happened after epoch 0's window closed -> epoch 1 sees it
    assert epoch_ends[1].payload["goodput"]["fractions"]["checkpoint"] > 0
    # fit-end window covers the final save
    fit_end = recorder.events[-1]
    assert fit_end.payload["goodput"]["fractions"]["checkpoint"] > 0
    assert fit_end.payload["spans"]["checkpoint"]["count"] == 2


@pytest.mark.jax
def test_untraced_fit_emits_no_goodput():
    """tracer=None keeps the event schema exactly as before (additive change)."""
    from replay_tpu.data import FeatureHint, FeatureType
    from replay_tpu.data.nn import TensorFeatureInfo, TensorSchema
    from replay_tpu.nn import OptimizerFactory, Trainer, make_mesh
    from replay_tpu.nn.loss import CE
    from replay_tpu.nn.sequential.sasrec import SasRec
    from replay_tpu.obs import RunLogger

    class Recorder(RunLogger):
        def __init__(self):
            self.events = []

        def log_event(self, event):
            self.events.append(event)

    num_items, seq_len = 12, 8
    schema = TensorSchema(
        TensorFeatureInfo("item_id", FeatureType.CATEGORICAL, is_seq=True,
                          feature_hint=FeatureHint.ITEM_ID, cardinality=num_items,
                          embedding_dim=8)
    )
    model = SasRec(schema=schema, embedding_dim=8, num_blocks=1, num_heads=1,
                   max_sequence_length=seq_len)
    trainer = Trainer(model=model, loss=CE(),
                      optimizer=OptimizerFactory(learning_rate=1e-2), mesh=make_mesh())
    rng = np.random.default_rng(1)
    items = rng.integers(0, num_items, size=(8, seq_len + 1)).astype(np.int32)
    mask = np.ones((8, seq_len), dtype=bool)
    batch = {
        "feature_tensors": {"item_id": items[:, :-1]},
        "padding_mask": mask,
        "positive_labels": items[:, 1:, None],
        "target_padding_mask": mask[:, :, None],
    }
    recorder = Recorder()
    trainer.fit(lambda: iter([batch, batch]), epochs=1, loggers=recorder)
    for event in recorder.events:
        assert "goodput" not in event.payload and "spans" not in event.payload


# --------------------------------------------------------------------------- #
# traced scan-chunked fit (jax smoke) — the CI chunked_smoke artifact producer
# --------------------------------------------------------------------------- #
@pytest.mark.jax
@pytest.mark.smoke
def test_traced_chunked_fit_goodput_sums_and_h2d_overlaps(tmp_path):
    """A traced fit(scan_chunk=K) with the device feed: goodput fractions
    still sum to 1.0, the chunk h2d spans land on the FEEDER thread (the
    overlap trace.json shows next to the fit thread's train_step spans), and
    chunked train_step spans carry their per-step attribution (steps=K)."""
    from replay_tpu.obs import JsonlLogger

    trainer, make_batch = _tiny_trainer()
    batches = [make_batch(i) for i in range(7)]  # two K=3 chunks + one tail step

    run_dir = _run_dir(tmp_path, "chunked_smoke")
    # mode="w": REPLAY_TPU_RUN_DIR is a fixed path in CI — re-runs must not append
    with JsonlLogger(run_dir, mode="w") as sink:
        trainer.fit(lambda: iter(batches), epochs=2, loggers=sink, tracer=True,
                    scan_chunk=3)

    payload = json.load(open(os.path.join(run_dir, "trace.json")))
    events = payload["traceEvents"]
    for event in events:
        assert "name" in event and "ph" in event and "ts" in event
        assert event["dur"] >= 0
    by_name = {}
    for event in events:
        by_name.setdefault(event["name"], []).append(event)
    # chunk dispatches carry per-step attribution; the tail step has none
    chunk_spans = [e for e in by_name["train_step"] if e.get("args", {}).get("steps")]
    assert [e["args"]["steps"] for e in chunk_spans] == [3, 3, 3, 3]
    # h2d overlaps: the device feed places chunks on the feeder thread, a
    # DIFFERENT tid than the fit thread's train_step spans
    step_tids = {e["tid"] for e in by_name["train_step"]}
    h2d_tids = {e["tid"] for e in by_name["h2d"]}
    assert h2d_tids - step_tids, "no h2d span on the feeder thread"
    # the fit thread still times its wait on the feed as data_wait
    assert "data_wait" in by_name

    lines = [json.loads(line) for line in open(os.path.join(run_dir, "events.jsonl"))]
    epoch_ends = [line for line in lines if line["event"] == "on_epoch_end"]
    fit_end = lines[-1]
    assert fit_end["event"] == "on_fit_end"
    assert len(epoch_ends) == 2
    for record in (*epoch_ends, fit_end):
        goodput = record["goodput"]
        fractions = goodput["fractions"]
        assert sum(fractions.values()) == pytest.approx(1.0, abs=0.05)
        assert all(value >= 0 for value in fractions.values())
        assert 0.0 <= goodput["input_starvation"] <= 1.0
    # per-step events fan out of the chunk: 7 steps per epoch, losses intact
    steps = [line for line in lines if line["event"] == "on_train_step"]
    assert len(steps) == 14
    assert all(np.isfinite(s["loss"]) for s in steps)
    # one compiled scan + one compiled per-step program (the tail)
    assert trainer.compile_tracker.traces["train_scan"] == 1
    assert trainer.compile_tracker.traces["train_step"] == 1


# --------------------------------------------------------------------------- #
# serve spans: cross-thread lifecycle timing + the serve goodput breakdown
# --------------------------------------------------------------------------- #
def test_lifecycle_span_records_across_threads():
    from replay_tpu.obs import lifecycle_span

    tracer = Tracer()
    started = {}

    def producer():
        started["at"] = tracer.now()

    producer_thread = threading.Thread(target=producer)
    producer_thread.start()
    producer_thread.join()
    time.sleep(0.02)
    duration = lifecycle_span(tracer, "queue_wait", started["at"], lane="hit")
    assert duration >= 0.015
    (event,) = tracer.to_chrome_trace()["traceEvents"]
    assert event["name"] == "queue_wait"
    assert event["args"] == {"lane": "hit"}
    assert event["dur"] == pytest.approx(duration * 1e6, rel=1e-3)
    summary = tracer.summary()
    assert summary["queue_wait"]["count"] == 1


def test_lifecycle_span_on_disabled_tracer_is_a_noop():
    from replay_tpu.obs import lifecycle_span

    tracer = Tracer(enabled=False)
    duration = lifecycle_span(tracer, "queue_wait", 0.0)
    assert duration >= 0.0
    assert tracer.to_chrome_trace()["traceEvents"] == []


def test_serve_goodput_fractions_sum_to_one():
    from replay_tpu.obs import SERVE_GOODPUT_SPANS

    spans = {"queue_wait": 0.6, "batch_build": 0.05, "score": 0.2,
             "retrieve": 0.04, "rerank": 0.03}
    breakdown = goodput_breakdown(spans, 1.0, spans=SERVE_GOODPUT_SPANS)
    fractions = breakdown["fractions"]
    assert set(fractions) == set(SERVE_GOODPUT_SPANS) | {"other"}
    assert sum(fractions.values()) == pytest.approx(1.0)
    assert fractions["queue_wait"] == pytest.approx(0.6)
    # no stepping pipeline in a serve breakdown -> starvation is None
    assert breakdown["input_starvation"] is None


def test_serve_goodput_renormalizes_overlapping_queue_waits():
    """queue_wait is inherently concurrent (many requests wait at once): when
    tracked span time exceeds the wall window the fractions renormalize so
    the sum-to-1.0 contract survives."""
    from replay_tpu.obs import SERVE_GOODPUT_SPANS

    spans = {"queue_wait": 5.0, "score": 1.0}  # 6s of spans in a 2s window
    breakdown = goodput_breakdown(spans, 2.0, spans=SERVE_GOODPUT_SPANS)
    fractions = breakdown["fractions"]
    assert sum(fractions.values()) == pytest.approx(1.0)
    assert fractions["queue_wait"] == pytest.approx(5.0 / 6.0)
    assert fractions["other"] == pytest.approx(0.0)


def test_training_goodput_still_reports_starvation():
    spans = {"data_wait": 0.2, "train_step": 0.6}
    breakdown = goodput_breakdown(spans, 1.0)
    assert breakdown["input_starvation"] == pytest.approx(0.25)
