"""Trainer.fit event emission + the bench driver's JSON-line contract.

The smoke test is the acceptance gate for the obs subsystem: two epochs of a
tiny SASRec through ``fit`` with a ``JsonlLogger`` must produce the full event
sequence with finite loss/throughput, exactly ONE train-step compile across
both epochs (the static-shapes invariant, now observable), and ``bench.py``
must still print its single JSON line with the additive observability fields.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from replay_tpu.data import FeatureHint, FeatureType
from replay_tpu.data.nn import TensorFeatureInfo, TensorSchema
from replay_tpu.nn import OptimizerFactory, Trainer, make_mesh
from replay_tpu.nn.loss import CE
from replay_tpu.nn.sequential.sasrec import SasRec
from replay_tpu.obs import JsonlLogger

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

NUM_ITEMS = 12
SEQ_LEN = 8
BATCH = 8  # divisible by the 8-device data axis


def _run_dir(tmp_path, name):
    """CI exports REPLAY_TPU_RUN_DIR so the smoke run's telemetry ships as a
    workflow artifact; locally the run log lands in tmp_path."""
    base = os.environ.get("REPLAY_TPU_RUN_DIR")
    return os.path.join(base, name) if base else str(tmp_path / name)


def _make_batch(rng):
    items = rng.integers(0, NUM_ITEMS, size=(BATCH, SEQ_LEN + 1)).astype(np.int32)
    mask = np.ones((BATCH, SEQ_LEN), dtype=bool)
    return {
        "feature_tensors": {"item_id": items[:, :-1]},
        "padding_mask": mask,
        "positive_labels": items[:, 1:, None],
        "target_padding_mask": mask[:, :, None],
    }


@pytest.mark.jax
@pytest.mark.smoke
def test_fit_event_stream_single_compile(tmp_path):
    schema = TensorSchema(
        TensorFeatureInfo(
            "item_id",
            FeatureType.CATEGORICAL,
            is_seq=True,
            feature_hint=FeatureHint.ITEM_ID,
            cardinality=NUM_ITEMS,
            embedding_dim=16,
        )
    )
    model = SasRec(schema=schema, embedding_dim=16, num_blocks=1, num_heads=1,
                   max_sequence_length=SEQ_LEN)
    trainer = Trainer(
        model=model,
        loss=CE(),
        optimizer=OptimizerFactory(name="adam", learning_rate=1e-2),
        mesh=make_mesh(),
    )
    rng = np.random.default_rng(0)
    batches = [_make_batch(rng) for _ in range(3)]

    def val_batches():
        batch = dict(batches[0])
        batch["ground_truth"] = batches[0]["positive_labels"][:, -1, :].astype(np.int32)
        return [batch]

    # mode="w": REPLAY_TPU_RUN_DIR is a fixed path — a re-run in the same
    # workspace must not append a second event stream and fail the counts
    run_dir = _run_dir(tmp_path, "fit_smoke")
    with JsonlLogger(run_dir, mode="w") as sink:
        trainer.fit(
            lambda: iter(batches),
            epochs=2,
            loggers=sink,
            val_batches=val_batches,
            metrics=("ndcg",),
            top_k=(5,),
        )

    lines = [json.loads(line) for line in open(os.path.join(run_dir, "events.jsonl"))]
    names = [line["event"] for line in lines]
    assert names[0] == "on_fit_start" and names[-1] == "on_fit_end"
    assert names.count("on_validation_end") == 2 and names.count("on_epoch_end") == 2
    steps = [line for line in lines if line["event"] == "on_train_step"]
    assert len(steps) == 6  # 3 batches x 2 epochs, one event per step
    for record in steps:
        assert np.isfinite(record["loss"])
        assert np.isfinite(record["samples_per_sec"]) and record["samples_per_sec"] > 0
        assert record["lr"] == pytest.approx(1e-2)
    assert [s["step"] for s in steps] == list(range(1, 7))
    # the validation record reaches the stream with the epoch's metrics
    val = [line for line in lines if line["event"] == "on_validation_end"]
    assert all("ndcg@5" in line["record"] for line in val)
    # static-shapes invariant: ONE compiled train step across both epochs
    assert trainer.compile_tracker.traces["train_step"] == 1
    fit_end = lines[-1]
    assert fit_end["compile"]["train_step"]["traces"] == 1
    assert fit_end["telemetry"]["steps"] == 5  # 6 ticks - 1 warmup
    assert np.isfinite(fit_end["telemetry"]["samples_per_sec"])


@pytest.mark.jax
def test_fit_sparse_cadence_reports_finite_telemetry(caplog):
    """log_every-only path, fit shorter than 2x the cadence: the epoch-boundary
    flush + warmup proration must still produce real steady-state numbers in
    the fit-end summary (not an all-NaN telemetry block)."""
    import logging

    schema = TensorSchema(
        TensorFeatureInfo(
            "item_id",
            FeatureType.CATEGORICAL,
            is_seq=True,
            feature_hint=FeatureHint.ITEM_ID,
            cardinality=NUM_ITEMS,
            embedding_dim=8,
        )
    )
    model = SasRec(schema=schema, embedding_dim=8, num_blocks=1, num_heads=1,
                   max_sequence_length=SEQ_LEN)
    trainer = Trainer(model=model, loss=CE(),
                      optimizer=OptimizerFactory(learning_rate=1e-2), mesh=make_mesh())
    rng = np.random.default_rng(1)
    batches = [_make_batch(rng) for _ in range(4)]
    with caplog.at_level(logging.INFO, logger="replay_tpu"):
        trainer.fit(lambda: iter(batches), epochs=1, log_every=3)
    fit_end = [r.getMessage() for r in caplog.records if "fit complete" in r.getMessage()]
    assert fit_end, caplog.records
    assert "'steps': 3.0" in fit_end[0]  # 4 steps - 1 warmup step (prorated)
    assert "nan" not in fit_end[0].split("'compile'")[0]  # telemetry is finite


@pytest.mark.jax
def test_fit_accepts_duck_typed_single_sink():
    """RunLogger is a protocol: a structurally-conforming sink that does not
    subclass it must be treated as ONE sink, not iterated as a sequence."""

    class Duck:
        def __init__(self):
            self.events = []

        def log_event(self, event):
            self.events.append(event)

    schema = TensorSchema(
        TensorFeatureInfo(
            "item_id",
            FeatureType.CATEGORICAL,
            is_seq=True,
            feature_hint=FeatureHint.ITEM_ID,
            cardinality=NUM_ITEMS,
            embedding_dim=8,
        )
    )
    model = SasRec(schema=schema, embedding_dim=8, num_blocks=1, num_heads=1,
                   max_sequence_length=SEQ_LEN)
    trainer = Trainer(model=model, loss=CE(),
                      optimizer=OptimizerFactory(learning_rate=1e-2), mesh=make_mesh())
    rng = np.random.default_rng(3)
    duck = Duck()
    trainer.fit(lambda: iter([_make_batch(rng), _make_batch(rng)]), epochs=1, loggers=duck)
    names = [e.event for e in duck.events]
    assert names[0] == "on_fit_start" and names[-1] == "on_fit_end"
    assert names.count("on_train_step") == 2


@pytest.mark.jax
def test_fit_lr_schedule_events_report_applied_rate(tmp_path):
    """The logged lr is the rate the optimizer applied: with linear warmup from
    0, the FIRST step's event must report 0.0 (optax indexes schedules by steps
    completed before the update)."""
    from replay_tpu.nn import LRSchedulerFactory

    schema = TensorSchema(
        TensorFeatureInfo(
            "item_id",
            FeatureType.CATEGORICAL,
            is_seq=True,
            feature_hint=FeatureHint.ITEM_ID,
            cardinality=NUM_ITEMS,
            embedding_dim=8,
        )
    )
    model = SasRec(schema=schema, embedding_dim=8, num_blocks=1, num_heads=1,
                   max_sequence_length=SEQ_LEN)
    trainer = Trainer(
        model=model,
        loss=CE(),
        optimizer=OptimizerFactory(
            learning_rate=1e-2,
            scheduler=LRSchedulerFactory(kind="warmup_linear", warmup_steps=4),
        ),
        mesh=make_mesh(),
    )
    rng = np.random.default_rng(2)
    batches = [_make_batch(rng) for _ in range(3)]
    run_dir = str(tmp_path / "lr_run")
    with JsonlLogger(run_dir) as sink:
        trainer.fit(lambda: iter(batches), epochs=1, loggers=sink)
    lines = [json.loads(line) for line in open(os.path.join(run_dir, "events.jsonl"))]
    lrs = [line["lr"] for line in lines if line["event"] == "on_train_step"]
    assert lrs[0] == pytest.approx(0.0)  # schedule(0), not schedule(1)
    assert lrs == sorted(lrs) and lrs[-1] > 0  # warming up


@pytest.mark.jax
@pytest.mark.smoke
def test_bench_json_line_carries_obs_fields(tmp_path):
    """bench.py (CPU-fallback import path, toy shapes) still prints exactly one
    JSON line; metric/value/vs_baseline schema unchanged, obs fields additive."""
    sidecar = os.path.join(REPO, "BENCH_TPU_SIDECAR.json")
    sidecar_before = open(sidecar).read() if os.path.exists(sidecar) else None
    env = {
        **os.environ,
        "REPLAY_TPU_BENCH_FALLBACK": "1",  # skip the backend health probe
        "REPLAY_TPU_BENCH_BATCH": "8",
        "REPLAY_TPU_BENCH_SEQ_LEN": "8",
        "REPLAY_TPU_BENCH_NUM_ITEMS": "64",
        "REPLAY_TPU_BENCH_EMBEDDING_DIM": "8",
        "REPLAY_TPU_BENCH_NUM_BLOCKS": "1",
        "REPLAY_TPU_BENCH_SCAN_K": "2",
        "JAX_PLATFORMS": "cpu",
    }
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True,
        text=True,
        timeout=240,
        env=env,
        cwd=REPO,
        check=False,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    payload = [line for line in proc.stdout.splitlines() if line.strip()]
    assert len(payload) == 1  # the driver contract: ONE JSON line on stdout
    record = json.loads(payload[0])
    assert record["metric"] == "sasrec_train_samples_per_sec_cpu_fallback"
    assert record["value"] > 0 and record["unit"] == "samples/sec"
    assert "vs_baseline" in record and "backend" in record
    # additive observability fields
    assert record["compile_seconds"] > 0
    assert "peak_memory_bytes" in record  # null on CPU, bytes on TPU
    assert record["shape_override"]["B"] == 8
    # a toy-shape run must never overwrite the real-silicon sidecar evidence
    sidecar_after = open(sidecar).read() if os.path.exists(sidecar) else None
    assert sidecar_after == sidecar_before
