"""Pallas fused attention == unfused reference (interpret mode on CPU)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from replay_tpu.ops import flash_attention

pytestmark = pytest.mark.jax

B, H, L, D = 2, 2, 16, 8


def reference(q, k, v, bias):
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D) + bias
    return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(scores, axis=-1), v)


def test_matches_unfused():
    rng = np.random.default_rng(0)
    q, k, v = (jnp.asarray(rng.normal(size=(B, H, L, D)).astype(np.float32)) for _ in range(3))
    causal = jnp.where(jnp.tril(jnp.ones((L, L), bool)), 0.0, -1e30)[None, None]
    got = flash_attention(q, k, v, causal, interpret=True)
    want = reference(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-6)


def test_padding_rows_stay_finite():
    rng = np.random.default_rng(1)
    q, k, v = (jnp.asarray(rng.normal(size=(B, H, L, D)).astype(np.float32)) for _ in range(3))
    bias = jnp.full((B, 1, L, L), -1e30)  # everything masked
    out = flash_attention(q, k, v, bias, interpret=True)
    assert bool(jnp.isfinite(out).all())


def test_mha_flash_matches_unfused():
    import flax.linen as nn_  # noqa: F401
    from replay_tpu.nn.attention import MultiHeadAttention
    from replay_tpu.nn.mask import causal_attention_mask

    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(2, L, 16)).astype(np.float32))
    mask = causal_attention_mask(jnp.ones((2, L), bool), deterministic=True)
    plain = MultiHeadAttention(num_heads=2)
    flash = MultiHeadAttention(num_heads=2, use_flash=True)
    params = plain.init(jax.random.PRNGKey(0), x, mask)
    out_plain = plain.apply(params, x, mask)
    out_flash = flash.apply(params, x, mask)
    np.testing.assert_allclose(np.asarray(out_flash), np.asarray(out_plain),
                               rtol=2e-5, atol=2e-6)


def test_gradients_match_unfused():
    """The custom VJP (rematerialized backward) must equal autodiff through the
    unfused path — use_flash=True is trainable."""
    rng = np.random.default_rng(3)
    q, k, v = (jnp.asarray(rng.normal(size=(B, H, L, D)).astype(np.float32)) for _ in range(3))
    bias = jnp.broadcast_to(
        jnp.where(jnp.tril(jnp.ones((L, L), bool)), 0.0, -1e30)[None, None], (B, 1, L, L)
    )

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, bias, True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference(q, k, v, bias) ** 2)

    got = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    want = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(got, want):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5)
