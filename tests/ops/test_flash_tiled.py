"""Tiled flash attention: parity with full attention at every shape class the
single-block kernel cannot reach (interpret mode — no TPU needed)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from replay_tpu.ops.flash_tiled import (
    NEG_INF,
    flash_attention_tiled,
    padding_mask_bias,
)

pytestmark = pytest.mark.jax


def reference(q, k, v, padding_mask, causal):
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    s = jnp.where(padding_mask[:, None, None, :], s, NEG_INF)
    if causal:
        length = q.shape[2]
        tri = np.tril(np.ones((length, length), bool))
        s = jnp.where(tri[None, None], s, NEG_INF)
    probs = jax.nn.softmax(s, axis=-1)
    # rows with no valid key: define output 0 (the kernel's convention)
    dead = jnp.max(s, axis=-1, keepdims=True) <= NEG_INF / 2
    probs = jnp.where(dead, 0.0, probs)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize(
    "batch,heads,length,dim,block",
    [
        (2, 2, 16, 8, 8),     # multiple blocks, exact division
        (1, 1, 23, 8, 8),     # ragged: L % block != 0
        (2, 1, 7, 4, 16),     # single block bigger than L
        (1, 2, 65, 16, 32),   # ragged again, larger dim
    ],
)
def test_matches_reference(batch, heads, length, dim, block, causal):
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(batch, heads, length, dim)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(batch, heads, length, dim)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(batch, heads, length, dim)).astype(np.float32))
    lengths = rng.integers(1, length + 1, batch)
    padding_mask = jnp.asarray(np.arange(length)[None, :] < lengths[:, None])
    got = flash_attention_tiled(
        q, k, v, padding_mask_bias(padding_mask), causal, block, block, True
    )
    want = reference(q, k, v, padding_mask, causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_all_padded_batch_row_is_zero_and_finite():
    q = jnp.ones((1, 1, 8, 4), jnp.float32)
    mask = jnp.zeros((1, 8), bool)  # nothing valid
    out = flash_attention_tiled(q, q, q, padding_mask_bias(mask), True, 4, 4, True)
    assert np.isfinite(np.asarray(out)).all()
    np.testing.assert_array_equal(np.asarray(out), 0.0)


@pytest.mark.parametrize("causal", [True, False])
def test_gradients_match_reference(causal):
    rng = np.random.default_rng(1)
    batch, heads, length, dim, block = 2, 2, 19, 8, 8
    q = jnp.asarray(rng.normal(size=(batch, heads, length, dim)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(batch, heads, length, dim)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(batch, heads, length, dim)).astype(np.float32))
    lengths = rng.integers(2, length + 1, batch)
    padding_mask = jnp.asarray(np.arange(length)[None, :] < lengths[:, None])
    bias = padding_mask_bias(padding_mask)

    def tiled_loss(q, k, v, bias):
        out = flash_attention_tiled(q, k, v, bias, causal, block, block, True)
        return jnp.sum(out**2)

    def ref_loss(q, k, v, bias):
        scale = 1.0 / np.sqrt(dim)
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale + bias[:, None, None, :]
        if causal:
            tri = np.tril(np.ones((length, length), bool))
            s = jnp.where(tri[None, None], s, NEG_INF)
        probs = jax.nn.softmax(s, axis=-1)
        out = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
        return jnp.sum(out**2)

    # dbias included: the kv_bias cotangent is part of the custom VJP
    got = jax.grad(tiled_loss, argnums=(0, 1, 2, 3))(q, k, v, bias)
    want = jax.grad(ref_loss, argnums=(0, 1, 2, 3))(q, k, v, bias)
    for g, w, name in zip(got, want, ["q", "k", "v", "bias"]):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(w), rtol=2e-4, atol=2e-5, err_msg=name
        )


def test_long_sequence_runs_blockwise():
    """L=2048 — the single-block kernel's OOM regime — streams through
    fixed-size blocks (interpret mode checks indexing, not memory)."""
    rng = np.random.default_rng(2)
    q = jnp.asarray(rng.normal(size=(1, 1, 2048, 8)).astype(np.float32))
    mask = jnp.ones((1, 2048), bool)
    out = flash_attention_tiled(q, q, q, padding_mask_bias(mask), True, 256, 256, True)
    assert out.shape == (1, 1, 2048, 8)
    # causal row 0 attends only to itself: output == v[0]
    np.testing.assert_allclose(
        np.asarray(out[0, 0, 0]), np.asarray(q[0, 0, 0]), rtol=1e-5
    )


@pytest.mark.parametrize("model_kind", ["sasrec", "bert4rec", "twotower"])
def test_model_tiled_route_matches_default(model_kind):
    """use_flash='tiled' through the REAL model API (mask never materialized)
    equals the default path on real rows — the production long-L entry point."""
    from replay_tpu.data import FeatureHint, FeatureType
    from replay_tpu.data.nn import TensorFeatureInfo, TensorSchema
    from replay_tpu.nn.sequential.bert4rec import Bert4Rec
    from replay_tpu.nn.sequential.sasrec import SasRec
    from replay_tpu.nn.sequential.twotower import TwoTower

    num_items, seq_len = 12, 10
    schema = TensorSchema(TensorFeatureInfo(
        "item_id", FeatureType.CATEGORICAL, is_seq=True,
        feature_hint=FeatureHint.ITEM_ID, cardinality=num_items, embedding_dim=8))
    cls = {"sasrec": SasRec, "bert4rec": Bert4Rec, "twotower": TwoTower}[model_kind]
    kwargs = dict(schema=schema, embedding_dim=8, num_blocks=2, num_heads=2,
                  max_sequence_length=seq_len)
    plain = cls(**kwargs)
    tiled = cls(**kwargs, use_flash="tiled")

    rng = np.random.default_rng(0)
    ids = np.full((3, seq_len), num_items, np.int32)
    lengths = rng.integers(2, seq_len + 1, 3)
    for b, n in enumerate(lengths):
        ids[b, seq_len - n:] = rng.integers(0, num_items, n)
    mask = ids != num_items
    params = plain.init(jax.random.PRNGKey(0), {"item_id": ids}, mask)["params"]

    want = plain.apply({"params": params}, {"item_id": ids}, mask)
    got = tiled.apply({"params": params}, {"item_id": ids}, mask)
    # padded rows differ only by the diagonal-rescue convention and are zeroed
    # by the keep-mask between blocks; real rows must match
    np.testing.assert_allclose(
        np.asarray(got)[np.asarray(mask)], np.asarray(want)[np.asarray(mask)],
        rtol=2e-5, atol=2e-5,
    )


@pytest.mark.parametrize("flash", [False, "tiled"])
def test_remat_trains_with_each_attention_route(flash):
    """remat=True (jax.checkpoint over blocks, static_argnums covering the
    deterministic + causal flags) trains through both attention routes —
    previously uncovered."""
    from replay_tpu.data import FeatureHint, FeatureType
    from replay_tpu.data.nn import TensorFeatureInfo, TensorSchema
    from replay_tpu.nn import OptimizerFactory, Trainer
    from replay_tpu.nn.loss import CE
    from replay_tpu.nn.sequential.sasrec import SasRec

    schema = TensorSchema(TensorFeatureInfo(
        "item_id", FeatureType.CATEGORICAL, is_seq=True,
        feature_hint=FeatureHint.ITEM_ID, cardinality=12, embedding_dim=8))
    model = SasRec(schema=schema, embedding_dim=8, num_blocks=1,
                   max_sequence_length=6, remat=True, use_flash=flash)
    trainer = Trainer(model=model, loss=CE(),
                      optimizer=OptimizerFactory(name="sgd", learning_rate=0.1))
    rng = np.random.default_rng(0)
    items = rng.integers(0, 12, (4, 7)).astype(np.int32)
    mask = np.ones((4, 6), bool)
    batch = {"feature_tensors": {"item_id": items[:, :-1]}, "padding_mask": mask,
             "positive_labels": items[:, 1:, None], "target_padding_mask": mask[:, :, None]}
    state = trainer.init_state(batch)
    losses = []
    for _ in range(4):
        state, loss_value = trainer.train_step(state, batch)
        losses.append(float(loss_value))
    assert losses[-1] < losses[0]


def test_tiled_misuse_guards():
    """Silent-misconfiguration guards: diff encoder + tiled raises at init,
    and a custom additive mask cannot be silently dropped."""
    from replay_tpu.data import FeatureHint, FeatureType
    from replay_tpu.data.nn import TensorFeatureInfo, TensorSchema
    from replay_tpu.nn.attention import dot_product_attention
    from replay_tpu.nn.sequential.sasrec import SasRec

    schema = TensorSchema(TensorFeatureInfo(
        "item_id", FeatureType.CATEGORICAL, is_seq=True,
        feature_hint=FeatureHint.ITEM_ID, cardinality=8, embedding_dim=8))
    model = SasRec(schema=schema, embedding_dim=8, num_blocks=1,
                   max_sequence_length=4, encoder_type="diff", use_flash="tiled")
    with pytest.raises(ValueError, match="tiled"):
        model.init(jax.random.PRNGKey(0), {"item_id": np.zeros((1, 4), np.int32)},
                   np.ones((1, 4), bool))

    q = jnp.ones((1, 1, 4, 4), jnp.float32)
    with pytest.raises(ValueError, match="padding_mask"):
        dot_product_attention(q, q, q, None, use_flash="tiled")
    with pytest.raises(ValueError, match="additive mask"):
        dot_product_attention(q, q, q, jnp.zeros((1, 1, 4, 4)), use_flash="tiled",
                              padding_mask=jnp.ones((1, 4), bool))

