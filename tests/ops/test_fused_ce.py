"""Pallas fused catalog logsumexp == plain jnp (interpret mode on CPU)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from replay_tpu.ops.fused_ce import fused_lse

pytestmark = pytest.mark.jax


@pytest.fixture
def data():
    rng = np.random.default_rng(0)
    h = jnp.asarray(rng.standard_normal((300, 64)), jnp.float32)  # N not a tile multiple
    w = jnp.asarray(rng.standard_normal((1000, 64)), jnp.float32)  # I not a lane multiple
    return h, w


def test_forward_matches_logsumexp(data):
    h, w = data
    want = jax.nn.logsumexp(h @ w.T, axis=-1)
    got = fused_lse(h, w, 128, None, True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_gradients_match(data):
    h, w = data
    g = jnp.asarray(np.random.default_rng(1).standard_normal(h.shape[0]), jnp.float32)

    def ref(h, w):
        return jnp.sum(jax.nn.logsumexp(h @ w.T, axis=-1) * g)

    def fused(h, w):
        return jnp.sum(fused_lse(h, w, 128, None, True) * g)

    ref_dh, ref_dw = jax.grad(ref, argnums=(0, 1))(h, w)
    got_dh, got_dw = jax.grad(fused, argnums=(0, 1))(h, w)
    np.testing.assert_allclose(np.asarray(got_dh), np.asarray(ref_dh), rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(np.asarray(got_dw), np.asarray(ref_dw), rtol=2e-4, atol=2e-5)


def test_bf16_inputs_accumulate_in_f32(data):
    h, w = data
    got = fused_lse(h.astype(jnp.bfloat16), w.astype(jnp.bfloat16), 128, None, True)
    want = jax.nn.logsumexp(
        h.astype(jnp.bfloat16).astype(jnp.float32) @ w.astype(jnp.bfloat16).astype(jnp.float32).T,
        axis=-1,
    )
    assert got.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-3, atol=2e-3)


def test_item_tiling_matches_single_tile(data):
    """Catalog swept in multiple tiles (online max/sum) == one-tile answer."""
    h, w = data
    g = jnp.asarray(np.random.default_rng(2).standard_normal(h.shape[0]), jnp.float32)
    want = jax.nn.logsumexp(h @ w.T, axis=-1)
    got = fused_lse(h, w, 128, 256, True)  # 1000 items -> 4 catalog tiles
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)

    def ref(h, w):
        return jnp.sum(jax.nn.logsumexp(h @ w.T, axis=-1) * g)

    def fused(h, w):
        return jnp.sum(fused_lse(h, w, 128, 256, True) * g)

    ref_dh, ref_dw = jax.grad(ref, argnums=(0, 1))(h, w)
    got_dh, got_dw = jax.grad(fused, argnums=(0, 1))(h, w)
    np.testing.assert_allclose(np.asarray(got_dh), np.asarray(ref_dh), rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(np.asarray(got_dw), np.asarray(ref_dw), rtol=2e-4, atol=2e-5)


def test_single_row_and_tiny_catalog():
    h = jnp.ones((1, 8), jnp.float32)
    w = jnp.ones((3, 8), jnp.float32)
    got = fused_lse(h, w, 8, None, True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(jax.nn.logsumexp(h @ w.T, -1)), rtol=1e-5)


@pytest.mark.smoke
def test_cefused_trains_identically_to_ce():
    """CEFused through the Trainer matches CE step losses (shared seed)."""
    from replay_tpu.data import FeatureHint, FeatureType
    from replay_tpu.data.nn import TensorFeatureInfo, TensorSchema
    from replay_tpu.nn import Trainer
    from replay_tpu.nn.loss import CE, CEFused
    from replay_tpu.nn.sequential.sasrec import SasRec

    n_items, length, batch_size = 50, 8, 4
    schema = TensorSchema(
        TensorFeatureInfo(
            "item_id",
            FeatureType.CATEGORICAL,
            is_seq=True,
            feature_hint=FeatureHint.ITEM_ID,
            cardinality=n_items,
            embedding_dim=16,
        )
    )
    rng = np.random.default_rng(0)
    items = rng.integers(0, n_items, size=(batch_size, length + 1)).astype(np.int32)
    batch = {
        "feature_tensors": {"item_id": items[:, :-1]},
        "padding_mask": np.ones((batch_size, length), bool),
        "positive_labels": items[:, 1:, None],
        "target_padding_mask": np.ones((batch_size, length, 1), bool),
    }

    def run(loss):
        model = SasRec(
            schema=schema, embedding_dim=16, num_blocks=1, num_heads=1,
            max_sequence_length=length, dropout_rate=0.0,
        )
        trainer = Trainer(model=model, loss=loss)
        state = trainer.init_state(batch)
        losses = []
        for _ in range(3):
            state, value = trainer.train_step(state, batch)
            losses.append(float(value))
        return losses

    plain, fused = run(CE()), run(CEFused(tile=8))
    np.testing.assert_allclose(fused, plain, rtol=1e-4)
    assert fused[-1] < fused[0]  # and it actually learns


def test_vmem_guard_shrinks_item_tile(caplog):
    """The [row_tile, item_tile] working set is budgeted UP FRONT: a config
    that would blow the Mosaic VMEM limit at compile time (the round-3 16 MB
    bwd-kernel incident: tile=256 x item_tile=4096 at d=300) auto-shrinks the
    item tile lane-aligned, with one warning recording the decision."""
    import logging

    from replay_tpu.ops.fused_ce import (
        _LANE,
        _VMEM_BUDGET_BYTES,
        _resolve_item_tile,
        _shrink_warned,
        _working_set_bytes,
    )

    _shrink_warned.clear()
    with caplog.at_level(logging.WARNING, logger="replay_tpu"):
        shrunk = _resolve_item_tile(1_000_000, None, 256, 300)
    assert shrunk < 4096
    assert shrunk % _LANE == 0
    assert _working_set_bytes(256, shrunk, 300) <= _VMEM_BUDGET_BYTES
    warnings = [r for r in caplog.records if "item_tile" in r.getMessage()]
    assert len(warnings) == 1
    # the same configuration warns ONCE, not once per trace
    with caplog.at_level(logging.WARNING, logger="replay_tpu"):
        assert _resolve_item_tile(1_000_000, None, 256, 300) == shrunk
    assert len([r for r in caplog.records if "item_tile" in r.getMessage()]) == 1


def test_vmem_guard_keeps_small_configs_unchanged():
    """The bench/test shapes that fit must resolve exactly as before."""
    from replay_tpu.ops.fused_ce import _resolve_item_tile

    assert _resolve_item_tile(1000, None, 128, 64) == 1024  # lane-padded catalog
    assert _resolve_item_tile(27278, None, 256, 64) == 4096  # the default tile
    assert _resolve_item_tile(1000, 256, 128, 64) == 256  # explicit, in budget


def test_vmem_guard_shrinks_explicit_item_tile(caplog):
    """An explicit item_tile beyond budget shrinks too — the guard exists to
    prevent the compile-time failure, not to trust the caller."""
    import logging

    from replay_tpu.ops.fused_ce import _resolve_item_tile, _shrink_warned

    _shrink_warned.clear()
    with caplog.at_level(logging.WARNING, logger="replay_tpu"):
        shrunk = _resolve_item_tile(1_000_000, 16384, 512, 512)
    assert shrunk < 16384


def test_cefused_refuses_non_tying_head_model():
    """A model without the bias-free-head declaration cannot bind CEFused —
    it would silently train with a different loss than CE (advisor r3)."""
    import flax.linen as nn

    from replay_tpu.nn import Trainer
    from replay_tpu.nn.loss import CEFused

    class BiasedHead(nn.Module):
        # exposes get_item_weights but get_logits is NOT plain h . W^T
        def __call__(self, feature_tensors, padding_mask):
            return jnp.zeros((1, 4, 8))

        def get_logits(self, hidden, candidates_to_score=None):
            return jnp.zeros((1, 4, 10))

        def get_item_weights(self):
            return jnp.zeros((10, 8))

    trainer = Trainer(model=BiasedHead(), loss=CEFused())
    with pytest.raises(ValueError, match="logits_via_item_weights"):
        trainer._build_train_step()
