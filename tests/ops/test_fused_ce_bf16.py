"""bf16-compute parity for the fused-CE head (the precision ladder's rung 1).

The sanctioned split the ``CEFused`` dtype check names: bf16 hidden states
against the f32 master table, accumulated in f32 inside the kernel. These
tests pin the two claims separately:

* **exactness of the kernel on bf16 inputs** — on the SAME (bf16-rounded,
  then upcast) inputs, the fused logsumexp and its gradients match the plain
  jnp reference tightly: the kernel's f32 accumulation loses nothing beyond
  the input rounding itself.
* **documented tolerance vs the f32 run** — against the UNROUNDED f32 inputs
  the gap is the bf16 input-rounding band: bf16 carries 8 mantissa bits, so
  values round within 2^-8 ≈ 4e-3 relative; forward lse and gradients are
  gated at rtol 2e-2 (a few rounding units through the dot products), far
  inside the PARITY_REPORT-style fit gate.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from replay_tpu.nn.loss import CEFused
from replay_tpu.ops.fused_ce import fused_lse

pytestmark = pytest.mark.jax

N, E, ITEMS = 24, 16, 53


@pytest.fixture(scope="module")
def inputs():
    rng = np.random.default_rng(0)
    hidden = jnp.asarray(rng.normal(size=(N, E)).astype(np.float32))
    table = jnp.asarray(rng.normal(size=(ITEMS, E)).astype(np.float32))
    return hidden, table


def reference_lse_loss(hidden, table):
    # promote exactly like the kernel: f32 logits, f32 logsumexp
    logits = hidden.astype(jnp.float32) @ table.astype(jnp.float32).T
    return jnp.sum(jax.nn.logsumexp(logits, axis=-1))


@pytest.mark.smoke
def test_bf16_hidden_fwd_and_grad_match_reference_exactly(inputs):
    """On identical bf16-rounded inputs, kernel == jnp reference to f32
    accumulation noise (fwd AND both gradients): the kernel's internal f32
    math is the same math the einsum promotion does."""
    hidden, table = inputs
    hidden_bf16 = hidden.astype(jnp.bfloat16)

    def fused_loss(h, w):
        return jnp.sum(fused_lse(h, w, tile=8, item_tile=None, interpret=True))

    value, grads = jax.value_and_grad(fused_loss, argnums=(0, 1))(hidden_bf16, table)
    ref_value, ref_grads = jax.value_and_grad(reference_lse_loss, argnums=(0, 1))(
        hidden_bf16, table
    )
    np.testing.assert_allclose(float(value), float(ref_value), rtol=1e-5)
    # dh comes back in the hidden dtype (bf16): compare in f32 against the
    # reference's dh, itself cast back to bf16 by jax's autodiff convention
    assert grads[0].dtype == jnp.bfloat16 and ref_grads[0].dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(grads[0], np.float32), np.asarray(ref_grads[0], np.float32),
        rtol=1e-2, atol=1e-3,  # ONE terminal bf16 rounding each side
    )
    assert grads[1].dtype == jnp.float32
    np.testing.assert_allclose(
        np.asarray(grads[1]), np.asarray(ref_grads[1]), rtol=1e-4, atol=1e-5
    )


def test_bf16_vs_f32_within_documented_tolerance(inputs):
    """Against the unrounded f32 inputs the gap is the bf16 input-rounding
    band — the documented rtol 2e-2 the fit-level gates build on."""
    hidden, table = inputs

    def fused_loss(h, w):
        return jnp.sum(fused_lse(h, w, tile=8, item_tile=None, interpret=True))

    value_f32, grads_f32 = jax.value_and_grad(fused_loss, argnums=(0, 1))(hidden, table)
    value_bf16, grads_bf16 = jax.value_and_grad(fused_loss, argnums=(0, 1))(
        hidden.astype(jnp.bfloat16), table
    )
    np.testing.assert_allclose(float(value_bf16), float(value_f32), rtol=2e-2)
    np.testing.assert_allclose(
        np.asarray(grads_bf16[0], np.float32), np.asarray(grads_f32[0]),
        rtol=5e-2, atol=5e-2,
    )
    np.testing.assert_allclose(
        np.asarray(grads_bf16[1]), np.asarray(grads_f32[1]), rtol=5e-2, atol=5e-2
    )


def test_cefused_loss_bf16_compute_parity(inputs):
    """The full CEFused loss (lse + label-logit term) under the sanctioned
    split: bf16 hidden vs f32 table agrees with the f32 run within the bf16
    band, fwd and grad — the loss-level half of the ops gate."""
    hidden, table = inputs
    rng = np.random.default_rng(1)
    labels = jnp.asarray(rng.integers(0, ITEMS, size=(4, 6, 1)).astype(np.int32))
    mask = jnp.ones((4, 6), bool)
    tmask = jnp.ones((4, 6, 1), bool)

    def loss_of(h3, w):
        loss = CEFused(tile=8, interpret=True)
        loss.item_embeddings_callback = lambda: w
        return loss(h3, {}, labels, None, mask, tmask)

    hidden3 = hidden.reshape(4, 6, E)
    value_f32, grad_f32 = jax.value_and_grad(loss_of, argnums=1)(hidden3, table)
    value_bf16, grad_bf16 = jax.value_and_grad(loss_of, argnums=1)(
        hidden3.astype(jnp.bfloat16), table
    )
    assert value_bf16.dtype == jnp.float32  # f32 accumulation, not bf16
    np.testing.assert_allclose(float(value_bf16), float(value_f32), rtol=2e-2)
    np.testing.assert_allclose(
        np.asarray(grad_bf16), np.asarray(grad_f32), rtol=5e-2, atol=5e-2
    )


def test_error_message_names_the_sanctioned_split():
    """The dtype-mismatch rejection must NAME the bf16-compute/f32-param
    split (and point int8 at the serving rung) so the fix is in the error."""
    loss = CEFused(tile=8)
    loss.item_embeddings_callback = lambda: jnp.zeros((ITEMS, E), jnp.float16)
    args = (
        jnp.zeros((2, 4, E), jnp.bfloat16), {}, jnp.zeros((2, 4, 1), jnp.int32),
        None, jnp.ones((2, 4), bool), jnp.ones((2, 4, 1), bool),
    )
    with pytest.raises(ValueError, match="bfloat16.*float32 master"):
        loss(*args)
    # an int8 table is pointed at the serving ladder rung, not papered over
    loss.item_embeddings_callback = lambda: jnp.zeros((ITEMS, E), jnp.int8)
    with pytest.raises(ValueError, match="serve.quant"):
        loss(*args)
