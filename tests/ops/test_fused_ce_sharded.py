"""TP vocab-sharded fused logsumexp == replicated fused_lse == plain jnp CE.

The sharded head (replay_tpu.parallel.sharded_ce) splits the item table
``[I/n_tp, E]`` per device over the mesh's ``model`` axis, runs the tile-wise
online max/sum per shard and combines with a psum-style two-pass reduction
inside ``shard_map``; the backward psums ``dh`` across shards and keeps ``dW``
shard-local. Parity is checked fwd + grads on the virtual 8-device CPU mesh
(DP×TP), including a catalog NOT divisible by ``n_tp`` (shard padding masked
inside the kernel) and a shard spanning several catalog tiles.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from replay_tpu.ops.fused_ce import fused_lse
from replay_tpu.parallel import sharded_fused_lse

pytestmark = pytest.mark.jax


def make_mesh(data: int, model: int) -> Mesh:
    devices = np.array(jax.devices()[: data * model]).reshape(data, model)
    return Mesh(devices, ("data", "model"))


def make_data(n, items, embed, seed=0):
    rng = np.random.default_rng(seed)
    h = jnp.asarray(rng.standard_normal((n, embed)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((items, embed)), jnp.float32)
    g = jnp.asarray(rng.standard_normal(n), jnp.float32)
    return h, w, g


def assert_parity(mesh, h, w, g, item_tile=None, data_axis="data"):
    """Sharded fwd/grads vs replicated fused_lse vs plain jnp logsumexp."""
    want = jax.nn.logsumexp(h @ w.T, axis=-1)
    replicated = fused_lse(h, w, 8, item_tile, True)
    got = sharded_fused_lse(
        h, w, mesh, data_axis=data_axis, tile=8, item_tile=item_tile, interpret=True
    )
    np.testing.assert_allclose(np.asarray(replicated), np.asarray(want), rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)

    def ref(h, w):
        return jnp.sum(jax.nn.logsumexp(h @ w.T, axis=-1) * g)

    def sharded(h, w):
        return jnp.sum(
            sharded_fused_lse(
                h, w, mesh, data_axis=data_axis, tile=8, item_tile=item_tile,
                interpret=True,
            )
            * g
        )

    ref_dh, ref_dw = jax.grad(ref, argnums=(0, 1))(h, w)
    got_dh, got_dw = jax.grad(sharded, argnums=(0, 1))(h, w)
    np.testing.assert_allclose(np.asarray(got_dh), np.asarray(ref_dh), rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(np.asarray(got_dw), np.asarray(ref_dw), rtol=2e-4, atol=2e-5)


@pytest.mark.smoke
def test_sharded_matches_replicated_and_jnp_dp_tp():
    """4×2 DP×TP mesh, catalog divisible by n_tp: exact-shape sharding."""
    h, w, g = make_data(32, 64, 16)
    assert_parity(make_mesh(4, 2), h, w, g)


@pytest.mark.smoke
def test_non_divisible_catalog_padding_masked():
    """37 items over n_tp=2: the padded shard tail must contribute exactly
    nothing to the softmax — forward AND both gradients."""
    h, w, g = make_data(16, 37, 8, seed=1)
    assert_parity(make_mesh(4, 2), h, w, g)


def test_multi_tile_shard():
    """Each 300-row shard sweeps several 128-column catalog tiles: the online
    max/sum inside a shard composes with the cross-shard combine."""
    h, w, g = make_data(16, 600, 8, seed=2)
    assert_parity(make_mesh(4, 2), h, w, g, item_tile=128)


def test_mostly_empty_shards():
    """A 5-item catalog over 8 shards: shards past the catalog are ENTIRELY
    padding and must yield a ~-1e30 local lse (finite — the kernel's mask is
    not -inf exactly so this case cannot NaN) that vanishes in the combine."""
    h, w, g = make_data(8, 5, 8, seed=3)
    assert_parity(make_mesh(1, 8), h, w, g)


def test_rows_replicated_without_data_axis():
    """data_axis=None replicates the rows over the shard groups (pure-TP
    call sites); values still match the replicated kernel."""
    h, w, g = make_data(12, 37, 8, seed=4)
    assert_parity(make_mesh(4, 2), h, w, g, data_axis=None)


def test_rejects_missing_axes():
    h, w, _ = make_data(8, 16, 8)
    mesh = make_mesh(4, 2)
    with pytest.raises(ValueError, match="no 'tp' axis"):
        sharded_fused_lse(h, w, mesh, axis_name="tp", interpret=True)
    with pytest.raises(ValueError, match="do not divide"):
        sharded_fused_lse(h[:3], w, mesh, interpret=True)


def test_num_valid_masks_table_tail():
    """The kernel-level seam the sharded wrapper relies on: a traced
    num_valid < table rows masks the tail out of the softmax."""
    h, w, _ = make_data(8, 24, 8, seed=5)
    want = jax.nn.logsumexp(h @ w[:17].T, axis=-1)
    got = fused_lse(h, w, 8, None, True, num_valid=jnp.int32(17))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)
