"""Worker for the launcher-forensics tests (tests/parallel/test_launch_artifacts.py).

Stdlib-only (no jax import, no collectives): argv is ``<rank> <coordinator>
<behavior>``. Every rank records a few events into the flight ring the
launcher handed it via ``REPLAY_TPU_FLIGHT_PATH`` (loading ``blackbox.py``
by file path, the same trick as tests/obs/flight_kill_worker.py), then:

* ``ok``      — prints a line and exits 0;
* ``fail``    — prints to both spools and exits 3;
* ``sigkill`` — dies by real ``kill -9`` mid-run, no flush, no close.
"""

import importlib.util
import os
import signal
import sys
from pathlib import Path

_BLACKBOX = Path(__file__).resolve().parents[2] / "replay_tpu" / "obs" / "blackbox.py"


def main() -> None:
    rank, _coordinator, behavior = sys.argv[1], sys.argv[2], sys.argv[3]

    spec = importlib.util.spec_from_file_location("blackbox", _BLACKBOX)
    blackbox = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = blackbox
    spec.loader.exec_module(blackbox)

    ring_path = os.environ.get(blackbox.FLIGHT_PATH_ENV)
    recorder = None
    if ring_path:
        recorder = blackbox.FlightRecorder(ring_path, capacity=32)
        for step in range(4):
            recorder.record({"event": "on_train_step", "step": step, "rank": int(rank)})

    print(f"rank {rank} stdout line", flush=True)
    if behavior == "fail":
        print(f"rank {rank} exploding", file=sys.stderr, flush=True)
        sys.exit(3)
    if behavior == "sigkill":
        os.kill(os.getpid(), signal.SIGKILL)
    if recorder is not None:
        recorder.close()


if __name__ == "__main__":
    main()
