"""Worker for the 2-process shard_vocab checkpoint round-trip test.

One host of a 2-host job (4 virtual CPU devices each, gloo collectives) on a
``("data", "model")`` = (4, 2) global mesh with vocab-sharded embeddings.
Phase "first": init, train 3 steps, save through CheckpointManager (backend
auto-selects orbax under multi-host — npz would raise on the non-addressable
vocab shards). Phase "resume": fresh processes restore the checkpoint through
``Trainer.restore_checkpoint`` and train 3 more steps. The parent test asserts
first+resume losses == 6 uninterrupted steps — the multi-host analogue of the
reference's Lightning resume + ItemTower cache-shape validation
(/root/reference/replay/nn/sequential/twotower/model.py:173-193).
"""

import json
import sys
from pathlib import Path

import numpy as np


def main() -> None:
    rank = int(sys.argv[1])
    coordinator = sys.argv[2]
    out_path = sys.argv[3]
    ckpt_dir = sys.argv[4]
    phase = sys.argv[5]  # "first" | "resume"

    import jax as _jax

    try:
        _jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass  # older/newer jax may configure this via env instead

    from replay_tpu.parallel import initialize_distributed

    layout = initialize_distributed(
        coordinator_address=coordinator, num_processes=2, process_id=rank
    )
    assert layout["num_processes"] == 2, layout

    import jax

    from replay_tpu.data import FeatureHint, FeatureType
    from replay_tpu.data.nn import TensorFeatureInfo, TensorSchema
    from replay_tpu.nn import OptimizerFactory, Trainer, make_mesh
    from replay_tpu.nn.loss import CE
    from replay_tpu.nn.sequential.sasrec import SasRec
    from replay_tpu.utils.checkpoint import CheckpointManager

    # 15 items -> 16-row table (cardinality + padding row), divisible by model=2
    num_items, seq_len, global_batch = 15, 6, 8
    local = global_batch // 2
    schema = TensorSchema(
        TensorFeatureInfo("item_id", FeatureType.CATEGORICAL, is_seq=True,
                          feature_hint=FeatureHint.ITEM_ID, cardinality=num_items,
                          embedding_dim=16)
    )
    trainer = Trainer(
        model=SasRec(schema=schema, embedding_dim=16, num_blocks=1,
                     max_sequence_length=seq_len),
        loss=CE(),
        optimizer=OptimizerFactory(name="sgd", learning_rate=0.1),
        mesh=make_mesh(model_parallel=2),  # (data=4, model=2) over 8 devices
        shard_vocab=True,
        seed=0,
    )

    def global_batch_for(step: int) -> dict:
        rng = np.random.default_rng(step)  # same on every rank
        items = rng.integers(0, num_items, (global_batch, seq_len + 1)).astype(np.int32)
        mask = np.ones((global_batch, seq_len), bool)
        return {
            "feature_tensors": {"item_id": items[:, :-1]},
            "padding_mask": mask,
            "positive_labels": items[:, 1:, None],
            "target_padding_mask": mask[:, :, None],
        }

    def local_slice(batch: dict) -> dict:
        return {
            k: ({n: v[rank * local : (rank + 1) * local] for n, v in val.items()}
                if isinstance(val, dict)
                else val[rank * local : (rank + 1) * local])
            for k, val in batch.items()
        }

    manager = CheckpointManager(ckpt_dir)
    if phase == "first":
        state = trainer.init_state(local_slice(global_batch_for(0)))
        step_range = range(3)
    else:
        state = trainer.restore_checkpoint(
            str(Path(ckpt_dir) / "step_3"), local_slice(global_batch_for(0))
        )
        assert int(np.asarray(state.step)) == 3, state.step
        step_range = range(3, 6)

    # the vocab tables must actually be sharded over the model axis — otherwise
    # this test silently degrades to the replicated case
    vocab_specs = [
        str(leaf.sharding.spec)
        for path, leaf in jax.tree_util.tree_flatten_with_path(state.params)[0]
        if "embedding_" in jax.tree_util.keystr(path)
    ]
    assert any("model" in spec for spec in vocab_specs), vocab_specs

    losses = []
    for step in step_range:
        state, loss_value = trainer.train_step(state, local_slice(global_batch_for(step)))
        losses.append(float(loss_value))  # replicated output: locally fetchable

    if phase == "first":
        manager.save(3, state)
        assert manager.latest_step() == 3

    with open(out_path, "w") as handle:
        json.dump({"rank": rank, "phase": phase, "losses": losses}, handle)


if __name__ == "__main__":
    main()
