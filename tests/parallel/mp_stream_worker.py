"""Worker for the process-real streaming-fit tests: one rank of a 2-process
job running the 3-axis-mesh (DP×TP×SP) scan-chunked fit over the disjoint
row-group streaming reader, with hard-kill chaos and elastic resume.

Each rank streams ITS shard of a shared parquet file (``Partitioning`` over
``ReplicasInfo(2, rank)`` — the same plan the single-process tests prove
exactly-once), feeds a ring-attention SasRec on a ``(data=2, model=2,
seq=2)`` global mesh with vocab-sharded embeddings, and checkpoints through
a shared ``CheckpointManager`` (orbax under multi-host) with per-process
cursor sidecars.

Phases (argv: ``rank coordinator out_path parquet_path ckpt_dir phase
kill_at``):

* ``full``   — 2 epochs uninterrupted; the reference trajectory.
* ``kill``   — same fit, but the rank whose ``kill_at >= 0`` SIGKILLs its own
  process (``KillAtStep.fire``) after that many train-step events: no
  handler, no cleanup — recovery must come entirely from what is on disk.
* ``resume`` — ``fit(resume=True)`` on the killed run's checkpoint dir; the
  parent asserts the post-restore (step, loss) pairs match the ``full`` run
  EXACTLY (same f32 CPU programs -> bitwise-equal trajectory).

The coordinator handshake arrives via env (``REPLAY_TPU_COORDINATOR`` etc.,
published by ``replay_tpu.parallel.launch``); the argv coordinator is
accepted for symmetry with the older workers but not needed.
"""

import json
import sys
from pathlib import Path

import numpy as np

NUM_ITEMS = 31  # 32-row table divides the model axis
SEQ_LEN = 9  # next-token shift -> [B, 8] inputs; 8 % seq_parallel(2) == 0
LOCAL_BATCH = 4  # x2 processes = global 8, divisible by the data axis
EPOCHS = 2
CHECKPOINT_EVERY = 3
STREAM_SEED = 3


def main() -> None:
    rank = int(sys.argv[1])
    out_path = sys.argv[3]
    parquet_path = sys.argv[4]
    ckpt_dir = sys.argv[5]
    phase = sys.argv[6]  # "full" | "kill" | "resume"
    kill_at = int(sys.argv[7])  # SIGKILL self after this many step events; -1 = never

    import jax as _jax

    try:
        _jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass  # older/newer jax may configure this via env instead

    from replay_tpu.parallel import initialize_distributed

    layout = initialize_distributed()  # resolved from the launcher's env handshake
    assert layout["num_processes"] == 2, layout
    assert layout["process_id"] == rank, (layout, rank)

    from replay_tpu.data import FeatureHint, FeatureType
    from replay_tpu.data.nn import (
        ParquetBatcher,
        Partitioning,
        ReplicasInfo,
        TensorFeatureInfo,
        TensorSchema,
        TransformedBatches,
    )
    from replay_tpu.nn import OptimizerFactory, Trainer, make_mesh
    from replay_tpu.nn.loss import CE
    from replay_tpu.nn.sequential.sasrec import SasRec
    from replay_tpu.nn.transform import Compose
    from replay_tpu.nn.transform.template import make_default_sasrec_transforms
    from replay_tpu.utils import CheckpointManager, KillAtStep

    schema = TensorSchema(
        TensorFeatureInfo(
            "item_id", FeatureType.CATEGORICAL, is_seq=True,
            feature_hint=FeatureHint.ITEM_ID, cardinality=NUM_ITEMS,
            embedding_dim=8,
        )
    )
    model = SasRec(
        schema=schema, embedding_dim=8, num_blocks=1, num_heads=1,
        max_sequence_length=SEQ_LEN,
    ).clone(use_flash="ring")
    trainer = Trainer(
        model=model,
        loss=CE(),
        optimizer=OptimizerFactory(name="sgd", learning_rate=0.1),
        mesh=make_mesh(model_parallel=2, seq_parallel=2),  # (data=2, model=2, seq=2)
        shard_vocab=True,
        seed=0,
    )

    batcher = ParquetBatcher(
        parquet_path, batch_size=LOCAL_BATCH, shuffle=True, seed=STREAM_SEED,
        shard="row_groups",
        metadata={"item_id": {"shape": SEQ_LEN, "padding": 0}},
        partitioning=Partitioning(ReplicasInfo(2, rank), shuffle=True, seed=STREAM_SEED),
    )
    pipeline = Compose(make_default_sasrec_transforms(schema)["train"])

    events = []

    class _Sink:
        def log_event(self, event):
            if event.event == "on_train_step":
                events.append([int(event.step), float(event.payload["loss"])])

    sinks = [_Sink()]
    if kill_at >= 0:
        injector = KillAtStep()

        class _KillSink:
            seen = 0

            def log_event(self, event):
                if event.event == "on_train_step":
                    type(self).seen += 1
                    if type(self).seen >= kill_at:
                        injector.fire()  # real SIGKILL: does not return

        sinks.append(_KillSink())

    manager = CheckpointManager(ckpt_dir)
    state = trainer.fit(
        TransformedBatches(batcher, pipeline),
        epochs=EPOCHS,
        scan_chunk=2,
        log_every=0,
        loggers=sinks,
        checkpoint_manager=manager,
        checkpoint_every=CHECKPOINT_EVERY,
        resume=(phase == "resume"),
    )

    with open(out_path, "w") as fh:
        json.dump(
            {
                "rank": rank,
                "phase": phase,
                "final_step": int(np.asarray(state.step)),
                "events": events,
                "valid_steps": manager.valid_steps(),
            },
            fh,
        )


if __name__ == "__main__":
    main()
