"""Worker for the true multi-process DP test: one host of a 2-host job.

Launched by test_multiprocess.py with a clean CPU env (4 virtual devices per
process). Joins the distributed job through the framework's own
initialize_distributed, feeds ITS disjoint slice of a deterministic global
batch, trains a small SASRec for a few steps over the 8-device global mesh, and
writes the per-step (replicated, hence locally fetchable) losses to a file.
"""

import json
import sys

import numpy as np


def main() -> None:
    rank = int(sys.argv[1])
    coordinator = sys.argv[2]
    out_path = sys.argv[3]

    import jax as _jax

    try:
        _jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass  # older/newer jax may configure this via env instead

    from replay_tpu.parallel import initialize_distributed

    layout = initialize_distributed(
        coordinator_address=coordinator, num_processes=2, process_id=rank
    )
    assert layout["num_processes"] == 2, layout

    import jax

    from replay_tpu.data import FeatureHint, FeatureType
    from replay_tpu.data.nn import TensorFeatureInfo, TensorSchema
    from replay_tpu.nn import OptimizerFactory, Trainer, make_mesh
    from replay_tpu.nn.loss import CE

    from replay_tpu.nn.sequential.sasrec import SasRec

    num_items, seq_len, global_batch = 16, 6, 8
    local = global_batch // 2
    schema = TensorSchema(
        TensorFeatureInfo("item_id", FeatureType.CATEGORICAL, is_seq=True,
                          feature_hint=FeatureHint.ITEM_ID, cardinality=num_items,
                          embedding_dim=16)
    )
    trainer = Trainer(
        model=SasRec(schema=schema, embedding_dim=16, num_blocks=1,
                     max_sequence_length=seq_len),
        loss=CE(),
        optimizer=OptimizerFactory(name="sgd", learning_rate=0.1),
        mesh=make_mesh(),  # all 8 GLOBAL devices
        seed=0,
    )

    def global_batch_for(step: int) -> dict:
        rng = np.random.default_rng(step)  # same on every rank
        items = rng.integers(0, num_items, (global_batch, seq_len + 1)).astype(np.int32)
        mask = np.ones((global_batch, seq_len), bool)
        return {
            "feature_tensors": {"item_id": items[:, :-1]},
            "padding_mask": mask,
            "positive_labels": items[:, 1:, None],
            "target_padding_mask": mask[:, :, None],
        }

    def local_slice(batch: dict) -> dict:
        return {
            k: ({n: v[rank * local : (rank + 1) * local] for n, v in val.items()}
                if isinstance(val, dict)
                else val[rank * local : (rank + 1) * local])
            for k, val in batch.items()
        }

    state = trainer.init_state(local_slice(global_batch_for(0)))
    losses = []
    for step in range(3):
        state, loss_value = trainer.train_step(state, local_slice(global_batch_for(step)))
        losses.append(float(loss_value))  # replicated output: locally fetchable

    # distributed validation: each host feeds its shard, metric states are
    # all-gathered and summed — both ranks must report identical global metrics
    val_rng = np.random.default_rng(99)
    val_items = val_rng.integers(0, num_items, (global_batch, seq_len)).astype(np.int32)
    val_gt = val_rng.integers(0, num_items, (global_batch, 2)).astype(np.int64)
    val_batch_local = {
        "feature_tensors": {"item_id": val_items[rank * local : (rank + 1) * local]},
        "padding_mask": np.ones((local, seq_len), bool),
        "ground_truth": val_gt[rank * local : (rank + 1) * local],
    }
    metrics = trainer.validate(state, [val_batch_local], metrics=("recall", "ndcg"),
                               top_k=(3,))

    # adam creates process-local optimizer scalars (count); one step proves the
    # multi-host globalization of opt_state works
    adam_trainer = Trainer(
        model=trainer.model, loss=CE(),
        optimizer=OptimizerFactory(name="adam", learning_rate=1e-3),
        mesh=make_mesh(), seed=0,
    )
    adam_state = adam_trainer.init_state(local_slice(global_batch_for(0)))
    adam_state, adam_loss = adam_trainer.train_step(adam_state, local_slice(global_batch_for(0)))
    assert np.isfinite(float(adam_loss))

    with open(out_path, "w") as handle:
        json.dump(
            {"rank": rank, "losses": losses, "adam_loss": float(adam_loss),
             "metrics": {k: float(v) for k, v in metrics.items()}},
            handle,
        )


if __name__ == "__main__":
    main()
