"""Collective + sharding introspection (parallel.introspect), and the
CEFusedTP no-table-gather regression guard.

Core tier parses synthetic HLO text (pure regex, no jax). The jax tier lowers
the real programs on the virtual 8-device mesh: the guard asserts PR 7's core
invariant STATICALLY — ``CEFusedTP``'s lowered program contains no all-gather
of the ``[I/n_tp, E]`` item-table shard, only the ``[rows]``-sized lse/max
combine collectives — so a future lowering/sharding change that silently
regathers the catalog fails CI before any memory graph is eyeballed.
"""

import numpy as np
import pytest

from replay_tpu.parallel.introspect import (
    collective_bytes,
    collective_inventory,
    summarize_collectives,
)

_SYNTHETIC_HLO = """
ENTRY %main {
  %all-gather.1 = f32[2,4]{1,0} all-gather(f32[1,4]{1,0} %slice.1), channel_id=1, replica_groups={{0,1},{2,3},{4,5},{6,7}}, dimensions={0}, use_global_device_ids=true
  %all-reduce.3 = f32[256,32]{1,0} all-reduce(f32[256,32]{1,0} %dot.9), channel_id=4, replica_groups={{0,2,4,6},{1,3,5,7}}, use_global_device_ids=true, to_apply=%region_25
  %reduce-scatter.1 = f32[1,4]{1,0} reduce-scatter(f32[2,4]{1,0} %fusion.2), channel_id=2, replica_groups={{0,1},{2,3},{4,5},{6,7}}, dimensions={0}, to_apply=%region_24
  %all-reduce.9 = f32[] all-reduce(f32[] %add.1), channel_id=5, replica_groups=[2,4]<=[4,2]T(1,0), use_global_device_ids=true, to_apply=%region_10
  %ag-start = (f32[4]{0}, f32[8]{0}) all-gather-start(f32[4]{0} %p0), replica_groups={{0,1}}, dimensions={0}
  %ag-done = f32[8]{0} all-gather-done((f32[4]{0}, f32[8]{0}) %ag-start)
  %all-gather.7 = bf16[16,128]{1,0:T(8,128)(2,1)S(1)} all-gather(bf16[8,128]{1,0:T(8,128)(2,1)} %p3), channel_id=9, replica_groups={{0,1},{2,3},{4,5},{6,7}}, dimensions={0}, use_global_device_ids=true
  %mul.2 = f32[8,8]{1,0} multiply(f32[8,8]{1,0} %p4, f32[8,8]{1,0} %all-gather.1)
  ROOT %dot.1 = f32[8,8]{1,0} dot(f32[8,8]{1,0} %p1, f32[8,8]{1,0} %p2), lhs_contracting_dims={1}, rhs_contracting_dims={0}
}
"""


@pytest.mark.core
def test_collective_inventory_parses_ops_shapes_and_groups():
    inventory = collective_inventory(_SYNTHETIC_HLO, mesh_shape={"data": 4, "model": 2})
    by_name = {entry["name"]: entry for entry in inventory}
    assert set(by_name) == {
        "all-gather.1", "all-reduce.3", "reduce-scatter.1", "all-reduce.9",
        "ag-start", "all-gather.7",
    }  # -done halves skipped; dot/mul (collective only as OPERAND) excluded
    # TPU-optimized layouts carry tiling/memory-space annotations — the real
    # hardware's as_text() must parse or the guard is inert exactly there
    tpu_layout = by_name["all-gather.7"]
    assert tpu_layout["bytes"] == 16 * 128 * 2
    assert tpu_layout["mesh_axis"] == "model"
    gather = by_name["all-gather.1"]
    assert gather["op"] == "all-gather"
    assert gather["bytes"] == 2 * 4 * 4
    assert gather["group_size"] == 2
    assert gather["mesh_axis"] == "model"  # consecutive-id groups = last axis
    reduce = by_name["all-reduce.3"]
    assert reduce["bytes"] == 256 * 32 * 4
    assert reduce["mesh_axis"] == "data"  # stride == model size = first axis
    iota = by_name["all-reduce.9"]
    assert iota["group_size"] == 4  # [2,4]<=... iota form: 2 groups of 4
    start = by_name["ag-start"]
    assert start["bytes"] == (4 + 8) * 4  # tuple shape sums elements


@pytest.mark.core
def test_collective_summary_and_bytes():
    inventory = collective_inventory(_SYNTHETIC_HLO)
    summary = summarize_collectives(inventory)
    assert summary["count"] == 6
    assert summary["bytes"] == collective_bytes(inventory)
    assert summary["by_op"]["all-reduce"]["count"] == 2
    assert summary["by_op"]["all-gather"]["count"] == 3
    assert summarize_collectives([]) == {"count": 0, "bytes": 0, "by_op": {}}


@pytest.mark.core
def test_collective_inventory_empty_for_collective_free_hlo():
    assert collective_inventory("ENTRY %main { ROOT %x = f32[4]{0} add(%a, %b) }") == []


# --------------------------------------------------------------------------- #
# jax tier: the CEFusedTP no-table-gather guard (8-device DPxTP mesh)
# --------------------------------------------------------------------------- #
def _tp_head_program(num_items, embed, rows, n_tp):
    """value_and_grad of the TP-sharded fused-lse head, lowered on a DPxTP
    mesh — the exact program whose table-locality PR 7 established."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from replay_tpu.nn import make_mesh
    from replay_tpu.parallel.sharded_ce import sharded_fused_lse

    mesh = make_mesh(model_parallel=n_tp)
    rng = np.random.default_rng(0)
    hidden = jax.device_put(
        rng.normal(size=(rows, embed)).astype(np.float32),
        NamedSharding(mesh, P("data", None)),
    )
    table = jax.device_put(
        rng.normal(size=(num_items, embed)).astype(np.float32),
        NamedSharding(mesh, P("model", None)),
    )

    def head(hidden, table):
        return jnp.sum(
            sharded_fused_lse(hidden, table, mesh, tile=8, interpret=True)
        )

    jitted = jax.jit(jax.value_and_grad(head, argnums=(0, 1)))
    return jitted.lower(hidden, table).compile().as_text(), mesh


@pytest.mark.jax
@pytest.mark.smoke
def test_cefused_tp_head_never_gathers_the_table_shard():
    import jax

    if jax.device_count() < 4:
        pytest.skip("needs the virtual multi-device mesh")
    n_tp = 2
    num_items, embed, rows = 4096, 64, 16  # shard table 512 kB >> combine bytes
    hlo, mesh = _tp_head_program(num_items, embed, rows, n_tp)
    inventory = collective_inventory(
        hlo, mesh_shape={axis: int(n) for axis, n in mesh.shape.items()}
    )
    shard_table_bytes = num_items // n_tp * embed * 4
    gathers = [e for e in inventory if e["op"] == "all-gather"]
    oversized = [e for e in gathers if (e.get("bytes") or 0) >= shard_table_bytes]
    assert not oversized, (
        "CEFusedTP's head all-gathers table-shard-sized tensors — the memory "
        f"wall is back: {oversized}"
    )
    # the lse/max combine IS there, and it is [rows]-sized: n_tp scalars per
    # row at most (async gathers report tuple shapes, <= 2x the bound)
    assert gathers, f"expected the lse-combine all-gather in: {inventory}"
    combine_bound = 2 * n_tp * rows * 4
    assert all((e.get("bytes") or 0) <= combine_bound for e in gathers), gathers
    # dW stays shard-local over the model axis: no model-axis reduce touches
    # table-sized tensors either (the data-axis grad psum legitimately does)
    model_reduces = [
        e
        for e in inventory
        if e["op"] in ("all-reduce", "reduce-scatter")
        and e.get("mesh_axis") == "model"
        and (e.get("bytes") or 0) >= shard_table_bytes
    ]
    assert not model_reduces, model_reduces


@pytest.mark.jax
def test_full_cefused_tp_train_scan_guard_via_trainer():
    """The same guard through the PRODUCTION program: the dryrun's chunked
    CEFusedTP fit — lowered from the trainer's recorded templates."""
    import jax

    if jax.device_count() < 4:
        pytest.skip("needs the virtual multi-device mesh")
    from replay_tpu.data import FeatureHint, FeatureType
    from replay_tpu.data.nn import TensorFeatureInfo, TensorSchema
    from replay_tpu.nn import OptimizerFactory, Trainer, make_mesh
    from replay_tpu.nn.loss import CEFusedTP
    from replay_tpu.nn.sequential.sasrec import SasRec

    n_tp, num_items, embed, seq_len = 2, 511, 16, 6
    schema = TensorSchema(
        TensorFeatureInfo(
            "item_id", FeatureType.CATEGORICAL, is_seq=True,
            feature_hint=FeatureHint.ITEM_ID, cardinality=num_items,
            embedding_dim=embed,
        )
    )
    model = SasRec(schema=schema, embedding_dim=embed, num_blocks=1, num_heads=1,
                   max_sequence_length=seq_len)
    trainer = Trainer(
        model=model, loss=CEFusedTP(tile=8, interpret=True),
        optimizer=OptimizerFactory(learning_rate=1e-2),
        mesh=make_mesh(model_parallel=n_tp), shard_vocab=True,
    )
    batch_size = 8

    def mk(seed):
        gen = np.random.default_rng(seed)
        items = gen.integers(0, num_items, size=(batch_size, seq_len + 1)).astype(np.int32)
        mask = np.ones((batch_size, seq_len), dtype=bool)
        return {"feature_tensors": {"item_id": items[:, :-1]}, "padding_mask": mask,
                "positive_labels": items[:, 1:, None],
                "target_padding_mask": mask[:, :, None]}

    trainer.fit([mk(i) for i in range(4)], epochs=1, scan_chunk=2, log_every=0)
    mesh_shape = {axis: int(n) for axis, n in trainer.mesh.shape.items()}
    inventory = collective_inventory(trainer.lowered_hlo("train_scan"), mesh_shape)
    # table rows pad to the shard grid: (511 + 1 padding row) / 2 per shard
    shard_table_bytes = (num_items + 1) // n_tp * embed * 4
    oversized = [
        e for e in inventory
        if e["op"] == "all-gather" and (e.get("bytes") or 0) >= shard_table_bytes
    ]
    assert not oversized, oversized

    # sharding introspection: the vocab table IS model-sharded (no flags)
    from replay_tpu.parallel.introspect import sharding_report

    batch = mk(99)
    state = trainer.init_state(batch)
    report = sharding_report(state.params, trainer.mesh, expect_sharded=("embedding_",))
    assert report["flags"] == []
    assert report["sharded_bytes"] > 0
    specs = {row["path"]: row["spec"] for row in report["params"]}
    assert any(
        "embedding_" in path and spec and "model" in spec for path, spec in specs.items()
    ), specs


@pytest.mark.jax
def test_sharding_report_flags_accidental_replication():
    """A vocab-sized table left replicated on a TP mesh is exactly the silent
    failure the flag exists for."""
    import jax

    if jax.device_count() < 4:
        pytest.skip("needs the virtual multi-device mesh")
    from jax.sharding import NamedSharding, PartitionSpec as P

    from replay_tpu.nn import make_mesh
    from replay_tpu.parallel.introspect import sharding_report

    mesh = make_mesh(model_parallel=2)
    params = {
        "embedding_item_id": {
            "embedding": jax.device_put(
                np.zeros((64, 8), np.float32), NamedSharding(mesh, P())
            )
        }
    }
    report = sharding_report(params, mesh, expect_sharded=("embedding_",))
    assert len(report["flags"]) == 1
    assert "accidental replication" in report["flags"][0]
    assert report["replicated_bytes"] == 64 * 8 * 4
