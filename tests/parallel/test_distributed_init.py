"""Distributed init wrapper: single-process no-op path + layout report."""

import pytest

from replay_tpu.parallel import initialize_distributed, replicas_info


@pytest.mark.jax
def test_single_process_noop():
    layout = initialize_distributed()
    assert layout["process_id"] == 0
    assert layout["num_processes"] == 1
    assert layout["global_devices"] >= 1
    # idempotent
    assert initialize_distributed() == layout
    info = replicas_info(num_workers=2)
    assert info.num_replicas == 2 and info.replica_id == 0
