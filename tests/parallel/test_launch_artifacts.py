"""Launcher forensics: ring hand-off, spool persistence, LaunchError paths.

Explicitly core tier — the launcher is pure subprocess supervision and the
worker (launch_artifact_worker.py) is stdlib-only, so none of this touches
jax. The claims: ``launch_workers(run_dir=...)`` hands every rank a flight
ring via ``REPLAY_TPU_FLIGHT_PATH``; a rank that dies abnormally (nonzero
exit or real SIGKILL) leaves its FULL stdout/stderr spools and a
``meta.json`` with the authoritative ``killed_by`` in
``<run_dir>/workers/rank<i>/``; a SIGKILLed rank's ring reads back with its
records intact; and ``LaunchError`` names the persisted artifact paths.
"""

import json
import signal
from pathlib import Path

import pytest

from replay_tpu.obs.blackbox import read_flight
from replay_tpu.parallel.launch import LaunchError, launch_workers

pytestmark = pytest.mark.core

WORKER = str(Path(__file__).with_name("launch_artifact_worker.py"))


def _launch(run_dir, behaviors, **kwargs):
    return launch_workers(
        WORKER,
        num_processes=len(behaviors),
        args_for=lambda rank: [behaviors[rank]],
        run_dir=str(run_dir),
        grace_s=10.0,
        timeout=60.0,
        **kwargs,
    )


def test_clean_workers_leave_rings_but_no_failure_artifacts(tmp_path):
    results = _launch(tmp_path, ["ok", "ok"])
    for result in results:
        assert result.returncode == 0 and not result.reaped
        assert result.artifacts_dir is None  # nothing abnormal to persist
        log = read_flight(result.flight_path)  # the hand-off worked end to end
        assert log.recovered == 4
        assert not (Path(tmp_path) / "workers" / f"rank{result.rank}" / "meta.json").exists()


def test_abnormal_exit_persists_full_spools_and_meta(tmp_path):
    results = _launch(tmp_path, ["ok", "fail"], check=False)
    ok, bad = results
    assert ok.returncode == 0 and ok.artifacts_dir is None
    assert bad.returncode == 3
    artifacts = Path(bad.artifacts_dir)
    assert artifacts == Path(tmp_path) / "workers" / "rank1"
    assert (artifacts / "stdout.log").read_text() == bad.stdout
    assert "rank 1 stdout line" in bad.stdout
    assert "rank 1 exploding" in (artifacts / "stderr.log").read_text()
    meta = json.loads((artifacts / "meta.json").read_text())
    assert meta == {"rank": 1, "returncode": 3, "killed_by": None, "reaped": False}


def test_sigkilled_rank_leaves_a_readable_ring_and_its_signal_on_record(tmp_path):
    results = _launch(tmp_path, ["ok", "sigkill"], check=False)
    victim = results[1]
    assert victim.returncode == -signal.SIGKILL
    assert victim.killed_by == signal.SIGKILL
    meta = json.loads((Path(victim.artifacts_dir) / "meta.json").read_text())
    assert meta["killed_by"] == signal.SIGKILL
    # the black box harvest: records written before kill -9, read after it
    log = read_flight(victim.flight_path)
    assert log.recovered == 4
    assert [r["rank"] for r in log.records] == [1, 1, 1, 1]


def test_launch_error_names_the_persisted_artifact_paths(tmp_path):
    with pytest.raises(LaunchError) as excinfo:
        _launch(tmp_path, ["ok", "fail"])
    message = str(excinfo.value)
    expected = str(Path(tmp_path) / "workers" / "rank1")
    assert f"artifacts={expected}" in message
    assert "rank 1 exploding" in message  # the stderr tail still rides along


def test_without_run_dir_nothing_changes(tmp_path):
    results = launch_workers(
        WORKER,
        num_processes=1,
        args_for=lambda rank: ["ok"],
        grace_s=10.0,
        timeout=60.0,
    )
    assert results[0].returncode == 0
    assert results[0].flight_path is None
    assert results[0].artifacts_dir is None
    assert not (Path(tmp_path) / "workers").exists()
