"""Multi-chip correctness on the 8-device virtual CPU mesh.

The analogue of the reference's fake-torch.distributed tests (SURVEY.md §4): data
parallelism, vocab tensor-parallelism and metric-state psum are asserted against
single-device ground truth without any real TPU.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from replay_tpu.data import FeatureHint, FeatureType
from replay_tpu.data.nn import TensorFeatureInfo, TensorSchema
from replay_tpu.metrics.builder import MetricsBuilder
from replay_tpu.nn import OptimizerFactory, Trainer, make_mesh
from replay_tpu.nn.loss import CE
from replay_tpu.nn.sequential.sasrec import SasRec

# 15 items -> a 16-row table (cardinality + padding row) that divides evenly
# over a model axis of 2 or 4; an odd row count would silently skip vocab
# sharding (run_training asserts it actually happened)
NUM_ITEMS = 15
SEQ_LEN = 6
BATCH = 8


def make_schema() -> TensorSchema:
    return TensorSchema(
        TensorFeatureInfo(
            "item_id",
            FeatureType.CATEGORICAL,
            is_seq=True,
            feature_hint=FeatureHint.ITEM_ID,
            cardinality=NUM_ITEMS,
            embedding_dim=16,
        )
    )


def make_train_batch(seed: int) -> dict:
    rng = np.random.default_rng(seed)
    items = rng.integers(0, NUM_ITEMS, size=(BATCH, SEQ_LEN)).astype(np.int32)
    mask = np.ones((BATCH, SEQ_LEN), dtype=bool)
    return {
        "feature_tensors": {"item_id": items[:, :-1]},
        "padding_mask": mask[:, :-1],
        "positive_labels": items[:, 1:, None],
        "target_padding_mask": mask[:, 1:, None],
    }


def run_training(mesh: Mesh, steps: int = 3, shard_vocab: bool = False):
    model = SasRec(schema=make_schema(), embedding_dim=16, num_blocks=1,
                   max_sequence_length=SEQ_LEN)
    # SGD: parity asserts exact-ish numerical equivalence, and adaptive optimizers
    # amplify device-count-dependent summation noise on near-zero gradients
    trainer = Trainer(
        model=model,
        loss=CE(),
        optimizer=OptimizerFactory(name="sgd", learning_rate=0.1),
        mesh=mesh,
        shard_vocab=shard_vocab,
        seed=0,
    )
    state = trainer.init_state(make_train_batch(0))
    if shard_vocab:
        # guard against the silent-degradation mode: a table whose row count
        # does not divide the model axis stays replicated and the comparison
        # below proves nothing
        specs = [
            str(leaf.sharding.spec)
            for path, leaf in jax.tree_util.tree_flatten_with_path(state.params)[0]
            if "embedding_" in jax.tree_util.keystr(path)
        ]
        assert any("model" in spec for spec in specs), specs
    losses = []
    for step in range(steps):
        state, loss_value = trainer.train_step(state, make_train_batch(step))
        losses.append(float(loss_value))
    return jax.tree.map(np.asarray, state.params), losses


@pytest.mark.jax
@pytest.mark.smoke
def test_data_parallel_matches_single_device():
    """DP over 8 devices must be numerically equivalent to 1 device: the XLA
    gradient all-reduce replaces DDP without changing the math."""
    params_1, losses_1 = run_training(make_mesh(jax.devices()[:1]))
    params_8, losses_8 = run_training(make_mesh(jax.devices()))
    np.testing.assert_allclose(np.array(losses_1), np.array(losses_8), rtol=2e-4)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-3, atol=2e-5),
        params_1,
        params_8,
    )


@pytest.mark.jax
def test_vocab_sharded_training_matches_replicated():
    """Sharding embedding tables over the model axis (vocab TP) must not change
    the computation — XLA all-gathers the rows when logits need them."""
    params_dp, losses_dp = run_training(make_mesh(jax.devices()))
    params_tp, losses_tp = run_training(
        make_mesh(jax.devices(), model_parallel=4), shard_vocab=True
    )
    np.testing.assert_allclose(np.array(losses_dp), np.array(losses_tp), rtol=2e-4)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-3, atol=2e-5),
        params_dp,
        params_tp,
    )


@pytest.mark.jax
def test_metrics_state_psums_across_devices():
    """Each device accumulates its shard; lax.psum of the state pytrees must equal
    the single-builder result over all the data (the sync_dist replacement)."""
    rng = np.random.default_rng(0)
    n_shards = 8
    preds = rng.integers(0, NUM_ITEMS, size=(n_shards, 4, 5))
    gts = np.where(
        rng.random((n_shards, 4, 3)) < 0.8,
        rng.integers(0, NUM_ITEMS, size=(n_shards, 4, 3)),
        -1,
    )

    def make_builder():
        return MetricsBuilder(metrics=("recall", "ndcg", "coverage"), top_k=(1, 5),
                              item_count=NUM_ITEMS)

    shard_states = []
    for s in range(n_shards):
        b = make_builder()
        b.add_prediction(preds[s], gts[s])
        shard_states.append(b.state())

    # the real collective: psum the stacked states over a mesh axis
    mesh = Mesh(np.array(jax.devices()), ("d",))
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *shard_states)

    def reduce_states(state):
        return jax.tree.map(lambda x: jax.lax.psum(x, "d"), state)

    specs_in = jax.tree.map(lambda _: P("d"), stacked)
    specs_out = jax.tree.map(lambda _: P(), stacked)
    total_state = shard_map(
        reduce_states, mesh=mesh, in_specs=(specs_in,), out_specs=specs_out
    )(stacked)
    # shard_map with in_specs P('d') leaves a leading per-device axis of size 1
    total_state = jax.tree.map(lambda x: x[0] if x.ndim and x.shape[0] == 1 else x, total_state)

    merged = make_builder()
    merged.load_state(total_state)

    reference = make_builder()
    for s in range(n_shards):
        reference.add_prediction(preds[s], gts[s])

    got, want = merged.get_metrics(), reference.get_metrics()
    assert set(got) == set(want)
    for key in want:
        assert got[key] == pytest.approx(want[key], rel=1e-5), key


@pytest.mark.jax
def test_fused_ce_composes_with_vocab_sharding():
    """CEFused (pallas head, interpret off-TPU) + shard_vocab on a (4, 2) mesh
    == plain CE data-parallel — the exact composition the large-catalog TPU
    configs run (bench_suite sasrec_100k_fused)."""
    from replay_tpu.nn.loss import CEFused

    def losses_for(loss, model_parallel, shard_vocab):
        model = SasRec(schema=make_schema(), embedding_dim=16, num_blocks=1,
                       max_sequence_length=SEQ_LEN)
        trainer = Trainer(
            model=model, loss=loss,
            optimizer=OptimizerFactory(name="sgd", learning_rate=0.1),
            mesh=make_mesh(jax.devices(), model_parallel=model_parallel),
            shard_vocab=shard_vocab, seed=0,
        )
        state = trainer.init_state(make_train_batch(0))
        out = []
        for step in range(3):
            state, loss_value = trainer.train_step(state, make_train_batch(step))
            out.append(float(loss_value))
        return out

    plain = losses_for(CE(), 1, False)
    fused_sharded = losses_for(CEFused(), 2, True)
    np.testing.assert_allclose(plain, fused_sharded, rtol=2e-4)
