"""TRUE multi-process data parallelism: 2 processes × 4 CPU devices with gloo
collectives must train identically to one process with all 8 devices.

This is the real multi-host path (jax.distributed + make_array_from_process_
local_data + psum over the global mesh), not the single-process mesh emulation
the rest of tests/parallel uses."""

import json
import os
import socket
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

REPO_ROOT = Path(__file__).resolve().parents[2]


def _free_port() -> int:
    with socket.socket() as sock:
        sock.bind(("127.0.0.1", 0))
        return sock.getsockname()[1]


def _clean_two_proc_env() -> dict:
    return {
        **{k: v for k, v in os.environ.items() if ".axon_site" not in v},
        "PYTHONPATH": str(REPO_ROOT),
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
        "JAX_CPU_COLLECTIVES_IMPLEMENTATION": "gloo",
        "REPLAY_TPU_CLEAN_REEXEC": "1",
    }


def _run_two_workers(script: str, extra_args, env) -> None:
    port = _free_port()
    coordinator = f"127.0.0.1:{port}"
    workers = [
        subprocess.Popen(
            [sys.executable, str(REPO_ROOT / "tests/parallel" / script),
             str(rank), coordinator, *extra_args(rank)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        )
        for rank in range(2)
    ]
    outputs = [w.communicate(timeout=300) for w in workers]
    for worker, (stdout, stderr) in zip(workers, outputs):
        assert worker.returncode == 0, stderr.decode()[-2000:]


@pytest.mark.jax
def test_two_process_dp_matches_single_process(tmp_path):
    _run_two_workers(
        "mp_worker.py",
        lambda rank: [str(tmp_path / f"rank{rank}.json")],
        _clean_two_proc_env(),
    )
    results = [json.loads((tmp_path / f"rank{r}.json").read_text()) for r in range(2)]
    # both hosts observe the SAME (psum-reduced, replicated) losses
    np.testing.assert_allclose(results[0]["losses"], results[1]["losses"], rtol=1e-6)

    # and they equal a single-process 8-device run over the same global batches
    from replay_tpu.data import FeatureHint, FeatureType
    from replay_tpu.data.nn import TensorFeatureInfo, TensorSchema
    from replay_tpu.nn import OptimizerFactory, Trainer, make_mesh
    from replay_tpu.nn.loss import CE
    from replay_tpu.nn.sequential.sasrec import SasRec

    num_items, seq_len, global_batch = 16, 6, 8
    schema = TensorSchema(
        TensorFeatureInfo("item_id", FeatureType.CATEGORICAL, is_seq=True,
                          feature_hint=FeatureHint.ITEM_ID, cardinality=num_items,
                          embedding_dim=16)
    )
    trainer = Trainer(
        model=SasRec(schema=schema, embedding_dim=16, num_blocks=1,
                     max_sequence_length=seq_len),
        loss=CE(),
        optimizer=OptimizerFactory(name="sgd", learning_rate=0.1),
        mesh=make_mesh(),
        seed=0,
    )
    state, reference_losses = None, []
    for step in range(3):
        rng = np.random.default_rng(step)
        items = rng.integers(0, num_items, (global_batch, seq_len + 1)).astype(np.int32)
        mask = np.ones((global_batch, seq_len), bool)
        batch = {
            "feature_tensors": {"item_id": items[:, :-1]},
            "padding_mask": mask,
            "positive_labels": items[:, 1:, None],
            "target_padding_mask": mask[:, :, None],
        }
        if state is None:
            state = trainer.init_state(batch)
        state, loss_value = trainer.train_step(state, batch)
        reference_losses.append(float(loss_value))

    np.testing.assert_allclose(results[0]["losses"], reference_losses, rtol=1e-5)

    # distributed validation: both hosts report the same GLOBAL metrics, equal
    # to a single-process validate over the full batch
    assert results[0]["metrics"] == results[1]["metrics"]
    val_rng = np.random.default_rng(99)
    val_items = val_rng.integers(0, num_items, (global_batch, seq_len)).astype(np.int32)
    val_gt = val_rng.integers(0, num_items, (global_batch, 2)).astype(np.int64)
    reference_metrics = trainer.validate(
        state,
        [{
            "feature_tensors": {"item_id": val_items},
            "padding_mask": np.ones((global_batch, seq_len), bool),
            "ground_truth": val_gt,
        }],
        metrics=("recall", "ndcg"), top_k=(3,),
    )
    for key, value in reference_metrics.items():
        assert results[0]["metrics"][key] == pytest.approx(value, rel=1e-5), key


@pytest.mark.jax
def test_two_process_shard_vocab_checkpoint_roundtrip(tmp_path):
    """Multi-host vocab-sharded save/kill/restore: 3 steps + orbax checkpoint
    + fresh processes + restore + 3 steps == 6 uninterrupted steps."""
    env = _clean_two_proc_env()
    ckpt_dir = tmp_path / "ckpt"

    _run_two_workers(
        "mp_ckpt_worker.py",
        lambda rank: [str(tmp_path / f"first_rank{rank}.json"), str(ckpt_dir), "first"],
        env,
    )
    first = [json.loads((tmp_path / f"first_rank{r}.json").read_text()) for r in range(2)]
    np.testing.assert_allclose(first[0]["losses"], first[1]["losses"], rtol=1e-6)
    assert (ckpt_dir / "step_3.json").exists()

    # kill-and-restart: brand-new processes restore and continue
    _run_two_workers(
        "mp_ckpt_worker.py",
        lambda rank: [str(tmp_path / f"resume_rank{rank}.json"), str(ckpt_dir), "resume"],
        env,
    )
    resume = [json.loads((tmp_path / f"resume_rank{r}.json").read_text()) for r in range(2)]
    np.testing.assert_allclose(resume[0]["losses"], resume[1]["losses"], rtol=1e-6)

    # single-process reference: 6 uninterrupted steps on the same (4, 2)
    # vocab-sharded mesh over the same global batches
    import jax

    from replay_tpu.data import FeatureHint, FeatureType
    from replay_tpu.data.nn import TensorFeatureInfo, TensorSchema
    from replay_tpu.nn import OptimizerFactory, Trainer, make_mesh
    from replay_tpu.nn.loss import CE
    from replay_tpu.nn.sequential.sasrec import SasRec

    num_items, seq_len, global_batch = 15, 6, 8
    schema = TensorSchema(
        TensorFeatureInfo("item_id", FeatureType.CATEGORICAL, is_seq=True,
                          feature_hint=FeatureHint.ITEM_ID, cardinality=num_items,
                          embedding_dim=16)
    )
    trainer = Trainer(
        model=SasRec(schema=schema, embedding_dim=16, num_blocks=1,
                     max_sequence_length=seq_len),
        loss=CE(),
        optimizer=OptimizerFactory(name="sgd", learning_rate=0.1),
        mesh=make_mesh(jax.devices(), model_parallel=2),
        shard_vocab=True,
        seed=0,
    )
    state, reference_losses = None, []
    for step in range(6):
        rng = np.random.default_rng(step)
        items = rng.integers(0, num_items, (global_batch, seq_len + 1)).astype(np.int32)
        mask = np.ones((global_batch, seq_len), bool)
        batch = {
            "feature_tensors": {"item_id": items[:, :-1]},
            "padding_mask": mask,
            "positive_labels": items[:, 1:, None],
            "target_padding_mask": mask[:, :, None],
        }
        if state is None:
            state = trainer.init_state(batch)
        state, loss_value = trainer.train_step(state, batch)
        reference_losses.append(float(loss_value))

    np.testing.assert_allclose(first[0]["losses"], reference_losses[:3], rtol=1e-5)
    np.testing.assert_allclose(resume[0]["losses"], reference_losses[3:], rtol=1e-5)
