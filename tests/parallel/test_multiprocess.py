"""TRUE multi-process data parallelism: 2 processes × 4 CPU devices with gloo
collectives must train identically to one process with all 8 devices.

This is the real multi-host path (jax.distributed + make_array_from_process_
local_data + psum over the global mesh), not the single-process mesh emulation
the rest of tests/parallel uses."""

import json
import os
from pathlib import Path

import numpy as np
import pytest

from replay_tpu.parallel.launch import clean_cpu_env, launch_workers

# each test spawns real jax.distributed worker processes (fresh interpreter +
# compile per worker, ~1 min apiece): excluded from the default tier via
# `-m 'not slow'`; the CI `multiproc_smoke` job and the full `-m jax` tier
# run this file explicitly
pytestmark = pytest.mark.slow

REPO_ROOT = Path(__file__).resolve().parents[2]


def _clean_two_proc_env() -> dict:
    return clean_cpu_env(local_devices=4, repo_root=REPO_ROOT)


def _run_two_workers(script: str, extra_args, env) -> None:
    launch_workers(
        str(REPO_ROOT / "tests/parallel" / script),
        num_processes=2,
        args_for=extra_args,
        env=env,
        timeout=300.0,
    )


@pytest.mark.jax
def test_two_process_dp_matches_single_process(tmp_path):
    _run_two_workers(
        "mp_worker.py",
        lambda rank: [str(tmp_path / f"rank{rank}.json")],
        _clean_two_proc_env(),
    )
    results = [json.loads((tmp_path / f"rank{r}.json").read_text()) for r in range(2)]
    # both hosts observe the SAME (psum-reduced, replicated) losses
    np.testing.assert_allclose(results[0]["losses"], results[1]["losses"], rtol=1e-6)

    # and they equal a single-process 8-device run over the same global batches
    from replay_tpu.data import FeatureHint, FeatureType
    from replay_tpu.data.nn import TensorFeatureInfo, TensorSchema
    from replay_tpu.nn import OptimizerFactory, Trainer, make_mesh
    from replay_tpu.nn.loss import CE
    from replay_tpu.nn.sequential.sasrec import SasRec

    num_items, seq_len, global_batch = 16, 6, 8
    schema = TensorSchema(
        TensorFeatureInfo("item_id", FeatureType.CATEGORICAL, is_seq=True,
                          feature_hint=FeatureHint.ITEM_ID, cardinality=num_items,
                          embedding_dim=16)
    )
    trainer = Trainer(
        model=SasRec(schema=schema, embedding_dim=16, num_blocks=1,
                     max_sequence_length=seq_len),
        loss=CE(),
        optimizer=OptimizerFactory(name="sgd", learning_rate=0.1),
        mesh=make_mesh(),
        seed=0,
    )
    state, reference_losses = None, []
    for step in range(3):
        rng = np.random.default_rng(step)
        items = rng.integers(0, num_items, (global_batch, seq_len + 1)).astype(np.int32)
        mask = np.ones((global_batch, seq_len), bool)
        batch = {
            "feature_tensors": {"item_id": items[:, :-1]},
            "padding_mask": mask,
            "positive_labels": items[:, 1:, None],
            "target_padding_mask": mask[:, :, None],
        }
        if state is None:
            state = trainer.init_state(batch)
        state, loss_value = trainer.train_step(state, batch)
        reference_losses.append(float(loss_value))

    np.testing.assert_allclose(results[0]["losses"], reference_losses, rtol=1e-5)

    # distributed validation: both hosts report the same GLOBAL metrics, equal
    # to a single-process validate over the full batch
    assert results[0]["metrics"] == results[1]["metrics"]
    val_rng = np.random.default_rng(99)
    val_items = val_rng.integers(0, num_items, (global_batch, seq_len)).astype(np.int32)
    val_gt = val_rng.integers(0, num_items, (global_batch, 2)).astype(np.int64)
    reference_metrics = trainer.validate(
        state,
        [{
            "feature_tensors": {"item_id": val_items},
            "padding_mask": np.ones((global_batch, seq_len), bool),
            "ground_truth": val_gt,
        }],
        metrics=("recall", "ndcg"), top_k=(3,),
    )
    for key, value in reference_metrics.items():
        assert results[0]["metrics"][key] == pytest.approx(value, rel=1e-5), key


STREAM_ROWS = 60


def _write_stream_parquet(path) -> None:
    import pandas as pd

    from replay_tpu.data import FeatureHint, FeatureType
    from replay_tpu.data.nn import (
        SequentialDataset,
        TensorFeatureInfo,
        TensorSchema,
        write_sequence_parquet,
    )

    rng = np.random.default_rng(11)
    frame = pd.DataFrame({
        "query_id": np.arange(STREAM_ROWS),
        "item_id": [
            rng.integers(1, 31, rng.integers(2, 9)).astype(np.int64)
            for _ in range(STREAM_ROWS)
        ],
    })
    schema = TensorSchema(
        TensorFeatureInfo("item_id", FeatureType.CATEGORICAL, is_seq=True,
                          feature_hint=FeatureHint.ITEM_ID, cardinality=31,
                          embedding_dim=8)
    )
    write_sequence_parquet(
        str(path), SequentialDataset(schema, "query_id", "item_id", frame),
        rows_per_chunk=8,
    )


def _stream_worker_args(tmp_path, parquet, ckpt_dir, phase, kill_ranks=()):
    def args(rank):
        kill_at = 13 if rank in kill_ranks else -1
        return [
            str(tmp_path / f"{phase}_rank{rank}.json"), str(parquet),
            str(ckpt_dir), phase, str(kill_at),
        ]
    return args


def _replayed_coverage(parquet, cursor, rank):
    """(consumed_ids, remaining_ids) for ``rank``'s shard at ``cursor`` —
    replayed on a fresh reader with the identical plan fingerprint."""
    from replay_tpu.data.nn import ParquetBatcher, Partitioning, ReplicasInfo

    def batcher():
        return ParquetBatcher(
            str(parquet), batch_size=4, shuffle=True, seed=3, shard="row_groups",
            metadata={"item_id": {"shape": 9, "padding": 0}},
            partitioning=Partitioning(ReplicasInfo(2, rank), shuffle=True, seed=3),
        )

    full = batcher()
    full.set_epoch(int(cursor["epoch"]))
    consumed = []
    for batch in list(full)[: int(cursor["batches"])]:
        consumed.extend(batch["query_id"][batch["valid"]].tolist())
    resumed = batcher()
    resumed.set_epoch(int(cursor["epoch"]))
    resumed.restore_cursor(cursor)
    remaining = []
    for batch in resumed:
        remaining.extend(batch["query_id"][batch["valid"]].tolist())
    return consumed, remaining


@pytest.mark.jax
def test_stream_fit_sigkill_resume_bitwise(tmp_path):
    """The process-real headline: a 2-process DP×TP×SP scan-chunked fit over
    the disjoint row-group streaming reader, SIGKILLed mid-epoch on one rank,
    resumes from the atomic checkpoint + per-process cursor sidecars onto the
    EXACT trajectory of the uninterrupted run — and the cursor sidecars prove
    exactly-once coverage of the interrupted epoch."""
    from replay_tpu.utils.checkpoint import CheckpointManager

    parquet = tmp_path / "stream.parquet"
    _write_stream_parquet(parquet)
    env = _clean_two_proc_env()
    worker = str(REPO_ROOT / "tests/parallel/mp_stream_worker.py")

    # 1) the uninterrupted reference trajectory
    full_ckpt = tmp_path / "ckpt_full"
    launch_workers(
        worker, 2, _stream_worker_args(tmp_path, parquet, full_ckpt, "full"),
        env=env, timeout=420.0, grace_s=90.0,
    )
    full = [json.loads((tmp_path / f"full_rank{r}.json").read_text()) for r in range(2)]
    assert full[0]["events"] == full[1]["events"]  # psum-replicated: identical
    assert full[0]["events"], "reference run emitted no steps"

    # 2) hard-kill one rank mid-epoch: a REAL SIGKILL, peers reaped by the
    # launcher once the collectives wedge. run_dir turns the launch forensic:
    # every rank records into a flight ring, the dead ones leave spools+meta
    # (CI points REPLAY_TPU_MP_RUN_DIR here to upload the evidence)
    kill_ckpt = tmp_path / "ckpt_kill"
    run_dir = os.environ.get("REPLAY_TPU_MP_RUN_DIR") or str(tmp_path / "kill_run")
    results = launch_workers(
        worker, 2,
        _stream_worker_args(tmp_path, parquet, kill_ckpt, "kill", kill_ranks=(1,)),
        env=env, timeout=420.0, grace_s=20.0, check=False, run_dir=run_dir,
    )
    import signal

    assert results[1].returncode == -signal.SIGKILL, results[1].stderr[-1000:]
    assert results[1].killed_by == signal.SIGKILL
    # the survivor cannot finish the epoch without its peer — either the
    # launcher reaped it out of the wedged collective or jax.distributed
    # surfaced the lost peer as an error; it must NOT have exited cleanly
    assert results[0].reaped or results[0].returncode != 0

    # the black box harvest: the SIGKILLed rank's ring reads back with the
    # fit's last events (the env hand-off needed NO worker change), and its
    # death is on record next to it for obs.report --postmortem
    from replay_tpu.obs.blackbox import read_flight

    flight = read_flight(results[1].flight_path)
    assert flight.recovered > 0, "the killed rank's ring recovered nothing"
    ring_events = [r["event"] for r in flight.records]
    assert "on_train_step" in ring_events
    assert "on_fit_end" not in ring_events  # SIGKILL: the fit never closed
    meta_path = Path(results[1].artifacts_dir) / "meta.json"
    assert json.loads(meta_path.read_text())["killed_by"] == signal.SIGKILL

    # 3) what the kill left behind: a valid mid-epoch checkpoint with one
    # cursor sidecar PER PROCESS, and exactly-once coverage when replayed
    manager = CheckpointManager(str(kill_ckpt))
    latest = manager.latest_step()
    assert latest is not None, "no valid checkpoint survived the kill"
    meta = manager.metadata(latest)
    assert meta.get("mid_epoch"), meta
    all_ids = []
    for rank in range(2):
        proc_meta = manager.process_metadata(latest, process_index=rank)
        cursor = proc_meta.get("stream_cursor")
        assert cursor is not None, f"rank {rank} has no cursor sidecar"
        assert int(cursor["batches"]) == int(meta["step_in_epoch"])
        consumed, remaining = _replayed_coverage(parquet, cursor, rank)
        ids = consumed + remaining
        assert len(ids) == len(set(ids)), f"rank {rank} re-emits a consumed row"
        all_ids.extend(ids)
    assert sorted(all_ids) == list(range(STREAM_ROWS))

    # 4) fresh processes resume from the sidecars: bit-for-bit the same
    # (step, loss) trajectory as the uninterrupted run, to the same end
    launch_workers(
        worker, 2, _stream_worker_args(tmp_path, parquet, kill_ckpt, "resume"),
        env=env, timeout=420.0, grace_s=90.0,
    )
    resume = [
        json.loads((tmp_path / f"resume_rank{r}.json").read_text()) for r in range(2)
    ]
    assert resume[0]["events"] == resume[1]["events"]
    assert resume[0]["events"], "resumed run emitted no steps"
    reference = dict(map(tuple, full[0]["events"]))
    for step, loss in resume[0]["events"]:
        assert reference[step] == loss, (  # EXACT float equality: bitwise resume
            f"step {step}: resumed loss {loss!r} != reference {reference[step]!r}"
        )
    assert resume[0]["final_step"] == full[0]["final_step"]


@pytest.mark.jax
def test_two_process_shard_vocab_checkpoint_roundtrip(tmp_path):
    """Multi-host vocab-sharded save/kill/restore: 3 steps + orbax checkpoint
    + fresh processes + restore + 3 steps == 6 uninterrupted steps."""
    env = _clean_two_proc_env()
    ckpt_dir = tmp_path / "ckpt"

    _run_two_workers(
        "mp_ckpt_worker.py",
        lambda rank: [str(tmp_path / f"first_rank{rank}.json"), str(ckpt_dir), "first"],
        env,
    )
    first = [json.loads((tmp_path / f"first_rank{r}.json").read_text()) for r in range(2)]
    np.testing.assert_allclose(first[0]["losses"], first[1]["losses"], rtol=1e-6)
    assert (ckpt_dir / "step_3.json").exists()

    # kill-and-restart: brand-new processes restore and continue
    _run_two_workers(
        "mp_ckpt_worker.py",
        lambda rank: [str(tmp_path / f"resume_rank{rank}.json"), str(ckpt_dir), "resume"],
        env,
    )
    resume = [json.loads((tmp_path / f"resume_rank{r}.json").read_text()) for r in range(2)]
    np.testing.assert_allclose(resume[0]["losses"], resume[1]["losses"], rtol=1e-6)

    # single-process reference: 6 uninterrupted steps on the same (4, 2)
    # vocab-sharded mesh over the same global batches
    import jax

    from replay_tpu.data import FeatureHint, FeatureType
    from replay_tpu.data.nn import TensorFeatureInfo, TensorSchema
    from replay_tpu.nn import OptimizerFactory, Trainer, make_mesh
    from replay_tpu.nn.loss import CE
    from replay_tpu.nn.sequential.sasrec import SasRec

    num_items, seq_len, global_batch = 15, 6, 8
    schema = TensorSchema(
        TensorFeatureInfo("item_id", FeatureType.CATEGORICAL, is_seq=True,
                          feature_hint=FeatureHint.ITEM_ID, cardinality=num_items,
                          embedding_dim=16)
    )
    trainer = Trainer(
        model=SasRec(schema=schema, embedding_dim=16, num_blocks=1,
                     max_sequence_length=seq_len),
        loss=CE(),
        optimizer=OptimizerFactory(name="sgd", learning_rate=0.1),
        mesh=make_mesh(jax.devices(), model_parallel=2),
        shard_vocab=True,
        seed=0,
    )
    state, reference_losses = None, []
    for step in range(6):
        rng = np.random.default_rng(step)
        items = rng.integers(0, num_items, (global_batch, seq_len + 1)).astype(np.int32)
        mask = np.ones((global_batch, seq_len), bool)
        batch = {
            "feature_tensors": {"item_id": items[:, :-1]},
            "padding_mask": mask,
            "positive_labels": items[:, 1:, None],
            "target_padding_mask": mask[:, :, None],
        }
        if state is None:
            state = trainer.init_state(batch)
        state, loss_value = trainer.train_step(state, batch)
        reference_losses.append(float(loss_value))

    np.testing.assert_allclose(first[0]["losses"], reference_losses[:3], rtol=1e-5)
    np.testing.assert_allclose(resume[0]["losses"], reference_losses[3:], rtol=1e-5)
