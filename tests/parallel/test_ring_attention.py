"""Ring attention == full attention, with the sequence sharded over 8 devices."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from replay_tpu.parallel import full_attention_reference, ring_attention

B, L, H, D = 2, 32, 2, 8  # L = 32 over 8 devices -> 4 tokens per shard


@pytest.fixture(scope="module")
def mesh():
    return Mesh(np.array(jax.devices()), ("sp",))


@pytest.fixture(scope="module")
def qkv():
    rng = np.random.default_rng(0)
    return tuple(jnp.asarray(rng.normal(size=(B, L, H, D)).astype(np.float32)) for _ in range(3))


@pytest.mark.jax
@pytest.mark.smoke
@pytest.mark.parametrize("causal", [False, True], ids=["bidirectional", "causal"])
def test_matches_full_attention(mesh, qkv, causal):
    q, k, v = qkv
    got = ring_attention(q, k, v, mesh, causal=causal)
    want = full_attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5)


@pytest.mark.jax
def test_respects_padding(mesh, qkv):
    q, k, v = qkv
    padding = jnp.asarray(np.random.default_rng(1).random((B, L)) > 0.3)
    got = ring_attention(q, k, v, mesh, causal=True, padding_mask=padding)
    want = full_attention_reference(q, k, v, causal=True, padding_mask=padding)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5)


@pytest.mark.jax
def test_rejects_indivisible_length(mesh, qkv):
    q, k, v = qkv
    with pytest.raises(ValueError, match="divisible"):
        ring_attention(q[:, :30], k[:, :30], v[:, :30], mesh)
