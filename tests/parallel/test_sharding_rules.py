"""The ONE sharding-rule table (parallel.sharding): logical-axis rules drive
every placement, the non-divisible fallback warns loudly, the ring-attention
DP×TP×SP production fit matches the unsharded fit, and the compiled SP program
moves exactly the intended collectives (ppermute ring traffic, no table
gather, no full-sequence all-gather).
"""

import warnings

import numpy as np
import pytest

from replay_tpu.parallel.sharding import (
    LOGICAL_AXES,
    ShardingRules,
    ShardingRuleWarning,
    _reset_rule_warnings,
    logical_axes,
)

# --------------------------------------------------------------------------- #
# core tier: the rule table + annotator are pure python
# --------------------------------------------------------------------------- #


@pytest.mark.core
def test_default_table_maps_the_dp_tp_sp_layout():
    rules = ShardingRules.default(shard_vocab=True)
    assert rules.mesh_axis("batch") == "data"
    assert rules.mesh_axis("length") == "seq"
    assert rules.mesh_axis("vocab") == "model"
    assert rules.mesh_axis("embed") is None
    assert ShardingRules.default().mesh_axis("vocab") is None  # TP is opt-in
    described = rules.describe()
    assert described["batch"] == "data" and described["vocab"] == "model"


@pytest.mark.core
def test_unknown_logical_name_is_an_error_not_replication():
    rules = ShardingRules.default()
    with pytest.raises(KeyError, match="unknown logical axis"):
        rules.mesh_axis("vocabb")
    with pytest.raises(KeyError, match="unknown logical axis"):
        rules.with_rule("vocabb", "model")


@pytest.mark.core
def test_with_rule_is_immutable_override():
    base = ShardingRules.default()
    tp = base.with_rule("vocab", "model")
    assert base.mesh_axis("vocab") is None
    assert tp.mesh_axis("vocab") == "model"


@pytest.mark.core
def test_annotator_covers_the_model_param_families():
    class Leaf:
        def __init__(self, *shape):
            self.shape = shape

    cases = {
        "body/embedder/embedding_item_id/table/embedding": (Leaf(16, 8), ("vocab", "embed")),
        "body/aggregator/positional_embedding": (Leaf(50, 8), ("position", "embed")),
        "body/mask_embedding": (Leaf(8,), ("embed",)),
        "body/encoder/block_0/attention/query/kernel": (Leaf(8, 8), ("embed", "heads")),
        "body/encoder/block_0/attention/out/kernel": (Leaf(8, 8), ("heads", "embed")),
        "body/encoder/block_0/ffn/inner/kernel": (Leaf(8, 32), ("embed", "mlp")),
        "body/encoder/block_0/ffn/outer/kernel": (Leaf(32, 8), ("mlp", "embed")),
        "body/encoder/block_0/attn_norm/scale": (Leaf(8,), ("embed",)),
        "body/final_norm/bias": (Leaf(8,), ("embed",)),
        # scan_blocks stacks a leading layers axis on every block param
        "body/encoder/blocks/block/attention/query/kernel": (
            Leaf(2, 8, 8), ("layers", "embed", "heads"),
        ),
        # unknown leaves replicate — never guessed from shapes
        "some/unknown/param": (Leaf(4, 4), (None, None)),
    }
    for path, (leaf, want) in cases.items():
        assert logical_axes(path, leaf) == want, path
    assert all(
        name in LOGICAL_AXES
        for _, (leaf, want) in cases.items()
        for name in want
        if name is not None
    )


# --------------------------------------------------------------------------- #
# jax tier: placement, parity, refusal and collective invariants on the
# virtual 8-device mesh
# --------------------------------------------------------------------------- #
NUM_ITEMS = 15  # 16-row table (cardinality + padding) divides model axes 2/4
SEQ_LEN = 8  # divides seq axes 2/4
BATCH = 4


def make_schema(cardinality=NUM_ITEMS):
    from replay_tpu.data import FeatureHint, FeatureType
    from replay_tpu.data.nn import TensorFeatureInfo, TensorSchema

    return TensorSchema(
        TensorFeatureInfo(
            "item_id",
            FeatureType.CATEGORICAL,
            is_seq=True,
            feature_hint=FeatureHint.ITEM_ID,
            cardinality=cardinality,
            embedding_dim=16,
        )
    )


def make_batch(seed, batch=BATCH, num_items=NUM_ITEMS):
    rng = np.random.default_rng(seed)
    items = rng.integers(0, num_items, size=(batch, SEQ_LEN + 1)).astype(np.int32)
    mask = np.ones((batch, SEQ_LEN), dtype=bool)
    return {
        "feature_tensors": {"item_id": items[:, :-1]},
        "padding_mask": mask,
        "positive_labels": items[:, 1:, None],
        "target_padding_mask": mask[:, :, None],
    }


def make_trainer(mesh, use_flash=False, num_items=NUM_ITEMS, loss=None, **kwargs):
    from replay_tpu.nn import OptimizerFactory, Trainer
    from replay_tpu.nn.loss import CE
    from replay_tpu.nn.sequential.sasrec import SasRec

    model = SasRec(
        schema=make_schema(num_items), embedding_dim=16, num_blocks=2,
        max_sequence_length=SEQ_LEN, use_flash=use_flash,
    )
    return Trainer(
        model=model,
        loss=loss if loss is not None else CE(),
        # SGD: parity asserts near-exact equivalence; adaptive optimizers
        # amplify device-count-dependent summation noise (test_mesh_training)
        optimizer=OptimizerFactory(name="sgd", learning_rate=0.1),
        mesh=mesh,
        seed=0,
        **kwargs,
    )


@pytest.mark.jax
@pytest.mark.smoke
def test_params_placed_by_the_rule_table():
    import jax

    from replay_tpu.nn import make_mesh

    trainer = make_trainer(make_mesh(model_parallel=2), shard_vocab=True)
    state = trainer.init_state(make_batch(0))
    specs = {
        jax.tree_util.keystr(path): str(leaf.sharding.spec)
        for path, leaf in jax.tree_util.tree_flatten_with_path(state.params)[0]
    }
    vocab = [spec for path, spec in specs.items() if "embedding_item_id" in path]
    assert vocab and all("model" in spec for spec in vocab), specs
    others = [
        spec for path, spec in specs.items() if "embedding_item_id" not in path
    ]
    assert others and all("model" not in spec for spec in others), specs


@pytest.mark.jax
@pytest.mark.smoke
def test_non_divisible_vocab_warns_once_and_replicates():
    """Satellite: the silent shard_vocab fallback is now loud — a table whose
    rows don't divide the model axis warns ONCE with the shape/axis, then
    replicates."""
    import jax

    from replay_tpu.nn import make_mesh

    _reset_rule_warnings()
    # cardinality 14 -> 15-row table: not divisible by the 2-way model axis
    trainer = make_trainer(make_mesh(model_parallel=2), num_items=14, shard_vocab=True)
    with pytest.warns(ShardingRuleWarning, match=r"15 rows.*2-way.*model"):
        state = trainer.init_state(make_batch(0, num_items=14))
    specs = [
        str(leaf.sharding.spec)
        for path, leaf in jax.tree_util.tree_flatten_with_path(state.params)[0]
        if "embedding_item_id" in jax.tree_util.keystr(path)
    ]
    assert specs and all("model" not in spec for spec in specs), specs
    # once per process: the same offending leaf does not warn again
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        trainer.init_state(make_batch(1, num_items=14))
    assert not [w for w in caught if issubclass(w.category, ShardingRuleWarning)]


@pytest.mark.jax
@pytest.mark.smoke
def test_ring_sp_fit_matches_unsharded_fit():
    """The SP production path: a DP×TP×SP chunked fit through ring attention
    equals the single-device fit (losses and params)."""
    import jax

    from replay_tpu.nn import make_mesh

    if jax.device_count() < 8:
        pytest.skip("needs the virtual 8-device mesh")

    def run(mesh, use_flash, **kwargs):
        trainer = make_trainer(mesh, use_flash=use_flash, **kwargs)
        batches = [make_batch(s) for s in range(4)]
        state = trainer.fit(batches, epochs=1, scan_chunk=2, log_every=0)
        return (
            [float(r["train_loss"]) for r in trainer.history],
            jax.tree.map(np.asarray, state.params),
        )

    losses_1, params_1 = run(make_mesh(jax.devices()[:1]), False)
    losses_sp, params_sp = run(
        make_mesh(model_parallel=2, seq_parallel=2), "ring", shard_vocab=True
    )
    np.testing.assert_allclose(losses_1, losses_sp, rtol=2e-4)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-3, atol=2e-5),
        params_1,
        params_sp,
    )


@pytest.mark.jax
def test_bert4rec_ring_sp_matches_unsharded():
    """The second model body: Bert4Rec's bidirectional attention through the
    ring SP route equals the single-device fit — one rule table, both models."""
    import jax

    from replay_tpu.nn import OptimizerFactory, Trainer, make_mesh
    from replay_tpu.nn.loss import CE
    from replay_tpu.nn.sequential.bert4rec import Bert4Rec

    if jax.device_count() < 8:
        pytest.skip("needs the virtual 8-device mesh")

    def mlm_batch(seed):
        rng = np.random.default_rng(seed)
        items = rng.integers(0, NUM_ITEMS, size=(BATCH, SEQ_LEN)).astype(np.int32)
        mask = np.ones((BATCH, SEQ_LEN), bool)
        token_mask = rng.random((BATCH, SEQ_LEN)) > 0.3
        return {
            "feature_tensors": {"item_id": items},
            "padding_mask": mask,
            "token_mask": token_mask,
            "positive_labels": items[:, :, None],
            "target_padding_mask": (~token_mask)[:, :, None],
        }

    def run(mesh, use_flash):
        model = Bert4Rec(
            schema=make_schema(), embedding_dim=16, num_blocks=2, num_heads=2,
            max_sequence_length=SEQ_LEN, use_flash=use_flash,
        )
        trainer = Trainer(
            model=model, loss=CE(),
            optimizer=OptimizerFactory(name="sgd", learning_rate=0.1),
            mesh=mesh, seed=0,
        )
        state = trainer.init_state(mlm_batch(0))
        out = []
        for step in range(3):
            state, loss_value = trainer.train_step(state, mlm_batch(step))
            out.append(float(loss_value))
        return out

    base = run(make_mesh(jax.devices()[:1]), False)
    sp = run(make_mesh(model_parallel=2, seq_parallel=2), "ring")
    np.testing.assert_allclose(base, sp, rtol=2e-4)


@pytest.mark.jax
def test_ring_sp_fit_parity_at_the_bf16_band():
    """The precision ladder composes with SP: the bf16 DP×SP ring fit stays
    within the bf16 input-rounding band of the bf16 unsharded fit."""
    import jax

    from replay_tpu.nn import make_mesh

    if jax.device_count() < 8:
        pytest.skip("needs the virtual 8-device mesh")

    def run(mesh, use_flash):
        trainer = make_trainer(mesh, use_flash=use_flash, precision="bf16")
        batches = [make_batch(s) for s in range(3)]
        trainer.fit(batches, epochs=1, log_every=0)
        return [float(r["train_loss"]) for r in trainer.history]

    base = run(make_mesh(jax.devices()[:1]), False)
    sp = run(make_mesh(seq_parallel=4), "ring")
    assert all(np.isfinite(base)) and all(np.isfinite(sp))
    np.testing.assert_allclose(base, sp, rtol=5e-2)


@pytest.mark.jax
def test_ring_attention_op_level_parity_under_scope():
    """Op-level: the MultiHeadAttention ring route under the trainer's scope
    equals the standard einsum route with the SAME params."""
    import jax
    import jax.numpy as jnp

    from replay_tpu.nn import make_mesh
    from replay_tpu.nn.attention import MultiHeadAttention
    from replay_tpu.nn.mask import causal_attention_mask
    from replay_tpu.parallel.sharding import sharding_scope

    mesh = make_mesh(seq_parallel=4)
    rules = ShardingRules.default()
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(2, SEQ_LEN, 16)).astype(np.float32))
    padding = jnp.ones((2, SEQ_LEN), bool)

    standard = MultiHeadAttention(num_heads=2)
    params = standard.init(
        jax.random.PRNGKey(0), x, causal_attention_mask(padding), padding_mask=padding
    )
    want = standard.apply(params, x, causal_attention_mask(padding), padding_mask=padding)
    ring = MultiHeadAttention(num_heads=2, use_flash="ring")
    with sharding_scope(rules, mesh):
        got = jax.jit(
            lambda p, x: ring.apply(p, x, None, padding_mask=padding, causal=True)
        )(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5)


@pytest.mark.jax
@pytest.mark.smoke
def test_packed_segments_meet_sp_route_rejected():
    """Satellite: PackedSequenceBatcher segment masks meeting the ring SP
    route must refuse (the flash-route refusal policy), never silently attend
    across packed segment boundaries."""
    import jax

    from replay_tpu.nn import make_mesh

    trainer = make_trainer(make_mesh(seq_parallel=2), use_flash="ring")
    batch = make_batch(0)
    batch["segment_ids"] = np.ones((BATCH, SEQ_LEN), np.int32)
    with pytest.raises(ValueError, match="ring SP route"):
        state = trainer.init_state({k: v for k, v in batch.items() if k != "segment_ids"})
        trainer.train_step(state, batch)


@pytest.mark.jax
def test_seq_parallel_without_ring_route_rejected():
    """A seq>1 mesh under a model that would build [B, 1, L, L] masks is a
    configuration error (XLA would all-gather the sequence), not a silent
    performance cliff."""
    from replay_tpu.nn import make_mesh

    with pytest.raises(ValueError, match="ring"):
        make_trainer(make_mesh(seq_parallel=2), use_flash=False)


@pytest.mark.jax
@pytest.mark.smoke
def test_sp_program_collectives_are_exactly_the_intended_ones():
    """The compiled DP×TP×SP step: ppermute-only ring traffic on the seq axis,
    no item-table-sized all-gather, no full-sequence activation all-gather —
    the rule table produces exactly the intended collectives."""
    import jax

    from replay_tpu.nn import make_mesh
    from replay_tpu.nn.loss import CEFusedTP
    from replay_tpu.parallel.introspect import collective_inventory, sharding_report

    if jax.device_count() < 8:
        pytest.skip("needs the virtual 8-device mesh")
    mesh = make_mesh(model_parallel=2, seq_parallel=2)
    trainer = make_trainer(
        mesh, use_flash="ring", shard_vocab=True,
        loss=CEFusedTP(tile=8, interpret=True),
    )
    # the rule table routes the loss's layout too: catalog over the vocab
    # rule, flattened [B·L] rows over (batch, length)
    batch = make_batch(0)
    state = trainer.init_state(batch)
    state, loss_value = trainer.train_step(state, batch)
    assert np.isfinite(float(loss_value))
    assert trainer.loss.axis_name == "model"
    assert trainer.loss.data_axis == ("data", "seq")

    report = sharding_report(state.params, mesh, rules=trainer.sharding_rules)
    assert report["flags"] == [], report["flags"]
    assert report["sharded_bytes"] > 0

    hlo = trainer.lowered_hlo("train_step")
    inventory = collective_inventory(
        hlo, mesh_shape={axis: int(n) for axis, n in mesh.shape.items()}
    )
    permutes = [e for e in inventory if e["op"] == "collective-permute"]
    assert permutes, "ring attention left no ppermute traffic"
    # ring traffic on the seq axis is ppermute-only at activation scale: an
    # all-gather of a [B_local, L, E] (or bigger) tensor over seq would be
    # the full-sequence materialization SP exists to avoid. Param-sized
    # combines (the replicated positional table's gradient) stay legal.
    full_seq_bytes = (BATCH // 2) * SEQ_LEN * 16 * 4  # [B/dp, L, E] f32
    seq_gathers = [
        e for e in inventory
        if e["op"] == "all-gather"
        and e.get("mesh_axis") == "seq"
        and (e.get("bytes") or 0) >= full_seq_bytes
    ]
    assert not seq_gathers, seq_gathers
    # the item table (16 padded rows × 16 f32 = 1 KiB) must never be gathered
    # to one device over the model axis — only the [rows]-sized lse combine
    # and sub-table-sized resharding traffic may move there
    full_table_bytes = (NUM_ITEMS + 1) * 16 * 4
    table_gathers = [
        e for e in inventory
        if e["op"] == "all-gather"
        and e.get("mesh_axis") == "model"
        and (e.get("bytes") or 0) >= full_table_bytes
    ]
    assert not table_gathers, table_gathers


@pytest.mark.jax
def test_rule_table_report_flags_accidental_replication():
    """sharding_report(rules=...): a table the rules wanted sharded but that
    lowered replicated is flagged — the silent degeneration mode."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from replay_tpu.nn import make_mesh
    from replay_tpu.parallel.introspect import sharding_report

    mesh = make_mesh(model_parallel=2)
    trainer = make_trainer(mesh, shard_vocab=True)
    state = trainer.init_state(make_batch(0))
    # force the vocab table fully replicated behind the rules' back
    broken = jax.tree_util.tree_map_with_path(
        lambda path, leaf: (
            jax.device_put(leaf, NamedSharding(mesh, P()))
            if "embedding_item_id" in jax.tree_util.keystr(path)
            else leaf
        ),
        state.params,
    )
    report = sharding_report(broken, mesh, rules=trainer.sharding_rules)
    assert any("accidental replication" in flag for flag in report["flags"]), report


@pytest.mark.jax
def test_scan_blocks_trains_and_stacks_params():
    """scan-over-blocks: one scanned block body, [layers, ...] params, finite
    losses, and the annotator prepends the layers axis."""
    import jax

    from replay_tpu.nn import make_mesh
    from replay_tpu.nn.loss import CE
    from replay_tpu.nn import OptimizerFactory, Trainer
    from replay_tpu.nn.sequential.sasrec import SasRec
    from replay_tpu.parallel.sharding import logical_axes

    model = SasRec(
        schema=make_schema(), embedding_dim=16, num_blocks=3,
        max_sequence_length=SEQ_LEN, scan_blocks=True,
    )
    trainer = Trainer(
        model=model, loss=CE(),
        optimizer=OptimizerFactory(name="sgd", learning_rate=0.1),
        mesh=make_mesh(jax.devices()[:1]), remat_policy="dots", seed=0,
    )
    state = trainer.init_state(make_batch(0))
    stacked = [
        (path, leaf)
        for path, leaf in jax.tree_util.tree_flatten_with_path(state.params)[0]
        if "blocks" in jax.tree_util.keystr(path)
    ]
    assert stacked and all(leaf.shape[0] == 3 for _, leaf in stacked)
    path, leaf = next(
        (p, l) for p, l in stacked if "kernel" in jax.tree_util.keystr(p)
    )
    assert logical_axes(path, leaf)[0] == "layers"
    state, loss_value = trainer.train_step(state, make_batch(1))
    assert np.isfinite(float(loss_value))


@pytest.mark.jax
def test_remat_policy_is_numerically_invisible():
    """Trainer(remat_policy=...) trades HBM for FLOPs only: losses equal the
    un-rematerialized fit exactly."""
    import jax

    from replay_tpu.nn import make_mesh

    def run(**kwargs):
        trainer = make_trainer(make_mesh(jax.devices()[:1]), **kwargs)
        state = trainer.init_state(make_batch(0))
        losses = []
        for step in range(3):
            state, loss_value = trainer.train_step(state, make_batch(step))
            losses.append(float(loss_value))
        return losses

    np.testing.assert_allclose(run(), run(remat_policy="full"), rtol=1e-6)
    np.testing.assert_allclose(run(), run(remat_policy="dots"), rtol=1e-6)
