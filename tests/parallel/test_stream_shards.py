"""Multi-host shard assignment for the streaming parquet reader, validated
with the fake-replica layout trick on the 8-device virtual mesh (the same
seam a real multi-host run derives from ``jax.process_index()``)."""

import numpy as np
import pytest

import jax

from replay_tpu.data.nn import ParquetBatcher, Partitioning, ReplicasInfo
from replay_tpu.data.nn.parquet import StreamCursor

N_ROWS = 103
GROUP_SIZE = 8  # 13 row groups: more groups than the 8 replicas
BATCH = 4


@pytest.fixture(scope="module")
def grouped_parquet(tmp_path_factory):
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(0)
    path = str(tmp_path_factory.mktemp("shards") / "stream.parquet")
    table = pa.table(
        {
            "query_id": np.arange(N_ROWS),
            "item_id": [
                rng.integers(0, 50, rng.integers(1, 7)).tolist()
                for _ in range(N_ROWS)
            ],
        }
    )
    pq.write_table(table, path, row_group_size=GROUP_SIZE)
    return path


def _batcher_for(path, replica, num_replicas, **kwargs):
    return ParquetBatcher(
        path, batch_size=BATCH, shuffle=True, seed=5, shard="row_groups",
        metadata={"item_id": {"shape": 5, "padding": 50}},
        partitioning=Partitioning(
            ReplicasInfo(num_replicas, replica), shuffle=True, seed=5
        ),
        **kwargs,
    )


def _batches_for(path, replica, num_replicas, epoch, **kwargs):
    batcher = _batcher_for(path, replica, num_replicas, **kwargs)
    batcher.set_epoch(epoch)
    return list(batcher)


def replica_batches(path, replica, num_replicas, epoch):
    return _batches_for(path, replica, num_replicas, epoch)


class TestEightProcessSharding:
    NUM = 8  # one replica per virtual device's host process

    def test_disjoint_exactly_once_per_epoch(self, grouped_parquet):
        for epoch in (0, 1):
            seen = []
            for replica in range(self.NUM):
                for batch in replica_batches(grouped_parquet, replica, self.NUM, epoch):
                    seen.extend(batch["query_id"][batch["valid"]].tolist())
            assert sorted(seen) == list(range(N_ROWS)), f"epoch {epoch}"

    def test_equal_step_counts_for_the_collective_invariant(self, grouped_parquet):
        counts = {
            replica: len(replica_batches(grouped_parquet, replica, self.NUM, 0))
            for replica in range(self.NUM)
        }
        assert len(set(counts.values())) == 1, counts

    def test_shapes_divide_the_data_axis(self, grouped_parquet):
        """Every emitted batch keeps the fixed [B, L]; B x process_count is
        divisible by the 8-way data axis, the _batch_sharding precondition."""
        assert len(jax.devices()) == 8
        for replica in range(self.NUM):
            for batch in replica_batches(grouped_parquet, replica, self.NUM, 0):
                assert batch["item_id"].shape == (BATCH, 5)
                assert (BATCH * self.NUM) % 8 == 0

    def test_reads_are_disjoint_byte_ranges(self, grouped_parquet):
        """Each replica's planned slabs touch a DISJOINT set of row groups —
        the I/O win over every host scanning every slab."""
        groups_by_replica = {}
        for replica in range(self.NUM):
            batcher = ParquetBatcher(
                grouped_parquet, batch_size=BATCH, shuffle=True, seed=5,
                shard="row_groups",
                metadata={"item_id": {"shape": 5, "padding": 50}},
                partitioning=Partitioning(
                    ReplicasInfo(self.NUM, replica), shuffle=True, seed=5
                ),
            )
            slabs, _, _ = batcher._plan(0)
            groups_by_replica[replica] = {slab.group for slab in slabs}
        for a in range(self.NUM):
            for b in range(a + 1, self.NUM):
                assert not (groups_by_replica[a] & groups_by_replica[b])
        assert sorted(
            g for groups in groups_by_replica.values() for g in groups
        ) == list(range(-(-N_ROWS // GROUP_SIZE)))

    def test_per_replica_cursor_resume(self, grouped_parquet):
        """Every replica's shard is independently resumable (each process
        checkpoints ITS cursor)."""
        for replica in (0, 3, 7):
            full = replica_batches(grouped_parquet, replica, self.NUM, 1)
            producer = _batcher_for(grouped_parquet, replica, self.NUM)
            producer.set_epoch(1)
            iterator = iter(producer)
            next(iterator)
            next(iterator)
            cursor = producer.cursor_for(2).to_metadata()
            resumed = _batcher_for(grouped_parquet, replica, self.NUM)
            resumed.set_epoch(1)
            resumed.restore_cursor(cursor)
            rest = list(resumed)
            assert len(rest) == len(full) - 2
            for a, b in zip(full[2:], rest):
                for key in a:
                    np.testing.assert_array_equal(a[key], b[key])


class TestElasticRehash:
    """``StreamCursor.rehash``: the sanctioned mid-epoch migration of a
    row-group plan onto a DIFFERENT replica count (elastic resume) — the
    refusal the plan fingerprint used to raise, turned into an exactly-once
    supported path."""

    def test_rehash_migrates_onto_more_replicas_exactly_once(self, grouped_parquet):
        """The elastic-resume headline: the SAME mid-epoch position that the
        fingerprint check refuses under a changed replica count migrates
        cleanly through ``StreamCursor.rehash`` — consumed rows never
        re-emitted, unseen rows all assigned, exactly once across the new
        layout."""
        old_n, new_n, epoch, ordinal = 2, 3, 0, 5
        consumed = []
        cursor = None
        for replica in range(old_n):
            batches = _batches_for(grouped_parquet, replica, old_n, epoch)
            for batch in batches[:ordinal]:
                consumed.extend(batch["query_id"][batch["valid"]].tolist())
            if replica == 0:
                producer = _batcher_for(grouped_parquet, replica, old_n)
                producer.set_epoch(epoch)
                list(producer)
                cursor = producer.cursor_for(ordinal)

        migrated = cursor.rehash(new_n)
        remaining = []
        for replica in range(new_n):
            resumed = _batcher_for(grouped_parquet, replica, new_n)
            resumed.set_epoch(epoch)
            resumed.restore_cursor(migrated.to_metadata())
            for batch in list(resumed):
                remaining.extend(batch["query_id"][batch["valid"]].tolist())

        assert len(consumed) == len(set(consumed))
        assert len(remaining) == len(set(remaining))
        assert not set(consumed) & set(remaining), "a consumed row was re-emitted"
        assert sorted(consumed + remaining) == list(range(N_ROWS))

    def test_rehash_equalizes_step_counts_on_the_new_layout(self, grouped_parquet):
        """The collective invariant survives migration: every NEW replica
        emits the same batch count, continuing from the migration ordinal."""
        old_n, new_n, epoch, ordinal = 2, 3, 1, 4
        producer = _batcher_for(grouped_parquet, 0, old_n)
        producer.set_epoch(epoch)
        list(producer)
        migrated = producer.cursor_for(ordinal).rehash(new_n)
        counts = {}
        for replica in range(new_n):
            resumed = _batcher_for(grouped_parquet, replica, new_n)
            resumed.set_epoch(epoch)
            resumed.restore_cursor(migrated)
            counts[replica] = len(list(resumed))
        assert len(set(counts.values())) == 1, counts

    def test_migration_coverage_audit_is_exact(self, grouped_parquet):
        old_n, new_n, epoch, ordinal = 2, 3, 0, 5
        producer = _batcher_for(grouped_parquet, 0, old_n)
        producer.set_epoch(epoch)
        list(producer)
        migrated = producer.cursor_for(ordinal).rehash(new_n)
        auditor = _batcher_for(grouped_parquet, 0, new_n)
        audit = auditor.migration_coverage(migrated)
        assert audit["total_rows"] == N_ROWS
        assert audit["consumed_rows"] + audit["assigned_rows"] == N_ROWS
        # at ordinal 5 no old replica had exhausted its ~51-row share yet
        assert audit["consumed_rows"] == old_n * ordinal * BATCH
        assert (
            sum(audit["assigned_rows_per_replica"].values())
            == audit["assigned_rows"]
        )
        assert audit["new_replicas"] == new_n

    def test_raw_cursor_still_refused_under_changed_layout(self, grouped_parquet):
        """rehash is the ONLY sanctioned migration: restoring an un-rehashed
        cursor under a different replica count keeps failing loudly (and the
        refusal now names the supported path)."""
        producer = _batcher_for(grouped_parquet, 0, 2)
        producer.set_epoch(0)
        list(producer)
        cursor = producer.cursor_for(3)
        stranger = _batcher_for(grouped_parquet, 0, 3)
        stranger.set_epoch(0)
        with pytest.raises(ValueError, match="rehash"):
            stranger.restore_cursor(cursor.to_metadata())

    def test_rehash_refuses_chaining_and_wrong_targets(self, grouped_parquet):
        producer = _batcher_for(grouped_parquet, 0, 2)
        producer.set_epoch(0)
        list(producer)
        migrated = producer.cursor_for(2).rehash(3)
        with pytest.raises(ValueError, match="rehash"):
            migrated.rehash(4)  # rehash-of-rehash: finish the epoch first
        with pytest.raises(ValueError):
            StreamCursor(epoch=0, slab=0, rows=0, batches=2).rehash(3)  # no plan
        # a rehashed cursor only restores on the layout it targets
        wrong = _batcher_for(grouped_parquet, 0, 4)
        wrong.set_epoch(0)
        with pytest.raises(ValueError, match="replica"):
            wrong.restore_cursor(migrated)

    def test_mid_migration_cursor_resumes_within_the_migrated_plan(
        self, grouped_parquet
    ):
        """Cursors recorded DURING a migrated epoch are themselves resumable:
        a new-layout replica that is preempted mid-migration seeks back to its
        position in the migration work list bit-for-bit."""
        old_n, new_n, epoch, ordinal = 2, 3, 0, 4
        producer = _batcher_for(grouped_parquet, 0, old_n)
        producer.set_epoch(epoch)
        list(producer)
        migrated = producer.cursor_for(ordinal).rehash(new_n)

        replica = 1
        first = _batcher_for(grouped_parquet, replica, new_n)
        first.set_epoch(epoch)
        first.restore_cursor(migrated)
        full = list(first)
        assert full, "migrated share should emit at least one batch"

        again = _batcher_for(grouped_parquet, replica, new_n)
        again.set_epoch(epoch)
        again.restore_cursor(migrated)
        iterator = iter(again)
        next(iterator)
        mid = again.cursor_for(ordinal + 1)
        assert mid.migration is not None

        resumed = _batcher_for(grouped_parquet, replica, new_n)
        resumed.set_epoch(epoch)
        resumed.restore_cursor(mid.to_metadata())
        rest = list(resumed)
        assert len(rest) == len(full) - 1
        for a, b in zip(full[1:], rest):
            for key in a:
                np.testing.assert_array_equal(a[key], b[key])

    def test_rehash_with_memory_budget_sub_slabs(self, grouped_parquet):
        """Migration replays the old plan's sub-slab split too: with a byte
        budget forcing multi-slab groups, coverage stays exactly-once."""
        old_n, new_n, epoch, ordinal = 2, 3, 0, 3
        kwargs = {"memory_budget_bytes": 256}
        consumed = []
        cursor = None
        for replica in range(old_n):
            batcher = _batcher_for(grouped_parquet, replica, old_n, **kwargs)
            batcher.set_epoch(epoch)
            batches = list(batcher)
            for batch in batches[:ordinal]:
                consumed.extend(batch["query_id"][batch["valid"]].tolist())
            if replica == 0:
                cursor = batcher.cursor_for(ordinal)
        migrated = cursor.rehash(new_n)
        remaining = []
        for replica in range(new_n):
            resumed = _batcher_for(grouped_parquet, replica, new_n, **kwargs)
            resumed.set_epoch(epoch)
            resumed.restore_cursor(migrated)
            for batch in list(resumed):
                remaining.extend(batch["query_id"][batch["valid"]].tolist())
        assert sorted(consumed + remaining) == list(range(N_ROWS))
        assert not set(consumed) & set(remaining)

