"""Multi-host shard assignment for the streaming parquet reader, validated
with the fake-replica layout trick on the 8-device virtual mesh (the same
seam a real multi-host run derives from ``jax.process_index()``)."""

import numpy as np
import pytest

import jax

from replay_tpu.data.nn import ParquetBatcher, Partitioning, ReplicasInfo

N_ROWS = 103
GROUP_SIZE = 8  # 13 row groups: more groups than the 8 replicas
BATCH = 4


@pytest.fixture(scope="module")
def grouped_parquet(tmp_path_factory):
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(0)
    path = str(tmp_path_factory.mktemp("shards") / "stream.parquet")
    table = pa.table(
        {
            "query_id": np.arange(N_ROWS),
            "item_id": [
                rng.integers(0, 50, rng.integers(1, 7)).tolist()
                for _ in range(N_ROWS)
            ],
        }
    )
    pq.write_table(table, path, row_group_size=GROUP_SIZE)
    return path


def replica_batches(path, replica, num_replicas, epoch):
    batcher = ParquetBatcher(
        path, batch_size=BATCH, shuffle=True, seed=5, shard="row_groups",
        metadata={"item_id": {"shape": 5, "padding": 50}},
        partitioning=Partitioning(
            ReplicasInfo(num_replicas, replica), shuffle=True, seed=5
        ),
    )
    batcher.set_epoch(epoch)
    return list(batcher)


class TestEightProcessSharding:
    NUM = 8  # one replica per virtual device's host process

    def test_disjoint_exactly_once_per_epoch(self, grouped_parquet):
        for epoch in (0, 1):
            seen = []
            for replica in range(self.NUM):
                for batch in replica_batches(grouped_parquet, replica, self.NUM, epoch):
                    seen.extend(batch["query_id"][batch["valid"]].tolist())
            assert sorted(seen) == list(range(N_ROWS)), f"epoch {epoch}"

    def test_equal_step_counts_for_the_collective_invariant(self, grouped_parquet):
        counts = {
            replica: len(replica_batches(grouped_parquet, replica, self.NUM, 0))
            for replica in range(self.NUM)
        }
        assert len(set(counts.values())) == 1, counts

    def test_shapes_divide_the_data_axis(self, grouped_parquet):
        """Every emitted batch keeps the fixed [B, L]; B x process_count is
        divisible by the 8-way data axis, the _batch_sharding precondition."""
        assert len(jax.devices()) == 8
        for replica in range(self.NUM):
            for batch in replica_batches(grouped_parquet, replica, self.NUM, 0):
                assert batch["item_id"].shape == (BATCH, 5)
                assert (BATCH * self.NUM) % 8 == 0

    def test_reads_are_disjoint_byte_ranges(self, grouped_parquet):
        """Each replica's planned slabs touch a DISJOINT set of row groups —
        the I/O win over every host scanning every slab."""
        groups_by_replica = {}
        for replica in range(self.NUM):
            batcher = ParquetBatcher(
                grouped_parquet, batch_size=BATCH, shuffle=True, seed=5,
                shard="row_groups",
                metadata={"item_id": {"shape": 5, "padding": 50}},
                partitioning=Partitioning(
                    ReplicasInfo(self.NUM, replica), shuffle=True, seed=5
                ),
            )
            slabs, _, _ = batcher._plan(0)
            groups_by_replica[replica] = {slab.group for slab in slabs}
        for a in range(self.NUM):
            for b in range(a + 1, self.NUM):
                assert not (groups_by_replica[a] & groups_by_replica[b])
        assert sorted(
            g for groups in groups_by_replica.values() for g in groups
        ) == list(range(-(-N_ROWS // GROUP_SIZE)))

    def test_per_replica_cursor_resume(self, grouped_parquet):
        """Every replica's shard is independently resumable (each process
        checkpoints ITS cursor)."""
        for replica in (0, 3, 7):
            full = replica_batches(grouped_parquet, replica, self.NUM, 1)
            part = Partitioning(ReplicasInfo(self.NUM, replica), shuffle=True, seed=5)
            producer = ParquetBatcher(
                grouped_parquet, batch_size=BATCH, shuffle=True, seed=5,
                shard="row_groups",
                metadata={"item_id": {"shape": 5, "padding": 50}},
                partitioning=part,
            )
            producer.set_epoch(1)
            iterator = iter(producer)
            next(iterator)
            next(iterator)
            cursor = producer.cursor_for(2).to_metadata()
            resumed = ParquetBatcher(
                grouped_parquet, batch_size=BATCH, shuffle=True, seed=5,
                shard="row_groups",
                metadata={"item_id": {"shape": 5, "padding": 50}},
                partitioning=part,
            )
            resumed.set_epoch(1)
            resumed.restore_cursor(cursor)
            rest = list(resumed)
            assert len(rest) == len(full) - 2
            for a, b in zip(full[2:], rest):
                for key in a:
                    np.testing.assert_array_equal(a[key], b[key])
