"""Discretizer, Sessionizer, CSRConverter, HistoryBasedFeaturesProcessor."""

import numpy as np
import pandas as pd
import pytest

from replay_tpu.preprocessing import (
    CSRConverter,
    Discretizer,
    HistoryBasedFeaturesProcessor,
    QuantileDiscretizingRule,
    Sessionizer,
    UniformDiscretizingRule,
)


class TestDiscretizer:
    def test_quantile_bins_balanced(self):
        df = pd.DataFrame({"x": np.arange(100, dtype=float)})
        out = Discretizer([QuantileDiscretizingRule("x", n_bins=4)]).fit_transform(df)
        counts = out["x"].value_counts()
        assert sorted(out["x"].unique()) == [0, 1, 2, 3]
        assert counts.max() - counts.min() <= 2  # equal-frequency to within edges

    def test_uniform_bins_edges(self):
        df = pd.DataFrame({"x": [0.0, 2.5, 4.9, 5.0, 9.9, 10.0]})
        out = Discretizer([UniformDiscretizingRule("x", n_bins=2)]).fit_transform(df)
        assert out["x"].tolist() == [0, 0, 0, 1, 1, 1]

    def test_nan_handling(self):
        df = pd.DataFrame({"x": [1.0, np.nan, 3.0]})
        with pytest.raises(ValueError, match="NaN"):
            Discretizer([QuantileDiscretizingRule("x", n_bins=2)]).fit_transform(df)
        keep = Discretizer([QuantileDiscretizingRule("x", n_bins=2, handle_invalid="keep")])
        out = keep.fit_transform(df)
        assert out["x"].iloc[1] == out["x"].max()  # NaN bucket is the extra last one
        skip = Discretizer([QuantileDiscretizingRule("x", n_bins=2, handle_invalid="skip")])
        out2 = skip.fit_transform(df)
        assert np.isnan(out2["x"].iloc[1])

    def test_few_distinct_values(self):
        df = pd.DataFrame({"x": [1.0, 1.0, 1.0, 2.0]})
        out = Discretizer([QuantileDiscretizingRule("x", n_bins=10)]).fit_transform(df)
        assert out["x"].nunique() <= 2

    def test_save_load(self, tmp_path):
        df = pd.DataFrame({"x": np.arange(50, dtype=float), "y": np.arange(50, dtype=float)})
        disc = Discretizer(
            [QuantileDiscretizingRule("x", 4), UniformDiscretizingRule("y", 3)]
        ).fit(df)
        disc.save(str(tmp_path / "disc"))
        restored = Discretizer.load(str(tmp_path / "disc"))
        pd.testing.assert_frame_equal(disc.transform(df), restored.transform(df))

    def test_bad_args(self):
        with pytest.raises(ValueError):
            QuantileDiscretizingRule("x", n_bins=1)
        with pytest.raises(ValueError):
            QuantileDiscretizingRule("x", handle_invalid="zzz")


class TestSessionizer:
    def test_gap_splits_sessions(self):
        df = pd.DataFrame(
            {
                "query_id": [1, 1, 1, 1, 2],
                "item_id": [0, 1, 2, 3, 4],
                "timestamp": [0, 10, 2000, 2010, 5],
            }
        )
        out = Sessionizer(session_gap=100).transform(df)
        sessions = out["session_id"].tolist()
        assert sessions[0] == sessions[1]  # gap 10 <= 100
        assert sessions[2] == sessions[3] != sessions[0]  # gap 1990 > 100
        assert sessions[4] not in sessions[:4]  # new user -> new session
        assert out.index.tolist() == df.index.tolist()  # original order kept

    def test_length_filters(self):
        df = pd.DataFrame(
            {
                "query_id": [1] * 3 + [2],
                "item_id": range(4),
                "timestamp": [0, 1, 2, 0],
            }
        )
        out = Sessionizer(session_gap=10, min_session_length=2).transform(df)
        assert set(out["query_id"]) == {1}
        out2 = Sessionizer(session_gap=10, max_session_length=1).transform(df)
        assert set(out2["query_id"]) == {2}


class TestCSRConverter:
    def test_basic_and_duplicates(self):
        df = pd.DataFrame(
            {"query_id": [0, 0, 1, 1], "item_id": [0, 0, 1, 2], "rating": [1.0, 2.0, 3.0, 4.0]}
        )
        matrix = CSRConverter(data_column="rating").transform(df)
        assert matrix.shape == (2, 3)
        assert matrix[0, 0] == 3.0  # duplicates summed
        assert matrix[1, 2] == 4.0
        ones = CSRConverter().transform(df)
        assert ones[0, 0] == 2.0

    def test_extent_and_validation(self):
        df = pd.DataFrame({"query_id": [0], "item_id": [1]})
        matrix = CSRConverter(row_count=5, column_count=7).transform(df)
        assert matrix.shape == (5, 7)
        with pytest.raises(ValueError, match="extent"):
            CSRConverter(column_count=1).transform(df)
        with pytest.raises(ValueError, match="integer-encoded"):
            CSRConverter().transform(pd.DataFrame({"query_id": ["a"], "item_id": [0]}))


class TestHistoryBasedFeaturesProcessor:
    def make_log(self):
        return pd.DataFrame(
            {
                "query_id": [0, 0, 0, 1, 1, 2],
                "item_id": [0, 1, 2, 0, 1, 2],
                "rating": [5.0, 3.0, 4.0, 1.0, 2.0, 3.0],
                "timestamp": [0, 10, 20, 5, 15, 30],
            }
        )

    def test_log_features(self):
        fp = HistoryBasedFeaturesProcessor(use_conditional_popularity=False)
        fp.fit(self.make_log())
        pairs = pd.DataFrame({"query_id": [0, 1], "item_id": [2, 2]})
        out = fp.transform(pairs)
        assert out.loc[0, "q_log_count"] == 3
        assert out.loc[1, "q_distinct_items"] == 2
        assert out.loc[0, "q_mean_rating"] == pytest.approx(4.0)
        assert out.loc[0, "i_log_count"] == 2  # item 2 appears twice
        assert "i_popularity_share" in out.columns

    def test_conditional_popularity(self):
        item_features = pd.DataFrame({"item_id": [0, 1, 2], "genre": ["a", "a", "b"]})
        fp = HistoryBasedFeaturesProcessor(
            use_log_features=False, item_cat_features_list=["genre"]
        )
        fp.fit(self.make_log(), item_features=item_features)
        out = fp.transform(pd.DataFrame({"query_id": [0], "item_id": [0]}))
        assert out.loc[0, "q_share_genre_a"] == pytest.approx(2 / 3)
        assert out.loc[0, "q_share_genre_b"] == pytest.approx(1 / 3)

    def test_unfitted_raises(self):
        with pytest.raises(RuntimeError, match="not fitted"):
            HistoryBasedFeaturesProcessor().transform(pd.DataFrame())