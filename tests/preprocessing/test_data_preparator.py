"""DataPreparator: raw intake → canonical log layout."""

import numpy as np
import pandas as pd
import pytest

from replay_tpu.preprocessing import DataPreparator


@pytest.fixture
def raw_log():
    return pd.DataFrame(
        {"user": [2, 2, 2, 1], "movie": [1, 2, 3, 3], "rel": [5, 5, 5, 5]}
    )


class TestTransform:
    def test_log_rename_and_defaults(self, raw_log):
        out = DataPreparator().transform(
            columns_mapping={"query_id": "user", "item_id": "movie", "rating": "rel"},
            data=raw_log,
        )
        assert sorted(out.columns) == ["item_id", "query_id", "rating", "timestamp"]
        assert out["rating"].dtype == float and out["rating"].iloc[0] == 5.0
        assert (out["timestamp"] == pd.Timestamp("2099-01-01")).all()

    def test_feature_frame_only_renames(self):
        features = pd.DataFrame(
            {"user": ["u1", "u2"], "f0": ["a", "b"], "ts": ["2019-01-01", "2019-01-01"]}
        )
        out = DataPreparator().transform(columns_mapping={"query_id": "user"}, data=features)
        assert sorted(out.columns) == ["f0", "query_id", "ts"]
        # untouched: not an interactions log, so no datetime coercion
        assert not pd.api.types.is_datetime64_any_dtype(out["ts"])

    def test_string_timestamps_parsed(self):
        raw = pd.DataFrame(
            {"u": [1, 2], "i": [1, 2], "t": ["2020-05-01", "2020-05-02"]}
        )
        out = DataPreparator().transform(
            columns_mapping={"query_id": "u", "item_id": "i", "timestamp": "t"}, data=raw
        )
        assert pd.api.types.is_datetime64_any_dtype(out["timestamp"])
        assert out["rating"].tolist() == [1.0, 1.0]  # defaulted

    def test_numeric_timestamps_kept(self):
        raw = pd.DataFrame({"u": [1], "i": [1], "t": [1234567]})
        out = DataPreparator().transform(
            columns_mapping={"query_id": "u", "item_id": "i", "timestamp": "t"}, data=raw
        )
        assert out["timestamp"].tolist() == [1234567]

    def test_csv_roundtrip(self, raw_log, tmp_path):
        path = tmp_path / "log.csv"
        raw_log.to_csv(path, index=False)
        out = DataPreparator().transform(
            columns_mapping={"query_id": "user", "item_id": "movie", "rating": "rel"},
            path=str(path),
            format_type="csv",
        )
        assert len(out) == 4 and "query_id" in out.columns

    def test_parquet_roundtrip(self, raw_log, tmp_path):
        path = tmp_path / "log.parquet"
        raw_log.to_parquet(path)
        out = DataPreparator().transform(
            columns_mapping={"query_id": "user", "item_id": "movie"},
            path=str(path),
            format_type="parquet",
        )
        assert out["rating"].tolist() == [1.0] * 4


class TestValidation:
    def test_empty_frame_rejected(self):
        with pytest.raises(ValueError, match="empty"):
            DataPreparator().transform(
                columns_mapping={"query_id": "u"}, data=pd.DataFrame({"u": []})
            )

    def test_missing_mapped_column(self, raw_log):
        with pytest.raises(ValueError, match="absent in dataframe"):
            DataPreparator().transform(
                columns_mapping={"query_id": "nope"}, data=raw_log
            )

    def test_unknown_mapping_key(self, raw_log):
        with pytest.raises(ValueError, match="Unknown columns_mapping"):
            DataPreparator().transform(
                columns_mapping={"user_idx": "user"}, data=raw_log
            )

    def test_no_input_rejected(self):
        with pytest.raises(ValueError, match="data or path"):
            DataPreparator().transform(columns_mapping={"query_id": "u"})

    def test_bad_format_type(self, tmp_path):
        with pytest.raises(ValueError, match="format_type"):
            DataPreparator().read_as_pandas_df(path=str(tmp_path / "x"), format_type="xml")

    def test_format_inferred_from_extension(self, raw_log, tmp_path):
        path = tmp_path / "log.csv"
        raw_log.to_csv(path, index=False)
        out = DataPreparator().transform(
            columns_mapping={"query_id": "user", "item_id": "movie"}, path=str(path)
        )
        assert len(out) == 4

    def test_uninferrable_extension_names_the_problem(self, tmp_path):
        with pytest.raises(ValueError, match="format_type not given"):
            DataPreparator().read_as_pandas_df(path=str(tmp_path / "x.xml"))
