"""Discretizer rule contracts: partial_fit, handle_invalid switching, rule serde."""

import pandas as pd
import pytest

from replay_tpu.preprocessing import Discretizer, QuantileDiscretizingRule



def test_rule_partial_fit_and_handle_invalid_switch():
    """Reference contract (discretizer.py:241-303): partial_fit == fit when
    unfitted, NotImplementedError after; set_handle_invalid validates."""
    import numpy as np

    rule = QuantileDiscretizingRule("x", n_bins=2)
    df = pd.DataFrame({"x": [1.0, 2.0, 3.0, 4.0]})
    rule.partial_fit(df)  # fit path
    assert rule.bin_edges is not None
    with pytest.raises(NotImplementedError):
        rule.partial_fit(df)
    rule.set_handle_invalid("keep")
    assert rule.handle_invalid == "keep"
    with pytest.raises(ValueError, match="handle_invalid"):
        rule.set_handle_invalid("explode")
    disc = Discretizer([QuantileDiscretizingRule("x", n_bins=2)])
    disc.partial_fit(df)
    disc.set_handle_invalid("skip")
    out = disc.transform(pd.DataFrame({"x": [1.0, np.nan]}))
    assert np.isnan(out["x"].iloc[1])


def test_rule_save_load_roundtrip(tmp_path):
    from replay_tpu.preprocessing import LabelEncodingRule

    rule = LabelEncodingRule("item_id").fit(pd.DataFrame({"item_id": ["b", "a"]}))
    rule.save(str(tmp_path / "rule"))
    restored = LabelEncodingRule.load(str(tmp_path / "rule"))
    assert restored.get_mapping() == rule.get_mapping()
    out = restored.transform(pd.DataFrame({"item_id": ["a", "b"]}))
    assert out["item_id"].tolist() == [1, 0]
