import pandas as pd
import pytest

from replay_tpu.preprocessing import (
    ConsecutiveDuplicatesFilter,
    EntityDaysFilter,
    GlobalDaysFilter,
    InteractionEntriesFilter,
    LowRatingFilter,
    MinCountFilter,
    NumInteractionsFilter,
    QuantileItemsFilter,
    TimePeriodFilter,
)


@pytest.fixture
def interactions():
    return pd.DataFrame(
        {
            "user_id": [1, 1, 1, 2, 2, 2, 3, 3, 3, 3],
            "item_id": [3, 7, 10, 5, 8, 11, 4, 9, 2, 5],
            "rating": [1, 2, 3, 3, 2, 1, 3, 12, 1, 4],
        }
    )


def test_interaction_entries_filter(interactions):
    out = InteractionEntriesFilter(min_inter_per_user=4).transform(interactions)
    assert out["user_id"].unique().tolist() == [3]
    assert len(out) == 4


def test_interaction_entries_filter_iterates():
    df = pd.DataFrame({"user_id": [1, 1, 2], "item_id": [10, 11, 11]})
    out = InteractionEntriesFilter(min_inter_per_user=2, min_inter_per_item=1).transform(df)
    assert out["user_id"].tolist() == [1, 1]


def test_min_count_filter():
    df = pd.DataFrame({"user_id": [1, 1, 2]})
    out = MinCountFilter(2).transform(df)
    assert out["user_id"].tolist() == [1, 1]


def test_low_rating_filter():
    df = pd.DataFrame({"rating": [1, 5, 3.5, 4]})
    out = LowRatingFilter(3.5).transform(df)
    assert out["rating"].tolist() == [5, 3.5, 4]


def test_num_interactions_filter():
    df = pd.DataFrame(
        {
            "user_id": ["u1", "u2", "u2", "u3", "u3", "u3"],
            "item_id": ["i1", "i2", "i3", "i1", "i2", "i3"],
            "timestamp": [1, 1, 2, 1, 2, 3],
        }
    )
    first = NumInteractionsFilter(1, first=True).transform(df)
    assert len(first) == 3
    assert first[first.user_id == "u3"]["timestamp"].tolist() == [1]
    last = NumInteractionsFilter(1, first=False).transform(df)
    assert last[last.user_id == "u3"]["timestamp"].tolist() == [3]


def test_entity_days_filter():
    base = pd.Timestamp("2024-01-01")
    df = pd.DataFrame(
        {
            "user_id": [1, 1, 1, 2, 2],
            "timestamp": [base, base + pd.Timedelta(days=5), base + pd.Timedelta(days=20), base, base],
        }
    )
    out = EntityDaysFilter(days=10, first=True).transform(df)
    assert len(out) == 4


def test_global_days_filter():
    base = pd.Timestamp("2024-01-01")
    df = pd.DataFrame({"timestamp": [base, base + pd.Timedelta(days=5), base + pd.Timedelta(days=30)]})
    out = GlobalDaysFilter(days=10).transform(df)
    assert len(out) == 2
    out_last = GlobalDaysFilter(days=10, first=False).transform(df)
    assert len(out_last) == 1


def test_time_period_filter():
    df = pd.DataFrame({"timestamp": pd.to_datetime(["2024-01-01", "2024-02-01", "2024-03-01"])})
    out = TimePeriodFilter(start_date="2024-01-15 00:00:00", end_date="2024-02-15 00:00:00").transform(df)
    assert len(out) == 1


def test_quantile_items_filter():
    df = pd.DataFrame(
        {
            "query_id": list(range(20)) + [0, 1, 2, 3],
            "item_id": [1] * 20 + [2, 2, 3, 3],
        }
    )
    out = QuantileItemsFilter(alpha_quantile=0.5, items_proportion=0.5).transform(df)
    assert len(out) < len(df)
    # long-tail items untouched
    assert (out["item_id"] == 2).sum() == 2
    assert (out["item_id"] == 3).sum() == 2


def test_consecutive_duplicates_filter():
    df = pd.DataFrame(
        {
            "query_id": [1, 1, 1, 1, 2],
            "item_id": [5, 5, 6, 5, 5],
            "timestamp": [0, 1, 2, 3, 0],
        }
    )
    out = ConsecutiveDuplicatesFilter().transform(df)
    assert out[out.query_id == 1]["item_id"].tolist() == [5, 6, 5]
    assert len(out) == 4
