import numpy as np
import pandas as pd
import pytest

from replay_tpu.preprocessing import (
    LabelEncoder,
    LabelEncoderPartialFitWarning,
    LabelEncoderTransformWarning,
    LabelEncodingRule,
    SequenceEncodingRule,
)


@pytest.fixture
def df():
    return pd.DataFrame({"item_id": ["a", "b", "a", "c"], "x": [1, 2, 3, 4]})


def test_fit_transform_contiguous(df):
    rule = LabelEncodingRule("item_id")
    out = rule.fit(df).transform(df)
    assert out["item_id"].tolist() == [0, 1, 0, 2]
    assert rule.get_mapping() == {"a": 0, "b": 1, "c": 2}
    assert rule.get_inverse_mapping() == {0: "a", 1: "b", 2: "c"}


def test_inverse_roundtrip(df):
    rule = LabelEncodingRule("item_id").fit(df)
    encoded = rule.transform(df)
    decoded = rule.inverse_transform(encoded)
    assert decoded["item_id"].tolist() == df["item_id"].tolist()


def test_unknown_error(df):
    rule = LabelEncodingRule("item_id").fit(df)
    new = pd.DataFrame({"item_id": ["a", "zzz"]})
    with pytest.raises(ValueError, match="unknown"):
        rule.transform(new)


def test_unknown_default_value(df):
    rule = LabelEncodingRule("item_id", handle_unknown="use_default_value", default_value=-1).fit(df)
    new = pd.DataFrame({"item_id": ["a", "zzz"]})
    out = rule.transform(new)
    assert out["item_id"].tolist() == [0, -1]


def test_unknown_default_last(df):
    rule = LabelEncodingRule("item_id", handle_unknown="use_default_value", default_value="last").fit(df)
    new = pd.DataFrame({"item_id": ["zzz", "b"]})
    out = rule.transform(new)
    assert out["item_id"].tolist() == [3, 1]


def test_unknown_drop(df):
    rule = LabelEncodingRule("item_id", handle_unknown="drop").fit(df)
    new = pd.DataFrame({"item_id": ["zzz", "b"]})
    out = rule.transform(new)
    assert out["item_id"].tolist() == [1]


def test_drop_to_empty_warns(df):
    rule = LabelEncodingRule("item_id", handle_unknown="drop").fit(df)
    new = pd.DataFrame({"item_id": ["zzz", "yyy"]})
    with pytest.warns(LabelEncoderTransformWarning):
        out = rule.transform(new)
    assert out.empty


def test_partial_fit_extends(df):
    rule = LabelEncodingRule("item_id").fit(df)
    rule.partial_fit(pd.DataFrame({"item_id": ["c", "d"]}))
    assert rule.get_mapping()["d"] == 3
    assert rule.get_mapping()["c"] == 2


def test_partial_fit_before_fit_warns(df):
    rule = LabelEncodingRule("item_id")
    with pytest.warns(LabelEncoderPartialFitWarning):
        rule.partial_fit(df)
    assert rule.is_fitted


def test_sequence_rule():
    df = pd.DataFrame({"genres": [["a", "b"], ["b", "c"], ["a"]]})
    rule = SequenceEncodingRule("genres").fit(df)
    out = rule.transform(df)
    assert out["genres"].iloc[0].tolist() == [0, 1]
    assert out["genres"].iloc[1].tolist() == [1, 2]
    decoded = rule.inverse_transform(out)
    assert decoded["genres"].iloc[1].tolist() == ["b", "c"]


def test_sequence_rule_unknown_drop():
    df = pd.DataFrame({"genres": [["a", "b"]]})
    rule = SequenceEncodingRule("genres", handle_unknown="drop").fit(df)
    out = rule.transform(pd.DataFrame({"genres": [["a", "zzz"]]}))
    assert out["genres"].iloc[0].tolist() == [0]


def test_label_encoder_composition(df):
    df2 = df.assign(user_id=["u1", "u2", "u1", "u3"])
    encoder = LabelEncoder([LabelEncodingRule("item_id"), LabelEncodingRule("user_id")])
    out = encoder.fit_transform(df2)
    assert out["user_id"].tolist() == [0, 1, 0, 2]
    assert set(encoder.mapping.keys()) == {"item_id", "user_id"}
    back = encoder.inverse_transform(out)
    assert back["user_id"].tolist() == df2["user_id"].tolist()


def test_set_strategies(df):
    encoder = LabelEncoder([LabelEncodingRule("item_id")]).fit(df)
    encoder.set_handle_unknowns({"item_id": "use_default_value"})
    encoder.set_default_values({"item_id": -1})
    out = encoder.transform(pd.DataFrame({"item_id": ["zzz"]}))
    assert out["item_id"].tolist() == [-1]
    with pytest.raises(ValueError):
        encoder.set_default_values({"nope": 1})


def test_encoder_save_load_roundtrip(tmp_path):
    import numpy as np

    df = pd.DataFrame({"item_id": ["a", "b", "c", "a"], "tags": [["x"], ["y", "x"], ["x"], []]})
    encoder = LabelEncoder(
        [LabelEncodingRule("item_id"), SequenceEncodingRule("tags", handle_unknown="drop")]
    ).fit(df)
    encoder.save(str(tmp_path / "enc"))
    restored = LabelEncoder.load(str(tmp_path / "enc"))
    assert restored.mapping == encoder.mapping
    out_a = encoder.transform(df)
    out_b = restored.transform(df)
    pd.testing.assert_frame_equal(out_a, out_b)
    # strategies survive the roundtrip
    assert restored.rules[1]._handle_unknown == "drop"
