"""Padder and SequenceGenerator (experimental preprocessing parity)."""

import pandas as pd
import pytest

from replay_tpu.preprocessing import Padder, SequenceGenerator


@pytest.fixture
def ragged():
    return pd.DataFrame(
        {
            "user_id": [1, 2, 3],
            "timestamp": [[1], [4, 7, 12, 126], [1, 2, 3, 4, 5, 6, 7]],
            "item_id": [["a"], ["d", "e", "m", "g"], ["a", "b", "c", "d", "a", "f", "e"]],
        }
    )


class TestPadder:
    def test_pad_cut_right(self, ragged):
        out = Padder(
            pad_columns=["item_id", "timestamp"],
            padding_side="right",
            padding_value=["[PAD]", 0],
            array_size=5,
            cut_array=True,
            cut_side="right",
        ).transform(ragged)
        assert out["timestamp"].tolist() == [
            [1, 0, 0, 0, 0],
            [4, 7, 12, 126, 0],
            [3, 4, 5, 6, 7],
        ]
        assert out["item_id"].tolist()[0] == ["a", "[PAD]", "[PAD]", "[PAD]", "[PAD]"]
        assert out["item_id"].tolist()[2] == ["c", "d", "a", "f", "e"]

    def test_left_padding_left_cut(self, ragged):
        out = Padder(
            pad_columns="timestamp", padding_side="left", array_size=3, cut_side="left"
        ).transform(ragged)
        assert out["timestamp"].tolist() == [[0, 0, 1], [4, 7, 12], [1, 2, 3]]

    def test_no_cut_keeps_long_rows(self, ragged):
        out = Padder(pad_columns="timestamp", array_size=3, cut_array=False).transform(ragged)
        assert out["timestamp"].tolist()[2] == [1, 2, 3, 4, 5, 6, 7]

    def test_default_width_is_max_length(self, ragged):
        out = Padder(pad_columns="timestamp").transform(ragged)
        assert all(len(row) == 7 for row in out["timestamp"])

    def test_non_list_becomes_padding(self):
        df = pd.DataFrame({"x": [[1, 2], None]})
        out = Padder(pad_columns="x", array_size=2).transform(df)
        assert out["x"].tolist() == [[1, 2], [0, 0]]

    def test_ndarray_and_tuple_cells(self):
        # parquet round-trips hand back np.ndarray cells; tuples also count
        import numpy as np

        df = pd.DataFrame({"x": [np.array([1, 2, 3]), (4,)]})
        out = Padder(pad_columns="x", array_size=2).transform(df)
        assert out["x"].tolist() == [[2, 3], [4, 0]]
        widest = Padder(pad_columns="x").transform(df)  # max-length path
        assert widest["x"].tolist() == [[1, 2, 3], [4, 0, 0]]

    def test_scalar_value_broadcast(self, ragged):
        padder = Padder(pad_columns=["item_id", "timestamp"], padding_value=0)
        assert padder.padding_value == [0, 0]

    def test_mismatched_values_raise(self):
        with pytest.raises(ValueError, match="same length"):
            Padder(pad_columns=["a", "b", "c"], padding_value=[0, 1])

    def test_missing_column_raises(self, ragged):
        with pytest.raises(ValueError, match="not in DataFrame"):
            Padder(pad_columns="nope").transform(ragged)

    def test_non_list_column_raises(self, ragged):
        with pytest.raises(ValueError, match="object dtype"):
            Padder(pad_columns="user_id").transform(ragged)

    def test_bad_sides_raise(self):
        with pytest.raises(ValueError, match="padding_side"):
            Padder(pad_columns="x", padding_side="middle")
        with pytest.raises(ValueError, match="cut_side"):
            Padder(pad_columns="x", cut_side="middle")

    def test_input_not_mutated(self, ragged):
        before = ragged.copy(deep=True)
        Padder(pad_columns="timestamp", array_size=2).transform(ragged)
        assert ragged["timestamp"].tolist() == before["timestamp"].tolist()


class TestSequenceGenerator:
    @pytest.fixture
    def log(self):
        return pd.DataFrame(
            {
                "user_id": [1, 1, 1, 2, 2, 2, 3, 3, 3, 3],
                "item_id": [3, 7, 10, 5, 8, 11, 4, 9, 2, 5],
                "timestamp": [1, 2, 3, 3, 2, 1, 3, 12, 1, 4],
            }
        )

    def test_reference_example(self, log):
        # expected rows are the reference doctest
        # (replay/experimental/preprocessing/sequence_generator.py:31-63)
        out = SequenceGenerator(
            groupby_column="user_id", transform_columns=["item_id", "timestamp"]
        ).transform(log)
        assert out["user_id"].tolist() == [1, 1, 2, 2, 3, 3, 3]
        assert out["item_id_list"].tolist() == [
            [3], [3, 7], [5], [5, 8], [4], [4, 9], [4, 9, 2],
        ]
        assert out["label_item_id"].tolist() == [7, 10, 8, 11, 9, 2, 5]
        assert out["timestamp_list"].tolist() == [
            [1], [1, 2], [3], [3, 2], [3], [3, 12], [3, 12, 1],
        ]

    def test_orderby(self, log):
        out = SequenceGenerator(
            groupby_column="user_id",
            orderby_column="timestamp",
            transform_columns="item_id",
        ).transform(log)
        user3 = out[out["user_id"] == 3]
        assert user3["item_id_list"].tolist() == [[2], [2, 4], [2, 4, 5]]
        assert user3["label_item_id"].tolist() == [4, 5, 9]

    def test_window_caps_history(self, log):
        out = SequenceGenerator(
            groupby_column="user_id", transform_columns="item_id", len_window=2
        ).transform(log)
        assert max(len(s) for s in out["item_id_list"]) == 2
        user3 = out[out["user_id"] == 3]
        assert user3["item_id_list"].tolist() == [[4], [4, 9], [9, 2]]

    def test_list_len_column(self, log):
        out = SequenceGenerator(
            groupby_column="user_id", transform_columns="item_id", get_list_len=True
        ).transform(log)
        assert out["list_len"].tolist() == [len(s) for s in out["item_id_list"]]

    def test_affixes(self, log):
        out = SequenceGenerator(
            groupby_column="user_id",
            transform_columns="item_id",
            sequence_prefix="hist_",
            sequence_suffix="",
            label_prefix="",
            label_suffix="_next",
        ).transform(log)
        assert "hist_item_id" in out.columns and "item_id_next" in out.columns

    def test_default_transform_columns(self, log):
        out = SequenceGenerator(groupby_column="user_id").transform(log)
        assert "item_id_list" in out.columns and "timestamp_list" in out.columns

    def test_bad_window_raises(self):
        with pytest.raises(ValueError, match="len_window"):
            SequenceGenerator("user_id", len_window=0)
