"""bench_serve.py emits one parseable JSON record with finite serving metrics."""

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.jax

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def test_bench_serve_one_json_line(tmp_path):
    env = {
        **os.environ,
        "PYTHONPATH": os.pathsep.join(
            p for p in os.environ.get("PYTHONPATH", "").split(os.pathsep)
            if p and ".axon_site" not in p
        ),
        "JAX_PLATFORMS": "cpu",
        "REPLAY_TPU_SERVE_FALLBACK": "1",  # skip the backend probe subprocess
        "REPLAY_TPU_SERVE_SEQ_LEN": "8",
        "REPLAY_TPU_SERVE_NUM_ITEMS": "30",
        "REPLAY_TPU_SERVE_EMBEDDING_DIM": "8",
        "REPLAY_TPU_SERVE_NUM_BLOCKS": "1",
        "REPLAY_TPU_SERVE_USERS": "12",
        "REPLAY_TPU_SERVE_CLIENTS": "2",
        "REPLAY_TPU_SERVE_CLOSED_REQUESTS": "8",
        "REPLAY_TPU_SERVE_RATE": "200",
        "REPLAY_TPU_SERVE_SECONDS": "1",
        "REPLAY_TPU_SERVE_CANDIDATES": "10",
        "REPLAY_TPU_SERVE_TOPK": "3",
        "REPLAY_TPU_SERVE_BATCH_BUCKETS": "1,4",
    }
    out = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "bench_serve.py")],
        capture_output=True,
        timeout=300,
        env=env,
        cwd=str(tmp_path),  # run dir artifacts land under the repo, record on stdout
        check=False,
    )
    assert out.returncode == 0, out.stderr.decode()
    record = json.loads(out.stdout.decode().strip().splitlines()[-1])
    assert record["metric"] == "serve_qps_cpu_fallback"
    assert record["unit"] == "req/s"
    for key in ("qps", "p50_ms", "p95_ms", "p99_ms", "closed_loop_qps"):
        assert isinstance(record[key], (int, float)) and record[key] > 0, key
    assert record["p50_ms"] <= record["p95_ms"] <= record["p99_ms"]
    assert 0.0 < record["batch_fill_ratio"] <= 1.0
    assert 0.0 <= record["cache_hit_rate"] <= 1.0
    assert record["request_errors"] == 0
    assert record["mode"] == "retrieval"
    assert record["shape_override"]["L"] == 8
