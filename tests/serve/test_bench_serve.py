"""bench_serve.py emits one parseable JSON record with finite serving metrics —
and, with overload + chaos enabled, the resilience accounting the acceptance
criteria gate on (bounded p99 with nonzero shed, zero hung futures, a breaker
that opens and recovers)."""

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.jax

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def test_bench_serve_one_json_line(tmp_path):
    env = {
        **os.environ,
        "PYTHONPATH": os.pathsep.join(
            p for p in os.environ.get("PYTHONPATH", "").split(os.pathsep)
            if p and ".axon_site" not in p
        ),
        "JAX_PLATFORMS": "cpu",
        "REPLAY_TPU_SERVE_FALLBACK": "1",  # skip the backend probe subprocess
        "REPLAY_TPU_SERVE_SEQ_LEN": "8",
        "REPLAY_TPU_SERVE_NUM_ITEMS": "30",
        "REPLAY_TPU_SERVE_EMBEDDING_DIM": "8",
        "REPLAY_TPU_SERVE_NUM_BLOCKS": "1",
        "REPLAY_TPU_SERVE_USERS": "12",
        "REPLAY_TPU_SERVE_CLIENTS": "2",
        "REPLAY_TPU_SERVE_CLOSED_REQUESTS": "8",
        "REPLAY_TPU_SERVE_RATE": "200",
        "REPLAY_TPU_SERVE_SECONDS": "1",
        "REPLAY_TPU_SERVE_CANDIDATES": "10",
        "REPLAY_TPU_SERVE_TOPK": "3",
        "REPLAY_TPU_SERVE_BATCH_BUCKETS": "1,4",
        # resilience phases: open-loop overload at 4x measured capacity with
        # per-request deadlines, then deterministic chaos injection; the swap
        # phase runs BEFORE them (its zero-error claim must stay unpolluted)
        "REPLAY_TPU_SERVE_CHAOS": "1",
        "REPLAY_TPU_SERVE_OVERLOAD_SECONDS": "1",
        "REPLAY_TPU_SERVE_SWAPS": "2",
        "REPLAY_TPU_SERVE_SWAP_GAP_MS": "100",
        # the tiny CPU model outruns a single open-loop generator thread, so
        # admission control must be made reachable: tight lanes + a high
        # factor (the default 4x/auto-depth shape is for real configs)
        "REPLAY_TPU_SERVE_MAX_DEPTH": "4",
        "REPLAY_TPU_SERVE_OVERLOAD_FACTOR": "16",
        "REPLAY_TPU_SERVE_DEADLINE_MS": "150",
        "REPLAY_TPU_SERVE_BREAKER_THRESHOLD": "3",
        "REPLAY_TPU_SERVE_BREAKER_RESET_MS": "100",
    }
    out = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "bench_serve.py")],
        capture_output=True,
        timeout=300,
        env=env,
        cwd=str(tmp_path),  # run dir artifacts land under the repo, record on stdout
        check=False,
    )
    assert out.returncode == 0, out.stderr.decode()
    record = json.loads(out.stdout.decode().strip().splitlines()[-1])
    assert record["metric"] == "serve_qps_cpu_fallback"
    assert record["unit"] == "req/s"
    for key in ("qps", "p50_ms", "p95_ms", "p99_ms", "closed_loop_qps"):
        assert isinstance(record[key], (int, float)) and record[key] > 0, key
    assert record["p50_ms"] <= record["p95_ms"] <= record["p99_ms"]
    assert 0.0 < record["batch_fill_ratio"] <= 1.0
    assert 0.0 <= record["cache_hit_rate"] <= 1.0
    assert record["request_errors"] == 0
    assert record["mode"] == "retrieval"
    assert record["shape_override"]["L"] == 8

    # run-wide resilience rates (the --compare gate inputs) are present/finite
    for key in ("serve_shed_rate", "serve_deadline_miss_rate", "serve_error_rate"):
        assert 0.0 <= record[key] <= 1.0, key
    assert record["hung_requests"] == 0

    # overload: arrivals ≫ capacity, bounded lanes must shed or drop expired
    # waiters — and p99 of COMPLETED requests stays bounded (nothing can queue
    # past its deadline, so latency is capped near deadline + one dispatch)
    overload = record["overload"]
    refused = (
        overload["shed"] + overload["deadline_missed"] + overload["circuit_refused"]
    )
    assert refused > 0, overload
    assert overload["submitted"] > overload["completed"]
    assert overload["hung_requests"] == 0
    assert overload["p99_ms"] <= 150 + 1000, overload  # deadline + slack, not ∞
    assert overload["errors"] == 0

    # swap under load (serve.promote): N hot swaps completed with ZERO request
    # errors, every swap a zero-recompile pointer move, p99 bounded/finite,
    # and the generation tags observed prove both sides of each swap served
    swap = record["swap"]
    assert swap["swaps"] == 2
    assert swap["errors"] == 0, swap["first_error"]
    assert swap["recompiled_swaps"] == 0  # same shapes: never recompiled
    assert swap["answered"] > 0
    assert swap["p99_ms"] > 0 and swap["p99_ms"] < 120_000
    assert swap["generations_seen"] >= 1
    assert swap["final_generation"] == 2
    assert swap["swap_apply_ms_max"] > 0

    # chaos: injected engine faults tripped the breaker, degraded traffic is
    # tagged, the breaker re-closed, and no future was left hanging
    chaos = record["chaos"]
    assert chaos["injected_engine_errors"] == 3
    assert chaos["breaker_opens"] >= 1
    assert chaos["breaker_state_after_trip"] == "open"
    assert chaos["recovered"] is True
    assert chaos["breaker_state_final"] == "closed"
    assert chaos["served_by_seen"]["advance_while_open"] == "cache_only"
    assert chaos["served_by_seen"]["cold_while_open"] == "fallback"
    assert chaos["client_abandoned"] == 1
    assert chaos["storm_deadline_missed"] > 0
    assert chaos["hung_requests"] == 0
    assert record["breaker"]["opens"] >= 1
