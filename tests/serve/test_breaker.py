"""CircuitBreaker state machine: closed → open → half-open → closed (host-only).

Clock is injected, so every timed transition is deterministic — no sleeps.
"""

import pytest

from replay_tpu.serve import CircuitBreaker


class Clock:
    def __init__(self, now: float = 100.0):
        self.now = now

    def __call__(self) -> float:
        return self.now

    def advance(self, seconds: float) -> None:
        self.now += seconds


def _breaker(clock, threshold=3, reset=2.0, probes=1, transitions=None):
    return CircuitBreaker(
        failure_threshold=threshold,
        reset_timeout_s=reset,
        half_open_max_probes=probes,
        clock=clock,
        on_transition=(
            (lambda old, new, info: transitions.append((old, new)))
            if transitions is not None
            else None
        ),
    )


class TestClosed:
    def test_starts_closed_and_allows(self):
        breaker = _breaker(Clock())
        assert breaker.state == "closed"
        assert all(breaker.allow() for _ in range(10))
        assert breaker.retry_after_s() is None

    def test_below_threshold_failures_stay_closed(self):
        breaker = _breaker(Clock(), threshold=3)
        breaker.record_failure()
        breaker.record_failure()
        assert breaker.state == "closed"
        assert breaker.allow()

    def test_success_resets_the_consecutive_streak(self):
        """reset-on-success: N-1 failures + success + N-1 failures never open."""
        breaker = _breaker(Clock(), threshold=3)
        for _ in range(2):
            breaker.record_failure()
        breaker.record_success()
        assert breaker.stats()["consecutive_failures"] == 0
        for _ in range(2):
            breaker.record_failure()
        assert breaker.state == "closed"
        breaker.record_failure()  # the streak completes only uninterrupted
        assert breaker.state == "open"

    def test_non_consecutive_failures_never_trip(self):
        breaker = _breaker(Clock(), threshold=2)
        for _ in range(10):
            breaker.record_failure()
            breaker.record_success()
        assert breaker.state == "closed"
        assert breaker.stats()["opens"] == 0


class TestOpen:
    def test_threshold_consecutive_failures_open(self):
        transitions = []
        breaker = _breaker(Clock(), threshold=3, transitions=transitions)
        for _ in range(3):
            breaker.record_failure()
        assert breaker.state == "open"
        assert transitions == [("closed", "open")]
        assert breaker.stats()["opens"] == 1

    def test_open_refuses_and_counts_refusals(self):
        breaker = _breaker(Clock(), threshold=1, reset=5.0)
        breaker.record_failure()
        assert not breaker.allow()
        assert not breaker.allow()
        assert breaker.stats()["refusals"] == 2

    def test_retry_after_tracks_the_remaining_window(self):
        clock = Clock()
        breaker = _breaker(clock, threshold=1, reset=2.0)
        breaker.record_failure()
        assert breaker.retry_after_s() == pytest.approx(2.0)
        clock.advance(1.5)
        assert breaker.retry_after_s() == pytest.approx(0.5)
        clock.advance(10.0)
        assert breaker.retry_after_s() == 0.0  # clamped, never negative

    def test_extra_failures_while_open_do_not_reopen(self):
        breaker = _breaker(Clock(), threshold=1)
        breaker.record_failure()
        breaker.record_failure()  # e.g. an in-flight call landing late
        assert breaker.stats()["opens"] == 1


class TestHalfOpen:
    def test_reset_timeout_admits_a_probe(self):
        clock = Clock()
        transitions = []
        breaker = _breaker(clock, threshold=1, reset=2.0, transitions=transitions)
        breaker.record_failure()
        assert not breaker.allow()
        clock.advance(2.0)
        assert breaker.allow()  # the probe
        assert breaker.state == "half_open"
        assert transitions == [("closed", "open"), ("open", "half_open")]

    def test_probe_limit_refuses_beyond_max_probes(self):
        clock = Clock()
        breaker = _breaker(clock, threshold=1, reset=1.0, probes=2)
        breaker.record_failure()
        clock.advance(1.0)
        assert breaker.allow()  # probe 1 (open -> half_open admits it)
        assert breaker.allow()  # probe 2
        assert not breaker.allow()  # over the probe budget
        assert not breaker.allow()
        assert breaker.stats()["refusals"] == 2

    def test_probe_success_closes_with_a_full_reset(self):
        clock = Clock()
        transitions = []
        breaker = _breaker(clock, threshold=2, reset=1.0, transitions=transitions)
        breaker.record_failure()
        breaker.record_failure()
        clock.advance(1.0)
        assert breaker.allow()
        breaker.record_success()
        assert breaker.state == "closed"
        assert transitions[-1] == ("half_open", "closed")
        stats = breaker.stats()
        assert stats["closes"] == 1
        assert stats["consecutive_failures"] == 0
        assert breaker.retry_after_s() is None
        # fully reset: it takes the full threshold to open again
        breaker.record_failure()
        assert breaker.state == "closed"

    def test_probe_failure_reopens_and_restarts_the_timer(self):
        clock = Clock()
        breaker = _breaker(clock, threshold=1, reset=2.0)
        breaker.record_failure()
        clock.advance(2.0)
        assert breaker.allow()
        clock.advance(1.7)  # mid-probe time passes before the outcome lands
        breaker.record_failure()
        assert breaker.state == "open"
        assert breaker.stats()["opens"] == 2
        # the window restarts at the REOPEN, not the original open
        assert breaker.retry_after_s() == pytest.approx(2.0)
        assert not breaker.allow()
        clock.advance(2.0)
        assert breaker.allow()

    def test_abandoned_probe_slots_are_reclaimed(self):
        """A probe admitted by allow() may never produce an outcome (shed,
        deadline-expired or cancelled before reaching the engine). Half-open
        must reclaim the slot after reset_timeout_s — an abandoned probe must
        not wedge the breaker in half-open forever."""
        clock = Clock()
        breaker = _breaker(clock, threshold=1, reset=2.0, probes=1)
        breaker.record_failure()
        clock.advance(2.0)
        assert breaker.allow()  # the probe — then it vanishes, no outcome
        assert not breaker.allow()  # slot held within the window
        clock.advance(2.0)
        assert breaker.allow()  # slot reclaimed: a fresh probe is admitted
        breaker.record_success()
        assert breaker.state == "closed"

    def test_reclaimed_probe_failure_still_reopens(self):
        clock = Clock()
        breaker = _breaker(clock, threshold=1, reset=1.0)
        breaker.record_failure()
        clock.advance(1.0)
        assert breaker.allow()
        clock.advance(1.0)
        assert breaker.allow()  # reclaim
        breaker.record_failure()
        assert breaker.state == "open"
        assert breaker.stats()["opens"] == 2

    def test_round_trip_closed_open_half_open_closed(self):
        clock = Clock()
        transitions = []
        breaker = _breaker(clock, threshold=2, reset=0.5, transitions=transitions)
        for _ in range(2):
            breaker.record_failure()
        clock.advance(0.5)
        assert breaker.allow()
        breaker.record_success()
        assert [t for t in transitions] == [
            ("closed", "open"),
            ("open", "half_open"),
            ("half_open", "closed"),
        ]


class TestValidation:
    def test_rejects_zero_threshold(self):
        with pytest.raises(ValueError, match="failure_threshold"):
            CircuitBreaker(failure_threshold=0)

    def test_rejects_zero_probes(self):
        with pytest.raises(ValueError, match="half_open_max_probes"):
            CircuitBreaker(half_open_max_probes=0)

    def test_stats_shape(self):
        stats = _breaker(Clock()).stats()
        assert set(stats) == {
            "state", "consecutive_failures", "opens", "closes",
            "refusals", "failures", "successes",
        }
