"""UserStateCache: LRU semantics, window advances, stale-write guard (host-only)."""

import numpy as np

from replay_tpu.serve import UserState, UserStateCache, make_window


def _state(items, L=8):
    window, mask, length = make_window(items, L)
    return UserState(window=window, mask=mask, length=length)


class TestMakeWindow:
    def test_right_aligned_with_left_padding(self):
        window, mask, length = make_window([5, 6, 7], 6)
        assert length == 3
        np.testing.assert_array_equal(window, [0, 0, 0, 5, 6, 7])
        np.testing.assert_array_equal(mask, [False, False, False, True, True, True])

    def test_long_history_keeps_most_recent(self):
        window, mask, length = make_window(list(range(10)), 4)
        assert length == 4
        np.testing.assert_array_equal(window, [6, 7, 8, 9])
        assert mask.all()

    def test_custom_pad_id(self):
        window, _, _ = make_window([1], 3, pad_id=-1)
        np.testing.assert_array_equal(window, [-1, -1, 1])


class TestAdvance:
    def test_append_within_capacity(self):
        cache = UserStateCache(4)
        advanced = cache.advance(_state([1, 2, 3]), [9])
        np.testing.assert_array_equal(advanced.window[-4:], [1, 2, 3, 9])
        assert advanced.length == 4
        assert advanced.embedding is None  # certifies the OLD window only
        assert advanced.generation == 1
        assert cache.advances == 1

    def test_append_rolls_a_full_window(self):
        state = _state(list(range(1, 9)))  # exactly L=8 events
        advanced = UserStateCache(4).advance(state, [99])
        np.testing.assert_array_equal(advanced.window, [2, 3, 4, 5, 6, 7, 8, 99])
        assert advanced.length == 8

    def test_multi_item_append(self):
        advanced = UserStateCache(4).advance(_state([1]), [2, 3])
        np.testing.assert_array_equal(advanced.window[-3:], [1, 2, 3])
        assert advanced.length == 3

    def test_advance_user_is_atomic_under_concurrent_appends(self):
        """Two clients appending for the same user must BOTH land: the
        lookup→advance→store sequence is one lock acquisition, so no
        interaction is erased by a concurrent last-write-wins."""
        import threading

        cache = UserStateCache(8)
        cache.store("u", _state([0], L=64))
        items_a = list(range(100, 110))
        items_b = list(range(200, 210))

        def appender(items):
            for item in items:
                assert cache.advance_user("u", [item]) is not None

        threads = [threading.Thread(target=appender, args=(i,)) for i in (items_a, items_b)]
        for thread in threads:
            thread.start()
        for thread in threads:
            thread.join()
        final = cache.peek("u")
        assert final.length == 21  # the seed + all 20 appends survived
        window_items = set(final.window[final.mask].tolist())
        assert set(items_a) <= window_items and set(items_b) <= window_items
        assert final.generation == 20

    def test_advance_user_unknown_user_returns_none_and_counts_miss(self):
        cache = UserStateCache(4)
        assert cache.advance_user("ghost", [1]) is None
        assert cache.misses == 1 and cache.advances == 0


class TestLRU:
    def test_eviction_order_is_least_recently_used(self):
        cache = UserStateCache(2)
        cache.store("a", _state([1]))
        cache.store("b", _state([2]))
        assert cache.lookup("a") is not None  # refreshes a's recency
        cache.store("c", _state([3]))  # evicts b, not a
        assert cache.peek("b") is None
        assert cache.peek("a") is not None and cache.peek("c") is not None
        assert cache.evictions == 1

    def test_hit_and_miss_counters(self):
        cache = UserStateCache(4)
        assert cache.lookup("ghost") is None
        cache.store("u", _state([1]))
        assert cache.lookup("u") is not None
        stats = cache.stats()
        assert stats["hits"] == 1 and stats["misses"] == 1
        assert stats["hit_rate"] == 0.5

    def test_peek_has_no_side_effects(self):
        cache = UserStateCache(4)
        cache.store("u", _state([1]))
        cache.peek("u")
        cache.peek("ghost")
        assert cache.hits == 0 and cache.misses == 0

    def test_store_beyond_capacity_evicts_oldest(self):
        cache = UserStateCache(3)
        for i in range(5):
            cache.store(i, _state([i]))
        assert len(cache) == 3
        assert cache.peek(0) is None and cache.peek(1) is None
        assert cache.peek(4) is not None


class TestRefreshEmbedding:
    def test_refresh_attaches_embedding(self):
        cache = UserStateCache(4)
        state = _state([1, 2])
        cache.store("u", state)
        cache.refresh_embedding("u", state, np.ones(16, np.float32))
        assert cache.peek("u").embedding is not None

    def test_stale_refresh_does_not_clobber_newer_generation(self):
        cache = UserStateCache(4)
        old = _state([1, 2])
        cache.store("u", old)
        newer = cache.advance(old, [3])
        cache.store("u", newer)
        # the encode of the OLD window finishes late: must not overwrite
        cache.refresh_embedding("u", old, np.ones(16, np.float32))
        current = cache.peek("u")
        assert current.generation == newer.generation
        assert current.embedding is None
        # the newer window's own refresh lands
        cache.refresh_embedding("u", newer, np.full(16, 2.0, np.float32))
        assert cache.peek("u").embedding is not None


class TestParamGenerationStamp:
    """Hot-swap staleness (serve.promote): cached embeddings carry the PARAM
    generation that encoded them, so a weight swap can treat every pre-swap
    embedding as a miss instead of scoring it through new weights."""

    def test_refresh_stamps_param_generation(self):
        cache = UserStateCache(4)
        state = _state([1, 2])
        cache.store("u", state)
        cache.refresh_embedding("u", state, np.ones(16, np.float32), param_generation=3)
        assert cache.peek("u").param_generation == 3

    def test_default_stamp_is_generation_zero(self):
        cache = UserStateCache(4)
        state = _state([1])
        cache.store("u", state)
        cache.refresh_embedding("u", state, np.ones(16, np.float32))
        assert cache.peek("u").param_generation == 0

    def test_advance_drops_embedding_and_next_refresh_restamps(self):
        cache = UserStateCache(4)
        state = _state([1, 2])
        cache.store("u", state)
        cache.refresh_embedding("u", state, np.ones(16, np.float32), param_generation=1)
        advanced = cache.advance_user("u", [3])
        assert advanced.embedding is None  # certifies the OLD window only
        cache.refresh_embedding("u", advanced, np.ones(16, np.float32), param_generation=2)
        assert cache.peek("u").param_generation == 2
