"""Fleet over REAL scoring replicas: routed-vs-direct bitwise parity,
replica-kill resolution, cold-miss failover, and the TP-sharded MIPS path.

The jax half of the fleet story (the routing/hedging/backoff logic itself is
host-only-tested in ``test_router.py``): N true ``ScoringService`` replicas
— each with its own compiled executables and state cache — behind the
router, plus the sharded 10M-item-retrieval layout checked bitwise against
the unsharded search and hard-asserted table-gather-free on the 8-device
mesh.
"""

import numpy as np
import pytest

import jax

from replay_tpu.data import FeatureHint, FeatureType
from replay_tpu.data.nn import TensorFeatureInfo, TensorSchema
from replay_tpu.nn.sequential.sasrec import SasRec
from replay_tpu.serve import (
    FallbackScorer,
    ScoringService,
    ServeError,
    ServingFleet,
)

pytestmark = [pytest.mark.jax, pytest.mark.smoke]

NUM_ITEMS, SEQ_LEN, DIM = 20, 8, 8
REPLICAS = 3


@pytest.fixture(scope="module")
def model_and_params():
    schema = TensorSchema(
        TensorFeatureInfo(
            "item_id", FeatureType.CATEGORICAL, is_seq=True,
            feature_hint=FeatureHint.ITEM_ID, cardinality=NUM_ITEMS, embedding_dim=DIM,
        )
    )
    model = SasRec(
        schema=schema, embedding_dim=DIM, num_blocks=1, max_sequence_length=SEQ_LEN
    )
    ids = np.zeros((2, SEQ_LEN), np.int32)
    params = model.init(
        jax.random.PRNGKey(0), {"item_id": ids}, np.ones((2, SEQ_LEN), bool)
    )["params"]
    return model, params


def _service(model_and_params, **kwargs):
    model, params = model_and_params
    kwargs.setdefault("length_buckets", (SEQ_LEN,))
    kwargs.setdefault("batch_buckets", (1, 4))
    kwargs.setdefault("max_wait_ms", 5.0)
    return ScoringService(model, params, **kwargs)


def _make_fleet(model_and_params, replicas=REPLICAS, **service_kwargs):
    services = {
        f"r{i}": _service(model_and_params, **service_kwargs)
        for i in range(replicas)
    }
    # poll()-driven health (no timing), hedging off: parity tests must see
    # exactly one replica answer each request
    fleet = ServingFleet(services, heartbeat_interval_s=None, hedge_ms=0)
    return fleet, services


def _history(rng):
    return rng.integers(0, NUM_ITEMS, size=int(rng.integers(1, 2 * SEQ_LEN))).tolist()


class TestRoutedParity:
    def test_routed_scores_bitwise_vs_direct_single_service(self, model_and_params):
        """A score served THROUGH the fleet is bit-for-bit the score a
        standalone single service produces for the same history — routing,
        micro-batching and the ring add exactly nothing to the math."""
        rng = np.random.default_rng(0)
        histories = {user: _history(rng) for user in range(12)}
        fleet, _ = _make_fleet(model_and_params)
        direct = _service(model_and_params).start()
        try:
            with fleet:
                for user, history in histories.items():
                    routed = fleet.score(user, history=history, timeout=30)
                    reference = direct.score(
                        f"direct-{user}", history=history, timeout=30
                    )
                    assert routed.replica in {f"r{i}" for i in range(REPLICAS)}
                    assert routed.batch_bucket == reference.batch_bucket
                    np.testing.assert_array_equal(routed.scores, reference.scores)
                    # the pure-hit path too: cached state, same bits
                    hit = fleet.score(user, timeout=30)
                    direct_hit = direct.score(f"direct-{user}", timeout=30)
                    np.testing.assert_array_equal(hit.scores, direct_hit.scores)
        finally:
            direct.close()

    def test_users_stick_to_their_replica(self, model_and_params):
        """Consistent hashing: every request of one user lands on one
        replica (that's what makes its cache hot)."""
        rng = np.random.default_rng(1)
        fleet, _ = _make_fleet(model_and_params)
        with fleet:
            for user in range(8):
                history = _history(rng)
                first = fleet.score(user, history=history, timeout=30)
                for _ in range(3):
                    again = fleet.score(user, timeout=30)
                    assert again.replica == first.replica
                    assert again.served_from == "hit"


class TestReplicaKill:
    def test_every_inflight_request_resolves_on_kill(self, model_and_params):
        """The chaos headline: close one replica while a burst is in flight
        — every future resolves as a success or a taxonomy error, none hang."""
        rng = np.random.default_rng(2)
        fleet, services = _make_fleet(model_and_params)
        with fleet:
            # seed users so the burst has cached state everywhere
            for user in range(24):
                fleet.score(user, history=_history(rng), timeout=30)
            futures = [fleet.submit(user) for user in range(24)]
            services["r1"].close()
            futures.extend(fleet.submit(user) for user in range(24))
            unresolved = 0
            outcomes = {"answered": 0, "taxonomy": 0}
            for future in futures:
                try:
                    future.result(timeout=30)
                    outcomes["answered"] += 1
                except (ServeError, KeyError):
                    outcomes["taxonomy"] += 1
                except Exception:  # noqa: BLE001 — anything else is a bug
                    unresolved += 1
            assert unresolved == 0, outcomes
            assert outcomes["answered"] > 0
            hung = [future for future in futures if not future.done()]
            assert not hung

    def test_failover_rides_the_ladder_with_cold_miss_fallback(self, model_and_params):
        """A dead replica's users get FALLBACK answers downstream (their
        cache is cold there) instead of KeyErrors — and the response tags
        prove the path: served_by names the rung, replica names who took it."""
        rng = np.random.default_rng(3)
        fallback = FallbackScorer(np.arange(NUM_ITEMS, dtype=np.float32))
        fleet, services = _make_fleet(
            model_and_params, cold_miss="fallback", fallback=fallback
        )
        with fleet:
            fleet.ring.preference("probe")
            victim = fleet.ring.route("probe")
            fleet.score("probe", history=_history(rng), timeout=30)
            services[victim].close()
            for _ in range(3):
                fleet.poll()
            assert fleet.health()[victim] == "dead"
            response = fleet.score("probe", timeout=30)
            assert response.replica != victim
            assert response.served_by == "fallback"
            assert response.served_from == "fallback"
            # an interaction that cannot land (new_items, no window anywhere
            # downstream) must ERROR, never be masked by a success response
            with pytest.raises(KeyError, match="re-anchor"):
                fleet.submit("never-seen", new_items=[1]).result(timeout=30)
            # an explicit history still gets a PRIMARY answer downstream:
            # degradation is about lost state, not lost capacity
            rehomed = fleet.score("probe", history=_history(rng), timeout=30)
            assert rehomed.replica != victim
            assert rehomed.served_by == "primary"


class TestShardedMIPS:
    def test_sharded_topk_bitwise_vs_unsharded_including_non_divisible(self):
        """The [I/n, E] row-sharded search (CEFusedTP's serving twin) on the
        8-device mesh: identical ids AND bitwise-identical scores vs the
        unsharded program, for divisible and non-divisible catalogs, f32 and
        the PR-11 int8 variant."""
        from replay_tpu.models.ann import MIPSIndex
        from replay_tpu.nn import make_mesh

        rng = np.random.default_rng(4)
        queries = rng.normal(size=(16, 32)).astype(np.float32)
        mesh = make_mesh(model_parallel=len(jax.devices()))
        for rows in (1024, 999):  # 999: zero-padded tail shard exercised
            table = rng.normal(size=(rows, 32)).astype(np.float32)
            for precision in ("f32", "int8"):
                sharded = MIPSIndex(
                    table, mesh=mesh, axis_name="model", precision=precision
                )
                unsharded = MIPSIndex(table, precision=precision)
                values_s, ids_s = sharded.search(queries, 24)
                values_u, ids_u = unsharded.search(queries, 24)
                np.testing.assert_array_equal(ids_s, ids_u)
                np.testing.assert_array_equal(values_s, values_u)

    def test_sharded_search_never_moves_table_sized_bytes(self):
        """The static no-gather invariant, hard-asserted from the compiled
        HLO: cross-shard traffic is bounded by the candidate merge (Q x
        local_k x shards rows), never the [I/n, E] table shard itself."""
        from replay_tpu.models.ann import MIPSIndex
        from replay_tpu.nn import make_mesh
        from replay_tpu.parallel.introspect import collective_inventory

        rng = np.random.default_rng(5)
        n = len(jax.devices())
        rows, dim, k, queries = 65536, 32, 50, 16
        table = rng.normal(size=(rows, dim)).astype(np.float32)
        mesh = make_mesh(model_parallel=n)
        for precision in ("f32", "int8"):
            index = MIPSIndex(table, mesh=mesh, axis_name="model", precision=precision)
            inventory = collective_inventory(index.search_hlo(queries, k))
            assert inventory, "sharded search must move SOME candidate bytes"
            shard_bytes = index.table_shard_bytes()
            merge_budget = 2 * queries * min(k, rows // n) * n * 8
            assert merge_budget < shard_bytes, "test shapes must separate the two"
            for collective in inventory:
                moved = collective.get("bytes") or 0
                assert moved <= merge_budget, (
                    f"{precision}: {collective['op']} moved {moved} B — "
                    f"table-sized traffic (shard is {shard_bytes} B)"
                )

    def test_sharded_index_serves_a_retrieval_fleet_replica(self, model_and_params):
        """End-to-end: a retrieval-mode replica whose MIPS index is mesh-
        sharded answers through the fleet, bitwise vs an unsharded-pipeline
        service for the same user state."""
        from replay_tpu.models.ann import MIPSIndex
        from replay_tpu.nn import make_mesh
        from replay_tpu.serve import CandidatePipeline

        model, params = model_and_params
        item_weights = np.asarray(
            model.apply({"params": params}, method=SasRec.get_item_weights)
        )
        mesh = make_mesh(model_parallel=len(jax.devices()))

        def pipeline(sharded: bool):
            index = (
                MIPSIndex(item_weights, mesh=mesh, axis_name="model")
                if sharded
                else MIPSIndex(item_weights)
            )
            return CandidatePipeline(index, num_candidates=10, top_k=5)

        rng = np.random.default_rng(6)
        history = _history(rng)
        sharded_service = _service(model_and_params, retrieval=pipeline(True))
        unsharded_service = _service(model_and_params, retrieval=pipeline(False))
        fleet = ServingFleet(
            {"sharded": sharded_service}, heartbeat_interval_s=None, hedge_ms=0
        )
        unsharded_service.start()
        try:
            with fleet:
                routed = fleet.score("u", history=history, timeout=30)
                reference = unsharded_service.score("u", history=history, timeout=30)
                assert routed.replica == "sharded"
                np.testing.assert_array_equal(routed.item_ids, reference.item_ids)
                # ids exact; scores allclose — the tiny per-shard matmul may
                # accumulate in a different order than the unsharded one (the
                # PR-6 "1 ulp across program shapes" XLA caveat; the bitwise
                # claim is pinned at real catalog shapes above)
                np.testing.assert_allclose(
                    routed.scores, reference.scores, rtol=1e-6, atol=1e-7
                )
        finally:
            unsharded_service.close()
