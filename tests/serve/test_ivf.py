"""IVF sub-linear retrieval — build, recall gates, padding honesty, sharding.

ISSUE 20 acceptance gates on a clustered synthetic catalog: recall@100 ≥ 0.99
vs the brute-force sweep at the index's own ``nprobe`` for every precision rung
(f32 and int8 raw; int8+pq through its serving configuration — 3× candidate
overfetch + ``exact_rescore`` — because PQ codes select candidates, they never
rank them), bitwise-deterministic builds, the PR-6-style adversarial padding
test (strictly-negative catalog: any padded zero row winning top-k fails
loudly), and the PR-15 no-table-gather assert on the sharded search's compiled
HLO via ``collective_inventory``.

The smoke tests leave ``REPLAY_TPU_RUN_DIR/ann_smoke/ivf_gate.json`` for the
CI ``ann_smoke`` gate.
"""

import json
import os

import numpy as np
import pytest

from replay_tpu.models.ivf import brute_bytes, default_nlist, ivf_bytes, ladder_width

NUM_ITEMS = 20000
DIM = 64
QUERIES = 64
MODES = 64
NLIST = 64
NPROBE = 32
PQ_M = 16
PQ_OVERFETCH = 3  # the pq rung's serving config: 3x candidates, then rescore


@pytest.fixture(scope="module")
def catalog():
    # clustered synthetic: item embeddings concentrate around latent modes
    # (the structure IVF exploits; an unclustered catalog is brute's turf)
    rng = np.random.default_rng(0)
    centers = rng.normal(size=(MODES, DIM)).astype(np.float32)
    centers /= np.linalg.norm(centers, axis=1, keepdims=True)
    table = (
        centers[rng.integers(0, MODES, size=NUM_ITEMS)]
        + 0.1 * rng.normal(size=(NUM_ITEMS, DIM))
    ).astype(np.float32)
    queries = (
        centers[rng.integers(0, MODES, size=QUERIES)]
        + 0.1 * rng.normal(size=(QUERIES, DIM))
    ).astype(np.float32)
    return table, queries


def _build(table, precision="f32", mesh=None, **overrides):
    from replay_tpu.models.ann import MIPSIndex

    kwargs = dict(
        index="ivf", precision=precision, nlist=NLIST, nprobe=NPROBE,
        build_sample=8192, pq_subspaces=PQ_M,
    )
    kwargs.update(overrides)
    if mesh is not None:
        kwargs.update(mesh=mesh, axis_name="model")
    return MIPSIndex(table, **kwargs)


@pytest.fixture(scope="module")
def ground_truth(catalog):
    from replay_tpu.models.ann import MIPSIndex

    table, queries = catalog
    brute = MIPSIndex(table)
    values, ids = brute.search(queries, 100)
    return values, ids


def _recall(reference_ids: np.ndarray, candidate_ids: np.ndarray) -> float:
    k = reference_ids.shape[1]
    return float(
        np.mean(
            [
                len(set(a.tolist()) & set(b.tolist())) / k
                for a, b in zip(reference_ids, candidate_ids)
            ]
        )
    )


def _rescored_top100(index, queries, candidates):
    exact = np.asarray(index.exact_rescore(queries, candidates))
    order = np.argsort(-exact, axis=1)[:, :100]
    return np.take_along_axis(np.asarray(candidates), order, axis=1)


# --------------------------------------------------------------------------- #
# host-side geometry and byte accounting (no device)
# --------------------------------------------------------------------------- #
@pytest.mark.core
def test_ladder_widths_are_aligned_and_monotone():
    widths = [ladder_width(n) for n in range(1, 4000, 7)]
    assert all(w % 8 == 0 for w in widths)
    assert all(w >= n for w, n in zip(widths, range(1, 4000, 7)))
    assert widths == sorted(widths)
    # the ladder is a SMALL fixed set of widths, not one per size
    assert len(set(widths)) < 40
    assert ladder_width(0) == 0


@pytest.mark.core
def test_default_nlist_is_mesh_divisible_power_of_two():
    for items in (257, 20000, 1_000_000, 100_000_000):
        for shards in (1, 8):
            nlist = default_nlist(items, shards)
            assert nlist & (nlist - 1) == 0, nlist  # power of two
            assert nlist % shards == 0
            assert nlist <= max(items // 4, 8 * shards)


@pytest.mark.core
def test_projected_100m_pq_fits_where_int8_brute_cannot():
    """The 100M-item memory claim, machine-derived from the same formula that
    prices the built index: at E=256 an int8 BRUTE table overflows a 16 GiB
    v5e HBM, while the full IVF int8+pq index (codes + centroids + codebooks
    + ids) fits with room for the model."""
    hbm = 16 * 2**30
    items, dim = 100_000_000, 256
    brute_int8 = brute_bytes(items, dim, "int8")
    pq = ivf_bytes(items, dim, nlist=65536, precision="int8+pq", pq_subspaces=32)
    assert brute_int8["total_bytes"] > hbm, brute_int8
    assert pq["total_bytes"] < hbm // 3, pq
    # breakdown components sum to the total (no hand-asserted slack)
    assert pq["total_bytes"] == (
        pq["cell_bytes"] + pq["centroid_bytes"] + pq["codebook_bytes"]
        + pq["scale_bytes"] + pq["id_bytes"]
    )


@pytest.mark.jax
def test_table_bytes_breakdown_matches_device_arrays(catalog):
    """The byte formula is anchored against the REAL device buffers — the
    same formula then prices the 100M projection, keeping it machine-derived."""
    table, _ = catalog
    for precision in ("f32", "int8", "int8+pq"):
        index = _build(table, precision)
        state = index._ivf
        reported = index.table_bytes()
        if precision == "int8+pq":
            assert reported["cell_bytes"] == state.codes.nbytes
            assert reported["codebook_bytes"] == state.codebooks.nbytes
        else:
            assert reported["cell_bytes"] == state.storage.nbytes
        assert reported["centroid_bytes"] == state.centroids.nbytes
        assert reported["id_bytes"] == state.storage_ids.nbytes
        assert reported["payload_bytes"] == reported["total_bytes"]


# --------------------------------------------------------------------------- #
# recall gates (the acceptance criteria) + determinism
# --------------------------------------------------------------------------- #
@pytest.mark.jax
@pytest.mark.smoke
def test_ivf_recall_gates_all_rungs(catalog, ground_truth):
    """recall@100 ≥ 0.99 vs brute at the index's own nprobe: f32 and int8
    raw; int8+pq via its serving config (3× overfetch + exact rescore —
    codes pick candidates, the rescore ranks them). Leaves the CI ann_smoke
    artifact."""
    table, queries = catalog
    _, brute_ids = ground_truth
    gate = {"catalog": NUM_ITEMS, "dim": DIM, "queries": QUERIES}

    for precision in ("f32", "int8"):
        index = _build(table, precision)
        _, ids = index.search(queries, 100)
        recall = _recall(brute_ids, ids)
        assert recall >= 0.99, (precision, recall)
        gate[f"recall_at_100_{precision}"] = recall

    pq_index = _build(table, "int8+pq")
    _, candidates = pq_index.search(queries, 100 * PQ_OVERFETCH)
    pq_top = _rescored_top100(pq_index, queries, candidates)
    pq_recall = _recall(brute_ids, pq_top)
    assert pq_recall >= 0.99, pq_recall
    gate["recall_at_100_int8+pq"] = pq_recall
    gate["pq_overfetch"] = PQ_OVERFETCH
    gate["bytes_ratio_pq"] = pq_index.table_bytes()["bytes_ratio"]
    gate["index_stats"] = _build(table, "f32").index_stats()

    base = os.environ.get("REPLAY_TPU_RUN_DIR")
    if base:
        run_dir = os.path.join(base, "ann_smoke")
        os.makedirs(run_dir, exist_ok=True)
        with open(os.path.join(run_dir, "ivf_gate.json"), "w") as fh:
            json.dump(gate, fh, indent=1)


@pytest.mark.jax
@pytest.mark.smoke
def test_f32_ivf_scores_are_exact_dots(catalog, ground_truth):
    """The f32 IVF rung approximates only the candidate SET: every returned
    score must equal the brute sweep's score for that same item."""
    table, queries = catalog
    index = _build(table, "f32")
    values, ids = index.search(queries, 100)
    exact = np.asarray(index.exact_rescore(queries, ids))
    np.testing.assert_allclose(values, exact, rtol=1e-5, atol=1e-5)


@pytest.mark.jax
def test_ivf_build_is_deterministic(catalog):
    """Same table, same seed → bitwise-same centroids, layout, and search
    results (the zero-retrace contract extends to the build)."""
    table, queries = catalog
    first = _build(table, "f32", seed=7)
    second = _build(table, "f32", seed=7)
    assert np.array_equal(np.asarray(first._ivf.centroids), np.asarray(second._ivf.centroids))
    assert np.array_equal(np.asarray(first._ivf.storage_ids), np.asarray(second._ivf.storage_ids))
    v1, i1 = first.search(queries, 50)
    v2, i2 = second.search(queries, 50)
    assert np.array_equal(i1, i2) and np.array_equal(v1, v2)
    stats = first.index_stats()
    assert stats["index"] == "ivf" and stats["scanned_fraction"] > 0
    assert stats["nlist"] == NLIST and stats["nprobe"] == NPROBE


# --------------------------------------------------------------------------- #
# adversarial padding honesty (PR-6 style) — unsharded and 8-way sharded
# --------------------------------------------------------------------------- #
@pytest.mark.jax
def test_strictly_negative_catalog_never_surfaces_padding():
    """Strictly-negative items vs strictly-positive queries: every true score
    is < 0 while cell-padding rows are zeros (score 0) — any unmasked padded
    row would WIN top-k. 611 items over non-divisible cells on the 8-device
    mesh exercise ladder padding, the tail guard, and shard equalization."""
    from replay_tpu.nn import make_mesh

    rng = np.random.default_rng(3)
    items = 611  # prime-ish: cells never divide evenly
    dim = 16
    table = (-np.abs(rng.normal(size=(items, dim))) - 0.5).astype(np.float32)
    queries = (np.abs(rng.normal(size=(16, dim))) + 0.5).astype(np.float32)
    mesh = make_mesh(model_parallel=8)

    for precision in ("f32", "int8", "int8+pq"):
        for use_mesh in (False, True):
            index = _build(
                table, precision, mesh=mesh if use_mesh else None,
                nlist=16, nprobe=16, build_sample=items, pq_subspaces=4,
            )
            values, ids = index.search(queries, 20)
            label = (precision, "sharded" if use_mesh else "unsharded")
            assert np.all(ids >= 0), (label, ids.min())
            assert np.all(ids < items), label
            assert np.all(np.isfinite(values)), label
            if precision != "int8+pq":  # pq scores are approximate sums
                assert np.all(values < 0.0), (label, values.max())
            for row in ids:
                assert len(set(row.tolist())) == len(row), (label, row)


# --------------------------------------------------------------------------- #
# sharded layout: no table-sized collectives, recall preserved
# --------------------------------------------------------------------------- #
@pytest.mark.jax
@pytest.mark.smoke
def test_sharded_ivf_search_never_moves_cell_rows(catalog, ground_truth):
    """The PR-15 contract extended to IVF: the sharded search's compiled HLO
    may move per-shard CANDIDATES (≤ the merge budget) but never cell rows —
    every collective must be orders below the per-shard cell payload."""
    from replay_tpu.nn import make_mesh
    from replay_tpu.parallel.introspect import collective_inventory

    table, queries = catalog
    _, brute_ids = ground_truth
    mesh = make_mesh(model_parallel=8)
    index = _build(table, "f32", mesh=mesh)
    n_shards = 8
    k = 100

    _, ids = index.search(queries, k)
    recall = _recall(brute_ids, ids)
    assert recall >= 0.99, recall

    state = index._ivf
    local_k = min(k, (NPROBE // n_shards) * state.cmax)
    merge_budget = 2 * QUERIES * local_k * n_shards * 8
    shard_bytes = index.table_shard_bytes()
    assert merge_budget < shard_bytes, (merge_budget, shard_bytes)
    inventory = collective_inventory(index.search_hlo(QUERIES, k))
    assert inventory, "sharded search must communicate candidates"
    for entry in inventory:
        size = entry.get("bytes") or 0
        assert size <= merge_budget, (entry, merge_budget, shard_bytes)


# --------------------------------------------------------------------------- #
# serving pipeline integration
# --------------------------------------------------------------------------- #
@pytest.mark.jax
@pytest.mark.smoke
def test_pipeline_rescores_ivf_and_agrees_with_brute(catalog):
    """IVF is approximate even at f32 — the pipeline must insert the
    exact-rescore stage (brute f32 must NOT) and its re-ranked top-k must
    agree with the brute f32 pipeline wherever the candidates cover the
    winners (approximation picks candidates, never ranks them)."""
    from replay_tpu.models.ann import MIPSIndex
    from replay_tpu.obs import Tracer
    from replay_tpu.serve import CandidatePipeline

    table, queries = catalog
    weights = np.asarray([0.05, 0.1], np.float32)
    brute_pipe = CandidatePipeline(
        MIPSIndex(table), num_candidates=100, top_k=10, reranker_weights=weights
    )
    ivf_pipe = CandidatePipeline(
        _build(table, "f32"), num_candidates=100, top_k=10, reranker_weights=weights
    )
    assert ivf_pipe.stats()["index_mode"] == "ivf"
    assert brute_pipe.stats()["index_mode"] == "brute"

    tracer = Tracer()
    _, brute_topk = brute_pipe.rank(queries, tracer=tracer)
    assert "rescore" not in set(tracer.summary())

    tracer = Tracer()
    _, ivf_topk = ivf_pipe.rank(queries, tracer=tracer)
    names = set(tracer.summary())
    assert {"retrieve", "rescore", "rerank"} <= names, names

    _, ivf_cands = ivf_pipe.index.search(queries, 100)
    covered = agreed = 0
    for row in range(queries.shape[0]):
        if set(brute_topk[row].tolist()) <= set(ivf_cands[row].tolist()):
            covered += 1
            if set(brute_topk[row].tolist()) == set(ivf_topk[row].tolist()):
                agreed += 1
    assert covered >= int(0.9 * queries.shape[0]), covered
    assert agreed == covered, (agreed, covered)
    assert _recall(brute_topk, ivf_topk) >= 0.99


# --------------------------------------------------------------------------- #
# rejection paths
# --------------------------------------------------------------------------- #
@pytest.mark.jax
def test_ivf_rejects_bad_configs(catalog):
    from replay_tpu.models.ann import MIPSIndex
    from replay_tpu.nn import make_mesh

    table, queries = catalog
    with pytest.raises(ValueError, match="index"):
        MIPSIndex(table, index="hnsw")
    with pytest.raises(ValueError, match="precision"):
        MIPSIndex(table, precision="int8+pq")  # pq is an IVF-only rung
    with pytest.raises(ValueError, match="precision"):
        MIPSIndex(table, index="ivf", precision="int4")
    with pytest.raises(ValueError, match="nlist"):
        MIPSIndex(table, index="ivf", nlist=NUM_ITEMS + 1)
    with pytest.raises(ValueError, match="nprobe"):
        MIPSIndex(table, index="ivf", nlist=16, nprobe=17)
    with pytest.raises(ValueError, match="pq_subspaces"):
        MIPSIndex(table, index="ivf", precision="int8+pq", pq_subspaces=7)
    with pytest.raises(ValueError, match="shards"):
        MIPSIndex(
            table, index="ivf", nlist=12, mesh=make_mesh(model_parallel=8),
            axis_name="model",
        )
    index = _build(table, "f32", nlist=16, nprobe=2)
    with pytest.raises(ValueError, match="probed candidate pool"):
        index.search(queries, 2 * index._ivf.cmax + 1)
