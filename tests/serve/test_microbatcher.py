"""MicroBatcher: lane routing, fill-vs-deadline dispatch, shutdown (host-only)."""

import threading
import time

import pytest

from replay_tpu.serve import MicroBatcher


class Collector:
    """Records every dispatch (lane, items) with a timestamp."""

    def __init__(self, delay: float = 0.0):
        self.batches = []
        self.delay = delay
        self.lock = threading.Lock()

    def __call__(self, lane, items):
        if self.delay:
            time.sleep(self.delay)
        with self.lock:
            self.batches.append((lane, list(items), time.perf_counter()))

    def rows(self):
        with self.lock:
            return [len(items) for _, items, _ in self.batches]


def test_full_lane_dispatches_without_waiting_for_deadline():
    collector = Collector()
    with MicroBatcher(collector, capacity=4, max_wait=5.0) as batcher:
        start = time.perf_counter()
        for i in range(4):
            batcher.submit("a", i)
        deadline = time.perf_counter() + 2.0
        while not collector.batches and time.perf_counter() < deadline:
            time.sleep(0.001)
        elapsed = time.perf_counter() - start
    assert collector.rows() == [4]
    assert elapsed < 2.0  # nowhere near the 5s max_wait
    assert collector.batches[0][1] == [0, 1, 2, 3]
    stats = batcher.stats()
    assert stats["full_flushes"] == 1 and stats["deadline_flushes"] == 0


def test_partial_batch_flushes_at_deadline():
    collector = Collector()
    with MicroBatcher(collector, capacity=8, max_wait=0.05) as batcher:
        batcher.submit("a", "only")
        time.sleep(0.3)
    assert collector.rows() == [1]
    assert batcher.stats()["deadline_flushes"] == 1


def test_lanes_do_not_mix():
    collector = Collector()
    with MicroBatcher(collector, capacity=4, max_wait=0.02) as batcher:
        for i in range(3):
            batcher.submit(("encode", 16), f"short{i}")
        for i in range(2):
            batcher.submit(("encode", 50), f"long{i}")
        time.sleep(0.3)
    lanes = {lane: items for lane, items, _ in collector.batches}
    assert set(lanes) == {("encode", 16), ("encode", 50)}
    assert lanes[("encode", 16)] == ["short0", "short1", "short2"]
    assert lanes[("encode", 50)] == ["long0", "long1"]


def test_oversubmission_splits_into_capacity_chunks():
    collector = Collector()
    with MicroBatcher(collector, capacity=4, max_wait=0.02) as batcher:
        for i in range(10):
            batcher.submit("a", i)
        time.sleep(0.4)
    rows = collector.rows()
    assert sum(rows) == 10
    assert max(rows) <= 4
    # order preserved across chunks
    flat = [item for _, items, _ in sorted(collector.batches, key=lambda b: b[2]) for item in items]
    assert flat == list(range(10))


def test_expired_deadline_beats_a_continuously_full_lane():
    """A lane kept full by fresh arrivals must not starve another lane's
    expired request: the deadline contract is per lane, whichever of
    fill/deadline comes first. (Preferring any full lane would defer lane b
    until lane a's traffic pauses — unbounded under sustained load.)"""
    batcher_box = []
    refills = [0]
    order = []
    lock = threading.Lock()

    def dispatch(lane, items):
        with lock:
            order.append(lane)
        time.sleep(0.02)
        if lane == "a" and refills[0] < 10:
            refills[0] += 1
            # keep lane a full with FRESH deadlines, like live traffic would
            batcher_box[0].submit("a", f"refill{refills[0]}a")
            batcher_box[0].submit("a", f"refill{refills[0]}b")

    batcher = MicroBatcher(dispatch, capacity=2, max_wait=0.03)
    batcher_box.append(batcher)
    with batcher:
        batcher.submit("a", 1)
        batcher.submit("a", 2)  # lane a full, and dispatches keep refilling it
        batcher.submit("b", "must not starve")
        deadline = time.perf_counter() + 3.0
        while time.perf_counter() < deadline:
            with lock:
                if "b" in order:
                    break
            time.sleep(0.005)
    with lock:
        assert "b" in order, f"lane b never dispatched: {order}"
        # b's 30ms deadline expires ~2 a-dispatches in; it must be served while
        # lane a is still refilling, not after the 10-refill backlog drains
        assert order.index("b") <= 5, f"lane b starved behind {order}"


def test_stop_flushes_pending_items():
    collector = Collector()
    batcher = MicroBatcher(collector, capacity=64, max_wait=60.0).start()
    for i in range(5):
        batcher.submit("a", i)
    batcher.stop()  # deadline is a minute away: stop must not wait for it
    assert sum(collector.rows()) == 5


def test_submit_after_stop_raises():
    batcher = MicroBatcher(Collector(), capacity=4, max_wait=0.01).start()
    batcher.stop()
    with pytest.raises(RuntimeError, match="not running"):
        batcher.submit("a", 1)


def test_dispatch_error_routes_to_on_error_and_worker_survives():
    errors = []
    calls = []

    def explode(lane, items):
        calls.append(list(items))
        if len(calls) == 1:
            raise RuntimeError("boom")

    batcher = MicroBatcher(
        explode,
        capacity=2,
        max_wait=0.01,
        on_error=lambda lane, items, exc: errors.append((list(items), str(exc))),
    ).start()
    batcher.submit("a", 1)
    batcher.submit("a", 2)
    time.sleep(0.1)
    batcher.submit("a", 3)  # the worker must still be alive
    batcher.stop()
    assert errors == [([1, 2], "boom")]
    assert [1, 2] in calls and [3] in calls


def test_concurrent_submitters_lose_nothing():
    collector = Collector(delay=0.001)
    with MicroBatcher(collector, capacity=8, max_wait=0.005) as batcher:
        def client(base):
            for i in range(50):
                batcher.submit("lane", base + i)

        threads = [threading.Thread(target=client, args=(1000 * t,)) for t in range(4)]
        for thread in threads:
            thread.start()
        for thread in threads:
            thread.join()
        time.sleep(0.5)
    dispatched = [item for _, items, _ in collector.batches for item in items]
    assert sorted(dispatched) == sorted(1000 * t + i for t in range(4) for i in range(50))
    assert max(collector.rows()) <= 8
