"""Promotion robustness: ParamStore atomicity, the canary state machine, and
zero-downtime hot swaps through a live ScoringService.

Host-only halves (ParamStore, PromotionController over a fake service) run in
the core tier; the end-to-end swap/canary tests are jax+smoke and pin the
PR's acceptance contract: every response carries ONE consistent generation
(its scores reproduce that generation's direct forward bit-for-bit), a swap
empties effective cache hits instead of mixing generations, a forced SLO
breach rolls back exactly once, and chaos mid-swap rides the degradation
ladder instead of erroring.
"""

import threading
import time

import numpy as np
import pytest

from replay_tpu.obs.metrics import MetricsRegistry
from replay_tpu.obs.slo import SLORule
from replay_tpu.serve.promote import (
    ParamStore,
    PromotionController,
    in_canary_slice,
)


class RecordingLogger:
    def __init__(self):
        self.events = []

    def log_event(self, event):
        self.events.append(event)

    def close(self):
        pass

    def named(self, name):
        return [e for e in self.events if e.event == name]


# --------------------------------------------------------------------------- #
# ParamStore (host-only)
# --------------------------------------------------------------------------- #
class TestParamStore:
    def test_generation_counter_and_resolution(self):
        store = ParamStore({"w": np.zeros(2)})
        assert store.stable_generation == 0
        g1 = store.publish({"w": np.ones(2)}, label="v1")
        assert g1 == 1
        assert store.candidate_generation == 1
        # candidate role resolves the candidate; stable stays pinned
        assert store.resolve("candidate").number == 1
        assert store.resolve("stable").number == 0

    def test_candidate_role_falls_back_to_stable(self):
        store = ParamStore({"w": 0})
        assert store.resolve("candidate").number == 0  # no candidate yet

    def test_promote_pins_previous_and_rollback_restores(self):
        store = ParamStore({"w": 0})
        g1 = store.publish({"w": 1})
        info = store.promote(g1)
        assert info == {"from_generation": 0, "to_generation": 1}
        assert store.stable_generation == 1
        assert store.previous_generation == 0
        assert store.candidate_generation is None
        back = store.rollback()
        assert back == {"from_generation": 1, "to_generation": 0}
        assert store.stable_generation == 0
        assert store.rollbacks == 1

    def test_rollback_without_previous_raises(self):
        store = ParamStore({"w": 0})
        with pytest.raises(ValueError, match="nothing to roll back"):
            store.rollback()

    def test_canary_rollback_drops_candidate_without_moving_stable(self):
        """Mid-canary rollback: stable never moved, so burning the candidate
        IS the restoration (no pointer swap, still ONE rollback incident)."""
        store = ParamStore({"w": 0})
        g1 = store.publish({"w": 1})
        info = store.rollback()
        assert info == {"from_generation": g1, "to_generation": 0}
        assert store.candidate_generation is None
        assert store.stable_generation == 0
        assert store.rollbacks == 1 and store.swaps == 0

    def test_promote_without_candidate_raises(self):
        store = ParamStore({"w": 0})
        with pytest.raises(ValueError, match="no candidate"):
            store.promote()

    def test_eviction_keeps_pinned_generations(self):
        store = ParamStore({"w": 0}, keep_history=1)
        first = store.publish({"w": 1})
        store.promote(first)  # stable=1, previous=0 (both pinned)
        for i in range(2, 6):
            store.publish({"w": i})
        stats = store.stats()
        assert 0 in stats["resident_generations"]  # pinned previous survives
        assert 1 in stats["resident_generations"]  # pinned stable survives
        assert stats["candidate_generation"] in stats["resident_generations"]
        # unpinned middle generations were dropped
        assert 2 not in stats["resident_generations"]
        with pytest.raises(KeyError, match="no longer resident"):
            store.generation(2)

    def test_history_log_is_pure_json(self):
        import json

        store = ParamStore({"w": 0})
        g1 = store.publish({"w": 1}, label="candidate-a")
        store.promote(g1)
        store.rollback()
        log = store.history()
        assert [entry["event"] for entry in log] == [
            "published", "published", "promoted", "rolled_back",
        ]
        json.dumps(log)  # serializable as-is (the CI artifact)

    def test_concurrent_resolve_never_sees_torn_state(self):
        """Readers racing promotes always get a COMPLETE generation whose
        number matches its params (the atomicity contract)."""
        store = ParamStore({"v": 0})
        stop = threading.Event()
        bad = []

        def reader():
            while not stop.is_set():
                gen = store.resolve("stable")
                if gen.params["v"] != gen.number:
                    bad.append((gen.number, gen.params["v"]))

        threads = [threading.Thread(target=reader) for _ in range(4)]
        for t in threads:
            t.start()
        for i in range(1, 50):
            number = store.publish({"v": i})
            assert store.generation(number).params["v"] == i
            store.promote(number)
        stop.set()
        for t in threads:
            t.join()
        assert not bad
        # v == generation number by construction in this test
        assert store.resolve("stable").params["v"] == store.stable_generation


class TestCanarySlice:
    def test_deterministic_and_stable(self):
        for user in ("alice", "bob", 7, ("t", 1)):
            assert in_canary_slice(user, 0.3) == in_canary_slice(user, 0.3)

    def test_edges(self):
        assert not in_canary_slice("anyone", 0.0)
        assert in_canary_slice("anyone", 1.0)

    def test_fraction_is_monotone_per_user(self):
        users = [f"user-{i}" for i in range(500)]
        small = {u for u in users if in_canary_slice(u, 0.1)}
        large = {u for u in users if in_canary_slice(u, 0.5)}
        assert small <= large  # growing the slice never reroutes existing members
        # and the slice size is roughly the fraction
        assert 20 <= len(small) <= 120
        assert 180 <= len(large) <= 320


# --------------------------------------------------------------------------- #
# PromotionController state machine (host-only, fake service, injectable clock)
# --------------------------------------------------------------------------- #
class FakeService:
    """Just enough ScoringService surface for the controller."""

    def __init__(self):
        self.metrics_registry = MetricsRegistry()
        self.events = []
        self.next_generation = 1
        self.canary = None
        self.promote_calls = []
        self.rollback_calls = 0
        self.counts = {
            "stable": {"requests": 0.0, "answered": 0.0, "errors": 0.0,
                       "shed": 0.0, "queue_wait_ms_max": 0.0},
            "candidate": {"requests": 0.0, "answered": 0.0, "errors": 0.0,
                          "shed": 0.0, "queue_wait_ms_max": 0.0},
        }

    def _route_event(self, event):
        self.events.append(event)

    def _emit(self, name, payload):
        from replay_tpu.obs import TrainerEvent

        self._route_event(TrainerEvent(event=name, payload=payload))

    def publish_candidate(self, params, label="", pipeline=None):
        generation = self.next_generation
        self.next_generation += 1
        return generation

    def begin_canary(self, generation, fraction):
        self.canary = (generation, fraction)

    def promote(self, generation=None):
        self.promote_calls.append(generation)
        self.canary = None
        return {"from_generation": 0, "to_generation": generation}

    def rollback(self):
        self.rollback_calls += 1
        self.canary = None
        return {"from_generation": 1, "to_generation": 0}

    def canary_stats(self):
        return {role: dict(stats) for role, stats in self.counts.items()}

    def serve_canary(self, answered=0, errors=0):
        counts = self.counts["candidate"]
        counts["requests"] += answered + errors
        counts["answered"] += answered
        counts["errors"] += errors

    def named(self, name):
        return [e for e in self.events if e.event == name]


class FakeClock:
    def __init__(self):
        self.now = 0.0

    def __call__(self):
        return self.now

    def advance(self, seconds):
        self.now += seconds


def make_controller(service=None, **kwargs):
    service = service if service is not None else FakeService()
    clock = FakeClock()
    kwargs.setdefault("promote_after", 3)
    kwargs.setdefault("min_canary_requests", 2)
    controller = PromotionController(service, clock=clock, **kwargs)
    return controller, service, clock


class TestPromotionController:
    def test_promotes_after_k_clean_evals(self):
        controller, service, clock = make_controller()
        generation = controller.publish({"w": 1}, label="v1")
        assert controller.stage == "shadow"
        controller.begin_canary(fraction=0.25)
        assert controller.stage == "canary"
        assert service.canary == (generation, 0.25)
        for i in range(3):
            service.serve_canary(answered=4)
            clock.advance(1.0)
            record = controller.evaluate()
            assert record["error_rate"] == 0.0
        assert controller.stage == "promoted"
        assert service.promote_calls == [generation]
        assert controller.clean_evals == 3
        promo = service.named("on_promotion")
        assert len(promo) == 1 and promo[0].payload["generation"] == generation

    def test_empty_windows_are_not_clean_evidence(self):
        controller, service, clock = make_controller()
        controller.publish({"w": 1})
        controller.begin_canary()
        for _ in range(10):  # no canary traffic at all
            clock.advance(1.0)
            controller.evaluate()
        assert controller.stage == "canary"  # never promoted on silence
        assert controller.clean_evals == 0

    def test_breach_rolls_back_exactly_once(self):
        controller, service, clock = make_controller()
        controller.publish({"w": 1})
        controller.begin_canary()
        service.serve_canary(answered=3, errors=2)
        clock.advance(1.0)
        record = controller.evaluate()
        assert record["action"] == "rollback"
        assert controller.stage == "rolled_back"
        assert service.rollback_calls == 1
        # further evaluations are inert: ONE rollback per canary
        for _ in range(5):
            clock.advance(1.0)
            assert controller.evaluate()["action"] is None
        assert service.rollback_calls == 1
        assert len(service.named("on_rollback")) == 1
        assert len(service.named("on_slo_violation")) == 1

    def test_recanary_after_rollback_requires_new_generation(self):
        controller, service, clock = make_controller()
        controller.publish({"w": 1})
        controller.begin_canary()
        service.serve_canary(answered=1, errors=1)
        controller.evaluate()
        assert controller.stage == "rolled_back"
        with pytest.raises(RuntimeError, match="new generation"):
            controller.begin_canary()
        # a NEW publish resets the machine to shadow and canary works again
        second = controller.publish({"w": 2})
        controller.begin_canary()
        assert controller.stage == "canary"
        assert service.canary[0] == second

    def test_clean_then_dirty_resets_nothing_but_rolls_back(self):
        """A breach after clean evaluations still rolls back — clean history
        is not credit against a live regression."""
        controller, service, clock = make_controller(promote_after=5)
        controller.publish({"w": 1})
        controller.begin_canary()
        for _ in range(3):
            service.serve_canary(answered=4)
            clock.advance(1.0)
            controller.evaluate()
        assert controller.stage == "canary" and controller.clean_evals == 3
        service.serve_canary(answered=1, errors=3)
        clock.advance(1.0)
        controller.evaluate()
        assert controller.stage == "rolled_back"

    def test_error_rate_is_windowed_not_cumulative(self):
        """Errors before the current window must not re-trip the watchdog:
        each evaluation reads the delta since the previous one."""
        controller, service, clock = make_controller(
            rules=(SLORule("replay_canary_error_rate", ">", 0.4, name="canary_err"),),
            promote_after=2,
        )
        controller.publish({"w": 1})
        controller.begin_canary()
        service.serve_canary(answered=1, errors=1)  # 50% in window 1 — breach
        clock.advance(1.0)
        assert controller.evaluate()["action"] == "rollback"

        second = FakeService()
        controller2, service2, clock2 = make_controller(
            service=second,
            rules=(SLORule("replay_canary_error_rate", ">", 0.4, name="canary_err"),),
            promote_after=2,
        )
        controller2.publish({"w": 1})
        controller2.begin_canary()
        service2.serve_canary(answered=8, errors=2)  # 20% — clean window
        clock2.advance(1.0)
        assert controller2.evaluate()["action"] is None
        service2.serve_canary(answered=8, errors=0)
        clock2.advance(1.0)
        assert controller2.evaluate()["action"] == "promote"

    def test_canary_gauges_land_in_registry(self):
        controller, service, clock = make_controller()
        controller.publish({"w": 1})
        controller.begin_canary()
        service.serve_canary(answered=4)
        clock.advance(1.0)
        controller.evaluate()
        registry = controller.registry
        assert registry.value("replay_canary_error_rate") == 0.0
        assert registry.value("replay_canary_requests") == 4.0
        assert registry.value("replay_canary_generation") == 1.0
        assert registry.value("replay_canary_stage") == 2.0

    def test_eval_events_are_emitted(self):
        controller, service, clock = make_controller()
        controller.publish({"w": 1})
        controller.begin_canary()
        service.serve_canary(answered=2)
        controller.evaluate()
        evals = service.named("on_canary_eval")
        assert len(evals) == 1
        payload = evals[0].payload
        assert payload["generation"] == 1 and payload["window"]["answered"] == 2.0
